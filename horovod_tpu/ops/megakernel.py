"""Fused data-plane megakernels for the eager collective executor.

PR 2 deleted the steady state's control-plane cost (response cache +
replayed fusion plans); what remained of the per-step tax was data-plane
dispatch: ``ops/collective._execute_response`` surrounded each jitted
collective with a Python loop of *eager* XLA dispatches — a
``jnp.concatenate`` pack, per-tensor slice/reshape unpacks, a separate
divide launch for AVERAGE — with no buffer donation and no executable
reuse tied to the cached plans.  That is exactly the fusion-buffer copy
overhead the original Horovod paper identifies as the small-tensor
scaling wall (arXiv:1802.05799 §4), re-materialized as host dispatch
latency instead of memcpy bandwidth.

This module replaces that choreography with **one jitted, donated
megakernel per fusion group**: a shape/dtype/layout/reduce-op/mesh-keyed
executable that packs the group's tensors into a flat fusion buffer,
runs the collective once, folds the AVERAGE divide in, and unpacks to
the result tensors *inside a single XLA program* — the compiler fuses
the copies into the collective and the drain thread performs exactly
one dispatch per group (asserted by tests/test_megakernel.py via
utils/xla_dispatch.py).  ``donate_argnums`` covers every input buffer
the executor itself owns (host-converted contributions, the packed
multi-process fusion buffer), so the steady state stops allocating; the
user's own arrays are never donated.

Compiled executables are cached per group structure and recorded under
the fusion-plan digest of the PR 2 response cache
(``ops/cache.py:plan_fusion`` / ``cycle_digest``), so a replayed cycle
goes straight from ``FRAME_RESPONSE_BATCH`` to a pre-compiled launch.
The cache is bounded and flushed through the same plan-memo
invalidation hook as the memoized fusion plans
(``Coordinator.set_fusion_threshold`` → :func:`flush`).

On multi-slice DCN deployments (``core/topology.replica_hierarchy``)
the ALLREDUCE reduction is lowered hierarchically — ``psum_scatter``
over ICI → ``psum`` over DCN → ``all_gather`` over ICI — which moves
``1/ici_size`` of the bytes over the slow DCN leg, optionally narrowed
to bf16/fp16 on that leg only (``HVD_TPU_DCN_COMPRESS``, reusing
ops/compression.py; cf. EQuARX, arXiv:2506.17615).

Env contract (docs/performance.md):
  HVD_TPU_MEGAKERNEL=0           fall back to the per-tensor eager
                                 executor (default on; the bench's
                                 comparison baseline)
  HVD_TPU_HIERARCHICAL=auto|on|off   see core/topology.py
  HVD_TPU_VIRTUAL_SLICES=<k>         see core/topology.py
  HVD_TPU_DCN_COMPRESS=none|bf16|fp16  DCN-leg wire dtype (default none)
"""

from __future__ import annotations

import functools
import math
import os
import sys
import time
import weakref
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import json

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..analysis import lockorder as _lockorder
from ..analysis import program as _program
from ..core import compat as _compat
from ..core import topology as _topology
from ..core.state import REPLICA_AXIS
from ..utils import xla_dispatch as _xla_dispatch
from . import compression as _compression
from .wire import ReduceOp

# Compiled-executable cache bound: a stable program needs one entry per
# (fusion group structure x mesh); jittery tick partitioning can mint a
# few orders, never hundreds — overflow means churn, so clear wholesale
# like the fusion-plan memo (ops/cache.py take_ready).
CACHE_CAPACITY = 128

DCN_COMPRESS_ENV = "HVD_TPU_DCN_COMPRESS"

# Persistent compile cache (hvd-pipeline): when set, (a) jax's XLA
# compilation cache persists to this directory (wired by core/state.init)
# and (b) every cold megakernel build appends its group structure to
# <dir>/megakernel_manifest.json, so an elastic relaunch — or any repeat
# run — can AOT-rebuild the steady-state executables at init time
# (:func:`warm_start`) and hit the disk cache instead of recompiling on
# the first training step.
COMPILE_CACHE_ENV = "HVD_TPU_COMPILE_CACHE_DIR"
MANIFEST_NAME = "megakernel_manifest.json"
MANIFEST_CAP = 256

_enabled_override: Optional[bool] = None


def enabled() -> bool:
    """Megakernel executor gate (default on); ``set_enabled`` overrides
    the env for in-process A/B runs (bench, tests)."""
    if _enabled_override is not None:
        return _enabled_override
    return os.environ.get("HVD_TPU_MEGAKERNEL", "1") != "0"


def set_enabled(value: Optional[bool]) -> None:
    """Force the executor on/off (``None`` restores the env gate)."""
    global _enabled_override
    _enabled_override = value


# Reduce-op kernel families the megakernel can lower.  ADASUM is absent
# by design: its per-tensor dot products are scale adaptations that the
# coordinator never fuses (ops/cache.plan_fusion) and that need the
# ladder/VHDD kernels of ops/collective.py.
_OPS = ("psum", "pmin", "pmax", "pprod")


@dataclass(frozen=True)
class Hierarchy:
    """Static hierarchical-reduction parameters baked into a kernel:
    the topology's ICI×DCN decomposition plus the DCN-leg wire dtype
    (None = uncompressed)."""

    topo: _topology.ReplicaHierarchy
    wire_dtype: Optional[str]


@dataclass(frozen=True)
class GroupSpec:
    """Cache key of one fused-group executable: everything that changes
    the traced program.  ``mesh_key`` is the tuple of jax Device
    OBJECTS (the same convention as ops/collective._kernels: a
    restarted backend's fresh devices miss naturally)."""

    mesh_key: Tuple[Any, ...]
    variant: str          # "sp_pr" | "sp_rep" | "mp"
    op: str               # _OPS member
    average: bool
    denom: int
    dtype: str
    shapes: Tuple[Tuple[int, ...], ...]
    donate: Tuple[bool, ...]
    hier: Optional[Hierarchy] = None


@dataclass
class MegakernelStats:
    builds: int = 0
    # hvd-telemetry: wall seconds constructing the jitted callables
    # (trace graph building) and — the dominant cost — the first
    # dispatch of each cold executable, which is where XLA compiles.
    # Surfaced as megakernel.build_seconds / megakernel.compile_seconds
    # gauges by the runtime collector (telemetry/__init__.py).
    build_seconds: float = 0.0
    compile_seconds: float = 0.0
    cache_hits: int = 0
    flushes: int = 0
    launches: int = 0
    # XLA executable launches observed DURING megakernel launches (only
    # populated under HVD_TPU_COUNT_DISPATCHES=1): the dispatch-count
    # regression contract is launch_dispatches == launches — exactly one
    # executable per fusion group, no eager-op creep.
    launch_dispatches: int = 0
    hier_launches: int = 0
    donated_inputs: int = 0
    # Executables AOT-rebuilt from the persistent-cache manifest at
    # init (warm_start) and the wall seconds it took — on a relaunch
    # with a warm XLA disk cache this is the recompile time saved from
    # the first training step.
    warm_starts: int = 0
    warm_seconds: float = 0.0


stats = MegakernelStats()

_lock = _lockorder.make_lock("megakernel._lock")
_compiled: Dict[GroupSpec, Callable] = {}  # guarded_by: _lock
_digests: Dict[GroupSpec, str] = {}  # guarded_by: _lock
_by_digest: Dict[str, GroupSpec] = {}  # guarded_by: _lock
# Donation-safety probes (tests): weakrefs of the inputs donated by the
# most recent launch — after the launch nothing in the runtime may hold
# them, so post-gc the refs must be dead.  Only recorded while dispatch
# counting is on; production launches skip the bookkeeping.
last_donated: List[weakref.ref] = []


def dcn_compress_name() -> str:
    return os.environ.get(DCN_COMPRESS_ENV, "none")


def flush(reason: str) -> None:
    """Drop every compiled executable (the plan-memo invalidation hook:
    fusion-threshold changes re-partition groups, so the old structures
    go cold — reclaim them instead of aging them out)."""
    with _lock:
        n = len(_compiled)
        _compiled.clear()
        _digests.clear()
        _by_digest.clear()
        stats.flushes += 1
    if n:
        print(f"[hvd-megakernel] cache flushed ({reason}): "
              f"{n} executables dropped", file=sys.stderr)


def cache_size() -> int:
    with _lock:
        return len(_compiled)


def digest_of(spec: GroupSpec) -> Optional[str]:
    """Fusion-plan digest a compiled spec was recorded under (tests)."""
    with _lock:
        return _digests.get(spec)


def spec_for_digest(digest: str) -> Optional[GroupSpec]:
    """Reverse lookup: the compiled group keyed by a plan digest — how
    bench/tests prove a replayed cycle lands on a pre-compiled
    executable."""
    with _lock:
        return _by_digest.get(digest)


def plan_digest(entries: Sequence[_program.SignatureEntry]) -> str:
    """The PR 2 fusion-plan digest of a group's signature entries
    (analysis/program.py's canonical scheme, shared with
    ops/cache.cycle_digest so cache diagnostics and executable records
    name a cycle identically)."""
    return _program.entries_digest(list(entries))


@functools.lru_cache(maxsize=64)
def _hierarchy_cached(mesh_key: Tuple, dtype: str, mode: str,
                      virtual: str, compress: str) -> Optional[Hierarchy]:
    # The env values are part of the key, so this memo needs no
    # invalidation: a changed knob is a different key (the O(n) device
    # scan + group-tuple construction runs once per configuration, not
    # once per fusion-group launch on the steady-state hot path).
    h = _topology.replica_hierarchy(mesh_key)
    if h is None:
        return None
    wire = _compression.wire_dtype_for(compress, jnp.dtype(dtype))
    return Hierarchy(
        topo=h,
        wire_dtype=(jnp.dtype(wire).name if wire is not None else None))


def hierarchy_for(mesh_devices: Tuple, op: str,
                  dtype) -> Optional[Hierarchy]:
    """The hierarchical-reduction plan for one group, or None for flat.

    Only the psum family decomposes (SUM/AVERAGE — the gradient path);
    the DCN wire dtype applies the compression.py applicability rule to
    the group's dtype at plan time so the kernel folds the casts."""
    if op != "psum":
        return None
    return _hierarchy_cached(
        tuple(mesh_devices), jnp.dtype(dtype).name,
        os.environ.get(_topology.HIERARCHICAL_ENV, "auto"),
        os.environ.get(_topology.VIRTUAL_SLICES_ENV, ""),
        dcn_compress_name())


# ---------------------------------------------------------------------------
# Kernel bodies
# ---------------------------------------------------------------------------

def _numel(shape: Tuple[int, ...]) -> int:
    return int(math.prod(shape)) if shape else 1


def _reduce_flat(spec: GroupSpec):
    """flat [T] local vector -> [T] reduced (replicated across the
    group's axis) — the collective core of every megakernel."""
    hier = spec.hier

    def reduce_fn(v):
        if spec.op == "pmin":
            return jax.lax.pmin(v, REPLICA_AXIS)
        if spec.op == "pmax":
            return jax.lax.pmax(v, REPLICA_AXIS)
        if spec.op == "pprod":
            # No lax.pprod exists: gather + local product, like the
            # per-tensor kernels (XLA fuses the pointwise product into
            # the gather's consumer).
            return jnp.prod(
                jax.lax.all_gather(v, REPLICA_AXIS, axis=0), axis=0)
        if hier is None:
            return jax.lax.psum(v, REPLICA_AXIS)
        # Hierarchical ICI x DCN: scatter-reduce inside the slice, sum
        # the 1/ici_size fragments across slices (optionally narrowed on
        # that slow leg only), then re-gather inside the slice.
        L = v.shape[0]
        pad = (-L) % hier.topo.ici_size
        if pad:
            v = jnp.concatenate([v, jnp.zeros((pad,), v.dtype)])
        ici = [list(g) for g in hier.topo.ici_groups]
        dcn = [list(g) for g in hier.topo.dcn_groups]
        s = jax.lax.psum_scatter(v, REPLICA_AXIS, scatter_dimension=0,
                                 tiled=True, axis_index_groups=ici)
        if hier.wire_dtype is not None:
            s = jax.lax.psum(s.astype(jnp.dtype(hier.wire_dtype)),
                             REPLICA_AXIS,
                             axis_index_groups=dcn).astype(v.dtype)
        else:
            s = jax.lax.psum(s, REPLICA_AXIS, axis_index_groups=dcn)
        g = jax.lax.all_gather(s, REPLICA_AXIS, axis=0, tiled=True,
                               axis_index_groups=ici)
        return g[:L] if pad else g

    return reduce_fn


def _unpack(spec: GroupSpec, red, lead: Tuple[int, ...]):
    """Split the reduced flat buffer back into the group's payload
    shapes, folding the AVERAGE divide (floor division for integer
    dtypes — the `_divide` contract of ops/collective.py)."""
    outs = []
    offs = 0
    integral = not jnp.issubdtype(jnp.dtype(spec.dtype), jnp.inexact)
    for shp in spec.shapes:
        cnt = _numel(shp)
        piece = red[..., offs:offs + cnt].reshape(lead + shp)
        offs += cnt
        if spec.average:
            piece = piece // spec.denom if integral else piece / spec.denom
        outs.append(piece)
    return tuple(outs)


def _build(spec: GroupSpec, mesh) -> Callable:
    """Trace + wrap one group executable: pack → reduce → unpack in a
    single XLA program over ``mesh``, donated on the owned inputs."""
    reduce_fn = _reduce_flat(spec)

    if spec.variant == "sp_pr":
        # Single-process, per-replica [n, *payload] inputs sharded over
        # the replica axis; outputs keep the layout (every row = the
        # reduction, Horovod's allreduce contract).
        def body(*ts):
            flat = jnp.concatenate(
                [t.reshape((t.shape[0], -1)) for t in ts], axis=1)
            red = reduce_fn(jnp.squeeze(flat, 0))[None]
            return _unpack(spec, red, (1,))

        in_specs = tuple(P(REPLICA_AXIS) for _ in spec.shapes)
        out_specs = tuple(P(REPLICA_AXIS) for _ in spec.shapes)
    elif spec.variant == "sp_rep":
        # Replicated inputs: every replica contributes the same value;
        # psum multiplies by the axis size exactly like the honest
        # per-tensor psum_rep kernel.
        def body(*ts):
            flat = jnp.concatenate([t.reshape(-1) for t in ts])
            red = reduce_fn(flat)
            return _unpack(spec, red, ())

        in_specs = tuple(P() for _ in spec.shapes)
        out_specs = tuple(P() for _ in spec.shapes)
    elif spec.variant == "mp":
        # Multi-process: one packed [P, T] fusion buffer (each process
        # contributed its flat shard), replicated payload outputs.
        def body(buf):
            red = reduce_fn(jnp.squeeze(buf, 0))
            return _unpack(spec, red, ())

        in_specs = (P(REPLICA_AXIS),)
        out_specs = tuple(P() for _ in spec.shapes)
    else:
        raise ValueError(f"unknown megakernel variant {spec.variant!r}")

    donate = tuple(i for i, d in enumerate(spec.donate) if d)
    return jax.jit(
        _compat.shard_map(body, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, check_vma=False),
        donate_argnums=donate)


def _pack_key(shapes, dtype, donate, mesh_key):
    return GroupSpec(mesh_key=mesh_key, variant="pack", op="psum",
                     average=False, denom=1, dtype=dtype, shapes=shapes,
                     donate=donate)


def _cache_insert(spec: GroupSpec, fn: Callable,
                  digest: Optional[str] = None,
                  seconds: float = 0.0) -> None:
    """Bounded insert shared by :func:`packer` and :func:`executable`:
    on overflow the whole table clears (wholesale, like the fusion-plan
    memo) rather than aging entries out."""
    with _lock:
        if len(_compiled) >= CACHE_CAPACITY:
            _compiled.clear()
            _digests.clear()
            _by_digest.clear()
            stats.flushes += 1
        _compiled[spec] = fn
        if digest is not None:
            _digests[spec] = digest
            _by_digest[digest] = spec
        stats.builds += 1
        stats.build_seconds += seconds


def packer(shapes: Tuple[Tuple[int, ...], ...], dtype: str,
           donate: Tuple[bool, ...], mesh_key) -> Callable:
    """Jitted local pack (multi-process leg): flatten + concatenate the
    group's local contributions into one fusion buffer in a single
    dispatch, donating the executor-owned inputs."""
    spec = _pack_key(shapes, dtype, donate, mesh_key)
    with _lock:
        fn = _compiled.get(spec)
        if fn is not None:
            stats.cache_hits += 1
            return fn
    fn = jax.jit(
        lambda *ts: jnp.concatenate([t.reshape(-1) for t in ts]),
        donate_argnums=tuple(i for i, d in enumerate(donate) if d))
    _cache_insert(spec, fn)
    return fn


def executable(spec: GroupSpec, mesh,
               digest_fn: Optional[Callable[[], str]] = None
               ) -> Tuple[Callable, bool]:
    """The compiled megakernel for ``spec`` — cached, bounded, recorded
    under its fusion-plan digest on the cold build (``digest_fn`` is
    only invoked then, keeping the hot path free of hashing).  Returns
    ``(fn, built)``: ``built`` tells THIS caller whether it did the
    cold build, so launch() can attribute the first (compiling)
    dispatch without racing other threads' builds."""
    with _lock:
        fn = _compiled.get(spec)
        if fn is not None:
            stats.cache_hits += 1
            return fn, False
    t0 = time.perf_counter()
    fn = _build(spec, mesh)
    digest = digest_fn() if digest_fn is not None else None
    _cache_insert(spec, fn, digest,
                  seconds=time.perf_counter() - t0)
    _record_manifest(spec, digest)  # cold builds only; no-op without env
    return fn, True


# ---------------------------------------------------------------------------
# Persistent compile cache: manifest + AOT warm start (hvd-pipeline)
# ---------------------------------------------------------------------------

def compile_cache_dir() -> Optional[str]:
    return os.environ.get(COMPILE_CACHE_ENV) or None


def _mesh_fingerprint(mesh_key) -> dict:
    d0 = mesh_key[0]
    return {"platform": getattr(d0, "platform", "?"),
            "device_kind": getattr(d0, "device_kind", "?"),
            "count": len(mesh_key)}


def _manifest_entry(spec: GroupSpec, digest: Optional[str]) -> dict:
    return {
        "variant": spec.variant,
        "op": spec.op,
        "average": spec.average,
        "denom": spec.denom,
        "dtype": spec.dtype,
        "shapes": [list(s) for s in spec.shapes],
        "donate": list(spec.donate),
        "hier": spec.hier is not None,
        "digest": digest,
        "mesh": _mesh_fingerprint(spec.mesh_key),
    }


def load_manifest(directory: str) -> List[dict]:
    path = os.path.join(directory, MANIFEST_NAME)
    try:
        with open(path) as f:
            data = json.load(f)
        entries = data.get("entries", [])
        return entries if isinstance(entries, list) else []
    except (OSError, ValueError):
        return []


def _record_manifest(spec: GroupSpec, digest: Optional[str]) -> None:
    """Best-effort append of one cold build to the persistent-cache
    manifest (dedup by structure, bounded, atomic rename; never takes
    the executable lock — file IO must not nest inside it).  Only the
    single-process group variants are recorded: the mp variant's mesh
    and packed-buffer layout are incarnation-specific."""
    d = compile_cache_dir()
    if d is None or spec.variant not in ("sp_pr", "sp_rep"):
        return
    try:
        entry = _manifest_entry(spec, digest)
        entries = load_manifest(d)
        key = {k: v for k, v in entry.items() if k != "digest"}
        if any({k: v for k, v in e.items() if k != "digest"} == key
               for e in entries):
            return
        entries.append(entry)
        entries = entries[-MANIFEST_CAP:]
        os.makedirs(d, exist_ok=True)
        path = os.path.join(d, MANIFEST_NAME)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump({"format": "hvd-megakernel-manifest-v1",
                       "entries": entries}, f, indent=1)
        os.replace(tmp, path)
    except Exception:  # noqa: BLE001 — the manifest is an optimization
        pass


def _warm_avals(spec: GroupSpec, mesh) -> List[jax.ShapeDtypeStruct]:
    """Abstract inputs for AOT-lowering one recorded group executable
    (global shapes + shardings exactly as launch() passes them)."""
    n = len(spec.mesh_key)
    dtype = jnp.dtype(spec.dtype)
    if spec.variant == "sp_pr":
        sh = NamedSharding(mesh, P(REPLICA_AXIS))
        return [jax.ShapeDtypeStruct((n,) + shp, dtype, sharding=sh)
                for shp in spec.shapes]
    sh = NamedSharding(mesh, P())
    return [jax.ShapeDtypeStruct(shp, dtype, sharding=sh)
            for shp in spec.shapes]


def warm_start(mesh, directory: Optional[str] = None) -> int:
    """AOT-rebuild the manifest's group executables for ``mesh``.

    Called by ``hvd.init()`` when ``HVD_TPU_COMPILE_CACHE_DIR`` is set:
    every recorded group whose mesh fingerprint matches is re-traced and
    compiled ahead of the first training step — against a warm XLA disk
    cache the compile is a cache read, so an elastic relaunch resumes at
    full step rate instead of paying the cold-compile stall mid-loop.
    Hierarchy is recomputed from the CURRENT env/topology (the knobs may
    legitimately differ across incarnations).  Best-effort per entry;
    returns the number of executables warmed."""
    d = directory or compile_cache_dir()
    if d is None:
        return 0
    fp = _mesh_fingerprint(tuple(mesh.devices.flat))
    mesh_key = tuple(mesh.devices.flat)
    warmed = 0
    t0 = time.perf_counter()
    for entry in load_manifest(d):
        if entry.get("mesh") != fp:
            continue
        if entry.get("variant") not in ("sp_pr", "sp_rep"):
            continue
        try:
            spec = GroupSpec(
                mesh_key=mesh_key, variant=entry["variant"],
                op=entry["op"], average=bool(entry["average"]),
                denom=int(entry["denom"]), dtype=entry["dtype"],
                shapes=tuple(tuple(s) for s in entry["shapes"]),
                donate=tuple(bool(x) for x in entry["donate"]),
                hier=hierarchy_for(mesh_key, entry["op"], entry["dtype"]))
            with _lock:
                if spec in _compiled:
                    continue
            fn = _build(spec, mesh)
            fn.lower(*_warm_avals(spec, mesh)).compile()
            _cache_insert(spec, fn, entry.get("digest"))
            warmed += 1
        except Exception:  # noqa: BLE001 — a stale entry must not
            continue       # break init; the group just compiles lazily
    if warmed:
        with _lock:
            stats.warm_starts += warmed
            stats.warm_seconds += time.perf_counter() - t0
        print(f"[hvd-megakernel] warm start: {warmed} executables "
              f"rebuilt from {os.path.join(d, MANIFEST_NAME)}",
              file=sys.stderr)
    return warmed


def launch(spec: GroupSpec, mesh, values: Sequence,
           digest_fn: Optional[Callable[[], str]] = None):
    """One megakernel dispatch for a fusion group.  Under dispatch
    counting (tests/bench) the launch is wrapped in a thread-local
    window and the observed executable count is accumulated on
    ``stats`` — the "exactly one dispatch per group" regression
    contract — and the donated inputs are recorded as weakrefs for the
    use-after-donate probe."""
    fn, cold = executable(spec, mesh, digest_fn)

    def dispatch():
        # XLA compiles on the cold executable's FIRST dispatch; time
        # exactly that call (one perf_counter pair, cold path only) so
        # megakernel.compile_seconds reports real compilation cost.
        if not cold:
            return fn(*values)
        t0 = time.perf_counter()
        out = fn(*values)
        with _lock:
            stats.compile_seconds += time.perf_counter() - t0
        return out

    counting = _xla_dispatch.counting_enabled()
    if counting:
        probes = [weakref.ref(v)
                  for v, d in zip(values, spec.donate) if d]
        with _xla_dispatch.record() as scope:
            outs = dispatch()
        with _lock:
            stats.launches += 1
            stats.launch_dispatches += scope.count
            stats.donated_inputs += sum(spec.donate)
            if spec.hier is not None:
                stats.hier_launches += 1
            last_donated[:] = probes
    else:
        outs = dispatch()
        with _lock:
            stats.launches += 1
            stats.donated_inputs += sum(spec.donate)
            if spec.hier is not None:
                stats.hier_launches += 1
    return outs
