"""Fused data-plane megakernels for the eager collective executor.

PR 2 deleted the steady state's control-plane cost (response cache +
replayed fusion plans); what remained of the per-step tax was data-plane
dispatch: ``ops/collective._execute_response`` surrounded each jitted
collective with a Python loop of *eager* XLA dispatches — a
``jnp.concatenate`` pack, per-tensor slice/reshape unpacks, a separate
divide launch for AVERAGE — with no buffer donation and no executable
reuse tied to the cached plans.  That is exactly the fusion-buffer copy
overhead the original Horovod paper identifies as the small-tensor
scaling wall (arXiv:1802.05799 §4), re-materialized as host dispatch
latency instead of memcpy bandwidth.

This module replaces that choreography with **one jitted, donated
megakernel per fusion group**: a shape/dtype/layout/reduce-op/mesh-keyed
executable that packs the group's tensors into a flat fusion buffer,
runs the collective once, folds the AVERAGE divide in, and unpacks to
the result tensors *inside a single XLA program* — the compiler fuses
the copies into the collective and the drain thread performs exactly
one dispatch per group (asserted by tests/test_megakernel.py via
utils/xla_dispatch.py).  ``donate_argnums`` covers every input buffer
the executor itself owns (host-converted contributions, the packed
multi-process fusion buffer), so the steady state stops allocating; the
user's own arrays are never donated.

Compiled executables are cached per group structure and recorded under
the fusion-plan digest of the PR 2 response cache
(``ops/cache.py:plan_fusion`` / ``cycle_digest``), so a replayed cycle
goes straight from ``FRAME_RESPONSE_BATCH`` to a pre-compiled launch.
The cache is bounded and flushed through the same plan-memo
invalidation hook as the memoized fusion plans
(``Coordinator.set_fusion_threshold`` → :func:`flush`).

On multi-slice DCN deployments (``core/topology.replica_hierarchy``)
the ALLREDUCE reduction is lowered hierarchically — ``psum_scatter``
over ICI → ``psum`` over DCN → ``all_gather`` over ICI — which moves
``1/ici_size`` of the bytes over the slow DCN leg, with each leg's wire
format composing independently (``HVD_TPU_DCN_COMPRESS`` /
``HVD_TPU_ICI_COMPRESS``: full precision, bf16/fp16 casts, or int8/int4
quantized exchanges; cf. EQuARX, arXiv:2506.17615).

Quantized reduction (this PR's tentpole): when the compression policy
(ops/compression.py, ``hvd.set_compression`` / ``HVD_TPU_COMPRESSION``)
selects int8/int4 for a fusion group, the pack→reduce→unpack executable
compiles the block-scaled quantize → wire exchange → dequantize
pipeline INTO the same single XLA program — zero extra dispatches —
with stochastic rounding (seeded per step via the ``st`` input, so the
executable is reused across steps) and **error-feedback residuals**:
per-tensor state owned by this executor, added to the next step's
contribution inside the kernel, flushed with the executable cache on
plan invalidation, and checkpoint-restorable
(:func:`compression_state` / :func:`load_compression_state`).

Env contract (docs/performance.md):
  HVD_TPU_MEGAKERNEL=0           fall back to the per-tensor eager
                                 executor (default on; the bench's
                                 comparison baseline)
  HVD_TPU_HIERARCHICAL=auto|on|off   see core/topology.py
  HVD_TPU_VIRTUAL_SLICES=<k>         see core/topology.py
  HVD_TPU_DCN_COMPRESS=none|bf16|fp16|int8|int4
                                 DCN-leg wire format (default: inherit
                                 the group's quantized format, else
                                 full precision)
  HVD_TPU_ICI_COMPRESS=none|int8|int4  ICI-leg wire format (default
                                 none = full precision)
  HVD_TPU_COMPRESSION / HVD_TPU_QUANT_*  see ops/compression.py
"""

from __future__ import annotations

import functools
import math
import os
import sys
import time
import weakref
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import json

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..analysis import donation as _donation
from ..analysis import lockorder as _lockorder
from ..analysis import program as _program
from ..core import compat as _compat
from ..core import topology as _topology
from ..core.state import REPLICA_AXIS
from ..utils import xla_dispatch as _xla_dispatch
from .. import telemetry as _telemetry
from .. import trace as _trace
from ..memory import ledger as _mem
from ..memory import oom as _oom
from ..memory import planner as _mem_planner
from . import compression as _compression
from .wire import ReduceOp

# hvd-telemetry (docs/metrics.md): per-launch bytes the fused
# collective moves in WIRE format — the quantized-allreduce observable
# (the matching logical bytes ride MegakernelStats and surface as the
# compression.ratio gauge).
_M_WIRE_BYTES = _telemetry.histogram(
    "collective.wire_bytes", "bytes",
    "wire-format bytes per fused collective launch")

# Compiled-executable cache bound: a stable program needs one entry per
# (fusion group structure x mesh); jittery tick partitioning can mint a
# few orders, never hundreds — overflow means churn, so clear wholesale
# like the fusion-plan memo (ops/cache.py take_ready).
CACHE_CAPACITY = 128

DCN_COMPRESS_ENV = "HVD_TPU_DCN_COMPRESS"
ICI_COMPRESS_ENV = "HVD_TPU_ICI_COMPRESS"

# Persistent compile cache (hvd-pipeline): when set, (a) jax's XLA
# compilation cache persists to this directory (wired by core/state.init)
# and (b) every cold megakernel build appends its group structure to
# <dir>/megakernel_manifest.json, so an elastic relaunch — or any repeat
# run — can AOT-rebuild the steady-state executables at init time
# (:func:`warm_start`) and hit the disk cache instead of recompiling on
# the first training step.
COMPILE_CACHE_ENV = "HVD_TPU_COMPILE_CACHE_DIR"
MANIFEST_NAME = "megakernel_manifest.json"
MANIFEST_CAP = 256

_enabled_override: Optional[bool] = None


def enabled() -> bool:
    """Megakernel executor gate (default on); ``set_enabled`` overrides
    the env for in-process A/B runs (bench, tests)."""
    if _enabled_override is not None:
        return _enabled_override
    return os.environ.get("HVD_TPU_MEGAKERNEL", "1") != "0"


def set_enabled(value: Optional[bool]) -> None:
    """Force the executor on/off (``None`` restores the env gate)."""
    global _enabled_override
    _enabled_override = value


# Reduce-op kernel families the megakernel can lower.  ADASUM is absent
# by design: its per-tensor dot products are scale adaptations that the
# coordinator never fuses (ops/cache.plan_fusion) and that need the
# ladder/VHDD kernels of ops/collective.py.
_OPS = ("psum", "pmin", "pmax", "pprod")


@dataclass(frozen=True)
class Hierarchy:
    """Static hierarchical-reduction parameters baked into a kernel:
    the topology's ICI×DCN decomposition plus each leg's wire format —
    ``wire_dtype`` is the DCN cast narrowing (bf16/fp16), ``dcn_quant``
    / ``ici_quant`` the quantized exchange formats (ops/compression.py
    WireFormat); None everywhere = full precision."""

    topo: _topology.ReplicaHierarchy
    wire_dtype: Optional[str]
    dcn_quant: Optional[_compression.WireFormat] = None
    ici_quant: Optional[_compression.WireFormat] = None


@dataclass(frozen=True)
class GroupSpec:
    """Cache key of one fused-group executable: everything that changes
    the traced program.  ``mesh_key`` is the tuple of jax Device
    OBJECTS (the same convention as ops/collective._kernels: a
    restarted backend's fresh devices miss naturally).  ``quant`` is
    the group's wire format from the compression policy (None = full
    precision; "cast" folds dtype narrowing around the reduction;
    "quant" compiles the int8/int4 pipeline in)."""

    mesh_key: Tuple[Any, ...]
    variant: str          # "sp_pr" | "sp_rep" | "mp"
    op: str               # _OPS member
    average: bool
    denom: int
    dtype: str
    shapes: Tuple[Tuple[int, ...], ...]
    donate: Tuple[bool, ...]
    hier: Optional[Hierarchy] = None
    quant: Optional[_compression.WireFormat] = None


@dataclass
class MegakernelStats:
    builds: int = 0
    # hvd-telemetry: wall seconds constructing the jitted callables
    # (trace graph building) and — the dominant cost — the first
    # dispatch of each cold executable, which is where XLA compiles.
    # Surfaced as megakernel.build_seconds / megakernel.compile_seconds
    # gauges by the runtime collector (telemetry/__init__.py).
    build_seconds: float = 0.0
    compile_seconds: float = 0.0
    cache_hits: int = 0
    flushes: int = 0
    launches: int = 0
    # XLA executable launches observed DURING megakernel launches (only
    # populated under HVD_TPU_COUNT_DISPATCHES=1): the dispatch-count
    # regression contract is launch_dispatches == launches — exactly one
    # executable per fusion group, no eager-op creep.
    launch_dispatches: int = 0
    hier_launches: int = 0
    donated_inputs: int = 0
    # Executables AOT-rebuilt from the persistent-cache manifest at
    # init (warm_start) and the wall seconds it took — on a relaunch
    # with a warm XLA disk cache this is the recompile time saved from
    # the first training step.
    warm_starts: int = 0
    warm_seconds: float = 0.0
    # Bytes-on-wire accounting (quantized allreduce): logical_bytes is
    # what the collective's payload traversals would move uncompressed,
    # wire_bytes what they move in the launched kernels' wire formats
    # (codes + block scales; per-leg on hierarchical launches).  The
    # ratio is surfaced as the compression.ratio gauge and in
    # bench.py --mode dataplane's bytes-on-wire section.
    logical_bytes: int = 0
    wire_bytes: int = 0
    quant_launches: int = 0


stats = MegakernelStats()

_lock = _lockorder.make_lock("megakernel._lock")
_compiled: Dict[GroupSpec, Callable] = {}  # guarded_by: _lock
_digests: Dict[GroupSpec, str] = {}  # guarded_by: _lock
_by_digest: Dict[str, GroupSpec] = {}  # guarded_by: _lock
# Error-feedback residual state (quantized allreduce), owned by the
# executor: ONE flat buffer per fusion group, keyed
# ("g", process_set_id, name_1, ..., name_k) — the concatenation of the
# group's per-tensor residuals in pack order (per-tensor kernel
# arguments would double the executable's arity and jax's per-array
# dispatch cost; the steady state's grouping is stable thanks to the
# PR 2 cached fusion plans, and a re-partition resets the affected
# tensors' error history to zero, which costs one step of correction,
# never correctness).  Flushed with the executable cache (plan
# invalidation re-partitions groups) and checkpoint-restorable via
# compression_state()/load_compression_state.
_residuals: Dict[Tuple, Any] = {}  # guarded_by: _lock
# Per-fusion-group launch counters: the stochastic-rounding tick.  The
# kernel takes (seed, tick) as a runtime input, so one compiled
# executable serves every step while the noise stays step-unique and —
# under a fixed HVD_TPU_QUANT_SEED — bitwise reproducible.
_ticks: Dict[Tuple, int] = {}  # guarded_by: _lock
# Donation-safety probes (tests): weakrefs of the inputs donated by the
# most recent launch — after the launch nothing in the runtime may hold
# them, so post-gc the refs must be dead.  Only recorded while dispatch
# counting is on; production launches skip the bookkeeping.
last_donated: List[weakref.ref] = []


def dcn_compress_name() -> str:
    """The DCN-leg compressor name; "" when the knob is UNSET — unset
    means "inherit the group's quantized format", while an explicit
    ``none`` pins the leg to full precision (the opt-out)."""
    return os.environ.get(DCN_COMPRESS_ENV, "")


def ici_compress_name() -> str:
    return os.environ.get(ICI_COMPRESS_ENV, "none")


def flush(reason: str) -> None:
    """Drop every compiled executable AND the quantization state (the
    plan-memo invalidation hook: fusion-threshold changes re-partition
    groups, so the old structures — and the error-feedback residuals
    accumulated against them — go cold; reclaim instead of aging
    out)."""
    with _lock:
        n = len(_compiled)
        nr = len(_residuals)
        _compiled.clear()
        _digests.clear()
        _by_digest.clear()
        _residuals.clear()
        _ticks.clear()
        stats.flushes += 1
    _sync_residual_ledger()
    if n or nr:
        print(f"[hvd-megakernel] cache flushed ({reason}): "
              f"{n} executables, {nr} residual tensors dropped",
              file=sys.stderr)


def cache_size() -> int:
    with _lock:
        return len(_compiled)


# ---------------------------------------------------------------------------
# Quantization state: error-feedback residuals + stochastic-rounding ticks
# ---------------------------------------------------------------------------

def next_tick(group_key: Tuple) -> int:
    """This launch's stochastic-rounding tick for one fusion group
    (0, 1, 2, ... per group identity) — both executor paths (fused and
    eager-reference) draw from the same counter, so the noise stream is
    a property of the PROGRAM, not of which executor ran it."""
    with _lock:
        t = _ticks.get(group_key, 0)
        _ticks[group_key] = t + 1
        return t


def take_residual(key: Tuple, dtype,
                  shapes: Sequence[Tuple[int, ...]]) -> Optional[Any]:
    """REMOVE and return the stored error-feedback residual for
    ``key``, or None when absent/stale (first use, post-flush, changed
    group shape).  Take-semantics on purpose: the caller donates the
    buffer into the launch, and the store must never keep a reference
    to soon-to-be-deleted device memory — a concurrent
    :func:`compression_state` (e.g. the background-checkpoint snapshot)
    would otherwise read a deleted array.  ``shapes`` lists the
    acceptable shapes (the mp path accepts both its live [P, T] global
    array and a checkpoint-restored local [T])."""
    with _lock:
        r = _residuals.pop(key, None)
    _sync_residual_ledger()
    if r is None \
            or not any(tuple(r.shape) == tuple(s) for s in shapes) \
            or str(r.dtype) != str(jnp.dtype(dtype)) \
            or (isinstance(r, jax.Array) and r.is_deleted()):
        return None
    return r


def store_residuals(keys: Sequence[Tuple], arrays: Sequence) -> None:
    with _lock:
        for key, arr in zip(keys, arrays):
            _residuals[key] = arr
    _sync_residual_ledger()


def drop_residuals(keys: Sequence[Tuple]) -> None:
    """Forget residual entries whose buffers were donated into a launch
    that then FAILED — they reference deleted device memory and must
    restart from zero rather than poison the next launch."""
    with _lock:
        for key in keys:
            _residuals.pop(key, None)
    _sync_residual_ledger()


def _sync_residual_ledger() -> None:
    """hvd-mem: mirror the EF residual store's byte total into the
    device-memory ledger (``megakernel.residuals``) — the store is the
    one long-lived executor-owned buffer set, so the ledger carries its
    absolute size rather than alloc/free deltas.  NOT gated on
    telemetry enablement: a flush/drop landing while an A/B leg has
    telemetry off must still clear the figure, or the ledger reports
    phantom residual bytes forever after re-enable (the frees in
    input.py/checkpoint.py are unconditional for the same reason);
    the cost is one dict walk per residual transition, nowhere near a
    hot path."""
    with _lock:
        arrays = list(_residuals.values())
    total = 0
    for v in arrays:
        nb = getattr(v, "nbytes", None)
        if nb:
            try:
                total += int(nb)
            except (TypeError, ValueError):
                pass
    _mem.ledger.set("megakernel.residuals", total)


def residual_count() -> int:
    with _lock:
        return len(_residuals)


def compression_state() -> Dict[str, Dict[str, Any]]:
    """Checkpoint-portable snapshot of the quantization state: the
    error-feedback residuals (host numpy) and per-group ticks.  Save it
    alongside the model tree and hand it back to
    :func:`load_compression_state` after restore, so a resumed run
    continues the telescoping error correction instead of restarting it
    (exported as ``hvd.compression_state``)."""
    import numpy as np

    with _lock:
        items = list(_residuals.items())
        ticks = {json.dumps(list(k)): int(v) for k, v in _ticks.items()}
    res = {}
    for k, v in items:
        if isinstance(v, jax.Array):
            if v.is_deleted():
                continue  # donated into an in-flight launch: skip
            if not v.is_fully_addressable:
                # mp residual: a [P, T] global — export this process's
                # local [T] shard (what the restore path re-uploads).
                v = np.asarray(v.addressable_data(0))[0]
        res[json.dumps(list(k))] = np.asarray(v)
    return {"residuals": res, "ticks": ticks}


def load_compression_state(state: Dict[str, Dict[str, Any]]) -> None:
    """Restore a :func:`compression_state` snapshot (exported as
    ``hvd.load_compression_state``)."""
    import numpy as np

    res = {tuple(json.loads(k)): np.asarray(v)
           for k, v in (state.get("residuals") or {}).items()}
    ticks = {tuple(json.loads(k)): int(v)
             for k, v in (state.get("ticks") or {}).items()}
    with _lock:
        _residuals.clear()
        _residuals.update(res)
        _ticks.clear()
        _ticks.update(ticks)
    _sync_residual_ledger()


def digest_of(spec: GroupSpec) -> Optional[str]:
    """Fusion-plan digest a compiled spec was recorded under (tests)."""
    with _lock:
        return _digests.get(spec)


def spec_for_digest(digest: str) -> Optional[GroupSpec]:
    """Reverse lookup: the compiled group keyed by a plan digest — how
    bench/tests prove a replayed cycle lands on a pre-compiled
    executable."""
    with _lock:
        return _by_digest.get(digest)


def plan_digest(entries: Sequence[_program.SignatureEntry],
                quant: Optional[_compression.WireFormat] = None) -> str:
    """The PR 2 fusion-plan digest of a group's signature entries
    (analysis/program.py's canonical scheme, shared with
    ops/cache.cycle_digest so cache diagnostics and executable records
    name a cycle identically).  The quantization spec is folded in —
    the same tensor program under a different codebook is a different
    compiled plan, and their records must never collide."""
    base = _program.entries_digest(list(entries))
    if quant is None:
        return base
    import hashlib

    return hashlib.sha256(
        f"{base}|{quant}".encode("utf-8")).hexdigest()[:len(base)]


@functools.lru_cache(maxsize=64)
def _hierarchy_cached(mesh_key: Tuple, dtype: str, mode: str,
                      virtual: str, dcn: str, ici: str,
                      group_name: str) -> Optional[Hierarchy]:
    # The env values are part of the key, so this memo needs no
    # invalidation: a changed knob is a different key (the O(n) device
    # scan + group-tuple construction runs once per configuration, not
    # once per fusion-group launch on the steady-state hot path).
    h = _topology.replica_hierarchy(mesh_key)
    if h is None:
        return None

    def quant_fmt(name):
        # Leg formats gate on dtype only — the whole fusion buffer
        # rides the leg, so the per-tensor min-elems floor is moot.
        fmt = _compression.wire_format_for(name, jnp.dtype(dtype),
                                           1 << 30)
        return fmt if fmt is not None and fmt.kind == "quant" else None

    wire = _compression.wire_dtype_for(dcn or "none", jnp.dtype(dtype))
    dcn_q = quant_fmt(dcn) if dcn else None
    if dcn == "" and dcn_q is None and wire is None and group_name:
        # Per-leg composition default: a group whose policy selected a
        # quantized format keeps it on the slow DCN leg when
        # HVD_TPU_DCN_COMPRESS is UNSET; an explicit value — including
        # ``none`` — overrides (the full-precision-DCN opt-out).  The
        # ICI legs stay full precision unless HVD_TPU_ICI_COMPRESS
        # opts them in.
        dcn_q = quant_fmt(group_name)
    return Hierarchy(
        topo=h,
        wire_dtype=(jnp.dtype(wire).name if wire is not None else None),
        dcn_quant=dcn_q, ici_quant=quant_fmt(ici))


def hierarchy_for(mesh_devices: Tuple, op: str, dtype,
                  group_fmt=None) -> Optional[Hierarchy]:
    """The hierarchical-reduction plan for one group, or None for flat.

    Only the psum family decomposes (SUM/AVERAGE — the gradient path);
    each leg's wire format applies the compression.py applicability
    rule to the group's dtype at plan time so the kernel folds the
    casts/codecs.  ``group_fmt`` (the group's policy WireFormat) feeds
    the DCN-leg inheritance default."""
    if op != "psum":
        return None
    return _hierarchy_cached(
        tuple(mesh_devices), jnp.dtype(dtype).name,
        os.environ.get(_topology.HIERARCHICAL_ENV, "auto"),
        os.environ.get(_topology.VIRTUAL_SLICES_ENV, ""),
        dcn_compress_name(), ici_compress_name(),
        group_fmt.name if (group_fmt is not None
                           and group_fmt.kind == "quant") else "")


# ---------------------------------------------------------------------------
# Kernel bodies
# ---------------------------------------------------------------------------

def _numel(shape: Tuple[int, ...]) -> int:
    return int(math.prod(shape)) if shape else 1


def _reduce_flat(spec: GroupSpec):
    """flat [T] local vector -> [T] reduced (replicated across the
    group's axis) — the collective core of every megakernel."""
    hier = spec.hier

    def reduce_fn(v):
        if spec.op == "pmin":
            return jax.lax.pmin(v, REPLICA_AXIS)
        if spec.op == "pmax":
            return jax.lax.pmax(v, REPLICA_AXIS)
        if spec.op == "pprod":
            # No lax.pprod exists: gather + local product, like the
            # per-tensor kernels (XLA fuses the pointwise product into
            # the gather's consumer).
            return jnp.prod(
                jax.lax.all_gather(v, REPLICA_AXIS, axis=0), axis=0)
        if hier is None:
            return jax.lax.psum(v, REPLICA_AXIS)
        # Hierarchical ICI x DCN: scatter-reduce inside the slice, sum
        # the 1/ici_size fragments across slices (optionally narrowed on
        # that slow leg only), then re-gather inside the slice.
        L = v.shape[0]
        pad = (-L) % hier.topo.ici_size
        if pad:
            v = jnp.concatenate([v, jnp.zeros((pad,), v.dtype)])
        ici = [list(g) for g in hier.topo.ici_groups]
        dcn = [list(g) for g in hier.topo.dcn_groups]
        s = jax.lax.psum_scatter(v, REPLICA_AXIS, scatter_dimension=0,
                                 tiled=True, axis_index_groups=ici)
        if hier.wire_dtype is not None:
            s = jax.lax.psum(s.astype(jnp.dtype(hier.wire_dtype)),
                             REPLICA_AXIS,
                             axis_index_groups=dcn).astype(v.dtype)
        else:
            s = jax.lax.psum(s, REPLICA_AXIS, axis_index_groups=dcn)
        g = jax.lax.all_gather(s, REPLICA_AXIS, axis=0, tiled=True,
                               axis_index_groups=ici)
        return g[:L] if pad else g

    if spec.quant is not None and spec.quant.kind == "cast":
        # Policy-selected cast compression (bf16/fp16): the whole
        # reduction runs in the wire dtype, restored on unpack —
        # decompress-then-divide order, like the eager compressors.
        wire = jnp.dtype(spec.quant.wire_dtype)
        inner = reduce_fn

        def reduce_cast(v):
            return inner(v.astype(wire)).astype(v.dtype)

        return reduce_cast
    return reduce_fn


def _unpack(spec: GroupSpec, red, lead: Tuple[int, ...]):
    """Split the reduced flat buffer back into the group's payload
    shapes, folding the AVERAGE divide (floor division for integer
    dtypes — the `_divide` contract of ops/collective.py)."""
    outs = []
    offs = 0
    integral = not jnp.issubdtype(jnp.dtype(spec.dtype), jnp.inexact)
    for shp in spec.shapes:
        cnt = _numel(shp)
        piece = red[..., offs:offs + cnt].reshape(lead + shp)
        offs += cnt
        if spec.average:
            piece = piece // spec.denom if integral else piece / spec.denom
        outs.append(piece)
    return tuple(outs)


def _needs_quant_build(spec: GroupSpec) -> bool:
    if spec.quant is not None and spec.quant.kind == "quant":
        return True
    h = spec.hier
    return h is not None and (h.dcn_quant is not None
                              or h.ici_quant is not None)


def _quant_unit(spec: GroupSpec) -> int:
    """Flat-buffer alignment so every exchange chunk is a whole number
    of scaling blocks: n·block for the flat two-phase exchange,
    ici_size·block for the hierarchical legs."""
    blocks = [f.block for f in (
        spec.quant, spec.hier.dcn_quant if spec.hier else None,
        spec.hier.ici_quant if spec.hier else None)
        if f is not None and f.kind == "quant"]
    block = max(blocks) if blocks else 2
    n = spec.hier.topo.ici_size if spec.hier is not None \
        else len(spec.mesh_key)
    return n * block


def _hier_quant_reduce(vin, spec: GroupSpec, key, pos):
    """Hierarchical ICI×DCN reduction with per-leg wire formats: the
    scatter and gather legs ride ICI (full precision, or int8/int4 via
    HVD_TPU_ICI_COMPRESS), the cross-slice sum rides DCN in its own
    format (cast or quantized).  Returns the reduced [Tp] float32."""
    hier = spec.hier
    topo = hier.topo
    ici = [list(g) for g in topo.ici_groups]
    dcn = [list(g) for g in topo.dcn_groups]
    myslice = jnp.take(
        jnp.asarray(topo.slice_of_positions(), dtype=jnp.int32), pos)
    if hier.ici_quant is not None:
        frag = _compression.quantized_scatter_sum(
            vin, hier.ici_quant, key, axis=REPLICA_AXIS,
            n=topo.ici_size, noise_pos=pos, groups=ici)
    else:
        frag = jax.lax.psum_scatter(
            vin, REPLICA_AXIS, scatter_dimension=0, tiled=True,
            axis_index_groups=ici).astype(jnp.float32)
    if hier.dcn_quant is not None:
        frag = _compression.quantized_gather_sum(
            frag, hier.dcn_quant, key, axis=REPLICA_AXIS, pos=myslice,
            groups=dcn)
    elif hier.wire_dtype is not None:
        frag = jax.lax.psum(
            frag.astype(jnp.dtype(hier.wire_dtype)), REPLICA_AXIS,
            axis_index_groups=dcn).astype(jnp.float32)
    else:
        frag = jax.lax.psum(frag, REPLICA_AXIS, axis_index_groups=dcn)
    if hier.ici_quant is not None:
        return _compression.quantized_all_gather(
            frag, hier.ici_quant, key, axis=REPLICA_AXIS, pos=pos,
            groups=ici)
    return jax.lax.all_gather(frag, REPLICA_AXIS, axis=0, tiled=True,
                              axis_index_groups=ici)


def _build_quant(spec: GroupSpec, mesh) -> Callable:
    """Trace + wrap one QUANTIZED group executable: pack → (residual
    add) → quantize → wire exchange → dequantize → unpack, all in the
    same single XLA program as the uncompressed megakernel — the
    quantize/dequantize stages cost zero extra dispatches.

    Signature per variant (``st`` = uint32[2] (seed, tick) — a runtime
    input, so one executable serves every step):

    =========  =================================================
    sp_pr      (t_1..t_k[, res], st) → (o_1..o_k[, res'])
    sp_rep     same, replicated layouts
    mp         (buf[, res], st) → (o_1..o_k[, res'])
    =========  =================================================

    ``res`` is the error-feedback residual as ONE flat buffer per
    group ([n, T] per-replica / [T] replicated) — per-TENSOR residual
    arrays would double the executable's argument count and pay jax's
    per-array dispatch cost twice over; the flat buffer is their exact
    concatenation, group-keyed in the executor's store.  Residual IO
    exists only on the error-feedback path (flat quantized reduction);
    the hierarchical per-leg codecs rely on stochastic rounding alone
    (docs/tensor-fusion.md)."""
    fmt = spec.quant if (spec.quant is not None
                         and spec.quant.kind == "quant") else None
    cast = spec.quant if (spec.quant is not None
                          and spec.quant.kind == "cast") else None
    hier = spec.hier
    n = len(spec.mesh_key)
    k = len(spec.shapes)
    T = sum(_numel(s) for s in spec.shapes)
    dtype = jnp.dtype(spec.dtype)
    use_ef = fmt is not None and fmt.error_feedback and hier is None
    shared = spec.variant == "sp_rep"
    pad = (-T) % _quant_unit(spec)

    def reduce_local(v, r, st):
        key = _compression.step_key(st[0], st[1])
        vin = v + r if r is not None else v
        if cast is not None:
            vin = vin.astype(jnp.dtype(cast.wire_dtype))
        if pad:
            vin = jnp.concatenate([vin, jnp.zeros((pad,), vin.dtype)])
        pos = jax.lax.axis_index(REPLICA_AXIS)
        if hier is None:
            red, r_new = _compression.quantized_reduce_collective(
                vin, fmt, key, axis=REPLICA_AXIS, n=n, my_chunk=pos,
                noise_pos=0 if shared else pos, error_feedback=use_ef,
                phase2_feedback=use_ef and not shared)
        else:
            red = _hier_quant_reduce(vin, spec, key, pos)
            r_new = None
        red = red[:T].astype(dtype)
        return red, (r_new[:T] if r_new is not None else None)

    nin = k + (1 if use_ef else 0)
    if spec.variant in ("sp_pr", "sp_rep"):
        lead = (1,) if spec.variant == "sp_pr" else ()

        def body(*args):
            ts, st = args[:k], args[-1]
            res = args[k] if use_ef else None
            if spec.variant == "sp_pr":
                v = jnp.squeeze(jnp.concatenate(
                    [t.reshape((t.shape[0], -1)) for t in ts], axis=1), 0)
                r = jnp.squeeze(res, 0) if use_ef else None
            else:
                v = jnp.concatenate([t.reshape(-1) for t in ts])
                r = res
            red, r_new = reduce_local(v, r, st)
            outs = _unpack(spec, red[None] if lead else red, lead)
            if use_ef:
                outs = outs + ((r_new[None] if lead else r_new),)
            return outs

        part = P(REPLICA_AXIS) if spec.variant == "sp_pr" else P()
        in_specs = tuple(part for _ in range(nin)) + (P(),)
        out_specs = tuple(part for _ in range(nin))
    elif spec.variant == "mp":
        def body(*args):
            buf = args[0]
            res = args[1] if use_ef else None
            st = args[-1]
            v = jnp.squeeze(buf, 0)
            r = jnp.squeeze(res, 0) if use_ef else None
            red, r_new = reduce_local(v, r, st)
            outs = _unpack(spec, red, ())
            if use_ef:
                outs = outs + (r_new[None],)
            return outs

        in_specs = (P(REPLICA_AXIS),) \
            + ((P(REPLICA_AXIS),) if use_ef else ()) + (P(),)
        out_specs = tuple(P() for _ in spec.shapes) \
            + ((P(REPLICA_AXIS),) if use_ef else ())
    else:
        raise ValueError(f"unknown megakernel variant {spec.variant!r}")

    if spec.variant == "mp":
        donate = (0, 1) if use_ef else (0,)
    else:
        donate = tuple(i for i, d in enumerate(spec.donate) if d) \
            + ((k,) if use_ef else ())  # the residual is executor-owned
    return jax.jit(
        _compat.shard_map(body, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, check_vma=False),
        donate_argnums=donate)


def _build(spec: GroupSpec, mesh) -> Callable:
    """Trace + wrap one group executable: pack → reduce → unpack in a
    single XLA program over ``mesh``, donated on the owned inputs."""
    if _needs_quant_build(spec):
        return _build_quant(spec, mesh)
    reduce_fn = _reduce_flat(spec)

    if spec.variant == "sp_pr":
        # Single-process, per-replica [n, *payload] inputs sharded over
        # the replica axis; outputs keep the layout (every row = the
        # reduction, Horovod's allreduce contract).
        def body(*ts):
            flat = jnp.concatenate(
                [t.reshape((t.shape[0], -1)) for t in ts], axis=1)
            red = reduce_fn(jnp.squeeze(flat, 0))[None]
            return _unpack(spec, red, (1,))

        in_specs = tuple(P(REPLICA_AXIS) for _ in spec.shapes)
        out_specs = tuple(P(REPLICA_AXIS) for _ in spec.shapes)
    elif spec.variant == "sp_rep":
        # Replicated inputs: every replica contributes the same value;
        # psum multiplies by the axis size exactly like the honest
        # per-tensor psum_rep kernel.
        def body(*ts):
            flat = jnp.concatenate([t.reshape(-1) for t in ts])
            red = reduce_fn(flat)
            return _unpack(spec, red, ())

        in_specs = tuple(P() for _ in spec.shapes)
        out_specs = tuple(P() for _ in spec.shapes)
    elif spec.variant == "mp":
        # Multi-process: one packed [P, T] fusion buffer (each process
        # contributed its flat shard), replicated payload outputs.
        def body(buf):
            red = reduce_fn(jnp.squeeze(buf, 0))
            return _unpack(spec, red, ())

        in_specs = (P(REPLICA_AXIS),)
        out_specs = tuple(P() for _ in spec.shapes)
    else:
        raise ValueError(f"unknown megakernel variant {spec.variant!r}")

    donate = tuple(i for i, d in enumerate(spec.donate) if d)
    return jax.jit(
        _compat.shard_map(body, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, check_vma=False),
        donate_argnums=donate)


def _pack_key(shapes, dtype, donate, mesh_key):
    return GroupSpec(mesh_key=mesh_key, variant="pack", op="psum",
                     average=False, denom=1, dtype=dtype, shapes=shapes,
                     donate=donate)


def _cache_insert(spec: GroupSpec, fn: Callable,
                  digest: Optional[str] = None,
                  seconds: float = 0.0) -> None:
    """Bounded insert shared by :func:`packer` and :func:`executable`:
    on overflow the whole table clears (wholesale, like the fusion-plan
    memo) rather than aging entries out."""
    with _lock:
        if len(_compiled) >= CACHE_CAPACITY:
            _compiled.clear()
            _digests.clear()
            _by_digest.clear()
            stats.flushes += 1
        _compiled[spec] = fn
        if digest is not None:
            _digests[spec] = digest
            _by_digest[digest] = spec
        stats.builds += 1
        stats.build_seconds += seconds


def packer(shapes: Tuple[Tuple[int, ...], ...], dtype: str,
           donate: Tuple[bool, ...], mesh_key) -> Callable:
    """Jitted local pack (multi-process leg): flatten + concatenate the
    group's local contributions into one fusion buffer in a single
    dispatch, donating the executor-owned inputs."""
    spec = _pack_key(shapes, dtype, donate, mesh_key)
    with _lock:
        fn = _compiled.get(spec)
        if fn is not None:
            stats.cache_hits += 1
            return fn
    fn = jax.jit(
        lambda *ts: jnp.concatenate([t.reshape(-1) for t in ts]),
        donate_argnums=tuple(i for i, d in enumerate(donate) if d))
    _cache_insert(spec, fn)
    return fn


def executable(spec: GroupSpec, mesh,
               digest_fn: Optional[Callable[[], str]] = None
               ) -> Tuple[Callable, bool]:
    """The compiled megakernel for ``spec`` — cached, bounded, recorded
    under its fusion-plan digest on the cold build (``digest_fn`` is
    only invoked then, keeping the hot path free of hashing).  Returns
    ``(fn, built)``: ``built`` tells THIS caller whether it did the
    cold build, so launch() can attribute the first (compiling)
    dispatch without racing other threads' builds."""
    with _lock:
        fn = _compiled.get(spec)
        if fn is not None:
            stats.cache_hits += 1
            return fn, False
    t0 = time.perf_counter()
    fn = _build(spec, mesh)
    digest = digest_fn() if digest_fn is not None else None
    _cache_insert(spec, fn, digest,
                  seconds=time.perf_counter() - t0)
    _record_manifest(spec, digest)  # cold builds only; no-op without env
    return fn, True


# ---------------------------------------------------------------------------
# Persistent compile cache: manifest + AOT warm start (hvd-pipeline)
# ---------------------------------------------------------------------------

def compile_cache_dir() -> Optional[str]:
    return os.environ.get(COMPILE_CACHE_ENV) or None


def _mesh_fingerprint(mesh_key) -> dict:
    d0 = mesh_key[0]
    return {"platform": getattr(d0, "platform", "?"),
            "device_kind": getattr(d0, "device_kind", "?"),
            "count": len(mesh_key)}


def _manifest_entry(spec: GroupSpec, digest: Optional[str]) -> dict:
    from dataclasses import asdict

    return {
        "variant": spec.variant,
        "op": spec.op,
        "average": spec.average,
        "denom": spec.denom,
        "dtype": spec.dtype,
        "shapes": [list(s) for s in spec.shapes],
        "donate": list(spec.donate),
        "hier": spec.hier is not None,
        "quant": asdict(spec.quant) if spec.quant is not None else None,
        "digest": digest,
        "mesh": _mesh_fingerprint(spec.mesh_key),
    }


def load_manifest(directory: str) -> List[dict]:
    path = os.path.join(directory, MANIFEST_NAME)
    try:
        with open(path) as f:
            data = json.load(f)
        entries = data.get("entries", [])
        return entries if isinstance(entries, list) else []
    except (OSError, ValueError):
        return []


def record_manifest_entry(entry: dict,
                          directory: Optional[str] = None) -> None:
    """Best-effort append of one executable record to the persistent-
    cache manifest (dedup by structure — the ``digest`` field is
    excluded from the key — bounded, atomic rename; never takes the
    executable lock: file IO must not nest inside it).

    Shared by the megakernel's cold-build recording and hvd-serve,
    whose prefill/decode executables ride the SAME manifest under
    ``variant: "serving"`` so one ``HVD_TPU_COMPILE_CACHE_DIR`` warms a
    relaunched fleet's training AND serving programs
    (:func:`warm_start` here skips serving entries;
    ``serving.engine.InferenceEngine.warm_start`` consumes them)."""
    d = directory or compile_cache_dir()
    if d is None:
        return
    try:
        entries = load_manifest(d)
        key = {k: v for k, v in entry.items() if k != "digest"}
        if any({k: v for k, v in e.items() if k != "digest"} == key
               for e in entries):
            return
        entries.append(entry)
        entries = entries[-MANIFEST_CAP:]
        os.makedirs(d, exist_ok=True)
        path = os.path.join(d, MANIFEST_NAME)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump({"format": "hvd-megakernel-manifest-v1",
                       "entries": entries}, f, indent=1)
        os.replace(tmp, path)
    except Exception:  # noqa: BLE001 — the manifest is an optimization
        pass


def serving_entries(directory: Optional[str] = None) -> List[dict]:
    """The manifest's hvd-serve executable records (variant
    ``"serving"``), for ``serving.engine.InferenceEngine.warm_start``."""
    d = directory or compile_cache_dir()
    if d is None:
        return []
    return [e for e in load_manifest(d)
            if e.get("variant") == "serving"]


def mesh_fingerprint(mesh_key) -> dict:
    """Public alias of the manifest's mesh identity (platform, device
    kind, count) — serving entries carry the same fingerprint."""
    return _mesh_fingerprint(tuple(mesh_key))


def _record_manifest(spec: GroupSpec, digest: Optional[str]) -> None:
    """Record one cold megakernel build.  Only the single-process group
    variants are recorded: the mp variant's mesh and packed-buffer
    layout are incarnation-specific."""
    if compile_cache_dir() is None or spec.variant not in ("sp_pr",
                                                           "sp_rep"):
        return
    record_manifest_entry(_manifest_entry(spec, digest))


def _warm_avals(spec: GroupSpec, mesh) -> List[jax.ShapeDtypeStruct]:
    """Abstract inputs for AOT-lowering one recorded group executable
    (global shapes + shardings exactly as launch() passes them —
    including the residual mirrors and the (seed, tick) state input on
    the quantized signatures)."""
    n = len(spec.mesh_key)
    dtype = jnp.dtype(spec.dtype)
    if spec.variant == "sp_pr":
        sh = NamedSharding(mesh, P(REPLICA_AXIS))
        avals = [jax.ShapeDtypeStruct((n,) + shp, dtype, sharding=sh)
                 for shp in spec.shapes]
    else:
        sh = NamedSharding(mesh, P())
        avals = [jax.ShapeDtypeStruct(shp, dtype, sharding=sh)
                 for shp in spec.shapes]
    if _needs_quant_build(spec):
        fmt = spec.quant
        if (fmt is not None and fmt.kind == "quant"
                and fmt.error_feedback and spec.hier is None):
            T = sum(_numel(s) for s in spec.shapes)
            if spec.variant == "sp_pr":
                avals.append(jax.ShapeDtypeStruct(
                    (n, T), dtype,
                    sharding=NamedSharding(mesh, P(REPLICA_AXIS))))
            else:
                avals.append(jax.ShapeDtypeStruct(
                    (T,), dtype, sharding=NamedSharding(mesh, P())))
        avals.append(jax.ShapeDtypeStruct(
            (2,), jnp.uint32, sharding=NamedSharding(mesh, P())))
    return avals


def warm_start(mesh, directory: Optional[str] = None) -> int:
    """AOT-rebuild the manifest's group executables for ``mesh``.

    Called by ``hvd.init()`` when ``HVD_TPU_COMPILE_CACHE_DIR`` is set:
    every recorded group whose mesh fingerprint matches is re-traced and
    compiled ahead of the first training step — against a warm XLA disk
    cache the compile is a cache read, so an elastic relaunch resumes at
    full step rate instead of paying the cold-compile stall mid-loop.
    Hierarchy is recomputed from the CURRENT env/topology (the knobs may
    legitimately differ across incarnations).  Best-effort per entry;
    returns the number of executables warmed."""
    d = directory or compile_cache_dir()
    if d is None:
        return 0
    fp = _mesh_fingerprint(tuple(mesh.devices.flat))
    mesh_key = tuple(mesh.devices.flat)
    warmed = 0
    t0 = time.perf_counter()
    for entry in load_manifest(d):
        if entry.get("mesh") != fp:
            continue
        if entry.get("variant") not in ("sp_pr", "sp_rep"):
            continue
        try:
            quant = (_compression.WireFormat(**entry["quant"])
                     if entry.get("quant") else None)
            spec = GroupSpec(
                mesh_key=mesh_key, variant=entry["variant"],
                op=entry["op"], average=bool(entry["average"]),
                denom=int(entry["denom"]), dtype=entry["dtype"],
                shapes=tuple(tuple(s) for s in entry["shapes"]),
                donate=tuple(bool(x) for x in entry["donate"]),
                hier=hierarchy_for(mesh_key, entry["op"], entry["dtype"],
                                   group_fmt=quant),
                quant=quant)
            with _lock:
                if spec in _compiled:
                    continue
            fn = _build(spec, mesh)
            compiled = fn.lower(*_warm_avals(spec, mesh)).compile()
            # hvd-mem: harvest compiled.memory_analysis() per warmed
            # executable (where the backend implements it) — the
            # static planner's per-mesh "compiled" section.
            _mem_planner.record_compiled(
                f"megakernel/{entry['op']}/{entry['variant']}"
                f"/{entry.get('digest') or warmed}", compiled)
            _cache_insert(spec, fn, entry.get("digest"))
            warmed += 1
        except Exception:  # noqa: BLE001 — a stale entry must not
            continue       # break init; the group just compiles lazily
    if warmed:
        with _lock:
            stats.warm_starts += warmed
            stats.warm_seconds += time.perf_counter() - t0
        print(f"[hvd-megakernel] warm start: {warmed} executables "
              f"rebuilt from {os.path.join(d, MANIFEST_NAME)}",
              file=sys.stderr)
    return warmed


def wire_accounting_legs(spec: GroupSpec) -> Tuple[int, int, int]:
    """``(logical_bytes, wire_bytes, dcn_bytes)`` one launch of ``spec``
    moves — ``dcn_bytes`` is the cross-slice share of ``wire_bytes``
    (0 for flat launches); the hvd-trace launch span carries both so
    the analyzer can split a hierarchical launch's time into its ICI
    and DCN legs.

    The model counts payload traversals per leg — flat reductions make
    two (the scatter- and gather-phase of a bandwidth-optimal
    allreduce), hierarchical ones two ICI traversals plus the 1/ici
    DCN fragment — each in that leg's wire format (codes + one 2-byte
    scale per block for quantized legs).  The per-member (n−1)/n factor
    is common to both figures and cancels in the ratio
    (docs/metrics.md)."""
    T = sum(_numel(s) for s in spec.shapes)
    item = jnp.dtype(spec.dtype).itemsize

    def fmt_bytes(count: int, fmt) -> int:
        if fmt is None:
            return count * item
        if fmt.kind == "cast":
            return count * (fmt.bits // 8)
        return (count * fmt.bits + 7) // 8 + (-(-count // fmt.block)) * 2

    if spec.hier is None:
        return 2 * T * item, 2 * fmt_bytes(T, spec.quant), 0
    h = spec.hier
    F = -(-T // h.topo.ici_size)
    cast = spec.quant if (spec.quant is not None
                          and spec.quant.kind == "cast") else None
    ici_f = h.ici_quant or cast
    if h.dcn_quant is not None:
        dcn_f = h.dcn_quant
    elif h.wire_dtype is not None:
        dcn_f = _compression.WireFormat(
            kind="cast", name=h.wire_dtype, wire_dtype=h.wire_dtype,
            bits=8 * jnp.dtype(h.wire_dtype).itemsize,
            stochastic=False, error_feedback=False)
    else:
        dcn_f = cast
    logical = (2 * T + F) * item
    dcn_b = fmt_bytes(F, dcn_f)
    return logical, 2 * fmt_bytes(T, ici_f) + dcn_b, dcn_b


def wire_accounting(spec: GroupSpec) -> Tuple[int, int]:
    """``(logical_bytes, wire_bytes)`` — see
    :func:`wire_accounting_legs`."""
    logical, wire_b, _dcn = wire_accounting_legs(spec)
    return logical, wire_b


def _launch_name(spec: GroupSpec) -> str:
    """Executable name for OOM forensics (cold/error paths only — the
    steady-state launch never builds it)."""
    return f"megakernel/{spec.op}/{spec.variant}x{len(spec.shapes)}"


def launch(spec: GroupSpec, mesh, values: Sequence,
           digest_fn: Optional[Callable[[], str]] = None,
           donate_mask: Optional[Sequence[bool]] = None):
    """One megakernel dispatch for a fusion group.  Under dispatch
    counting (tests/bench) the launch is wrapped in a thread-local
    window and the observed executable count is accumulated on
    ``stats`` — the "exactly one dispatch per group" regression
    contract — and the donated inputs are recorded as weakrefs for the
    use-after-donate probe.  ``donate_mask`` extends ``spec.donate``
    when the quantized kernels append executor-owned inputs (residuals)
    beyond the per-tensor contributions."""
    fn, cold = executable(spec, mesh, digest_fn)
    mask = tuple(donate_mask) if donate_mask is not None else spec.donate
    logical_b, wire_b, dcn_b = wire_accounting_legs(spec)
    # hvd-mem: the launch's HBM footprint (contributions + outputs, the
    # SAME byte model the planner predicts with) is accounted against
    # the ledger for the dispatch's lifetime, and a RESOURCE_EXHAUSTED
    # dumps the flight ring naming this executable and the top ledger
    # categories.  The byte arithmetic only runs when something
    # consumes it (ledger, trace span, simulated capacity), so the
    # telemetry-off A/B leg measures a true zero-accounting path and
    # the ≤5 % overhead gate covers the accounting it claims to.
    mem_on = _mem.enabled()
    trace_on = _trace.enabled()
    cap = _oom.simulated_capacity()
    fusion_b = (_mem_planner.fusion_group_bytes(
        spec.shapes, spec.dtype, len(spec.mesh_key), spec.variant)
        if (mem_on or trace_on or cap is not None) else 0)
    if cap is not None:
        # The capacity knob is per-DEVICE HBM: project the per-device
        # footprint (one payload of inputs + one of outputs per
        # device, identical across variants), not the 2·world global
        # figure the ledger/planner consistency contract shares — a
        # world>1 job with a correctly pinned per-rank capacity must
        # not raise fake OOMs (docs/memory.md).
        _oom.check_simulated(
            lambda: _launch_name(spec),
            _mem_planner.fusion_group_device_bytes(spec.shapes,
                                                   spec.dtype))
    trace_t0 = time.monotonic() if trace_on else 0.0

    # hvd-race donation sanitizer: every launch routes through the
    # registry — re-dispatching a buffer a previous launch donated
    # raises a DonationError naming THAT launch, and this launch's
    # donated inputs are registered afterwards (HVD_TPU_DONATION_CHECK).
    donated_idx = tuple(i for i, d in enumerate(mask) if d)

    def dispatch():
        # XLA compiles on the cold executable's FIRST dispatch; time
        # exactly that call (one perf_counter pair, cold path only) so
        # megakernel.compile_seconds reports real compilation cost.
        if not cold:
            return _donation.guard_dispatch(
                _launch_name(spec), fn, values, donated_idx)
        t0 = time.perf_counter()
        out = _donation.guard_dispatch(
            _launch_name(spec), fn, values, donated_idx)
        with _lock:
            stats.compile_seconds += time.perf_counter() - t0
        return out

    counting = _xla_dispatch.counting_enabled()
    if mem_on:
        _mem.ledger.alloc("megakernel.fusion", fusion_b)
    try:
        if counting:
            probes = [weakref.ref(v)
                      for v, d in zip(values, mask) if d]
            with _xla_dispatch.record() as scope:
                outs = dispatch()
            with _lock:
                stats.launches += 1
                stats.launch_dispatches += scope.count
                stats.donated_inputs += sum(mask)
                stats.logical_bytes += logical_b
                stats.wire_bytes += wire_b
                if spec.hier is not None:
                    stats.hier_launches += 1
                if _needs_quant_build(spec):
                    stats.quant_launches += 1
                last_donated[:] = probes
        else:
            outs = dispatch()
            with _lock:
                stats.launches += 1
                stats.donated_inputs += sum(mask)
                stats.logical_bytes += logical_b
                stats.wire_bytes += wire_b
                if spec.hier is not None:
                    stats.hier_launches += 1
                if _needs_quant_build(spec):
                    stats.quant_launches += 1
    except Exception as e:  # noqa: BLE001 — re-raised: forensics only
        if _oom.is_resource_exhausted(e):
            _oom.oom_event(_launch_name(spec), e, fusion_b or None)
        raise
    finally:
        if mem_on:
            _mem.ledger.free("megakernel.fusion", fusion_b)
    if _telemetry.enabled():
        _M_WIRE_BYTES.observe(wire_b)
    if _trace.enabled():
        # hvd-trace launch span: the compiled collective itself.  The
        # wire-byte legs let the analyzer split a hierarchical launch's
        # time into its ICI ("collective") and DCN shares; mem_bytes
        # mirrors the ledger charge so the fleet trace shows each
        # launch's HBM footprint next to its wall time (hvd-mem).
        _trace.span(f"megakernel/{spec.op}", "collective", trace_t0,
                    time.monotonic(),
                    args={"groups": len(spec.shapes),
                          "hier": spec.hier is not None,
                          "wire_bytes": wire_b, "dcn_bytes": dcn_b,
                          "mem_bytes": fusion_b})
    return outs
