"""Arbitrary-object collectives: ``broadcast_object`` / ``allgather_object``.

The reference snapshot (v0.13.0) predates these; Horovod later added them
(``hvd.broadcast_object`` appeared for sharing optimizer state and resume
epochs without hand-rolled tensor packing).  They are pure composition
over the existing eager collectives:

* ``allgather_object`` — pickle the object to a uint8 vector and ride the
  variable-dim-0 allgather (the one collective whose negotiation already
  handles per-rank sizes, ≙ MPIResponse.tensor_sizes); a first allgather
  of the byte counts gives the split points for unpickling per rank.
* ``broadcast_object`` — rank ordering of collectives requires every rank
  to submit a matching shape, so the root first broadcasts the byte count
  (scalar), then the payload (non-roots contribute a zero buffer of that
  size, which broadcast semantics discard).

Objects must be picklable.  Only trust peers you would trust with code
execution — unpickling attacker-controlled bytes runs arbitrary code,
the same caveat Horovod's own object APIs carry.
"""

from __future__ import annotations

import pickle
from typing import Any, List, Optional

import numpy as np

from . import collective as _C

__all__ = ["allgather_object", "broadcast_object"]


def _to_bytes_array(obj: Any) -> np.ndarray:
    return np.frombuffer(pickle.dumps(obj), dtype=np.uint8).copy()


def allgather_object(obj: Any, name: Optional[str] = None) -> List[Any]:
    """Gather one picklable object per rank; returns the rank-ordered
    list on every rank (≙ the post-v0.13 hvd.allgather_object)."""
    name = name or "allgather.object"
    data = _to_bytes_array(obj)
    # The payload gather does not depend on the sizes result — launch
    # both async so they negotiate in the same coordinator tick (one
    # cross-process round trip, not two).  int64 sizes: a pickle can
    # exceed the int32 range.
    h_sizes = _C.allgather_async(np.array([data.size], dtype=np.int64),
                                 name=f"{name}.sizes")
    h_data = _C.allgather_async(data, name=f"{name}.data")
    sizes = np.asarray(_C.synchronize(h_sizes))
    payload = np.asarray(_C.synchronize(h_data))
    out: List[Any] = []
    off = 0
    for sz in sizes.tolist():
        out.append(pickle.loads(payload[off:off + sz].tobytes()))
        off += sz
    return out


def broadcast_object(obj: Any = None, root_rank: int = 0,
                     name: Optional[str] = None) -> Any:
    """Broadcast one picklable object from ``root_rank``; every rank
    returns the root's object (≙ the post-v0.13 hvd.broadcast_object).
    Non-root ranks may pass ``obj=None``."""
    from ..core import state as _state

    name = name or "broadcast.object"
    is_root = _state.rank() == root_rank
    if is_root:
        data = _to_bytes_array(obj)
        size = np.array([data.size], dtype=np.int64)
    else:
        size = np.zeros((1,), dtype=np.int64)
    size = int(np.asarray(_C.broadcast(size, root_rank,
                                       name=f"{name}.size"))[0])
    if not is_root:
        data = np.zeros((size,), dtype=np.uint8)
    payload = np.asarray(_C.broadcast(data, root_rank,
                                      name=f"{name}.data"))
    return pickle.loads(payload.tobytes())
