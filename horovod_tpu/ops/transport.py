"""Cross-process control-plane transport for eager collectives.

Reference architecture (horovod/common/operations.cc:1226-1374): rank 0 is
the coordinator; every worker ships its ``MPIRequest`` messages to it
(MPI_Gather of lengths + MPI_Gatherv of payloads) and receives the fused
``MPIResponse`` list back (MPI_Bcast), after which all ranks execute the
responses in the identical broadcast order.  This module keeps that exact
message flow over one TCP connection per worker, speaking the same binary
wire format the in-process coordinator already uses (ops/wire.py — which
existed precisely to move Request/Response between processes).

The connection doubles as the node-topology rendezvous: each worker's
HELLO carries its hostname, and the controller answers with
(local_rank, local_size, cross_rank, cross_size) — the reference derives
the same numbers from ``MPI_Comm_split_type(SHARED)``
(operations.cc:1184-1196).

Frame layout: ``<u32 length><u8 type><payload>`` (little-endian).

Transient-fault hardening (hvd-chaos, docs/chaos.md)
----------------------------------------------------
A dropped TCP connection used to be terminal: the controller poisoned
the fleet, the worker poisoned itself.  Both sides now run a
**session-resume protocol**: every post-handshake frame is counted and
retained in a bounded replay ring per direction; on a connection loss
the worker reconnects with exponential backoff + jitter
(utils/retry.py) and the two sides exchange their received-frame
counts (FRAME_RECONNECT / FRAME_RESUME), re-sending exactly the lost
suffix — the response stream every replica's cache alignment depends on
is preserved bit-for-bit.  The handshake is epoch-stamped: a worker
whose response-cache replica epoch no longer matches what the
controller recorded at disconnect resumes **cache-less** instead of
desyncing.  The controller holds a disconnected rank in a bounded
grace window (``HVD_TPU_RECONNECT_GRACE``) — its in-flight negotiation
entries stay pending (re-requested via the replay ring, never
poisoned) — and only an expired window or an unplayable gap turns the
rank into a dead peer with a diagnostic naming the fault.  Frame reads
and writes additionally carry **mid-frame deadlines**
(``HVD_TPU_FRAME_TIMEOUT``): a peer that stalls midway through a frame
produces a diagnostic naming the peer and the frame type instead of a
hang.  Chaos injection (``HVD_TPU_FAULTS``) hooks the send path —
frame drop/delay/duplicate/truncate, connection reset, slow peer — so
every one of these recoveries is deterministically testable
(python -m horovod_tpu.chaos --matrix).
"""

from __future__ import annotations

import atexit
import collections
import itertools
import json
import os
import queue
import socket
import struct
import sys
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from . import wire
from .. import chaos as _chaos
from .. import telemetry as _telemetry
from .. import trace as _trace
from ..analysis import lockorder as _lockorder
from ..analysis import threads as _athreads
from ..analysis import races as _races
from ..telemetry import flight as _flight
from ..trace import clock as _trace_clock
from ..utils.retry import BackoffPolicy
from .wire import DEAD_PEER_MARKER, Request, Response, ResponseType

FRAME_HELLO = 0       # worker→controller: <i rank><H len><hostname>
                      #   <H len><env fingerprint> — the SPMD env-knob
                      #   uniformity check (ops/compression.py)
FRAME_REQUEST = 1     # worker→controller: packed Request
FRAME_RESPONSES = 2   # controller→worker: packed response list
FRAME_TOPO = 3        # controller→worker: <iiiii> local_rank local_size
                      #   cross_rank cross_size cache_enabled — the last
                      #   int advertises whether rank 0 runs the response
                      #   cache, so a worker never populates a replica
                      #   the controller cannot resolve bits against
FRAME_SHUTDOWN = 4    # either direction: cooperative shutdown
FRAME_WITHDRAW = 5    # worker→controller: <i rank><H len><name><H psid> —
                      # the rank's synchronize timed out on <name>; the
                      # coordinator (of process set psid; 0 = global)
                      # fails the op for the whole group
FRAME_SIGNATURE = 6   # worker→controller: <i rank><I round> + packed
                      # program signature (analysis/program.py
                      # verify_program); the round counter pairs
                      # payloads with their verify call so a stale
                      # signature left by a timed-out round can never
                      # complete a later one
FRAME_SIGRESULT = 7   # controller→worker: <I round><B ok> + utf-8
                      # diagnostic
FRAME_REQUEST_BATCH = 8   # worker→controller, one per drain tick:
                          # <i rank><I epoch><I nbitbytes><bit-vector>
                          # <H nreq><packed Requests...> — the bit-vector
                          # marks response-cache hits by entry index
                          # (ops/cache.py); full requests ride the same
                          # frame, so the steady state costs ONE frame
                          # per tick instead of one per tensor
FRAME_RESPONSE_BATCH = 9  # controller→worker: <I epoch><H ngroups>
                          # (<H n><I idx>*)* — a pure cache-replay cycle
                          # as fused entry-index groups; each worker
                          # reconstitutes the identical fused response
                          # list from its cache replica instead of
                          # re-parsing full Response payloads
FRAME_METRICS = 10        # hvd-telemetry pull (telemetry/__init__.py):
                          # controller→worker <I round> requests a
                          # snapshot; worker→controller <i rank><I round>
                          # + utf-8 JSON answers it.  Round-keyed like
                          # FRAME_SIGNATURE so a straggler snapshot from
                          # a timed-out pull never completes a later one
FRAME_RECONNECT = 11      # worker→controller on a FRESH socket:
                          # <i rank><I frames_received><i cache_epoch>
                          # <B has_cache> — the session-resume request.
                          # frames_received lets the controller replay
                          # exactly the frames the worker never got;
                          # the epoch stamp decides whether the
                          # worker's cache replica may resume or must
                          # be dropped (hvd-chaos reconnect protocol)
FRAME_RESUME = 12         # controller→worker, answering RECONNECT:
                          # <I frames_received><B verdict><H len><utf-8
                          # reason>; verdict 0 = reject (reason names
                          # why), 1 = resume with cache, 2 = resume
                          # cache-less.  Followed by the raw replay of
                          # every controller→worker frame the worker
                          # missed, in original stream order
FRAME_PING = 13           # hvd-trace clock probe, controller→worker:
                          # <I seq><d t0> (rank 0's monotonic at send).
                          # Rides the replay ring like every broadcast;
                          # a ring-replayed stale ping yields a
                          # huge-RTT pong the min-RTT filter discards
FRAME_PONG = 14           # worker→controller: <i rank><I seq><d t0>
                          # <d t1> — t0 echoed, t1 the worker's
                          # monotonic at receipt; rank 0 stamps arrival
                          # (t2) and folds the NTP sample into its
                          # per-peer offset estimator (trace/clock.py)
FRAME_TRACE = 15          # hvd-trace span pull (trace/merge.py):
                          # controller→worker <I round> requests the
                          # worker's span buffer; worker→controller
                          # <i rank><I round> + utf-8 JSON answers.
                          # Round-keyed like FRAME_METRICS so a
                          # straggler buffer from a timed-out pull
                          # never completes a later one
# -- tree-overlay frames (ops/tree.py, docs/performance.md
# -- "Scale-out control plane") ----------------------------------------
FRAME_HELLO_TREE = 16     # child→parent at handshake: <H n> + n x
                          # (<i rank><H hlen><host><H flen><fp>) — one
                          # connection's whole-subtree HELLO, merged
                          # bottom-up so the root sees fanout
                          # connections instead of world-1
FRAME_TOPO_TREE = 17      # parent→child, answering HELLO_TREE:
                          # <B cache><H n> + n x (<i rank><iiii topo>)
                          # — the subtree's placement slice; interiors
                          # forward each child its own sub-slice
FRAME_SUBTREE_BATCH = 18  # child→parent, one per relay tick: the
                          # subtree's merged negotiation traffic as
                          # typed sections (tree.py owns the layout) —
                          # cache-hit bit-vectors grouped by (epoch,
                          # entries) across ranks, per-rank full
                          # requests, per-rank trace arrivals, and
                          # cumulative per-rank frame counts for the
                          # re-parent resume protocol
FRAME_METRICS_TREE = 19   # child→parent: <I round><H n> + n x
                          # (<i rank><I len><json>) — a subtree's
                          # merged FRAME_METRICS replies, so a pull
                          # costs the root fanout frames, not world
FRAME_TRACE_TREE = 20     # child→parent: same layout as METRICS_TREE
                          # for FRAME_TRACE span-buffer replies
FRAME_CHILD_LOST = 21     # child→parent: <i rank><H len><reason> — an
                          # interior's child link died and its grace
                          # expired; only the ROOT arbitrates liveness
                          # (the rank may have re-parented meanwhile)

_FRAME_NAMES = {
    FRAME_HELLO: "HELLO", FRAME_REQUEST: "REQUEST",
    FRAME_RESPONSES: "RESPONSES", FRAME_TOPO: "TOPO",
    FRAME_SHUTDOWN: "SHUTDOWN", FRAME_WITHDRAW: "WITHDRAW",
    FRAME_SIGNATURE: "SIGNATURE", FRAME_SIGRESULT: "SIGRESULT",
    FRAME_REQUEST_BATCH: "REQUEST_BATCH",
    FRAME_RESPONSE_BATCH: "RESPONSE_BATCH", FRAME_METRICS: "METRICS",
    FRAME_RECONNECT: "RECONNECT", FRAME_RESUME: "RESUME",
    FRAME_PING: "PING", FRAME_PONG: "PONG", FRAME_TRACE: "TRACE",
    FRAME_HELLO_TREE: "HELLO_TREE", FRAME_TOPO_TREE: "TOPO_TREE",
    FRAME_SUBTREE_BATCH: "SUBTREE_BATCH",
    FRAME_METRICS_TREE: "METRICS_TREE",
    FRAME_TRACE_TREE: "TRACE_TREE", FRAME_CHILD_LOST: "CHILD_LOST",
}


def frame_name(ftype: Optional[int]) -> str:
    return _FRAME_NAMES.get(ftype, f"type-{ftype}")


_HDR = struct.Struct("<IB")

# Control-plane wire telemetry: frames flow at the 5 ms drain cadence
# (coalesced — that is the PR 2 point), so per-frame accounting is far
# off the per-request hot path.
_M_TX = _telemetry.counter("transport.frames_sent")
_M_TX_BYTES = _telemetry.counter("transport.bytes_sent")
_M_RX = _telemetry.counter("transport.frames_received")
_M_RX_BYTES = _telemetry.counter("transport.bytes_received")
_M_FRAME_BYTES = _telemetry.histogram(
    "transport.frame_bytes", "bytes", "payload size per control frame")
_M_BATCH_BITS = _telemetry.counter(
    "transport.batched_cache_bits", "cache-hit bits coalesced into "
    "FRAME_REQUEST_BATCH frames")
_M_BATCH_REQS = _telemetry.counter(
    "transport.batched_requests", "full requests coalesced into "
    "FRAME_REQUEST_BATCH frames")
_M_BATCH_WIDTH = _telemetry.histogram(
    "transport.batch_width", "count",
    "items (bits + requests) per coalesced control frame")
# hvd-chaos hardening counters (docs/metrics.md "Fault tolerance").
_M_DISCONNECTS = _telemetry.counter(
    "transport.disconnects", "control-plane connections lost without a "
    "shutdown handshake (reconnect grace entered)")
_M_RECONNECTS = _telemetry.counter(
    "transport.reconnects", "worker control-plane reconnects completed")
_M_RECONNECTS_ACCEPTED = _telemetry.counter(
    "transport.reconnects_accepted", "worker reconnects the controller "
    "resumed")
_M_RECONNECT_FAILURES = _telemetry.counter(
    "transport.reconnect_failures", "reconnect attempts that failed "
    "(connect refused / handshake error)")
_M_REPLAYED = _telemetry.counter(
    "transport.frames_replayed", "frames re-sent from a replay ring "
    "after a reconnect")
_M_FRAME_TIMEOUTS = _telemetry.counter(
    "transport.frame_timeouts", "mid-frame read deadlines exceeded "
    "(slow/stalled peer)")
# Tree-overlay counters (ops/tree.py, docs/metrics.md).
_M_TREE_MERGED = _telemetry.counter(
    "transport.tree_merged_frames", "child control frames dissolved "
    "into merged FRAME_SUBTREE_BATCH / *_TREE envelopes")
_M_TREE_RELAYED = _telemetry.counter(
    "transport.tree_relayed_frames", "broadcast frames an interior "
    "node relayed down to its children")
_M_REPARENTS = _telemetry.counter(
    "transport.reparents", "orphaned tree ranks the root adopted as "
    "direct children after their interior parent died")
_M_CHILD_LOST = _telemetry.counter(
    "transport.tree_child_lost", "FRAME_CHILD_LOST reports interiors "
    "escalated to the root")


# -- env knobs (hvd-chaos hardening; read at call time so tests and the
# -- chaos matrix can repoint them per scenario) ---------------------------

def _reconnect_enabled() -> bool:
    return os.environ.get("HVD_TPU_RECONNECT", "1") != "0"


def _grace_seconds() -> float:
    return float(os.environ.get("HVD_TPU_RECONNECT_GRACE", "10"))


def _reconnect_deadline_seconds() -> float:
    return float(os.environ.get("HVD_TPU_RECONNECT_DEADLINE", "10"))


def _ring_limit() -> int:
    return int(os.environ.get("HVD_TPU_RECONNECT_RING", "1024"))


def _frame_timeout() -> Optional[float]:
    v = float(os.environ.get("HVD_TPU_FRAME_TIMEOUT", "30"))
    return v if v > 0 else None


class FrameDeadlineError(OSError):
    """A peer stalled midway through a frame (hvd-chaos frame-level
    deadline).  Subclasses OSError so every broken-connection path —
    reconnect on the worker, grace on the controller — handles it."""


def _frame_deadline(peer: str, what: str, got: int,
                    want: int) -> FrameDeadlineError:
    msg = (f"control-plane frame deadline exceeded: peer {peer} stalled "
           f"mid-frame ({what}, {got}/{want} bytes within "
           f"{_frame_timeout()}s)")
    _M_FRAME_TIMEOUTS.inc()
    _flight.record("frame_timeout", peer, what, got, want)
    print(f"WARNING: {msg}", file=sys.stderr)
    return FrameDeadlineError(msg)


class _FrameRing:
    """Bounded replay ring for one send direction: every post-handshake
    frame is appended with a cumulative index; ``since(n)`` returns the
    frames the peer (which received ``n`` of them) is missing, or None
    when the gap outgrew the ring — the unrecoverable case.  Callers
    serialize access under their send lock."""

    def __init__(self, limit: int) -> None:
        self._limit = max(1, limit)
        self._frames: collections.deque = collections.deque()
        self._base = 0   # stream index of _frames[0]
        self.count = 0   # frames ever appended

    def append(self, ftype: int, payload: bytes) -> int:
        self._frames.append((ftype, payload))
        self.count += 1
        if len(self._frames) > self._limit:
            self._frames.popleft()
            self._base += 1
        return self.count

    def since(self, received: int) -> Optional[List[Tuple[int, bytes]]]:
        if received < self._base or received > self.count:
            return None
        return list(itertools.islice(
            self._frames, received - self._base, len(self._frames)))


def _send_frame(sock: socket.socket, ftype: int, payload: bytes = b"") -> None:
    sock.sendall(_HDR.pack(len(payload), ftype) + payload)
    _M_TX.inc()
    _M_TX_BYTES.inc(_HDR.size + len(payload))
    _M_FRAME_BYTES.observe(len(payload))


def _wake_close(sock: socket.socket) -> None:
    """Close a socket ANOTHER thread may be blocked reading.  A bare
    ``close()`` does not wake a thread already parked in ``recv`` (the
    fd is released but the syscall stays blocked — observed on this
    kernel); ``shutdown`` delivers the EOF first, so the reader wakes
    immediately instead of hanging until peer traffic arrives."""
    try:
        sock.shutdown(socket.SHUT_RDWR)
    except OSError:
        pass
    try:
        sock.close()
    except OSError:
        pass


def _hard_close(sock: socket.socket) -> None:
    """Close with RST (SO_LINGER 0) — the chaos 'connection reset'
    wire effect; the peer's recv fails instead of seeing a clean EOF.
    The shutdown also wakes any LOCAL thread blocked in recv on this
    socket (the worker's own receive loop must notice a self-inflicted
    reset and start reconnecting)."""
    try:
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_LINGER,
                        struct.pack("ii", 1, 0))
    except OSError:
        pass
    _wake_close(sock)


def _apply_send_chaos(sock: socket.socket, ftype: int,
                      payload: bytes) -> str:
    """Consult the hvd-chaos schedule for one outgoing post-handshake
    frame and perform the fault's wire effect.  Returns "send" (the
    caller sends normally), "done" (the frame was dropped or already
    put on the wire), or "dup" (the caller sends the frame TWICE and
    accounts BOTH copies in its replay ring — the receiver counts both
    deliveries, so the ring must too or a later session resume would
    misalign).  Raises ConnectionResetError for the connection-killing
    faults — the caller's broken-connection handling (reconnect /
    grace) takes over, which is exactly the recovery under test."""
    if not _chaos.active():
        return "send"
    if _chaos.fire("transport.drop") is not None:
        return "done"  # silent loss; only a reconnect replay recovers it
    if _chaos.fire("transport.reset") is not None:
        _hard_close(sock)
        raise ConnectionResetError(
            f"hvd-chaos: transport.reset before {frame_name(ftype)}")
    f = _chaos.fire("transport.trunc")
    if f is not None:
        blob = _HDR.pack(len(payload), ftype) + payload
        cut = max(1, (len(blob) * 2) // 3)
        try:
            sock.sendall(blob[:cut])
        except OSError:
            pass
        _hard_close(sock)
        raise ConnectionResetError(
            f"hvd-chaos: transport.trunc mid-{frame_name(ftype)} "
            f"({cut}/{len(blob)} bytes)")
    f = _chaos.fire("transport.stall")
    if f is not None:
        blob = _HDR.pack(len(payload), ftype) + payload
        sock.sendall(blob[:_HDR.size])
        time.sleep(f.delay)
        sock.sendall(blob[_HDR.size:])
        _M_TX.inc()
        _M_TX_BYTES.inc(len(blob))
        return "done"  # already on the wire, slowly
    f = _chaos.fire("transport.delay")
    if f is not None:
        time.sleep(f.delay)
    if _chaos.fire("transport.dup") is not None:
        return "dup"
    return "send"


def _send_frame_or_fault(sock: socket.socket, ftype: int,
                         payload: bytes = b"",
                         allow_dup: bool = True) -> int:
    """The steady-state send: chaos consultation + the real send.
    Returns the number of stream slots the frame consumed on the wire
    (2 when chaos duplicated it) so the caller's replay ring stays
    aligned with the receiver's frame count.  ``allow_dup=False``
    downgrades a chaos duplication into a plain send — the tree
    overlay's broadcast stream uses it because a per-link dup would
    desync the GLOBAL stream index the re-parent resume replays from
    (docs/chaos.md)."""
    act = _apply_send_chaos(sock, ftype, payload)
    if act == "done":
        return 1
    _send_frame(sock, ftype, payload)
    if act == "dup" and allow_dup:
        _send_frame(sock, ftype, payload)
        return 2
    return 1


def _recv_exact(sock: socket.socket, n: int, idle_ok: bool = False,
                peer: str = "", what: str = "") -> Optional[bytes]:
    """Read exactly ``n`` bytes.  With a socket timeout armed
    (post-handshake), a timeout BETWEEN frames is legal idleness
    (``idle_ok``, header position only); a timeout once any byte of the
    frame has arrived is a stalled peer — raised as
    :class:`FrameDeadlineError` naming the peer and frame type."""
    buf = b""
    while len(buf) < n:
        try:
            chunk = sock.recv(n - len(buf))
        except socket.timeout:
            if idle_ok and not buf:
                continue
            raise _frame_deadline(peer or "?", what or "frame",
                                  len(buf), n) from None
        if not chunk:
            if buf:
                # EOF midway through a frame: a truncated frame (the
                # chaos transport.trunc wire effect, or a real reset
                # mid-send).  Name the peer and the frame type — the
                # reconnect/grace machinery recovers; this record is
                # the forensic trail.
                _flight.record("truncated_frame", peer or "?",
                               what or "frame", len(buf), n)
                print(f"WARNING: truncated control frame from "
                      f"{peer or '?'} ({what or 'frame'}: {len(buf)}/"
                      f"{n} bytes before EOF)", file=sys.stderr)
            return None
        buf += chunk
    return buf


def _recv_frame(sock: socket.socket, peer: str = "",
                idle_ok: bool = True):
    """Read one frame.  ``idle_ok=False`` makes even the wait for the
    frame's FIRST byte subject to the socket timeout — the handshake
    reads (RECONNECT/RESUME) use it so a silent peer bounds the wait
    instead of idling forever."""
    hdr = _recv_exact(sock, _HDR.size, idle_ok=idle_ok, peer=peer,
                      what="header")
    if hdr is None:
        return None, None
    length, ftype = _HDR.unpack(hdr)
    payload = _recv_exact(sock, length, peer=peer,
                          what=frame_name(ftype)) if length else b""
    if length and payload is None:
        return None, None
    _M_RX.inc()
    _M_RX_BYTES.inc(_HDR.size + length)
    return ftype, payload


def _check_env_fingerprint(rank: int, payload: bytes, offset: int) -> None:
    """Cross-rank uniformity check of the SPMD-program-selecting env
    knobs (compression/quantization/hierarchy/overlap — see
    ops/compression.env_fingerprint): the worker's HELLO carries its
    fingerprint; a divergence from the controller's means the ranks
    would compile DIFFERENT collective programs — silent garbage or a
    hang — so warn AT INIT naming the rank and every divergent knob.
    ``HVD_TPU_OVERLAP`` rides the same fingerprint: a rank running the
    bucketed-backward schedule against monolithic peers would submit a
    per-bucket collective program the others never produce."""
    if len(payload) < offset + 2:
        return  # pre-fingerprint HELLO (tests poking raw frames)
    (flen,) = struct.unpack_from("<H", payload, offset)
    _check_env_fingerprint_str(
        rank, payload[offset + 2:offset + 2 + flen].decode("utf-8"))


def _check_env_fingerprint_str(rank: int, theirs: str) -> None:
    """String-level half of :func:`_check_env_fingerprint` — the tree
    handshake carries fingerprints pre-parsed per subtree entry."""
    from . import compression as _compression

    mine = _compression.env_fingerprint()
    if theirs == mine:
        return
    their_map = dict(kv.split("=", 1) for kv in theirs.split(";") if kv)
    my_map = dict(kv.split("=", 1) for kv in mine.split(";") if kv)
    diffs = [f"{k}: rank0={my_map.get(k, '?')} rank{rank}="
             f"{their_map.get(k, '?')}"
             for k in sorted(set(my_map) | set(their_map))
             if my_map.get(k) != their_map.get(k)]
    print(f"WARNING: rank {rank} disagrees with rank 0 on env knobs "
          f"that change the compiled SPMD program — collectives WILL "
          f"diverge (docs/performance.md \"Env-knob uniformity\"): "
          f"{'; '.join(diffs)}", file=sys.stderr)


@dataclass(frozen=True)
class Topology:
    local_rank: int
    local_size: int
    cross_rank: int
    cross_size: int


def _assign_topology(hosts: Dict[int, str]) -> Dict[int, Topology]:
    """rank→hostname ⇒ rank→(local/cross) placement, reference semantics:
    local = ranks sharing a host (SHARED split), cross = one rank per host
    ordered by lowest global rank (operations.cc:1184-1196)."""
    by_host: Dict[str, List[int]] = {}
    for rank in sorted(hosts):
        by_host.setdefault(hosts[rank], []).append(rank)
    host_order = sorted(by_host, key=lambda h: by_host[h][0])
    out: Dict[int, Topology] = {}
    for ci, host in enumerate(host_order):
        ranks = by_host[host]
        for li, rank in enumerate(ranks):
            out[rank] = Topology(local_rank=li, local_size=len(ranks),
                                 cross_rank=ci, cross_size=len(host_order))
    return out


@dataclass
class _PeerSession:
    """Controller-side per-worker session state surviving reconnects:
    the live socket (None while disconnected), the outgoing replay
    ring, the received-frame count, and the grace bookkeeping.  The
    socket/grace fields are mutated under ControllerTransport._lock;
    the ring under _send_lock; rx_count only by the one live receive
    thread."""

    rank: int
    conn: Optional[socket.socket]
    ring: _FrameRing
    rx_count: int = 0
    rx_thread: Optional[threading.Thread] = None
    grace_deadline: Optional[float] = None
    disc_epoch: int = -1
    # True while a session resume is in flight on the accept thread:
    # expire_grace must not declare the rank dead out from under a
    # resume that is about to complete (the boundary-timing race).
    resuming: bool = False
    # Tree mode: every rank this connection's subtree covers (incl.
    # the direct child itself); a covered rank that re-parents moves
    # into its own session.  Flat mode: just {rank}.  Mutated under
    # ControllerTransport._lock like the socket/grace fields.
    covers: set = field(default_factory=set)


@_races.race_checked
class ControllerTransport:
    """Rank 0: accepts one connection per worker, feeds their Requests into
    the in-process coordinator, broadcasts Response lists to everyone."""

    def __init__(self, coordinator, num_processes: int, port: int,
                 hostname: Optional[str] = None, tree=None):
        self.coordinator = coordinator
        # Tree overlay (ops/tree.py TreeLayout) or None for the flat
        # star.  In tree mode the root accepts only its direct
        # children; each connection's HELLO_TREE covers a whole
        # subtree, every broadcast goes into ONE shared ring (the
        # downward stream is identical on every path, which is what
        # lets an orphaned rank re-parent here and resume from the
        # global stream index), and per-rank upward frame counts come
        # from the interiors' merged envelopes.
        self.tree = tree
        self._bcast_ring = _FrameRing(_ring_limit()) if tree is not None \
            else None
        # Tree mode: logical upward frames processed per ORIGIN rank —
        # direct links count link frames, routed ranks count via the
        # cumulative counts interiors fold into their envelopes.
        # guarded_by: _lock
        self._rank_rx: Dict[int, int] = {}
        # Shared response-cache replica (ops/cache.py), attached by
        # core.state.init after construction; None = caching disabled.
        self.cache = None
        self.num_processes = num_processes
        self.shutdown_requested = threading.Event()
        # Ranks whose connection dropped without a SHUTDOWN frame and
        # whose reconnect grace (if any) expired — i.e. the process
        # died (SURVEY §5 failure detection; the reference can only
        # hang or MPI-abort here).
        self.lost_ranks: set = set()
        # rank -> why it was declared lost (grace expiry / ring
        # overflow); folded into the dead-peer diagnostic so the
        # poison message names the fault, not just the rank.
        self.lost_reasons: Dict[int, str] = {}
        self._closing = False
        # Requests whose process set was not yet registered on arrival
        # (registration race): retried by flush_unrouted.
        self._unrouted: List = []
        self._lock = _lockorder.make_lock("ControllerTransport._lock")
        self._send_lock = _lockorder.make_lock(
            "ControllerTransport._send_lock")
        # Per-worker sessions (socket + replay ring + grace state);
        # the mapping itself is fixed after init — only session fields
        # mutate (see _PeerSession's locking note).
        self._sess: Dict[int, _PeerSession] = {}
        # verify_program rendezvous: round → rank → signature payload,
        # collected by the receive threads, consumed by rank 0's
        # verify_program (analysis/program.py).  Keyed by round so a
        # straggler from a timed-out round is never mis-paired.
        self._sig_cond = threading.Condition(self._lock)
        # guarded_by: _sig_cond
        self._signatures: Dict[int, Dict[int, bytes]] = {}
        self._sig_round = 0  # guarded_by: _sig_cond
        # hvd-telemetry pull rendezvous: round → rank → decoded
        # snapshot, same round-keying discipline as the signatures.
        self._met_cond = threading.Condition(self._lock)
        # guarded_by: _met_cond
        self._met_payloads: Dict[int, Dict[int, dict]] = {}
        self._met_round = 0  # guarded_by: _met_cond
        # hvd-trace span pull rendezvous (FRAME_TRACE): round → rank →
        # decoded span list, same round-keying discipline.
        self._trc_cond = threading.Condition(self._lock)
        # guarded_by: _trc_cond
        self._trc_payloads: Dict[int, Dict[int, list]] = {}
        self._trc_round = 0  # guarded_by: _trc_cond
        # hvd-trace clock alignment: per-peer NTP offset estimators fed
        # by FRAME_PONG on the receive threads, reset on session resume.
        self.clock = _trace_clock.ClockSync()
        self._ping_seq = 0
        self._last_ping = 0.0
        # Probe cadence parsed ONCE: maybe_ping runs every drain tick,
        # and an env read + float() per tick is avoidable hot-path
        # cost (tests repointing HVD_TPU_TRACE_PING construct a fresh
        # transport anyway).
        self._ping_interval = _trace.ping_interval()
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind(("0.0.0.0", port))
        self._srv.listen(num_processes)
        self.port = self._srv.getsockname()[1]
        self._threads: List[threading.Thread] = []

        hosts = {0: hostname or socket.gethostname()}
        socks: Dict[int, socket.socket] = {}
        # rank of the direct child -> set of ranks its subtree covers
        # (tree mode; flat mode every connection covers itself only).
        coverage: Dict[int, set] = {}
        # Bound the wait for stragglers so a worker that died between the
        # jax.distributed rendezvous and its HELLO produces an error naming
        # the missing ranks instead of a silent hang.
        accept_timeout = float(
            os.environ.get("HVD_TPU_CONNECT_TIMEOUT", "120"))
        self._srv.settimeout(accept_timeout)
        expected_links = (len(tree.children(0)) if tree is not None
                          else num_processes - 1)
        for _ in range(expected_links):
            try:
                conn, _addr = self._srv.accept()
            except socket.timeout:
                missing = sorted(set(range(num_processes)) - set(hosts))
                raise TimeoutError(
                    f"controller: ranks {missing} did not connect within "
                    f"{accept_timeout}s; did those processes die during "
                    f"startup?") from None
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            ftype, payload = _recv_frame(conn)
            if tree is not None:
                if ftype != FRAME_HELLO_TREE:
                    raise RuntimeError(
                        f"controller expected HELLO_TREE, got frame "
                        f"type {ftype}")
                from . import tree as _tree_mod

                entries = _tree_mod.parse_hello_tree(payload)
                child = entries[0][0]  # subtree root connects itself
                coverage[child] = {r for r, _h, _fp in entries}
                for rank, host, fp in entries:
                    hosts[rank] = host
                    _check_env_fingerprint_str(rank, fp)
                socks[child] = conn
                continue
            if ftype != FRAME_HELLO:
                raise RuntimeError(
                    f"controller expected HELLO, got frame type {ftype}")
            (rank,) = struct.unpack_from("<i", payload)
            (hlen,) = struct.unpack_from("<H", payload, 4)
            hosts[rank] = payload[6:6 + hlen].decode("utf-8")
            _check_env_fingerprint(rank, payload, 6 + hlen)
            socks[rank] = conn
            coverage[rank] = {rank}
        if len(hosts) != num_processes:
            missing = sorted(set(range(num_processes)) - set(hosts))
            raise RuntimeError(
                f"controller: tree handshake left ranks {missing} "
                f"uncovered (HVD_TPU_TREE_FANOUT mismatch across "
                f"ranks?)")
        from . import cache as _cache_mod

        self.topology = _assign_topology(hosts)
        cache_flag = 1 if _cache_mod.cache_enabled() else 0
        for rank, conn in socks.items():
            if tree is not None:
                from . import tree as _tree_mod

                _send_frame(conn, FRAME_TOPO_TREE,
                            _tree_mod.pack_topo_tree(
                                cache_flag,
                                [(r, self.topology[r])
                                 for r in sorted(coverage[rank])]))
            else:
                t = self.topology[rank]
                _send_frame(conn, FRAME_TOPO, struct.pack(
                    "<iiiii", t.local_rank, t.local_size,
                    t.cross_rank, t.cross_size, cache_flag))
        with self._lock:
            for rank, conn in socks.items():
                # Frame deadlines arm AFTER the handshake: idleness
                # between frames stays legal, a stall mid-frame names
                # the peer (FrameDeadlineError).
                conn.settimeout(_frame_timeout())
                self._sess[rank] = _PeerSession(
                    rank=rank, conn=conn, ring=_FrameRing(_ring_limit()),
                    covers=set(coverage[rank]))
        for rank in socks:
            self._start_rx(rank, socks[rank])
        # Session-resume listener: the server socket stays open so a
        # worker whose connection dropped can reconnect
        # (FRAME_RECONNECT) for the remainder of the job.
        if _reconnect_enabled() and num_processes > 1:
            self._srv.settimeout(None)
            th = threading.Thread(target=self._accept_loop,
                                  name="hvd-controller-accept",
                                  daemon=True)
            th.start()
            self._threads.append(th)
        # Mirror of the worker exit handshake: a controller whose
        # interpreter exits without hvd.shutdown() still broadcasts a clean
        # SHUTDOWN, so workers take the cooperative path (and keep jax's
        # exit barrier, which a cleanly-exiting controller does reach).
        atexit.register(self._atexit_handshake)

    def _start_rx(self, rank: int, conn: socket.socket) -> None:
        th = threading.Thread(target=self._serve, args=(rank, conn),
                              name=f"hvd-controller-rx-{rank}",
                              daemon=True)
        with self._lock:
            self._sess[rank].rx_thread = th
        th.start()
        self._threads.append(th)

    def _atexit_handshake(self) -> None:
        if self._closing:
            return
        try:
            self.broadcast_responses(
                [Response(ResponseType.SHUTDOWN)])
        except OSError:
            pass

    # -- session-resume listener (hvd-chaos reconnect) ---------------------
    def _accept_loop(self) -> None:  # thread: accept
        _athreads.set_role("accept")
        try:
            self._accept_loop_inner()
        except Exception:
            import traceback

            _telemetry.exception_event(
                "controller-accept", traceback.format_exc())
            raise

    def _accept_loop_inner(self) -> None:
        while not self._closing:
            try:
                conn, _addr = self._srv.accept()
            except OSError:
                return  # close() shut the server socket down
            try:
                conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                conn.settimeout(10.0)
                ftype, payload = _recv_frame(conn, peer="reconnecting",
                                             idle_ok=False)
            except OSError:
                continue
            if (ftype != FRAME_RECONNECT or self._closing
                    or len(payload) < 13):
                # Wrong/garbled first frame (version skew, a stray
                # client probing the port): drop the connection, keep
                # the listener — this loop must survive the whole job
                # or every later legitimate reconnect dies with it.
                try:
                    conn.close()
                except OSError:
                    pass
                continue
            try:
                self._handle_reconnect(conn, payload)
            except Exception:  # noqa: BLE001 — one bad resume must
                # not kill the listener for the rest of the job
                import traceback

                _telemetry.exception_event(
                    "controller-resume", traceback.format_exc())
                try:
                    conn.close()
                except OSError:
                    pass

    def _mark_disconnected(self, sess: _PeerSession, why: str) -> None:
        """A worker's connection broke (receive EOF, send failure, or a
        reconnect superseding a half-dead socket): close it and either
        open the reconnect grace window or — reconnect disabled /
        already shutting down — declare the rank lost immediately (the
        pre-chaos behavior)."""
        with self._lock:
            conn, sess.conn = sess.conn, None
            if conn is not None:
                # shutdown-then-close: the rank's receive thread may be
                # blocked in recv on this socket and must wake NOW (a
                # bare close leaves it parked on this kernel).
                _wake_close(conn)
            if self.shutdown_requested.is_set() or self._closing:
                return
            if sess.rank in self.lost_ranks:
                return
            if not _reconnect_enabled():
                self.lost_ranks.add(sess.rank)
                return
            if sess.grace_deadline is None:
                grace = _grace_seconds()
                sess.grace_deadline = time.monotonic() + grace
                sess.disc_epoch = (self.cache.epoch
                                   if self.cache is not None else -1)
                _M_DISCONNECTS.inc()
                _telemetry.transport_fault_event(
                    "peer-disconnect", f"rank {sess.rank}: {why}")
                print(f"[hvd-reconnect] controller: rank {sess.rank} "
                      f"control-plane connection lost ({why}); holding "
                      f"its session for {grace:.1f}s grace",
                      file=sys.stderr)

    def expire_grace(self) -> None:
        """Drain-tick sweep: a disconnected rank whose grace window
        expired without a reconnect becomes a dead peer — the bounded
        end of the no-hang contract, with a diagnostic naming the
        fault (``lost_reasons``)."""
        if not self._sess:
            return
        now = time.monotonic()
        with self._lock:
            for sess in list(self._sess.values()):
                if (sess.grace_deadline is not None
                        and not sess.resuming
                        and now > sess.grace_deadline
                        and sess.rank not in self.lost_ranks):
                    reason = (f"control-plane connection lost; no "
                              f"reconnect within "
                              f"{_grace_seconds():.1f}s grace")
                    self.lost_ranks.add(sess.rank)
                    self.lost_reasons[sess.rank] = reason
                    sess.grace_deadline = None
                    _flight.record("grace_expired", sess.rank)
                    print(f"ERROR: rank {sess.rank}: {reason}",
                          file=sys.stderr)
                    # Tree mode: the expired link covered a subtree.
                    # The covered ranks are probably mid-re-parent —
                    # give each its OWN grace window instead of an
                    # instant death sentence; a rank that neither
                    # re-parents nor is re-reported becomes lost with
                    # a diagnostic naming the interior (bounded at
                    # 2x grace end to end).
                    for crank in sorted(sess.covers - {sess.rank}):
                        if (crank in self.lost_ranks
                                or crank in self._sess):
                            continue
                        orphan = _PeerSession(
                            rank=crank, conn=None, ring=_FrameRing(1),
                            covers={crank},
                            grace_deadline=now + _grace_seconds(),
                            disc_epoch=(self.cache.epoch
                                        if self.cache is not None
                                        else -1))
                        self._sess[crank] = orphan
                        print(f"[hvd-tree] controller: rank {crank} "
                              f"was routed via lost rank {sess.rank}; "
                              f"holding {_grace_seconds():.1f}s for a "
                              f"re-parent", file=sys.stderr)
                    sess.covers = {sess.rank}

    def _handle_reconnect(self, conn: socket.socket,
                          payload: bytes) -> None:
        """Resume one worker's session on a fresh socket: compare
        received-frame counts, replay the lost controller→worker
        suffix, and verdict the worker's cache replica (resume when its
        epoch matches the disconnect-time epoch, drop it otherwise).
        Serialized against broadcasts by ``_send_lock`` so no new frame
        can interleave ahead of the replayed suffix."""
        rank, their_rx, epoch, has_cache = struct.unpack_from(
            "<iIiB", payload)
        adopted = False
        with self._lock:
            sess = self._sess.get(rank)
            lost = rank in self.lost_ranks
            if (sess is None and not lost and self.tree is not None
                    and 0 < rank < self.num_processes):
                # Tree re-parent: a rank routed via an interior lost
                # its parent and is reconnecting to the root directly.
                # Adopt it as a direct child — the shared broadcast
                # ring replays the downward suffix it missed (the
                # stream is identical on every path), and its own
                # outgoing ring replays the upward suffix the dead
                # interior swallowed (duplicate submits/bits are
                # idempotent by design).
                for other in self._sess.values():
                    other.covers.discard(rank)
                sess = _PeerSession(
                    rank=rank, conn=None, ring=_FrameRing(1),
                    covers={rank},
                    disc_epoch=(self.cache.epoch
                                if self.cache is not None else -1))
                self._sess[rank] = sess
                adopted = True
        if sess is None or lost:
            why = (self.lost_reasons.get(rank, "declared dead")
                   if lost else "unknown rank")
            self._reject_reconnect(conn, rank, why)
            return
        if adopted:
            _M_REPARENTS.inc()
            _flight.record("tree_reparent", rank)
            print(f"[hvd-tree] controller: adopting rank {rank} as a "
                  f"direct child (re-parented after interior loss)",
                  file=sys.stderr)
        # Shield the session from expire_grace while the resume is in
        # flight: a reconnect landing near the grace deadline must not
        # be completed here while the drain tick concurrently declares
        # the rank dead (resuming is cleared — and the grace window
        # re-armed on failure — in the finally below).
        with self._lock:
            sess.resuming = True
        try:
            self._resume_session(sess, conn, their_rx, epoch, has_cache)
        finally:
            with self._lock:
                sess.resuming = False
                if (sess.conn is None
                        and sess.rank not in self.lost_ranks
                        and not (self.shutdown_requested.is_set()
                                 or self._closing)):
                    # The resume failed mid-handshake: give the worker
                    # a fresh full grace window to try again — and keep
                    # the bounded no-hang contract armed.
                    sess.grace_deadline = (time.monotonic()
                                           + _grace_seconds())

    def _resume_session(self, sess: _PeerSession, conn: socket.socket,
                        their_rx: int, epoch: int,
                        has_cache: int) -> None:
        rank = sess.rank
        # Supersede a half-dead socket the controller had not noticed
        # dropping yet, and wait for its receive thread to finish so
        # the rx_count we report is final (no frame can be double-
        # counted between our report and the worker's replay).
        self._mark_disconnected(sess, "superseded by reconnect")
        rx_th = sess.rx_thread
        if rx_th is not None and rx_th is not threading.current_thread():
            rx_th.join(timeout=5.0)
        with self._send_lock:
            ring = self._bcast_ring if self.tree is not None \
                else sess.ring
            suffix = ring.since(their_rx)
            if suffix is None:
                reason = (f"cannot resume rank {rank}: it received "
                          f"{their_rx} of {ring.count} frames but "
                          f"the replay ring no longer holds that "
                          f"suffix (gap beyond HVD_TPU_RECONNECT_RING)")
                with self._lock:
                    self.lost_ranks.add(rank)
                    self.lost_reasons[rank] = \
                        "reconnect replay ring overflow"
                self._reject_reconnect(conn, rank, reason)
                return
            if self.tree is not None:
                # Tree mode: the GLOBAL broadcast stream replay applies
                # any missed flush markers in order, so a replica at an
                # OLDER epoch re-converges deterministically; only a
                # bogus future epoch (or no controller cache) drops it.
                live_epoch = (self.cache.epoch
                              if self.cache is not None else -1)
                drop_cache = bool(has_cache) and (
                    self.cache is None or epoch > live_epoch)
                reason = (f"cache epoch {epoch} ahead of controller "
                          f"epoch {live_epoch}; resume cache-less"
                          if drop_cache else "")
            else:
                drop_cache = bool(has_cache) and (
                    self.cache is None or epoch != sess.disc_epoch)
                reason = (f"cache epoch {epoch} != disconnect-time "
                          f"epoch {sess.disc_epoch}; resume cache-less"
                          if drop_cache else "")
            verdict = 2 if drop_cache else 1
            rb = reason.encode("utf-8")
            if self.tree is not None:
                with self._lock:
                    rx_report = self._rank_rx.get(rank, 0)
            else:
                rx_report = sess.rx_count
            try:
                _send_frame(conn, FRAME_RESUME,
                            struct.pack("<IBH", rx_report, verdict,
                                        len(rb)) + rb)
                for ftype, fpayload in suffix:
                    _send_frame(conn, ftype, fpayload)
                    _M_REPLAYED.inc()
            except OSError:
                try:
                    conn.close()
                except OSError:
                    pass
                return  # still in grace; the worker may try again
            conn.settimeout(_frame_timeout())
            with self._lock:
                sess.conn = conn
                sess.grace_deadline = None
        _M_RECONNECTS_ACCEPTED.inc()
        # hvd-trace: the peer's network path changed — its old clock
        # samples measured a connection that no longer exists.  Fresh
        # pings (the drain tick's maybe_ping) re-converge the estimate.
        self.clock.reset(rank)
        _flight.record("reconnect_accepted", rank, their_rx,
                       len(suffix), verdict)
        print(f"[hvd-reconnect] controller: rank {rank} resumed "
              f"(replayed {len(suffix)} frames"
              f"{', cache dropped' if drop_cache else ''})",
              file=sys.stderr)
        if drop_cache and self.cache is not None:
            # The worker's replica is gone: flush the shared cache so
            # no compact replay frame it cannot reconstitute is ever
            # broadcast; mid-flight cached submissions downgrade into
            # real negotiations (never lost).
            for req in self.cache.flush(
                    f"rank {rank} reconnected cache-less",
                    broadcast=True):
                if not self._try_submit(req):
                    with self._lock:
                        self._unrouted.append(
                            (time.monotonic() + 5.0, req))
        self._start_rx(rank, conn)

    def _reject_reconnect(self, conn: socket.socket, rank: int,
                          reason: str) -> None:
        print(f"[hvd-reconnect] controller: rejecting reconnect from "
              f"rank {rank}: {reason}", file=sys.stderr)
        _flight.record("reconnect_rejected", rank, reason)
        rb = reason.encode("utf-8")
        try:
            _send_frame(conn, FRAME_RESUME,
                        struct.pack("<IBH", 0, 0, len(rb)) + rb)
        except OSError:
            pass
        try:
            conn.close()
        except OSError:
            pass

    def _serve(self, rank: int, conn: socket.socket) -> None:  # thread: rx
        _athreads.set_role("rx")
        # An unhandled exception on a receive thread silently kills the
        # control plane for that worker; dump the flight ring naming
        # the thread before the (daemon) thread dies.
        try:
            self._serve_inner(rank, conn)
        except Exception:
            import traceback

            _telemetry.exception_event(
                "controller-rx", traceback.format_exc())
            raise

    def _serve_inner(self, rank: int, conn: socket.socket) -> None:
        sess = self._sess[rank]
        while True:
            try:
                ftype, payload = _recv_frame(conn, peer=f"rank {rank}")
            except OSError:
                ftype = None  # worker died mid-frame / reset the conn
            if ftype is None:
                with self._lock:
                    superseded = sess.conn is not conn
                if superseded:
                    return  # a reconnect already installed a new socket
                # EOF without a SHUTDOWN frame = the connection (or the
                # worker) went away; grace/lost handling decides which.
                if not (self.shutdown_requested.is_set() or self._closing):
                    _flight.record("peer_eof", rank)
                    self._mark_disconnected(sess, "eof")
                return
            sess.rx_count += 1
            if self.tree is not None:
                # Per-origin logical frame count (the re-parent resume
                # protocol's upward half): a direct link's frames count
                # against the link's own rank; dissolved child frames
                # arrive via the envelopes' counts sections.
                with self._lock:
                    self._rank_rx[rank] = self._rank_rx.get(rank, 0) + 1
            if ftype == FRAME_SUBTREE_BATCH:
                self._handle_subtree_batch(payload)
            elif ftype == FRAME_METRICS_TREE:
                from . import tree as _tree_mod

                rnd, entries = _tree_mod.parse_merged_pull(payload)
                with self._met_cond:
                    if rnd in self._met_payloads:
                        for erank, blob in entries:
                            try:
                                snap = json.loads(blob.decode("utf-8"))
                            except (ValueError, UnicodeDecodeError):
                                snap = {}
                            self._met_payloads[rnd][erank] = snap
                        self._met_cond.notify_all()
            elif ftype == FRAME_TRACE_TREE:
                from . import tree as _tree_mod

                rnd, entries = _tree_mod.parse_merged_pull(payload)
                with self._trc_cond:
                    if rnd in self._trc_payloads:
                        for erank, blob in entries:
                            try:
                                evs = json.loads(blob.decode("utf-8"))
                            except (ValueError, UnicodeDecodeError):
                                evs = []
                            self._trc_payloads[rnd][erank] = \
                                evs if isinstance(evs, list) else []
                        self._trc_cond.notify_all()
            elif ftype == FRAME_CHILD_LOST:
                (crank,) = struct.unpack_from("<i", payload)
                (rlen,) = struct.unpack_from("<H", payload, 4)
                reason = payload[6:6 + rlen].decode("utf-8")
                self._handle_child_lost(crank, reason)
            elif ftype == FRAME_REQUEST:
                req, _ = Request.unpack(payload)
                if not self._try_submit(req):
                    # Registration race: the worker's set request can
                    # arrive before the controller's own add_process_set
                    # finishes.  Never block THIS receive thread (later
                    # frames — withdraw, shutdown — must not queue
                    # behind an orphan); the drain loop retries via
                    # flush_unrouted with a bounded lifetime.
                    with self._lock:
                        self._unrouted.append(
                            (time.monotonic() + 5.0, req))
            elif ftype == FRAME_REQUEST_BATCH:
                self._handle_request_batch(payload)
            elif ftype == FRAME_SHUTDOWN:
                self.shutdown_requested.set()
            elif ftype == FRAME_SIGNATURE:
                srank, srnd = struct.unpack_from("<iI", payload)
                with self._sig_cond:
                    self._signatures.setdefault(srnd, {})[srank] = \
                        payload[8:]
                    self._sig_cond.notify_all()
            elif ftype == FRAME_METRICS:
                mrank, mrnd = struct.unpack_from("<iI", payload)
                try:
                    snap = json.loads(payload[8:].decode("utf-8"))
                except (ValueError, UnicodeDecodeError):
                    snap = {}
                with self._met_cond:
                    # Only rounds with a live waiter accept replies: a
                    # straggler answer to an abandoned pull must not
                    # resurrect its round dict (it would leak forever).
                    if mrnd in self._met_payloads:
                        self._met_payloads[mrnd][mrank] = snap
                        self._met_cond.notify_all()
            elif ftype == FRAME_PONG:
                # Clock sample: stamp the arrival FIRST (t2), before
                # any parsing cost lands in the RTT.
                t2 = time.monotonic()
                prank, _seq, t0, t1 = struct.unpack_from("<iIdd",
                                                         payload)
                self.clock.on_pong(prank, t0, t1, t2)
            elif ftype == FRAME_TRACE:
                trank, trnd = struct.unpack_from("<iI", payload)
                try:
                    evs = json.loads(payload[8:].decode("utf-8"))
                except (ValueError, UnicodeDecodeError):
                    evs = []
                with self._trc_cond:
                    # Same live-waiter discipline as FRAME_METRICS.
                    if trnd in self._trc_payloads:
                        self._trc_payloads[trnd][trank] = \
                            evs if isinstance(evs, list) else []
                        self._trc_cond.notify_all()
            elif ftype == FRAME_WITHDRAW:
                (wrank,) = struct.unpack_from("<i", payload)
                (nlen,) = struct.unpack_from("<H", payload, 4)
                name = payload[6:6 + nlen].decode("utf-8")
                psid = 0
                if len(payload) >= 8 + nlen:
                    (psid,) = struct.unpack_from("<H", payload, 6 + nlen)
                # The next drain tick broadcasts the resulting ERROR
                # response to every rank (including the withdrawer).
                coord = self._route_coord(psid)
                if coord is not None:
                    coord.withdraw(name, wrank)

    def _handle_request_batch(self, payload: bytes) -> None:
        """One worker drain tick's coalesced control frame: a cache-hit
        bit-vector (entry indices into the shared response cache) plus
        any full requests.  A bit whose epoch predates the live cache
        generation is DOWNGRADED into a real submit of the retired
        entry's stored request — a flush can delay a submission but
        never lose it."""
        srank, epoch, nbits = struct.unpack_from("<iII", payload)
        off = 12
        bitvec = payload[off:off + nbits]
        off += nbits
        (nreq,) = struct.unpack_from("<H", payload, off)
        off += 2
        _flight.record("frame_rx_batch", srank, epoch, nreq)
        for byte_i, b in enumerate(bitvec):
            while b:
                low = b & -b
                idx = byte_i * 8 + low.bit_length() - 1
                b ^= low
                self._account_bit(idx, srank, epoch)
        for _ in range(nreq):
            req, off = Request.unpack(payload, off)
            if not self._try_submit(req):
                with self._lock:
                    self._unrouted.append((time.monotonic() + 5.0, req))
        # hvd-trace trailer: the worker's (step, cycle) context — the
        # controller's per-rank arrival stamp for this cycle, feeding
        # the live skew tracker and the analyzer's straggler signal.
        ctx = _trace.unpack_ctx(payload, off)
        if ctx is not None:
            _trace.note_batch_arrival(srank, ctx[0], ctx[1])

    def _account_bit(self, idx: int, srank: int, epoch: int) -> None:
        """One worker cache-hit bit (flat frame or dissolved from a
        subtree envelope): account it, or downgrade a stale-epoch bit
        into a real submit of the retired entry's stored request."""
        cache = self.cache
        if cache is None:
            print(f"WARNING: rank {srank} sent a response-cache bit "
                  f"but the controller cache is disabled "
                  f"(HVD_TPU_RESPONSE_CACHE mismatch across ranks?)",
                  file=sys.stderr)
            return
        down = cache.hit_from_wire(idx, srank, epoch)
        if down is not None and not self._try_submit(down):
            with self._lock:
                self._unrouted.append((time.monotonic() + 5.0, down))

    def _handle_subtree_batch(self, payload: bytes) -> None:
        """One merged subtree envelope (tree overlay): the interiors'
        per-tick aggregation of their subtree's FRAME_REQUEST_BATCH
        traffic.  Sections dissolve into the IDENTICAL per-bit /
        per-request processing the flat path runs, so the negotiation
        outcome — and with it the broadcast response stream every cache
        replica is aligned by — is byte-for-byte the flat one."""
        from . import tree as _tree_mod

        nbits = nreqs = 0
        for sec in _tree_mod.iter_subtree_sections(payload):
            kind = sec[0]
            if kind == "bits":
                _kind, epoch, ranks, idxs = sec
                for srank in ranks:
                    for idx in idxs:
                        self._account_bit(idx, srank, epoch)
                    nbits += len(idxs)
            elif kind == "reqs":
                _kind, srank, reqs = sec
                for req in reqs:
                    nreqs += 1
                    if not self._try_submit(req):
                        with self._lock:
                            self._unrouted.append(
                                (time.monotonic() + 5.0, req))
            elif kind == "arrival":
                _kind, srank, ctx = sec
                if ctx is not None:
                    _trace.note_batch_arrival(srank, ctx[0], ctx[1])
            elif kind == "counts":
                with self._lock:
                    for srank, cum in sec[1].items():
                        if cum > self._rank_rx.get(srank, 0):
                            self._rank_rx[srank] = cum
        _flight.record("frame_rx_subtree", nbits, nreqs)

    def _handle_child_lost(self, crank: int, reason: str) -> None:
        """An interior reported a dead child link.  Only the root
        arbitrates liveness: the rank may have re-parented here in the
        meantime (its own live session wins), otherwise it gets its own
        grace window — re-parent within it or become a dead peer with
        the interior's diagnostic."""
        _M_CHILD_LOST.inc()
        with self._lock:
            sess = self._sess.get(crank)
            if crank in self.lost_ranks:
                return
            if sess is not None and (sess.conn is not None
                                     or sess.resuming):
                return  # already re-parented; the report is stale
            for other in self._sess.values():
                other.covers.discard(crank)
            if sess is None:
                sess = _PeerSession(
                    rank=crank, conn=None, ring=_FrameRing(1),
                    covers={crank})
                self._sess[crank] = sess
            if sess.grace_deadline is None:
                sess.grace_deadline = time.monotonic() + _grace_seconds()
                sess.disc_epoch = (self.cache.epoch
                                   if self.cache is not None else -1)
        _flight.record("tree_child_lost", crank, reason)
        print(f"[hvd-tree] controller: interior reported rank {crank} "
              f"unreachable ({reason}); holding {_grace_seconds():.1f}s "
              f"for a re-parent", file=sys.stderr)

    def _route_coord(self, psid: int):
        """Coordinator for a process-set id (0 = global); None when the
        set is not (yet) registered on this controller."""
        if psid == 0:
            return self.coordinator
        from ..core import state as _st

        # Locked read: this runs on a receive thread while user threads
        # register/remove sets (guarded-by lint finding).
        ps = _st.get_process_set(psid)
        return None if ps is None else ps.coordinator

    def _try_submit(self, req: Request) -> bool:
        coord = self._route_coord(req.process_set_id)
        if coord is None:
            return False
        try:
            coord.submit(req)
        except ValueError:
            # Duplicate-name submissions are a caller bug on the
            # worker; it learns via its own synchronize timeout.
            pass
        return True

    def flush_unrouted(self) -> None:
        """Retry buffered requests whose process set was unknown when
        they arrived (called from the drain loop each tick).  Requests
        past their lifetime are dropped — the submitter's stall/withdraw
        path reports the op."""
        with self._lock:
            if not self._unrouted:
                return
            items, self._unrouted = self._unrouted, []
        now = time.monotonic()
        keep = [(dl, req) for dl, req in items
                if not self._try_submit(req) and now < dl]
        if keep:
            with self._lock:
                self._unrouted = keep + self._unrouted

    # -- verify_program rendezvous (analysis/program.py) -------------------
    def collect_signatures(self, own: bytes, timeout: float) -> Dict[int,
                                                                     bytes]:
        """Wait until every rank's program signature for THIS round
        arrived (rank 0's is ``own``), then return the payloads.  Rounds
        advance once per call on every rank in lockstep, so a straggler
        payload from a timed-out round sits under its own round key and
        can never complete a later round.  A rank that died mid-round
        surfaces as a TimeoutError naming it."""
        deadline = time.monotonic() + timeout
        with self._sig_cond:
            self._sig_round += 1
            rnd = self._sig_round
            this_round = self._signatures.setdefault(rnd, {})
            this_round[0] = own
            try:
                while len(this_round) < self.num_processes:
                    remaining = deadline - time.monotonic()
                    missing = sorted(set(range(self.num_processes))
                                     - set(this_round))
                    if remaining <= 0 or (self.lost_ranks
                                          and set(missing) <=
                                          set(self.lost_ranks)):
                        raise TimeoutError(
                            f"verify_program: ranks {missing} did not "
                            f"send their collective-program signature "
                            f"within {timeout:.0f}s (did they call "
                            f"verify_program too?)")
                    self._sig_cond.wait(min(remaining, 0.1))
                return dict(this_round)
            finally:
                # Drop this and any earlier (abandoned) rounds.
                for r in [r for r in self._signatures if r <= rnd]:
                    del self._signatures[r]

    def broadcast_signature_result(self, error: Optional[str]) -> None:
        with self._sig_cond:
            rnd = self._sig_round
        payload = struct.pack("<IB", rnd, 0 if error else 1) + (
            error or "").encode("utf-8")
        self._broadcast_frame(FRAME_SIGRESULT, payload)

    # -- hvd-telemetry pull (telemetry/__init__.py cluster_metrics) --------
    def collect_metrics(self, own: dict,
                        timeout: float = 10.0) -> Dict[int, dict]:
        """Pull every rank's metrics snapshot: broadcast a FRAME_METRICS
        request carrying this round's counter, then wait until every
        live rank answered (rank 0's snapshot is ``own``).  Returns the
        snapshots it got — a rank that died or timed out is simply
        absent (the aggregate's ``ranks`` field records coverage;
        observability must not fail the job)."""
        deadline = time.monotonic() + timeout
        with self._met_cond:
            self._met_round += 1
            rnd = self._met_round
            this_round = self._met_payloads.setdefault(rnd, {})
            this_round[0] = own
        self._broadcast_frame(FRAME_METRICS, struct.pack("<I", rnd))
        with self._met_cond:
            try:
                while len(this_round) < self.num_processes:
                    remaining = deadline - time.monotonic()
                    missing = set(range(self.num_processes)) \
                        - set(this_round)
                    if remaining <= 0 or (self.lost_ranks
                                          and missing <=
                                          set(self.lost_ranks)):
                        break
                    self._met_cond.wait(min(remaining, 0.1))
                return dict(this_round)
            finally:
                # Drop ONLY this round: unlike the signature rendezvous
                # (lockstep rounds, at most one in flight), concurrent
                # cluster_metrics() callers each own a round, and a
                # faster caller must not delete a slower one's dict out
                # from under its wait loop.
                self._met_payloads.pop(rnd, None)

    # -- hvd-trace clock probes + span pull (trace/merge.py) ---------------
    def ping_peers(self) -> None:
        """One clock-probe broadcast: every worker answers FRAME_PONG
        with its receive stamp; the receive threads fold the samples
        into :attr:`clock`."""
        self._ping_seq += 1
        self._broadcast_frame(FRAME_PING, struct.pack(
            "<Id", self._ping_seq, time.monotonic()))

    def maybe_ping(self) -> None:
        """Drain-tick hook: keep the per-peer offset estimates (and the
        ``trace.clock_offset_seconds.*`` gauges) fresh at the
        HVD_TPU_TRACE_PING cadence (parsed once at construction).  One
        no-op float compare per tick when not due; silent when tracing
        is disabled."""
        if not _trace.enabled() or self._ping_interval <= 0:
            return
        now = time.monotonic()
        if now - self._last_ping >= self._ping_interval:
            self._last_ping = now
            self.ping_peers()

    def measure_clock_offsets(self, probes: int = 8,
                              timeout: float = 2.0) -> Dict[int, float]:
        """Probe burst ahead of a fleet-trace merge: send ``probes``
        pings and wait until every connected peer contributed at least
        one NEW sample (or the timeout lapses — a dead peer must not
        stall the dump).  Returns the refreshed offsets."""
        with self._lock:
            live: set = set()
            for s in self._sess.values():
                if s.conn is not None:
                    # Tree mode: a live link reaches its whole subtree.
                    live |= (s.covers or {s.rank})
            live.discard(0)
        before = self.clock.sample_counts()
        deadline = time.monotonic() + timeout
        for i in range(max(1, probes)):
            self.ping_peers()
            time.sleep(min(0.005, timeout / max(1, probes)))
        while time.monotonic() < deadline:
            counts = self.clock.sample_counts()
            if all(counts.get(r, 0) > before.get(r, 0) for r in live):
                break
            time.sleep(0.005)
        return self.clock.offsets()

    def collect_traces(self, own: list,
                       timeout: float = 10.0) -> Dict[int, list]:
        """Pull every rank's span buffer (FRAME_TRACE) — the
        ``collect_metrics`` rendezvous, round-keyed so a straggler
        buffer from an abandoned pull never completes a later one.  A
        rank that died or timed out is simply absent."""
        deadline = time.monotonic() + timeout
        with self._trc_cond:
            self._trc_round += 1
            rnd = self._trc_round
            this_round = self._trc_payloads.setdefault(rnd, {})
            this_round[0] = list(own)
        self._broadcast_frame(FRAME_TRACE, struct.pack("<I", rnd))
        with self._trc_cond:
            try:
                while len(this_round) < self.num_processes:
                    remaining = deadline - time.monotonic()
                    missing = set(range(self.num_processes)) \
                        - set(this_round)
                    if remaining <= 0 or (self.lost_ranks
                                          and missing <=
                                          set(self.lost_ranks)):
                        break
                    self._trc_cond.wait(min(remaining, 0.1))
                return dict(this_round)
            finally:
                # Drop ONLY this round (concurrent callers each own
                # one — the collect_metrics discipline).
                self._trc_payloads.pop(rnd, None)

    # -- controller-side API used by the drain loop ------------------------
    def submit(self, req: Request) -> bool:
        """Rank 0's own submit; returns True when the request was served
        from the response cache (the coordinator facade's fast path)."""
        # hvd-trace arrival stamp: rank 0's traffic never crosses the
        # wire, so its first submit of the cycle stands in for the
        # request-batch arrival the workers' trailers produce — the
        # skew baseline StragglerWatch measures the fleet against.
        # note_batch_arrival dedups per (rank, step, cycle), so the
        # per-tensor calls after the first are one tracker lookup.
        if _trace.enabled():
            step, cycle, _tid = _trace.current_ctx()
            _trace.note_batch_arrival(0, step, cycle)
        coord = self._route_coord(req.process_set_id)
        if coord is None:
            raise RuntimeError(
                f"process set {req.process_set_id} is not registered on "
                f"the controller")
        try:
            if hasattr(coord, "submit_ex"):
                _, hit = coord.submit_ex(req)
                return hit
            coord.submit(req)
        except ValueError:
            pass  # duplicate-name caller bug; surfaces via timeout
        return False

    def _broadcast_frame(self, ftype: int, payload: bytes) -> None:
        """Send one frame to every worker session.  Every frame is
        appended to the per-rank replay ring FIRST — a rank in its
        reconnect grace window receives the frames on resume, in
        original order, so the response stream (and with it every
        cache replica) survives the disconnect bit-for-bit.
        ``_send_lock`` serializes whole frames: the drain thread and a
        shutdown()-calling user thread must not interleave bytes on
        one socket."""
        with self._send_lock:
            with self._lock:
                sessions = list(self._sess.values())
            if self.tree is not None:
                # Tree mode: ONE shared ring — every path relays the
                # identical broadcast stream, so any rank (direct child
                # or re-parented orphan) resumes from its global stream
                # index.  Chaos dup is downgraded on these links: a
                # per-link duplicate would desync that index.
                self._bcast_ring.append(ftype, payload)
                for sess in sessions:
                    conn = sess.conn
                    if conn is None:
                        continue
                    try:
                        _send_frame_or_fault(conn, ftype, payload,
                                             allow_dup=False)
                    except OSError as e:
                        self._mark_disconnected(sess,
                                                f"send failed: {e}")
                return
            for sess in sessions:
                sess.ring.append(ftype, payload)
                conn = sess.conn
                if conn is None:
                    continue
                try:
                    if _send_frame_or_fault(conn, ftype, payload) == 2:
                        sess.ring.append(ftype, payload)  # chaos dup
                except OSError as e:
                    # Send-side break detection (connection reset
                    # mid-frame): same grace path as a receive EOF.
                    self._mark_disconnected(sess, f"send failed: {e}")

    def broadcast_responses(self, responses: List[Response]) -> None:
        _flight.record("bcast_responses", len(responses),
                       ",".join(r.response_type.name for r in responses))
        # hvd-trace trailer: rank 0's (step, cycle, trace_id) rides
        # every response broadcast so all ranks tag the cycle's
        # execution spans with the SAME fleet-wide cycle id.  The
        # packed list is self-delimiting; pre-trace parsers never read
        # the 16 extra bytes.
        self._broadcast_frame(FRAME_RESPONSES,
                              wire.pack_response_list(responses)
                              + _trace.pack_ctx())

    def broadcast_replay(self, groups: List[List[int]],
                         epoch: int) -> None:
        """Broadcast a pure cache-replay cycle as fused entry-index
        groups (FRAME_RESPONSE_BATCH) — a handful of bytes per tensor
        instead of full Response payloads; each worker reconstitutes the
        identical fused response list from its cache replica."""
        _flight.record("bcast_replay", epoch, len(groups))
        payload = struct.pack("<IH", epoch, len(groups))
        for g in groups:
            payload += struct.pack("<H", len(g))
            payload += struct.pack(f"<{len(g)}I", *g)
        self._broadcast_frame(FRAME_RESPONSE_BATCH,
                              payload + _trace.pack_ctx())

    def poll_responses(self):
        return None  # responses come from the coordinator on rank 0

    def close(self) -> None:
        self._closing = True
        atexit.unregister(self._atexit_handshake)
        with self._lock:
            conns = [s.conn for s in self._sess.values()
                     if s.conn is not None]
            for s in self._sess.values():
                s.conn = None
        for conn in conns:
            _wake_close(conn)
        self._srv.close()


@_races.race_checked
class WorkerTransport:
    """Ranks 1..N-1: one connection to the controller; sends Requests,
    receives Response lists into a queue the local drain loop empties."""

    def __init__(self, host: str, port: int, rank: int,
                 hostname: Optional[str] = None,
                 connect_timeout: float = 60.0):
        self.rank = rank
        self._host = host
        self._port = port
        # Shared response-cache replica (ops/cache.py), attached by
        # core.state.init after construction; None = caching disabled.
        self.cache = None
        self.shutdown_received = threading.Event()
        self._closing = False
        self._buf_lock = _lockorder.make_lock("WorkerTransport._buf_lock")
        # One drain tick's outgoing control traffic, coalesced into a
        # single FRAME_REQUEST_BATCH by flush_requests: ("bit", epoch,
        # entry_idx) response-cache hits and ("req", packed) fulls.
        self._pending: List[tuple] = []  # guarded_by: _buf_lock
        # Queued (responses, trace_ctx) batches: the hvd-trace context
        # trailer travels WITH its batch so the drain tick adopts the
        # right cycle id even when several broadcasts queue up.
        self._responses: "queue.Queue[tuple]" = queue.Queue()
        # The last popped batch's trace context (step, cycle, trace_id)
        # or None; read by the drain loop right after poll_responses.
        self.last_trace_ctx: Optional[tuple] = None
        # verify_program verdicts (FRAME_SIGRESULT) as (round, verdict);
        # the round counter lets exchange_signature discard a stale
        # verdict left queued by a timed-out earlier round.
        self._sig_results: "queue.Queue" = queue.Queue()
        self._sig_round = 0
        # Session-resume state (hvd-chaos): outgoing replay ring +
        # received-frame count, mirroring the controller's per-rank
        # session.  The ring and _broken are guarded by _send_lock;
        # _rx_count is only touched by the receive thread.
        self._ring = _FrameRing(_ring_limit())
        self._rx_count = 0
        self._broken = False
        self._send_lock = _lockorder.make_lock("WorkerTransport._send_lock")
        # Initial connect: capped exponential backoff with full jitter
        # (utils/retry.py — the SAME policy the reconnect path uses),
        # each attempt logged with the remaining deadline so a slow
        # controller start is observable, not silent.
        deadline = time.monotonic() + connect_timeout
        policy = BackoffPolicy(base=0.05, cap=2.0)
        attempt = 0
        while True:
            try:
                self._sock = socket.create_connection((host, port),
                                                      timeout=5.0)
                break
            except OSError as e:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise TimeoutError(
                        f"rank {rank} could not reach the controller at "
                        f"{host}:{port} within {connect_timeout}s: "
                        f"{e}") from e
                delay = min(policy.delay(attempt), max(remaining, 0.0))
                attempt += 1
                print(f"[hvd-connect] rank {rank}: controller "
                      f"{host}:{port} not reachable (attempt {attempt}: "
                      f"{e}); retrying in {delay:.2f}s "
                      f"({remaining:.1f}s before deadline)",
                      file=sys.stderr)
                time.sleep(delay)
        self._sock.settimeout(None)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._handshake(hostname)
        # Frame deadlines arm after the handshake (see the controller's
        # mirror): idle-between-frames is legal, a mid-frame stall
        # names the controller and the frame type.
        self._sock.settimeout(_frame_timeout())
        self._rx = threading.Thread(target=self._recv_loop,
                                    name=f"hvd-worker-rx-{rank}", daemon=True)
        self._rx.start()
        # Exit handshake (≙ the reference's DONE/shutdown flag on the last
        # MPIRequestList, mpi_message.h:87-103): a worker whose interpreter
        # exits without an explicit hvd.shutdown() still tells the
        # controller it left *cleanly*.  An EOF without this frame is
        # therefore always a crash.  Registered after jax.distributed
        # initialize, so (atexit LIFO) it runs before jax's exit barrier.
        atexit.register(self._atexit_handshake)

    def _handshake(self, hostname: Optional[str]) -> None:
        """HELLO → TOPO exchange on the fresh socket (overridden by the
        tree overlay, which speaks HELLO_TREE / TOPO_TREE and must
        collect its children's hellos first — ops/tree.py)."""
        rank = self.rank
        hb = (hostname or socket.gethostname()).encode("utf-8")
        from . import compression as _compression

        fp = _compression.env_fingerprint().encode("utf-8")
        _send_frame(self._sock, FRAME_HELLO,
                    struct.pack("<i", rank) + struct.pack("<H", len(hb))
                    + hb + struct.pack("<H", len(fp)) + fp)
        ftype, payload = _recv_frame(self._sock)
        if ftype != FRAME_TOPO:
            raise RuntimeError(
                f"rank {rank} expected TOPO from controller, got {ftype}")
        lr, ls, cr, cs = struct.unpack_from("<iiii", payload)
        # The controller's response-cache advertisement: a worker whose
        # own env enables the cache must still run WITHOUT a replica
        # when rank 0 cannot resolve its bits (core.state.init reads
        # this before attaching the cache).
        self.controller_cache = bool(struct.unpack_from(
            "<i", payload, 16)[0]) if len(payload) >= 20 else True
        self.topology = Topology(lr, ls, cr, cs)

    def _atexit_handshake(self) -> None:
        # Sent even when a shutdown was already received (it's idempotent):
        # skipping it would make this worker's EOF look like a crash to a
        # controller whose own exit handshake fired first.
        if self._closing:
            return
        try:
            self.request_shutdown()
        except OSError:
            pass  # controller already gone

    # -- outgoing frames (ring + chaos + broken-socket buffering) ----------
    def _send(self, ftype: int, payload: bytes = b"") -> None:
        """The one post-handshake send path: append to the replay ring,
        then send unless the connection is currently broken — a broken
        connection buffers in the ring and the reconnect handshake
        replays exactly the suffix the controller never received, so a
        send during a disconnect is delayed, never lost (until the
        ring's bound, which fails the reconnect loudly)."""
        with self._send_lock:
            self._ring.append(ftype, payload)
            if self._broken:
                return
            sock = self._sock
            try:
                if _send_frame_or_fault(sock, ftype, payload) == 2:
                    self._ring.append(ftype, payload)  # chaos dup
            except OSError:
                # Mark broken and shutdown-close: the receive thread
                # (possibly parked in recv) wakes on the EOF and runs
                # the reconnect path.
                self._broken = True
                _wake_close(sock)

    def _recv_loop(self) -> None:  # thread: rx
        _athreads.set_role("rx")
        # Mirror of the controller's receive-thread guard: dump the
        # flight ring before an unhandled exception kills the thread.
        try:
            self._recv_loop_inner()
        except Exception:
            import traceback

            _telemetry.exception_event(
                "worker-rx", traceback.format_exc())
            raise

    def _poison(self, detail: str) -> None:
        """Controller connection unrecoverable: surface a synthetic
        SHUTDOWN response so pending ops fail with a diagnosis instead
        of hanging (mirror of the controller's dead-worker path)."""
        from ..core.cluster import disarm_distributed_shutdown

        # The controller can never reach jax.distributed's exit
        # barrier; don't block (then abort) on it.
        disarm_distributed_shutdown()
        _telemetry.dead_peer_event(
            f"rank {self.rank}: controller unreachable ({detail})")
        self._responses.put(([Response(
            ResponseType.SHUTDOWN,
            error_message="Horovod has been shut down: the rank-0 "
            f"controller {DEAD_PEER_MARKER} while collectives were "
            f"pending ({detail}).")], None))

    def _recv_loop_inner(self) -> None:
        while True:
            sock = self._sock
            try:
                ftype, payload = _recv_frame(sock, peer="controller")
            except OSError:
                ftype = None
            if ftype is None:
                # Connection lost: clean shutdown → exit quietly;
                # otherwise try the session-resume protocol, and only
                # an exhausted/failed reconnect poisons pending ops.
                if self.shutdown_received.is_set() or self._closing:
                    return
                _flight.record("ctrl_eof", self.rank)
                if _reconnect_enabled():
                    why = self._reconnect()
                    if why is None:
                        continue  # resumed; keep receiving
                else:
                    why = "reconnect disabled (HVD_TPU_RECONNECT=0)"
                if self._closing or self.shutdown_received.is_set():
                    return
                self._poison(why)
                return
            self._rx_count += 1
            # Tree overlay hook: an interior node relays every
            # broadcast frame to its children BEFORE local processing,
            # so each child's downward stream is the root's, verbatim
            # (no-op on leaves / flat workers).
            self._relay_downward(ftype, payload)
            if ftype == FRAME_RESPONSE_BATCH:
                epoch, ngroups = struct.unpack_from("<IH", payload)
                off = 6
                groups = []
                for _ in range(ngroups):
                    (n,) = struct.unpack_from("<H", payload, off)
                    off += 2
                    groups.append(list(struct.unpack_from(
                        f"<{n}I", payload, off)))
                    off += 4 * n
                ctx = _trace.unpack_ctx(payload, off)
                try:
                    if self.cache is None:
                        raise RuntimeError(
                            "replay frame without a cache replica "
                            "(HVD_TPU_RESPONSE_CACHE mismatch across "
                            "ranks?)")
                    resps = self.cache.rebuild_groups(groups, epoch)
                except RuntimeError as e:
                    # A replica desync is a protocol bug: fail the job
                    # loudly instead of executing desynced responses.
                    print(f"ERROR: rank {self.rank}: {e}",
                          file=sys.stderr)
                    self._responses.put(([Response(
                        ResponseType.SHUTDOWN,
                        error_message="Horovod has been shut down: "
                        f"response-cache replica desync on rank "
                        f"{self.rank}: {e}")], None))
                    continue
                self._responses.put((resps, ctx))
                continue
            if ftype == FRAME_SIGRESULT:
                (rnd,) = struct.unpack_from("<I", payload)
                ok = payload[4:5] == b"\x01"
                self._sig_results.put(
                    (rnd, None if ok else payload[5:].decode("utf-8")))
                continue
            if ftype == FRAME_METRICS:
                # hvd-telemetry pull: answer with this rank's snapshot,
                # echoing the round so a slow reply from an abandoned
                # pull can never complete a later one.  Snapshot +
                # serialization run on this receive thread — collectors
                # only read cheap stats structs, nothing blocks.
                # (Interior tree nodes override _answer_metrics to
                # aggregate their subtree's replies into one frame.)
                (rnd,) = struct.unpack_from("<I", payload)
                self._answer_metrics(rnd)
                continue
            if ftype == FRAME_PING:
                # hvd-trace clock probe: stamp the receipt FIRST so
                # parsing cost never lands in the offset, then answer
                # immediately from this thread — any queueing would
                # inflate the RTT (the filter would only discard it).
                t1 = time.monotonic()
                seq, t0 = struct.unpack_from("<Id", payload)
                self._send(FRAME_PONG, struct.pack(
                    "<iIdd", self.rank, seq, t0, t1))
                continue
            if ftype == FRAME_TRACE:
                # hvd-trace span pull: answer with this rank's buffer,
                # echoing the round (the FRAME_METRICS discipline).
                (rnd,) = struct.unpack_from("<I", payload)
                self._answer_trace(rnd)
                continue
            if ftype == FRAME_RESPONSES:
                resps, off = wire.unpack_response_list_ex(payload)
                ctx = _trace.unpack_ctx(payload, off)
                # Controller-initiated shutdown arrives as a SHUTDOWN-type
                # Response inside the list (the one spelling of the
                # protocol); note it for observability.
                if any(r.response_type == ResponseType.SHUTDOWN
                       for r in resps):
                    self.shutdown_received.set()
                self._responses.put((resps, ctx))

    def _relay_downward(self, ftype: int, payload: bytes) -> None:
        """Tree-overlay hook (no-op here): interiors relay the frame to
        their children verbatim before processing it locally."""

    def _metrics_snapshot(self) -> bytes:
        try:
            return json.dumps(_telemetry.metrics()).encode("utf-8")
        except Exception:  # noqa: BLE001 — must answer regardless
            return b"{}"

    def _trace_snapshot(self) -> bytes:
        try:
            return json.dumps(_trace.export_events()).encode("utf-8")
        except Exception:  # noqa: BLE001 — must answer anyway
            return b"[]"

    def _answer_metrics(self, rnd: int) -> None:
        self._send(FRAME_METRICS,
                   struct.pack("<iI", self.rank, rnd)
                   + self._metrics_snapshot())

    def _answer_trace(self, rnd: int) -> None:
        self._send(FRAME_TRACE,
                   struct.pack("<iI", self.rank, rnd)
                   + self._trace_snapshot())

    # -- session resume (hvd-chaos reconnect protocol) ---------------------
    def _drop_cache_replica(self) -> None:
        """The controller's cache-less resume verdict: drop the local
        replica — this rank sends full requests from here on (a
        supported steady state: the controller marks its cycles
        non-compact), instead of executing desynced replays."""
        self.cache = None
        try:
            from ..core import state as _state

            st = _state.global_state()
            if st.transport is self:
                st.response_cache = None
        except Exception:  # noqa: BLE001 — best-effort state sync
            pass

    def _reconnect(self) -> Optional[str]:
        """Re-establish the controller session with exponential backoff
        + jitter (shared BackoffPolicy) within
        ``HVD_TPU_RECONNECT_DEADLINE``.  Returns None on success (the
        receive loop continues on the fresh socket) or the failure
        diagnostic — the bounded, named end of the no-hang contract."""
        deadline = time.monotonic() + _reconnect_deadline_seconds()
        policy = BackoffPolicy(base=0.05, cap=2.0)
        attempt = 0
        last: Optional[str] = None
        while not (self._closing or self.shutdown_received.is_set()):
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            attempt += 1
            print(f"[hvd-reconnect] rank {self.rank}: attempt {attempt} "
                  f"to {self._host}:{self._port} ({remaining:.1f}s "
                  f"before deadline"
                  f"{'; last error: ' + last if last else ''})",
                  file=sys.stderr)
            _flight.record("reconnect_attempt", self.rank, attempt)
            try:
                terminal = self._try_resume(min(5.0, max(0.2, remaining)))
                if terminal is None:
                    return None
                # A terminal verdict (controller rejection, outgoing
                # ring overflow): retrying cannot succeed.
                return terminal
            except OSError as e:
                last = f"{type(e).__name__}: {e}"
                _M_RECONNECT_FAILURES.inc()
            delay = min(policy.delay(attempt - 1),
                        max(0.0, deadline - time.monotonic()))
            time.sleep(delay)
        return (f"no reconnect within "
                f"{_reconnect_deadline_seconds():.1f}s "
                f"({attempt} attempts; last error: {last})")

    def _try_resume(self, timeout: float) -> Optional[str]:
        """One reconnect attempt: fresh socket, FRAME_RECONNECT with
        our received-frame count + cache epoch, FRAME_RESUME verdict,
        then replay our unacknowledged outgoing suffix.  Returns None
        on resume, a TERMINAL failure reason (controller rejection,
        outgoing-ring overflow — conditions no retry can cure) as a
        string; raises OSError on a retryable failure."""
        sock = socket.create_connection((self._host, self._port),
                                        timeout=timeout)
        try:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            sock.settimeout(10.0)
            cache = self.cache
            epoch = cache.epoch if cache is not None else -1
            _send_frame(sock, FRAME_RECONNECT, struct.pack(
                "<iIiB", self.rank, self._rx_count, epoch,
                1 if cache is not None else 0))
            ftype, payload = _recv_frame(sock, peer="controller",
                                         idle_ok=False)
            if ftype != FRAME_RESUME:
                raise OSError(
                    f"expected RESUME, got {frame_name(ftype)}")
            ctrl_rx, verdict, rlen = struct.unpack_from("<IBH", payload)
            reason = payload[7:7 + rlen].decode("utf-8")
            if verdict == 0:
                print(f"[hvd-reconnect] rank {self.rank}: controller "
                      f"rejected resume: {reason}", file=sys.stderr)
                sock.close()
                return f"controller rejected the session resume: {reason}"
            if verdict == 2:
                print(f"[hvd-reconnect] rank {self.rank}: resuming "
                      f"cache-less: {reason}", file=sys.stderr)
                self._drop_cache_replica()
            with self._send_lock:
                suffix = self._ring.since(ctrl_rx)
                if suffix is None:
                    # Permanent: ctrl_rx is fixed and the ring only
                    # sheds more frames — retrying burns the deadline
                    # for nothing.  Fail terminally, like the
                    # controller-side mirror of this condition.
                    sock.close()
                    return (f"outgoing replay ring overflow "
                            f"(controller received {ctrl_rx} of "
                            f"{self._ring.count} frames; "
                            f"HVD_TPU_RECONNECT_RING too small)")
                for ftype2, payload2 in suffix:
                    _send_frame(sock, ftype2, payload2)
                    _M_REPLAYED.inc()
                sock.settimeout(_frame_timeout())
                old, self._sock = self._sock, sock
                self._broken = False
            _wake_close(old)
            _M_RECONNECTS.inc()
            _flight.record("reconnected", self.rank, ctrl_rx,
                           len(suffix), verdict)
            _telemetry.transport_fault_event(
                "reconnect", f"rank {self.rank} resumed: replayed "
                f"{len(suffix)} frames, verdict {verdict}")
            print(f"[hvd-reconnect] rank {self.rank}: session resumed "
                  f"(replayed {len(suffix)} frames"
                  f"{', cache dropped' if verdict == 2 else ''})",
                  file=sys.stderr)
            return None
        except OSError:
            try:
                sock.close()
            except OSError:
                pass
            raise

    def submit(self, req: Request) -> bool:
        """Buffer one request for the next coalesced control frame;
        returns True when it was served from the response cache (a hit
        bit ships instead of the full request).  The buffer flushes on
        every local drain tick and before any other outgoing frame, so
        a sync collective's request leaves within its first synchronize
        poll — coalescing batches a tick's traffic, it does not delay
        the conversation."""
        hit = False
        item: tuple
        cache = self.cache
        if cache is not None and req.request_type != wire.RequestType.JOIN:
            pos = cache.worker_lookup(req)
            if pos is not None:
                epoch, idx = pos
                item = ("bit", epoch, idx)
                hit = True
        if not hit:
            item = ("req", req.pack())
        with self._buf_lock:
            self._pending.append(item)
        return hit

    def flush_requests(self) -> None:
        """Ship the buffered tick's requests + cache-hit bits as one
        FRAME_REQUEST_BATCH (one frame per distinct cache epoch — more
        than one only when a flush marker raced this tick's hits)."""
        with self._buf_lock:
            items, self._pending = self._pending, []
        if not items:
            return
        by_epoch: Dict[int, List[int]] = {}
        reqs: List[bytes] = []
        for item in items:
            if item[0] == "bit":
                by_epoch.setdefault(item[1], []).append(item[2])
            else:
                reqs.append(item[1])
        _M_BATCH_REQS.inc(len(reqs))
        _M_BATCH_BITS.inc(len(items) - len(reqs))
        _M_BATCH_WIDTH.observe(len(items))
        _flight.record("frame_tx_batch", len(items) - len(reqs),
                       len(reqs))
        epochs = sorted(by_epoch) or [0]
        for i, epoch in enumerate(epochs):
            idxs = by_epoch.get(epoch, [])
            bitvec = b""
            if idxs:
                arr = bytearray(max(idxs) // 8 + 1)
                for b in idxs:
                    arr[b // 8] |= 1 << (b % 8)
                bitvec = bytes(arr)
            # The full requests ride the last epoch's frame; the
            # hvd-trace trailer (this rank's step/cycle context) rides
            # every one — the controller's arrival stamp per cycle.
            tail = b"".join(reqs) if i == len(epochs) - 1 else b""
            nreq = len(reqs) if i == len(epochs) - 1 else 0
            self._send(
                FRAME_REQUEST_BATCH,
                struct.pack("<iII", self.rank, epoch, len(bitvec))
                + bitvec + struct.pack("<H", nreq) + tail
                + _trace.pack_ctx())

    def request_shutdown(self) -> None:
        self.flush_requests()  # preserve request-before-shutdown order
        self._send(FRAME_SHUTDOWN)

    def exchange_signature(self, payload: bytes,
                           timeout: float) -> Optional[str]:
        """Ship this rank's program signature to the controller and
        block for THIS round's verdict: ``None`` = every rank agreed,
        else the divergence diagnostic (analysis/program.py).  Rounds
        advance once per call in lockstep with the controller; a stale
        verdict queued by a timed-out earlier round is discarded."""
        self._sig_round += 1
        rnd = self._sig_round
        self.flush_requests()  # keep buffered requests ahead in-stream
        self._send(FRAME_SIGNATURE,
                   struct.pack("<iI", self.rank, rnd) + payload)
        deadline = time.monotonic() + timeout
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise TimeoutError(
                    f"verify_program: rank {self.rank} got no verdict "
                    f"from the controller within {timeout:.0f}s (did "
                    f"every rank call verify_program?)")
            try:
                got_rnd, verdict = self._sig_results.get(
                    timeout=remaining)
            except queue.Empty:
                continue
            if got_rnd == rnd:
                return verdict
            # got_rnd < rnd: stale verdict from an abandoned round.

    def withdraw(self, name: str, process_set_id: int = 0) -> None:
        """Tell the controller this rank gave up waiting on ``name`` (its
        synchronize timed out); the coordinator of ``process_set_id``
        fails the op group-wide."""
        nb = name.encode("utf-8")
        self.flush_requests()  # keep buffered requests ahead in-stream
        self._send(FRAME_WITHDRAW,
                   struct.pack("<i", self.rank)
                   + struct.pack("<H", len(nb)) + nb
                   + struct.pack("<H", process_set_id))

    def poll_responses(self) -> Optional[List[Response]]:
        """Next broadcast response list, or None if nothing arrived.
        The batch's hvd-trace context (when its frame carried one) is
        left on :attr:`last_trace_ctx` for the drain loop to adopt
        before executing — context and batch stay paired even when
        several broadcasts queued up."""
        try:
            resps, ctx = self._responses.get_nowait()
        except queue.Empty:
            return None
        self.last_trace_ctx = ctx
        return resps

    def close(self) -> None:
        self._closing = True
        atexit.unregister(self._atexit_handshake)
        _wake_close(self._sock)
