"""Cross-process control-plane transport for eager collectives.

Reference architecture (horovod/common/operations.cc:1226-1374): rank 0 is
the coordinator; every worker ships its ``MPIRequest`` messages to it
(MPI_Gather of lengths + MPI_Gatherv of payloads) and receives the fused
``MPIResponse`` list back (MPI_Bcast), after which all ranks execute the
responses in the identical broadcast order.  This module keeps that exact
message flow over one TCP connection per worker, speaking the same binary
wire format the in-process coordinator already uses (ops/wire.py — which
existed precisely to move Request/Response between processes).

The connection doubles as the node-topology rendezvous: each worker's
HELLO carries its hostname, and the controller answers with
(local_rank, local_size, cross_rank, cross_size) — the reference derives
the same numbers from ``MPI_Comm_split_type(SHARED)``
(operations.cc:1184-1196).

Frame layout: ``<u32 length><u8 type><payload>`` (little-endian).
"""

from __future__ import annotations

import atexit
import json
import os
import queue
import socket
import struct
import sys
import threading
import time
from dataclasses import dataclass
from typing import Dict, List, Optional

from . import wire
from .. import telemetry as _telemetry
from ..analysis import lockorder as _lockorder
from ..telemetry import flight as _flight
from .wire import DEAD_PEER_MARKER, Request, Response, ResponseType

FRAME_HELLO = 0       # worker→controller: <i rank><H len><hostname>
                      #   <H len><env fingerprint> — the SPMD env-knob
                      #   uniformity check (ops/compression.py)
FRAME_REQUEST = 1     # worker→controller: packed Request
FRAME_RESPONSES = 2   # controller→worker: packed response list
FRAME_TOPO = 3        # controller→worker: <iiiii> local_rank local_size
                      #   cross_rank cross_size cache_enabled — the last
                      #   int advertises whether rank 0 runs the response
                      #   cache, so a worker never populates a replica
                      #   the controller cannot resolve bits against
FRAME_SHUTDOWN = 4    # either direction: cooperative shutdown
FRAME_WITHDRAW = 5    # worker→controller: <i rank><H len><name><H psid> —
                      # the rank's synchronize timed out on <name>; the
                      # coordinator (of process set psid; 0 = global)
                      # fails the op for the whole group
FRAME_SIGNATURE = 6   # worker→controller: <i rank><I round> + packed
                      # program signature (analysis/program.py
                      # verify_program); the round counter pairs
                      # payloads with their verify call so a stale
                      # signature left by a timed-out round can never
                      # complete a later one
FRAME_SIGRESULT = 7   # controller→worker: <I round><B ok> + utf-8
                      # diagnostic
FRAME_REQUEST_BATCH = 8   # worker→controller, one per drain tick:
                          # <i rank><I epoch><I nbitbytes><bit-vector>
                          # <H nreq><packed Requests...> — the bit-vector
                          # marks response-cache hits by entry index
                          # (ops/cache.py); full requests ride the same
                          # frame, so the steady state costs ONE frame
                          # per tick instead of one per tensor
FRAME_RESPONSE_BATCH = 9  # controller→worker: <I epoch><H ngroups>
                          # (<H n><I idx>*)* — a pure cache-replay cycle
                          # as fused entry-index groups; each worker
                          # reconstitutes the identical fused response
                          # list from its cache replica instead of
                          # re-parsing full Response payloads
FRAME_METRICS = 10        # hvd-telemetry pull (telemetry/__init__.py):
                          # controller→worker <I round> requests a
                          # snapshot; worker→controller <i rank><I round>
                          # + utf-8 JSON answers it.  Round-keyed like
                          # FRAME_SIGNATURE so a straggler snapshot from
                          # a timed-out pull never completes a later one

_HDR = struct.Struct("<IB")

# Control-plane wire telemetry: frames flow at the 5 ms drain cadence
# (coalesced — that is the PR 2 point), so per-frame accounting is far
# off the per-request hot path.
_M_TX = _telemetry.counter("transport.frames_sent")
_M_TX_BYTES = _telemetry.counter("transport.bytes_sent")
_M_RX = _telemetry.counter("transport.frames_received")
_M_RX_BYTES = _telemetry.counter("transport.bytes_received")
_M_FRAME_BYTES = _telemetry.histogram(
    "transport.frame_bytes", "bytes", "payload size per control frame")
_M_BATCH_BITS = _telemetry.counter(
    "transport.batched_cache_bits", "cache-hit bits coalesced into "
    "FRAME_REQUEST_BATCH frames")
_M_BATCH_REQS = _telemetry.counter(
    "transport.batched_requests", "full requests coalesced into "
    "FRAME_REQUEST_BATCH frames")
_M_BATCH_WIDTH = _telemetry.histogram(
    "transport.batch_width", "count",
    "items (bits + requests) per coalesced control frame")


def _send_frame(sock: socket.socket, ftype: int, payload: bytes = b"") -> None:
    sock.sendall(_HDR.pack(len(payload), ftype) + payload)
    _M_TX.inc()
    _M_TX_BYTES.inc(_HDR.size + len(payload))
    _M_FRAME_BYTES.observe(len(payload))


def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf += chunk
    return buf


def _recv_frame(sock: socket.socket):
    hdr = _recv_exact(sock, _HDR.size)
    if hdr is None:
        return None, None
    length, ftype = _HDR.unpack(hdr)
    payload = _recv_exact(sock, length) if length else b""
    if length and payload is None:
        return None, None
    _M_RX.inc()
    _M_RX_BYTES.inc(_HDR.size + length)
    return ftype, payload


def _check_env_fingerprint(rank: int, payload: bytes, offset: int) -> None:
    """Cross-rank uniformity check of the SPMD-program-selecting env
    knobs (compression/quantization/hierarchy/overlap — see
    ops/compression.env_fingerprint): the worker's HELLO carries its
    fingerprint; a divergence from the controller's means the ranks
    would compile DIFFERENT collective programs — silent garbage or a
    hang — so warn AT INIT naming the rank and every divergent knob.
    ``HVD_TPU_OVERLAP`` rides the same fingerprint: a rank running the
    bucketed-backward schedule against monolithic peers would submit a
    per-bucket collective program the others never produce."""
    from . import compression as _compression

    if len(payload) < offset + 2:
        return  # pre-fingerprint HELLO (tests poking raw frames)
    (flen,) = struct.unpack_from("<H", payload, offset)
    theirs = payload[offset + 2:offset + 2 + flen].decode("utf-8")
    mine = _compression.env_fingerprint()
    if theirs == mine:
        return
    their_map = dict(kv.split("=", 1) for kv in theirs.split(";") if kv)
    my_map = dict(kv.split("=", 1) for kv in mine.split(";") if kv)
    diffs = [f"{k}: rank0={my_map.get(k, '?')} rank{rank}="
             f"{their_map.get(k, '?')}"
             for k in sorted(set(my_map) | set(their_map))
             if my_map.get(k) != their_map.get(k)]
    print(f"WARNING: rank {rank} disagrees with rank 0 on env knobs "
          f"that change the compiled SPMD program — collectives WILL "
          f"diverge (docs/performance.md \"Env-knob uniformity\"): "
          f"{'; '.join(diffs)}", file=sys.stderr)


@dataclass(frozen=True)
class Topology:
    local_rank: int
    local_size: int
    cross_rank: int
    cross_size: int


def _assign_topology(hosts: Dict[int, str]) -> Dict[int, Topology]:
    """rank→hostname ⇒ rank→(local/cross) placement, reference semantics:
    local = ranks sharing a host (SHARED split), cross = one rank per host
    ordered by lowest global rank (operations.cc:1184-1196)."""
    by_host: Dict[str, List[int]] = {}
    for rank in sorted(hosts):
        by_host.setdefault(hosts[rank], []).append(rank)
    host_order = sorted(by_host, key=lambda h: by_host[h][0])
    out: Dict[int, Topology] = {}
    for ci, host in enumerate(host_order):
        ranks = by_host[host]
        for li, rank in enumerate(ranks):
            out[rank] = Topology(local_rank=li, local_size=len(ranks),
                                 cross_rank=ci, cross_size=len(host_order))
    return out


class ControllerTransport:
    """Rank 0: accepts one connection per worker, feeds their Requests into
    the in-process coordinator, broadcasts Response lists to everyone."""

    def __init__(self, coordinator, num_processes: int, port: int,
                 hostname: Optional[str] = None):
        self.coordinator = coordinator
        # Shared response-cache replica (ops/cache.py), attached by
        # core.state.init after construction; None = caching disabled.
        self.cache = None
        self.num_processes = num_processes
        self.shutdown_requested = threading.Event()
        # Ranks whose connection dropped without a SHUTDOWN frame — i.e.
        # the process died (SURVEY §5 failure detection; the reference can
        # only hang or MPI-abort here).
        self.lost_ranks: set = set()
        self._closing = False
        self._conns: Dict[int, socket.socket] = {}
        # Requests whose process set was not yet registered on arrival
        # (registration race): retried by flush_unrouted.
        self._unrouted: List = []
        self._lock = _lockorder.make_lock("ControllerTransport._lock")
        self._send_lock = _lockorder.make_lock(
            "ControllerTransport._send_lock")
        # verify_program rendezvous: round → rank → signature payload,
        # collected by the receive threads, consumed by rank 0's
        # verify_program (analysis/program.py).  Keyed by round so a
        # straggler from a timed-out round is never mis-paired.
        self._sig_cond = threading.Condition(self._lock)
        # guarded_by: _sig_cond
        self._signatures: Dict[int, Dict[int, bytes]] = {}
        self._sig_round = 0  # guarded_by: _sig_cond
        # hvd-telemetry pull rendezvous: round → rank → decoded
        # snapshot, same round-keying discipline as the signatures.
        self._met_cond = threading.Condition(self._lock)
        # guarded_by: _met_cond
        self._met_payloads: Dict[int, Dict[int, dict]] = {}
        self._met_round = 0  # guarded_by: _met_cond
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind(("0.0.0.0", port))
        self._srv.listen(num_processes)
        self._threads: List[threading.Thread] = []

        hosts = {0: hostname or socket.gethostname()}
        socks: Dict[int, socket.socket] = {}
        # Bound the wait for stragglers so a worker that died between the
        # jax.distributed rendezvous and its HELLO produces an error naming
        # the missing ranks instead of a silent hang.
        accept_timeout = float(
            os.environ.get("HVD_TPU_CONNECT_TIMEOUT", "120"))
        self._srv.settimeout(accept_timeout)
        for _ in range(num_processes - 1):
            try:
                conn, _addr = self._srv.accept()
            except socket.timeout:
                missing = sorted(set(range(num_processes)) - set(hosts))
                raise TimeoutError(
                    f"controller: ranks {missing} did not connect within "
                    f"{accept_timeout}s; did those processes die during "
                    f"startup?") from None
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            ftype, payload = _recv_frame(conn)
            if ftype != FRAME_HELLO:
                raise RuntimeError(
                    f"controller expected HELLO, got frame type {ftype}")
            (rank,) = struct.unpack_from("<i", payload)
            (hlen,) = struct.unpack_from("<H", payload, 4)
            hosts[rank] = payload[6:6 + hlen].decode("utf-8")
            _check_env_fingerprint(rank, payload, 6 + hlen)
            socks[rank] = conn
        from . import cache as _cache_mod

        self.topology = _assign_topology(hosts)
        for rank, conn in socks.items():
            t = self.topology[rank]
            _send_frame(conn, FRAME_TOPO, struct.pack(
                "<iiiii", t.local_rank, t.local_size,
                t.cross_rank, t.cross_size,
                1 if _cache_mod.cache_enabled() else 0))
        with self._lock:
            self._conns = socks
        for rank, conn in socks.items():
            th = threading.Thread(target=self._serve, args=(rank, conn),
                                  name=f"hvd-controller-rx-{rank}",
                                  daemon=True)
            th.start()
            self._threads.append(th)
        # Mirror of the worker exit handshake: a controller whose
        # interpreter exits without hvd.shutdown() still broadcasts a clean
        # SHUTDOWN, so workers take the cooperative path (and keep jax's
        # exit barrier, which a cleanly-exiting controller does reach).
        atexit.register(self._atexit_handshake)

    def _atexit_handshake(self) -> None:
        if self._closing:
            return
        try:
            self.broadcast_responses(
                [Response(ResponseType.SHUTDOWN)])
        except OSError:
            pass

    def _serve(self, rank: int, conn: socket.socket) -> None:
        # An unhandled exception on a receive thread silently kills the
        # control plane for that worker; dump the flight ring naming
        # the thread before the (daemon) thread dies.
        try:
            self._serve_inner(rank, conn)
        except Exception:
            import traceback

            _telemetry.exception_event(
                "controller-rx", traceback.format_exc())
            raise

    def _serve_inner(self, rank: int, conn: socket.socket) -> None:
        while True:
            try:
                ftype, payload = _recv_frame(conn)
            except OSError:
                ftype = None  # worker died mid-frame / reset the conn
            if ftype is None:
                # EOF without a SHUTDOWN frame = the worker terminated
                # unexpectedly; the drain loop will poison pending ops.
                if not (self.shutdown_requested.is_set() or self._closing):
                    _flight.record("peer_eof", rank)
                    with self._lock:
                        self.lost_ranks.add(rank)
                return
            if ftype == FRAME_REQUEST:
                req, _ = Request.unpack(payload)
                if not self._try_submit(req):
                    # Registration race: the worker's set request can
                    # arrive before the controller's own add_process_set
                    # finishes.  Never block THIS receive thread (later
                    # frames — withdraw, shutdown — must not queue
                    # behind an orphan); the drain loop retries via
                    # flush_unrouted with a bounded lifetime.
                    with self._lock:
                        self._unrouted.append(
                            (time.monotonic() + 5.0, req))
            elif ftype == FRAME_REQUEST_BATCH:
                self._handle_request_batch(payload)
            elif ftype == FRAME_SHUTDOWN:
                self.shutdown_requested.set()
            elif ftype == FRAME_SIGNATURE:
                srank, srnd = struct.unpack_from("<iI", payload)
                with self._sig_cond:
                    self._signatures.setdefault(srnd, {})[srank] = \
                        payload[8:]
                    self._sig_cond.notify_all()
            elif ftype == FRAME_METRICS:
                mrank, mrnd = struct.unpack_from("<iI", payload)
                try:
                    snap = json.loads(payload[8:].decode("utf-8"))
                except (ValueError, UnicodeDecodeError):
                    snap = {}
                with self._met_cond:
                    # Only rounds with a live waiter accept replies: a
                    # straggler answer to an abandoned pull must not
                    # resurrect its round dict (it would leak forever).
                    if mrnd in self._met_payloads:
                        self._met_payloads[mrnd][mrank] = snap
                        self._met_cond.notify_all()
            elif ftype == FRAME_WITHDRAW:
                (wrank,) = struct.unpack_from("<i", payload)
                (nlen,) = struct.unpack_from("<H", payload, 4)
                name = payload[6:6 + nlen].decode("utf-8")
                psid = 0
                if len(payload) >= 8 + nlen:
                    (psid,) = struct.unpack_from("<H", payload, 6 + nlen)
                # The next drain tick broadcasts the resulting ERROR
                # response to every rank (including the withdrawer).
                coord = self._route_coord(psid)
                if coord is not None:
                    coord.withdraw(name, wrank)

    def _handle_request_batch(self, payload: bytes) -> None:
        """One worker drain tick's coalesced control frame: a cache-hit
        bit-vector (entry indices into the shared response cache) plus
        any full requests.  A bit whose epoch predates the live cache
        generation is DOWNGRADED into a real submit of the retired
        entry's stored request — a flush can delay a submission but
        never lose it."""
        srank, epoch, nbits = struct.unpack_from("<iII", payload)
        off = 12
        bitvec = payload[off:off + nbits]
        off += nbits
        (nreq,) = struct.unpack_from("<H", payload, off)
        off += 2
        _flight.record("frame_rx_batch", srank, epoch, nreq)
        cache = self.cache
        for byte_i, b in enumerate(bitvec):
            while b:
                low = b & -b
                idx = byte_i * 8 + low.bit_length() - 1
                b ^= low
                if cache is None:
                    print(f"WARNING: rank {srank} sent a response-cache "
                          f"bit but the controller cache is disabled "
                          f"(HVD_TPU_RESPONSE_CACHE mismatch across "
                          f"ranks?)", file=sys.stderr)
                    continue
                down = cache.hit_from_wire(idx, srank, epoch)
                if down is not None and not self._try_submit(down):
                    with self._lock:
                        self._unrouted.append(
                            (time.monotonic() + 5.0, down))
        for _ in range(nreq):
            req, off = Request.unpack(payload, off)
            if not self._try_submit(req):
                with self._lock:
                    self._unrouted.append((time.monotonic() + 5.0, req))

    def _route_coord(self, psid: int):
        """Coordinator for a process-set id (0 = global); None when the
        set is not (yet) registered on this controller."""
        if psid == 0:
            return self.coordinator
        from ..core import state as _st

        # Locked read: this runs on a receive thread while user threads
        # register/remove sets (guarded-by lint finding).
        ps = _st.get_process_set(psid)
        return None if ps is None else ps.coordinator

    def _try_submit(self, req: Request) -> bool:
        coord = self._route_coord(req.process_set_id)
        if coord is None:
            return False
        try:
            coord.submit(req)
        except ValueError:
            # Duplicate-name submissions are a caller bug on the
            # worker; it learns via its own synchronize timeout.
            pass
        return True

    def flush_unrouted(self) -> None:
        """Retry buffered requests whose process set was unknown when
        they arrived (called from the drain loop each tick).  Requests
        past their lifetime are dropped — the submitter's stall/withdraw
        path reports the op."""
        with self._lock:
            if not self._unrouted:
                return
            items, self._unrouted = self._unrouted, []
        now = time.monotonic()
        keep = [(dl, req) for dl, req in items
                if not self._try_submit(req) and now < dl]
        if keep:
            with self._lock:
                self._unrouted = keep + self._unrouted

    # -- verify_program rendezvous (analysis/program.py) -------------------
    def collect_signatures(self, own: bytes, timeout: float) -> Dict[int,
                                                                     bytes]:
        """Wait until every rank's program signature for THIS round
        arrived (rank 0's is ``own``), then return the payloads.  Rounds
        advance once per call on every rank in lockstep, so a straggler
        payload from a timed-out round sits under its own round key and
        can never complete a later round.  A rank that died mid-round
        surfaces as a TimeoutError naming it."""
        deadline = time.monotonic() + timeout
        with self._sig_cond:
            self._sig_round += 1
            rnd = self._sig_round
            this_round = self._signatures.setdefault(rnd, {})
            this_round[0] = own
            try:
                while len(this_round) < self.num_processes:
                    remaining = deadline - time.monotonic()
                    missing = sorted(set(range(self.num_processes))
                                     - set(this_round))
                    if remaining <= 0 or (self.lost_ranks
                                          and set(missing) <=
                                          set(self.lost_ranks)):
                        raise TimeoutError(
                            f"verify_program: ranks {missing} did not "
                            f"send their collective-program signature "
                            f"within {timeout:.0f}s (did they call "
                            f"verify_program too?)")
                    self._sig_cond.wait(min(remaining, 0.1))
                return dict(this_round)
            finally:
                # Drop this and any earlier (abandoned) rounds.
                for r in [r for r in self._signatures if r <= rnd]:
                    del self._signatures[r]

    def broadcast_signature_result(self, error: Optional[str]) -> None:
        with self._sig_cond:
            rnd = self._sig_round
        payload = struct.pack("<IB", rnd, 0 if error else 1) + (
            error or "").encode("utf-8")
        with self._send_lock:
            with self._lock:
                conns = list(self._conns.values())
            for conn in conns:
                try:
                    _send_frame(conn, FRAME_SIGRESULT, payload)
                except OSError:
                    pass  # worker already gone; its own timeout reports

    # -- hvd-telemetry pull (telemetry/__init__.py cluster_metrics) --------
    def collect_metrics(self, own: dict,
                        timeout: float = 10.0) -> Dict[int, dict]:
        """Pull every rank's metrics snapshot: broadcast a FRAME_METRICS
        request carrying this round's counter, then wait until every
        live rank answered (rank 0's snapshot is ``own``).  Returns the
        snapshots it got — a rank that died or timed out is simply
        absent (the aggregate's ``ranks`` field records coverage;
        observability must not fail the job)."""
        deadline = time.monotonic() + timeout
        with self._met_cond:
            self._met_round += 1
            rnd = self._met_round
            this_round = self._met_payloads.setdefault(rnd, {})
            this_round[0] = own
        payload = struct.pack("<I", rnd)
        with self._send_lock:
            with self._lock:
                conns = list(self._conns.values())
            for conn in conns:
                try:
                    _send_frame(conn, FRAME_METRICS, payload)
                except OSError:
                    pass  # worker already gone; absent from the result
        with self._met_cond:
            try:
                while len(this_round) < self.num_processes:
                    remaining = deadline - time.monotonic()
                    missing = set(range(self.num_processes)) \
                        - set(this_round)
                    if remaining <= 0 or (self.lost_ranks
                                          and missing <=
                                          set(self.lost_ranks)):
                        break
                    self._met_cond.wait(min(remaining, 0.1))
                return dict(this_round)
            finally:
                # Drop ONLY this round: unlike the signature rendezvous
                # (lockstep rounds, at most one in flight), concurrent
                # cluster_metrics() callers each own a round, and a
                # faster caller must not delete a slower one's dict out
                # from under its wait loop.
                self._met_payloads.pop(rnd, None)

    # -- controller-side API used by the drain loop ------------------------
    def submit(self, req: Request) -> bool:
        """Rank 0's own submit; returns True when the request was served
        from the response cache (the coordinator facade's fast path)."""
        coord = self._route_coord(req.process_set_id)
        if coord is None:
            raise RuntimeError(
                f"process set {req.process_set_id} is not registered on "
                f"the controller")
        try:
            if hasattr(coord, "submit_ex"):
                _, hit = coord.submit_ex(req)
                return hit
            coord.submit(req)
        except ValueError:
            pass  # duplicate-name caller bug; surfaces via timeout
        return False

    def broadcast_responses(self, responses: List[Response]) -> None:
        _flight.record("bcast_responses", len(responses),
                       ",".join(r.response_type.name for r in responses))
        payload = wire.pack_response_list(responses)
        # _send_lock serializes whole frames: the drain thread and a
        # shutdown()-calling user thread must not interleave bytes on one
        # socket.
        with self._send_lock:
            with self._lock:
                conns = list(self._conns.values())
            for conn in conns:
                try:
                    _send_frame(conn, FRAME_RESPONSES, payload)
                except OSError:
                    pass  # worker already gone; its own stall path reports

    def broadcast_replay(self, groups: List[List[int]],
                         epoch: int) -> None:
        """Broadcast a pure cache-replay cycle as fused entry-index
        groups (FRAME_RESPONSE_BATCH) — a handful of bytes per tensor
        instead of full Response payloads; each worker reconstitutes the
        identical fused response list from its cache replica."""
        _flight.record("bcast_replay", epoch, len(groups))
        payload = struct.pack("<IH", epoch, len(groups))
        for g in groups:
            payload += struct.pack("<H", len(g))
            payload += struct.pack(f"<{len(g)}I", *g)
        with self._send_lock:
            with self._lock:
                conns = list(self._conns.values())
            for conn in conns:
                try:
                    _send_frame(conn, FRAME_RESPONSE_BATCH, payload)
                except OSError:
                    pass  # worker already gone; its own stall path reports

    def poll_responses(self):
        return None  # responses come from the coordinator on rank 0

    def close(self) -> None:
        self._closing = True
        atexit.unregister(self._atexit_handshake)
        with self._lock:
            conns = list(self._conns.values())
            self._conns.clear()
        for conn in conns:
            try:
                conn.close()
            except OSError:
                pass
        self._srv.close()


class WorkerTransport:
    """Ranks 1..N-1: one connection to the controller; sends Requests,
    receives Response lists into a queue the local drain loop empties."""

    def __init__(self, host: str, port: int, rank: int,
                 hostname: Optional[str] = None,
                 connect_timeout: float = 60.0):
        self.rank = rank
        # Shared response-cache replica (ops/cache.py), attached by
        # core.state.init after construction; None = caching disabled.
        self.cache = None
        self.shutdown_received = threading.Event()
        self._closing = False
        self._buf_lock = _lockorder.make_lock("WorkerTransport._buf_lock")
        # One drain tick's outgoing control traffic, coalesced into a
        # single FRAME_REQUEST_BATCH by flush_requests: ("bit", epoch,
        # entry_idx) response-cache hits and ("req", packed) fulls.
        self._pending: List[tuple] = []  # guarded_by: _buf_lock
        self._responses: "queue.Queue[List[Response]]" = queue.Queue()
        # verify_program verdicts (FRAME_SIGRESULT) as (round, verdict);
        # the round counter lets exchange_signature discard a stale
        # verdict left queued by a timed-out earlier round.
        self._sig_results: "queue.Queue" = queue.Queue()
        self._sig_round = 0
        deadline = time.monotonic() + connect_timeout
        last_err: Optional[Exception] = None
        while True:
            try:
                self._sock = socket.create_connection((host, port),
                                                      timeout=5.0)
                break
            except OSError as e:
                last_err = e
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f"rank {rank} could not reach the controller at "
                        f"{host}:{port} within {connect_timeout}s: "
                        f"{last_err}") from last_err
                time.sleep(0.1)
        self._sock.settimeout(None)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._send_lock = _lockorder.make_lock("WorkerTransport._send_lock")
        hb = (hostname or socket.gethostname()).encode("utf-8")
        from . import compression as _compression

        fp = _compression.env_fingerprint().encode("utf-8")
        _send_frame(self._sock, FRAME_HELLO,
                    struct.pack("<i", rank) + struct.pack("<H", len(hb))
                    + hb + struct.pack("<H", len(fp)) + fp)
        ftype, payload = _recv_frame(self._sock)
        if ftype != FRAME_TOPO:
            raise RuntimeError(
                f"rank {rank} expected TOPO from controller, got {ftype}")
        lr, ls, cr, cs = struct.unpack_from("<iiii", payload)
        # The controller's response-cache advertisement: a worker whose
        # own env enables the cache must still run WITHOUT a replica
        # when rank 0 cannot resolve its bits (core.state.init reads
        # this before attaching the cache).
        self.controller_cache = bool(struct.unpack_from(
            "<i", payload, 16)[0]) if len(payload) >= 20 else True
        self.topology = Topology(lr, ls, cr, cs)
        self._rx = threading.Thread(target=self._recv_loop,
                                    name=f"hvd-worker-rx-{rank}", daemon=True)
        self._rx.start()
        # Exit handshake (≙ the reference's DONE/shutdown flag on the last
        # MPIRequestList, mpi_message.h:87-103): a worker whose interpreter
        # exits without an explicit hvd.shutdown() still tells the
        # controller it left *cleanly*.  An EOF without this frame is
        # therefore always a crash.  Registered after jax.distributed
        # initialize, so (atexit LIFO) it runs before jax's exit barrier.
        atexit.register(self._atexit_handshake)

    def _atexit_handshake(self) -> None:
        # Sent even when a shutdown was already received (it's idempotent):
        # skipping it would make this worker's EOF look like a crash to a
        # controller whose own exit handshake fired first.
        if self._closing:
            return
        try:
            self.request_shutdown()
        except OSError:
            pass  # controller already gone

    def _recv_loop(self) -> None:
        # Mirror of the controller's receive-thread guard: dump the
        # flight ring before an unhandled exception kills the thread.
        try:
            self._recv_loop_inner()
        except Exception:
            import traceback

            _telemetry.exception_event(
                "worker-rx", traceback.format_exc())
            raise

    def _recv_loop_inner(self) -> None:
        while True:
            try:
                ftype, payload = _recv_frame(self._sock)
            except OSError:
                ftype = None
            if ftype is None:
                # Controller connection lost: if this wasn't a clean
                # shutdown, surface it as a synthetic SHUTDOWN response so
                # pending ops fail with a diagnosis instead of hanging
                # (mirror of the controller's dead-worker detection).
                if not (self.shutdown_received.is_set() or self._closing):
                    from ..core.cluster import disarm_distributed_shutdown

                    # EOF without a SHUTDOWN response (not even the
                    # controller's exit handshake): the controller crashed
                    # and can never reach jax.distributed's exit barrier;
                    # don't block (then abort) on it.
                    disarm_distributed_shutdown()
                    self._responses.put([Response(
                        ResponseType.SHUTDOWN,
                        error_message="Horovod has been shut down: the "
                        f"rank-0 controller {DEAD_PEER_MARKER} while "
                        "collectives were pending.")])
                return
            if ftype == FRAME_RESPONSE_BATCH:
                epoch, ngroups = struct.unpack_from("<IH", payload)
                off = 6
                groups = []
                for _ in range(ngroups):
                    (n,) = struct.unpack_from("<H", payload, off)
                    off += 2
                    groups.append(list(struct.unpack_from(
                        f"<{n}I", payload, off)))
                    off += 4 * n
                try:
                    if self.cache is None:
                        raise RuntimeError(
                            "replay frame without a cache replica "
                            "(HVD_TPU_RESPONSE_CACHE mismatch across "
                            "ranks?)")
                    resps = self.cache.rebuild_groups(groups, epoch)
                except RuntimeError as e:
                    # A replica desync is a protocol bug: fail the job
                    # loudly instead of executing desynced responses.
                    print(f"ERROR: rank {self.rank}: {e}",
                          file=sys.stderr)
                    self._responses.put([Response(
                        ResponseType.SHUTDOWN,
                        error_message="Horovod has been shut down: "
                        f"response-cache replica desync on rank "
                        f"{self.rank}: {e}")])
                    continue
                self._responses.put(resps)
                continue
            if ftype == FRAME_SIGRESULT:
                (rnd,) = struct.unpack_from("<I", payload)
                ok = payload[4:5] == b"\x01"
                self._sig_results.put(
                    (rnd, None if ok else payload[5:].decode("utf-8")))
                continue
            if ftype == FRAME_METRICS:
                # hvd-telemetry pull: answer with this rank's snapshot,
                # echoing the round so a slow reply from an abandoned
                # pull can never complete a later one.  Snapshot +
                # serialization run on this receive thread — collectors
                # only read cheap stats structs, nothing blocks.
                (rnd,) = struct.unpack_from("<I", payload)
                try:
                    body = json.dumps(_telemetry.metrics()).encode("utf-8")
                except Exception:  # noqa: BLE001 — must answer regardless
                    body = b"{}"
                with self._send_lock:
                    try:
                        _send_frame(self._sock, FRAME_METRICS,
                                    struct.pack("<iI", self.rank, rnd)
                                    + body)
                    except OSError:
                        pass  # controller gone; its pull times out
                continue
            if ftype == FRAME_RESPONSES:
                resps = wire.unpack_response_list(payload)
                # Controller-initiated shutdown arrives as a SHUTDOWN-type
                # Response inside the list (the one spelling of the
                # protocol); note it for observability.
                if any(r.response_type == ResponseType.SHUTDOWN
                       for r in resps):
                    self.shutdown_received.set()
                self._responses.put(resps)

    def submit(self, req: Request) -> bool:
        """Buffer one request for the next coalesced control frame;
        returns True when it was served from the response cache (a hit
        bit ships instead of the full request).  The buffer flushes on
        every local drain tick and before any other outgoing frame, so
        a sync collective's request leaves within its first synchronize
        poll — coalescing batches a tick's traffic, it does not delay
        the conversation."""
        hit = False
        item: tuple
        cache = self.cache
        if cache is not None and req.request_type != wire.RequestType.JOIN:
            pos = cache.worker_lookup(req)
            if pos is not None:
                epoch, idx = pos
                item = ("bit", epoch, idx)
                hit = True
        if not hit:
            item = ("req", req.pack())
        with self._buf_lock:
            self._pending.append(item)
        return hit

    def flush_requests(self) -> None:
        """Ship the buffered tick's requests + cache-hit bits as one
        FRAME_REQUEST_BATCH (one frame per distinct cache epoch — more
        than one only when a flush marker raced this tick's hits)."""
        with self._buf_lock:
            items, self._pending = self._pending, []
        if not items:
            return
        by_epoch: Dict[int, List[int]] = {}
        reqs: List[bytes] = []
        for item in items:
            if item[0] == "bit":
                by_epoch.setdefault(item[1], []).append(item[2])
            else:
                reqs.append(item[1])
        _M_BATCH_REQS.inc(len(reqs))
        _M_BATCH_BITS.inc(len(items) - len(reqs))
        _M_BATCH_WIDTH.observe(len(items))
        _flight.record("frame_tx_batch", len(items) - len(reqs),
                       len(reqs))
        epochs = sorted(by_epoch) or [0]
        with self._send_lock:
            for i, epoch in enumerate(epochs):
                idxs = by_epoch.get(epoch, [])
                bitvec = b""
                if idxs:
                    arr = bytearray(max(idxs) // 8 + 1)
                    for b in idxs:
                        arr[b // 8] |= 1 << (b % 8)
                    bitvec = bytes(arr)
                # The full requests ride the last epoch's frame.
                tail = b"".join(reqs) if i == len(epochs) - 1 else b""
                nreq = len(reqs) if i == len(epochs) - 1 else 0
                _send_frame(
                    self._sock, FRAME_REQUEST_BATCH,
                    struct.pack("<iII", self.rank, epoch, len(bitvec))
                    + bitvec + struct.pack("<H", nreq) + tail)

    def request_shutdown(self) -> None:
        self.flush_requests()  # preserve request-before-shutdown order
        with self._send_lock:
            _send_frame(self._sock, FRAME_SHUTDOWN)

    def exchange_signature(self, payload: bytes,
                           timeout: float) -> Optional[str]:
        """Ship this rank's program signature to the controller and
        block for THIS round's verdict: ``None`` = every rank agreed,
        else the divergence diagnostic (analysis/program.py).  Rounds
        advance once per call in lockstep with the controller; a stale
        verdict queued by a timed-out earlier round is discarded."""
        self._sig_round += 1
        rnd = self._sig_round
        self.flush_requests()  # keep buffered requests ahead in-stream
        with self._send_lock:
            _send_frame(self._sock, FRAME_SIGNATURE,
                        struct.pack("<iI", self.rank, rnd) + payload)
        deadline = time.monotonic() + timeout
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise TimeoutError(
                    f"verify_program: rank {self.rank} got no verdict "
                    f"from the controller within {timeout:.0f}s (did "
                    f"every rank call verify_program?)")
            try:
                got_rnd, verdict = self._sig_results.get(
                    timeout=remaining)
            except queue.Empty:
                continue
            if got_rnd == rnd:
                return verdict
            # got_rnd < rnd: stale verdict from an abandoned round.

    def withdraw(self, name: str, process_set_id: int = 0) -> None:
        """Tell the controller this rank gave up waiting on ``name`` (its
        synchronize timed out); the coordinator of ``process_set_id``
        fails the op group-wide."""
        nb = name.encode("utf-8")
        self.flush_requests()  # keep buffered requests ahead in-stream
        with self._send_lock:
            _send_frame(self._sock, FRAME_WITHDRAW,
                        struct.pack("<i", self.rank)
                        + struct.pack("<H", len(nb)) + nb
                        + struct.pack("<H", process_set_id))

    def poll_responses(self) -> Optional[List[Response]]:
        """Next broadcast response list, or None if nothing arrived."""
        try:
            return self._responses.get_nowait()
        except queue.Empty:
            return None

    def close(self) -> None:
        self._closing = True
        atexit.unregister(self._atexit_handshake)
        try:
            self._sock.close()
        except OSError:
            pass
