"""Sparse-gradient collectives — the embedding/word2vec path.

The reference allreduces ``tf.IndexedSlices`` gradients (sparse rows of an
embedding matrix) as an *allgather of values and indices* instead of a
dense allreduce (tensorflow/__init__.py:67-78, exercised by
examples/tensorflow_word2vec.py:156-183): each rank contributes its touched
rows; ranks then apply the union of updates.

TPU-native design: the same gather-of-(values, indices) semantics via the
variable-size allgather (XLA ``all_gather`` after size negotiation), plus a
``scatter-sum`` densifier for applying the result — XLA lowers
``segment_sum`` onto the TPU's native scatter path.  For embeddings small
enough that a dense psum wins on ICI, ``as_dense`` + the dense path remains
available; the choice mirrors the reference's ``device_dense`` /
``device_sparse`` per-call override (tensorflow/__init__.py:49-60).
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp


class IndexedSlices(NamedTuple):
    """Sparse rows of a dense tensor (≙ tf.IndexedSlices as used by the
    reference's sparse allreduce).  ``values[i]`` is the update for row
    ``indices[i]`` of a tensor with shape ``dense_shape``."""

    values: jax.Array    # [nnz, ...row shape]
    indices: jax.Array   # [nnz] int32
    dense_shape: Tuple[int, ...]


def allreduce(slices, average: bool = True, name: Optional[str] = None,
              process_set=None):
    """Allreduce an :class:`IndexedSlices` by gathering values + indices
    from every replica (≙ tensorflow/__init__.py:67-78).

    ``slices`` may be a single IndexedSlices (replicated contribution) or a
    list of per-replica IndexedSlices with differing nnz (the realistic
    case — each replica touched different rows).  Returns one
    IndexedSlices holding the union of all contributions, with values
    divided by the replica count when ``average`` (the reference divides
    the gathered values the same way, tensorflow/__init__.py:75-77).
    With ``process_set`` the gather and the averaging denominator cover
    only the set's members.
    """
    from . import collective as C
    from ..core import state as _state

    name = name or C._auto_name("sparse_allreduce", process_set)
    if isinstance(slices, IndexedSlices):
        values = C.allgather(slices.values, name=f"{name}.values",
                             process_set=process_set)
        indices = C.allgather(slices.indices, name=f"{name}.indices",
                              process_set=process_set)
        dense_shape = slices.dense_shape
    else:
        per = list(slices)
        if not per:
            raise ValueError("empty sparse allreduce")
        values = C.allgather([s.values for s in per], name=f"{name}.values",
                             process_set=process_set)
        indices = C.allgather([s.indices for s in per],
                              name=f"{name}.indices",
                              process_set=process_set)
        dense_shape = per[0].dense_shape
    if average:
        denom = (_state.contributor_count() if process_set is None
                 else process_set.size())
        values = values / denom
    return IndexedSlices(values=values, indices=indices,
                         dense_shape=dense_shape)


def as_dense(slices: IndexedSlices) -> jax.Array:
    """Scatter-sum the slices into the dense tensor (duplicate indices
    accumulate — same semantics the frameworks apply to IndexedSlices)."""
    num_rows = slices.dense_shape[0]
    dense = jax.ops.segment_sum(slices.values, slices.indices,
                                num_segments=num_rows)
    return dense.reshape(slices.dense_shape)


def apply_to(param: jax.Array, slices: IndexedSlices,
             scale: float = 1.0) -> jax.Array:
    """``param += scale * scatter(slices)`` without materializing the dense
    gradient — the embedding-update fast path."""
    return param.at[slices.indices].add(scale * slices.values)


def sparse_grad_from_dense(dense_grad: jax.Array,
                           touched_rows: jax.Array) -> IndexedSlices:
    """Extract the touched rows of a dense embedding gradient as
    IndexedSlices.  JAX computes embedding grads dense; this recovers the
    reference's sparse form for wire-efficient exchange when
    ``len(touched_rows) * row_bytes << dense bytes``.

    Host-side (eager) helper: deduplication uses ``np.unique`` so the
    result has exactly the unique touched rows, no padding — padded
    duplicate indices would double-apply the last row's gradient when the
    slices are scatter-accumulated.
    """
    import numpy as np

    rows = jnp.asarray(np.unique(np.asarray(touched_rows)))
    values = dense_grad[rows]
    return IndexedSlices(values=values, indices=rows,
                         dense_shape=tuple(dense_grad.shape))
