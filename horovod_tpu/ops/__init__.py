"""horovod_tpu.ops"""
