"""Async handle manager for the eager collective API.

Reference: horovod/torch/handle_manager.{h,cc} — an atomic counter plus a
mutex-guarded map handle→Status that backs ``allreduce_async`` / ``poll`` /
``synchronize`` (handle_manager.cc:21-51).

On TPU the asynchrony is owned by XLA's async dispatch: every collective we
launch returns a ``jax.Array`` future immediately.  The handle therefore maps
to the in-flight result array (plus any host-side finalizer), and

* ``poll(handle)``      → ``result.is_ready()``   (non-blocking, like the
  reference's cudaEventQuery-based ready events — torch/ready_event.cc:65-72)
* ``synchronize(handle)`` → block until ready, run the finalizer, release the
  handle (reference: horovod_torch_wait_and_clear, torch/mpi_ops.cc:326-332,
  minus the 1 ms poll loop — XLA gives us a real blocking wait).

When the native runtime library is built, handle bookkeeping lives in C++
(native/handle_manager.cc) exactly like the reference; this module falls back
to a Python dict when the .so is absent.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, Optional, Tuple

import jax

from .. import telemetry as _telemetry
from ..native import lib as _native
from ..analysis import races as _races

# Handle churn counters (pool DEPTH is the handles.live gauge, read
# pull-side from live_count() by the runtime collector).
_M_ALLOCATED = _telemetry.counter(
    "handles.allocated", "async-collective handles created")
_M_RELEASED = _telemetry.counter(
    "handles.released", "handles synchronized and released")


class Handle:
    """One in-flight eager collective."""

    __slots__ = ("id", "result", "finalizer", "name", "cache_hit")

    def __init__(self, id: int, result: Any, finalizer: Optional[Callable], name: str):
        self.id = id
        self.result = result  # jax.Array or pytree of jax.Arrays
        self.finalizer = finalizer  # host-side post-processing (e.g. unpad)
        self.name = name
        # True when negotiation was served from the response cache
        # (ops/cache.py) — set by _enqueue once the request is routed;
        # observability for timeline args and tests.
        self.cache_hit = False


@_races.race_checked
class HandleManager:
    """Allocates integer handles for async collectives.

    The id counter and live-handle set are kept in the native library when
    available (mirroring the reference's C++ HandleManager); the Python map
    keeps the GC-visible references to the in-flight arrays, playing the role
    of the reference's ``_handle_map`` which keeps tensors alive during the
    async operation (torch/mpi_ops.py:27-30).
    """

    def __init__(self) -> None:
        from ..analysis import lockorder as _lockorder

        self._lock = _lockorder.make_lock("HandleManager._lock")
        self._handles: Dict[int, Handle] = {}  # guarded_by: _lock
        self._native = _native.handle_manager_create()

    def allocate(self, result: Any, finalizer: Optional[Callable] = None,
                 name: str = "") -> int:
        hid = _native.handle_manager_allocate(self._native)
        h = Handle(hid, result, finalizer, name)
        _M_ALLOCATED.inc()
        with self._lock:
            self._handles[hid] = h
        return hid

    def _get(self, handle: int) -> Handle:
        with self._lock:
            h = self._handles.get(handle)
        if h is None:
            raise ValueError(
                f"Handle {handle} was not created or has already been cleared."
            )
        return h

    def poll(self, handle: int) -> bool:
        """Non-blocking readiness check."""
        h = self._get(handle)
        if h.result is None:
            return False  # not yet launched (still queued for fusion)
        leaves = jax.tree_util.tree_leaves(h.result)
        ready = all(
            leaf.is_ready() if hasattr(leaf, "is_ready") else True
            for leaf in leaves
        )
        if ready:
            _native.handle_manager_mark_done(self._native, handle)
        return ready

    def synchronize(self, handle: int) -> Any:
        """Block until the collective completes; return its output."""
        h = self._get(handle)
        result = jax.block_until_ready(h.result)
        if h.finalizer is not None:
            result = h.finalizer(result)
        _native.handle_manager_mark_done(self._native, handle)
        with self._lock:
            del self._handles[handle]
        _native.handle_manager_release(self._native, handle)
        _M_RELEASED.inc()
        return result

    def take(self, handle: int) -> Any:
        """Release the handle and return its (possibly still-computing)
        result without blocking on device completion — the pipelined
        variant behind ``collective.take_async`` (XLA async dispatch
        owns the asynchrony; per-device program order protects
        consumers that feed the future straight into another
        program)."""
        h = self._get(handle)
        result = h.result
        if h.finalizer is not None:
            result = h.finalizer(result)
        _native.handle_manager_mark_done(self._native, handle)
        with self._lock:
            del self._handles[handle]
        _native.handle_manager_release(self._native, handle)
        _M_RELEASED.inc()
        return result

    def live_count(self) -> int:
        with self._lock:
            return len(self._handles)
