"""Fused computation-collective kernels: compute inside the reduction.

The megakernel ladder fused the *collective's* phases (pack→reduce→
unpack, PR 3; quantize→exchange→dequantize, PR 6) and the overlap/1F1B
paths hid *whole* reductions under *other* programs' compute — but the
producer computation and its own collective still ran as sequential
phases: the GEMM finishes, THEN its psum/reduce_scatter/all_to_all
dispatches.  This module is the remaining step (arXiv:2305.06942,
ROADMAP open item 4): chunk the producer GEMM along a reduction-free
axis and emit ONE XLA program in which chunk *i*'s partial product
enters its collective leg while chunk *i+1* computes.  The original
Horovod (arXiv:1802.05799) could never express this — its runtime sat
outside the framework's graph; here the transform is compiler-visible,
so XLA's async collective scheduling overlaps the legs without any new
runtime machinery.

**Bitwise contract** (tests/test_fused.py, gated by ``bench.py --mode
fused``): every fused primitive is bitwise-identical to its unfused
reference program.  Three facts make that possible without the PR-6
pow2/ordered-sum discipline:

* chunking runs along a **reduction-free** axis (GEMM rows, the MoE
  capacity axis) — each output element's contraction is computed by
  exactly one chunk, with the same K-axis accumulation order the
  unfused GEMM uses (verified empirically per backend; the dispatch
  gate in the bench re-checks it every run);
* ``psum`` / ``psum_scatter`` / ``all_gather`` are elementwise in the
  chunked axis — splitting rows never reorders any element's
  cross-replica reduction;
* the MoE ``all_to_all`` pair is chunked as a ROUND TRIP: a lone
  tiled all_to_all permutes chunk rows relative to the unfused layout,
  but the inverse all_to_all on the same chunk undoes it, so the
  dispatch→FFN→combine pipeline concatenates back to the exact
  unfused bytes.

Chunks of fewer than :data:`MIN_CHUNK_ROWS` rows are never emitted:
XLA:CPU's single-row GEMM (a gemv) may accumulate in a different order
than the M≥2 GEMM kernel (the PR-7 serving discovery), so a plan that
would degenerate falls back to fewer — ultimately one — chunk.  One
chunk IS the unfused reference program; ``HVD_TPU_FUSE=off`` pins it.

Env contract (validated at ``hvd.init``; both knobs ride the
control-plane HELLO env fingerprint — they select the compiled SPMD
program, so they must be uniform fleet-wide):

  HVD_TPU_FUSE=auto|on|off
      auto (default) = on: the transform is bitwise and costs nothing
      when the chunk plan degenerates, so there is no mesh on which
      auto should decline it.  ``off`` pins the unfused reference
      programs (the fallback-parity leg CI runs).
  HVD_TPU_FUSE_CHUNKS=<n>
      default 4.  Upper bound on chunks per fused group; plans clamp
      so every chunk keeps ≥ MIN_CHUNK_ROWS rows.

Host-side, :class:`FusedProgram` wraps each fused group's executable
with the repo's standard compiled-program services: AOT compile on
first dispatch with ``compiled.memory_analysis()`` harvested into the
memory planner, a manifest record (``variant: "fused"``) so a
relaunched fleet warm-starts the same groups from
``HVD_TPU_COMPILE_CACHE_DIR``, per-launch hvd-mem ledger charges via
the planner's shared byte formula (:func:`..memory.planner.
fused_group_bytes`), OOM-guarded dispatch, and the
``fused.groups_compiled`` / ``fused.launches`` /
``fused.exposed_comm_seconds`` telemetry documented in
docs/metrics.md.

Threading: everything here runs on the caller's (main/user) thread —
module state is one counter-protected lock, and no method is entered
from the runtime's thread fleet, so there are no ``# thread:`` roles
to declare.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Callable, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from .. import telemetry as _telemetry
from ..memory import ledger as _mem
from ..memory import oom as _oom
from ..memory import planner as _mem_planner

FUSE_ENV = "HVD_TPU_FUSE"
CHUNKS_ENV = "HVD_TPU_FUSE_CHUNKS"
_VALID_MODES = ("auto", "on", "off")
DEFAULT_CHUNKS = 4
# The PR-7 gemv trap: a 1-row chunk's dot may accumulate differently
# from the M≥2 GEMM kernel, breaking the bitwise contract.
MIN_CHUNK_ROWS = 2

# hvd-telemetry (docs/metrics.md "Fused computation-collective").
_M_GROUPS = _telemetry.counter(
    "fused.groups_compiled",
    "fused computation-collective executables compiled (one per "
    "FusedProgram, on its first dispatch)")
_M_LAUNCHES = _telemetry.counter(
    "fused.launches",
    "fused-group executable dispatches")
_M_EXPOSED = _telemetry.histogram(
    "fused.exposed_comm_seconds", "seconds",
    "communication seconds NOT hidden under producer compute in one "
    "fused group (max(0, fused_total - compute_only) — the figure "
    "bench.py --mode fused gates strictly below the unfused leg)")


def fuse_mode() -> str:
    """The fusion knob, normalized (1/0 alias on/off)."""
    v = (os.environ.get(FUSE_ENV, "auto").strip().lower() or "auto")
    return {"1": "on", "0": "off"}.get(v, v)


def fuse_chunks() -> int:
    """Requested chunks per fused group (``HVD_TPU_FUSE_CHUNKS``)."""
    v = os.environ.get(CHUNKS_ENV, "").strip()
    if not v:
        return DEFAULT_CHUNKS
    try:
        n = int(v)
    except ValueError:
        raise ValueError(
            f"{CHUNKS_ENV}={v!r}: expected a positive integer "
            f"(chunks per fused computation-collective group)") \
            from None
    if n < 1:
        raise ValueError(
            f"{CHUNKS_ENV}={v!r}: expected a positive integer "
            f"(chunks per fused computation-collective group)")
    return n


def validate_env() -> None:
    """Fail ``hvd.init()`` — not the first fused dispatch — on a
    malformed fusion knob (same contract as the overlap/pipeline
    knobs; cross-rank uniformity is checked by the HELLO env
    fingerprint, ops/transport.py)."""
    v = os.environ.get(FUSE_ENV)
    if v and fuse_mode() not in _VALID_MODES:
        raise ValueError(
            f"{FUSE_ENV}={v!r}: expected one of "
            f"{'|'.join(_VALID_MODES)} (1/0 alias on/off)")
    fuse_chunks()


def enabled(override: Optional[bool] = None) -> bool:
    """Whether fused (chunk-interleaved) program bodies are emitted.
    ``auto`` means on: the transform is bitwise-identical by contract
    and free when the chunk plan degenerates to one chunk."""
    if override is not None:
        return bool(override)
    return fuse_mode() != "off"


def plan_chunks(n_rows: int, chunks: Optional[int] = None
                ) -> Tuple[Tuple[int, int], ...]:
    """Static ``(start, size)`` chunk plan for a reduction-free axis of
    ``n_rows`` rows.

    The requested chunk count (default :func:`fuse_chunks`) is clamped
    so every chunk keeps at least :data:`MIN_CHUNK_ROWS` rows; the
    remainder spreads one row at a time over the leading chunks, so the
    plan is a pure function of ``(n_rows, chunks)`` — part of the
    compiled program's identity, like every other SPMD knob."""
    want = fuse_chunks() if chunks is None else int(chunks)
    if want < 1:
        raise ValueError(f"chunks must be >= 1, got {want}")
    c = max(1, min(want, n_rows // MIN_CHUNK_ROWS))
    base, extra = divmod(n_rows, c)
    plan = []
    start = 0
    for i in range(c):
        size = base + (1 if i < extra else 0)
        plan.append((start, size))
        start += size
    return tuple(plan)


def _slice(x, start: int, size: int, axis: int):
    return jax.lax.dynamic_slice_in_dim(x, start, size, axis=axis)


def chunked_map(fn: Callable, x, *, axis: int = 0,
                chunks: Optional[int] = None,
                fuse: Optional[bool] = None):
    """Apply ``fn`` to static chunks of ``x`` along a reduction-free
    ``axis`` and concatenate — THE fused-group building block.

    ``fn`` is a chunk-shaped compute+collective pipeline (e.g. the MoE
    dispatch→FFN→combine round trip); emitting it per chunk inside one
    traced program lets XLA overlap chunk *i*'s collective with chunk
    *i+1*'s compute.  Disabled (or degenerate) plans call ``fn`` once
    on the whole array — exactly the unfused reference program."""
    if not enabled(fuse):
        return fn(x)
    plan = plan_chunks(int(x.shape[axis]), chunks)
    if len(plan) == 1:
        return fn(x)
    outs = [fn(_slice(x, start, size, axis)) for start, size in plan]
    return jnp.concatenate(outs, axis=axis)


def matmul_psum(x, w, *, axis_name: str, chunks: Optional[int] = None,
                fuse: Optional[bool] = None,
                preferred_element_type=jnp.float32):
    """``psum(x @ w)`` with the GEMM chunked along ``x``'s rows so each
    chunk's partial-product reduction overlaps the next chunk's GEMM
    (the Megatron row-parallel closer, fused).  Bitwise-identical to
    the unfused ``psum(dot(x, w))``: rows are reduction-free and psum
    is elementwise."""
    def leg(xc):
        part = jnp.dot(xc, w, preferred_element_type=preferred_element_type)
        return jax.lax.psum(part, axis_name)
    return chunked_map(leg, x, axis=0, chunks=chunks, fuse=fuse)


def matmul_reduce_scatter(x, w, *, axis_name: str,
                          scatter_axis: int = -1,
                          chunks: Optional[int] = None,
                          fuse: Optional[bool] = None,
                          preferred_element_type=jnp.float32):
    """``psum_scatter(x @ w)`` chunked along ``x``'s rows — the
    sequence-parallel variant of the row-parallel closer: each device
    keeps only its ``scatter_axis`` shard of the summed output."""
    def leg(xc):
        part = jnp.dot(xc, w, preferred_element_type=preferred_element_type)
        ax = scatter_axis if scatter_axis >= 0 else part.ndim + scatter_axis
        return jax.lax.psum_scatter(part, axis_name,
                                    scatter_dimension=ax, tiled=True)
    return chunked_map(leg, x, axis=0, chunks=chunks, fuse=fuse)


def all_gather_matmul(x, w, *, axis_name: str, gather_axis: int = -1,
                      chunks: Optional[int] = None,
                      fuse: Optional[bool] = None,
                      preferred_element_type=jnp.float32):
    """``all_gather(x) @ w`` chunked along ``x``'s rows — the
    sequence-parallel opener: chunk *i+1*'s gather flies while chunk
    *i* multiplies.  ``gather_axis`` is the sharded feature axis of
    ``x`` (the contraction axis of the dot)."""
    def leg(xc):
        ax = gather_axis if gather_axis >= 0 else xc.ndim + gather_axis
        xg = jax.lax.all_gather(xc, axis_name, axis=ax, tiled=True)
        return jnp.dot(xg, w, preferred_element_type=preferred_element_type)
    return chunked_map(leg, x, axis=0, chunks=chunks, fuse=fuse)


# ---------------------------------------------------------------------------
# Host-side fused-group executables
# ---------------------------------------------------------------------------

_state_lock = threading.Lock()
_n_groups = 0  # guarded_by: _state_lock


def _next_group_id() -> int:
    global _n_groups
    with _state_lock:
        _n_groups += 1
        return _n_groups


def fused_manifest_entry(name: str, mesh, shapes: Sequence[Tuple[int, ...]],
                         dtype, chunks: int) -> dict:
    """The persistent-cache manifest record for one fused group
    (``variant: "fused"`` — same file, same dedup/bound/atomic-rename
    contract as the megakernel and serving entries, so one
    ``HVD_TPU_COMPILE_CACHE_DIR`` warms a relaunched fleet's fused
    groups too).  The chunk count is part of the record: it is part of
    the compiled program."""
    from . import megakernel as _mk

    return {
        "variant": "fused",
        "op": name,
        "dtype": str(jnp.dtype(dtype)),
        "shapes": [list(s) for s in shapes],
        "chunks": int(chunks),
        "digest": None,
        "mesh": _mk.mesh_fingerprint(tuple(mesh.devices.flat)),
    }


def fused_entries(directory: Optional[str] = None) -> list:
    """The manifest's fused-group records (warm-start consumer side)."""
    from . import megakernel as _mk

    d = directory or _mk.compile_cache_dir()
    if d is None:
        return []
    return [e for e in _mk.load_manifest(d)
            if e.get("variant") == "fused"]


class FusedProgram:
    """One fused computation-collective group's executable, wrapped in
    the repo's standard compiled-program services (the pipeline
    ``_AotProgram`` pattern): AOT compile on first dispatch —
    ``compiled.memory_analysis()`` harvested into the planner's
    per-mesh table, a ``variant: "fused"`` manifest record for warm
    start — then OOM-guarded dispatches that bump ``fused.launches``
    and charge the hvd-mem ledger with the planner's shared byte
    formula for the group's live set (output + one chunk's partial
    product).  Any compiled-call failure that is not
    RESOURCE_EXHAUSTED falls back to the jit wrapper permanently —
    semantics identical to plain jit."""

    __slots__ = ("name", "chunks", "_fn", "_compiled", "_mesh",
                 "_launch_bytes")

    def __init__(self, name: str, fn, *, mesh, chunks: int,
                 launch_bytes: int = 0) -> None:
        self.name = f"fused/{name}.g{_next_group_id()}"
        self.chunks = int(chunks)
        self._fn = fn
        self._compiled = None
        self._mesh = mesh
        self._launch_bytes = int(launch_bytes)

    def _record(self, args) -> None:
        shapes = [tuple(a.shape) for a in jax.tree_util.tree_leaves(args)]
        dtypes = [a.dtype for a in jax.tree_util.tree_leaves(args)]
        from . import megakernel as _mk

        _mk.record_manifest_entry(fused_manifest_entry(
            self.name, self._mesh, shapes,
            dtypes[0] if dtypes else jnp.float32, self.chunks))

    def __call__(self, *args):
        with _oom.guard(self.name):
            if self._compiled is None:
                try:
                    compiled = self._fn.lower(*args).compile()
                    _mem_planner.record_compiled(self.name, compiled)
                    self._compiled = compiled
                except Exception:  # noqa: BLE001 — AOT lowering is an
                    self._compiled = False  # optimization; jit is the
                    # semantic baseline
                _M_GROUPS.inc()
                self._record(args)
            if _telemetry.enabled():
                _M_LAUNCHES.inc()
            mem_on = _mem.enabled() and self._launch_bytes
            if mem_on:
                _mem.ledger.alloc("fused.launch", self._launch_bytes)
            try:
                if self._compiled:
                    try:
                        return self._compiled(*args)
                    except Exception as e:  # noqa: BLE001 — fall back
                        if _oom.is_resource_exhausted(e):
                            raise
                        self._compiled = False
                return self._fn(*args)
            finally:
                if mem_on:
                    _mem.ledger.free("fused.launch", self._launch_bytes)


def observe_exposed(seconds: float) -> None:
    """Record one fused group's exposed-communication window
    (``fused.exposed_comm_seconds``; bench.py --mode fused is the
    measuring side)."""
    if _telemetry.enabled():
        _M_EXPOSED.observe(max(0.0, float(seconds)))


def measure_exposed_comm(program: Callable, compute_only: Callable,
                         args: tuple, *, cycles: int = 5) -> float:
    """Median exposed-communication seconds of ``program`` over
    ``compute_only`` (the same chunked producer computation with the
    collective legs elided): ``max(0, total - compute)`` per cycle.

    Both legs pay their dispatch and a full fence inside the measured
    window — the idiom the pipeline bubble gate established so a
    loaded box inflates both sides instead of faking an improvement.
    Shared by ``bench.py --mode fused`` and the telemetry tests."""
    def timed(fn):
        lats = []
        fn(*args)  # warm (compile outside the window)
        for _ in range(cycles):
            t0 = time.perf_counter()
            jax.block_until_ready(fn(*args))
            lats.append(time.perf_counter() - t0)
        lats.sort()
        return lats[len(lats) // 2]

    total = timed(program)
    compute = timed(compute_only)
    exposed = max(0.0, total - compute)
    observe_exposed(exposed)
    return exposed
