"""Tree-structured control-plane overlay: the thousand-rank scale-out.

Every remaining O(world) cost in the control plane funnels through rank
0: the flat star (one TCP connection per worker, ops/transport.py — the
original Horovod topology, arXiv:1802.05799) means every drain tick
delivers world-1 FRAME_REQUEST_BATCH frames to one process, and every
``cluster_metrics()`` / ``dump_fleet_trace()`` pull collects world-1
replies point-to-point — the flat-topology scaling wall characterized
in arXiv:1810.11112.  This module turns the star into a **fanout-ary
tree**:

* **Upward aggregation** — interior ranks parse their children's
  coalesced request frames, merge the cache-hit bit-vectors (grouped by
  ``(epoch, entry set)`` across ranks — in the steady state every rank
  hits the same entries, so a whole subtree collapses into ONE group),
  concatenate the full requests, and forward a single
  ``FRAME_SUBTREE_BATCH`` per tick.  ``FRAME_METRICS`` /
  ``FRAME_TRACE`` pull replies aggregate the same way
  (``FRAME_METRICS_TREE`` / ``FRAME_TRACE_TREE``).  Rank 0 receives
  ≤ fanout frames per cycle instead of world-1.
* **Downward relay** — interiors copy every root broadcast to their
  children verbatim, in order, so each rank's downward stream IS the
  root's broadcast stream bit-for-bit.  That invariant is what keeps
  every response-cache replica index-aligned across interior merging,
  and what makes **re-parenting** possible: the root keeps ONE shared
  broadcast ring, and any rank can resume from its global stream index
  regardless of which path used to feed it.
* **Self-healing** — a rank whose parent link dies reconnects straight
  to the root's session-resume listener (the PR-8 machinery): the root
  adopts it as a direct child, replays the missed broadcast suffix
  from the shared ring, and the worker replays its own unacknowledged
  upward suffix (duplicate submits/bits are idempotent by design).  An
  interior that loses a child reports ``FRAME_CHILD_LOST`` after a
  grace window; only the root arbitrates liveness — a re-parented rank
  ignores the stale report, a dead one gets its own grace window and
  then the dead-peer diagnostic.  The tree heals into a flatter shape
  rather than reconstructing; a lost interior degrades its subtree to
  direct root children, never orphans it.

Tree shape
----------
Ranks are ordered slice-major using the same ICI x DCN contract as
``core/topology.replica_hierarchy`` (real multi-host jobs group ranks
by host/slice; ``HVD_TPU_VIRTUAL_SLICES`` declares contiguous virtual
slices for dryruns), then arranged as a heap: ``parent(order[i]) =
order[(i-1) // fanout]``.  Subtrees nest inside slices, so aggregation
traffic rides ICI and only the top of the tree crosses DCN.

Env contract (docs/deploy.md, docs/performance.md):
  HVD_TPU_TREE=auto|on|off       auto (default): tree when world size
                                 reaches HVD_TPU_TREE_THRESHOLD
  HVD_TPU_TREE_FANOUT=<k>        children per interior node (default 8)
  HVD_TPU_TREE_THRESHOLD=<n>     auto-on world size (default 64)
  HVD_TPU_TREE_PORT_BASE=<p>     relay listen ports (base + rank;
                                 default controller port + 1000)
  HVD_TPU_TREE_HOSTS=r=host,...  interior host map (default: the
                                 controller host — single-host fleets)
  HVD_TPU_TREE_PULL_TIMEOUT=<s>  interior partial-aggregation flush
                                 deadline for metrics/trace pulls

Like every knob that changes the control-plane wire conversation, the
tree knobs must be uniform across ranks (they ride the HELLO env
fingerprint — ops/compression.env_fingerprint).
"""

from __future__ import annotations

import os
import socket
import struct
import sys
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from . import transport as T
from . import wire
from .. import chaos as _chaos
from .. import telemetry as _telemetry
from .. import trace as _trace
from ..analysis import lockorder as _lockorder
from ..analysis import threads as _athreads
from ..analysis import races as _races
from ..telemetry import flight as _flight
from .wire import Request, Response, ResponseType

TREE_ENV = "HVD_TPU_TREE"
FANOUT_ENV = "HVD_TPU_TREE_FANOUT"
THRESHOLD_ENV = "HVD_TPU_TREE_THRESHOLD"
PORT_BASE_ENV = "HVD_TPU_TREE_PORT_BASE"
HOSTS_ENV = "HVD_TPU_TREE_HOSTS"
PULL_TIMEOUT_ENV = "HVD_TPU_TREE_PULL_TIMEOUT"


def tree_mode() -> str:
    mode = os.environ.get(TREE_ENV, "auto").lower()
    if mode not in ("auto", "on", "off"):
        raise ValueError(f"{TREE_ENV}={mode!r}: expected auto, on or off")
    return mode


def tree_fanout() -> int:
    v = int(os.environ.get(FANOUT_ENV, "8"))
    if v < 1:
        raise ValueError(f"{FANOUT_ENV}={v}: expected >= 1")
    return v


def tree_threshold() -> int:
    return int(os.environ.get(THRESHOLD_ENV, "64"))


def pull_timeout() -> float:
    return float(os.environ.get(PULL_TIMEOUT_ENV, "5"))


def validate_env() -> None:
    """Fail ``hvd.init()`` — not the first drain tick — on malformed
    tree knobs (the same up-front contract every other control-plane
    knob follows)."""
    tree_mode()
    tree_fanout()
    tree_threshold()
    base = os.environ.get(PORT_BASE_ENV)
    if base:
        int(base)
    hosts = os.environ.get(HOSTS_ENV)
    if hosts:
        _parse_hosts(hosts)


def tree_active(world: int) -> bool:
    """Whether the overlay is armed for this world size."""
    mode = tree_mode()
    if mode == "off" or world < 3:
        return False
    if mode == "on":
        return True
    return world >= tree_threshold()


def _parse_hosts(spec: str) -> Dict[int, str]:
    out: Dict[int, str] = {}
    for kv in spec.split(","):
        kv = kv.strip()
        if not kv:
            continue
        r, _, h = kv.partition("=")
        out[int(r)] = h
    return out


def relay_port(controller_port: int, rank: int) -> int:
    """Deterministic relay listen port for an interior rank — every
    rank derives the same map with no extra rendezvous round."""
    base = int(os.environ.get(PORT_BASE_ENV, "0") or 0)
    if not base:
        base = controller_port + 1000
    return base + rank


def parent_address(controller_host: str, controller_port: int,
                   parent: int) -> Tuple[str, int]:
    """Where a child connects: the controller itself for parent 0,
    otherwise the parent's relay listener (host from HVD_TPU_TREE_HOSTS
    when the fleet spans machines; the controller host by default —
    the single-host multiprocess deployment)."""
    if parent == 0:
        return controller_host, controller_port
    host = _parse_hosts(os.environ.get(HOSTS_ENV, "")).get(
        parent, controller_host)
    return host, relay_port(controller_port, parent)


# ---------------------------------------------------------------------------
# Layout
# ---------------------------------------------------------------------------

def _slice_table(world: int) -> Optional[List[int]]:
    """Slice id per rank, from the same HVD_TPU_VIRTUAL_SLICES contract
    ``core/topology.replica_hierarchy`` applies to the replica axis —
    contiguous equal blocks, or None when the process space is flat."""
    k = int(os.environ.get("HVD_TPU_VIRTUAL_SLICES", "0") or 0)
    if k > 1 and world % k == 0 and world // k >= 1:
        ici = world // k
        return [r // ici for r in range(world)]
    return None


@dataclass(frozen=True)
class TreeLayout:
    """The agreed tree shape: every rank derives the identical layout
    from (world, fanout, slice table) with no communication."""

    world: int
    fanout: int
    order: Tuple[int, ...]          # heap order; order[0] == 0
    pos: Dict[int, int]             # rank -> index in order

    def parent(self, rank: int) -> Optional[int]:
        i = self.pos[rank]
        if i == 0:
            return None
        return self.order[(i - 1) // self.fanout]

    def children(self, rank: int) -> Tuple[int, ...]:
        i = self.pos[rank]
        lo = i * self.fanout + 1
        return tuple(self.order[j]
                     for j in range(lo, min(lo + self.fanout,
                                            len(self.order))))

    def subtree(self, rank: int) -> Tuple[int, ...]:
        """The rank and every descendant (preorder)."""
        out = [rank]
        stack = list(self.children(rank))
        while stack:
            r = stack.pop()
            out.append(r)
            stack.extend(self.children(r))
        return tuple(out)

    def is_interior(self, rank: int) -> bool:
        return rank != 0 and bool(self.children(rank))

    def interior_ranks(self) -> Tuple[int, ...]:
        return tuple(r for r in self.order if self.is_interior(r))

    def depth(self) -> int:
        """Edges on the longest root-to-leaf path."""
        d = 0
        n = len(self.order)
        i = n - 1
        while i > 0:
            i = (i - 1) // self.fanout
            d += 1
        return d


def build_layout(world: int, fanout: Optional[int] = None,
                 slices: Optional[Sequence[int]] = None) -> TreeLayout:
    """Derive the tree shape.  Ranks order slice-major (ICI x DCN:
    subtrees nest inside slices so aggregation rides the fast links),
    rank 0 always the root; then a ``fanout``-ary heap over that
    order."""
    if fanout is None:
        fanout = tree_fanout()
    if slices is None:
        slices = _slice_table(world)
    rest = [r for r in range(world) if r != 0]
    if slices is not None:
        rest.sort(key=lambda r: (slices[r], r))
    order = tuple([0] + rest)
    return TreeLayout(world=world, fanout=fanout, order=order,
                      pos={r: i for i, r in enumerate(order)})


def expected_root_frames(world: int, fanout: Optional[int] = None) -> int:
    """Frames rank 0 receives per steady-state tick under the tree —
    one merged envelope per direct child (vs world-1 flat)."""
    return len(build_layout(world, fanout).children(0))


def depth_bound(world: int, fanout: Optional[int] = None) -> int:
    return max(1, build_layout(world, fanout).depth())


# ---------------------------------------------------------------------------
# Wire helpers (handshake + merged frames)
# ---------------------------------------------------------------------------

def pack_hello_tree(entries: List[Tuple[int, str, str]]) -> bytes:
    """``entries`` = (rank, hostname, env fingerprint) for a whole
    subtree, the subtree's own root FIRST (the controller reads
    ``entries[0]`` as the connecting child)."""
    out = [struct.pack("<H", len(entries))]
    for rank, host, fp in entries:
        hb = host.encode("utf-8")
        fb = fp.encode("utf-8")
        out.append(struct.pack("<iH", rank, len(hb)) + hb
                   + struct.pack("<H", len(fb)) + fb)
    return b"".join(out)


def parse_hello_tree(payload: bytes) -> List[Tuple[int, str, str]]:
    (n,) = struct.unpack_from("<H", payload)
    off = 2
    out = []
    for _ in range(n):
        rank, hlen = struct.unpack_from("<iH", payload, off)
        off += 6
        host = payload[off:off + hlen].decode("utf-8")
        off += hlen
        (flen,) = struct.unpack_from("<H", payload, off)
        off += 2
        fp = payload[off:off + flen].decode("utf-8")
        off += flen
        out.append((rank, host, fp))
    return out


def pack_topo_tree(cache_flag: int,
                   entries: List[Tuple[int, "T.Topology"]]) -> bytes:
    out = [struct.pack("<BH", cache_flag, len(entries))]
    for rank, t in entries:
        out.append(struct.pack("<iiiii", rank, t.local_rank,
                               t.local_size, t.cross_rank, t.cross_size))
    return b"".join(out)


def parse_topo_tree(payload: bytes) -> Tuple[int, Dict[int, "T.Topology"]]:
    cache_flag, n = struct.unpack_from("<BH", payload)
    off = 3
    out: Dict[int, T.Topology] = {}
    for _ in range(n):
        rank, lr, ls, cr, cs = struct.unpack_from("<iiiii", payload, off)
        off += 20
        out[rank] = T.Topology(lr, ls, cr, cs)
    return cache_flag, out


def pack_merged_pull(rnd: int,
                     entries: List[Tuple[int, bytes]]) -> bytes:
    out = [struct.pack("<IH", rnd, len(entries))]
    for rank, blob in entries:
        out.append(struct.pack("<iI", rank, len(blob)) + blob)
    return b"".join(out)


def parse_merged_pull(payload: bytes) -> Tuple[int, List[Tuple[int,
                                                               bytes]]]:
    rnd, n = struct.unpack_from("<IH", payload)
    off = 6
    out = []
    for _ in range(n):
        rank, blen = struct.unpack_from("<iI", payload, off)
        off += 8
        out.append((rank, payload[off:off + blen]))
        off += blen
    return rnd, out


# -- subtree batch (the merged negotiation envelope) -----------------------
#
# Payload: <H nsections> then typed sections:
#   kind 0 bits:    <B><I epoch><H nranks><i*nranks><H nidx><I*nidx>
#                   — every listed rank hit exactly these cache entries
#                   at this epoch (the steady-state group: one section
#                   for the whole subtree)
#   kind 1 reqs:    <B><i rank><H nreq><packed Requests...>
#   kind 2 arrival: <B><i rank><B len><trace ctx bytes>
#   kind 3 counts:  <B><H n> + n x (<i rank><I cum>) — cumulative
#                   upward frames per origin rank whose content has
#                   been folded into envelopes (the re-parent resume
#                   protocol's bookkeeping)

def parse_request_batch(payload: bytes) -> Tuple[int, int, List[int],
                                                 List[bytes], bytes]:
    """Split one flat FRAME_REQUEST_BATCH payload into its parts
    (rank, epoch, hit indices, packed request blobs, trace ctx) —
    the interior's parse side of the merge.  Byte-exact: re-submitting
    the parts reproduces the flat path's processing verbatim."""
    rank, epoch, nbits = struct.unpack_from("<iII", payload)
    off = 12
    bitvec = payload[off:off + nbits]
    off += nbits
    idxs: List[int] = []
    for byte_i, b in enumerate(bitvec):
        while b:
            low = b & -b
            idxs.append(byte_i * 8 + low.bit_length() - 1)
            b ^= low
    (nreq,) = struct.unpack_from("<H", payload, off)
    off += 2
    blobs: List[bytes] = []
    for _ in range(nreq):
        start = off
        _req, off = Request.unpack(payload, off)
        blobs.append(payload[start:off])
    return rank, epoch, idxs, blobs, payload[off:]


def pack_subtree_batch(bits: List[Tuple[int, Tuple[int, ...],
                                        Tuple[int, ...]]],
                       reqs: List[Tuple[int, List[bytes]]],
                       arrivals: List[Tuple[int, bytes]],
                       counts: Dict[int, int]) -> bytes:
    """Assemble one merged envelope.  ``bits`` = (epoch, ranks, idxs)
    groups; ``reqs`` = (rank, packed blobs); ``arrivals`` = (rank, raw
    trace ctx); ``counts`` = cumulative per-rank upward frame counts."""
    sections: List[bytes] = []
    for epoch, ranks, idxs in bits:
        sections.append(
            struct.pack("<BIH", 0, epoch, len(ranks))
            + struct.pack(f"<{len(ranks)}i", *ranks)
            + struct.pack("<H", len(idxs))
            + (struct.pack(f"<{len(idxs)}I", *idxs) if idxs else b""))
    for rank, blobs in reqs:
        sections.append(struct.pack("<BiH", 1, rank, len(blobs))
                        + b"".join(blobs))
    for rank, ctx in arrivals:
        sections.append(struct.pack("<BiB", 2, rank, len(ctx)) + ctx)
    if counts:
        items = sorted(counts.items())
        sections.append(struct.pack("<BH", 3, len(items))
                        + b"".join(struct.pack("<iI", r, c)
                                   for r, c in items))
    return struct.pack("<H", len(sections)) + b"".join(sections)


def iter_subtree_sections(payload: bytes):
    """Yield the envelope's sections: ("bits", epoch, ranks, idxs),
    ("reqs", rank, [Request]), ("arrival", rank, ctx tuple | None),
    ("counts", {rank: cum})."""
    (n,) = struct.unpack_from("<H", payload)
    off = 2
    for _ in range(n):
        (kind,) = struct.unpack_from("<B", payload, off)
        off += 1
        if kind == 0:
            epoch, nranks = struct.unpack_from("<IH", payload, off)
            off += 6
            ranks = struct.unpack_from(f"<{nranks}i", payload, off)
            off += 4 * nranks
            (nidx,) = struct.unpack_from("<H", payload, off)
            off += 2
            idxs = struct.unpack_from(f"<{nidx}I", payload, off) \
                if nidx else ()
            off += 4 * nidx
            yield ("bits", epoch, list(ranks), list(idxs))
        elif kind == 1:
            rank, nreq = struct.unpack_from("<iH", payload, off)
            off += 6
            reqs = []
            for _r in range(nreq):
                req, off = Request.unpack(payload, off)
                reqs.append(req)
            yield ("reqs", rank, reqs)
        elif kind == 2:
            rank, clen = struct.unpack_from("<iB", payload, off)
            off += 5
            ctx = _trace.unpack_ctx(payload[off:off + clen], 0) \
                if clen else None
            off += clen
            yield ("arrival", rank, ctx)
        elif kind == 3:
            (nc,) = struct.unpack_from("<H", payload, off)
            off += 2
            counts: Dict[int, int] = {}
            for _c in range(nc):
                r, c = struct.unpack_from("<iI", payload, off)
                off += 8
                counts[r] = c
            yield ("counts", counts)
        else:  # pragma: no cover - version skew guard
            raise ValueError(f"unknown subtree section kind {kind}")


def merge_batch_items(items: List[Tuple]) -> Tuple[
        List[Tuple[int, Tuple[int, ...], Tuple[int, ...]]],
        List[Tuple[int, List[bytes]]],
        List[Tuple[int, bytes]]]:
    """Group buffered per-rank items for one envelope.  ``items``:
    ("bits", epoch, rank, idx tuple) singles or pre-grouped
    ("bits", epoch, ranks tuple, idxs) from a child envelope;
    ("reqs", rank, [blobs]); ("arrival", rank, ctx bytes).  Bits merge
    by (epoch, idx set) — the steady state collapses a subtree into a
    single group; request order per rank is preserved."""
    bit_groups: Dict[Tuple[int, Tuple[int, ...]], List[int]] = {}
    req_by_rank: Dict[int, List[bytes]] = {}
    req_order: List[int] = []
    arrivals: List[Tuple[int, bytes]] = []
    for item in items:
        kind = item[0]
        if kind == "bits":
            _k, epoch, ranks, idxs = item
            if isinstance(ranks, int):
                ranks = (ranks,)
            key = (epoch, tuple(sorted(idxs)))
            bit_groups.setdefault(key, []).extend(ranks)
        elif kind == "reqs":
            _k, rank, blobs = item
            if rank not in req_by_rank:
                req_order.append(rank)
                req_by_rank[rank] = []
            req_by_rank[rank].extend(blobs)
        elif kind == "arrival":
            arrivals.append((item[1], item[2]))
    bits = [(epoch, tuple(sorted(set(ranks))), idxs)
            for (epoch, idxs), ranks in sorted(bit_groups.items())]
    reqs = [(r, req_by_rank[r]) for r in req_order]
    return bits, reqs, arrivals


# ---------------------------------------------------------------------------
# The tree worker / relay transport
# ---------------------------------------------------------------------------

@dataclass
class _ChildLink:
    """One accepted child connection on an interior's relay listener.
    ``conn``/``grace_deadline``/``reported`` are mutated under
    TreeWorkerTransport._links_lock; the rx thread owns the reads."""

    rank: int
    conn: Optional[socket.socket]
    covers: set = field(default_factory=set)
    rx_thread: Optional[threading.Thread] = None
    grace_deadline: Optional[float] = None
    reported: bool = False


@dataclass
class _Pull:
    """One in-flight metrics/trace aggregation round at an interior."""

    kind: str                       # "m" | "t"
    rnd: int
    deadline: float
    got: Dict[int, bytes] = field(default_factory=dict)
    sent: bool = False


@_races.race_checked
class TreeWorkerTransport(T.WorkerTransport):
    """A non-root rank under the tree overlay.

    Leaves are plain workers whose "controller" is their parent's relay
    listener; interiors additionally accept their children, merge the
    subtree's upward traffic into per-tick envelopes, and relay every
    downward broadcast verbatim.  Reconnects ALWAYS target the root's
    session-resume listener (the re-parent path): the root is the
    session authority, and a re-parented interior keeps serving its
    own children on its new uplink — a lost parent flattens the tree,
    it never orphans a subtree.
    """

    def __init__(self, host: str, port: int, rank: int, layout: TreeLayout,
                 hostname: Optional[str] = None,
                 connect_timeout: float = 60.0):
        self.layout = layout
        # super().__init__ re-sets this; the child-accept phase below
        # runs first and needs it for ports/diagnostics.
        self.rank = rank
        self._root_host, self._root_port = host, port
        self._reparented = False
        self._children_ranks = layout.children(rank)
        self._links: Dict[int, _ChildLink] = {}
        self._links_lock = _lockorder.make_lock(
            "TreeWorkerTransport._links_lock")
        # Broadcasts that arrive between our own handshake completing
        # (uplink rx thread live) and the children's TOPO slices going
        # out must not overtake the handshake on the child links —
        # buffered here, flushed by _finish_children, so every child's
        # stream starts exactly at global index 0.
        # guarded_by: _links_lock
        self._relay_ready = False
        self._relay_buffer: List[Tuple[int, bytes]] = []
        self._pulls: Dict[Tuple[str, int], _Pull] = {}  # guarded_by: _pulls_lock
        self._pulls_lock = _lockorder.make_lock(
            "TreeWorkerTransport._pulls_lock")
        # Serializes an envelope's pop+send against the verbatim
        # forwards that must stay ORDERED BEHIND it: without it, the
        # ticker thread could pop a child's buffered batch, get
        # preempted, and let the child-rx thread ship a later WITHDRAW/
        # SIGNATURE first — inverting that child's frame order on the
        # merged stream.  Re-entrant: the forward path holds it across
        # flush_requests() + its own _send.
        self._flush_lock = _lockorder.make_rlock(
            "TreeWorkerTransport._flush_lock")
        # Buffered upward child traffic, merged into the next envelope.
        # Shares the flush path with the inherited _pending buffer, so
        # both ride ONE per-tick frame; guarded by the same _buf_lock
        # (created by super().__init__ — nothing touches these before
        # the child rx threads start, which is after that).
        self._child_items: List[Tuple] = []
        self._pending_frame_counts: Dict[int, int] = {}
        self._pending_counts: Dict[int, int] = {}
        self._fwd_count: Dict[int, int] = {}
        self._ticker: Optional[threading.Thread] = None
        self._hello_entries: List[Tuple[int, str, str]] = []
        self._child_hellos: Dict[int, List[Tuple[int, str, str]]] = {}
        self._srv: Optional[socket.socket] = None
        # Interiors collect their children's subtree HELLOs FIRST: the
        # merged HELLO_TREE this rank sends upward must cover the whole
        # subtree before the root will complete its handshake.
        if self._children_ranks:
            self._accept_children(port)
        parent = layout.parent(rank)
        phost, pport = parent_address(host, port, parent)
        super().__init__(phost, pport, rank, hostname=hostname,
                         connect_timeout=connect_timeout)
        # Handshake done: hand each child its TOPO slice, arm frame
        # deadlines, start the relay rx threads + the merge ticker.
        if self._children_ranks:
            self._finish_children()

    # -- bootstrap ---------------------------------------------------------
    def _accept_children(self, controller_port: int) -> None:
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind(("0.0.0.0", relay_port(controller_port, self.rank)))
        srv.listen(len(self._children_ranks))
        accept_timeout = float(
            os.environ.get("HVD_TPU_CONNECT_TIMEOUT", "120"))
        srv.settimeout(accept_timeout)
        self._srv = srv
        got: Dict[int, socket.socket] = {}
        for _ in range(len(self._children_ranks)):
            try:
                conn, _addr = srv.accept()
            except socket.timeout:
                missing = sorted(set(self._children_ranks) - set(got))
                raise TimeoutError(
                    f"tree rank {self.rank}: child ranks "
                    f"{missing} did not connect within "
                    f"{accept_timeout}s") from None
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            ftype, payload = T._recv_frame(conn)
            if ftype != T.FRAME_HELLO_TREE:
                raise RuntimeError(
                    f"tree rank {self.rank}: expected "
                    f"HELLO_TREE from a child, got {ftype}")
            entries = parse_hello_tree(payload)
            child = entries[0][0]
            self._child_hellos[child] = entries
            got[child] = conn
            with self._links_lock:
                self._links[child] = _ChildLink(
                    rank=child, conn=conn,
                    covers={r for r, _h, _f in entries})
        # Children are in; the relay listener's job is done (reconnects
        # go to the root, never back through an interior).
        srv.close()
        self._srv = None

    def _handshake(self, hostname: Optional[str]) -> None:
        from . import compression as _compression

        own = (self.rank, hostname or socket.gethostname(),
               _compression.env_fingerprint())
        entries = [own]
        for child in self._children_ranks:
            entries.extend(self._child_hellos.get(child, []))
        self._hello_entries = entries
        T._send_frame(self._sock, T.FRAME_HELLO_TREE,
                      pack_hello_tree(entries))
        ftype, payload = T._recv_frame(self._sock)
        if ftype != T.FRAME_TOPO_TREE:
            raise RuntimeError(
                f"tree rank {self.rank} expected TOPO_TREE from its "
                f"parent, got {ftype}")
        cache_flag, topo_map = parse_topo_tree(payload)
        self.controller_cache = bool(cache_flag)
        self.topology = topo_map[self.rank]
        self._topo_map = topo_map

    def _finish_children(self) -> None:
        with self._links_lock:
            links = list(self._links.values())
        for link in links:
            slice_entries = [(r, self._topo_map[r])
                             for r in sorted(link.covers)]
            T._send_frame(link.conn, T.FRAME_TOPO_TREE,
                          pack_topo_tree(
                              1 if self.controller_cache else 0,
                              slice_entries))
            link.conn.settimeout(T._frame_timeout())
            th = threading.Thread(
                target=self._child_rx, args=(link,),
                name=f"hvd-tree-rx-{self.rank}-{link.rank}", daemon=True)
            link.rx_thread = th
            th.start()
        # Drain-then-arm: buffered frames go out BEFORE ready flips, so
        # a concurrently arriving broadcast (which keeps buffering
        # until ready) can never overtake them on a child link.
        while True:
            with self._links_lock:
                if not self._relay_buffer:
                    self._relay_ready = True
                    break
                buffered, self._relay_buffer = self._relay_buffer, []
            for ftype, payload in buffered:
                self._relay_send(ftype, payload)
        tick = float(os.environ.get("HOROVOD_CYCLE_TIME", 5.0)) / 1000.0
        self._ticker = threading.Thread(
            target=self._tick_loop, args=(max(0.001, tick),),
            name=f"hvd-tree-tick-{self.rank}", daemon=True)
        self._ticker.start()

    # -- downward relay ----------------------------------------------------
    def _relay_downward(self, ftype: int, payload: bytes) -> None:
        with self._links_lock:
            if not self._relay_ready:
                if self._links:
                    self._relay_buffer.append((ftype, payload))
                return
        self._relay_send(ftype, payload)

    def _relay_send(self, ftype: int, payload: bytes) -> None:
        # Snapshot (link, conn) PAIRS: _drop_link (a concurrent child
        # rx thread seeing EOF) nulls link.conn, and dereferencing it
        # again after the lock would raise AttributeError — which the
        # OSError handler below does not catch, and which would kill
        # the uplink rx thread and stall the whole subtree.
        with self._links_lock:
            links = [(l, l.conn) for l in self._links.values()
                     if l.conn is not None]
        for link, conn in links:
            if _chaos.active() \
                    and _chaos.fire("tree.relay_reset") is not None:
                # The "interior node died" wire effect on ONE child
                # link: the child's recv fails and it re-parents to
                # the root (deterministically testable — the chaos
                # matrix tree_interior_down scenario).
                T._hard_close(conn)
                self._drop_link(link,
                                "hvd-chaos: tree.relay_reset")
                continue
            try:
                # No dup: each child's downward stream must stay the
                # root broadcast stream index-exact (the re-parent
                # resume replays from that global index).
                T._send_frame_or_fault(conn, ftype, payload,
                                       allow_dup=False)
                T._M_TREE_RELAYED.inc()
            except OSError as e:
                self._drop_link(link, f"relay send failed: {e}")

    # -- upward relay (child rx threads) -----------------------------------
    def _child_rx(self, link: _ChildLink) -> None:  # thread: rx
        _athreads.set_role("rx")
        try:
            self._child_rx_inner(link)
        except Exception:
            import traceback

            _telemetry.exception_event(
                "tree-child-rx", traceback.format_exc())
            raise

    def _child_rx_inner(self, link: _ChildLink) -> None:
        conn = link.conn
        while True:
            try:
                ftype, payload = T._recv_frame(
                    conn, peer=f"child rank {link.rank}")
            except OSError:
                ftype = None
            if ftype is None:
                if not (self._closing
                        or self.shutdown_received.is_set()):
                    self._drop_link(link, "eof")
                return
            if ftype == T.FRAME_REQUEST_BATCH:
                rank, epoch, idxs, blobs, tail = \
                    parse_request_batch(payload)
                with self._buf_lock:
                    if idxs:
                        self._child_items.append(
                            ("bits", epoch, (rank,), tuple(idxs)))
                    if blobs:
                        self._child_items.append(("reqs", rank, blobs))
                    if tail:
                        self._child_items.append(("arrival", rank, tail))
                    self._pending_frame_counts[link.rank] = \
                        self._pending_frame_counts.get(link.rank, 0) + 1
                T._M_TREE_MERGED.inc()
            elif ftype == T.FRAME_SUBTREE_BATCH:
                self._buffer_child_envelope(link, payload)
                T._M_TREE_MERGED.inc()
            elif ftype in (T.FRAME_METRICS, T.FRAME_METRICS_TREE,
                           T.FRAME_TRACE, T.FRAME_TRACE_TREE):
                kind = "m" if ftype in (T.FRAME_METRICS,
                                        T.FRAME_METRICS_TREE) else "t"
                if ftype in (T.FRAME_METRICS, T.FRAME_TRACE):
                    crank, rnd = struct.unpack_from("<iI", payload)
                    entries = [(crank, payload[8:])]
                else:
                    rnd, entries = parse_merged_pull(payload)
                with self._buf_lock:
                    self._pending_frame_counts[link.rank] = \
                        self._pending_frame_counts.get(link.rank, 0) + 1
                self._pull_add(kind, rnd, entries)
                T._M_TREE_MERGED.inc()
            else:
                # WITHDRAW / SIGNATURE / PONG / SHUTDOWN / CHILD_LOST /
                # legacy REQUEST: forward verbatim, AFTER flushing any
                # buffered batches so this child's frame order is
                # preserved on the merged stream.  _flush_lock makes
                # flush+forward atomic against the ticker's own flush.
                with self._flush_lock:
                    self.flush_requests()
                    self._send(ftype, payload)
                with self._buf_lock:
                    self._fwd_count[link.rank] = \
                        self._fwd_count.get(link.rank, 0) + 1

    def _buffer_child_envelope(self, link: _ChildLink,
                               payload: bytes) -> None:
        """A child interior's merged envelope: keep its groups intact
        (they re-merge with ours), max-merge its cumulative counts."""
        with self._buf_lock:
            for sec in iter_subtree_sections(payload):
                kind = sec[0]
                if kind == "bits":
                    _k, epoch, ranks, idxs = sec
                    self._child_items.append(
                        ("bits", epoch, tuple(ranks), tuple(idxs)))
                elif kind == "reqs":
                    _k, rank, reqs = sec
                    self._child_items.append(
                        ("reqs", rank, [r.pack() for r in reqs]))
                elif kind == "arrival":
                    _k, rank, ctx = sec
                    if ctx is not None:
                        self._child_items.append(
                            ("arrival", rank,
                             struct.pack("<IIQ", ctx[0], ctx[1],
                                         ctx[2])))
                elif kind == "counts":
                    for r, c in sec[1].items():
                        if c > self._pending_counts.get(r, 0):
                            self._pending_counts[r] = c
            self._pending_frame_counts[link.rank] = \
                self._pending_frame_counts.get(link.rank, 0) + 1

    # -- the per-tick merge ------------------------------------------------
    def flush_requests(self) -> None:
        """Ship the tick's merged envelope: this rank's own pending
        requests/bits PLUS everything its children delivered since the
        last tick, as ONE FRAME_SUBTREE_BATCH (leaves fall back to the
        flat FRAME_REQUEST_BATCH their parent knows how to merge)."""
        if not self._children_ranks:
            super().flush_requests()
            return
        with self._flush_lock:
            self._flush_requests_merged()

    def _flush_requests_merged(self) -> None:
        # guarded_by: _flush_lock (pop-to-send must be atomic vs the
        # verbatim-forward path — see _flush_lock's comment)
        with self._buf_lock:
            own, self._pending = self._pending, []
            items = self._child_items
            self._child_items = []
            frame_counts = self._pending_frame_counts
            self._pending_frame_counts = {}
            merged_counts = self._pending_counts
            self._pending_counts = {}
            for r, n in frame_counts.items():
                self._fwd_count[r] = self._fwd_count.get(r, 0) + n
            for r, c in merged_counts.items():
                if c > self._fwd_count.get(r, 0):
                    self._fwd_count[r] = c
            counts = dict(self._fwd_count)
        own_items: List[Tuple] = []
        by_epoch: Dict[int, List[int]] = {}
        blobs: List[bytes] = []
        for item in own:
            if item[0] == "bit":
                by_epoch.setdefault(item[1], []).append(item[2])
            else:
                blobs.append(item[1])
        for epoch in sorted(by_epoch):
            own_items.append(("bits", epoch, (self.rank,),
                              tuple(by_epoch[epoch])))
        if blobs:
            own_items.append(("reqs", self.rank, blobs))
        if own:
            own_items.append(("arrival", self.rank, _trace.pack_ctx()))
        all_items = own_items + items
        if not all_items:
            return
        bits, reqs, arrivals = merge_batch_items(all_items)
        T._M_BATCH_REQS.inc(sum(len(b) for _r, b in reqs))
        T._M_BATCH_BITS.inc(sum(len(i) for _e, rs, i in bits
                                for _rr in rs))
        _flight.record("frame_tx_subtree", len(bits), len(reqs))
        self._send(T.FRAME_SUBTREE_BATCH,
                   pack_subtree_batch(bits, reqs, arrivals, counts))

    # -- metrics / trace pull aggregation ----------------------------------
    def _expected_pull(self) -> int:
        return len(self.layout.subtree(self.rank))

    def _pull_add(self, kind: str, rnd: int,
                  entries: List[Tuple[int, bytes]]) -> None:
        supplement: List[Tuple[int, bytes]] = []
        with self._pulls_lock:
            key = (kind, rnd)
            pull = self._pulls.get(key)
            if pull is None:
                pull = _Pull(kind=kind, rnd=rnd,
                             deadline=time.monotonic() + pull_timeout())
                self._pulls[key] = pull
            if pull.sent:
                # Entries landing AFTER a partial flush (every level
                # of a deep tree arms the same deadline, so a child
                # interior's own partial flush can lose the race to
                # ours): forward them as a SUPPLEMENTARY merged frame
                # instead of dropping a whole live subtree from the
                # pull — the root's round dict accepts entries for as
                # long as the round's waiter is live.
                supplement = [(r, b) for r, b in entries
                              if r not in pull.got]
                for rank, blob in supplement:
                    pull.got[rank] = blob
            else:
                for rank, blob in entries:
                    pull.got[rank] = blob
            ready = (not pull.sent
                     and len(pull.got) >= self._expected_pull())
        if supplement:
            ftype = T.FRAME_METRICS_TREE if kind == "m" \
                else T.FRAME_TRACE_TREE
            self._send(ftype, pack_merged_pull(rnd, sorted(supplement)))
        if ready:
            self._pull_flush(kind, rnd)

    def _pull_flush(self, kind: str, rnd: int) -> None:
        with self._pulls_lock:
            pull = self._pulls.get((kind, rnd))
            if pull is None or pull.sent:
                return
            pull.sent = True
            entries = sorted(pull.got.items())
        ftype = T.FRAME_METRICS_TREE if kind == "m" \
            else T.FRAME_TRACE_TREE
        self._send(ftype, pack_merged_pull(rnd, entries))

    def _answer_metrics(self, rnd: int) -> None:
        if not self._children_ranks:
            super()._answer_metrics(rnd)
            return
        self._pull_add("m", rnd, [(self.rank, self._metrics_snapshot())])

    def _answer_trace(self, rnd: int) -> None:
        if not self._children_ranks:
            super()._answer_trace(rnd)
            return
        self._pull_add("t", rnd, [(self.rank, self._trace_snapshot())])

    # -- link health / sweeps ----------------------------------------------
    def _drop_link(self, link: _ChildLink, why: str) -> None:
        with self._links_lock:
            conn, link.conn = link.conn, None
            if conn is not None:
                T._wake_close(conn)
            if self._closing or link.reported:
                return
            if link.grace_deadline is None:
                link.grace_deadline = (time.monotonic()
                                       + T._grace_seconds())
                _flight.record("tree_link_down", link.rank, why)
                print(f"[hvd-tree] rank {self.rank}: child rank "
                      f"{link.rank} link lost ({why}); it should "
                      f"re-parent to the root", file=sys.stderr)

    def _sweep(self) -> None:
        now = time.monotonic()
        report: List[Tuple[int, set]] = []
        with self._links_lock:
            for link in self._links.values():
                if (link.grace_deadline is not None
                        and not link.reported
                        and now > link.grace_deadline):
                    link.reported = True
                    report.append((link.rank, set(link.covers)))
        for crank, covers in report:
            # Escalate to the root (the liveness arbiter): every rank
            # this link covered is unreachable VIA US; ranks that
            # re-parented meanwhile are ignored there.
            self.flush_requests()
            for r in sorted(covers):
                reason = (f"child link of interior rank {self.rank} "
                          f"died without re-parent")
                rb = reason.encode("utf-8")
                self._send(T.FRAME_CHILD_LOST,
                           struct.pack("<iH", r, len(rb)) + rb)
        overdue: List[Tuple[str, int]] = []
        with self._pulls_lock:
            for key, pull in list(self._pulls.items()):
                if pull.sent and now > pull.deadline:
                    del self._pulls[key]  # straggler window over
                elif not pull.sent and now > pull.deadline:
                    if pull.got:
                        overdue.append(key)
                    else:
                        del self._pulls[key]
        for kind, rnd in overdue:
            # Partial flush: a dead subtree member must not starve the
            # root's pull of the live members' snapshots.
            self._pull_flush(kind, rnd)

    def _tick_loop(self, tick: float) -> None:  # thread: ticker
        _athreads.set_role("ticker")
        while not self._closing:
            time.sleep(tick)
            try:
                self.flush_requests()
                self._sweep()
            except OSError:
                pass  # uplink mid-reconnect; the ring buffers for us
            except Exception:  # noqa: BLE001 — a dead ticker would
                # silently stall the whole subtree's merge cadence;
                # dump the forensic trail and keep ticking.
                import traceback

                _telemetry.exception_event(
                    "tree-ticker", traceback.format_exc())

    # -- failure propagation / reconnect -----------------------------------
    def _poison(self, detail: str) -> None:
        # The subtree below us can no longer reach the root either:
        # hand children the same synthetic SHUTDOWN diagnosis so they
        # fail loudly instead of idling on a silent stream.  (This
        # frame is outside the root's broadcast stream, but poison is
        # terminal — nobody resumes from it.)
        resp = Response(
            ResponseType.SHUTDOWN,
            error_message="Horovod has been shut down: interior tree "
            f"rank {self.rank} lost the controller ({detail}).")
        payload = wire.pack_response_list([resp]) + _trace.pack_ctx()
        with self._links_lock:
            links = [l for l in self._links.values()
                     if l.conn is not None]
        for link in links:
            try:
                T._send_frame(link.conn, T.FRAME_RESPONSES, payload)
            except OSError:
                pass
        super()._poison(detail)

    def _reconnect(self) -> Optional[str]:
        if not self._reparented and (self._host, self._port) != (
                self._root_host, self._root_port):
            # Re-parent: the root runs the only session-resume listener
            # (interior relays do not resume).  A re-parented interior
            # keeps its children — the subtree rides the new uplink.
            print(f"[hvd-tree] rank {self.rank}: parent link lost; "
                  f"re-parenting to the root controller at "
                  f"{self._root_host}:{self._root_port}",
                  file=sys.stderr)
            _flight.record("tree_reparent_attempt", self.rank)
            self._host, self._port = self._root_host, self._root_port
            self._reparented = True
        return super()._reconnect()

    def close(self) -> None:
        with self._links_lock:
            links = list(self._links.values())
            self._links = {}
        for link in links:
            if link.conn is not None:
                T._wake_close(link.conn)
        if self._srv is not None:
            try:
                self._srv.close()
            except OSError:
                pass
        super().close()


# ---------------------------------------------------------------------------
# Dryrun simulation (bench.py --mode control "tree" section + CI gate)
# ---------------------------------------------------------------------------

def steady_envelope(layout: TreeLayout, child: int, epoch: int,
                    idxs: Sequence[int]) -> bytes:
    """The envelope one direct-root child ships for a steady-state tick
    where every rank of its subtree hit the same cache entries — built
    through the SAME grouping path the live interiors run."""
    items = [("bits", epoch, (r,), tuple(idxs))
             for r in layout.subtree(child)]
    bits, reqs, arrivals = merge_batch_items(items)
    counts = {r: 1 for r in layout.subtree(child) if r != child}
    return pack_subtree_batch(bits, reqs, arrivals, counts)


def simulate_cycle_frames(world: int,
                          fanout: Optional[int] = None) -> Dict[str, int]:
    """Frame accounting for one steady-state negotiation cycle and one
    metrics/trace pull, flat vs tree — the quantity the CI gate bounds
    (rank-0 rx frames <= c * fanout * log_fanout(world))."""
    layout = build_layout(world, fanout)
    root_children = len(layout.children(0))
    return {
        "world": world,
        "fanout": layout.fanout,
        "depth": layout.depth(),
        "flat_frames_per_cycle": world - 1,
        "tree_frames_per_cycle": root_children,
        "flat_frames_per_pull": world - 1,
        "tree_frames_per_pull": root_children,
        "interior_ranks": len(layout.interior_ranks()),
    }
