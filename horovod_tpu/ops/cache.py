"""Steady-state response cache for the eager-collective control plane.

A training loop's collective program is identical from step to step, yet
every step pays the full Horovod-style negotiation round trip: one
request frame per tensor to rank 0, table accumulation in
``PyCoordinator.submit``, cross-rank validation in
``construct_response``, and a broadcast back.  The original paper
(arXiv:1802.05799) introduced that op-negotiation control plane; the MPI
characterization study (arXiv:1810.11112) measures it becoming the
scaling wall as tensor counts and ranks grow.  Later Horovod releases
answered with a response cache — this module is that idea rebuilt for
the TPU-native control plane (this reproduction seeds from v0.13.0,
which predates it).

Design
------
Every rank keeps a replica of one :class:`ResponseCache`.  Entries are
inserted **in broadcast-response-stream order** — every rank processes
the identical response list in the identical order, so entry index
``i`` names the same tensor on every rank without any extra agreement
round.  An entry records, per participating rank, the exact packed
:class:`~horovod_tpu.ops.wire.Request` bytes of the completed
negotiation plus the (single-tensor) validated Response.

Fast path: a submit whose packed request matches a cached entry is a
**hit** — accounted as a per-entry rank bit instead of going through the
coordinator's request table.  Workers ship the tick's hits as one
compact bit-vector inside a coalesced ``FRAME_REQUEST_BATCH``
(ops/transport.py).  When every rank of the entry's process set has
hit, rank 0 *replays* the stored response — ``submit`` /
``construct_response`` never run — and fuses replayed responses with a
**memoized fusion plan** (:func:`plan_fusion` result cached per cycle
key), so the packing decision is computed once, not per step.

Invalidation
------------
Flushes are *epoch* transitions and must happen at the same response
stream position on every rank:

* explicit ``ResponseType.CACHE_FLUSH`` marker responses broadcast by
  rank 0 (hvd.join(), rank withdraw, a program change detected as a
  request whose name matches a live entry but whose signature differs,
  capacity overflow);
* deterministic stream rules applied identically everywhere (a
  ``process_set.register.*`` / ``process_set.remove.*`` registration
  allgather flushing the cache on add/remove_process_set).

A worker bit that raced a flush arrives tagged with its pre-flush epoch;
rank 0 resolves it against the *retired* entries of that epoch by
synthesizing the stored request into a real ``submit`` — a stale hit is
downgraded, never lost and never misrouted.  ``hvd.join()`` additionally
*disarms* insertion until the JOIN release response (negotiations
completed via joins have no request from the joined ranks and must not
become entries); the release is itself stream-visible, so every rank
re-arms at the same position.

Env contract (see docs/performance.md):
  HVD_TPU_RESPONSE_CACHE=0           disable (default on)
  HVD_TPU_RESPONSE_CACHE_CAPACITY    max live entries before a flush
                                     (default 4096; enforced on rank 0)
"""

from __future__ import annotations

import hashlib
import os
import sys
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from . import wire
from .wire import Request, Response, ResponseType
from ..analysis import lockorder as _lockorder
from ..analysis import program as _program
from ..analysis import races as _races
from ..telemetry import flight as _flight

# Retired epochs kept for stale-bit downgrade resolution.  Bits flow at
# the 5 ms drain cadence while flushes are rare events, so a handful of
# epochs is an enormous safety margin.
RETAINED_EPOCHS = 8

# Substrings of allgather names that mark a process-set membership
# change; observing one flushes the cache deterministically on every
# rank (ops/collective.py add_process_set / remove_process_set).
_MEMBERSHIP_MARKERS = ("process_set.register.", "process_set.remove.")


def cache_enabled() -> bool:
    """Env gate.  The cache is OFF while the in-negotiation program
    tracker runs (HVD_TPU_VERIFY_PROGRAM=1): cache hits bypass
    ``Coordinator.submit``, which would blind the tracker's positional
    streams and mis-pair later entries."""
    if os.environ.get("HVD_TPU_RESPONSE_CACHE", "1") == "0":
        return False
    return not _program.program_check_enabled()


def cache_capacity() -> int:
    return int(os.environ.get("HVD_TPU_RESPONSE_CACHE_CAPACITY", "4096"))


def request_key(req: Request) -> tuple:
    """Exact cache key: every negotiated field — name, op, dtype, shape,
    reduce op, process set, root, device, splits AND the submitting rank
    — so two ranks' (or two programs') requests collide only when they
    are identical, i.e. when replaying the cached response is exactly
    what negotiation would have produced.  A plain field tuple (not the
    packed wire bytes): this lookup runs once per collective per rank on
    the steady-state hot path, and tuple hashing is several times
    cheaper than re-serializing."""
    return (req.request_rank, req.request_type, req.tensor_type,
            req.tensor_name, req.root_rank, req.device,
            tuple(req.tensor_shape), req.reduce_op,
            req.process_set_id, tuple(req.splits))


def signature_of(req: Request) -> _program.SignatureEntry:
    """The hvd-analyze signature record of one request — reused from
    analysis/program.py so cache diagnostics and program digests render
    entries identically to verify_program."""
    return _program.SignatureEntry(
        seq=0, op=req.request_type.name.lower(), name=req.tensor_name,
        dtype=wire.dtype_name(req.tensor_type),
        shape=tuple(req.tensor_shape),
        reduce_op=(wire.reduce_op_name(req.reduce_op)
                   if req.request_type in (wire.RequestType.ALLREDUCE,
                                           wire.RequestType.REDUCESCATTER)
                   else ""),
        process_set_id=req.process_set_id)


def cycle_digest(entries: List[_program.SignatureEntry]) -> str:
    """Program digest of one cached cycle (the fusion-plan memo key's
    printable form) — analysis/program.py's canonical digest over the
    cycle's signature entries."""
    return _program.entries_digest(entries)


@dataclass
class _FusionMeta:
    """The fields the fusion packing decision reads, per response."""

    response_type: ResponseType
    devices: Tuple[int, ...]
    reduce_op: wire.ReduceOp
    process_set_id: int
    dtype: Optional[wire.DataType]
    nbytes: int


def plan_fusion(metas: List[_FusionMeta],
                threshold_of: Callable[[int], int]) -> List[List[int]]:
    """The Tensor Fusion packing decision (≙ reference
    operations.cc:1328-1374), factored out of the coordinator's response
    loop so the cache can memoize it per cycle: same-dtype, same-device,
    same-reduce-op, same-process-set ALLREDUCE responses merge while the
    payload sum stays under the process set's fusion threshold; Adasum
    never fuses (its dot products are per-tensor scale adaptations).
    Returns index groups in emission order."""
    n = len(metas)
    used = [False] * n
    groups: List[List[int]] = []
    for i in range(n):
        if used[i]:
            continue
        used[i] = True
        m = metas[i]
        group = [i]
        if m.response_type != ResponseType.ALLREDUCE \
                or m.reduce_op == wire.ReduceOp.ADASUM:
            groups.append(group)
            continue
        total = m.nbytes
        threshold = threshold_of(m.process_set_id)
        for j in range(i + 1, n):
            if used[j]:
                continue
            o = metas[j]
            if (o.response_type == ResponseType.ALLREDUCE
                    and o.devices == m.devices
                    and o.reduce_op == m.reduce_op
                    and o.process_set_id == m.process_set_id
                    and o.dtype == m.dtype
                    and total + o.nbytes <= threshold):
                total += o.nbytes
                group.append(j)
                used[j] = True
        groups.append(group)
    return groups


def _nbytes_of_request(req: Request) -> int:
    n = 1
    for d in req.tensor_shape:
        n *= int(d)
    return n * wire.dtype_size(req.tensor_type)


@dataclass
class _Entry:
    """One cached negotiation outcome (a single tensor's response)."""

    idx: int
    name: str
    process_set_id: int
    # Validated single-tensor response template; replay copies it, never
    # mutates it (fusion extends name/shape lists on fresh objects).
    response: Response
    # global rank -> that rank's Request from the completed negotiation
    # (set-local request_rank inside, ready for a downgrade re-submit).
    # Empty on a rank that held no local op (process-set non-member):
    # such a placeholder keeps entry indices aligned across ranks but
    # can never be hit.
    requests: Dict[int, Request] = field(default_factory=dict)
    nbytes: int = 0
    dtype: Optional[wire.DataType] = None
    # Ranks that hit this entry in the current cycle.
    pending: set = field(default_factory=set)
    # False when any of this cycle's hits arrived as a full request
    # frame (a rank running with the cache disabled): the replay must
    # then broadcast full responses — that rank has no replica to
    # rebuild a compact FRAME_RESPONSE_BATCH from.
    compact_ok: bool = True


@dataclass
class CacheStats:
    hits: int = 0
    misses: int = 0
    replayed_responses: int = 0
    replayed_tensors: int = 0
    plan_hits: int = 0
    plan_misses: int = 0
    flushes: int = 0
    downgrades: int = 0
    inserts: int = 0


@_races.race_checked
class ResponseCache:
    """One rank's replica of the negotiation response cache.

    Thread-safety: a single leaf lock — no other runtime lock is ever
    acquired while holding it (submit paths, the drain tick and the
    controller's receive threads all call in).  Methods returning
    orphaned requests expect the CALLER to re-submit them outside the
    lock."""

    def __init__(self, rank: int = 0, capacity: Optional[int] = None):
        self.rank = rank
        self.capacity = capacity if capacity is not None \
            else cache_capacity()
        self._lock = _lockorder.make_lock("ResponseCache._lock")
        self._entries: List[_Entry] = []  # guarded_by: _lock
        self._by_key: Dict[tuple, Tuple[int, int]] = {}  # guarded_by: _lock
        self._by_name: Dict[str, int] = {}  # guarded_by: _lock
        self._ready: List[int] = []  # guarded_by: _lock
        self._retired: Dict[int, Dict[int, _Entry]] = {}  # guarded_by: _lock
        self._plans: Dict[tuple, List[List[int]]] = {}  # guarded_by: _lock
        self._epoch = 0  # guarded_by: _lock
        self._disarmed = False  # guarded_by: _lock
        # Controller-side: a pending CACHE_FLUSH marker to broadcast
        # (epoch, disarm) — consumed by the drain tick.
        self._marker: Optional[Tuple[int, bool]] = None  # guarded_by: _lock
        # Controller-side staging: name -> {global rank -> Request} of
        # freshly completed negotiations, captured by the Coordinator
        # facade at poll time and consumed by observe_response.
        self._staged: Dict[str, Dict[int, Request]] = {}  # guarded_by: _lock
        self.stats = CacheStats()

    # -- introspection ----------------------------------------------------
    @property
    def epoch(self) -> int:
        with self._lock:
            return self._epoch

    def live_entries(self) -> int:
        with self._lock:
            return len(self._entries)

    def entry_index(self, name: str) -> Optional[int]:
        """Live entry index for a tensor name (tests + bench)."""
        with self._lock:
            return self._by_name.get(name)

    def signature_entries(self) -> List[_program.SignatureEntry]:
        """hvd-analyze signatures of the live entries (diagnostics)."""
        with self._lock:
            return self._signature_entries_locked()

    def _signature_entries_locked(self) -> List[_program.SignatureEntry]:
        out = []
        for e in self._entries:
            req = next(iter(e.requests.values()), None)
            if req is not None:
                out.append(signature_of(req))
        return out

    def _replica_id_locked(self) -> str:
        """Replica fingerprint for desync diagnostics: the program
        digest of the live entries (analysis/program.py's scheme) —
        equal fingerprints across ranks ⇔ identical replicas."""
        return (f"epoch {self._epoch}, {len(self._entries)} entries, "
                f"digest {cycle_digest(self._signature_entries_locked())[:12]}")

    # -- flush / epoch machinery ------------------------------------------
    def _log(self, msg: str) -> None:
        print(f"[hvd-cache] rank {self.rank}: {msg}", file=sys.stderr)

    def _flush_locked(self, reason: str, disarm: bool,
                      broadcast: bool) -> List[Request]:
        orphans: List[Request] = []
        for idx in self._ready:
            # Ready-but-untaken entries: all ranks agreed, but the
            # replay never went out — downgrade every participant so
            # the ops still complete through a real negotiation.
            entry = self._entries[idx]
            entry.pending = set(entry.requests)
        for entry in self._entries:
            for r in sorted(entry.pending):
                req = entry.requests.get(r)
                if req is not None:
                    orphans.append(req)
            entry.pending = set()
        if self._entries:
            self._retired[self._epoch] = {e.idx: e for e in self._entries}
            for old in sorted(self._retired):
                if old <= self._epoch - RETAINED_EPOCHS:
                    del self._retired[old]
        n = len(self._entries)
        self._entries = []
        self._by_key = {}
        self._by_name = {}
        self._ready = []
        self._plans = {}
        self._epoch += 1
        self._disarmed = disarm or self._disarmed
        if broadcast:
            self._marker = (self._epoch, self._disarmed)
        self.stats.flushes += 1
        # Flight ring: epoch transitions are exactly the divergence
        # points a forensic replay needs (record() takes no lock, so
        # the cache lock stays a leaf).
        _flight.record("cache_flush", reason, self._epoch, n)
        if n or disarm:
            self._log(f"cache flush ({reason}): {n} entries dropped, "
                      f"epoch {self._epoch}"
                      + (", insertion disarmed" if self._disarmed else ""))
        return orphans

    def flush(self, reason: str, disarm: bool = False,
              broadcast: bool = False) -> List[Request]:
        """Invalidate every live entry.  Returns the requests of any
        partially-hit entries — the caller MUST re-submit them through
        the real negotiation path (outside this cache's lock)."""
        with self._lock:
            return self._flush_locked(reason, disarm, broadcast)

    def disarm(self, reason: str) -> List[Request]:
        """hvd.join(): flush and stop inserting until the JOIN release
        (negotiations completed via joins lack the joined ranks'
        requests and must never become entries)."""
        return self.flush(reason, disarm=True, broadcast=True)

    def take_flush_marker(self) -> Optional[Response]:
        """Controller drain tick: the pending CACHE_FLUSH response to
        broadcast (epoch + disarm flag in tensor_sizes), or None."""
        with self._lock:
            if self._marker is None:
                return None
            epoch, disarm = self._marker
            self._marker = None
        return Response(ResponseType.CACHE_FLUSH,
                        tensor_sizes=[epoch, 1 if disarm else 0])

    def check_capacity(self) -> List[Request]:
        """Controller drain tick, before polling: flush when the entry
        table outgrew the capacity (rank-0-enforced so every replica
        flushes via the broadcast marker, even if their local env
        differs)."""
        with self._lock:
            if len(self._entries) <= self.capacity:
                return []  # flush only on OVERFLOW: a program with
                # exactly `capacity` tensors must still cache
            return self._flush_locked(
                f"capacity {self.capacity} exceeded", disarm=False,
                broadcast=True)

    def invalidate_plans(self, reason: str) -> None:
        """Autotune hook: a fusion-threshold change invalidates the
        memoized packing plans (entries stay valid — the negotiation
        outcome does not depend on the threshold)."""
        with self._lock:
            n = len(self._plans)
            self._plans = {}
        if n:
            self._log(f"fusion plans flushed ({reason}): {n} plans")

    # -- submit-side fast path --------------------------------------------
    def lookup_and_hit(self, req: Request) -> Tuple[str, object]:
        """Classify one locally-submitted request against the cache.

        Returns one of:
          ("hit", completed: bool)     — accounted; True when every rank
                                         of the entry's set has now hit
                                         (the entry joined the replay
                                         queue);
          ("miss", None)               — no entry; negotiate normally;
          ("conflict", orphans: list)  — the NAME matches a live entry
                                         but the request changed (the
                                         program changed mid-run): the
                                         cache flushed itself; the
                                         caller must submit the orphaned
                                         requests AND this one through
                                         the real path.
        """
        key = request_key(req)
        with self._lock:
            pos = self._by_key.get(key)
            if pos is not None:
                idx, grank = pos
                # A hit that arrived as a FULL request from another
                # rank (not a bit) means that rank may have no replica
                # (HVD_TPU_RESPONSE_CACHE off there): the replay must
                # then broadcast full responses it can parse, never the
                # compact entry-index frame.
                done = self._hit_locked(idx, grank,
                                        compact=grank == self.rank)
                self.stats.hits += 1
                return "hit", done
            if req.tensor_name in self._by_name:
                entry = self._entries[self._by_name[req.tensor_name]]
                old = next(iter(entry.requests.values()), None)
                desc = (signature_of(old).describe() if old is not None
                        else "<placeholder>")
                self._log(
                    f"program changed: {signature_of(req).describe()} no "
                    f"longer matches cached {desc}")
                orphans = self._flush_locked(
                    f"program change on {req.tensor_name!r}",
                    disarm=False, broadcast=True)
                self.stats.misses += 1
                return "conflict", orphans
            self.stats.misses += 1
            return "miss", None

    def worker_lookup(self, req: Request) -> Optional[Tuple[int, int]]:
        """Worker submit path: (epoch, entry idx) when the request hits
        the replica — the transport ships the bit — else None (ship the
        full request; rank 0 owns conflict/downgrade resolution)."""
        with self._lock:
            pos = self._by_key.get(request_key(req))
            if pos is None:
                self.stats.misses += 1
                return None
            self.stats.hits += 1
            return self._epoch, pos[0]

    def _hit_locked(self, idx: int, grank: int, compact: bool) -> bool:
        entry = self._entries[idx]
        entry.pending.add(grank)
        if not compact:
            entry.compact_ok = False
        if len(entry.pending) == len(entry.requests):
            entry.pending = set()
            self._ready.append(idx)
            return True
        return False

    def hit_from_wire(self, idx: int, grank: int,
                      epoch: int) -> Optional[Request]:
        """Controller: account one worker bit.  Returns None when
        accounted against a live entry; returns the stored Request to
        DOWNGRADE into a real submit when the bit raced a flush (its
        epoch names a retired generation); logs and drops a bit no
        retired generation can explain (the sender's own stall/withdraw
        machinery reports the op)."""
        with self._lock:
            if epoch == self._epoch and 0 <= idx < len(self._entries):
                entry = self._entries[idx]
                if grank in entry.requests:
                    self._hit_locked(idx, grank, compact=True)
                    return None
            retired = self._retired.get(epoch, {})
            entry = retired.get(idx)
            if entry is not None and grank in entry.requests:
                self.stats.downgrades += 1
                _flight.record("cache_downgrade", entry.name, grank,
                               epoch)
                return entry.requests[grank]
        self._log(f"dropping unresolvable cache bit (entry {idx}, rank "
                  f"{grank}, epoch {epoch}; current epoch {self.epoch})")
        return None

    # -- replay ------------------------------------------------------------
    def take_ready(self, threshold_of: Callable[[int], int]
                   ) -> Tuple[List[Response], List[List[int]], int, bool]:
        """Drain the fully-hit entries into fused replay responses.

        Returns (responses, index groups, epoch, compact_ok): the index
        groups let the transport broadcast the cycle as a compact
        FRAME_RESPONSE_BATCH when every hit was a true bit
        (``compact_ok``); workers rebuild the identical fused responses
        from their replicas.  The fusion packing is memoized per cycle
        key — the ordered entry indices — so the steady state never
        recomputes it (the cached-fusion-plan leg of the fast path).

        ``threshold_of`` runs under this cache's LEAF lock and must be
        pure — callers snapshot per-process-set thresholds beforehand
        (ops/collective._threshold_snapshot).
        """
        with self._lock:
            idxs, self._ready = self._ready, []
            if not idxs:
                return [], [], self._epoch, True
            entries = [self._entries[i] for i in idxs]
            compact = all(e.compact_ok for e in entries)
            for e in entries:
                e.compact_ok = True
            plan_key = tuple(idxs)
            plan = self._plans.get(plan_key)
            if plan is None:
                if len(self._plans) >= 256:
                    # Jittery tick partitioning of a stable program can
                    # mint a new ready-order key per step; bound the
                    # memo instead of growing for the job's lifetime.
                    self._plans = {}
                metas = [_FusionMeta(
                    response_type=e.response.response_type,
                    devices=tuple(e.response.devices),
                    reduce_op=e.response.reduce_op,
                    process_set_id=e.process_set_id,
                    dtype=e.dtype, nbytes=e.nbytes) for e in entries]
                plan = plan_fusion(metas, threshold_of)
                self._plans[plan_key] = plan
                self.stats.plan_misses += 1
            else:
                self.stats.plan_hits += 1
            groups = [[idxs[i] for i in g] for g in plan]
            responses = [self._build_group_locked(g) for g in groups]
            self.stats.replayed_responses += len(responses)
            self.stats.replayed_tensors += len(idxs)
            epoch = self._epoch
        return responses, groups, epoch, compact

    def _build_group_locked(self, idxs: List[int]) -> Response:
        r = self._entries[idxs[0]].response
        names: List[str] = []
        shapes: List[Tuple[int, ...]] = []
        for i in idxs:
            e = self._entries[i].response
            names.extend(e.tensor_names)
            shapes.extend(e.tensor_shapes)
        return Response(
            response_type=r.response_type, tensor_names=names,
            error_message="", devices=list(r.devices),
            tensor_sizes=list(r.tensor_sizes), tensor_type=r.tensor_type,
            tensor_shapes=shapes, reduce_op=r.reduce_op,
            process_set_id=r.process_set_id)

    def rebuild_groups(self, groups: List[List[int]],
                       epoch: int) -> List[Response]:
        """Worker: reconstitute a compact FRAME_RESPONSE_BATCH into the
        full fused response list from the local replica.  Raises when
        the epoch or an index cannot be resolved — a replica desync is a
        protocol bug and must fail loudly, not execute garbage."""
        with self._lock:
            if epoch != self._epoch:
                raise RuntimeError(
                    f"response-cache replica desync: controller replayed "
                    f"epoch {epoch} but this rank holds "
                    f"{self._replica_id_locked()}")
            for g in groups:
                for i in g:
                    if not 0 <= i < len(self._entries):
                        raise RuntimeError(
                            f"response-cache replica desync: controller "
                            f"replayed entry {i} but this rank holds "
                            f"{self._replica_id_locked()}")
            return [self._build_group_locked(g) for g in groups]

    # -- insertion (response-stream driven, identical order everywhere) ----
    def stage_negotiated(self, name: str,
                         requests: Dict[int, Request]) -> None:
        """Controller facade, at poll time: remember the per-rank
        requests of a freshly completed negotiation for the
        observe_response insertion that follows in the same tick."""
        with self._lock:
            self._staged[name] = requests

    def drop_staged(self, names: List[str]) -> None:
        with self._lock:
            self._drop_staged_locked(names)

    def _drop_staged_locked(self, names: List[str]) -> None:
        for n in names:
            self._staged.pop(n, None)

    def observe_response(self, resp: Response,
                         own_requests: Optional[Dict[int, Dict[
                             str, Request]]] = None,
                         replay: bool = False) -> None:
        """Process one broadcast response IN STREAM ORDER — the one rule
        that keeps every rank's replica index-aligned.  ``own_requests``
        (worker side) maps global rank -> {name -> Request} for this
        rank's own pending ops; the controller side uses the staged
        per-rank requests instead.

        Replayed responses are never inserted: rank 0 marks them
        explicitly (``replay=True`` — its replica may have flushed
        between building the replay and observing it), while workers
        skip them through the name-presence check (their replica cannot
        flush before the marker that follows the replays in-stream) —
        the two rules reach the same decision in every interleaving,
        which is what keeps entry indices aligned."""
        rt = resp.response_type
        if rt == ResponseType.CACHE_FLUSH:
            sizes = list(resp.tensor_sizes) + [0, 0]
            epoch, disarm = int(sizes[0]), bool(sizes[1])
            with self._lock:
                if epoch > self._epoch:
                    self._flush_locked("flush marker from rank 0",
                                       disarm=disarm, broadcast=False)
                    # Adopt rank 0's numbering exactly (several flushes
                    # may collapse into one observed marker).
                    self._epoch = epoch
                elif disarm:
                    self._disarmed = True
            return
        if rt == ResponseType.RETUNE:
            # hvd-tune knob marker: cache entries stay valid (the
            # negotiated outcome is knob-independent); the stale packing
            # plans / compiled megakernels are dropped by the apply path
            # (tuning/actuation.py) on every rank at this same stream
            # position, so replicas never mix pre- and post-retune
            # executables within one cycle.
            return
        if rt == ResponseType.JOIN:
            with self._lock:
                if self._disarmed:
                    self._disarmed = False
                    self._log("insertion re-armed (join released)")
            return
        if rt in (ResponseType.ERROR, ResponseType.SHUTDOWN,
                  ResponseType.DONE):
            self.drop_staged(list(resp.tensor_names))
            return
        if not replay:
            self._insert_from(resp, own_requests or {})
        # Deterministic membership-change rule: the registration
        # allgather names the event; every rank flushes at this exact
        # stream position (single-process registration flushes directly
        # from add/remove_process_set instead).
        if rt == ResponseType.ALLGATHER and any(
                m in n for n in resp.tensor_names
                for m in _MEMBERSHIP_MARKERS):
            orphans = self.flush("process-set membership change")
            if orphans:
                # Cannot happen on a healthy stream (a membership change
                # is collective, so no cached cycle is mid-flight), but
                # never swallow a submission silently.
                self._log(f"dropping {len(orphans)} mid-flight cached "
                          f"submissions across a membership change")

    def _insert_from(self, resp: Response,
                     own_requests: Dict[int, Dict[str, Request]]) -> None:
        with self._lock:
            if self._disarmed:
                self._drop_staged_locked(list(resp.tensor_names))
                return
            for pos, name in enumerate(resp.tensor_names):
                if name in self._by_name:
                    continue
                staged = True
                reqs = self._staged.pop(name, None)
                if reqs is None:
                    staged = False
                    reqs = {}
                    for grank, by_name in own_requests.items():
                        req = by_name.get(name)
                        if req is not None:
                            reqs[grank] = req
                if os.environ.get("HVD_TPU_CACHE_DEBUG") == "1":
                    self._log(f"insert entry {len(self._entries)} "
                              f"{name!r} ranks={sorted(reqs)} "
                              f"{'staged' if staged else 'fallback'}")
                single = self._single_response(resp, pos)
                sample = next(iter(reqs.values()), None)
                entry = _Entry(
                    idx=len(self._entries), name=name,
                    process_set_id=resp.process_set_id, response=single,
                    requests=reqs,
                    nbytes=(_nbytes_of_request(sample)
                            if sample is not None else 0),
                    dtype=(sample.tensor_type if sample is not None
                           else resp.tensor_type))
                self._entries.append(entry)
                self._by_name[name] = entry.idx
                for grank, req in reqs.items():
                    self._by_key[request_key(req)] = (entry.idx, grank)
                self.stats.inserts += 1

    @staticmethod
    def _single_response(resp: Response, pos: int) -> Response:
        """The single-tensor slice of a (possibly fused) data response —
        what replay re-fuses from.  Non-fusing response types (only
        ALLREDUCE fuses) keep their full metadata."""
        if len(resp.tensor_names) == 1:
            shapes = [tuple(s) for s in resp.tensor_shapes]
        else:
            shapes = ([tuple(resp.tensor_shapes[pos])]
                      if pos < len(resp.tensor_shapes) else [])
        return Response(
            response_type=resp.response_type,
            tensor_names=[resp.tensor_names[pos]], error_message="",
            devices=list(resp.devices),
            tensor_sizes=list(resp.tensor_sizes),
            tensor_type=resp.tensor_type, tensor_shapes=shapes,
            reduce_op=resp.reduce_op,
            process_set_id=resp.process_set_id)
