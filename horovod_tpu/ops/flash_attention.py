"""Flash attention as a Pallas TPU kernel (forward + backward).

The reference framework has no attention code at all (SURVEY.md §5,
"Long-context / sequence parallelism: absent") — this is a beyond-parity
component that the long-context stack (:mod:`..parallel.sequence`) builds
on.  It is written TPU-first:

* blocks are MXU/VPU aligned (q/k block sizes default to 128 lanes),
* the softmax runs online (one pass over K/V, O(seq) memory instead of
  O(seq²)) so HBM traffic is linear,
* matmuls accumulate in float32 via ``preferred_element_type`` regardless
  of input dtype (bfloat16 inputs stay MXU-friendly),
* the backward pass is two Pallas kernels (dKdV then dQ) using the saved
  log-sum-exp rows plus the standard ``delta = rowsum(dO * O)`` trick, so
  nothing quadratic is ever materialized.

On non-TPU backends (the CPU test mesh) the default is a dense-jnp exact
attention with the same (o, lse) contract — the Pallas interpreter is
~1000x slower and only exercises the kernels, which the kernel tests do
explicitly via ``interpret=True`` / ``HVD_TPU_FLASH_INTERPRET=1``.
`flash_attention` is the single entry point either way.

Layout: ``q, k, v : [batch, heads, seq, head_dim]``.
"""

from __future__ import annotations

import functools
import os
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

DEFAULT_MASK_VALUE = -0.7 * float(jnp.finfo(jnp.float32).max)


def _interpret_default() -> bool:
    return jax.default_backend() != "tpu"


def _dense_default() -> bool:
    """On non-TPU backends, ``interpret=None`` resolves to a dense-jnp
    path (mathematically identical exact attention) instead of the Pallas
    interpreter, which executes ~1000x slower and exists only to test the
    kernels themselves.  Kernel tests opt back in with ``interpret=True``
    or ``HVD_TPU_FLASH_INTERPRET=1``."""
    force_interpret = os.environ.get(
        "HVD_TPU_FLASH_INTERPRET", "").lower() in ("1", "true", "yes")
    return jax.default_backend() != "tpu" and not force_interpret


def _dense_mask(s, *, causal, q_block_offset, q_len, k_len):
    if not causal:
        return s
    q_pos = q_block_offset + jnp.arange(q_len)[:, None]
    k_pos = jnp.arange(k_len)[None, :]
    return jnp.where(q_pos >= k_pos, s, -jnp.inf)


def _dense_forward(q, k, v, sm_scale, causal, q_block_offset):
    """(o, lse) via exact dense attention — same contract as the kernel."""
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * sm_scale
    s = _dense_mask(s, causal=causal, q_block_offset=q_block_offset,
                    q_len=q.shape[2], k_len=k.shape[2])
    lse = jax.scipy.special.logsumexp(s, axis=-1)  # -inf for masked rows
    p = jnp.where(jnp.isneginf(lse)[..., None], 0.0,
                  jnp.exp(s - lse[..., None]))
    o = jnp.einsum("bhqk,bhkd->bhqd", p,
                   v.astype(jnp.float32)).astype(q.dtype)
    return o, lse


def _dense_backward(res, g, *, sm_scale, causal, q_block_offset):
    """Flash-backward math, densely: uses the caller's (possibly globally
    accumulated) ``o``/``lse`` so ring attention's per-chunk gradients
    stay normalized across the whole sequence."""
    q, k, v, o, lse = res
    qf, kf, vf = (x.astype(jnp.float32) for x in (q, k, v))
    gf, of = g.astype(jnp.float32), o.astype(jnp.float32)
    s = jnp.einsum("bhqd,bhkd->bhqk", qf, kf) * sm_scale
    s = _dense_mask(s, causal=causal, q_block_offset=q_block_offset,
                    q_len=q.shape[2], k_len=k.shape[2])
    p = jnp.where(jnp.isneginf(lse)[..., None], 0.0,
                  jnp.exp(s - lse[..., None]))
    dv = jnp.einsum("bhqk,bhqd->bhkd", p, gf)
    delta = jnp.sum(gf * of, axis=-1)                 # [b, h, q]
    dp = jnp.einsum("bhqd,bhkd->bhqk", gf, vf)
    ds = p * (dp - delta[..., None]) * sm_scale
    dq = jnp.einsum("bhqk,bhkd->bhqd", ds, kf).astype(q.dtype)
    dk = jnp.einsum("bhqk,bhqd->bhkd", ds, qf).astype(k.dtype)
    return dq, dk, dv.astype(v.dtype)


def _apply_mask(s, *, q_start, k_start, kv_actual, kv_padded, causal,
                q_block_offset):
    """Shared score mask for all three kernels: padded keys (past
    ``kv_actual``) and, when ``causal``, future positions.  Forward and
    backward MUST mask identically or gradients silently diverge."""
    block_q, block_k = s.shape
    if not causal and kv_actual == kv_padded:
        return s
    k_pos = k_start + jax.lax.broadcasted_iota(jnp.int32,
                                               (block_q, block_k), 1)
    valid = k_pos < kv_actual
    if causal:
        q_pos = (q_start + q_block_offset
                 + jax.lax.broadcasted_iota(jnp.int32,
                                            (block_q, block_k), 0))
        valid = jnp.logical_and(valid, q_pos >= k_pos)
    return jnp.where(valid, s, DEFAULT_MASK_VALUE)


# ---------------------------------------------------------------------------
# Forward kernel
# ---------------------------------------------------------------------------

def _resident_max_seq() -> int:
    """Sequences up to this length use the "resident" kernels (whole K/V
    — or whole Q on the dKdV pass — held in VMEM, blocks walked by an
    in-kernel loop): fewer grid cells, measurably faster at short seq.
    Beyond it, the streaming kernels bound VMEM at O(block) — the
    resident layout's O(seq) operand blows the ~16 MB VMEM around
    seq 8K.  Read at TRACE time: changing the env after a function was
    jit-compiled does not re-route its cached executable; tests force a
    path by setting the env before tracing."""
    return int(os.environ.get("HVD_TPU_FLASH_RESIDENT_SEQ", "4096"))


def _fwd_kernel_resident(q_ref, k_ref, v_ref, o_ref, lse_ref, *,
                         sm_scale: float, causal: bool, block_k: int,
                         kv_seq_len: int, kv_actual: int,
                         q_block_offset: int):
    """One (batch*head, q_block) grid cell: online-softmax over K blocks
    held resident in VMEM."""
    block_q = q_ref.shape[0]
    head_dim = q_ref.shape[1]
    q_idx = pl.program_id(1)

    # Keep q/k/v in their input dtype for the dots: bf16 operands run the
    # MXU at full rate (f32 accumulation via preferred_element_type); an
    # f32 upcast here would halve matmul throughput.  sm_scale is applied
    # to the f32 scores instead of the (possibly bf16) q.
    q = q_ref[:, :]
    m_init = jnp.full((block_q, 1), -jnp.inf, jnp.float32)
    l_init = jnp.zeros((block_q, 1), jnp.float32)
    acc_init = jnp.zeros((block_q, head_dim), jnp.float32)

    num_k_blocks = pl.cdiv(kv_seq_len, block_k)

    def body(kb, carry):
        m_prev, l_prev, acc = carry
        k = k_ref[pl.ds(kb * block_k, block_k), :]
        v = v_ref[pl.ds(kb * block_k, block_k), :]
        s = jnp.dot(q, k.T,
                    preferred_element_type=jnp.float32) * sm_scale
        s = _apply_mask(s, q_start=q_idx * block_q, k_start=kb * block_k,
                        kv_actual=kv_actual, kv_padded=kv_seq_len,
                        causal=causal, q_block_offset=q_block_offset)
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_new = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc * alpha + jnp.dot(p.astype(v.dtype), v,
                                    preferred_element_type=jnp.float32)
        return m_new, l_new, acc

    if causal:
        # Blocks entirely in the future contribute nothing — skip them.
        hi = jnp.minimum(
            num_k_blocks,
            pl.cdiv((q_idx + 1) * block_q + q_block_offset, block_k))
    else:
        hi = num_k_blocks
    m, l, acc = jax.lax.fori_loop(0, hi, body,
                                  (m_init, l_init, acc_init))
    no_valid = jnp.logical_or(l == 0.0, m <= DEFAULT_MASK_VALUE * 0.5)
    l_safe = jnp.where(no_valid, 1.0, l)
    o_ref[:, :] = jnp.where(no_valid, 0.0,
                            acc / l_safe).astype(o_ref.dtype)
    lse = jnp.where(no_valid, -jnp.inf, m + jnp.log(l_safe))
    lse_ref[:, :] = lse.astype(jnp.float32)


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_acc, l_acc, acc,
                *, sm_scale: float, causal: bool, kv_actual: int,
                kv_padded: int, q_block_offset: int):
    """Grid cell (batch*head, q_block, k_block): one K block of the
    online softmax, state carried in VMEM scratch across the
    (sequential, innermost) k dimension.  Streaming K/V through the grid
    keeps VMEM O(block) instead of O(seq) — see the backward kernels.

    ``q_block_offset`` shifts the causal comparison for ring attention,
    where the local q shard's global position differs from its local index.
    ``kv_actual`` is the unpadded key count (keys past it are masked).
    """
    block_q = q_ref.shape[0]
    block_k = k_ref.shape[0]
    q_idx = pl.program_id(1)
    k_idx = pl.program_id(2)
    num_k_blocks = pl.num_programs(2)

    @pl.when(k_idx == 0)
    def _init():
        m_acc[:, :] = jnp.full_like(m_acc, -jnp.inf)
        l_acc[:, :] = jnp.zeros_like(l_acc)
        acc[:, :] = jnp.zeros_like(acc)

    # Causal: K blocks entirely in the future contribute nothing.
    live = True
    if causal:
        live = (k_idx * block_k
                < (q_idx + 1) * block_q + q_block_offset)

    @pl.when(live)
    def _accumulate():
        # Native-dtype dots (see _fwd_kernel_resident): bf16 operands keep
        # the MXU at full rate; scores/state accumulate in f32.
        q = q_ref[:, :]
        k = k_ref[:, :]
        v = v_ref[:, :]
        s = jnp.dot(q, k.T,
                    preferred_element_type=jnp.float32) * sm_scale
        s = _apply_mask(s, q_start=q_idx * block_q,
                        k_start=k_idx * block_k, kv_actual=kv_actual,
                        kv_padded=kv_padded, causal=causal,
                        q_block_offset=q_block_offset)
        m_prev = m_acc[:, :]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        m_acc[:, :] = m_new
        l_acc[:, :] = alpha * l_acc[:, :] + jnp.sum(p, axis=-1,
                                                    keepdims=True)
        acc[:, :] = acc[:, :] * alpha + jnp.dot(
            p.astype(v.dtype), v, preferred_element_type=jnp.float32)

    @pl.when(k_idx == num_k_blocks - 1)
    def _emit():
        m, l = m_acc[:, :], l_acc[:, :]
        # Rows with no visible keys: either no block executed (l == 0) or
        # every entry carried the mask value (m stayed at the mask
        # floor).  Emit zeros with lse = -inf rather than dividing by
        # zero / averaging junk.
        no_valid = jnp.logical_or(l == 0.0, m <= DEFAULT_MASK_VALUE * 0.5)
        l_safe = jnp.where(no_valid, 1.0, l)
        o_ref[:, :] = jnp.where(no_valid, 0.0,
                                acc[:, :] / l_safe).astype(o_ref.dtype)
        lse = jnp.where(no_valid, -jnp.inf, m + jnp.log(l_safe))
        lse_ref[:, :] = lse.astype(jnp.float32)


def _pad_seq(x, multiple):
    """Zero-pad the seq (next-to-last) axis up to a block multiple."""
    s = x.shape[-2]
    pad = (-s) % multiple
    if pad == 0:
        return x
    widths = [(0, 0)] * (x.ndim - 2) + [(0, pad), (0, 0)]
    return jnp.pad(x, widths)


def _flash_forward(q, k, v, sm_scale, causal, block_q, block_k,
                   q_block_offset, interpret):
    if interpret is None:
        if _dense_default():
            return _dense_forward(q, k, v, sm_scale, causal,
                                  q_block_offset)
        interpret = _interpret_default()
    batch, heads, q_len, head_dim = q.shape
    kv_len = k.shape[2]
    block_q = min(block_q, q_len)
    block_k = min(block_k, kv_len)

    # Pad ragged tails up to block multiples; padded keys are masked in the
    # kernel (kv_actual), padded q rows are sliced away below.
    qr = _pad_seq(q.reshape(batch * heads, q_len, head_dim), block_q)
    kr = _pad_seq(k.reshape(batch * heads, kv_len, head_dim), block_k)
    vr = _pad_seq(v.reshape(batch * heads, kv_len, head_dim), block_k)
    q_pad, kv_pad = qr.shape[1], kr.shape[1]

    out_shape = [
        jax.ShapeDtypeStruct((batch * heads, q_pad, head_dim), q.dtype),
        jax.ShapeDtypeStruct((batch * heads, q_pad, 1), jnp.float32),
    ]
    if kv_pad <= _resident_max_seq():
        o, lse = pl.pallas_call(
            functools.partial(
                _fwd_kernel_resident, sm_scale=sm_scale, causal=causal,
                block_k=block_k, kv_seq_len=kv_pad, kv_actual=kv_len,
                q_block_offset=q_block_offset),
            grid=(batch * heads, q_pad // block_q),
            in_specs=[
                pl.BlockSpec((None, block_q, head_dim),
                             lambda b, i: (b, i, 0)),
                pl.BlockSpec((None, kv_pad, head_dim),
                             lambda b, i: (b, 0, 0)),
                pl.BlockSpec((None, kv_pad, head_dim),
                             lambda b, i: (b, 0, 0)),
            ],
            out_specs=[
                pl.BlockSpec((None, block_q, head_dim),
                             lambda b, i: (b, i, 0)),
                pl.BlockSpec((None, block_q, 1), lambda b, i: (b, i, 0)),
            ],
            out_shape=out_shape,
            interpret=interpret,
        )(qr, kr, vr)
        return (o[:, :q_len].reshape(batch, heads, q_len, head_dim),
                lse[:, :q_len].reshape(batch, heads, q_len))

    grid = (batch * heads, q_pad // block_q, kv_pad // block_k)
    kernel = functools.partial(
        _fwd_kernel, sm_scale=sm_scale, causal=causal, kv_actual=kv_len,
        kv_padded=kv_pad, q_block_offset=q_block_offset)
    # Causal: K blocks past the diagonal are skipped in the kernel
    # (pl.when); clamping their index map to the last live block makes
    # the block index repeat, so Pallas elides the dead cells' DMA too.
    if causal:
        def kv_index(b, i, j):
            hi = ((i + 1) * block_q + q_block_offset - 1) // block_k
            return (b, jnp.minimum(j, jnp.maximum(hi, 0)), 0)
    else:
        def kv_index(b, i, j):
            return (b, j, 0)
    o, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((None, block_q, head_dim),
                         lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((None, block_k, head_dim), kv_index),
            pl.BlockSpec((None, block_k, head_dim), kv_index),
        ],
        out_specs=[
            pl.BlockSpec((None, block_q, head_dim),
                         lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((None, block_q, 1), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=out_shape,
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, head_dim), jnp.float32),
        ],
        interpret=interpret,
    )(qr, kr, vr)
    return (o[:, :q_len].reshape(batch, heads, q_len, head_dim),
            lse[:, :q_len].reshape(batch, heads, q_len))


# ---------------------------------------------------------------------------
# Backward kernels
# ---------------------------------------------------------------------------


def _bwd_p_ds(q, k, v, do, lse, delta, *, sm_scale, q_start, k_start,
              kv_actual, kv_padded, causal, q_block_offset):
    """(p, ds) for one (q_block, k_block) tile — THE backward math,
    shared by all four backward kernels (resident + streaming dKdV/dQ)
    so the short-seq and long-seq paths cannot diverge.
    p = exp(s - lse); fully-masked rows have lse = -inf -> p = 0;
    masked entries underflow exp(MASK - lse) -> 0.

    q/k/v/do arrive in their input dtype and feed the MXU directly (f32
    accumulation); p/ds come out f32 and the callers cast them back to
    the operand dtype at their own dot sites."""
    s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * sm_scale
    s = _apply_mask(s, q_start=q_start, k_start=k_start,
                    kv_actual=kv_actual, kv_padded=kv_padded,
                    causal=causal, q_block_offset=q_block_offset)
    p = jnp.exp(s - jnp.where(jnp.isfinite(lse), lse, 0.0))
    p = jnp.where(jnp.isfinite(lse), p, 0.0)
    dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32)
    ds = p * (dp - delta) * sm_scale
    return p, ds


# Resident backward kernels (short-seq fast path): whole Q (dKdV
# pass) / whole K,V (dQ pass) held in VMEM, in-kernel fori_loop
# walks the blocks.  See _resident_max_seq.
def _bwd_dkdv_kernel_resident(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                     dk_ref, dv_ref, *, sm_scale: float, causal: bool,
                     block_q: int, q_seq_len: int, kv_actual: int,
                     q_block_offset: int):
    """Grid cell (batch*head, k_block): accumulate dK, dV over q blocks."""
    block_k = k_ref.shape[0]
    head_dim = k_ref.shape[1]
    k_idx = pl.program_id(1)
    kv_padded = pl.num_programs(1) * block_k

    k = k_ref[:, :]
    v = v_ref[:, :]
    dk_init = jnp.zeros((block_k, head_dim), jnp.float32)
    dv_init = jnp.zeros((block_k, head_dim), jnp.float32)
    num_q_blocks = pl.cdiv(q_seq_len, block_q)

    def body(qb, carry):
        dk, dv = carry
        q = q_ref[pl.ds(qb * block_q, block_q), :]
        do = do_ref[pl.ds(qb * block_q, block_q), :]
        lse = lse_ref[pl.ds(qb * block_q, block_q), :]
        delta = delta_ref[pl.ds(qb * block_q, block_q), :]
        p, ds = _bwd_p_ds(q, k, v, do, lse, delta, sm_scale=sm_scale,
                          q_start=qb * block_q, k_start=k_idx * block_k,
                          kv_actual=kv_actual, kv_padded=kv_padded,
                          causal=causal, q_block_offset=q_block_offset)
        dv = dv + jnp.dot(p.astype(do.dtype).T, do,
                          preferred_element_type=jnp.float32)
        dk = dk + jnp.dot(ds.astype(q.dtype).T, q,
                          preferred_element_type=jnp.float32)
        return dk, dv

    if causal:
        # q blocks strictly before this k block see none of it.
        lo = jnp.maximum(
            0, (k_idx * block_k - q_block_offset) // block_q)
        lo = jnp.minimum(lo, num_q_blocks)
    else:
        lo = 0
    dk, dv = jax.lax.fori_loop(lo, num_q_blocks, body, (dk_init, dv_init))
    dk_ref[:, :] = dk.astype(dk_ref.dtype)
    dv_ref[:, :] = dv.astype(dv_ref.dtype)


def _bwd_dq_kernel_resident(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                   dq_ref, *, sm_scale: float, causal: bool, block_k: int,
                   kv_seq_len: int, kv_actual: int, q_block_offset: int):
    """Grid cell (batch*head, q_block): accumulate dQ over k blocks."""
    block_q = q_ref.shape[0]
    head_dim = q_ref.shape[1]
    q_idx = pl.program_id(1)

    q = q_ref[:, :]
    do = do_ref[:, :]
    lse = lse_ref[:, :]
    delta = delta_ref[:, :]
    dq_init = jnp.zeros((block_q, head_dim), jnp.float32)
    num_k_blocks = pl.cdiv(kv_seq_len, block_k)

    def body(kb, dq):
        k = k_ref[pl.ds(kb * block_k, block_k), :]
        v = v_ref[pl.ds(kb * block_k, block_k), :]
        _, ds = _bwd_p_ds(q, k, v, do, lse, delta, sm_scale=sm_scale,
                          q_start=q_idx * block_q, k_start=kb * block_k,
                          kv_actual=kv_actual, kv_padded=kv_seq_len,
                          causal=causal, q_block_offset=q_block_offset)
        return dq + jnp.dot(ds.astype(k.dtype), k,
                            preferred_element_type=jnp.float32)

    if causal:
        hi = jnp.minimum(
            num_k_blocks,
            pl.cdiv((q_idx + 1) * block_q + q_block_offset, block_k))
    else:
        hi = num_k_blocks
    dq = jax.lax.fori_loop(0, hi, body, dq_init)
    dq_ref[:, :] = dq.astype(dq_ref.dtype)


def _bwd_dkdv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                     dk_ref, dv_ref, dk_acc, dv_acc, *, sm_scale: float,
                     causal: bool, kv_actual: int, kv_padded: int,
                     q_block_offset: int):
    """Grid cell (batch*head, k_block, q_block): one q-block contribution
    to this k-block's dK/dV, accumulated in f32 VMEM scratch across the
    (sequential, innermost) q dimension.

    Streaming q block-by-block through the grid keeps the kernel's VMEM
    working set O(block) — a whole-q operand would scale with sequence
    length and blow the vmem limit around seq 8K (seen in practice)."""
    block_q = q_ref.shape[0]
    block_k = k_ref.shape[0]
    k_idx = pl.program_id(1)
    q_idx = pl.program_id(2)
    num_q_blocks = pl.num_programs(2)

    @pl.when(q_idx == 0)
    def _init():
        dk_acc[:, :] = jnp.zeros_like(dk_acc)
        dv_acc[:, :] = jnp.zeros_like(dv_acc)

    # Causal: q blocks strictly before this k block see none of it.
    live = True
    if causal:
        live = ((q_idx + 1) * block_q + q_block_offset
                > k_idx * block_k)

    @pl.when(live)
    def _accumulate():
        k = k_ref[:, :]
        v = v_ref[:, :]
        q = q_ref[:, :]
        do = do_ref[:, :]
        lse = lse_ref[:, :]
        delta = delta_ref[:, :]
        p, ds = _bwd_p_ds(q, k, v, do, lse, delta, sm_scale=sm_scale,
                          q_start=q_idx * block_q,
                          k_start=k_idx * block_k, kv_actual=kv_actual,
                          kv_padded=kv_padded, causal=causal,
                          q_block_offset=q_block_offset)
        dv_acc[:, :] += jnp.dot(p.astype(do.dtype).T, do,
                                preferred_element_type=jnp.float32)
        dk_acc[:, :] += jnp.dot(ds.astype(q.dtype).T, q,
                                preferred_element_type=jnp.float32)

    @pl.when(q_idx == num_q_blocks - 1)
    def _emit():
        dk_ref[:, :] = dk_acc[:, :].astype(dk_ref.dtype)
        dv_ref[:, :] = dv_acc[:, :].astype(dv_ref.dtype)


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                   dq_ref, dq_acc, *, sm_scale: float, causal: bool,
                   kv_actual: int, kv_padded: int, q_block_offset: int):
    """Grid cell (batch*head, q_block, k_block): one k-block contribution
    to this q-block's dQ, accumulated in f32 VMEM scratch across the
    (sequential, innermost) k dimension — same streaming rationale as
    :func:`_bwd_dkdv_kernel`."""
    block_q = q_ref.shape[0]
    block_k = k_ref.shape[0]
    q_idx = pl.program_id(1)
    k_idx = pl.program_id(2)
    num_k_blocks = pl.num_programs(2)

    @pl.when(k_idx == 0)
    def _init():
        dq_acc[:, :] = jnp.zeros_like(dq_acc)

    live = True
    if causal:
        live = (k_idx * block_k
                < (q_idx + 1) * block_q + q_block_offset)

    @pl.when(live)
    def _accumulate():
        q = q_ref[:, :]
        do = do_ref[:, :]
        lse = lse_ref[:, :]
        delta = delta_ref[:, :]
        k = k_ref[:, :]
        v = v_ref[:, :]
        _, ds = _bwd_p_ds(q, k, v, do, lse, delta, sm_scale=sm_scale,
                          q_start=q_idx * block_q,
                          k_start=k_idx * block_k, kv_actual=kv_actual,
                          kv_padded=kv_padded, causal=causal,
                          q_block_offset=q_block_offset)
        dq_acc[:, :] += jnp.dot(ds.astype(k.dtype), k,
                                preferred_element_type=jnp.float32)

    @pl.when(k_idx == num_k_blocks - 1)
    def _emit():
        dq_ref[:, :] = dq_acc[:, :].astype(dq_ref.dtype)


def _flash_backward_resident(q, k, v, qr, kr, vr, dor, lser, deltar, *,
                             sm_scale, causal, bq, bk, q_block_offset,
                             interpret):
    """Short-seq backward: 2D grids with the streamed side resident in
    VMEM (see _resident_max_seq)."""
    batch, heads, q_len, head_dim = q.shape
    kv_len = k.shape[2]
    q_pad, kv_pad = qr.shape[1], kr.shape[1]

    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkdv_kernel_resident, sm_scale=sm_scale,
                          causal=causal, block_q=bq, q_seq_len=q_pad,
                          kv_actual=kv_len,
                          q_block_offset=q_block_offset),
        grid=(batch * heads, kv_pad // bk),
        in_specs=[
            pl.BlockSpec((None, q_pad, head_dim), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((None, bk, head_dim), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, bk, head_dim), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, q_pad, head_dim), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((None, q_pad, 1), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((None, q_pad, 1), lambda b, i: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, bk, head_dim), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, bk, head_dim), lambda b, i: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((batch * heads, kv_pad, head_dim), k.dtype),
            jax.ShapeDtypeStruct((batch * heads, kv_pad, head_dim), v.dtype),
        ],
        interpret=interpret,
    )(qr, kr, vr, dor, lser, deltar)

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel_resident, sm_scale=sm_scale,
                          causal=causal, block_k=bk, kv_seq_len=kv_pad,
                          kv_actual=kv_len,
                          q_block_offset=q_block_offset),
        grid=(batch * heads, q_pad // bq),
        in_specs=[
            pl.BlockSpec((None, bq, head_dim), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, kv_pad, head_dim), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((None, kv_pad, head_dim), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((None, bq, head_dim), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, bq, 1), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, bq, 1), lambda b, i: (b, i, 0)),
        ],
        out_specs=pl.BlockSpec((None, bq, head_dim),
                               lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((batch * heads, q_pad, head_dim),
                                       q.dtype),
        interpret=interpret,
    )(qr, kr, vr, dor, lser, deltar)

    rs = lambda x, n: x[:, :n].reshape(batch, heads, n, head_dim)
    return rs(dq, q_len), rs(dk, kv_len), rs(dv, kv_len)


def _flash_backward(res, g, *, sm_scale, causal, block_q, block_k,
                    q_block_offset, interpret):
    if interpret is None:
        if _dense_default():
            return _dense_backward(res, g, sm_scale=sm_scale,
                                   causal=causal,
                                   q_block_offset=q_block_offset)
        interpret = _interpret_default()
    q, k, v, o, lse = res
    batch, heads, q_len, head_dim = q.shape
    kv_len = k.shape[2]
    bq = min(block_q, q_len)
    bk = min(block_k, kv_len)

    do = g.astype(q.dtype)  # native dtype into the kernels' MXU dots
    delta = jnp.sum(g.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1)  # [B,H,Sq], f32


    flat = lambda x: x.reshape(batch * heads, x.shape[2], -1)
    # Pad tails to block multiples.  Padded q rows carry lse = -inf so
    # their p (and thus every contribution) is exactly zero; padded keys
    # are masked via kv_actual.
    qr = _pad_seq(flat(q), bq)
    kr = _pad_seq(flat(k), bk)
    vr = _pad_seq(flat(v), bk)
    dor = _pad_seq(flat(do), bq)
    lser = flat(lse[..., None])
    pad_q = qr.shape[1] - q_len
    if pad_q:
        lser = jnp.pad(lser, ((0, 0), (0, pad_q), (0, 0)),
                       constant_values=-jnp.inf)
    deltar = _pad_seq(flat(delta[..., None]), bq)
    q_pad, kv_pad = qr.shape[1], kr.shape[1]

    if max(q_pad, kv_pad) <= _resident_max_seq():
        return _flash_backward_resident(
            q, k, v, qr, kr, vr, dor, lser, deltar, sm_scale=sm_scale,
            causal=causal, bq=bq, bk=bk, q_block_offset=q_block_offset,
            interpret=interpret)

    n_qb = q_pad // bq
    # Causal DMA elision, as in the forward: dkdv's dead cells are q
    # blocks before the diagonal (clamp up); dq's are K blocks past it
    # (clamp down).
    if causal:
        def q_index(b, i, j):
            lo = (i * bk - q_block_offset) // bq
            return (b, jnp.maximum(j, jnp.clip(lo, 0, n_qb - 1)), 0)

        def kv_index(b, i, j):
            hi = ((i + 1) * bq + q_block_offset - 1) // bk
            return (b, jnp.minimum(j, jnp.maximum(hi, 0)), 0)
    else:
        def q_index(b, i, j):
            return (b, j, 0)

        kv_index = q_index

    dkdv = pl.pallas_call(
        functools.partial(_bwd_dkdv_kernel, sm_scale=sm_scale,
                          causal=causal, kv_actual=kv_len,
                          kv_padded=kv_pad,
                          q_block_offset=q_block_offset),
        grid=(batch * heads, kv_pad // bk, n_qb),
        in_specs=[
            pl.BlockSpec((None, bq, head_dim), q_index),
            pl.BlockSpec((None, bk, head_dim), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((None, bk, head_dim), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((None, bq, head_dim), q_index),
            pl.BlockSpec((None, bq, 1), q_index),
            pl.BlockSpec((None, bq, 1), q_index),
        ],
        out_specs=[
            pl.BlockSpec((None, bk, head_dim), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((None, bk, head_dim), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((batch * heads, kv_pad, head_dim), k.dtype),
            jax.ShapeDtypeStruct((batch * heads, kv_pad, head_dim), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, head_dim), jnp.float32),
            pltpu.VMEM((bk, head_dim), jnp.float32),
        ],
        interpret=interpret,
    )(qr, kr, vr, dor, lser, deltar)
    dk, dv = dkdv

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, sm_scale=sm_scale, causal=causal,
                          kv_actual=kv_len, kv_padded=kv_pad,
                          q_block_offset=q_block_offset),
        grid=(batch * heads, q_pad // bq, kv_pad // bk),
        in_specs=[
            pl.BlockSpec((None, bq, head_dim), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((None, bk, head_dim), kv_index),
            pl.BlockSpec((None, bk, head_dim), kv_index),
            pl.BlockSpec((None, bq, head_dim), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((None, bq, 1), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((None, bq, 1), lambda b, i, j: (b, i, 0)),
        ],
        out_specs=pl.BlockSpec((None, bq, head_dim),
                               lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((batch * heads, q_pad, head_dim),
                                       q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, head_dim), jnp.float32)],
        interpret=interpret,
    )(qr, kr, vr, dor, lser, deltar)

    rs = lambda x, n: x[:, :n].reshape(batch, heads, n, head_dim)
    return rs(dq, q_len), rs(dk, kv_len), rs(dv, kv_len)


# ---------------------------------------------------------------------------
# Public entry points
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def _flash(q, k, v, sm_scale, causal, block_q, block_k, q_block_offset,
           interpret):
    o, _ = _flash_forward(q, k, v, sm_scale, causal, block_q, block_k,
                          q_block_offset, interpret)
    return o


def _flash_fwd(q, k, v, sm_scale, causal, block_q, block_k, q_block_offset,
               interpret):
    o, lse = _flash_forward(q, k, v, sm_scale, causal, block_q, block_k,
                            q_block_offset, interpret)
    return o, (q, k, v, o, lse)


def _flash_bwd(sm_scale, causal, block_q, block_k, q_block_offset,
               interpret, res, g):
    return _flash_backward(res, g, sm_scale=sm_scale, causal=causal,
                           block_q=block_q, block_k=block_k,
                           q_block_offset=q_block_offset,
                           interpret=interpret)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q, k, v, *, causal: bool = False,
                    sm_scale: Optional[float] = None, block_q: int = 128,
                    block_k: int = 128, q_block_offset: int = 0,
                    interpret: Optional[bool] = None):
    """Memory-linear attention, differentiable, Pallas-TPU compiled.

    Args:
      q, k, v: ``[batch, heads, seq, head_dim]`` (q_len may differ from
        kv_len).
      causal: apply a lower-triangular mask; future K blocks are skipped
        entirely (compute proportional to the unmasked area).
      sm_scale: softmax temperature; default ``1/sqrt(head_dim)``.
      q_block_offset: global position of q's first row relative to k's
        first row, for sequence-sharded callers (ring attention).
      interpret: True forces Pallas interpreter mode; None (default)
        compiles the kernel on TPU and uses the dense-jnp fallback on
        other backends (e.g. the CPU test mesh).
    """
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    # interpret stays None here so _flash_forward/_flash_backward can pick
    # the dense fallback on non-TPU backends.
    return _flash(q, k, v, float(sm_scale), bool(causal), int(block_q),
                  int(block_k), int(q_block_offset),
                  None if interpret is None else bool(interpret))


def flash_attention_with_lse(q, k, v, *, causal: bool = False,
                             sm_scale: Optional[float] = None,
                             block_q: int = 128, block_k: int = 128,
                             q_block_offset: int = 0,
                             interpret: Optional[bool] = None):
    """Forward-only variant returning ``(out, lse)`` for callers that merge
    partial attention across sequence shards (ring attention's online
    softmax across devices)."""
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    return _flash_forward(q, k, v, float(sm_scale), bool(causal),
                          int(block_q), int(block_k), int(q_block_offset),
                          None if interpret is None else bool(interpret))


def mha_reference(q, k, v, *, causal: bool = False,
                  sm_scale: Optional[float] = None,
                  q_block_offset: int = 0):
    """O(seq²) reference attention (tests compare the kernel against it).
    One implementation with :func:`_dense_forward` so the production
    fallback and the test reference cannot diverge."""
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    return _dense_forward(q, k, v, sm_scale, causal, q_block_offset)[0]
