"""Gradient compression for the allreduce wire (≙ hvd.Compression).

The reference snapshot (v0.13.0) predates Horovod's compression API; this
implements the contract Horovod later standardized (horovod.torch
``Compression.fp16``) *and* extends it with true low-bit quantized
reduction (cf. the original paper's fp16 compression, arXiv:1802.05799,
and EQuARX's in-XLA quantized allreduce, arXiv:2506.17615):

* **Cast compressors** (``fp16``/``bf16``): gradients are cast down
  before the collective and restored after, halving the bytes every
  allreduce moves.  Safe to wrap around a sum (casting commutes with
  addition up to rounding).
* **Quantized wire formats** (``int8``/``int4``): block-wise scaled
  integer codebooks with stochastic rounding and error-feedback
  residuals.  A sum of int8 *codes* is meaningless, so these cannot
  wrap a collective the way cast compressors do — they are compiled
  INTO the fused pack→reduce→unpack megakernels
  (ops/megakernel.py) as a two-phase exchange:

      phase 1   each replica splits its local vector into n chunks,
                quantizes block-wise, and all_to_alls the *wire* payload
                (int8 codes / packed int4 nibbles + bfloat16 scales);
      reduce    each replica dequantizes the n received chunks and
                accumulates its chunk of the sum in float32;
      phase 2   the reduced chunk is re-quantized and all_gathered in
                wire format, then dequantized everywhere.

  Every byte crossing a link is in wire format — the bandwidth shape of
  a ring allreduce with ``bits/8 + 2/block`` bytes per element instead
  of 4.  Quantization error is handled twice over: stochastic rounding
  makes each step unbiased, and the **error-feedback residual** (the
  difference between what a replica meant to send and what its peers
  decoded) is carried by the executor and added to the next step's
  contribution, so the error telescopes instead of accumulating
  (SNIPPETS.md §EF-SGD lineage).  The residual store is real HBM — one
  flat full-precision buffer per fusion group, held across steps by
  ``ops/megakernel.py`` for the fused AND eager-reference paths alike —
  and is accounted by the hvd-mem device-memory ledger as
  ``megakernel.residuals`` (docs/memory.md): its absolute byte size is
  re-synced on every store/take/flush, so a name churn that
  re-partitions groups and mints fresh residuals shows up as ledger
  growth ``hvd.MemoryWatch`` names.

Per-tensor / per-process-set selection rides a small policy registry
(:func:`set_compression`): regex rules map tensor names to compressor
names (embeddings → int8, layernorm/scalars → none), with per-set
overrides; ``HVD_TPU_COMPRESSION`` sets the process-wide default.

TPU note: prefer :data:`Compression.bf16` for casts — bfloat16 keeps
float32's exponent range and is the MXU-native dtype.  ``fp16`` is
provided for drop-in parity with GPU Horovod scripts: every
``DistributedOptimizer`` (the core optax wrapper and the torch/keras/
tensorflow frontends) and the torch/tf ``allreduce`` functions accept
the same ``compression=`` kwarg.

Usage (core JAX surface)::

    opt = hvd.DistributedOptimizer(optax.sgd(0.01),
                                   compression=hvd.Compression.bf16)

or explicitly around a single collective::

    compressor = hvd.Compression.bf16
    t, ctx = compressor.compress(tensor)
    out = compressor.decompress(hvd.allreduce(t, average=True), ctx)

Quantized reduction (wire-level; see docs/tensor-fusion.md)::

    hvd.set_compression(default="int8",
                        rules=[(r".*(bias|scale|ln)", "none")])
    # or: HVD_TPU_COMPRESSION=int8
"""

from __future__ import annotations

import os
import re
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

__all__ = ["Compression", "Compressor", "NoneCompressor", "FP16Compressor",
           "BF16Compressor", "Int8Compressor", "Int4Compressor",
           "WireFormat", "set_compression", "get_compression",
           "CompressionPolicy", "resolve", "wire_format_for",
           "reference_allreduce"]

# Env contract (docs/performance.md, docs/tensor-fusion.md).  All of
# these change the compiled SPMD program and MUST be uniform across
# ranks — core/state.init validates them and the control-plane
# handshake cross-checks the fingerprint (env_fingerprint()).
DEFAULT_ENV = "HVD_TPU_COMPRESSION"          # default wire compressor
BLOCK_ENV = "HVD_TPU_QUANT_BLOCK"            # scaling-block elements
ROUNDING_ENV = "HVD_TPU_QUANT_ROUNDING"      # stochastic | nearest
EF_ENV = "HVD_TPU_QUANT_ERROR_FEEDBACK"      # 1 (default) | 0
SEED_ENV = "HVD_TPU_QUANT_SEED"              # stochastic-rounding seed
MIN_ELEMS_ENV = "HVD_TPU_QUANT_MIN_ELEMS"    # quantization floor

_DEFAULT_BLOCK = 256
_DEFAULT_MIN_ELEMS = 16


class Compressor:
    """Interface: ``compress(tensor) -> (tensor, ctx)`` before the wire,
    ``decompress(tensor, ctx)`` after.  Pure casts — safe both inside jit
    (the static psum path) and on eager numpy-backed arrays."""

    @staticmethod
    def compress(tensor):
        raise NotImplementedError

    @staticmethod
    def decompress(tensor, ctx):
        raise NotImplementedError


class NoneCompressor(Compressor):
    """Identity (≙ Horovod's Compression.none)."""

    @staticmethod
    def compress(tensor):
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor


class _CastCompressor(Compressor):
    wire_dtype: jnp.dtype = None  # set by subclasses

    @classmethod
    def compress(cls, tensor):
        tensor = jnp.asarray(tensor)
        dtype = tensor.dtype
        # Only floating inputs wider than the wire dtype are compressed;
        # integer/bool tensors and already-narrow floats pass through
        # (casting int64 indices to fp16 would corrupt them).
        if (jnp.issubdtype(dtype, jnp.floating)
                and jnp.dtype(dtype).itemsize
                > jnp.dtype(cls.wire_dtype).itemsize):
            return tensor.astype(cls.wire_dtype), dtype
        return tensor, None

    @classmethod
    def decompress(cls, tensor, ctx):
        if ctx is None:
            return tensor
        return jnp.asarray(tensor).astype(ctx)


class FP16Compressor(_CastCompressor):
    """float16 wire dtype (≙ Horovod's Compression.fp16).  Mind the 5-bit
    exponent: loss-scale or prefer bf16 on TPU."""

    wire_dtype = jnp.float16


class BF16Compressor(_CastCompressor):
    """bfloat16 wire dtype — float32 exponent range, MXU-native; the
    recommended cast compressor on TPU."""

    wire_dtype = jnp.bfloat16


class _QuantCompressor(Compressor):
    """Block-wise integer codebook (int8/int4).

    A quantized code stream cannot be summed, so this class does NOT
    implement the wrap-a-collective ``compress``/``decompress`` contract
    — attempting to raises with the correct API.  Select quantized
    reduction through :func:`set_compression` / ``HVD_TPU_COMPRESSION``
    instead; the megakernel executor compiles the quantize → exchange →
    dequantize pipeline into the fused reduction.  The eager
    :meth:`quantize`/:meth:`dequantize` pair is the standalone codec
    (storage, allgather-style exchanges, tests)."""

    bits: int = 0  # set by subclasses

    @classmethod
    def compress(cls, tensor):
        raise ValueError(
            f"{cls.__name__} is a wire-level quantized reduction format: "
            f"int codes cannot wrap a sum collective the way fp16/bf16 "
            f"casts do.  Select it with hvd.set_compression(default="
            f"'int{cls.bits}', ...) or HVD_TPU_COMPRESSION=int{cls.bits}; "
            f"the fused executor (ops/megakernel.py) compiles the "
            f"quantization into the reduction itself.")

    decompress = compress

    @classmethod
    def quantize(cls, tensor, *, key=None):
        """Standalone block-wise quantization of ``tensor`` →
        ``(wire, ctx)`` where ``wire`` is the int8 code array (packed
        nibbles for int4) and ctx carries scales/shape/dtype for
        :meth:`dequantize`.  Deterministic (round-to-nearest) unless a
        PRNG ``key`` requests stochastic rounding."""
        fmt = wire_format(cls.__name__.replace("Compressor", "").lower())
        t = jnp.asarray(tensor)
        flat = t.reshape(-1)
        pad = (-flat.shape[0]) % fmt.block
        if pad:
            flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
        use = fmt if key is not None else \
            WireFormat(kind="quant", name=fmt.name, bits=fmt.bits,
                       block=fmt.block, stochastic=False,
                       error_feedback=False)
        q, s = quantize_blocks(flat[None], use, key)
        return (q[0], s[0]), (t.dtype, t.shape, use)

    @classmethod
    def dequantize(cls, wire, ctx):
        dtype, shape, fmt = ctx
        q, s = wire
        out = dequantize_blocks(q[None], s[None], fmt)[0]
        n = 1
        for d in shape:
            n *= d
        return out[:n].reshape(shape).astype(dtype)


class Int8Compressor(_QuantCompressor):
    """8-bit block-scaled codebook: ~3.97x fewer wire bytes than fp32
    (1 B/element + 2 B bfloat16 scale per block)."""

    bits = 8


class Int4Compressor(_QuantCompressor):
    """4-bit block-scaled codebook (two codes per wire byte): ~7.9x
    fewer wire bytes than fp32.  Needs error feedback for training
    parity — see docs/performance.md for the convergence caveats."""

    bits = 4


class Compression:
    """Namespace matching Horovod's ``hvd.Compression`` surface."""

    none = NoneCompressor
    fp16 = FP16Compressor
    bf16 = BF16Compressor
    int8 = Int8Compressor
    int4 = Int4Compressor


def valid_names() -> Tuple[str, ...]:
    """Every name :func:`resolve` accepts (the registry's vocabulary)."""
    return tuple(
        n for n in vars(Compression)
        if not n.startswith("_")
        and isinstance(getattr(Compression, n), type)
        and issubclass(getattr(Compression, n), Compressor))


def resolve(name: str):
    """Compressor by env-style name — the lookup behind
    ``HVD_TPU_COMPRESSION`` / ``HVD_TPU_DCN_COMPRESS`` /
    ``HVD_TPU_ICI_COMPRESS`` and any other string-keyed configuration
    surface.  A typo raises naming every valid choice."""
    key = str(name).strip().lower()
    comp = getattr(Compression, key, None)
    if not (isinstance(comp, type) and issubclass(comp, Compressor)):
        raise ValueError(
            f"unknown compressor {name!r}: expected one of "
            f"{', '.join(sorted(valid_names()))}")
    return comp


def wire_dtype_for(name: str, dtype):
    """The narrowed wire dtype ``name`` implies for tensors of
    ``dtype``, or ``None`` when cast compression does not apply
    (identity/quantized compressors, non-float payloads, already-narrow
    floats) — the same applicability rule as
    :meth:`_CastCompressor.compress`, decidable from the dtype alone so
    jitted kernels can fold the casts at trace time."""
    comp = resolve(name)
    wire = getattr(comp, "wire_dtype", None)
    if wire is None:
        return None
    if (jnp.issubdtype(jnp.dtype(dtype), jnp.floating)
            and jnp.dtype(dtype).itemsize > jnp.dtype(wire).itemsize):
        return wire
    return None


# ---------------------------------------------------------------------------
# Wire formats (the executor's static view of one compressor choice)
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class WireFormat:
    """Everything about one compressor choice that changes the traced
    program — hashable, part of the megakernel GroupSpec cache key and
    of the fusion-plan digest the executable is recorded under."""

    kind: str                  # "cast" | "quant"
    name: str                  # registry name ("bf16", "int8", ...)
    bits: int                  # wire bits per element (16 / 8 / 4)
    wire_dtype: str = ""       # cast only: "bfloat16" / "float16"
    block: int = 0             # quant only: scaling-block elements
    stochastic: bool = True    # quant only: stochastic rounding
    error_feedback: bool = True  # quant only: EF residuals


def quant_block() -> int:
    return max(2, int(os.environ.get(BLOCK_ENV, str(_DEFAULT_BLOCK))))


def quant_seed() -> int:
    return int(os.environ.get(SEED_ENV, "0") or 0)


def _rounding() -> str:
    mode = os.environ.get(ROUNDING_ENV, "stochastic").strip().lower()
    if mode not in ("stochastic", "nearest"):
        raise ValueError(
            f"{ROUNDING_ENV}={mode!r}: expected stochastic or nearest")
    return mode


def wire_format(name: str) -> Optional[WireFormat]:
    """The :class:`WireFormat` of compressor ``name`` (dtype-independent
    form; ``None`` for the identity compressor)."""
    comp = resolve(name)
    if comp is NoneCompressor:
        return None
    cast = getattr(comp, "wire_dtype", None)
    if cast is not None:
        return WireFormat(kind="cast", name=name.strip().lower(),
                          bits=8 * jnp.dtype(cast).itemsize,
                          wire_dtype=jnp.dtype(cast).name,
                          stochastic=False, error_feedback=False)
    return WireFormat(
        kind="quant", name=name.strip().lower(), bits=comp.bits,
        block=quant_block(), stochastic=_rounding() == "stochastic",
        error_feedback=os.environ.get(EF_ENV, "1") != "0")


def wire_format_for(name: str, dtype, numel: int) -> Optional[WireFormat]:
    """``wire_format`` gated by applicability: compression applies only
    to floating payloads wider than the wire format, and quantization
    additionally skips tiny tensors (scalars, layernorm vectors —
    ``HVD_TPU_QUANT_MIN_ELEMS``) where a per-block scale would cost more
    than it saves."""
    fmt = wire_format(name)
    if fmt is None:
        return None
    dt = jnp.dtype(dtype)
    if not jnp.issubdtype(dt, jnp.floating):
        return None
    if fmt.kind == "cast":
        if dt.itemsize * 8 <= fmt.bits:
            return None
        return fmt
    floor = int(os.environ.get(MIN_ELEMS_ENV, str(_DEFAULT_MIN_ELEMS)))
    if numel < max(floor, 1):
        return None
    return fmt


# ---------------------------------------------------------------------------
# Per-tensor / per-process-set selection policy
# ---------------------------------------------------------------------------

class CompressionPolicy:
    """Name-pattern → compressor registry (the per-tensor selection
    surface).  Precedence: first matching rule > the process set's
    override > the default.  All fields are resolved at construction so
    a typo fails at ``set_compression`` time with the full name list."""

    def __init__(self, default: Optional[str] = None,
                 rules: Sequence[Tuple[str, str]] = (),
                 process_sets: Optional[Dict[int, str]] = None):
        self.default = (default.strip().lower()
                        if default is not None else None)
        if self.default is not None:
            resolve(self.default)
        self.rules: List[Tuple[re.Pattern, str]] = []
        for pattern, name in rules or ():
            resolve(name)
            self.rules.append((re.compile(pattern), name.strip().lower()))
        self.process_sets = {int(k): v.strip().lower()
                             for k, v in (process_sets or {}).items()}
        for name in self.process_sets.values():
            resolve(name)

    def name_for(self, tensor_name: str, process_set_id: int = 0) -> str:
        for pattern, name in self.rules:
            if pattern.search(tensor_name):
                return name
        if process_set_id in self.process_sets:
            return self.process_sets[process_set_id]
        if self.default is not None:
            return self.default
        return os.environ.get(DEFAULT_ENV, "none")


_policy: Optional[CompressionPolicy] = None


def set_compression(default: Optional[str] = None,
                    rules: Optional[Sequence[Tuple[str, str]]] = None,
                    process_sets: Optional[Dict[int, str]] = None) -> None:
    """Install the process-wide wire-compression policy for the dynamic
    collective path (``None``/no args restores the env default).

    MUST be called identically on every rank — like the env knobs, the
    policy selects the compiled SPMD program.  Installing a policy
    flushes the executor's compiled kernels and error-feedback
    residuals (a residual accumulated under one codebook is meaningless
    under another)."""
    global _policy
    if default is None and not rules and not process_sets:
        _policy = None
    else:
        _policy = CompressionPolicy(default, rules or (), process_sets)
    from . import megakernel as _megakernel

    _megakernel.flush("compression policy change")


def get_compression() -> Optional[CompressionPolicy]:
    return _policy


def policy_name_for(tensor_name: str, process_set_id: int = 0) -> str:
    """The effective compressor NAME for one tensor (rules > set
    override > default > env)."""
    p = _policy
    if p is not None:
        return p.name_for(tensor_name, process_set_id)
    return os.environ.get(DEFAULT_ENV, "none")


def policy_format_for(tensor_name: str, process_set_id: int,
                      dtype, numel: int) -> Optional[WireFormat]:
    """Policy lookup + applicability gate in one step (what the
    executor partitions fusion groups by)."""
    return wire_format_for(policy_name_for(tensor_name, process_set_id),
                           dtype, numel)


# ---------------------------------------------------------------------------
# Block-wise quantization primitives (trace-safe jnp; shared verbatim by
# the megakernel bodies and the eager reference so the two are bitwise
# comparable)
# ---------------------------------------------------------------------------

def _levels(bits: int) -> int:
    return (1 << (bits - 1)) - 1  # 127 for int8, 7 for int4


def pack_int4(q):
    """Pack int8 values in [-7, 7] into nibbles: two codes per wire
    byte, even/odd interleaved (last dim must be even)."""
    u = (q.astype(jnp.int16) + 8).astype(jnp.uint8)
    return (u[..., 0::2] | (u[..., 1::2] << 4)).astype(jnp.uint8)


def unpack_int4(p):
    lo = (p & 0xF).astype(jnp.int8) - 8
    hi = (p >> 4).astype(jnp.int8) - 8
    return jnp.stack([lo, hi], axis=-1).reshape(
        p.shape[:-1] + (p.shape[-1] * 2,))


def _dither(key, shape):
    """The stochastic-rounding dither: an 8-bit discrete uniform on
    {0, 1/256, ..., 255/256}.  256 rounding levels bias an element by
    at most 2^-9 of a quantization step — far below the codebooks'
    resolution — while costing a quarter of a float32 uniform's
    threefry work (the dominant quantization cost on the CPU bench)."""
    return (jax.random.bits(key, shape, jnp.uint8)
            .astype(jnp.float32) * jnp.float32(1.0 / 256.0))


def _pow2_scale(amax, bits: int):
    """The smallest power of two ``s`` with ``amax <= levels * s``,
    computed with INTEGER exponent arithmetic on the float bits.

    Power-of-two scales are the load-bearing determinism choice: every
    multiply/divide by the scale is exact, the bfloat16 wire cast is
    exact, and — because no float rounding is involved anywhere in the
    scale path — no XLA algebraic rewrite (constant-division strength
    reduction, convert folding, ...) can produce different bits in
    different surrounding programs.  A float formulation (amax/levels)
    measurably diverged between the fused kernel and the eager
    reference compilation.  Cost: at most one extra bit of
    quantization step vs the optimal scale, which stochastic rounding
    and error feedback absorb (docs/tensor-fusion.md)."""
    a = jax.lax.bitcast_convert_type(amax, jnp.uint32)
    E = (a >> 23).astype(jnp.int32) - 127
    m_field = (a & jnp.uint32(0x7FFFFF)).astype(jnp.int32)
    if bits == 8:
        # levels=127: 127*2^(E-6) covers mantissas up to 1.984375.
        base, thresh = 6, int(0.984375 * (1 << 23))
    else:
        # levels=7: 7*2^(E-2) covers mantissas up to 1.75.
        base, thresh = 2, int(0.75 * (1 << 23))
    p = E - base + jnp.where(m_field > thresh, 1, 0)
    pe = jnp.clip(p + 127, 1, 254).astype(jnp.uint32)
    scale = jax.lax.bitcast_convert_type(pe << 23, jnp.float32)
    return jnp.where(amax > 0, scale, jnp.float32(0.0))


def quantize_blocks(rows, fmt: WireFormat, key=None):
    """Block-wise quantize ``rows[..., m]`` (m % fmt.block == 0) →
    ``(wire, scales)``: int8 codes (packed nibbles for int4) plus one
    bfloat16 power-of-two scale per block (:func:`_pow2_scale`) —
    exactly the bytes a peer needs to decode.  Stochastic rounding
    (floor(x + u), u~U[0,1)) keeps each element unbiased; ``key`` must
    be supplied when fmt.stochastic."""
    lead, m = rows.shape[:-1], rows.shape[-1]
    lv = float(_levels(fmt.bits))
    b = rows.astype(jnp.float32).reshape(lead + (m // fmt.block, fmt.block))
    scale = _pow2_scale(jnp.max(jnp.abs(b), axis=-1), fmt.bits)
    x = b / jnp.where(scale > 0, scale, jnp.float32(1.0))[..., None]
    if fmt.stochastic:
        x = jnp.floor(x + _dither(key, x.shape))
    else:
        # floor(x + 1/2) (round-half-up), not round-to-nearest-even:
        # bitwise-deterministic like RNE but an order of magnitude
        # cheaper on the CPU backend's scalarized round lowering.
        x = jnp.floor(x + jnp.float32(0.5))
    q = jnp.clip(x, -lv, lv).astype(jnp.int8).reshape(lead + (m,))
    if fmt.bits == 4:
        q = pack_int4(q)
    return q, scale.astype(jnp.bfloat16)


def dequantize_blocks(wire, scales, fmt: WireFormat):
    """Inverse of :func:`quantize_blocks` in float32 (the accumulation
    dtype): decode codes, multiply by the block scales."""
    q = unpack_int4(wire) if fmt.bits == 4 else wire
    lead, m = q.shape[:-1], q.shape[-1]
    b = q.astype(jnp.float32).reshape(lead + (m // fmt.block, fmt.block))
    out = b * scales.astype(jnp.float32)[..., None]
    return out.reshape(lead + (m,))


def wire_bytes_per_chunk(m: int, fmt: WireFormat) -> int:
    """Bytes one m-element chunk occupies on the wire: packed codes
    plus 2-byte bfloat16 block scales — the exact frame
    :func:`wire_pack` builds."""
    return m * fmt.bits // 8 + (m // fmt.block) * 2


def wire_pack(q, s, fmt: WireFormat):
    """Frame codes + scales as ONE uint8 wire buffer per chunk row —
    one collective moves the whole frame (codes and scales in two
    separate exchanges would double the per-collective latency)."""
    qb = q if fmt.bits == 4 else jax.lax.bitcast_convert_type(q, jnp.uint8)
    sb = jax.lax.bitcast_convert_type(s, jnp.uint8).reshape(
        s.shape[:-1] + (2 * s.shape[-1],))
    return jnp.concatenate([qb, sb], axis=-1)


def wire_unpack(w, m: int, fmt: WireFormat):
    """Split a :func:`wire_pack` frame back into ``(codes, scales)``
    for an m-element chunk."""
    q_len = m * fmt.bits // 8
    n_blocks = m // fmt.block
    qb = w[..., :q_len]
    q = qb if fmt.bits == 4 else jax.lax.bitcast_convert_type(qb, jnp.int8)
    sb = w[..., q_len:q_len + 2 * n_blocks]
    s = jax.lax.bitcast_convert_type(
        sb.reshape(sb.shape[:-1] + (n_blocks, 2)), jnp.bfloat16)
    return q, s


def step_key(seed, tick):
    """The per-step PRNG root: every stochastic-rounding draw of one
    fused launch descends from fold_in(PRNGKey(seed), tick), so a fixed
    seed + the executor's per-group tick give bitwise-reproducible
    noise (tests/test_megakernel.py)."""
    return jax.random.fold_in(jax.random.PRNGKey(seed), tick)


def _noise_key(key, tag: int, pos):
    """Leg/participant key derivation: ``tag`` separates phases/legs,
    ``pos`` decorrelates participants (may be a traced axis index)."""
    return jax.random.fold_in(jax.random.fold_in(key, tag), pos)


def padded_length(T: int, n: int, block: int) -> int:
    """T rounded up so each of the n exchange chunks is a whole number
    of scaling blocks."""
    unit = n * block
    return -(-T // unit) * unit


def ordered_sum(rows):
    """Accumulate ``rows[0] + rows[1] + ...`` as an explicit sequential
    chain instead of ``jnp.sum(axis=0)``: XLA may vectorize a reduce
    with a different float association per surrounding program, and the
    megakernel↔reference BITWISE contract needs the exact same addition
    order in both compilations (n is small and static — the chain costs
    the same n−1 adds)."""
    acc = rows[0]
    for i in range(1, rows.shape[0]):
        acc = acc + rows[i]
    return acc


# ---------------------------------------------------------------------------
# The quantized reduction itself
# ---------------------------------------------------------------------------
# Two formulations of the same math:
#   * quantized_reduce_collective — lax collectives, runs INSIDE a
#     shard_map megakernel body (one XLA program per fusion group);
#   * reference_allreduce — pure eager jnp over the stacked rows, the
#     specification the kernel is tested bitwise against and the eager
#     executor fallback when HVD_TPU_MEGAKERNEL=0.
# Both call the exact helpers above in the exact same order.

def quantized_reduce_collective(vin, fmt: WireFormat, key, *, axis,
                                n: int, my_chunk, noise_pos,
                                groups=None, error_feedback=False,
                                phase2_feedback=False):
    """Two-phase quantized allreduce of the local vector ``vin`` [Tp]
    (pre-padded: Tp % (n * fmt.block) == 0) over ``axis`` (optionally
    ``axis_index_groups``-scoped).  Returns ``(reduced [Tp] float32,
    new_residual [Tp] vin.dtype | None)``."""
    dtype = vin.dtype
    C = vin.shape[0] // n
    c = vin.reshape(n, C)
    q, s = quantize_blocks(c, fmt, _noise_key(key, 1, noise_pos))
    wx = jax.lax.all_to_all(wire_pack(q, s, fmt), axis, split_axis=0,
                            concat_axis=0, axis_index_groups=groups)
    qx, sx = wire_unpack(wx, C, fmt)
    red = ordered_sum(dequantize_blocks(qx, sx, fmt))  # [C] f32
    q2, s2 = quantize_blocks(red[None], fmt, _noise_key(key, 2, my_chunk))
    wg = jax.lax.all_gather(wire_pack(q2, s2, fmt), axis, axis=0,
                            tiled=True, axis_index_groups=groups)
    qg, sg = wire_unpack(wg, C, fmt)
    out = dequantize_blocks(qg, sg, fmt).reshape(-1)  # [Tp] f32
    r_new = None
    if error_feedback:
        r_new = vin - dequantize_blocks(q, s, fmt).reshape(-1).astype(dtype)
        if phase2_feedback:
            # The chunk owner also knows phase 2's error; feeding it
            # back through the owner's own residual re-enters the sum
            # next step (the telescoping EF argument covers both).
            e2 = (red - dequantize_blocks(q2, s2, fmt)[0]).astype(dtype)
            start = my_chunk * C
            cur = jax.lax.dynamic_slice(r_new, (start,), (C,))
            r_new = jax.lax.dynamic_update_slice(r_new, cur + e2, (start,))
    return out, r_new


def quantized_gather_sum(frag, fmt: WireFormat, key, *, axis, pos,
                         groups=None):
    """Single-shot quantized sum of a fragment across a (small) group:
    quantize locally, all_gather the wire payload, dequantize and sum
    in float32 — the DCN leg of the hierarchical allreduce (a handful
    of slices, so one exchange beats the two-phase latency)."""
    q, s = quantize_blocks(frag[None], fmt, _noise_key(key, 3, pos))
    wg = jax.lax.all_gather(wire_pack(q, s, fmt), axis, axis=0,
                            tiled=True, axis_index_groups=groups)
    qg, sg = wire_unpack(wg, frag.shape[0], fmt)
    return ordered_sum(dequantize_blocks(qg, sg, fmt))


def quantized_all_gather(frag, fmt: WireFormat, key, *, axis, pos,
                         groups=None):
    """All_gather in wire format: quantize the local fragment, gather
    the codes+scales, dequantize everything — the final (ICI) leg of a
    fully-quantized hierarchical allreduce."""
    q, s = quantize_blocks(frag[None], fmt, _noise_key(key, 4, pos))
    wg = jax.lax.all_gather(wire_pack(q, s, fmt), axis, axis=0,
                            tiled=True, axis_index_groups=groups)
    qg, sg = wire_unpack(wg, frag.shape[0], fmt)
    return dequantize_blocks(qg, sg, fmt).reshape(-1)


def quantized_scatter_sum(v, fmt: WireFormat, key, *, axis, n: int,
                          noise_pos, groups=None):
    """Quantized reduce-scatter (phase 1 of the two-phase exchange,
    standalone): returns this participant's reduced chunk [C] float32 —
    the ICI leg of a fully-quantized hierarchical allreduce."""
    C = v.shape[0] // n
    c = v.reshape(n, C)
    q, s = quantize_blocks(c, fmt, _noise_key(key, 1, noise_pos))
    wx = jax.lax.all_to_all(wire_pack(q, s, fmt), axis, split_axis=0,
                            concat_axis=0, axis_index_groups=groups)
    qx, sx = wire_unpack(wx, C, fmt)
    return ordered_sum(dequantize_blocks(qx, sx, fmt))


def reference_allreduce(rows, fmt: WireFormat, tick: int, *,
                        seed: Optional[int] = None, residuals=None,
                        shared_noise: bool = False):
    """Eager-quantized reference: the exact math of the fused quantized
    megakernel, computed from the stacked per-replica rows.

    ``rows``: [n, T] (row i = replica i's contribution); ``residuals``:
    [n, T] or None.  Returns ``(reduced [T] rows.dtype, new_residuals
    [n, T] | None)`` — ``reduced`` is what every replica decodes (the
    allreduce SUM; callers fold AVERAGE themselves), bitwise identical
    to the megakernel's output under the same (seed, tick)."""
    rows = jnp.asarray(rows)
    n, T = rows.shape
    dtype = rows.dtype
    Tp = padded_length(T, n, fmt.block)
    vin = rows if residuals is None else rows + jnp.asarray(residuals)
    if Tp != T:
        vin = jnp.pad(vin, ((0, 0), (0, Tp - T)))
    C = Tp // n
    key = step_key(quant_seed() if seed is None else seed, tick)
    ef = fmt.error_feedback
    phase2 = ef and not shared_noise
    qs, ss = [], []
    for i in range(n):
        q, s = quantize_blocks(
            vin[i].reshape(n, C), fmt,
            _noise_key(key, 1, 0 if shared_noise else i))
        qs.append(q)
        ss.append(s)
    deq = jnp.stack([dequantize_blocks(q, s, fmt)
                     for q, s in zip(qs, ss)])     # [contrib, chunk, C]
    red = ordered_sum(deq)                         # [chunk, C] float32
    deq2 = []
    for d in range(n):
        q2, s2 = quantize_blocks(red[d][None], fmt, _noise_key(key, 2, d))
        deq2.append(dequantize_blocks(q2, s2, fmt)[0])
    out = jnp.concatenate(deq2)[:T].astype(dtype)
    r_new = None
    if ef:
        r_new = vin - deq.reshape(n, Tp).astype(dtype)
        if phase2:
            e2 = (red - jnp.stack(deq2)).astype(dtype)
            for i in range(n):
                cur = jax.lax.dynamic_slice(r_new[i], (i * C,), (C,))
                r_new = r_new.at[i].set(jax.lax.dynamic_update_slice(
                    r_new[i], cur + e2[i], (i * C,)))
        r_new = r_new[:, :T]
    return out, r_new


# ---------------------------------------------------------------------------
# Init-time validation (the env-knob uniformity contract)
# ---------------------------------------------------------------------------

_SPMD_ENV_KNOBS = (
    DEFAULT_ENV, "HVD_TPU_DCN_COMPRESS", "HVD_TPU_ICI_COMPRESS",
    BLOCK_ENV, ROUNDING_ENV, EF_ENV, SEED_ENV, MIN_ELEMS_ENV,
    "HVD_TPU_HIERARCHICAL", "HVD_TPU_VIRTUAL_SLICES",
    "HVD_TPU_MEGAKERNEL",
    # Backward/communication overlap (parallel/overlap.py): selects
    # which compiled programs a training step runs — monolithic vs
    # bucketed sub-programs — so a rank diverging on it must be named
    # at startup exactly like the compression/topology knobs.
    "HVD_TPU_OVERLAP",
    # MPMD pipeline schedule (parallel/pipeline.py): selects the
    # dispatch ORDER of the per-stage executables (1f1b vs gpipe,
    # interleave depth) — rank-divergent orders would desynchronize
    # the per-stage partial-cycle negotiation.
    "HVD_TPU_PIPELINE_SCHEDULE", "HVD_TPU_PIPELINE_INTERLEAVE",
    # Tree control-plane overlay (ops/tree.py): these select the wire
    # conversation itself (who connects to whom, which frames flow), so
    # a divergent rank would deadlock the handshake — name it at init.
    "HVD_TPU_TREE", "HVD_TPU_TREE_FANOUT", "HVD_TPU_TREE_THRESHOLD",
    # Fused computation-collective kernels (ops/fused.py): mode and
    # chunk count are part of the compiled SPMD program's identity —
    # a rank with a different chunk plan compiles a DIFFERENT program
    # for the same collective, so divergence must be named at startup.
    "HVD_TPU_FUSE", "HVD_TPU_FUSE_CHUNKS",
)


def validate_env() -> None:
    """Fail init — not the first collective — on a malformed compression
    knob, with the full valid-name list in the error."""
    for knob in (DEFAULT_ENV, "HVD_TPU_DCN_COMPRESS",
                 "HVD_TPU_ICI_COMPRESS"):
        value = os.environ.get(knob)
        if value:
            try:
                resolve(value)
            except ValueError as e:
                raise ValueError(f"{knob}={value!r}: {e}") from None
    _rounding()
    for knob in (BLOCK_ENV, SEED_ENV, MIN_ELEMS_ENV):
        value = os.environ.get(knob)
        if value:
            try:
                int(value)
            except ValueError:
                raise ValueError(
                    f"{knob}={value!r}: expected an integer") from None
    block = quant_block()
    if block % 2:
        raise ValueError(f"{BLOCK_ENV}={block}: the int4 nibble packing "
                         f"needs an even block size")


def env_fingerprint() -> str:
    """Canonical ``knob=value`` line of every SPMD-program-affecting
    compression/topology knob — exchanged in the control-plane HELLO
    handshake so rank-divergent settings are caught AT INIT (a divergent
    knob means divergent compiled programs: silent garbage or a hang).
    Values are the *effective* ones (unset == default)."""
    parts = []
    for knob in _SPMD_ENV_KNOBS:
        parts.append(f"{knob}={os.environ.get(knob, '') or '<unset>'}")
    return ";".join(parts)
