"""Gradient compression for the allreduce wire (≙ hvd.Compression).

The reference snapshot (v0.13.0) predates Horovod's compression API; this
implements the contract Horovod later standardized (horovod.torch
``Compression.fp16``): gradients are cast down before the collective and
restored after, halving the bytes every allreduce moves.  On TPU the
collective rides ICI, so the win is ICI/DCN bandwidth — most valuable on
the DCN (multi-slice) axis of a hybrid mesh.

TPU note: prefer :data:`Compression.bf16` — bfloat16 keeps float32's
exponent range (gradients overflow easily in float16's 5-bit exponent)
and is the MXU-native dtype.  ``fp16`` is provided for drop-in parity
with GPU Horovod scripts: every ``DistributedOptimizer`` (the core optax
wrapper and the torch/keras/tensorflow frontends) and the torch/tf
``allreduce`` functions accept the same ``compression=`` kwarg.

Usage (core JAX surface)::

    opt = hvd.DistributedOptimizer(optax.sgd(0.01),
                                   compression=hvd.Compression.bf16)

or explicitly around a single collective::

    compressor = hvd.Compression.bf16
    t, ctx = compressor.compress(tensor)
    out = compressor.decompress(hvd.allreduce(t, average=True), ctx)
"""

from __future__ import annotations

import jax.numpy as jnp

__all__ = ["Compression", "Compressor", "NoneCompressor", "FP16Compressor",
           "BF16Compressor"]


class Compressor:
    """Interface: ``compress(tensor) -> (tensor, ctx)`` before the wire,
    ``decompress(tensor, ctx)`` after.  Pure casts — safe both inside jit
    (the static psum path) and on eager numpy-backed arrays."""

    @staticmethod
    def compress(tensor):
        raise NotImplementedError

    @staticmethod
    def decompress(tensor, ctx):
        raise NotImplementedError


class NoneCompressor(Compressor):
    """Identity (≙ Horovod's Compression.none)."""

    @staticmethod
    def compress(tensor):
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor


class _CastCompressor(Compressor):
    wire_dtype: jnp.dtype = None  # set by subclasses

    @classmethod
    def compress(cls, tensor):
        tensor = jnp.asarray(tensor)
        dtype = tensor.dtype
        # Only floating inputs wider than the wire dtype are compressed;
        # integer/bool tensors and already-narrow floats pass through
        # (casting int64 indices to fp16 would corrupt them).
        if (jnp.issubdtype(dtype, jnp.floating)
                and jnp.dtype(dtype).itemsize
                > jnp.dtype(cls.wire_dtype).itemsize):
            return tensor.astype(cls.wire_dtype), dtype
        return tensor, None

    @classmethod
    def decompress(cls, tensor, ctx):
        if ctx is None:
            return tensor
        return jnp.asarray(tensor).astype(ctx)


class FP16Compressor(_CastCompressor):
    """float16 wire dtype (≙ Horovod's Compression.fp16).  Mind the 5-bit
    exponent: loss-scale or prefer bf16 on TPU."""

    wire_dtype = jnp.float16


class BF16Compressor(_CastCompressor):
    """bfloat16 wire dtype — float32 exponent range, MXU-native; the
    recommended compressor on TPU."""

    wire_dtype = jnp.bfloat16


class Compression:
    """Namespace matching Horovod's ``hvd.Compression`` surface."""

    none = NoneCompressor
    fp16 = FP16Compressor
    bf16 = BF16Compressor


def resolve(name: str):
    """Compressor by env-style name (``none``/``fp16``/``bf16``) — the
    lookup behind ``HVD_TPU_DCN_COMPRESS`` (the hierarchical-allreduce
    DCN-leg compressor, ops/megakernel.py) and any other string-keyed
    configuration surface."""
    try:
        return getattr(Compression, name.strip().lower())
    except AttributeError:
        raise ValueError(
            f"unknown compressor {name!r}: expected one of "
            f"none, fp16, bf16") from None


def wire_dtype_for(name: str, dtype):
    """The narrowed wire dtype ``name`` implies for tensors of
    ``dtype``, or ``None`` when compression does not apply (identity
    compressor, non-float payloads, already-narrow floats) — the same
    applicability rule as :meth:`_CastCompressor.compress`, decidable
    from the dtype alone so jitted kernels can fold the casts at trace
    time."""
    comp = resolve(name)
    wire = getattr(comp, "wire_dtype", None)
    if wire is None:
        return None
    if (jnp.issubdtype(jnp.dtype(dtype), jnp.floating)
            and jnp.dtype(dtype).itemsize > jnp.dtype(wire).itemsize):
        return wire
    return None
