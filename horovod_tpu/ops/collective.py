"""Eager (dynamic-path) collective operations.

TPU-native re-design of the reference's op layer: the TF/Torch adapters +
enqueue API (tensorflow/mpi_ops.cc, torch/mpi_ops.cc,
common/operations.cc:1543-1650) collapse into this module because JAX is the
only frontend tensor type and XLA owns async execution.

Semantics (Horovod parity):
  * ``allreduce(x)``  — sum (or average) across all replicas; every replica
    receives the reduced tensor (reference: operations.cc:941-1034).
  * ``allgather(x)``  — concatenate along dim 0 in rank order; every replica
    receives the full result; non-first dims must agree, dim 0 may differ
    per replica (reference: operations.cc:695-756, MPI_Allgatherv).
  * ``broadcast(x, root_rank)`` — every replica receives root's tensor
    (reference: operations.cc:1040-1059).
  * ``*_async`` / ``poll`` / ``synchronize`` — handle-based async API
    (reference: torch/mpi_ops.cc:206-332); backed by XLA async dispatch.

Input layouts:
  * a *per-replica* array created by :func:`shard` (leading axis == size,
    sharded over the ``"hvd"`` mesh axis): element ``i`` is replica ``i``'s
    contribution — the moral equivalent of each MPI rank passing its local
    tensor.
  * a plain (host or replicated) array: every replica contributes the same
    value — the common case for metrics and single-controller use.
  * a *list* of per-replica arrays (allgather only): contributions whose
    dim 0 differs per replica (the MPI_Allgatherv case).

Every eager call runs the full dynamic-path machinery for observability
parity: named request submitted to the coordinator per replica,
cross-replica validation (mismatch errors raised as
:class:`HorovodError`), timeline NEGOTIATE/QUEUE/XLA_* events, then a
compiled ``shard_map`` collective over the replica mesh.  Async calls are
*queued* and executed in fused buckets (Tensor Fusion,
reference: docs/tensor-fusion.md, operations.cc:1328-1374) when drained.
"""

from __future__ import annotations

import contextlib
import math
import sys
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from .. import telemetry as _telemetry
from .. import trace as _trace
from ..analysis import lockorder as _lockorder
from ..analysis import program as _program
from ..analysis import threads as _athreads
from .. import chaos as _chaos
from ..core import compat as _compat
from ..core import state as _state
from ..core.state import REPLICA_AXIS
from . import compression as _compression
from . import megakernel as _megakernel
from . import wire
from ..analysis import races as _races
from .wire import ReduceOp, Request, RequestType, Response, ResponseType

# Public reduction-operator constants (≙ the post-v0.13 hvd.Average /
# hvd.Sum / hvd.Adasum / hvd.Min / hvd.Max / hvd.Product; the v0.13
# reference hard-codes MPI_SUM + the average divide).
Average = ReduceOp.AVERAGE
Sum = ReduceOp.SUM
Adasum = ReduceOp.ADASUM
Min = ReduceOp.MIN
Max = ReduceOp.MAX
Product = ReduceOp.PRODUCT

# Kernel-table prefix per reduce op ("psum" kernels serve both SUM and
# AVERAGE — average is a post-divide, reference mpi_ops.cc:57-62).
_OP_KERNEL = {
    ReduceOp.SUM: "psum", ReduceOp.AVERAGE: "psum",
    ReduceOp.MIN: "pmin", ReduceOp.MAX: "pmax",
    ReduceOp.PRODUCT: "pprod", ReduceOp.ADASUM: "adasum",
}


class HorovodError(RuntimeError):
    """Cross-replica validation failure (≙ the reference's
    FailedPreconditionError surfaced from ERROR responses,
    operations.cc:1060-1067)."""


# hvd-telemetry instrumentation (docs/metrics.md).  Event-granularity
# budget: _enqueue and the response executor each spend exactly one
# perf_counter pair per event; the per-submit steady-state hot path
# (cache hits) is instrumented pull-side from CacheStats instead.
_M_SUBMITTED = _telemetry.counter(
    "collective.submitted", "eager collectives entering negotiation")
_M_COMPLETED = _telemetry.counter(
    "collective.completed", "eager collectives executed")
_M_ERRORS = _telemetry.counter(
    "collective.errors", "validation/shutdown errors surfaced")
_M_NEGOTIATE_S = _telemetry.histogram(
    "collective.negotiate_seconds", "seconds",
    "submit -> broadcast response (negotiate + queue phases)")
_M_EXECUTE_S = _telemetry.histogram(
    "collective.execute_seconds", "seconds",
    "response -> XLA dispatch complete (execute phase)")
_M_PAYLOAD_B = _telemetry.histogram(
    "collective.payload_bytes", "bytes", "per-tensor payload size")
_M_GROUP_WIDTH = _telemetry.histogram(
    "fusion.group_width", "count", "tensors per fused response")


# Error-message parity with the reference's SHUT_DOWN_ERROR
# (operations.cc:181-188); pending callbacks are flushed with it during
# shutdown and late arrivals raise it.
SHUT_DOWN_ERROR_MESSAGE = (
    "Horovod has been shut down. This was caused by an exception on one of "
    "the ranks or an attempt to allreduce, allgather or broadcast a tensor "
    "after one of the ranks finished execution.")


def _poison_pending(message: str = SHUT_DOWN_ERROR_MESSAGE) -> None:
    """Fail every queued-but-unlaunched collective (≙ the reference's
    SHUT_DOWN_ERROR callback flush, operations.cc:1377-1474)."""
    st = _state.global_state()
    ops = _queue.take(list(_queue.pending_meta()))
    err = HorovodError(message)
    for o in ops:
        st.handle_manager._get(o.handle).result = err


def _initiate_shutdown(message: str = SHUT_DOWN_ERROR_MESSAGE) -> None:
    """One rank decided to shut down (or died): mark the runtime, tell
    the workers (controller only), flush pending ops.  Callers must hold
    ``_drain_lock`` or have stopped the background drain first — the
    single shutdown-protocol step shared by ``hvd.shutdown()`` and the
    controller's drain loop (≙ operations.cc:1377-1403)."""
    st = _state.global_state()
    st.peer_shutdown = True
    if st.response_cache is not None:
        # Dead-peer / shutdown poisoning: cached cycles must never
        # replay across the teardown; orphans are dropped — everything
        # pending is about to be poisoned below anyway.
        st.response_cache.flush("shutdown")
    if (st.multiprocess and st.transport is not None
            and st.process_index == 0):
        st.transport.broadcast_responses(
            [Response(ResponseType.SHUTDOWN, error_message=message)])
    _poison_pending(message)


def _handle_lost_ranks(st, tp) -> None:
    """Controller-side dead-peer handling: EOF without the exit handshake
    = the process died.  It can never reach jax.distributed's exit
    barrier; don't let that block (then abort) any survivor — the marked
    diagnosis makes the workers disarm too.  Callers must hold
    ``_drain_lock`` or have stopped the background drain first (same
    contract as ``_initiate_shutdown``); called from the drain loop and
    from ``hvd.shutdown()`` when the death lands after the last tick."""
    from ..core import cluster as _cluster

    _cluster.disarm_distributed_shutdown()
    ranks = sorted(tp.lost_ranks)
    pending = bool(_queue.pending_meta()) or bool(
        st.coordinator.check_stalled(threshold=0.0))
    detail = " while collectives were pending" if pending else ""
    # hvd-chaos: a rank lost through the reconnect machinery (grace
    # expiry, replay-ring overflow) carries a reason naming the fault —
    # fold it into the diagnostic so operators see WHY, not just WHO.
    reasons = getattr(tp, "lost_reasons", {})
    why = "; ".join(f"rank {r}: {reasons[r]}" for r in ranks
                    if r in reasons)
    if why:
        detail += f" ({why})"
    _telemetry.dead_peer_event(
        f"rank(s) {ranks} {wire.DEAD_PEER_MARKER}{detail}")
    _initiate_shutdown(
        f"Horovod has been shut down: rank(s) {ranks} "
        f"{wire.DEAD_PEER_MARKER}{detail}.")
    print(f"ERROR: worker rank(s) {ranks} {wire.DEAD_PEER_MARKER};"
          f"{' pending collectives failed;' if pending else ''}"
          f" shutting down.", file=sys.stderr)


# Autogenerated op names (≙ torch/mpi_ops.cc:35-40 "prefix.noname.<n>").
_name_lock = _lockorder.make_lock("collective._name_lock")
_name_counters: Dict[str, int] = {}


def _auto_name(prefix: str, ps=None) -> str:
    """Generate a unique op name (≙ the reference's prefix.noname.<n>,
    torch/mpi_ops.cc:35-40).  Process-set ops get their own namespace
    AND counter: set members consume names non-members never see, so a
    shared counter would desync the ranks' auto-names for later GLOBAL
    ops (and a bare collision could misroute a set response into a
    non-member's global op of the same name)."""
    if ps is not None:
        prefix = f"ps{ps.process_set_id}.{prefix}"
    with _name_lock:
        n = _name_counters.get(prefix, 0) + 1
        _name_counters[prefix] = n
        return f"{prefix}.noname.{n}"


# ---------------------------------------------------------------------------
# Input classification and device placement
# ---------------------------------------------------------------------------

@dataclass
class _Contribution:
    """Normalized description of one eager collective's input."""

    per_replica: bool                 # leading axis is the replica axis
    shapes: List[Tuple[int, ...]]     # per-replica payload shapes
    dtype: Any
    devices: List[int]                # wire device ids per replica
    value: Any                        # canonical device array
    ragged: bool = False              # list input with differing dim-0
    orig_sizes: List[int] = field(default_factory=list)
    # True when ``value`` is a buffer the executor itself materialized
    # (host input converted by jnp.asarray / an _on_mesh copy) and the
    # caller can never observe again: the megakernel donates exactly
    # these (ops/megakernel.py) — user-held jax.Arrays are never donated.
    owned: bool = False


def _wire_device(x) -> int:
    if isinstance(x, jax.Array):
        try:
            dev = list(x.devices())[0]
            return dev.id
        except Exception:
            return wire.CPU_DEVICE_ID
    return wire.CPU_DEVICE_ID


def is_per_replica(x) -> bool:
    """True if ``x`` is laid out with its leading axis sharded over the
    replica mesh axis (the layout :func:`shard` produces)."""
    if not isinstance(x, jax.Array):
        return False
    sh = x.sharding
    if not isinstance(sh, NamedSharding):
        return False
    spec = sh.spec
    if len(spec) == 0:
        return False
    first = spec[0]
    if isinstance(first, tuple):
        return REPLICA_AXIS in first
    return first == REPLICA_AXIS


def shard(per_replica_values, axis: int = 0) -> jax.Array:
    """Build a per-replica array from stacked contributions.

    ``per_replica_values`` is an array (or list) whose leading axis indexes
    replicas (length == ``size()``).  The result is a global array with that
    axis sharded over the replica mesh — the TPU analogue of "each MPI rank
    holds its local tensor".
    """
    st = _state.global_state()
    _state._check_initialized()
    if st.multiprocess:
        raise ValueError(
            "shard() assembles all replicas' contributions from one host "
            "and is single-process only; in multi-process mode each "
            "process passes its own local tensor to the collective "
            "directly (the reference's per-rank calling convention).")
    x = jnp.asarray(per_replica_values) if not isinstance(
        per_replica_values, jax.Array) else per_replica_values
    if x.shape[0] != st.size:
        raise ValueError(
            f"Leading axis ({x.shape[0]}) must equal the replica count "
            f"({st.size}) for a per-replica array.")
    spec = [None] * x.ndim
    spec[axis] = REPLICA_AXIS
    sharding = NamedSharding(st.mesh, P(*spec))
    return jax.device_put(x, sharding)


def _on_mesh(xa, mesh):
    """Normalize an array COMMITTED to a different device set (e.g. a
    process-set collective's output fed into a global one, or vice
    versa) back to host so the target mesh's jitted kernel can place it
    — users naturally chain collectives across communicators.
    Uncommitted arrays are left alone (jit moves those freely)."""
    if isinstance(xa, jax.Array) and getattr(xa, "committed", False):
        try:
            devs = xa.sharding.device_set
        except Exception:  # noqa: BLE001 — conservative across jax versions
            return xa
        if devs != set(mesh.devices.flat):
            return jnp.asarray(np.asarray(xa))
    return xa


def _classify(x, op: RequestType, ps=None) -> _Contribution:
    st = _state.global_state()
    size = st.size
    if ps is not None and not st.multiprocess:
        # Single-process process-set contribution: replicated values (one
        # logical contribution per member) or a per-member list for the
        # ragged allgather.  A globally-sharded per-replica array has no
        # canonical sub-slicing onto the set, so it is rejected.
        k = ps.size()
        if isinstance(x, (list, tuple)) and op == RequestType.ALLGATHER:
            if len(x) != k:
                raise ValueError(
                    f"allgather over process set {ps.process_set_id} with "
                    f"a list input needs one contribution per member "
                    f"({k}), got {len(x)}.")
            arrs = [jnp.asarray(v) for v in x]
            shapes = [tuple(a.shape) for a in arrs]
            return _Contribution(
                per_replica=True, shapes=shapes, dtype=arrs[0].dtype,
                devices=[_wire_device(a) for a in arrs], value=arrs,
                ragged=len(set(shapes)) > 1,
                orig_sizes=[s[0] if s else 0 for s in shapes])
        xa = x if isinstance(x, jax.Array) else jnp.asarray(x)
        if is_per_replica(xa):
            raise ValueError(
                "process-set collectives take replicated values or "
                "per-member lists; a per-replica array sharded over the "
                "GLOBAL mesh has no canonical sub-slicing onto the set — "
                "use the static path with a mesh over the subset instead.")
        xa = _on_mesh(xa, ps.mesh_and_kernels()[0])
        payload = tuple(xa.shape)
        return _Contribution(
            per_replica=False, shapes=[payload] * k, dtype=xa.dtype,
            devices=[_wire_device(xa)] * k, value=xa,
            orig_sizes=[payload[0] if payload else 0] * k,
            owned=xa is not x)
    if st.multiprocess:
        # Reference layout: each process contributes exactly its own local
        # tensor (one MPI rank per process); the coordinator learns the
        # other ranks' shapes from their own requests.
        if isinstance(x, (list, tuple)) and op == RequestType.ALLGATHER:
            raise ValueError(
                "list-input allgather is the single-process spelling; in "
                "multi-process mode pass this process's own contribution "
                "(dim 0 may differ per rank).")
        xa = x if isinstance(x, jax.Array) else jnp.asarray(x)
        payload = tuple(xa.shape)
        return _Contribution(
            per_replica=True, shapes=[payload], dtype=xa.dtype,
            devices=[_wire_device(xa)], value=xa,
            orig_sizes=[payload[0] if payload else 0],
            owned=xa is not x)
    if isinstance(x, (list, tuple)) and op == RequestType.ALLGATHER:
        if len(x) != size:
            raise ValueError(
                f"allgather with a list input needs one contribution per "
                f"replica ({size}), got {len(x)}.")
        arrs = [jnp.asarray(v) for v in x]
        shapes = [tuple(a.shape) for a in arrs]
        sizes = [s[0] if s else 0 for s in shapes]
        ragged = len(set(shapes)) > 1
        return _Contribution(
            per_replica=True, shapes=shapes, dtype=arrs[0].dtype,
            devices=[_wire_device(a) for a in arrs], value=arrs,
            ragged=ragged, orig_sizes=sizes)
    dev = _wire_device(x)
    xa = x if isinstance(x, jax.Array) else jnp.asarray(x)
    xa = _on_mesh(xa, st.mesh)  # a set-collective output fed back in
    if is_per_replica(xa):
        payload = tuple(xa.shape[1:])
        return _Contribution(
            per_replica=True, shapes=[payload] * size, dtype=xa.dtype,
            devices=[d.id for d in st.devices],
            value=xa, orig_sizes=[payload[0] if payload else 0] * size,
            owned=xa is not x)
    payload = tuple(xa.shape)
    return _Contribution(
        per_replica=False, shapes=[payload] * size, dtype=xa.dtype,
        devices=[dev] * size, value=xa,
        orig_sizes=[payload[0] if payload else 0] * size,
        owned=xa is not x)


# ---------------------------------------------------------------------------
# Compiled collective kernels (cached per mesh via jit's shape/dtype cache)
# ---------------------------------------------------------------------------

def _build_kernels(mesh):
    """All jitted shard_map collective kernels for one mesh.

    Shared by the single-process replica mesh and the multi-process
    process mesh — the kernel bodies are identical; only the mesh (and
    which entries get used) differs.
    """

    def sm(fn, in_spec, out_spec, check_vma=True):
        # check_vma=False where the output is replicated by construction
        # (all_gather / masked-psum broadcast) but the static checker cannot
        # infer it.
        return jax.jit(_compat.shard_map(
            fn, mesh=mesh, in_specs=in_spec, out_specs=out_spec,
            check_vma=check_vma))

    def _gather_block(x):
        x = jnp.squeeze(x, axis=0)
        return jax.lax.all_gather(x, REPLICA_AXIS, axis=0, tiled=True)

    def _psum_squeeze_block(x):
        return jax.lax.psum(jnp.squeeze(x, axis=0), REPLICA_AXIS)

    def _bcast_block(x, root):
        x = jnp.squeeze(x, axis=0)
        idx = jax.lax.axis_index(REPLICA_AXIS)
        contrib = jnp.where(idx == root, x, jnp.zeros_like(x))
        return jax.lax.psum(contrib, REPLICA_AXIS)

    n = mesh.shape[REPLICA_AXIS]

    def _rscatter_pr_block(x):
        # Per-replica [n, d0, ...]: reduce then keep this replica's
        # dim-0 chunk (the post-v0.13 hvd.reducescatter semantics) —
        # XLA's native ReduceScatter over ICI, not a psum + slice.
        v = jnp.squeeze(x, axis=0)
        return jax.lax.psum_scatter(v, REPLICA_AXIS, scatter_dimension=0,
                                    tiled=True)[None]

    def _rscatter_rep_block(x):
        return jax.lax.psum_scatter(x, REPLICA_AXIS, scatter_dimension=0,
                                    tiled=True)[None]

    def _a2a_block(x):
        # Per-sender [n(dest), M, rest] blocks → per-receiver
        # [n(sender), M, rest]: XLA's native AllToAll on ICI.  Ragged
        # splits ride pad-to-max M (the split matrix is negotiated, so
        # M is static at trace time), like the ragged allgather.
        v = jnp.squeeze(x, axis=0)
        return jax.lax.all_to_all(v, REPLICA_AXIS, split_axis=0,
                                  concat_axis=0, tiled=False)[None]

    def _prod_all(x):
        # No lax.pprod exists: gather every contribution and reduce
        # locally (XLA fuses the pointwise product into the gather's
        # consumer).
        return jnp.prod(jax.lax.all_gather(x, REPLICA_AXIS, axis=0), axis=0)

    def _adasum_ladder(x):
        """Adasum recursive-doubling ladder over the mesh axis.

        The post-v0.13 Horovod Adasum operator (scale-insensitive
        gradient combining, arXiv:2006.02924): for a pair (a, b),
        ``adasum(a,b) = (1 - a·b/2||a||²) a + (1 - a·b/2||b||²) b``,
        applied log2(n) times at doubling distances so every replica
        ends with the full combination — expressed TPU-natively as
        ``ppermute`` exchange rounds on ICI (instead of the reference
        era's MPI recursive halving).  The formula is symmetric, so
        both partners compute bit-identical results with no extra
        agreement round.  Requires power-of-two n (checked at enqueue).
        """
        shape = x.shape
        acc = jnp.promote_types(x.dtype, jnp.float32)
        v = x.reshape(-1).astype(acc)
        for r in range(int(math.log2(n))):
            dist = 1 << r
            perm = [(i, i ^ dist) for i in range(n)]
            other = jax.lax.ppermute(v, REPLICA_AXIS, perm)
            dot = jnp.sum(v * other)
            na = jnp.sum(v * v)
            nb = jnp.sum(other * other)
            ca = 1.0 - jnp.where(na > 0, dot / (2.0 * na), 0.0)
            cb = 1.0 - jnp.where(nb > 0, dot / (2.0 * nb), 0.0)
            v = ca * v + cb * other
        return v.astype(x.dtype).reshape(shape)

    def _adasum_vhdd(x):
        """Bandwidth-optimal Adasum: vector-halving distance-doubling
        (the VHDD scheme of the Adasum paper, arXiv:2006.02924 §4.2).

        The ladder above exchanges the FULL vector every round —
        log2(n)·|v| on the wire.  VHDD computes the SAME recursive
        pairwise tree with distributed fragments: at round r partners at
        distance 2^r swap complementary halves of their |v|/2^r working
        fragment (wire: |v|/2^(r+1)), combine, and recurse; after
        log2(n) rounds each replica owns the fully-combined |v|/n
        fragment, and a mirrored doubling phase allgathers the result —
        total wire ≈ 2·|v| plus 3 scalars per round.

        The level-r dot products span the level's full distributed
        vector: after the swap each replica in the 2^(r+1)-block holds a
        distinct sub-range of the block's (A, B) pair — partners keep
        complementary halves, sibling pairs cover the other ranges — so
        one grouped psum of the per-fragment partials yields the exact
        full-vector dot, each element counted once.  Results match the
        ladder (asserted in tests/test_allreduce.py)."""
        shape = x.shape
        acc = jnp.promote_types(x.dtype, jnp.float32)
        v = x.reshape(-1).astype(acc)
        orig = v.size
        padding = (-orig) % n
        if padding:
            v = jnp.concatenate([v, jnp.zeros((padding,), acc)])
        idx = jax.lax.axis_index(REPLICA_AXIS)
        logn = int(math.log2(n))
        frag = v
        for r in range(logn):
            dist = 1 << r
            half = frag.shape[0] // 2
            lo, hi = frag[:half], frag[half:]
            keep_lo = ((idx >> r) & 1) == 0
            mine = jnp.where(keep_lo, lo, hi)
            send = jnp.where(keep_lo, hi, lo)
            recv = jax.lax.ppermute(send, REPLICA_AXIS,
                                    [(i, i ^ dist) for i in range(n)])
            a = jnp.where(keep_lo, mine, recv)  # block-0's fragment
            b = jnp.where(keep_lo, recv, mine)  # block-1's fragment
            groups = [[g * 2 * dist + j for j in range(2 * dist)]
                      for g in range(n // (2 * dist))]
            dot, na, nb = jax.lax.psum(
                jnp.stack([jnp.sum(a * b), jnp.sum(a * a),
                           jnp.sum(b * b)]),
                REPLICA_AXIS, axis_index_groups=groups)
            ca = 1.0 - jnp.where(na > 0, dot / (2.0 * na), 0.0)
            cb = 1.0 - jnp.where(nb > 0, dot / (2.0 * nb), 0.0)
            frag = ca * a + cb * b
        for r in range(logn - 1, -1, -1):
            dist = 1 << r
            recv = jax.lax.ppermute(frag, REPLICA_AXIS,
                                    [(i, i ^ dist) for i in range(n)])
            keep_lo = ((idx >> r) & 1) == 0
            frag = jnp.where(keep_lo, jnp.concatenate([frag, recv]),
                             jnp.concatenate([recv, frag]))
        return frag[:orig].astype(x.dtype).reshape(shape)

    def _adasum(x):
        # Static (trace-time) dispatch: VHDD's ~2|v| wire beats the
        # ladder's log2(n)|v| once the vector amortizes its pad-to-n and
        # per-round scalar psum; at n=2 the two are the same wire cost
        # and the ladder is one collective per round instead of two.
        if n > 2 and x.size >= 2 * n:
            return _adasum_vhdd(x)
        return _adasum_ladder(x)

    def _pr_block(fn):
        # Per-replica [size, ...] layout: reduce this replica's squeezed
        # shard, emit one identical row per replica.
        def body(x):
            return fn(jnp.squeeze(x, axis=0))[None]
        return body

    def _fold_avg(fn):
        # AVERAGE's post-reduce divide folded INTO the compiled kernel
        # (one launch, not reduce + a separate eager _divide dispatch);
        # integer dtypes floor-divide exactly like _divide.  The mesh
        # extent n == the averaging denominator by construction (global
        # mesh: st.size; process-set sub-mesh: the set size).
        def body(x):
            out = fn(x)
            if jnp.issubdtype(out.dtype, jnp.inexact):
                return out / n
            return out // n
        return body

    extra = {}
    for key, fn in (("pmin", lambda x: jax.lax.pmin(x, REPLICA_AXIS)),
                    ("pmax", lambda x: jax.lax.pmax(x, REPLICA_AXIS)),
                    ("pprod", _prod_all)):
        extra[f"{key}_pr"] = sm(_pr_block(fn), P(REPLICA_AXIS),
                                P(REPLICA_AXIS), check_vma=False)
        extra[f"{key}_rep"] = sm(fn, P(), P(), check_vma=False)
        extra[f"{key}_out_rep"] = sm(
            lambda x, fn=fn: fn(jnp.squeeze(x, axis=0)),
            P(REPLICA_AXIS), P(), check_vma=False)
    if n & (n - 1) == 0:  # adasum needs a power-of-two axis
        extra["adasum_pr"] = sm(_pr_block(_adasum), P(REPLICA_AXIS),
                                P(REPLICA_AXIS), check_vma=False)
        extra["adasum_rep"] = sm(_adasum, P(), P(), check_vma=False)
        extra["adasum_out_rep"] = sm(
            lambda x: _adasum(jnp.squeeze(x, axis=0)),
            P(REPLICA_AXIS), P(), check_vma=False)

    _psum = lambda x: jax.lax.psum(x, REPLICA_AXIS)  # noqa: E731

    return {
        **extra,
        # Per-replica [size, ...] -> per-replica [size, ...] (each = sum).
        "psum_pr": sm(_psum, P(REPLICA_AXIS), P(REPLICA_AXIS)),
        # Replicated [...] -> replicated [...] (= x * size, honest
        # collective).
        "psum_rep": sm(_psum, P(), P()),
        # Per-replica [size, ...] -> replicated [...] (sum of shards).
        "psum_out_rep": sm(_psum_squeeze_block, P(REPLICA_AXIS), P(),
                           check_vma=False),
        # AVERAGE variants: the mean's divide folded into the compiled
        # program — no separate eager _divide launch after the
        # collective (the data-plane megakernel work, docs/tensor-fusion.md).
        "psum_pr_avg": sm(_fold_avg(_psum), P(REPLICA_AXIS),
                          P(REPLICA_AXIS)),
        "psum_rep_avg": sm(_fold_avg(_psum), P(), P()),
        "psum_out_rep_avg": sm(_fold_avg(_psum_squeeze_block),
                               P(REPLICA_AXIS), P(), check_vma=False),
        "rscatter_pr_avg": sm(_fold_avg(_rscatter_pr_block),
                              P(REPLICA_AXIS), P(REPLICA_AXIS),
                              check_vma=False),
        "rscatter_rep_avg": sm(_fold_avg(_rscatter_rep_block), P(),
                               P(REPLICA_AXIS), check_vma=False),
        # Replicated-input broadcast: the identity-with-execution-parity
        # psum(x)/n collapsed into one compiled program (inexact dtypes
        # only; integer replicated broadcasts stay the pure identity).
        "bcast_rep": sm(lambda x: jax.lax.psum(x, REPLICA_AXIS) / n,
                        P(), P()),
        # Per-replica [size, d0, ...] -> replicated [size*d0, ...].
        "gather_pr": sm(_gather_block, P(REPLICA_AXIS), P(),
                        check_vma=False),
        # Replicated [d0, ...] -> replicated [size*d0, ...].
        "gather_rep": sm(
            lambda x: jax.lax.all_gather(x, REPLICA_AXIS, axis=0,
                                         tiled=True),
            P(), P(), check_vma=False),
        # Per-replica [size, ...] + root -> replicated [...] = root's shard.
        "bcast_pr": jax.jit(_compat.shard_map(
            _bcast_block, mesh=mesh, in_specs=(P(REPLICA_AXIS), P()),
            out_specs=P(), check_vma=False)),
        # Reducescatter: per-replica [n, d0, ...] -> per-replica
        # [n, d0/n, ...] (row r = rank r's chunk of the reduction).
        "rscatter_pr": sm(_rscatter_pr_block, P(REPLICA_AXIS),
                          P(REPLICA_AXIS), check_vma=False),
        # Replicated [d0, ...] -> per-replica [n, d0/n, ...].
        "rscatter_rep": sm(_rscatter_rep_block, P(), P(REPLICA_AXIS),
                           check_vma=False),
        # Alltoall: [n(sender), n(dest), M, ...] -> [n(recv), n(sender),
        # M, ...] (padded blocks; the host slices by the split matrix).
        "a2a_pr": sm(_a2a_block, P(REPLICA_AXIS), P(REPLICA_AXIS),
                     check_vma=False),
    }


# Compiled-kernel tables.  Previously unbounded lru_caches keyed on
# Device OBJECTS: a restarted backend mints fresh Devices that never
# compare equal to the dead ones, so the old entries became immortal,
# pinning dead meshes and their jitted kernels forever.  This bounded
# cache keeps the useful property (same-backend re-inits — every test —
# share one compilation because live Devices compare equal) while, on
# every miss, evicting entries whose Device objects no longer appear in
# ``jax.devices()``, plus insertion-order overflow eviction as a
# backstop.
_KERNEL_CACHE_CAPACITY = 16
_kernel_cache_lock = _lockorder.make_lock("collective._kernel_cache")
# table name -> {device-tuple key -> built kernels}
_kernel_caches: Dict[str, dict] = {
    "replica": {}, "subset": {}, "mp": {}}  # guarded_by: _kernel_cache_lock


def _cached_kernels(table: str, key: tuple, build):
    with _kernel_cache_lock:
        hit = _kernel_caches[table].get(key)
    if hit is not None:
        return hit
    # Miss: evict stale-device and overflow entries first; the build
    # itself runs OUTSIDE the lock (jit construction must never happen
    # under a runtime lock), and a concurrent builder's entry wins via
    # setdefault.
    try:
        live = set(jax.devices())
    except Exception:  # noqa: BLE001 — backend down; skip eviction
        live = None
    with _kernel_cache_lock:
        if live is not None:
            # Stale-device entries are dead in EVERY table (the backend
            # restarted) — sweep them all.
            for cache in _kernel_caches.values():
                for k in [k for k in cache if not set(k) <= live]:
                    del cache[k]
        # The overflow backstop applies only to the table receiving
        # this insert: another table's live at-capacity entries must
        # not lose compilations to an unrelated miss.
        target = _kernel_caches[table]
        while len(target) >= _KERNEL_CACHE_CAPACITY:
            del target[next(iter(target))]  # oldest insertion first
    built = build()
    with _kernel_cache_lock:
        return _kernel_caches[table].setdefault(key, built)


def _kernels(mesh_key):
    """Kernels over the replica mesh; ``mesh_key`` is the tuple of
    Device OBJECTS (not ids) so the replica set changing (tests re-init
    with device subsets) or the backend restarting rebuilds them."""
    return _cached_kernels(
        "replica", mesh_key,
        lambda: _build_kernels(_state.global_state().mesh))


def _mesh_kernels():
    st = _state.global_state()
    return _kernels(tuple(st.devices))


def _subset_kernels(devs: tuple):
    """Mesh + kernels over an arbitrary device subset, cached by the
    device tuple so process sets over identical subsets (or the same set
    re-registered across re-inits) share one compilation."""

    def build():
        mesh = jax.sharding.Mesh(np.asarray(devs), (REPLICA_AXIS,))
        return mesh, _build_kernels(mesh)

    return _cached_kernels("subset", devs, build)


# ---------------------------------------------------------------------------
# Multi-process eager path (reference: one MPI rank per process)
# ---------------------------------------------------------------------------
# Negotiation runs at process granularity and each process holds only its
# own contribution.  Collectives execute over a one-device-per-process mesh
# (the lowest-id local device of every process), mirroring the reference's
# one-GPU-per-rank binding; any extra local devices serve the static pjit
# path instead.

def _mp_mesh_and_kernels(mesh_key):
    # mesh_key is the tuple of local Device objects (see _kernels on why
    # object identity, not ids; bounded + stale-evicting like _kernels).
    def build():
        by_proc: Dict[int, Any] = {}
        for d in jax.devices():
            if d.process_index not in by_proc \
                    or d.id < by_proc[d.process_index].id:
                by_proc[d.process_index] = d
        devs = [by_proc[p] for p in sorted(by_proc)]
        mesh = jax.sharding.Mesh(np.asarray(devs), (REPLICA_AXIS,))
        return mesh, _build_kernels(mesh)

    return _cached_kernels("mp", mesh_key, build)


def _mp_kernels():
    st = _state.global_state()
    return _mp_mesh_and_kernels(tuple(st.devices))


def _mp_global(x: jax.Array, ps=None):
    """Local contribution → global ``[P, ...]`` array sharded over the
    process mesh (this process supplies shard ``process_index``; for a
    process set, the SET mesh with this process at its set-local slot)."""
    st = _state.global_state()
    if ps is None:
        mesh, _ = _mp_kernels()
        count = st.process_count
    else:
        mesh, _ = ps.mesh_and_kernels()
        count = ps.size()
    if isinstance(x, jax.Array) and not x.is_fully_addressable:
        # A previous collective's (replicated) output — or eager math on
        # one — fed straight back in: take this process's full local
        # copy so device_put gets an addressable array (users naturally
        # chain collectives, e.g. allreduce(f(broadcast(w)))).
        x = np.asarray(x.addressable_data(0))
    # The shard this process owns lives on its device in the process mesh.
    mine = [d for d in mesh.devices.flat
            if d.process_index == st.process_index][0]
    local = jax.device_put(jnp.asarray(x), mine)[None]
    gshape = (count,) + tuple(local.shape[1:])
    spec = [None] * (local.ndim)
    spec[0] = REPLICA_AXIS
    sharding = NamedSharding(mesh, P(*spec))
    return jax.make_array_from_single_device_arrays(gshape, sharding, [local])


def _divide(x, denom: int):
    """Post-reduce division for ``average=True``; integer dtypes use floor
    division like the reference's in-place integer divide
    (torch/tensor_util.h DivideTensorInPlace)."""
    if jnp.issubdtype(x.dtype, jnp.inexact):
        return x / denom
    return x // denom


# ---------------------------------------------------------------------------
# Megakernel launches (ops/megakernel.py): one donated pack→reduce→unpack
# executable per fusion group instead of the per-tensor eager choreography
# ---------------------------------------------------------------------------

def _group_digest_fn(group: List["_QueuedOp"], psid: int, quant=None):
    """Lazy fusion-plan digest of one response group — the PR 2 cycle
    digest (ops/cache.cycle_digest scheme) the compiled executable is
    recorded under; only evaluated on a cold compile.  The quantization
    spec is folded into the digest (ops/megakernel.plan_digest)."""
    def digest() -> str:
        entries = [_program.SignatureEntry(
            seq=0, op=o.op.name.lower(), name=o.name,
            dtype=wire.dtype_name(wire.dtype_of(o.contrib.dtype)),
            shape=tuple(o.contrib.shapes[0]),
            reduce_op=wire.reduce_op_name(o.red_op),
            process_set_id=psid) for o in group]
        return _megakernel.plan_digest(entries, quant)
    return digest


def _megakernel_eligible(group: List["_QueuedOp"]) -> bool:
    return (_megakernel.enabled()
            and group[0].red_op != ReduceOp.ADASUM)


def _tensor_wire_format(name: str, psid: int, red_op: ReduceOp, dtype,
                        shape) -> Optional["_compression.WireFormat"]:
    """The compression policy's wire format for ONE tensor, or None for
    full precision.  Only the psum family quantizes (SUM/AVERAGE — the
    gradient path); min/max/prod and Adasum always ride uncompressed."""
    if _OP_KERNEL.get(red_op) != "psum":
        return None
    numel = int(np.prod(shape, dtype=np.int64)) if shape else 1
    return _compression.policy_format_for(name, psid, dtype, numel)


def _partition_by_wire(group: List["_QueuedOp"], psid: int):
    """Split one coordinator fusion group by per-tensor wire format
    (the policy registry's selection surface: embeddings int8,
    layernorm/scalars uncompressed, ...).  Deterministic across ranks:
    keyed only on negotiated fields (name/dtype/shape/op) plus the
    policy, which the env-uniformity contract pins fleet-wide.
    Preserves first-appearance order."""
    buckets: Dict[Any, List["_QueuedOp"]] = {}
    order: List[Any] = []
    for o in group:
        fmt = _tensor_wire_format(o.name, psid, o.red_op,
                                  o.contrib.dtype, o.contrib.shapes[0])
        if fmt not in buckets:
            buckets[fmt] = []
            order.append(fmt)
        buckets[fmt].append(o)
    return [(fmt, buckets[fmt]) for fmt in order]


def _tl_group_start(tl, group: List["_QueuedOp"]) -> None:
    for o in group:
        _tl_start(tl, o, "ALLREDUCE")
        tl.activity_start(o.name, "FUSED_KERNEL")


def _tl_group_end(tl, group: List["_QueuedOp"], hier) -> None:
    for o in group:
        tl.activity_end(o.name)
        if hier is not None:
            tl.instant(o.name, "DCN_ALLREDUCE", args={
                "slices": hier.topo.n_slices, "ici": hier.topo.ici_size,
                "wire_dtype": hier.wire_dtype or str(o.contrib.dtype)})
        tl.end(o.name, dtype=str(o.contrib.dtype))


def _quant_group_key(variant: str, psid: int, names: Sequence[str],
                     fmt) -> tuple:
    """The ONE tick/noise-stream key scheme for every executor path
    (fused sp/mp and the eager reference fallback) — the bitwise
    fused≡eager contract depends on all of them counting steps under
    the same key.  Flat tuple of scalars only: it round-trips through
    JSON in compression_state() (a nested tuple would come back as an
    unhashable list)."""
    return (variant, psid, fmt.name if fmt is not None else "") \
        + tuple(names)


def _launch_group_megakernel(group: List["_QueuedOp"], layout: bool,
                             denom: int, ps, mesh, tl, hm,
                             fmt=None) -> bool:
    """Single-process fused-group launch: ONE jitted donated executable
    packs the group, reduces once (hierarchically on multi-slice
    meshes, quantized when the compression policy says so), folds the
    AVERAGE divide and unpacks — exactly one XLA dispatch per fusion
    group.  Returns False to fall back to the per-tensor eager path
    (unbuildable spec)."""
    o0 = group[0]
    op_kernel = _OP_KERNEL[o0.red_op]
    mesh_key = tuple(mesh.devices.flat)
    variant = "sp_pr" if layout else "sp_rep"
    psid = 0 if ps is None else ps.process_set_id
    spec = _megakernel.GroupSpec(
        mesh_key=mesh_key, variant=variant,
        op=op_kernel, average=o0.red_op == ReduceOp.AVERAGE, denom=denom,
        dtype=jnp.dtype(o0.contrib.dtype).name,
        shapes=tuple(tuple(o.contrib.shapes[0]) for o in group),
        donate=tuple(bool(o.contrib.owned) for o in group),
        hier=_megakernel.hierarchy_for(mesh_key, op_kernel,
                                       o0.contrib.dtype, group_fmt=fmt),
        quant=fmt)
    values = [o.contrib.value for o in group]
    donate_mask = list(spec.donate)
    res_keys: List[tuple] = []
    if _megakernel._needs_quant_build(spec):
        use_ef = (fmt is not None and fmt.kind == "quant"
                  and fmt.error_feedback and spec.hier is None)
        if use_ef:
            # Error-feedback residual: executor-owned flat group buffer
            # fed back in (and donated) each step, replaced by the
            # kernel's residual output after the launch.  take_
            # semantics: once donated, the store must not reference it.
            res_keys = [("g", psid) + tuple(o.name for o in group)]
            T = sum(int(np.prod(s, dtype=np.int64)) if s else 1
                    for s in spec.shapes)
            res_shape = (len(mesh_key), T) if layout else (T,)
            stored = _megakernel.take_residual(
                res_keys[0], o0.contrib.dtype, [res_shape])
            values.append(stored if stored is not None
                          else np.zeros(res_shape,
                                        jnp.dtype(o0.contrib.dtype)))
            donate_mask.append(True)
        tick = _megakernel.next_tick(_quant_group_key(
            variant, psid, [o.name for o in group], fmt))
        values.append(np.asarray(
            [_compression.quant_seed(), tick], np.uint32))
        donate_mask.append(False)
    if tl: _tl_group_start(tl, group)
    try:
        outs = _megakernel.launch(
            spec, mesh, values,
            digest_fn=_group_digest_fn(group, psid, fmt),
            donate_mask=donate_mask)
    except Exception as e:  # noqa: BLE001 — unbuildable spec
        import traceback

        traceback.print_exc(file=sys.stderr)
        if tl:
            for o in group:
                tl.activity_end(o.name)
                tl.end(o.name, dtype=str(o.contrib.dtype))
        consumed = any(d and isinstance(v, jax.Array) and v.is_deleted()
                       for v, d in zip(values, donate_mask))
        if res_keys and consumed:
            # The stored residual buffers were donated into a launch
            # that died: they reference deleted memory — restart them
            # from zero rather than poison the next launch.
            _megakernel.drop_residuals(res_keys)
        if not consumed:
            return False  # inputs intact: per-tensor eager fallback
        # A RUNTIME failure after XLA already consumed the donated
        # inputs (trace/compile errors leave them intact): an eager
        # retry would read deleted buffers — fail the group loudly at
        # synchronize instead (mirrors _launch_mp_megakernel).
        err = HorovodError(
            f"megakernel launch failed after its inputs were donated "
            f"({type(e).__name__}: {e}); the group cannot fall back to "
            f"the per-tensor path.")
        for o in group:
            hm._get(o.handle).result = err
        return True
    if res_keys:
        _megakernel.store_residuals(res_keys, [outs[-1]])
        outs = outs[:len(group)]
    for o, out in zip(group, outs):
        # Donated (or simply consumed) input: nothing may read it after
        # dispatch — drop the reference so use-after-donate is
        # impossible by construction (tests/test_megakernel.py probes
        # this with weakrefs).
        o.contrib.value = None
        hm._get(o.handle).result = out
    if tl: _tl_group_end(tl, group, spec.hier)
    return True


def _eager_quantized_group(group: List["_QueuedOp"], layout: bool,
                           denom: int, ps, mesh, tl, hm, fmt) -> None:
    """Per-tensor-executor fallback for a quantized group
    (HVD_TPU_MEGAKERNEL=0, or an unbuildable fused spec): the
    eager-quantized REFERENCE math (ops/compression.reference_allreduce
    — the function the megakernel is tested bitwise against), driven by
    the same residual store and tick counter as the fused path.  Always
    the flat two-phase formulation — the hierarchical per-leg pipeline
    exists only inside the fused executable."""
    n = len(tuple(mesh.devices.flat))
    psid = 0 if ps is None else ps.process_set_id
    variant = "sp_pr" if layout else "sp_rep"
    dtype = jnp.dtype(group[0].contrib.dtype)
    use_ef = fmt.error_feedback
    res_key = ("g", psid) + tuple(o.name for o in group)
    if tl: _tl_group_start(tl, group)
    if layout:
        rows = jnp.concatenate(
            [jnp.asarray(o.contrib.value).reshape(n, -1) for o in group],
            axis=1)
    else:
        flat = jnp.concatenate(
            [jnp.ravel(jnp.asarray(o.contrib.value)) for o in group])
        rows = jnp.broadcast_to(flat[None], (n, flat.shape[0]))
    T = rows.shape[1]
    residuals = None
    if use_ef:
        res_shape = (n, T) if layout else (T,)
        stored = _megakernel.take_residual(res_key, dtype, [res_shape])
        residuals = jnp.asarray(
            stored if stored is not None
            else np.zeros(res_shape, dtype))
        if not layout:
            residuals = jnp.broadcast_to(residuals[None], (n, T))
    tick = _megakernel.next_tick(_quant_group_key(
        variant, psid, [o.name for o in group], fmt))
    red, r_new = _compression.reference_allreduce(
        rows, fmt, tick, residuals=residuals, shared_noise=not layout)
    if r_new is not None:
        _megakernel.store_residuals(
            [res_key], [r_new if layout else r_new[0]])
    offs = 0
    for o in group:
        cnt = int(np.prod(o.contrib.shapes[0], dtype=np.int64)) \
            if o.contrib.shapes[0] else 1
        shape = tuple(o.contrib.shapes[0])
        piece = red[offs:offs + cnt].reshape(shape)
        if o.red_op == ReduceOp.AVERAGE:
            piece = _divide(piece, denom)
        if layout:
            piece = jnp.broadcast_to(piece[None], (n,) + shape)
        offs += cnt
        o.contrib.value = None
        hm._get(o.handle).result = piece
    if tl: _tl_group_end(tl, group, None)


def _launch_mp_megakernel(resp: Response, ops: List["_QueuedOp"], ps,
                          mesh, denom: int, tl, hm) -> bool:
    """Multi-process fused launch of one coordinator response,
    sub-partitioned by the compression policy's per-tensor wire format
    (the partition is a pure function of negotiated fields + the
    rank-uniform policy, so every process splits the response
    identically).  A bucket whose fused spec is unbuildable falls back
    to the per-bucket eager path — deterministically on every rank.
    Returns True once the whole response is handled."""
    by_name = {o.name: o for o in ops}
    dtype = (jnp.dtype(ops[0].contrib.dtype) if ops
             else jnp.dtype(wire.np_dtype_of(resp.tensor_type)))
    red_op = ops[0].red_op if ops else resp.reduce_op
    psid = 0 if ps is None else ps.process_set_id
    shapes = []
    for pos, name in enumerate(resp.tensor_names):
        o = by_name.get(name)
        if o is not None:
            shapes.append(tuple(o.contrib.shapes[0]))
        else:
            shapes.append(tuple(resp.tensor_shapes[pos])
                          if pos < len(resp.tensor_shapes)
                          else tuple(resp.tensor_shapes[0]))
    buckets: Dict[Any, List[int]] = {}
    order: List[Any] = []
    for pos, name in enumerate(resp.tensor_names):
        fmt = _tensor_wire_format(name, psid, red_op, dtype, shapes[pos])
        if fmt not in buckets:
            buckets[fmt] = []
            order.append(fmt)
        buckets[fmt].append(pos)
    for fmt in order:
        idxs = buckets[fmt]
        names_sub = [resp.tensor_names[i] for i in idxs]
        shapes_sub = [shapes[i] for i in idxs]
        if not _launch_mp_megakernel_sub(
                names_sub, shapes_sub, by_name, ps, mesh, denom, tl, hm,
                fmt, red_op, dtype, psid):
            _eager_mp_subset(names_sub, shapes_sub, by_name, ps, denom,
                             red_op, dtype, tl, hm)
    return True


def _launch_mp_megakernel_sub(names: List[str], shapes: List[tuple],
                              by_name: Dict[str, "_QueuedOp"], ps, mesh,
                              denom: int, tl, hm, fmt, red_op, dtype,
                              psid: int) -> bool:
    """One wire-format bucket of a multi-process response: one jitted
    local pack (donating executor-owned contributions) → one donated
    reduce+divide+unpack executable over the process mesh — quantized
    in-kernel when ``fmt`` says so.  Handles the joined-rank case
    transparently: ``names`` may include tensors this rank never
    submitted — they contribute zeros and their outputs are discarded,
    exactly like the peers' buffer."""
    values = []
    donate = []
    for name, shp in zip(names, shapes):
        o = by_name.get(name)
        if o is not None:
            values.append(o.contrib.value)
            donate.append(bool(o.contrib.owned))
        else:
            values.append(jnp.zeros(shp, dtype))  # joined: zero slot
            donate.append(True)
    avg = red_op == ReduceOp.AVERAGE
    op_kernel = _OP_KERNEL[red_op]
    mesh_key = tuple(mesh.devices.flat)
    spec = _megakernel.GroupSpec(
        mesh_key=mesh_key, variant="mp", op=op_kernel, average=avg,
        denom=denom, dtype=dtype.name, shapes=tuple(shapes),
        donate=(True,),  # the packed buffer is always executor-owned
        hier=_megakernel.hierarchy_for(mesh_key, op_kernel, dtype,
                                       group_fmt=fmt),
        quant=fmt)
    group = [by_name[n] for n in names if n in by_name]
    if tl: _tl_group_start(tl, group)
    consumed = False
    res_key = None
    try:
        pack = _megakernel.packer(tuple(shapes), dtype.name,
                                  tuple(donate), mesh_key)
        flat = pack(*values)
        # Fallback is only off the table if the pack REALLY donated a
        # contribution the eager path would need (mirrors the
        # is_deleted probe of _launch_group_megakernel; all-user-held
        # groups donate nothing and stay recoverable).
        consumed = any(d and isinstance(v, jax.Array) and v.is_deleted()
                       for v, d in zip(values, donate))
        buf = _mp_global(flat, ps)
        launch_values = [buf]
        donate_mask = [True]
        if _megakernel._needs_quant_build(spec):
            use_ef = (fmt is not None and fmt.kind == "quant"
                      and fmt.error_feedback and spec.hier is None)
            if use_ef:
                T = sum(int(np.prod(s, dtype=np.int64)) if s else 1
                        for s in shapes)
                Pn = len(mesh_key)
                res_key = ("g", psid) + tuple(names)
                # The live residual is the previous launch's [P, T]
                # global OUTPUT, reused on-device (no per-step
                # device→host→device round trip); a checkpoint-restored
                # local [T] numpy shard re-uploads once.
                stored = _megakernel.take_residual(
                    res_key, dtype, [(Pn, T), (T,)])
                if isinstance(stored, jax.Array) \
                        and stored.shape == (Pn, T):
                    res_buf = stored
                elif stored is not None:
                    res_buf = _mp_global(jnp.asarray(stored), ps)
                else:
                    res_buf = _mp_global(jnp.zeros((T,), dtype), ps)
                launch_values.append(res_buf)
                donate_mask.append(True)
            tick = _megakernel.next_tick(
                _quant_group_key("mp", psid, names, fmt))
            launch_values.append(np.asarray(
                [_compression.quant_seed(), tick], np.uint32))
            donate_mask.append(False)
        outs = _megakernel.launch(
            spec, mesh, launch_values,
            digest_fn=_group_digest_fn(group, psid, fmt)
            if group else None,
            donate_mask=donate_mask)
    except Exception as e:  # noqa: BLE001 — unbuildable spec
        import traceback

        traceback.print_exc(file=sys.stderr)
        if tl:
            for o in group:
                tl.activity_end(o.name)
                tl.end(o.name, dtype=str(o.contrib.dtype))
        if res_key is not None:
            _megakernel.drop_residuals([res_key])
        if not consumed:
            return False  # inputs intact: per-tensor eager fallback
        # The pack already donated the executor-owned inputs; an eager
        # retry would read deleted buffers.  Fail the group loudly at
        # synchronize instead of silently wedging it.
        err = HorovodError(
            f"megakernel launch failed after the fusion buffer was "
            f"packed ({type(e).__name__}: {e}); the group cannot fall "
            f"back to the per-tensor path.")
        for o in group:
            hm._get(o.handle).result = err
        return True
    if res_key is not None:
        # Store the residual output — a P(hvd)-sharded [P, T] global —
        # AS the device array: the next launch donates it straight back
        # in (compression_state() exports the addressable shard when a
        # snapshot is taken).
        _megakernel.store_residuals([res_key], [outs[-1]])
        outs = outs[:-1]
    for name, out in zip(names, outs):
        o = by_name.get(name)
        if o is not None:
            o.contrib.value = None  # consumed: see _launch_group_megakernel
            hm._get(o.handle).result = out
    if tl: _tl_group_end(tl, group, spec.hier)
    return True


def _eager_mp_subset(names: List[str], shapes: List[tuple],
                     by_name: Dict[str, "_QueuedOp"], ps, denom: int,
                     red_op, dtype, tl, hm) -> None:
    """Eager (uncompressed) execution of one wire-format bucket of a
    multi-process response — the deterministic per-bucket fallback when
    its fused spec is unbuildable.  A quantized bucket landing here
    loses its compression for the step, never its correctness (every
    rank takes the same branch, so the SPMD programs still match)."""
    _, ks = (_mp_kernels() if ps is None else ps.mesh_and_kernels())
    group = [by_name[n] for n in names if n in by_name]
    for o in group:
        if tl: _tl_start(tl, o, "ALLREDUCE")
        if tl: tl.activity_start(o.name, "MEMCPY_IN_FUSION_BUFFER")

    def numel(s):
        return int(np.prod(s, dtype=np.int64)) if s else 1

    parts = [jnp.ravel(by_name[n].contrib.value) if n in by_name
             else jnp.zeros((numel(s),), dtype)
             for n, s in zip(names, shapes)]
    buf = jnp.concatenate(parts) if len(parts) > 1 else parts[0]
    for o in group:
        if tl: tl.activity_end(o.name)
        if tl: tl.activity_start(o.name, "XLA_ALLREDUCE")
    red = ks[_OP_KERNEL[red_op] + "_out_rep"](_mp_global(buf, ps))
    offs = 0
    for n, s in zip(names, shapes):
        o = by_name.get(n)
        cnt = numel(s)
        if o is not None:
            if tl: tl.activity_end(o.name)
            if tl: tl.activity_start(o.name, "MEMCPY_OUT_FUSION_BUFFER")
            piece = red[offs:offs + cnt].reshape(s)
            if o.red_op == ReduceOp.AVERAGE:
                piece = _divide(piece, denom)
            if tl: tl.activity_end(o.name)
            if tl: tl.end(o.name, dtype=str(o.contrib.dtype))
            hm._get(o.handle).result = piece
        offs += cnt


# ---------------------------------------------------------------------------
# Async op queue with Tensor Fusion execution
# ---------------------------------------------------------------------------

@dataclass
class _QueuedOp:
    name: str
    op: RequestType
    contrib: _Contribution
    red_op: ReduceOp
    root_rank: int
    handle: int
    nbytes: int
    ps: Any = None  # ProcessSet for non-global ops
    # This rank's wire Request (multi-process; rank 0's in
    # single-process), retained so the response cache can store the
    # exact negotiated request at insertion time (ops/cache.py).
    request: Any = None
    # True when negotiation was served from the response cache — rides
    # the timeline EXECUTE span so cache wins are visible per tensor.
    cache_hit: bool = False
    # perf_counter at enqueue: the telemetry negotiate-latency stamp
    # (the one clock read this op spends before execution).
    t_submit: float = 0.0
    # monotonic at enqueue (hvd-trace): start of the negotiate.wait
    # span.  Separate stamp because spans must live on the clock the
    # offset estimator aligns; 0.0 = tracing disabled at enqueue.
    t_submit_mono: float = 0.0


@_races.race_checked
class _OpQueue:
    """Pending async collectives awaiting (possibly fused) execution.

    Plays the role of the reference's message_queue + fusion loop
    (operations.cc:1226-1374): async calls enqueue; ``drain`` polls the
    coordinator for (fused) responses and launches the XLA collectives.
    """

    def __init__(self) -> None:
        self._lock = _lockorder.make_lock("OpQueue._lock")
        self._ops: Dict[str, _QueuedOp] = {}  # guarded_by: _lock

    def put(self, op: _QueuedOp) -> None:
        with self._lock:
            if op.name in self._ops:
                raise ValueError(
                    f"A collective named {op.name!r} is already pending; "
                    f"tensor names must be unique among in-flight ops "
                    f"(reference keys its TensorTable the same way, "
                    f"operations.cc:1568-1572).")
            self._ops[op.name] = op

    def take(self, names: Sequence[str]) -> List[_QueuedOp]:
        with self._lock:
            out = []
            for n in names:
                op = self._ops.pop(n, None)
                if op is not None:
                    out.append(op)
            return out

    def pending_meta(self) -> Dict[str, int]:
        with self._lock:
            return {n: o.nbytes for n, o in self._ops.items()}

    def peek_ps(self, name: str):
        """The ProcessSet of a pending op (None = global / unknown) —
        lets synchronize route a withdrawal to the right coordinator."""
        with self._lock:
            op = self._ops.get(name)
            return None if op is None else op.ps


_queue = _OpQueue()
_drain_lock = _lockorder.make_lock("collective._drain_lock")

# Background tick cadence — same 5 ms as the reference's coordinator loop
# (operations.cc:1221).  The thread only serves *async* eager ops; sync ops
# and the static path never wait on it.
TICK_SECONDS = 0.005


def _background_loop(stop_event: threading.Event) -> None:  # thread: drain
    """≙ BackgroundThreadLoop (operations.cc:1167-1475): drain the async op
    queue on a fixed tick so ``*_async`` collectives make progress even if
    the caller never polls.  The period is runtime-adjustable
    (HOROVOD_CYCLE_TIME / the autotuner)."""
    _athreads.set_role("drain")
    import traceback

    st = _state.global_state()
    while not stop_event.wait(st.tick_seconds or TICK_SECONDS):
        try:
            # hvd-chaos coord.tick_delay: a starved/descheduled drain
            # thread — the runtime must tolerate arbitrary tick jitter
            # (stall warnings may fire; results must not change).
            if _chaos.active():
                _chaos.sleep_site("coord.tick_delay")
            _drain()
        except Exception:
            # Validation errors never propagate here (they are stored on
            # handles); anything that does is a runtime bug — report it
            # rather than silently dropping queued ops, but keep ticking.
            # The flight ring dumps too: the drain thread IS the control
            # plane, and the events before the exception are the
            # diagnosis.
            _telemetry.exception_event("drain", traceback.format_exc())
            traceback.print_exc(file=sys.stderr)


def _submit_requests(name: str, op: RequestType, c: _Contribution,
                     root_rank: int = -1,
                     red_op: ReduceOp = ReduceOp.SUM, ps=None,
                     splits: Tuple[int, ...] = (),
                     queued_op: Optional[_QueuedOp] = None) -> bool:
    """Submit the negotiation request(s) for one collective; returns
    True when negotiation was served from the response cache (the
    steady-state fast path, ops/cache.py)."""
    st = _state.global_state()
    psid = 0 if ps is None else ps.process_set_id
    if st.timeline is not None:
        st.timeline.negotiate_start(name, op.name)
    if st.multiprocess:
        # One request per process, carrying only THIS process's metadata —
        # cross-rank validation happens on real information at the rank-0
        # coordinator (≙ the MPI_Gatherv of MPIRequests,
        # operations.cc:1240-1288).  Set requests carry SET-LOCAL ranks.
        rank = st.process_index if ps is None else ps.rank()
        req = Request(
            request_rank=rank, request_type=op,
            tensor_type=wire.dtype_of(c.dtype), tensor_name=name,
            root_rank=root_rank, device=c.devices[0],
            tensor_shape=c.shapes[0], reduce_op=red_op,
            process_set_id=psid, splits=splits)
        if queued_op is not None:
            # Set BEFORE the send: once the request is on the wire a
            # response may arrive any time, and the cache insertion
            # reads it from the queued op.
            queued_op.request = req
        return bool(st.transport.submit(req))
    coord = st.coordinator if ps is None else ps.coordinator
    hit_any = False
    for r in range(st.size if ps is None else ps.size()):
        req = Request(
            request_rank=r, request_type=op,
            tensor_type=wire.dtype_of(c.dtype), tensor_name=name,
            root_rank=root_rank, device=c.devices[r],
            tensor_shape=c.shapes[r], reduce_op=red_op,
            process_set_id=psid, splits=splits)
        if queued_op is not None and r == 0:
            queued_op.request = req
        _, hit = coord.submit_ex(req)
        hit_any = hit_any or hit
    return hit_any


def _tl_start(tl, o: _QueuedOp, op_name: str) -> None:
    """Open the tensor's top-level EXECUTE-phase span, tagged with
    whether its negotiation was served from the response cache (the
    NEGOTIATE span carries phase=NEGOTIATE symmetrically, so cache wins
    are visible per tensor in the Chrome trace)."""
    tl.start(o.name, op_name,
             args={"phase": "EXECUTE",
                   "cache": "hit" if o.cache_hit else "miss"})


_DATA_RESPONSES = (ResponseType.ALLREDUCE, ResponseType.ALLGATHER,
                   ResponseType.BROADCAST, ResponseType.REDUCESCATTER,
                   ResponseType.ALLTOALL)


def _execute_response(resp: Response, ops: List[_QueuedOp]) -> None:
    """Telemetry shell around :func:`_execute_response_inner`: one
    perf_counter pair per response feeds the negotiate- and
    execute-latency histograms, payload bytes and fusion-group width;
    ERROR and dead-peer SHUTDOWN responses additionally dump the flight
    ring — the forensic record of the 2000 control-plane events that
    led here."""
    tracing = _trace.enabled()
    if not _telemetry.enabled() and not tracing:
        return _execute_response_inner(resp, ops)
    t0 = time.perf_counter()
    mt0 = time.monotonic() if tracing else 0.0
    is_data = resp.response_type in _DATA_RESPONSES
    if _telemetry.enabled():
        for o in ops:
            if o.t_submit:
                _M_NEGOTIATE_S.observe(t0 - o.t_submit)
            _M_PAYLOAD_B.observe(o.nbytes)
        if is_data:
            _M_GROUP_WIDTH.observe(len(resp.tensor_names))
        elif resp.response_type == ResponseType.ERROR:
            _M_ERRORS.inc(max(len(ops), 1))
            _telemetry.error_event(resp.error_message or "")
        elif resp.response_type == ResponseType.SHUTDOWN and \
                wire.DEAD_PEER_MARKER in (resp.error_message or ""):
            # Worker-side dead-peer poison (the controller side dumps in
            # _handle_lost_ranks before broadcasting this diagnosis).
            _telemetry.dead_peer_event(resp.error_message or "")
    out = _execute_response_inner(resp, ops)
    # Counted AFTER a successful data launch only: an ERROR/SHUTDOWN
    # response (or an exception from the executor) must not inflate the
    # success counter — "failed = submitted - completed" has to read
    # true during a failure storm.
    if ops and is_data and _telemetry.enabled():
        _M_COMPLETED.inc(len(ops))
        _M_EXECUTE_S.observe(time.perf_counter() - t0)
    if ops and tracing and (is_data
                            or resp.response_type == ResponseType.ERROR):
        # hvd-trace: (1) the negotiate.wait span — this rank's local
        # submit up to execution.  Every participating rank's wait span
        # for one collective CONTAINS the shared window [last submit,
        # broadcast], so same-(step, cycle) spans are guaranteed to
        # overlap across ranks once clocks are aligned — the fleet
        # -trace acceptance property.  (2) the dispatch span — the
        # response execution (pack + launch + unpack); the launch span
        # it contains (ops/megakernel.launch) lets the analyzer carve
        # it into pack / collective / dcn / unpack legs.  ERROR
        # responses trace too (the error path is real work and the
        # control-plane-only tests ride it); the completed counter
        # above stays data-only.
        t_neg = min((o.t_submit_mono for o in ops
                     if o.t_submit_mono > 0.0), default=0.0)
        if t_neg:
            _trace.span("negotiate.wait", "negotiate", t_neg, mt0,
                        args={"tensors": len(resp.tensor_names)})
        _trace.span(
            f"execute/{resp.response_type.name.lower()}",
            "dispatch", mt0, time.monotonic(),
            args={"tensors": len(resp.tensor_names),
                  "first": resp.tensor_names[0]
                  if resp.tensor_names else ""})
    return out


def _execute_response_inner(resp: Response, ops: List[_QueuedOp]) -> None:
    """Launch the XLA collective(s) for one coordinator response.

    A fused ALLREDUCE response concatenates its tensors into one flat
    buffer (MEMCPY_IN_FUSION_BUFFER), reduces once, and splits results back
    (MEMCPY_OUT_FUSION_BUFFER) — the reference's Tensor Fusion
    (operations.cc:941-1034) expressed as XLA ops so the compiler can fuse
    the copies into the collective.
    """
    st = _state.global_state()
    tl = st.timeline
    hm = st.handle_manager

    if resp.response_type == ResponseType.CACHE_FLUSH:
        return  # response-cache epoch marker; handled by observe_response

    if resp.response_type == ResponseType.RETUNE:
        # hvd-tune knob marker: every rank applies the carried knob
        # values HERE — the same response-stream position fleet-wide —
        # so env knobs, compiled-kernel caches and cache replicas flip
        # at one cycle boundary (tuning/actuation.py).
        from ..tuning import actuation as _actuation

        _actuation.apply_marker(resp, st)
        return

    if resp.response_type == ResponseType.ERROR:
        err = HorovodError(resp.error_message)
        for o in ops:
            hm._get(o.handle).result = err  # surfaced at synchronize/poll
        return

    if resp.response_type == ResponseType.JOIN:
        # Release from hvd.join(): every rank joined; tensor_sizes
        # carries the last joining rank (join()'s return value).
        st.join_result = resp.tensor_sizes[0] if resp.tensor_sizes else -1
        return

    if resp.response_type == ResponseType.SHUTDOWN:
        # A rank initiated shutdown (or died): flush everything pending
        # with the shut-down error — carrying the initiator's diagnosis
        # when present — and refuse new work (operations.cc:1377-1403).
        # A diagnosis naming a dead process means that process can never
        # reach jax.distributed's exit barrier — every survivor (not just
        # the controller) must skip it or block 300 s and abort.  Clean
        # cooperative shutdowns carry no marker and keep the barrier.
        if wire.DEAD_PEER_MARKER in (resp.error_message or ""):
            from ..core.cluster import disarm_distributed_shutdown

            disarm_distributed_shutdown()
        st.peer_shutdown = True
        _poison_pending(resp.error_message or SHUT_DOWN_ERROR_MESSAGE)
        return

    if st.multiprocess:
        _execute_response_mp(resp, ops)
        return

    # Process-set responses execute over the set's sub-mesh with the
    # set's member count as the averaging denominator.
    ps = _state.get_process_set(resp.process_set_id) \
        if resp.process_set_id else None
    denom = st.size if ps is None else ps.size()

    if resp.response_type == ResponseType.ALLREDUCE:
        ks = _mesh_kernels() if ps is None else ps.mesh_and_kernels()[1]
        mesh = st.mesh if ps is None else ps.mesh_and_kernels()[0]
        # Sub-group by layout: per-replica vs replicated inputs reduce with
        # different shardings and cannot share one flat buffer.  The group
        # is homogeneous in red_op (the coordinator fuses like-op only).
        psid = 0 if ps is None else ps.process_set_id
        for layout in (True, False):
            lgroup = [o for o in ops if o.contrib.per_replica == layout]
            if not lgroup:
                continue
            # Sub-partition by the compression policy's per-tensor wire
            # format (embeddings int8, layernorm/scalars uncompressed,
            # ...): tensors with different codecs cannot share one
            # fused executable.  With the default policy (none) this is
            # a single bucket — the pre-quantization behavior.
            for fmt, group in _partition_by_wire(lgroup, psid):
                # Megakernel path (default): one donated
                # pack→reduce→unpack executable per fusion group — a
                # single XLA dispatch, with the AVERAGE divide (and the
                # quantize/dequantize pipeline) folded in and a
                # hierarchical ICI×DCN reduction on multi-slice meshes
                # (ops/megakernel.py).
                if _megakernel_eligible(group) \
                        and _launch_group_megakernel(
                            group, layout, denom, ps, mesh, tl, hm, fmt):
                    continue
                if fmt is not None and fmt.kind == "quant":
                    # Eager fallback keeps the quantized semantics via
                    # the reference math (same residuals, same ticks).
                    _eager_quantized_group(group, layout, denom, ps,
                                           mesh, tl, hm, fmt)
                    continue
                # Eager fallback (HVD_TPU_MEGAKERNEL=0): the per-tensor
                # choreography — also the bench's comparison baseline.
                avg = group[0].red_op == ReduceOp.AVERAGE
                kernel = ks[_OP_KERNEL[group[0].red_op]
                            + ("_pr" if layout else "_rep")]
                wire_dt = jnp.dtype(fmt.wire_dtype) if fmt is not None \
                    else None
                if len(group) == 1 and fmt is None:
                    o = group[0]
                    if tl: _tl_start(tl, o, "ALLREDUCE")
                    if tl: tl.activity_start(o.name, "XLA_ALLREDUCE")
                    if avg:
                        # Single-tensor AVERAGE: divide folded into the
                        # compiled kernel, not a separate eager dispatch.
                        out = ks["psum_pr_avg" if layout
                                 else "psum_rep_avg"](o.contrib.value)
                    else:
                        out = kernel(o.contrib.value)
                    if tl: tl.activity_end(o.name)
                    if tl: tl.end(o.name, dtype=str(o.contrib.dtype))
                    hm._get(o.handle).result = out
                    continue
                # Fused path (also the cast-wire path: compress the
                # flat buffer, reduce in the wire dtype, decompress
                # BEFORE the divide — the compression.py order).
                for o in group:
                    if tl: _tl_start(tl, o, "ALLREDUCE")
                    if tl: tl.activity_start(o.name,
                                             "MEMCPY_IN_FUSION_BUFFER")
                if layout:
                    # per-replica: flatten payload per replica, concat
                    # axis 1.
                    parts = [o.contrib.value.reshape(st.size, -1)
                             for o in group]
                    buf = jnp.concatenate(parts, axis=1)
                else:
                    buf = jnp.concatenate(
                        [jnp.ravel(o.contrib.value) for o in group])
                for o in group:
                    if tl: tl.activity_end(o.name)
                    if tl: tl.activity_start(o.name, "XLA_ALLREDUCE")
                if wire_dt is not None:
                    red = kernel(buf.astype(wire_dt)).astype(buf.dtype)
                else:
                    red = kernel(buf)
                offs = 0
                for o in group:
                    n = int(np.prod(o.contrib.shapes[0],
                                    dtype=np.int64)) if \
                        o.contrib.shapes[0] else 1
                    if tl: tl.activity_end(o.name)
                    if tl: tl.activity_start(o.name,
                                             "MEMCPY_OUT_FUSION_BUFFER")
                    if layout:
                        piece = red[:, offs:offs + n].reshape(
                            (st.size,) + tuple(o.contrib.shapes[0]))
                    else:
                        piece = red[offs:offs + n].reshape(
                            o.contrib.shapes[0])
                    offs += n
                    if o.red_op == ReduceOp.AVERAGE:
                        piece = _divide(piece, denom)
                    if tl: tl.activity_end(o.name)
                    if tl: tl.end(o.name, dtype=str(o.contrib.dtype))
                    hm._get(o.handle).result = piece
        return

    if resp.response_type == ResponseType.ALLTOALL:
        ks = _mesh_kernels() if ps is None else ps.mesh_and_kernels()[1]
        n = denom
        matrix = np.asarray(resp.tensor_sizes,
                            dtype=np.int64).reshape(n, n)
        M = int(matrix.max()) if matrix.size else 0
        # Pad-to-max staging runs ON DEVICE as one vectorized gather
        # (round-4 verdict: the previous host double loop built an
        # O(n²·M) numpy matrix with per-element copies).  The index
        # plan is O(n²·M) int32 built with numpy broadcasting — the
        # payload itself never round-trips through the host.
        starts = np.zeros((n, n), np.int64)
        if matrix.size:
            starts[:, 1:] = np.cumsum(matrix, axis=1)[:, :-1]
        Mp = max(M, 1)
        m_idx = np.arange(Mp)
        row_last = np.maximum(matrix.sum(axis=1), 1)[:, None, None] - 1
        gather_idx = jnp.asarray(np.minimum(  # [sender, dest, M]; the
            starts[:, :, None] + m_idx[None, None, :],  # clamp keeps
            row_last).astype(np.int32))                 # padding legal
        pad_mask = jnp.asarray(m_idx[None, None, :] < matrix[:, :, None])
        for o in ops:
            c = o.contrib
            if tl: _tl_start(tl, o, "ALLTOALL")
            if tl: tl.activity_start(o.name, "XLA_ALLTOALL")
            rest = tuple(c.shapes[0][1:])
            x = jnp.asarray(c.value)
            per_sender = (x if c.per_replica
                          else jnp.broadcast_to(x[None], (n,) + x.shape))
            L = int(per_sender.shape[1])
            if L == 0:  # nobody sends anything
                send = jnp.zeros((n, n, Mp) + rest, x.dtype)
            else:
                flat = per_sender.reshape(n, L, -1)
                g = jnp.take_along_axis(
                    flat, gather_idx.reshape(n, n * Mp)[:, :, None],
                    axis=1)
                send = jnp.where(
                    pad_mask.reshape(n, n, Mp, *([1] * len(rest))),
                    g.reshape((n, n, Mp) + rest),
                    jnp.zeros((), g.dtype))  # keep bool/int dtypes
            if ps is None:
                placed = shard(send)
            else:
                mesh_ps, _ = ps.mesh_and_kernels()
                spec = [None] * send.ndim
                spec[0] = REPLICA_AXIS
                placed = jax.device_put(
                    send, NamedSharding(mesh_ps, P(*spec)))
            recv = ks["a2a_pr"](placed)  # [recv, sender, M, ...]
            outs = [
                jnp.concatenate([recv[r, s, :int(matrix[s, r])]
                                 for s in range(n)], axis=0)
                for r in range(n)
            ]
            if tl: tl.activity_end(o.name)
            if tl: tl.end(o.name, dtype=str(c.dtype))
            hm._get(o.handle).result = outs
        return

    if resp.response_type == ResponseType.REDUCESCATTER:
        ks = _mesh_kernels() if ps is None else ps.mesh_and_kernels()[1]
        for o in ops:  # never fused: each op owns its chunk layout
            if tl: _tl_start(tl, o, "REDUCESCATTER")
            if tl: tl.activity_start(o.name, "XLA_REDUCESCATTER")
            # AVERAGE folds its divide into the compiled kernel — one
            # launch instead of reduce + a separate eager _divide.
            avg = "_avg" if o.red_op == ReduceOp.AVERAGE else ""
            kernel = ks[("rscatter_pr" if o.contrib.per_replica
                         else "rscatter_rep") + avg]
            out = kernel(o.contrib.value)
            if tl: tl.activity_end(o.name)
            if tl: tl.end(o.name, dtype=str(o.contrib.dtype))
            hm._get(o.handle).result = out
        return

    if resp.response_type == ResponseType.ALLGATHER:
        ks = _mesh_kernels() if ps is None else ps.mesh_and_kernels()[1]
        for o in ops:
            c = o.contrib
            if tl: _tl_start(tl, o, "ALLGATHER")
            if tl: tl.activity_start(o.name, "XLA_ALLGATHER")
            if c.ragged or isinstance(c.value, list):
                sizes = list(resp.tensor_sizes or c.orig_sizes)
                dmax = max(sizes)
                rest = tuple(c.shapes[0][1:])
                total = int(sum(sizes))
                k = len(c.value)
                if total == 0 or dmax == 0:
                    out = jnp.zeros((0,) + rest, c.dtype)
                else:
                    # Vectorized pad/stack (round-4 alltoall treatment
                    # applied here): the padded [k, dmax, rest] staging
                    # buffer is built with ONE device-side gather over
                    # the concatenated contributions instead of a
                    # per-tensor host loop of jnp.concatenate zero-pads
                    # — the O(k) eager-dispatch chain becomes 2
                    # launches.  The index plan is host-side int32;
                    # clamped duplicate rows stand in for the zero
                    # padding (both are sliced off by the unpad below,
                    # so the values never surface).
                    sz = np.asarray(sizes, np.int64)
                    starts = np.zeros(k, np.int64)
                    starts[1:] = np.cumsum(sz)[:-1]
                    j = np.arange(dmax)
                    gather_idx = starts[:, None] + np.minimum(
                        j[None, :], np.maximum(sz[:, None] - 1, 0))
                    gather_idx = np.clip(gather_idx, 0,
                                         total - 1).astype(np.int32)
                    flat = jnp.concatenate(
                        [jnp.asarray(v) for v in c.value], axis=0)
                    padded = jnp.take(flat, jnp.asarray(gather_idx),
                                      axis=0)  # [k, dmax, rest...]
                    if ps is None:
                        padded = shard(padded)
                    else:
                        mesh_ps, _ = ps.mesh_and_kernels()
                        spec = [None] * padded.ndim
                        spec[0] = REPLICA_AXIS
                        padded = jax.device_put(
                            padded, NamedSharding(mesh_ps, P(*spec)))
                    gathered = ks["gather_pr"](padded)  # [k*dmax, ...]
                    # Unpad with one gather too: row plan of each
                    # rank's first s_i rows, in rank order.
                    unpad_idx = np.concatenate(
                        [i * dmax + np.arange(s)
                         for i, s in enumerate(sizes)]).astype(np.int32)
                    out = jnp.take(gathered, jnp.asarray(unpad_idx),
                                   axis=0)
            elif c.per_replica:
                out = ks["gather_pr"](c.value)
            else:
                out = ks["gather_rep"](c.value)
            if tl: tl.activity_end(o.name)
            if tl: tl.end(o.name, dtype=str(c.dtype))
            hm._get(o.handle).result = out
        return

    if resp.response_type == ResponseType.BROADCAST:
        ks = _mesh_kernels() if ps is None else ps.mesh_and_kernels()[1]
        for o in ops:
            c = o.contrib
            if tl: _tl_start(tl, o, "BROADCAST")
            if tl: tl.activity_start(o.name, "XLA_BCAST")
            if c.per_replica:
                out = ks["bcast_pr"](c.value, jnp.int32(o.root_rank))
            else:
                # Replicated input: broadcast is the identity, but still run
                # a collective for execution parity with the reference's
                # unconditional MPI_Bcast (operations.cc:1053-1055) —
                # psum(x)/n compiled as ONE kernel, not psum + an eager
                # divide launch.
                out = ks["bcast_rep"](c.value) \
                    if jnp.issubdtype(c.value.dtype, jnp.inexact) \
                    else c.value
            if tl: tl.activity_end(o.name)
            if tl: tl.end(o.name, dtype=str(c.dtype))
            hm._get(o.handle).result = out
        return


def _execute_response_mp(resp: Response, ops: List[_QueuedOp]) -> None:
    """Multi-process execution of one broadcast response.

    Every process receives the same response list in the same order and
    calls the same jitted collective over the process mesh with its own
    shard — the SPMD property the reference gets from executing MPI ops in
    MPI_Bcast order (operations.cc:1290-1326).
    """
    st = _state.global_state()
    tl = st.timeline
    hm = st.handle_manager
    ps = _state.get_process_set(resp.process_set_id) \
        if resp.process_set_id else None
    if ps is not None:
        if not ops:
            # Not a member of this set (or a member with nothing pending,
            # e.g. after shutdown poisoning): this process takes no part
            # in the sub-mesh collective.
            return
        _, ks = ps.mesh_and_kernels()
        denom = ps.size()
    else:
        _, ks = _mp_kernels()
        denom = st.process_count

    if st.joining and ps is None and resp.tensor_type is not None \
            and len(ops) < len(resp.tensor_names):
        # This process called hvd.join(): participate in the peers'
        # collective with ZERO contributions so the SPMD program still
        # runs on every process (Horovod's Join semantics — post-v0.13;
        # the v0.13 reference could only hang on uneven workloads).
        # ``ops`` may be a PARTIAL subset: an async op this rank
        # submitted before joining can fuse with tensors completed by
        # its JOIN — the mixed buffer must still match the peers'.
        _execute_response_mp_joined(resp, ops)
        return

    if not ops:
        # The local op is gone (shutdown poisoning, or the local-fallback
        # withdrawal after the controller never answered a WITHDRAW
        # frame): skip this response rather than crash mid-list.  In the
        # normal timeout path this cannot happen anymore — a timed-out
        # rank withdraws through the coordinator, which broadcasts an
        # ERROR response (handled above) instead of ever constructing a
        # collective response missing a participant.
        return

    if resp.response_type == ResponseType.ALLREDUCE:
        mesh = (_mp_kernels()[0] if ps is None
                else ps.mesh_and_kernels()[0])
        # Megakernel path (default): one jitted local pack → one donated
        # reduce+divide+unpack executable over the process mesh
        # (ops/megakernel.py) instead of the per-tensor slice/divide
        # chain below.
        if _megakernel_eligible(ops) and _launch_mp_megakernel(
                resp, ops, ps, mesh, denom, tl, hm):
            return
        if len(ops) == 1:
            o = ops[0]
            if tl: _tl_start(tl, o, "ALLREDUCE")
            if tl: tl.activity_start(o.name, "XLA_ALLREDUCE")
            if o.red_op == ReduceOp.AVERAGE:
                # Divide folded into the compiled kernel, not a
                # separate eager dispatch after it.
                out = ks["psum_out_rep_avg"](
                    _mp_global(o.contrib.value, ps))
            else:
                out = ks[_OP_KERNEL[o.red_op] + "_out_rep"](
                    _mp_global(o.contrib.value, ps))
            if tl: tl.activity_end(o.name)
            if tl: tl.end(o.name, dtype=str(o.contrib.dtype))
            hm._get(o.handle).result = out
            return
        # Fused eager fallback (HVD_TPU_MEGAKERNEL=0): one flat buffer
        # per response (≙ MEMCPY_IN_FUSION_BUFFER).  Homogeneous in
        # red_op — the coordinator fuses like-op only (and never fuses
        # adasum, whose dots are per-tensor).
        for o in ops:
            if tl: _tl_start(tl, o, "ALLREDUCE")
            if tl: tl.activity_start(o.name, "MEMCPY_IN_FUSION_BUFFER")
        buf = jnp.concatenate([jnp.ravel(o.contrib.value) for o in ops])
        for o in ops:
            if tl: tl.activity_end(o.name)
            if tl: tl.activity_start(o.name, "XLA_ALLREDUCE")
        red = ks[_OP_KERNEL[ops[0].red_op] + "_out_rep"](
            _mp_global(buf, ps))
        offs = 0
        for o in ops:
            n = int(np.prod(o.contrib.shapes[0], dtype=np.int64)) if \
                o.contrib.shapes[0] else 1
            if tl: tl.activity_end(o.name)
            if tl: tl.activity_start(o.name, "MEMCPY_OUT_FUSION_BUFFER")
            piece = red[offs:offs + n].reshape(o.contrib.shapes[0])
            offs += n
            if o.red_op == ReduceOp.AVERAGE:
                piece = _divide(piece, denom)
            if tl: tl.activity_end(o.name)
            if tl: tl.end(o.name, dtype=str(o.contrib.dtype))
            hm._get(o.handle).result = piece
        return

    if resp.response_type == ResponseType.ALLTOALL:
        st_me = (st.process_index if ps is None else ps.rank())
        n = denom
        matrix = np.asarray(resp.tensor_sizes,
                            dtype=np.int64).reshape(n, n)
        M = int(matrix.max()) if matrix.size else 0
        for o in ops:
            c = o.contrib
            if tl: _tl_start(tl, o, "ALLTOALL")
            if tl: tl.activity_start(o.name, "XLA_ALLTOALL")
            rest = tuple(c.shapes[0][1:])
            local = np.asarray(c.value)
            send = np.zeros((n, M) + rest, local.dtype)
            off = 0
            for d in range(n):
                cnt = int(matrix[st_me, d])
                send[d, :cnt] = local[off:off + cnt]
                off += cnt
            res = ks["a2a_pr"](_mp_global(jnp.asarray(send), ps))
            mine = np.asarray(res.addressable_data(0))[0]  # [sender, M,..]
            out = jnp.concatenate(
                [mine[s, :int(matrix[s, st_me])] for s in range(n)],
                axis=0)
            if tl: tl.activity_end(o.name)
            if tl: tl.end(o.name, dtype=str(c.dtype))
            hm._get(o.handle).result = out
        return

    if resp.response_type == ResponseType.REDUCESCATTER:
        for o in ops:
            if tl: _tl_start(tl, o, "REDUCESCATTER")
            if tl: tl.activity_start(o.name, "XLA_REDUCESCATTER")
            # AVERAGE folds its divide into the compiled kernel (no
            # separate eager dispatch on the extracted chunk).
            kernel = ks["rscatter_pr_avg"
                        if o.red_op == ReduceOp.AVERAGE else "rscatter_pr"]
            res = kernel(_mp_global(o.contrib.value, ps))
            # This process's chunk: its addressable row of the P(A)
            # output (Horovod returns only the caller's chunk).
            mine = jnp.squeeze(jnp.asarray(res.addressable_data(0)),
                               axis=0)
            if tl: tl.activity_end(o.name)
            if tl: tl.end(o.name, dtype=str(o.contrib.dtype))
            hm._get(o.handle).result = mine
        return

    if resp.response_type == ResponseType.ALLGATHER:
        for o in ops:
            c = o.contrib
            if tl: _tl_start(tl, o, "ALLGATHER")
            if tl: tl.activity_start(o.name, "XLA_ALLGATHER")
            # The coordinator's response carries every rank's dim-0 extent
            # (≙ MPIResponse.tensor_sizes, mpi_message.h:48-51).
            sizes = resp.tensor_sizes or [c.orig_sizes[0]] * denom
            dmax = max(sizes)
            v = c.value
            if v.shape[0] < dmax:
                pad = jnp.zeros((dmax - v.shape[0],) + tuple(v.shape[1:]),
                                v.dtype)
                v = jnp.concatenate([v, pad], axis=0)
            gathered = ks["gather_pr"](_mp_global(v, ps))  # [P*dmax, ...]
            if any(s != dmax for s in sizes):
                pieces = [gathered[i * dmax:i * dmax + s]
                          for i, s in enumerate(sizes)]
                out = jnp.concatenate(pieces, axis=0)
            else:
                out = gathered
            if tl: tl.activity_end(o.name)
            if tl: tl.end(o.name, dtype=str(c.dtype))
            hm._get(o.handle).result = out
        return

    if resp.response_type == ResponseType.BROADCAST:
        for o in ops:
            c = o.contrib
            if tl: _tl_start(tl, o, "BROADCAST")
            if tl: tl.activity_start(o.name, "XLA_BCAST")
            out = ks["bcast_pr"](_mp_global(c.value, ps),
                                 jnp.int32(o.root_rank))
            if tl: tl.activity_end(o.name)
            if tl: tl.end(o.name, dtype=str(c.dtype))
            hm._get(o.handle).result = out
        return


def _execute_response_mp_joined(resp: Response,
                                ops: List["_QueuedOp"] = ()) -> None:
    """Joined-rank execution of one data response: same jitted collective
    over the process mesh, zero contributions built from the response's
    dtype + shapes (wire fields added for exactly this).  ``ops`` holds
    any of the rank's OWN outstanding async ops that rode the same fused
    response — they contribute their real values (exactly like the live
    path) and receive their slice of the result."""
    st = _state.global_state()
    hm = st.handle_manager
    _, ks = _mp_kernels()
    dtype = wire.np_dtype_of(resp.tensor_type)
    shapes = [tuple(s) for s in resp.tensor_shapes]
    by_name = {o.name: o for o in ops}

    if resp.response_type == ResponseType.ALLREDUCE:
        # Megakernel path: the zero-contribution slots are packed into
        # the identical fused program the live ranks run —
        # _launch_mp_megakernel fills zeros for tensors this rank never
        # submitted and discards their outputs.
        if (_megakernel.enabled()
                and (not ops or ops[0].red_op != ReduceOp.ADASUM)
                and _launch_mp_megakernel(
                    resp, ops, None, _mp_kernels()[0],
                    st.process_count, st.timeline, hm)):
            return

        def numel(s):
            return int(np.prod(s, dtype=np.int64)) if s else 1

        if len(resp.tensor_names) == 1:
            o = by_name.get(resp.tensor_names[0])
            val = o.contrib.value if o is not None \
                else jnp.zeros(shapes[0], dtype)
            # Only SUM/AVERAGE can reach a joined rank (the coordinator
            # errors other reduce ops once a rank has joined).
            out = ks["psum_out_rep"](_mp_global(val))
            if o is not None:
                if o.red_op == ReduceOp.AVERAGE:
                    out = _divide(out, st.process_count)
                hm._get(o.handle).result = out
            return
        # Fused: the peers reduce ONE flat buffer — build the identical
        # buffer with zeros in the slots this rank never submitted.
        parts = [jnp.ravel(by_name[n].contrib.value) if n in by_name
                 else jnp.zeros((numel(s),), dtype)
                 for n, s in zip(resp.tensor_names, shapes)]
        red = ks["psum_out_rep"](_mp_global(jnp.concatenate(parts)))
        offs = 0
        for n, s in zip(resp.tensor_names, shapes):
            o = by_name.get(n)
            cnt = numel(s)
            if o is not None:
                piece = red[offs:offs + cnt].reshape(s)
                if o.red_op == ReduceOp.AVERAGE:
                    piece = _divide(piece, st.process_count)
                hm._get(o.handle).result = piece
            offs += cnt
        return
    if resp.response_type == ResponseType.ALLGATHER:
        dmax = max(resp.tensor_sizes) if resp.tensor_sizes else 0
        rest = shapes[0][1:]
        ks["gather_pr"](_mp_global(jnp.zeros((dmax,) + rest, dtype)))
        return
    if resp.response_type == ResponseType.BROADCAST:
        root = resp.tensor_sizes[0] if resp.tensor_sizes else 0
        ks["bcast_pr"](_mp_global(jnp.zeros(shapes[0], dtype)),
                       jnp.int32(root))


def join() -> int:
    """Barrier for uneven workloads (the post-v0.13 ``hvd.join()`` API).

    A process that has run out of data calls ``join()``; until every
    process joins, it keeps participating in the others' collectives
    with ZERO contributions (allreduce adds zeros and still divides by
    the full size — Horovod's documented Join semantics; allgather
    contributes 0 rows).  Returns the rank of the LAST process to join,
    so callers can e.g. pick a rank that saw every batch.  The v0.13
    reference predates Join and could only hang on uneven workloads.

    Single-process mode is trivially a no-op returning this rank: all
    replicas advance in lockstep inside one program.
    """
    import os as _os
    import time as _time

    _state._check_initialized()
    st = _state.global_state()
    if not st.multiprocess:
        return st.process_index
    if st.peer_shutdown:
        raise HorovodError(SHUT_DOWN_ERROR_MESSAGE)
    req = wire.Request(st.process_index, wire.RequestType.JOIN,
                       wire.DataType.UINT8, "hvd.join")
    st.join_result = None
    st.joining = True
    try:
        if st.process_index == 0:
            st.coordinator.submit(req)
        else:
            st.transport.submit(req)
        timeout = float(_os.environ.get("HOROVOD_TPU_JOIN_TIMEOUT", "600"))
        deadline = _time.monotonic() + timeout
        while st.join_result is None and _time.monotonic() < deadline:
            if st.peer_shutdown:
                raise HorovodError(SHUT_DOWN_ERROR_MESSAGE)
            _drain()
            _time.sleep(0.001)
    finally:
        st.joining = False
    if st.join_result is None:
        raise HorovodError(
            f"hvd.join() timed out after {timeout:.0f}s waiting for the "
            f"remaining processes to join (HOROVOD_TPU_JOIN_TIMEOUT).")
    return st.join_result


def _threshold_snapshot(st):
    """psid -> fusion threshold of the owning coordinator, snapshotted
    BEFORE entering the cache (ResponseCache._lock is a leaf lock; the
    take_ready callback must therefore be pure — resolving process sets
    from inside it would acquire st.lock under the cache lock).  The
    replay plan uses the same packing budget the live negotiation
    would; a psid not in the snapshot (set removed this tick — its
    entries are flushed anyway) falls back to the global threshold."""
    default = (st.coordinator.fusion_threshold
               if st.coordinator is not None
               else st.fusion_threshold_bytes)
    thresholds = {0: default}
    for set_ps in _state.process_sets_snapshot():
        if set_ps.coordinator is not None:
            thresholds[set_ps.process_set_id] = \
                set_ps.coordinator.fusion_threshold
    return lambda psid: thresholds.get(psid, default)


def _resubmit_orphans(st, orphans) -> None:
    """Route cached submissions downgraded by a flush back into the real
    negotiation path (each carries its process-set id)."""
    for req in orphans:
        coord = st.coordinator if req.process_set_id == 0 else None
        if coord is None:
            ps = _state.get_process_set(req.process_set_id)
            coord = None if ps is None else ps.coordinator
        if coord is None:
            continue  # set removed meanwhile; submitter times out/report
        try:
            coord.submit(req)
        except ValueError:
            pass  # duplicate: the rank re-submitted meanwhile


def _coordinator_tick(st):
    """One rank-0 (or single-process) negotiation tick: cache replay +
    flush markers + freshly negotiated responses, in the stream order
    every replica relies on.  Returns (responses, replay groups, epoch,
    compact_ok, n_non_replay, replay_ids) — the groups let the
    transport broadcast a pure-replay cycle compactly, and replay_ids
    identifies the replayed responses so observation never re-inserts
    them (the worker-side equivalent is the name-presence check)."""
    cache = st.response_cache
    meta = _queue.pending_meta()
    marker = None
    replayed: List[Response] = []
    groups: List[List[int]] = []
    epoch = 0
    compact = True
    if cache is not None:
        _resubmit_orphans(st, cache.check_capacity())
        marker = cache.take_flush_marker()
        replayed, groups, epoch, compact = cache.take_ready(
            _threshold_snapshot(st))
        if replayed and st.timeline is not None:
            # The one NEGOTIATE-span closer for cache-served tensors:
            # submit-side hits deliberately leave the span open (a
            # remote bit may be the completing hit, which submit never
            # sees), and this runs exactly once per replayed tensor.
            for r in replayed:
                for n in r.tensor_names:
                    st.timeline.negotiate_end(n)
    # hvd-tune: pending retune decisions become stream markers HERE, on
    # the coordinator tick that owns stream ordering — after the flush
    # marker (flush-before-anything), before replay/negotiation (so the
    # knob flip never splits a cycle's responses).  They count as
    # non-replay traffic below, forcing a full-frame broadcast.
    retunes: List[Response] = []
    if st.tuner is not None:
        retunes = st.tuner.take_markers()
    negotiated = st.coordinator.poll_responses(meta)
    for set_ps in _state.process_sets_snapshot():
        if set_ps.coordinator is not None:
            negotiated += set_ps.coordinator.poll_responses(meta)
    # hvd-chaos coord.reorder: permute ONLY the freshly negotiated
    # responses of this tick (never across the marker/replay prefix —
    # that ordering is load-bearing for replica alignment).  Responses
    # within one tick carry no cross-response ordering contract, so a
    # recovered run must stay bitwise-identical under the permutation.
    if _chaos.active():
        negotiated = _chaos.maybe_reorder("coord.reorder", negotiated)
    # Marker FIRST: replicas must flush before inserting anything this
    # tick's negotiations produce; replayed responses reference live
    # (post-flush) entries whenever a marker is present, so the order
    # [marker, replays, negotiated] is safe in every interleaving.
    resps = ([marker] if marker is not None else []) + retunes \
        + replayed + negotiated
    return resps, groups, epoch, compact, \
        (1 if marker is not None else 0) + len(retunes) + len(negotiated), \
        frozenset(id(r) for r in replayed)


def _drain() -> None:
    """Poll the coordinator and execute every ready (fused) response
    (≙ one background-loop tick, operations.cc:1219-1374).  Validation
    errors are stored on their handles and surfaced at synchronize/poll,
    matching the reference's callback-with-error-Status flow
    (operations.cc:1060-1067)."""
    st = _state.global_state()
    with _drain_lock:
        cache = st.response_cache
        if st.multiprocess:
            tp = st.transport
            if tp is None:
                return
            if st.process_index == 0:
                # A worker asked for shutdown: broadcast it and poison
                # local pending ops (≙ operations.cc:1377-1403).
                if tp.shutdown_requested.is_set() and not st.peer_shutdown:
                    _initiate_shutdown()
                # hvd-chaos reconnect: a disconnected worker whose
                # grace window expired without a session resume becomes
                # a lost rank (with a diagnostic naming the fault).
                tp.expire_grace()
                # A worker's connection dropped without a shutdown frame:
                # the process died (or exited without calling shutdown()).
                # With collectives pending this is fatal — fail them with
                # a message naming the rank (the reference can only hang
                # here); otherwise it is an implicit shutdown.
                if tp.lost_ranks and not st.peer_shutdown:
                    _handle_lost_ranks(st, tp)
                # Coordinator: poll, broadcast the fused responses to every
                # worker, then execute locally in the same order
                # (≙ MPI_Bcast of the response list, operations.cc:1290).
                tp.flush_unrouted()  # set requests that beat registration
                tp.maybe_ping()  # hvd-trace clock probes (trace/clock.py)
                tick_t0 = time.monotonic() if _trace.enabled() else 0.0
                resps, groups, epoch, compact, n_other, replay_ids = \
                    _coordinator_tick(st)
                if resps:
                    # Advance the fleet-wide cycle id BEFORE the
                    # broadcast: the frame's trace trailer and every
                    # rank's execution spans then share it.
                    if _trace.enabled():
                        _trace.next_cycle()
                        _trace.span("negotiate.tick", "negotiate",
                                    tick_t0, time.monotonic(),
                                    args={"responses": len(resps)})
                    # The controller reaches its own cache stream
                    # position BEFORE publishing the stream: a fast
                    # worker can observe the frame, hit its fresh
                    # replica entry and ship the hit bit back before
                    # this thread returns from the send — the bit must
                    # find the entry already inserted, or it is dropped
                    # as unresolvable and the op stalls into a withdraw
                    # (the roaming fault-free chaos-cp abandonment).
                    if cache is not None:
                        for resp in resps:
                            cache.observe_response(
                                resp, replay=id(resp) in replay_ids)
                    if compact and groups and n_other == 0:
                        # Pure cache replay: the steady-state frame —
                        # entry-index groups instead of full payloads.
                        tp.broadcast_replay(groups, epoch)
                    else:
                        tp.broadcast_responses(resps)
                for resp in resps:
                    ops = _queue.take(resp.tensor_names)
                    _execute_response(resp, ops)
                    if st.autotuner is not None:
                        st.autotuner.record_bytes(
                            sum(o.nbytes for o in ops))
                if st.autotuner is not None:
                    st.autotuner.maybe_step()
            else:
                tp.flush_requests()  # the tick's coalesced control frame
                while True:
                    resps = tp.poll_responses()
                    if resps is None:
                        break
                    # Adopt the controller's cycle id (the batch's
                    # trace trailer) before executing, so this rank's
                    # spans land under the same fleet-wide cycle.
                    ctx = tp.last_trace_ctx
                    if ctx is not None and _trace.enabled():
                        _trace.observe_ctx(*ctx)
                    for resp in resps:
                        ops = _queue.take(resp.tensor_names)
                        if cache is not None:
                            cache.observe_response(resp, own_requests={
                                st.process_index: {
                                    o.name: o.request for o in ops
                                    if o.request is not None}})
                        _execute_response(resp, ops)
            return
        tick_t0 = time.monotonic() if _trace.enabled() else 0.0
        resps, _groups, _epoch, _compact, _n, replay_ids = \
            _coordinator_tick(st)
        if resps and _trace.enabled():
            # Single-process cycles advance the same counter so the
            # local trace analyzes identically to a fleet's.
            _trace.next_cycle()
            _trace.span("negotiate.tick", "negotiate", tick_t0,
                        time.monotonic(), args={"responses": len(resps)})
        for resp in resps:
            ops = _queue.take(resp.tensor_names)
            if cache is not None:
                cache.observe_response(resp,
                                       replay=id(resp) in replay_ids)
            _execute_response(resp, ops)
            if st.autotuner is not None:
                st.autotuner.record_bytes(sum(o.nbytes for o in ops))
        if st.autotuner is not None:
            st.autotuner.maybe_step()


@contextlib.contextmanager
def quiesce():
    """Hold the drain lock across a group of ``*_async`` submissions so
    the background 5 ms tick cannot negotiate a partial group, then run
    one explicit drain on exit.

    This is the sanctioned fix for the submission-split race: without
    it, a tick that fires between two submissions of one logical cycle
    negotiates them as two fused responses, which perturbs anything
    that asserts on fusion granularity (bench dataplane legs, ledger
    accounting tests).  Same pattern as
    ``overlap.dispatch_bucket_segment``::

        with C.quiesce():
            h1 = C.allreduce_async(a, name="cycle.a")
            h2 = C.allreduce_async(b, name="cycle.b")
        C.synchronize(h1); C.synchronize(h2)

    The body must only *submit* — calling :func:`synchronize` (or
    anything that waits on a response) inside the block deadlocks,
    because progress requires the drain the block is deferring.
    """
    with _drain_lock:
        yield
    _drain()


# ---------------------------------------------------------------------------
# Public API
# ---------------------------------------------------------------------------

def _resolve_op(average, op) -> ReduceOp:
    """Resolve the (average, op) pair into one ReduceOp.

    Mirrors the post-v0.13 Horovod contract: ``op`` and ``average`` are
    mutually exclusive — passing both raises ValueError; with neither,
    the default is Average (the reference's allreduce default,
    tensorflow/__init__.py:49, torch/mpi_ops.py:58)."""
    if op is not None:
        if average is not None:
            raise ValueError(
                "specify either average= or op=, not both "
                "(they are mutually exclusive).")
        return ReduceOp(op)
    if average is None or average:
        return ReduceOp.AVERAGE
    return ReduceOp.SUM


def _check_reduce_op(red_op: ReduceOp, dtype, process_set=None) -> None:
    st = _state.global_state()
    if red_op == ReduceOp.ADASUM:
        n = (_state.contributor_count() if process_set is None
             else process_set.size())
        if n & (n - 1) != 0:
            raise ValueError(
                f"op=Adasum requires a power-of-two contributor count for "
                f"its recursive-doubling ppermute ladder; got {n}.")
        if not jnp.issubdtype(jnp.dtype(dtype), jnp.inexact):
            raise ValueError(
                f"op=Adasum is defined on floating-point gradients; got "
                f"dtype {dtype}.")
        if st.joining:
            raise HorovodError(
                "op=Adasum cannot run while this rank has joined: a zero "
                "contribution is only an identity for sum/average.")


def _enqueue(x, op: RequestType, name: Optional[str],
             red_op: ReduceOp = ReduceOp.SUM,
             root_rank: int = -1, prefix: str = "",
             process_set=None, splits: Tuple[int, ...] = (),
             owned: Optional[bool] = None) -> int:
    _state._check_initialized()
    st = _state.global_state()
    if st.peer_shutdown:
        raise HorovodError(SHUT_DOWN_ERROR_MESSAGE)
    if process_set is not None and process_set.process_set_id == 0:
        process_set = None  # hvd.global_process_set() ≡ the world
    if process_set is not None and \
            _state.get_process_set(process_set.process_set_id) is None:
        raise HorovodError(
            f"process set {process_set.process_set_id} is not registered "
            f"(was it removed, or created before a re-init?).")
    if process_set is not None and not process_set.included():
        raise HorovodError(
            f"rank {st.process_index} is not a member of process set "
            f"{process_set.process_set_id} (ranks "
            f"{list(process_set.ranks)}) and cannot submit collectives "
            f"into it (the post-v0.13 process-set contract).")
    c = _classify(x, op, ps=process_set)
    if owned is not None and not isinstance(c.value, (list, tuple)):
        # Caller-declared ownership (donate_inputs=True): the submitter
        # promises never to observe the array again, so the megakernel
        # may donate it even though _classify saw a caller-held
        # jax.Array.  The overlap path's gradient buffers ride this —
        # they are step-internal producer outputs nothing else reads.
        c.owned = bool(owned)
    if op == RequestType.ALLREDUCE:
        _check_reduce_op(red_op, c.dtype, process_set)
    name = name or _auto_name(prefix or op.name.lower(), process_set)
    # Payload bytes of ONE replica's tensor — the quantity the reference's
    # fusion accounting uses (tensor->size(), operations.cc:1341-1352).
    item = wire.dtype_size(wire.dtype_of(c.dtype))
    s0 = c.shapes[0]
    nbytes = int(np.prod(s0, dtype=np.int64)) * item if s0 else item
    # hvd-analyze signature capture (analysis/program.py): one record
    # per collective, before negotiation, so verify_program can prove
    # cross-rank agreement of the traced program ahead of the data
    # plane.  Every frontend funnels through this point.
    _program.record_collective(
        op.name.lower(), name,
        wire.dtype_name(wire.dtype_of(c.dtype)), s0,
        reduce_op=(wire.reduce_op_name(red_op)
                   if op in (RequestType.ALLREDUCE,
                             RequestType.REDUCESCATTER) else ""),
        process_set_id=0 if process_set is None
        else process_set.process_set_id)
    handle = st.handle_manager.allocate(None, name=name)
    # Clock stamp gated like every other instrument: disabled telemetry
    # must cost a flag check, and the bench's overhead A/B must compare
    # against a leg that truly pays nothing.
    qop = _QueuedOp(name=name, op=op, contrib=c, red_op=red_op,
                    root_rank=root_rank, handle=handle, nbytes=nbytes,
                    ps=process_set,
                    t_submit=(time.perf_counter()
                              if _telemetry.enabled() else 0.0),
                    t_submit_mono=(time.monotonic()
                                   if _trace.enabled() else 0.0))
    _M_SUBMITTED.inc()
    _queue.put(qop)
    # The execute paths read split info from the NEGOTIATED response
    # matrix, never from the local op — splits ride the request only.
    hit = _submit_requests(name, op, c, root_rank, red_op=red_op,
                           ps=process_set, splits=tuple(splits),
                           queued_op=qop)
    qop.cache_hit = hit
    st.handle_manager._get(handle).cache_hit = hit
    return handle


def allreduce_async(tensor, average=None, name: Optional[str] = None,
                    op=None, process_set=None) -> int:
    """Queue an allreduce; returns a handle for poll/synchronize
    (≙ horovod_torch_allreduce_async_*, torch/mpi_ops.cc:206-253).
    Averages by default for parity with the reference API
    (torch/mpi_ops.py:58, tensorflow/__init__.py:49); ``op`` takes any
    of hvd.Average/Sum/Adasum/Min/Max/Product (the post-v0.13 API) and
    is mutually exclusive with ``average`` (passing both raises
    ValueError); ``process_set`` (from :func:`add_process_set`)
    restricts the collective to a rank subset."""
    return _enqueue(tensor, RequestType.ALLREDUCE, name,
                    red_op=_resolve_op(average, op), prefix="allreduce",
                    process_set=process_set)


def grouped_allreduce_async(tensors, average=None,
                            name: Optional[str] = None,
                            op=None, donate_inputs: bool = False) -> List[int]:
    """Queue a group of allreduces in one call; returns one handle per
    tensor (≙ the post-v0.13 hvd.grouped_allreduce API).  The group
    enters the request queue back-to-back, so Tensor Fusion batches it
    — normally into one wire collective; a concurrent background tick
    can split a group across two fused responses, which changes wire
    batching, never results.  The default base name is unique per call
    so overlapping anonymous groups never collide.

    ``donate_inputs=True`` declares the tensors executor-owned: the
    caller promises never to observe them again, and the fused
    megakernel donates their buffers (the backward/communication-overlap
    step passes its gradient buffers this way — on TPU the reduction
    then reuses the gradients' memory instead of allocating)."""
    base = name or _auto_name("grouped.allreduce")
    red_op = _resolve_op(average, op)
    return [
        _enqueue(t, RequestType.ALLREDUCE, f"{base}.{i}", red_op=red_op,
                 prefix="allreduce",
                 owned=True if donate_inputs else None)
        for i, t in enumerate(tensors)
    ]


def grouped_allreduce(tensors, average=None, name: Optional[str] = None,
                      op=None) -> List:
    """Synchronous grouped allreduce: fused under the hood, one result
    per input tensor, input order preserved."""
    return [synchronize(h)
            for h in grouped_allreduce_async(tensors, average, name, op)]


def grouped_allgather_async(tensors, name: Optional[str] = None,
                            process_set=None) -> List[int]:
    """Queue a group of allgathers (≙ the post-v0.13
    hvd.grouped_allgather): one handle per tensor, back-to-back enqueue
    so every gather negotiates in the same coordinator tick."""
    base = name or _auto_name("grouped.allgather", process_set)
    return [_enqueue(t, RequestType.ALLGATHER, f"{base}.{i}",
                     prefix="allgather", process_set=process_set)
            for i, t in enumerate(tensors)]


def grouped_allgather(tensors, name: Optional[str] = None,
                      process_set=None) -> List:
    return [synchronize(h)
            for h in grouped_allgather_async(tensors, name, process_set)]


def grouped_reducescatter_async(tensors, average=None,
                                name: Optional[str] = None, op=None,
                                process_set=None) -> List[int]:
    """Queue a group of reducescatters (≙ the post-v0.13
    hvd.grouped_reducescatter): one handle per tensor."""
    base = name or _auto_name("grouped.reducescatter", process_set)
    return [reducescatter_async(t, average, f"{base}.{i}", op, process_set)
            for i, t in enumerate(tensors)]


def grouped_reducescatter(tensors, average=None,
                          name: Optional[str] = None, op=None,
                          process_set=None) -> List:
    return [synchronize(h) for h in grouped_reducescatter_async(
        tensors, average, name, op, process_set)]


def allgather_async(tensor, name: Optional[str] = None,
                    process_set=None) -> int:
    return _enqueue(tensor, RequestType.ALLGATHER, name, prefix="allgather",
                    process_set=process_set)


def remove_process_set(process_set) -> bool:
    """Deregister a process set (≙ the post-v0.13
    ``hvd.remove_process_set``).  Collective in multi-process mode (every
    process must call it for the same set, like registration); returns
    False when the set was already removed.  The global set cannot be
    removed."""
    _state._check_initialized()
    st = _state.global_state()
    psid = process_set.process_set_id
    if psid == 0:
        raise ValueError("the global process set cannot be removed")
    if _state.get_process_set(psid) is None:
        return False
    if st.multiprocess:
        # The registration allgather is itself a blocking collective, so
        # it must run OUTSIDE st.lock (blocking-under-lock lint rule).
        from .objects import allgather_object

        regs = allgather_object(psid, name=f"process_set.remove.{psid}")
        if any(r != psid for r in regs):
            raise HorovodError(
                f"remove_process_set must be called by every process for "
                f"the same set; this process removed {psid} but the job "
                f"removed {regs}.")
    with st.lock:
        ps = st.process_sets.pop(psid, None)
    if ps is not None:
        ps.close()
    if not st.multiprocess and st.response_cache is not None:
        # Multi-process mode flushes deterministically when every rank
        # observes the process_set.remove.* allgather in the response
        # stream (ops/cache.py); single-process has no such collective,
        # so flush directly — a cached cycle must never replay a
        # response into a removed set.
        _resubmit_orphans(st, st.response_cache.flush(
            f"remove_process_set({psid})"))
    return True


def global_process_set():
    """The implicit world communicator as a :class:`ProcessSet`
    (≙ ``hvd.global_process_set``; a function here because the world is
    only known after ``init()``).  Passing it (or ``None``) to a
    collective's ``process_set=`` is equivalent."""
    from .process_set import ProcessSet

    _state._check_initialized()
    return ProcessSet(0, tuple(range(_state.contributor_count())))


def alltoall_async(tensor, splits=None, name: Optional[str] = None,
                   process_set=None) -> int:
    """Queue an alltoall (the post-v0.13 ``hvd.alltoall``): rank r's
    dim-0 rows are scattered to every rank by ``splits`` (one count per
    destination; ``None`` = even split), and the rows received from all
    ranks concatenate in rank order.

    Multi-process mode returns the caller's received tensor;
    single-process mode returns the LIST of per-replica received
    tensors (row counts may differ per receiver).  The negotiated split
    matrix rides the response, so ragged exchanges work like the ragged
    allgather (pad-to-max around XLA's native AllToAll on ICI).
    """
    n = (_state.contributor_count() if process_set is None
         else process_set.size())
    if isinstance(tensor, (list, tuple)):
        raise ValueError("alltoall takes one tensor per rank, not a list.")
    shape = tuple(jnp.shape(tensor))
    if not shape:
        raise ValueError("An alltoall tensor needs at least one dimension.")
    st = _state.global_state()
    d0 = (shape[0] if (st.multiprocess or not (
        isinstance(tensor, jax.Array) and is_per_replica(tensor)))
        else (shape[1] if len(shape) > 1 else 0))
    if splits is None:
        if not shape or d0 % n != 0:
            raise ValueError(
                f"alltoall without splits needs dim 0 divisible by the "
                f"rank count ({n}); got shape {list(shape)}.")
        splits = ()
    else:
        splits = tuple(int(s) for s in splits)
        if len(splits) != n or any(s < 0 for s in splits) or \
                sum(splits) != d0:
            raise ValueError(
                f"alltoall splits {list(splits)} must have one "
                f"non-negative entry per rank ({n}) summing to dim 0 "
                f"({d0}).")
    return _enqueue(tensor, RequestType.ALLTOALL, name, prefix="alltoall",
                    process_set=process_set, splits=splits)


def alltoall(tensor, splits=None, name: Optional[str] = None,
             process_set=None):
    """Synchronous alltoall — see :func:`alltoall_async`."""
    return synchronize(alltoall_async(tensor, splits, name, process_set))


def barrier(process_set=None) -> None:
    """Block until every rank reaches the barrier (the post-v0.13
    ``hvd.barrier``): one tiny named allreduce through the full
    negotiation path, so it also surfaces peer failures/stalls like any
    other collective."""
    synchronize(allreduce_async(
        np.zeros((1,), np.float32), average=False,
        name=_auto_name("barrier", process_set),
        process_set=process_set))


def reducescatter_async(tensor, average=None, name: Optional[str] = None,
                        op=None, process_set=None) -> int:
    """Queue a reducescatter (the post-v0.13 ``hvd.reducescatter``):
    reduce across ranks, then split dim 0 — rank r receives chunk r.
    Multi-process mode returns only the caller's chunk;
    single-process mode returns the per-replica stack ``[n, d0/n, ...]``
    (row r = replica r's chunk).  ``op`` ∈ {Average, Sum}."""
    red = _resolve_op(average, op)
    if red not in (ReduceOp.SUM, ReduceOp.AVERAGE):
        raise ValueError(
            f"reducescatter supports op=Average/Sum (Horovod's contract "
            f"for this collective); got {wire.reduce_op_name(red)}.")
    if isinstance(tensor, (list, tuple)):
        raise ValueError(
            "reducescatter takes one tensor (identical shape on every "
            "rank), not a list.")
    n = (_state.contributor_count() if process_set is None
         else process_set.size())
    shape = tuple(jnp.shape(tensor))
    # is_per_replica can only be True for an already-sharded jax.Array —
    # don't transfer host inputs to device just to learn that.
    if _state.global_state().multiprocess or not (
            isinstance(tensor, jax.Array) and is_per_replica(tensor)):
        d0 = shape[0] if shape else 0
    else:
        d0 = shape[1] if len(shape) > 1 else 0  # [n, d0, ...] shard
    if not shape or d0 % n != 0 or d0 == 0:
        raise ValueError(
            f"reducescatter needs dim 0 divisible by the rank count "
            f"({n}); got shape {list(shape)}.")
    return _enqueue(tensor, RequestType.REDUCESCATTER, name, red_op=red,
                    prefix="reducescatter", process_set=process_set)


def reducescatter(tensor, average=None, name: Optional[str] = None,
                  op=None, process_set=None):
    """Synchronous reducescatter — see :func:`reducescatter_async`."""
    return synchronize(reducescatter_async(tensor, average, name, op,
                                           process_set))


def broadcast_async(tensor, root_rank: int,
                    name: Optional[str] = None, process_set=None) -> int:
    # In multi-process mode ranks are processes (the bcast mask compares
    # against the process-mesh axis index), not devices.  For a process
    # set the API takes the GLOBAL rank (Horovod's convention) and
    # translates it to the set-local index used on the wire.
    if process_set is not None:
        root_rank = process_set.local_rank_of(root_rank)
    else:
        bound = _state.contributor_count()
        if not (0 <= root_rank < bound):
            raise ValueError(f"root_rank {root_rank} outside [0, {bound}).")
    return _enqueue(tensor, RequestType.BROADCAST, name, root_rank=root_rank,
                    prefix="broadcast", process_set=process_set)


def add_process_set(ranks):
    """Register a process set (≙ the post-v0.13 ``hvd.add_process_set``).

    ``ranks`` are GLOBAL rank numbers — replica indices in
    single-process mode, process ranks in multi-process mode.  In
    multi-process mode this is a COLLECTIVE call: every process must
    call it with the identical ranks, in the same registration order
    (Horovod's contract); registration is validated with an
    allgather_object round over the global set and diverging
    registrations raise on every rank.  Returns the
    :class:`~horovod_tpu.ops.process_set.ProcessSet` to pass as
    ``process_set=`` on collectives.
    """
    from .process_set import ProcessSet

    _state._check_initialized()
    st = _state.global_state()
    ranks = tuple(sorted({int(r) for r in ranks}))
    if not ranks:
        raise ValueError("a process set needs at least one rank")
    bound = st.process_count if st.multiprocess else st.size
    bad = [r for r in ranks if not 0 <= r < bound]
    if bad:
        raise ValueError(
            f"process-set ranks {bad} outside [0, {bound}).")
    with st.lock:  # id counter + registry shared with drain/serve threads
        psid = st.next_process_set_id
        st.next_process_set_id = psid + 1
    if st.multiprocess:
        # The registration allgather is itself a blocking collective, so
        # it must run OUTSIDE st.lock (blocking-under-lock lint rule);
        # a failed registration burns the id identically on every rank.
        from .objects import allgather_object

        regs = allgather_object((psid, ranks),
                                name=f"process_set.register.{psid}")
        if any(reg != (psid, ranks) for reg in regs):
            raise HorovodError(
                f"add_process_set must be called by every process with "
                f"identical ranks in the same order; this process "
                f"registered set {psid} as {list(ranks)} but the job "
                f"registered {regs}.")
    ps = ProcessSet(psid, ranks)
    # Per-set coordinator wherever negotiation happens: the rank-0
    # controller in multi-process mode, the in-process coordinator
    # single-process.  It shares the one response-cache replica (entry
    # indices span every set — insertion order is the broadcast stream)
    # and carries the set's global-rank table for hit accounting.
    if st.coordinator is not None:
        from .coordinator import Coordinator

        ps.coordinator = Coordinator(
            size=ps.size(), fusion_threshold=st.fusion_threshold_bytes,
            timeline=st.timeline, cache=st.response_cache, ranks=ranks)
    with st.lock:
        st.process_sets[psid] = ps
    if not st.multiprocess and st.response_cache is not None:
        # Same rationale as remove_process_set: multi-process flushes on
        # the registration allgather; single-process flushes here.
        _resubmit_orphans(st, st.response_cache.flush(
            f"add_process_set({psid})"))
    return ps


def poll(handle: int) -> bool:
    """Non-blocking completion check (≙ horovod_torch_poll,
    torch/mpi_ops.cc:322-324).  Returns False while the op is still queued
    (awaiting the background tick) or its XLA execution is in flight."""
    st = _state.global_state()
    h = st.handle_manager._get(handle)
    if h.result is None:
        return False
    if isinstance(h.result, HorovodError):
        return True
    return st.handle_manager.poll(handle)


def _wait_mp_result(st, h) -> None:
    """Drain until a multi-process collective's response has been
    executed locally (``h.result`` set) — completion depends on the
    other processes, so this waits (with the background tick also
    draining) up to a timeout, then withdraws GROUP-WIDE (round 4):
    tell the coordinator we gave up so it broadcasts an ERROR response
    and every rank fails this op within the grace window — instead of
    each peer serially eating its own full timeout, or (the SPMD
    hazard) this rank later skipping a broadcast response its peers
    execute and block on.  Shared by :func:`synchronize` (which then
    blocks on device completion) and :func:`take_async` (which
    returns the in-flight array — the overlap path's mp partial
    cycles ride this)."""
    import os as _os
    import time as _time

    timeout = float(_os.environ.get("HOROVOD_TPU_SYNC_TIMEOUT", "300"))
    deadline = _time.monotonic() + timeout
    while h.result is None and _time.monotonic() < deadline:
        _drain()
        _time.sleep(0.001)
    if h.result is None:
        try:
            w_ps = _queue.peek_ps(h.name)
            if st.process_index == 0:
                coord = (st.coordinator if w_ps is None
                         else w_ps.coordinator)
                coord.withdraw(h.name, 0)
            else:
                st.transport.withdraw(
                    h.name,
                    0 if w_ps is None else w_ps.process_set_id)
        except (OSError, AttributeError):
            pass  # controller unreachable: fall back to local
        grace_dl = _time.monotonic() + float(_os.environ.get(
            "HOROVOD_TPU_WITHDRAW_GRACE", "10"))
        while h.result is None and _time.monotonic() < grace_dl:
            _drain()
            _time.sleep(0.001)
    if h.result is None:
        # Controller never answered the withdrawal: error locally
        # so the name can be reused and the handle doesn't pin
        # the contribution forever.
        _queue.take([h.name])
        h.result = HorovodError(
            f"Collective {h.name} timed out after {timeout:.0f}s "
            f"waiting for the remaining processes (see the "
            f"coordinator's stall warnings for which ranks are "
            f"missing).")


def synchronize(handle: int):
    """Block until the collective completes and return its output
    (≙ horovod_torch_wait_and_clear + synchronize, torch/mpi_ops.py:328-344).
    Raises :class:`HorovodError` if cross-replica validation failed."""
    st = _state.global_state()
    h = st.handle_manager._get(handle)
    if h.result is None:
        if st.multiprocess:
            _wait_mp_result(st, h)
        else:
            _drain()
            h = st.handle_manager._get(handle)
    if h.result is None:
        raise HorovodError(
            f"Collective {h.name} cannot complete: not all replica requests "
            f"were submitted (it would stall).")
    if isinstance(h.result, HorovodError):
        err = h.result
        h.result = ()  # release without re-running the finalizer
        st.handle_manager.synchronize(handle)
        raise err
    return st.handle_manager.synchronize(handle)


def take_async(handle: int):
    """Take a collective's result WITHOUT blocking on device completion.

    :func:`synchronize` calls ``jax.block_until_ready`` — the right
    contract for user code handing buffers to non-JAX consumers, but a
    pipeline bubble for a consumer that immediately feeds the result
    into another XLA program (the backward/communication-overlap step:
    blocking on the reduced buckets before dispatching the optimizer
    apply would serialize exactly the work the overlap hides).  This
    variant drains until the op's kernel is *dispatched* and returns
    the in-flight ``jax.Array`` future; XLA's per-device program order
    guarantees the consumer reads it after the reduction wrote it.

    Multi-process callers keep :func:`synchronize`'s full
    wait-with-withdraw semantics for the CONTROL plane (the response
    must have been broadcast and executed locally — that depends on
    the other processes) but skip the device-completion block, so an
    overlapped mp step can feed each bucket's in-flight reduction
    straight into the optimizer apply.  Raises :class:`HorovodError`
    exactly like synchronize.
    """
    st = _state.global_state()
    h = st.handle_manager._get(handle)
    if h.result is None:
        if st.multiprocess:
            _wait_mp_result(st, h)
        else:
            _drain()
    if h.result is None:
        raise HorovodError(
            f"Collective {h.name} cannot complete: not all replica requests "
            f"were submitted (it would stall).")
    if isinstance(h.result, HorovodError):
        err = h.result
        h.result = ()  # release without re-running the finalizer
        st.handle_manager.synchronize(handle)
        raise err
    return st.handle_manager.take(handle)


def allreduce(tensor, average=None, name: Optional[str] = None, op=None,
              process_set=None):
    """Synchronous allreduce — mean by default, sum with ``average=False``
    (defaults match the reference: tensorflow/__init__.py:49,
    torch/mpi_ops.py:58), or any reduction via ``op`` —
    hvd.Average/Sum/Adasum/Min/Max/Product (the post-v0.13 API; ``op``
    and ``average`` are mutually exclusive — passing both raises);
    ``process_set`` restricts to a rank subset.

    :class:`~horovod_tpu.ops.sparse.IndexedSlices` inputs dispatch to the
    sparse gather-of-(values, indices) path transparently, exactly like
    the reference's IndexedSlices branch (tensorflow/__init__.py:67-78).
    """
    from . import sparse as _sparse

    if isinstance(tensor, _sparse.IndexedSlices) or (
            isinstance(tensor, (list, tuple)) and tensor
            and all(isinstance(t, _sparse.IndexedSlices) for t in tensor)):
        red = _resolve_op(average, op)
        if red not in (ReduceOp.AVERAGE, ReduceOp.SUM):
            raise ValueError(
                f"sparse (IndexedSlices) allreduce supports only "
                f"sum/average — it is a gather of (values, indices), "
                f"reference tensorflow/__init__.py:67-78; got op="
                f"{wire.reduce_op_name(red)}.")
        return _sparse.allreduce(tensor, average=red == ReduceOp.AVERAGE,
                                 name=name, process_set=process_set)
    return synchronize(allreduce_async(tensor, average=average, name=name,
                                       op=op, process_set=process_set))


def allgather(tensor, name: Optional[str] = None, process_set=None):
    """Synchronous allgather along dim 0, rank order."""
    return synchronize(allgather_async(tensor, name=name,
                                       process_set=process_set))


def broadcast(tensor, root_rank: int, name: Optional[str] = None,
              process_set=None):
    """Synchronous broadcast from ``root_rank``."""
    return synchronize(broadcast_async(tensor, root_rank, name=name,
                                       process_set=process_set))
