"""Dynamic-path coordinator: negotiation, validation, fusion, stall watch.

TPU-native re-design of the reference coordinator that lives inside
``BackgroundThreadLoop`` (horovod/common/operations.cc:1167-1475).  Under
SPMD the *static* path (collectives traced into a jitted step) needs no
runtime agreement — the compiled XLA program is identical on every host and
the compiler schedules the ICI collectives.  What remains irreducible is the
dynamic path: eager collectives issued one at a time, variable-size
allgather, and cross-replica consistency checking.  This module reproduces
that machinery observably:

* name-keyed request table with readiness counting
  (≙ ``IncrementTensorCount``, operations.cc:222-247),
* cross-replica type/dtype/shape/root/device validation with the
  reference's error-message shapes (≙ ``ConstructMPIResponse``,
  operations.cc:255-461),
* response fusion — same-dtype, same-device ALLREDUCE responses merge while
  the summed payload stays under the fusion threshold
  (≙ operations.cc:1328-1374; threshold env ``HOROVOD_FUSION_THRESHOLD``,
  default 64 MB, operations.cc:140),
* stall detection — tensors stuck in negotiation longer than 60 s are
  reported with the set of ready vs. missing replicas
  (≙ ``CheckForStalledTensors``, operations.cc:1072-1115, cadence
  operations.cc:208-209),
* cooperative shutdown (≙ operations.cc:1377-1403).

When the native library is built the same logic runs in C++
(native/coordinator.cc) over the shared wire format; this Python class is
the behavior-identical fallback and the executable specification.
"""

from __future__ import annotations

import os
import sys
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from . import cache as _cache
from . import wire
from .wire import (DataType, Request, RequestType, Response, ResponseType)
from .. import telemetry as _telemetry
from ..analysis import lockorder as _lockorder
from ..analysis import program as _program
from ..analysis import races as _races
from ..native import lib as _native
from ..telemetry import flight as _flight

# Seconds a tensor may sit in negotiation before a stall warning
# (≙ STALL_WARNING_TIME, operations.cc:208).  Env-tunable so tests and
# impatient deployments can tighten the watchdog.
STALL_WARNING_SECONDS = float(
    os.environ.get("HOROVOD_STALL_WARNING_SECONDS", "60"))

_M_WITHDRAWALS = _telemetry.counter(
    "events.withdrawals", "collectives abandoned by a timed-out rank")
# Bound once: the submit miss path calls this per request.
_flight_record = _flight.recorder.record


@dataclass
class _PendingTensor:
    requests: List[Request] = field(default_factory=list)
    ranks: set = field(default_factory=set)
    first_seen: float = 0.0
    # Payload bytes of one replica's tensor, computed ONCE at submit
    # time from the first request's shape × dtype (the same formula the
    # op queue uses) instead of re-derived for every pending response on
    # every drain tick.
    nbytes: int = 0


def _withdraw_message(name: str, rank: int) -> str:
    """Shared ERROR text for an abandoned collective — must stay
    byte-identical with native/coordinator.cc's WithdrawMessage (the
    parity fuzz test compares packed responses)."""
    return (f"Collective {name} was abandoned: rank {rank} timed out "
            f"waiting for the remaining ranks; the operation fails on "
            f"all ranks.")


@_races.race_checked
class PyCoordinator:
    """Pure-Python coordinator (executable spec for native/coordinator.cc).

    Mutex-guarded like its C++ twin (and like the reference's single global
    mutex, operations.cc:113): ``submit`` runs on user threads while
    ``poll_responses`` runs on the background drain thread.
    """

    def __init__(self, size: int, fusion_threshold: int):
        self.size = size
        self.fusion_threshold = fusion_threshold
        self._lock = _lockorder.make_lock("PyCoordinator._lock")
        self.table: Dict[str, _PendingTensor] = {}  # guarded_by: _lock
        self.ready: List[str] = []  # guarded_by: _lock
        # dtype per constructed response, for fusion compatibility checks
        # (the reference reads this from its TensorTable during the fusion
        # loop, operations.cc:1328-1374).
        self._resp_dtype: Dict[str, DataType] = {}  # guarded_by: _lock
        # Submit-time payload bytes per constructed response: the fusion
        # loop's fallback when the queue-side size table has no entry,
        # carried from _PendingTensor so it is never recomputed per tick.
        self._resp_nbytes: Dict[str, int] = {}  # guarded_by: _lock
        # ERROR responses queued by withdraw(); drained ahead of the ready
        # tensors by poll_responses.
        self._withdrawn: List[Response] = []  # guarded_by: _lock
        # Ranks that called hvd.join() (post-v0.13 uneven-workload
        # barrier): they count as ready for every tensor and contribute
        # zeros at execution.  When all ranks joined, a JOIN response
        # releases them carrying the last joining rank.
        self.joined: set = set()  # guarded_by: _lock
        self._last_joined: int = -1  # guarded_by: _lock
        self._join_release: List[Response] = []  # guarded_by: _lock
        self.shutdown = False

    # -- withdraw (round 4; no reference equivalent — the reference can
    # -- only hang when a rank gives up, operations.cc:1290-1326) ---------
    def withdraw(self, name: str, rank: int) -> None:
        """A rank abandoned ``name`` (synchronize timeout): drop the
        pending entry and queue an ERROR response for every rank, so the
        whole group fails the op promptly instead of each peer serially
        eating its own timeout.  No-op when negotiation already completed
        (the op is about to finish normally — let it)."""
        with self._lock:
            if name in self.ready:
                return
            self.table.pop(name, None)
            self._withdrawn.append(Response(
                ResponseType.ERROR, [name],
                error_message=_withdraw_message(name, rank)))

    # -- IncrementTensorCount (operations.cc:222-247) ----------------------
    def submit(self, req: Request, now: Optional[float] = None) -> bool:
        """Record one replica's request; returns True when all replicas have
        reported the tensor (negotiation complete).  Joined ranks count
        as ready for every tensor; a JOIN request may itself complete
        pending tensors (and, from the last rank, the join barrier)."""
        now = time.monotonic() if now is None else now
        with self._lock:
            if req.request_type == RequestType.JOIN:
                self.joined.add(req.request_rank)
                self._last_joined = req.request_rank
                for name, entry in list(self.table.items()):
                    if len(entry.ranks | self.joined) == self.size \
                            and name not in self.ready:
                        self.ready.append(name)
                if len(self.joined) == self.size:
                    # Released AFTER the data responses of the same poll:
                    # a joined rank must still be joining (contributing
                    # zeros) while those execute.
                    self._join_release.append(Response(
                        ResponseType.JOIN,
                        tensor_sizes=[self._last_joined]))
                    self.joined = set()
                    return True
                return False
            entry = self.table.get(req.tensor_name)
            if entry is None:
                entry = _PendingTensor(first_seen=now)
                n = 1
                for d in req.tensor_shape:
                    n *= int(d)
                entry.nbytes = n * wire.dtype_size(req.tensor_type)
                self.table[req.tensor_name] = entry
            if req.request_rank in entry.ranks:
                raise ValueError(
                    f"Duplicate request for tensor {req.tensor_name} from "
                    f"replica {req.request_rank}; a name may be used by at "
                    f"most one pending collective per replica.")
            entry.requests.append(req)
            entry.ranks.add(req.request_rank)
            if len(entry.ranks | self.joined) == self.size:
                self.ready.append(req.tensor_name)
                return True
            return False

    # -- ConstructMPIResponse (operations.cc:255-461) ----------------------
    def construct_response(self, name: str) -> Response:
        with self._lock:
            return self._construct_response_locked(name)

    def _construct_response_locked(self, name: str) -> Response:
        entry = self.table.pop(name)
        reqs = sorted(entry.requests, key=lambda r: r.request_rank)
        first = reqs[0]
        error = None

        # Data-type agreement (operations.cc:266-279).
        for r in reqs[1:]:
            if r.tensor_type != first.tensor_type:
                error = (f"Mismatched data types: One rank had type "
                         f"{wire.dtype_name(first.tensor_type)}, but another "
                         f"rank had type {wire.dtype_name(r.tensor_type)}.")
                break
        # Operation agreement (operations.cc:283-296).
        if error is None:
            for r in reqs[1:]:
                if r.request_type != first.request_type:
                    error = (f"Mismatched collective operations: One rank did "
                             f"an {first.request_type.name.lower()}, but "
                             f"another rank did an "
                             f"{r.request_type.name.lower()}.")
                    break
        op = first.request_type
        # Allreduce: full shape agreement (operations.cc:299-330).
        if error is None and op == RequestType.ALLREDUCE:
            for r in reqs[1:]:
                if r.tensor_shape != first.tensor_shape:
                    error = (f"Mismatched allreduce tensor shapes: One rank "
                             f"sent a tensor of shape "
                             f"{list(first.tensor_shape)}, but another rank "
                             f"sent a tensor of shape "
                             f"{list(r.tensor_shape)}.")
                    break
        # Reducescatter (post-v0.13): full shape agreement like
        # allreduce, and it can never complete via joins — the joined
        # rank must participate to receive its own chunk.
        if error is None and op == RequestType.REDUCESCATTER:
            for r in reqs[1:]:
                if r.tensor_shape != first.tensor_shape:
                    error = (f"Mismatched reducescatter tensor shapes: One "
                             f"rank sent a tensor of shape "
                             f"{list(first.tensor_shape)}, but another rank "
                             f"sent a tensor of shape "
                             f"{list(r.tensor_shape)}.")
                    break
            if error is None and len(reqs) < self.size:
                error = ("Reducescatter cannot complete after a rank has "
                         "joined: every rank must participate to receive "
                         "its chunk of the result.")
        # Allreduce/reducescatter: reduce-op agreement (post-v0.13 hvd
        # op= API; no reference analogue — v0.13 hard-codes MPI_SUM).
        if error is None and op in (RequestType.ALLREDUCE,
                                    RequestType.REDUCESCATTER):
            for r in reqs[1:]:
                if r.reduce_op != first.reduce_op:
                    error = (f"Mismatched reduce operations: One rank "
                             f"specified reduce op "
                             f"{wire.reduce_op_name(first.reduce_op)}, but "
                             f"another rank specified reduce op "
                             f"{wire.reduce_op_name(r.reduce_op)}.")
                    break
            if error is None and op == RequestType.ALLREDUCE \
                    and len(reqs) < self.size and \
                    first.reduce_op not in (wire.ReduceOp.SUM,
                                            wire.ReduceOp.AVERAGE):
                # Completed via joins: a joined rank's zero contribution
                # is only an identity for sum/average.
                error = (f"Allreduce with reduce op "
                         f"{wire.reduce_op_name(first.reduce_op)} cannot "
                         f"complete after a rank has joined: a joined "
                         f"rank's zero contribution is only an identity "
                         f"for sum/average.")
        # Allgather: same ndim, same non-first dims (operations.cc:334-392).
        tensor_sizes: List[int] = []
        if error is None and op == RequestType.ALLGATHER:
            if len(first.tensor_shape) == 0:
                error = "Rank zero tried to gather a rank-zero tensor."
            else:
                for r in reqs[1:]:
                    if len(r.tensor_shape) != len(first.tensor_shape):
                        error = (
                            f"Mismatched allgather tensor shapes: One rank "
                            f"sent a tensor of rank {len(first.tensor_shape)},"
                            f" but another rank sent a tensor of rank "
                            f"{len(r.tensor_shape)}.")
                        break
                    for dim in range(1, len(first.tensor_shape)):
                        if r.tensor_shape[dim] != first.tensor_shape[dim]:
                            error = (
                                f"Mismatched allgather tensor shapes: One "
                                f"rank sent a tensor with dimension {dim} "
                                f"equal to {first.tensor_shape[dim]}, but "
                                f"another rank sent a tensor with dimension "
                                f"{dim} equal to {r.tensor_shape[dim]}.")
                            break
                    if error:
                        break
            if error is None:
                # RANK-indexed extents: joined ranks contribute 0 rows
                # (identical to the old per-submitter list when no rank
                # has joined).
                by_rank = {r.request_rank: r.tensor_shape[0] for r in reqs}
                tensor_sizes = [by_rank.get(r, 0) for r in range(self.size)]
        # Alltoall (post-v0.13): trailing-dim agreement; each rank's
        # splits must cover its own dim 0; never completes via joins
        # (every rank both sends and receives).  The response's
        # tensor_sizes carries the full split matrix, row-major by
        # sender, so receivers know every incoming row count.
        alltoall_sizes: List[int] = []
        if error is None and op == RequestType.ALLTOALL:
            if len(first.tensor_shape) == 0:
                error = "An alltoall tensor needs at least one dimension."
            for r in reqs[1:]:
                if error:
                    break
                if len(r.tensor_shape) != len(first.tensor_shape) or \
                        r.tensor_shape[1:] != first.tensor_shape[1:]:
                    error = (f"Mismatched alltoall tensor shapes: One rank "
                             f"sent a tensor of shape "
                             f"{list(first.tensor_shape)}, but another "
                             f"rank sent a tensor of shape "
                             f"{list(r.tensor_shape)}.")
            if error is None and len(reqs) < self.size:
                error = ("Alltoall cannot complete after a rank has "
                         "joined: every rank must both send and receive.")
            if error is None:
                for r in reqs:
                    d0 = r.tensor_shape[0]
                    if not r.splits:
                        if d0 % self.size != 0:
                            error = (f"Alltoall without splits needs dim 0 "
                                     f"divisible by the rank count "
                                     f"({self.size}); rank "
                                     f"{r.request_rank} sent {d0} rows.")
                            break
                        row = [d0 // self.size] * self.size
                    elif len(r.splits) != self.size or \
                            sum(r.splits) != d0 or \
                            any(s < 0 for s in r.splits):
                        error = (f"Invalid alltoall splits from rank "
                                 f"{r.request_rank}: {list(r.splits)} "
                                 f"must have one non-negative entry per "
                                 f"rank ({self.size}) summing to its dim "
                                 f"0 ({d0}).")
                        break
                    else:
                        row = list(r.splits)
                    alltoall_sizes.extend(row)
        # Broadcast: root agreement + shape agreement
        # (operations.cc:396-431).
        if error is None and op == RequestType.BROADCAST:
            for r in reqs[1:]:
                if r.root_rank != first.root_rank:
                    error = (f"Mismatched broadcast root ranks: One rank "
                             f"specified root rank {first.root_rank}, but "
                             f"another rank specified root rank "
                             f"{r.root_rank}.")
                    break
            if error is None:
                for r in reqs[1:]:
                    if r.tensor_shape != first.tensor_shape:
                        error = (f"Mismatched broadcast tensor shapes: One "
                                 f"rank sent a tensor of shape "
                                 f"{list(first.tensor_shape)}, but another "
                                 f"rank sent a tensor of shape "
                                 f"{list(r.tensor_shape)}.")
                        break
            if error is None and len(reqs) < self.size \
                    and first.root_rank not in {r.request_rank
                                                for r in reqs}:
                # Completed via joins and the root is among the joined:
                # there is no data to broadcast.
                error = (f"Broadcast root rank {first.root_rank} has "
                         f"joined; a joined rank cannot be the source "
                         f"of a broadcast.")
        # Device agreement (operations.cc:418-440): collectives must run on a
        # consistent device class across replicas.
        if error is None:
            for r in reqs[1:]:
                if (r.device == wire.CPU_DEVICE_ID) != (
                        first.device == wire.CPU_DEVICE_ID):
                    error = (f"Mismatched host/device selection: One rank "
                             f"specified device {first.device}, but another "
                             f"rank specified device {r.device}.")
                    break

        if error is not None:
            return Response(ResponseType.ERROR, [name], error_message=error,
                            process_set_id=first.process_set_id)
        self._resp_dtype[name] = first.tensor_type
        self._resp_nbytes[name] = entry.nbytes
        devices = [r.device for r in reqs]
        # dtype + shape ride every data response so joined ranks can
        # build zero contributions (hvd.join); BROADCAST also carries
        # its root in tensor_sizes (a joined rank has no local op).
        common = dict(devices=devices, tensor_type=first.tensor_type,
                      tensor_shapes=[tuple(first.tensor_shape)],
                      process_set_id=first.process_set_id)
        if op == RequestType.ALLREDUCE:
            return Response(ResponseType.ALLREDUCE, [name],
                            reduce_op=first.reduce_op, **common)
        if op == RequestType.REDUCESCATTER:
            return Response(ResponseType.REDUCESCATTER, [name],
                            reduce_op=first.reduce_op, **common)
        if op == RequestType.ALLTOALL:
            return Response(ResponseType.ALLTOALL, [name],
                            tensor_sizes=alltoall_sizes, **common)
        if op == RequestType.ALLGATHER:
            return Response(ResponseType.ALLGATHER, [name],
                            tensor_sizes=tensor_sizes, **common)
        return Response(ResponseType.BROADCAST, [name],
                        tensor_sizes=[first.root_rank], **common)

    # -- Fusion loop (operations.cc:1328-1374) -----------------------------
    def poll_responses(self, sizes_bytes: Dict[str, int]) -> List[Response]:
        """Drain ready tensors into (possibly fused) responses.

        ``sizes_bytes`` maps tensor name → payload bytes, used to respect the
        fusion threshold exactly like the reference's
        ``TensorFusionThresholdBytes`` accounting.
        """
        with self._lock:
            withdrawn, self._withdrawn = self._withdrawn, []
            release, self._join_release = self._join_release, []
            ready, self.ready = self.ready, []
            responses = [self._construct_response_locked(n) for n in ready]
            # Snapshots for the fusion planning below: it runs outside
            # the lock, and both maps are mutated by concurrent submits'
            # construct_response (surfaced by the guarded-by lint pass).
            dtypes = dict(self._resp_dtype)
            nbytes_map = dict(self._resp_nbytes)

        # Per-response payload bytes, resolved ONCE: the queue-side size
        # table wins when present, else the submit-time value carried on
        # the table entry (a process set excluding the controller has no
        # entries in ITS queue, and an unbounded fallback of 0 would
        # defeat the threshold).
        metas = [_cache._FusionMeta(
            response_type=r.response_type, devices=tuple(r.devices),
            reduce_op=r.reduce_op, process_set_id=r.process_set_id,
            dtype=dtypes.get(r.tensor_names[0]),
            nbytes=sizes_bytes.get(r.tensor_names[0],
                                   nbytes_map.get(r.tensor_names[0], 0)))
            for r in responses]
        fused: List[Response] = list(withdrawn)
        for group in _cache.plan_fusion(metas,
                                        lambda _psid: self.fusion_threshold):
            r = responses[group[0]]
            for j in group[1:]:
                nxt = responses[j]
                r.tensor_names.extend(nxt.tensor_names)
                r.tensor_shapes.extend(nxt.tensor_shapes)
            fused.append(r)
        with self._lock:
            for r in fused:
                for n in r.tensor_names:
                    self._resp_dtype.pop(n, None)
                    self._resp_nbytes.pop(n, None)
        # The JOIN release comes LAST: joined ranks must execute this
        # batch's data responses (with zero contributions) before being
        # released from join().
        fused.extend(release)
        return fused

    # -- CheckForStalledTensors (operations.cc:1072-1115) ------------------
    def check_stalled(self, now: Optional[float] = None,
                      threshold: float = STALL_WARNING_SECONDS) -> List[str]:
        now = time.monotonic() if now is None else now
        warnings = []
        with self._lock:
            # Copy the rank sets too: submit() mutates them under the
            # lock while this report renders (guarded-by lint pass).
            items = [(name, entry.first_seen, set(entry.ranks))
                     for name, entry in self.table.items()]
        for name, first_seen, ranks in items:
            if now - first_seen > threshold:
                ready = sorted(ranks)
                missing = sorted(set(range(self.size)) - ranks)
                warnings.append(
                    f"Tensor {name} has been pending for "
                    f"{now - first_seen:.0f}s; ready replicas: {ready}; "
                    f"waiting on replicas: {missing}. One or more replicas "
                    f"submitted this collective and are waiting for the "
                    f"remaining replicas to do the same.")
        return warnings

    def set_fusion_threshold(self, v: int) -> None:
        """Autotune hook (≙ the post-v0.13 HOROVOD_AUTOTUNE subsystem
        re-tuning TensorFusionThresholdBytes between cycles)."""
        with self._lock:
            self.fusion_threshold = v

    def request_shutdown(self) -> None:
        self.shutdown = True

    def close(self) -> None:
        # Locked: shutdown() can close while the drain thread is mid-poll
        # (surfaced by the guarded-by lint pass).
        with self._lock:
            self.table.clear()
            self.ready.clear()


class NativeCoordinator:
    """ctypes facade over native/coordinator.cc (same wire format)."""

    def __init__(self, size: int, fusion_threshold: int):
        import ctypes

        self._lib = _native.raw()
        self._ptr = self._lib.hvd_coord_create(size, fusion_threshold)
        self.size = size
        self.fusion_threshold = fusion_threshold
        # Response fetch buffer, reused across polls: poll runs every
        # 5 ms tick, and a fresh 1 MB create_string_buffer per call is
        # a 1 MB memset on the steady-state hot path.  Only the drain
        # thread polls, so one buffer is safe.
        self._out_cap = 1 << 20
        self._out = ctypes.create_string_buffer(self._out_cap)

    def submit(self, req: Request, now: Optional[float] = None) -> bool:
        buf = req.pack()
        rc = self._lib.hvd_coord_submit(self._ptr, buf, len(buf))
        if rc == -1:
            raise ValueError(
                f"Duplicate request for tensor {req.tensor_name} from replica "
                f"{req.request_rank}; a name may be used by at most one "
                f"pending collective per replica.")
        if rc < 0:
            raise RuntimeError(
                f"Native coordinator rejected a malformed request buffer for "
                f"tensor {req.tensor_name} (wire-format mismatch between "
                f"ops/wire.py and native/wire.cc?).")
        return bool(rc)

    def withdraw(self, name: str, rank: int) -> None:
        nb = name.encode("utf-8")
        self._lib.hvd_coord_withdraw(self._ptr, nb, len(nb), rank)

    def poll_responses(self, sizes_bytes: Dict[str, int]) -> List[Response]:
        # Ship the payload sizes as a serialized side table.
        import struct
        side = struct.pack("<H", len(sizes_bytes))
        for k, v in sizes_bytes.items():
            kb = k.encode()
            side += struct.pack("<H", len(kb)) + kb + struct.pack("<q", v)
        n = self._lib.hvd_coord_poll_responses(self._ptr, side, len(side), 0.0)
        if n < 0:
            raise RuntimeError("native coordinator poll failed")
        # Responses are fetched via a second call writing into the
        # reused buffer.
        n = self._lib.hvd_coord_fetch_responses(self._ptr, self._out,
                                                self._out_cap)
        if n < 0:
            raise RuntimeError("native coordinator fetch overflow")
        return wire.unpack_response_list(self._out.raw[:n])

    def check_stalled(self, now: Optional[float] = None,
                      threshold: float = STALL_WARNING_SECONDS) -> List[str]:
        import ctypes
        cap = 1 << 16
        out = ctypes.create_string_buffer(cap)
        n = self._lib.hvd_coord_check_stalled(
            self._ptr, threshold, out, cap)
        if n <= 0:
            return []
        text = out.raw[:n].decode("utf-8")
        return [w for w in text.split("\n") if w]

    def set_fusion_threshold(self, v: int) -> None:
        self.fusion_threshold = v
        self._lib.hvd_coord_set_fusion_threshold(self._ptr, v)

    def close(self) -> None:
        if self._ptr:
            self._lib.hvd_coord_destroy(self._ptr)
            self._ptr = None


@_races.race_checked
class Coordinator:
    """Facade selecting the native coordinator when built, Python otherwise,
    and layering the timeline + stderr stall reporting over either.

    With ``HVD_TPU_VERIFY_PROGRAM=1`` it also runs the hvd-analyze
    program tracker (analysis/program.py) over the request streams: a
    rank-divergent program ORDER — which the name-keyed request table
    below can only ever stall on — is converted into an immediate ERROR
    response naming the first divergent entry, before any data-plane
    work.

    With a :class:`~horovod_tpu.ops.cache.ResponseCache` attached it
    also runs the steady-state fast path ABOVE both implementations: a
    submit whose packed request matches a cached negotiation is
    accounted as a cache hit instead of entering the request table, and
    fully-hit cycles replay from the cache (the drain loop drains them
    via ``cache.take_ready``), skipping ``submit`` and
    ``construct_response`` entirely.  Successful negotiations are
    retained per rank (``_inflight``) and staged into the cache at poll
    time so the insertion that follows — driven by the broadcast
    response stream — can store each rank's exact request."""

    def __init__(self, size: int, fusion_threshold: int, timeline=None,
                 cache=None, ranks=None):
        self.timeline = timeline
        self._last_stall_check = time.monotonic()
        # Gate on the newest symbol so a stale prebuilt .so falls back to
        # the Python twin instead of AttributeError-ing at call time.
        if _native.NATIVE and hasattr(_native.raw(),
                                      "hvd_coord_set_fusion_threshold"):
            self._impl = NativeCoordinator(size, fusion_threshold)
        else:
            self._impl = PyCoordinator(size, fusion_threshold)
        self.size = size
        self.cache = cache
        # Global rank per set-local index (identity for the global set);
        # cache entries account readiness in global ranks so worker bits
        # and process-set submits share one table.
        self._ranks = tuple(ranks) if ranks is not None \
            else tuple(range(size))
        self._inflight_lock = _lockorder.make_lock("Coordinator._inflight")
        # name -> {global rank -> Request} of in-negotiation requests,
        # retained for cache insertion once the response broadcasts.
        self._inflight: Dict[str, Dict[int, Request]] = {}  # guarded_by: _inflight_lock
        # True when the underlying impl has seen a submit/withdraw since
        # the last poll: in the cache steady state every request is
        # served as a hit, and polling an untouched impl every 5 ms tick
        # is pure overhead (the native impl's poll crosses ctypes).
        # ORDERING CONTRACT: the flag is set AFTER the impl call lands
        # and cleared BEFORE the poll.  Either a concurrent clearing
        # poll runs after the submit landed (and sees it), or the flag
        # survives for the next poll — one explicit drain after a submit
        # always observes it.  Setting the flag BEFORE the impl call is
        # a lost wakeup: a tick between flag-set and submit-landing
        # clears the flag, polls empty tables, and leaves the landed
        # request invisible behind dirty=False (the roaming single-
        # process "it would stall" HorovodError).
        self._impl_dirty = True
        self._tracker = (_program.ProgramTracker(size)
                         if _program.program_check_enabled() else None)
        self._tracker_lock = _lockorder.make_lock("Coordinator._tracker")
        # guarded_by: _tracker_lock
        self._program_errors: List[Response] = []

    @property
    def fusion_threshold(self) -> int:
        return self._impl.fusion_threshold

    def submit(self, req: Request) -> bool:
        done, _ = self.submit_ex(req)
        return done

    def submit_ex(self, req: Request) -> "tuple[bool, bool]":
        """Submit one request; returns (negotiation_complete,
        served_from_cache)."""
        if self.timeline is not None:
            self.timeline.negotiate_rank_ready(req.tensor_name,
                                               req.request_rank,
                                               first=req.request_rank == 0)
        if self._tracker is not None:
            # JOIN disables the tracker (join legalizes rank-divergent
            # programs — see ProgramTracker).  The tracker and the
            # response cache are mutually exclusive (cache_enabled), so
            # every request reaches this feed when tracking.
            diag = self._tracker.feed(req)
            if diag is not None:
                # Fail the divergent op on every rank at the next poll —
                # negotiation can never complete for a reordered stream.
                with self._tracker_lock:
                    self._program_errors.append(Response(
                        ResponseType.ERROR, [req.tensor_name],
                        error_message=diag,
                        process_set_id=req.process_set_id))
        if self.cache is not None:
            if req.request_type == RequestType.JOIN:
                # Joined ranks complete tensors they never requested;
                # such negotiations must not become cache entries, and
                # existing entries' rank accounting no longer holds.
                self._resubmit(self.cache.disarm("hvd.join()"))
            else:
                kind, info = self.cache.lookup_and_hit(req)
                if self.timeline is not None:
                    self.timeline.cache_event(req.tensor_name,
                                              hit=kind == "hit")
                    st = self.cache.stats
                    self.timeline.cache_counter(st.hits, st.misses)
                if kind == "hit":
                    # NEGOTIATE-span closure for cache-served tensors
                    # happens once, at replay time in the drain tick —
                    # the completing hit may be a remote bit this
                    # submit path never sees.
                    return bool(info), True
                if kind == "conflict":
                    # The program changed mid-run: the cache flushed;
                    # the peers' raced cached submissions downgrade to
                    # real negotiation so nothing is lost, and THIS
                    # request follows them through the normal path
                    # (surfacing the usual mismatch diagnostics).
                    self._resubmit(info)
                self._retain(req)
        # Flight ring: real (non-cache-hit) negotiation traffic.  The
        # steady-state hit path above returns before this point, so the
        # ring records exactly the divergences a forensic replay needs
        # — misses, first-time programs, downgrades — not the per-step
        # replay noise (which the replay/frame events already cover).
        # Bound method + raw enum: this runs once per miss-submit, and
        # the enum stringifies at dump time, not here.
        _flight_record("submit", req.tensor_name, req.request_rank,
                       req.request_type)
        try:
            done = self._impl.submit(req)
        finally:
            # AFTER the impl call — see the _impl_dirty ordering
            # contract in __init__.
            self._impl_dirty = True
        if done and self.timeline is not None:
            self.timeline.negotiate_end(req.tensor_name)
        return done, False

    def _retain(self, req: Request) -> None:
        local = req.request_rank
        grank = self._ranks[local] if 0 <= local < len(self._ranks) \
            else local
        with self._inflight_lock:
            self._inflight.setdefault(req.tensor_name, {})[grank] = req

    def _resubmit(self, orphans: List[Request]) -> None:
        """Feed cached submissions back through the real negotiation
        path (cache flush / conflict / withdraw downgrades)."""
        for req in orphans:
            try:
                self._retain(req)
                self._impl.submit(req)
            except ValueError:
                pass  # duplicate: the rank re-submitted meanwhile
            finally:
                self._impl_dirty = True

    def withdraw(self, name: str, rank: int) -> None:
        _M_WITHDRAWALS.inc()
        _flight.record("withdraw", name, rank)
        if self.cache is not None:
            # A withdrawal is a program-divergence signal (a rank timed
            # out waiting): invalidate, downgrading any mid-flight
            # cached submissions so the impl's withdraw below can fail
            # the op group-wide with the standard diagnosis.
            self._resubmit(self.cache.flush(
                f"withdraw of {name!r} by rank {rank}", broadcast=True))
        try:
            self._impl.withdraw(name, rank)
        finally:
            self._impl_dirty = True

    def set_fusion_threshold(self, v: int) -> None:
        self._impl.set_fusion_threshold(v)
        if self.cache is not None:
            # Entries stay valid (the negotiated outcome is threshold-
            # independent) but every memoized packing plan is stale.
            self.cache.invalidate_plans(f"fusion threshold -> {v}")
        # The compiled megakernels are keyed by group STRUCTURE, which a
        # re-partitioned threshold changes wholesale — drop them with
        # the plan memo instead of aging stale executables out (lazy
        # import: megakernel pulls in jax kernels this control-plane
        # module otherwise never needs).
        from . import megakernel as _megakernel

        _megakernel.flush(f"fusion threshold -> {v}")

    def poll_responses(self, sizes_bytes: Dict[str, int]) -> List[Response]:
        now = time.monotonic()
        if now - self._last_stall_check > STALL_WARNING_SECONDS:
            self._last_stall_check = now
            # Threshold passed explicitly (the module global, read at
            # call time) so tests can tighten the watchdog, and the
            # warnings feed the telemetry stall counter + a flight-
            # recorder dump whose tail names the stalled tensor and the
            # non-ready ranks.
            warnings = self._impl.check_stalled(now,
                                                STALL_WARNING_SECONDS)
            for w in warnings:
                print(f"WARNING: {w}", file=sys.stderr)
            _telemetry.stall_event(warnings)
        if self.cache is not None and not self._impl_dirty:
            # Steady state: every request since the last poll was a
            # cache hit, so the impl's tables are exactly as the last
            # poll left them — empty of ready work.
            resps: List[Response] = []
        else:
            self._impl_dirty = False
            resps = self._impl.poll_responses(sizes_bytes)
        if self.cache is not None and resps:
            staged = []
            with self._inflight_lock:
                for r in resps:
                    if r.response_type in (ResponseType.ALLREDUCE,
                                           ResponseType.ALLGATHER,
                                           ResponseType.BROADCAST,
                                           ResponseType.REDUCESCATTER,
                                           ResponseType.ALLTOALL):
                        for n in r.tensor_names:
                            reqs = self._inflight.pop(n, None)
                            if reqs:
                                staged.append((n, reqs))
                    else:
                        for n in r.tensor_names:
                            self._inflight.pop(n, None)
            for n, reqs in staged:
                self.cache.stage_negotiated(n, reqs)
        with self._tracker_lock:
            if self._program_errors:
                resps = self._program_errors + resps
                self._program_errors = []
        return resps

    def check_stalled(self, now=None, threshold=STALL_WARNING_SECONDS):
        return self._impl.check_stalled(now, threshold)

    def close(self) -> None:
        self._impl.close()
