"""Control-message wire format.

TPU-native re-design of the reference's flatbuffers control-message layer
(horovod/common/mpi_message.{h,cc} + wire/mpi_message.fbs).  The reference
serializes worker→coordinator ``MPIRequest`` and coordinator→worker
``MPIResponse`` messages with flatbuffers; we use a hand-rolled
little-endian binary layout (packed here and parsed identically by
native/wire.cc) because the messages are tiny, fixed-field, and the control
plane only runs on the *dynamic* path (eager ops, variable-size allgather,
error negotiation) — the static pjit path needs no control messages at all.

Field-for-field parity with the reference schema:
  Request  ≙ MPIRequest  (mpi_message.h:43-85): request_rank, request_type,
             tensor_type, tensor_name, root_rank, device, tensor_shape.
  Response ≙ MPIResponse (mpi_message.h:112-157): response_type (incl.
             ERROR/DONE/SHUTDOWN), fused tensor_names, error_message,
             devices, tensor_sizes (allgather dim-0 per rank).
"""

from __future__ import annotations

import struct
from dataclasses import dataclass, field
from enum import IntEnum
from typing import List, Optional, Tuple

import numpy as np


class DataType(IntEnum):
    """Mirrors MPIDataType (mpi_message.h:26-36) plus TPU-first additions:
    bfloat16 is the native TPU matmul dtype and float16 completes the
    half-precision pair."""

    UINT8 = 0
    INT8 = 1
    UINT16 = 2
    INT16 = 3
    INT32 = 4
    INT64 = 5
    FLOAT32 = 6
    FLOAT64 = 7
    BOOL = 8
    BFLOAT16 = 9
    FLOAT16 = 10
    UINT32 = 11
    UINT64 = 12


_NP_TO_DTYPE = {
    np.dtype(np.uint8): DataType.UINT8,
    np.dtype(np.int8): DataType.INT8,
    np.dtype(np.uint16): DataType.UINT16,
    np.dtype(np.int16): DataType.INT16,
    np.dtype(np.int32): DataType.INT32,
    np.dtype(np.int64): DataType.INT64,
    np.dtype(np.float32): DataType.FLOAT32,
    np.dtype(np.float64): DataType.FLOAT64,
    np.dtype(np.bool_): DataType.BOOL,
    np.dtype(np.float16): DataType.FLOAT16,
    np.dtype(np.uint32): DataType.UINT32,
    np.dtype(np.uint64): DataType.UINT64,
}

_DTYPE_SIZE = {
    DataType.UINT8: 1, DataType.INT8: 1, DataType.UINT16: 2,
    DataType.INT16: 2, DataType.INT32: 4, DataType.INT64: 8,
    DataType.FLOAT32: 4, DataType.FLOAT64: 8, DataType.BOOL: 1,
    DataType.BFLOAT16: 2, DataType.FLOAT16: 2,
    DataType.UINT32: 4, DataType.UINT64: 8,
}


def dtype_of(array_dtype) -> DataType:
    """np/jnp dtype → wire DataType (≙ GetMPIDataType table,
    operations.cc:463-487)."""
    d = np.dtype(array_dtype) if not str(array_dtype) == "bfloat16" else None
    if d is not None and d in _NP_TO_DTYPE:
        return _NP_TO_DTYPE[d]
    if str(array_dtype) == "bfloat16":
        return DataType.BFLOAT16
    raise ValueError(f"Unsupported dtype for horovod_tpu collective: {array_dtype}")


def dtype_name(dt: DataType) -> str:
    return DataType(dt).name.lower()


_DTYPE_TO_NP = {v: k for k, v in _NP_TO_DTYPE.items()}


def np_dtype_of(dt: DataType):
    """Wire DataType → numpy dtype (inverse of :func:`dtype_of`); a
    joined rank uses it to build zero contributions from a Response."""
    dt = DataType(dt)
    if dt == DataType.BFLOAT16:
        import ml_dtypes

        return np.dtype(ml_dtypes.bfloat16)
    return _DTYPE_TO_NP[dt]


def dtype_size(dt: DataType) -> int:
    return _DTYPE_SIZE[DataType(dt)]


class RequestType(IntEnum):
    """≙ MPIRequestType (mpi_message.h), plus JOIN — the post-v0.13
    Horovod barrier for uneven workloads (a rank out of data declares it
    will contribute zeros to every remaining collective) — and
    REDUCESCATTER (post-v0.13: reduce, then split dim 0 across ranks)."""

    ALLREDUCE = 0
    ALLGATHER = 1
    BROADCAST = 2
    JOIN = 3
    REDUCESCATTER = 4
    ALLTOALL = 5


class ReduceOp(IntEnum):
    """Allreduce reduction operator (the post-v0.13 Horovod ``op=``
    API — hvd.Average/Sum/Adasum/Min/Max/Product; the v0.13 reference
    hard-codes MPI_SUM, operations.cc:984-988).  Carried per Request so
    the coordinator validates cross-rank agreement and fuses only
    like-op responses."""

    AVERAGE = 0
    SUM = 1
    ADASUM = 2
    MIN = 3
    MAX = 4
    PRODUCT = 5


def reduce_op_name(op) -> str:
    return ReduceOp(op).name.lower()


class ResponseType(IntEnum):
    """≙ MPIResponseType (mpi_message.h) — ERROR carries a cross-replica
    validation message; DONE/SHUTDOWN close the negotiation; JOIN
    releases every joined rank (tensor_sizes carries the last joining
    rank, hvd.join()'s return value).  CACHE_FLUSH is a response-cache
    epoch marker (ops/cache.py): it rides the broadcast response list so
    every rank flushes its cache replica at the same position of the
    response stream; tensor_sizes carries [new_epoch, disarm_flag].
    RETUNE is an hvd-tune knob-change marker (tuning/actuation.py): it
    rides the same stream so every rank applies the new knob value at
    the same cycle boundary; tensor_names carries ``["knob=value", ...]``
    and tensor_sizes carries ``[decision_seq]``.  Both markers are
    Python-constructed and broadcast by the Python transport, so the
    native twin (native/wire.cc) never sees them and needs no mirror."""

    ALLREDUCE = 0
    ALLGATHER = 1
    BROADCAST = 2
    ERROR = 3
    DONE = 4
    SHUTDOWN = 5
    JOIN = 6
    REDUCESCATTER = 7
    ALLTOALL = 8
    CACHE_FLUSH = 9
    RETUNE = 10


# Device id of a host-resident tensor (≙ CPU_DEVICE_ID, common.h:28).
CPU_DEVICE_ID = -1

# Phrase carried by every dead-peer SHUTDOWN diagnosis (a peer vanished
# without its exit handshake).  Survivors that see it must skip
# jax.distributed's exit barrier — the dead process can never join it —
# via core.cluster.disarm_distributed_shutdown.  Defined here because the
# producers live in three modules (ops/collective.py and core/state.py on
# the controller side, ops/transport.py on the worker side).  Deliberate
# tradeoff: this rides the existing error_message field rather than a new
# wire flag, which would also touch the C++ twin (native/wire.cc) for one
# bit; every producer MUST build its message from this constant.
DEAD_PEER_MARKER = "terminated unexpectedly"


@dataclass
class Request:
    request_rank: int
    request_type: RequestType
    tensor_type: DataType
    tensor_name: str
    root_rank: int = -1
    device: int = CPU_DEVICE_ID
    tensor_shape: Tuple[int, ...] = ()
    # ALLREDUCE only (ALLGATHER/BROADCAST ignore it): the reduction
    # operator, validated for cross-rank agreement by the coordinator.
    reduce_op: ReduceOp = ReduceOp.AVERAGE
    # Process set this op negotiates within (post-v0.13 hvd process
    # sets; 0 = the global set).  request_rank/root_rank are SET-LOCAL
    # indices for non-global sets, so readiness counting, stall
    # reporting and allgather size ordering stay rank-table-shaped.
    process_set_id: int = 0
    # ALLTOALL only: rows of dim 0 this rank sends to each destination
    # (length = communicator size; empty = even split).
    splits: Tuple[int, ...] = ()

    def pack(self) -> bytes:
        name_b = self.tensor_name.encode("utf-8")
        out = struct.pack(
            "<BBiiiBHH", int(self.request_type), int(self.tensor_type),
            self.request_rank, self.root_rank, self.device,
            int(self.reduce_op), self.process_set_id, len(name_b))
        out += name_b
        out += struct.pack("<B", len(self.tensor_shape))
        for d in self.tensor_shape:
            out += struct.pack("<q", d)
        out += struct.pack("<H", len(self.splits))
        for s in self.splits:
            out += struct.pack("<q", s)
        return out

    @staticmethod
    def unpack(buf: bytes, off: int = 0) -> Tuple["Request", int]:
        rt, tt, rank, root, dev, rop, psid, nlen = struct.unpack_from(
            "<BBiiiBHH", buf, off)
        off += struct.calcsize("<BBiiiBHH")
        name = buf[off:off + nlen].decode("utf-8")
        off += nlen
        (ndim,) = struct.unpack_from("<B", buf, off)
        off += 1
        dims = struct.unpack_from(f"<{ndim}q", buf, off) if ndim else ()
        off += 8 * ndim
        (nspl,) = struct.unpack_from("<H", buf, off)
        off += 2
        spl = struct.unpack_from(f"<{nspl}q", buf, off) if nspl else ()
        off += 8 * nspl
        return Request(rank, RequestType(rt), DataType(tt), name, root, dev,
                       tuple(dims), ReduceOp(rop), psid, tuple(spl)), off


@dataclass
class Response:
    response_type: ResponseType
    tensor_names: List[str] = field(default_factory=list)
    error_message: str = ""
    devices: List[int] = field(default_factory=list)
    # For ALLGATHER: dim-0 extent contributed by each replica, in RANK
    # order with 0 for joined ranks (ordering ≙ mpi_message.h:48-51).
    # For BROADCAST: [root_rank] (a joined rank has no local op to read
    # the root from).  For JOIN: [last joining rank].
    tensor_sizes: List[int] = field(default_factory=list)
    # Round 4 (hvd.join support): the validated dtype and each fused
    # tensor's shape, aligned with tensor_names — a joined rank builds
    # its zero contributions from these.  255 on the wire = no dtype.
    tensor_type: Optional[DataType] = None
    tensor_shapes: List[Tuple[int, ...]] = field(default_factory=list)
    # ALLREDUCE: the validated reduction operator (fusion groups are
    # homogeneous in it; joined ranks execute from it).
    reduce_op: ReduceOp = ReduceOp.AVERAGE
    # Process set the response belongs to (0 = global); a joined rank
    # skips non-global responses it holds no ops for.
    process_set_id: int = 0

    def pack(self) -> bytes:
        out = struct.pack("<BH", int(self.response_type), len(self.tensor_names))
        for n in self.tensor_names:
            nb = n.encode("utf-8")
            out += struct.pack("<H", len(nb)) + nb
        eb = self.error_message.encode("utf-8")
        out += struct.pack("<I", len(eb)) + eb
        out += struct.pack("<H", len(self.devices))
        for d in self.devices:
            out += struct.pack("<i", d)
        out += struct.pack("<H", len(self.tensor_sizes))
        for s in self.tensor_sizes:
            out += struct.pack("<q", s)
        out += struct.pack("<B", 255 if self.tensor_type is None
                           else int(self.tensor_type))
        out += struct.pack("<H", len(self.tensor_shapes))
        for shape in self.tensor_shapes:
            out += struct.pack("<B", len(shape))
            for d in shape:
                out += struct.pack("<q", d)
        out += struct.pack("<B", int(self.reduce_op))
        out += struct.pack("<H", self.process_set_id)
        return out

    @staticmethod
    def unpack(buf: bytes, off: int = 0) -> Tuple["Response", int]:
        rt, nnames = struct.unpack_from("<BH", buf, off)
        off += struct.calcsize("<BH")
        names = []
        for _ in range(nnames):
            (ln,) = struct.unpack_from("<H", buf, off)
            off += 2
            names.append(buf[off:off + ln].decode("utf-8"))
            off += ln
        (elen,) = struct.unpack_from("<I", buf, off)
        off += 4
        err = buf[off:off + elen].decode("utf-8")
        off += elen
        (ndev,) = struct.unpack_from("<H", buf, off)
        off += 2
        devices = list(struct.unpack_from(f"<{ndev}i", buf, off)) if ndev else []
        off += 4 * ndev
        (nsz,) = struct.unpack_from("<H", buf, off)
        off += 2
        sizes = list(struct.unpack_from(f"<{nsz}q", buf, off)) if nsz else []
        off += 8 * nsz
        (tt,) = struct.unpack_from("<B", buf, off)
        off += 1
        (nshp,) = struct.unpack_from("<H", buf, off)
        off += 2
        shapes: List[Tuple[int, ...]] = []
        for _ in range(nshp):
            (ndim,) = struct.unpack_from("<B", buf, off)
            off += 1
            dims = struct.unpack_from(f"<{ndim}q", buf, off) if ndim else ()
            off += 8 * ndim
            shapes.append(tuple(dims))
        (rop,) = struct.unpack_from("<B", buf, off)
        off += 1
        (psid,) = struct.unpack_from("<H", buf, off)
        off += 2
        return Response(ResponseType(rt), names, err, devices, sizes,
                        None if tt == 255 else DataType(tt), shapes,
                        ReduceOp(rop), psid), off


def pack_response_list(responses: List[Response]) -> bytes:
    out = struct.pack("<H", len(responses))
    for r in responses:
        out += r.pack()
    return out


def unpack_response_list_ex(buf: bytes) -> Tuple[List[Response], int]:
    """Parse a packed response list and ALSO return the consumed byte
    count — the list is self-delimiting, so callers can carry trailers
    after it (the hvd-trace context on FRAME_RESPONSES) that old
    parsers simply never read."""
    (n,) = struct.unpack_from("<H", buf, 0)
    off = 2
    out = []
    for _ in range(n):
        r, off = Response.unpack(buf, off)
        out.append(r)
    return out, off


def unpack_response_list(buf: bytes) -> List[Response]:
    return unpack_response_list_ex(buf)[0]
