"""Process sets: collectives over a subset of ranks.

≙ the post-v0.13 Horovod process-set API (``hvd.add_process_set`` +
the ``process_set=`` argument on collectives); the v0.13 reference
fixes every collective to MPI_COMM_WORLD.  On the TPU *static* path a
process set is just a mesh over a device subset (any ``shard_map`` over
a sub-``Mesh``); this module gives the *dynamic* (eager) path the same
capability: per-set negotiation through a per-set coordinator on the
controller, per-set sub-mesh execution, and cross-rank registration
validation.

Rank-number convention: a set is declared with GLOBAL rank numbers
(sorted, deduplicated); on the wire and in the coordinator the set's
members are re-indexed 0..k-1 (set-local), so readiness counting, stall
reports and allgather size tables keep their rank-table shape.
Broadcast ``root_rank`` is likewise the GLOBAL rank at the API and
translated to set-local internally — matching Horovod's convention.

Restrictions (each documented at the raise site): a non-member may not
submit into a set; ``hvd.join()`` interoperates with the GLOBAL set
only; single-process set collectives take replicated values or
per-member lists (a globally-sharded per-replica array has no canonical
sub-slicing).
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

from ..core import state as _state


class ProcessSet:
    """A registered subset of ranks (``process_set_id`` 0 = global).

    ``ranks`` are GLOBAL rank numbers: replica indices in
    single-process mode, process ranks in multi-process mode.
    """

    def __init__(self, process_set_id: int, ranks: Tuple[int, ...]):
        self.process_set_id = process_set_id
        self.ranks = tuple(sorted(ranks))
        # Controller-side per-set coordinator (set by add_process_set).
        self.coordinator = None
        self._mesh_kernels = None

    def size(self) -> int:
        return len(self.ranks)

    def included(self) -> bool:
        """Is the calling process a member?  Always True single-process
        (the one host drives every replica)."""
        st = _state.global_state()
        if not st.multiprocess:
            return True
        return st.process_index in self.ranks

    def rank(self) -> int:
        """The caller's SET-LOCAL index, or -1 if not a member."""
        st = _state.global_state()
        if not st.multiprocess:
            return 0
        try:
            return self.ranks.index(st.process_index)
        except ValueError:
            return -1

    def local_rank_of(self, global_rank: int) -> int:
        try:
            return self.ranks.index(global_rank)
        except ValueError:
            raise ValueError(
                f"rank {global_rank} is not a member of process set "
                f"{self.process_set_id} (ranks {list(self.ranks)})"
            ) from None

    # -- execution mesh ----------------------------------------------------
    def mesh_and_kernels(self):
        """The set's sub-mesh + jitted collective kernels, built lazily.

        Single-process: the member replicas' devices.  Multi-process:
        one device per member process (the lowest-id local device, the
        same convention as the global process mesh).
        """
        if self._mesh_kernels is None:
            import jax

            from . import collective as C

            st = _state.global_state()
            if st.multiprocess:
                by_proc: Dict[int, object] = {}
                for d in jax.devices():
                    if (d.process_index not in by_proc
                            or d.id < by_proc[d.process_index].id):
                        by_proc[d.process_index] = d
                devs = [by_proc[p] for p in self.ranks]
            else:
                devs = [st.devices[r] for r in self.ranks]
            # Cached by device tuple: identical subsets share the ~20
            # jitted kernels instead of recompiling per ProcessSet.
            self._mesh_kernels = C._subset_kernels(tuple(devs))
        return self._mesh_kernels

    def close(self) -> None:
        if self.coordinator is not None:
            self.coordinator.close()
            self.coordinator = None
        self._mesh_kernels = None

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return (f"ProcessSet(id={self.process_set_id}, "
                f"ranks={list(self.ranks)})")
