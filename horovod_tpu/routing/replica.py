"""Replica clients: how the router speaks to one serving replica.

The wire contract is exactly what hvd-serve already exports — no new
replica-side protocol: ``GET /healthz`` (readiness + queue depth + KV
headroom + the prefix index, ``serving/engine.py health()``), ``POST
/generate`` (the front door), and the fleet hooks ``POST /drain`` /
``POST /resume`` / ``GET /prefixes`` (``serving/server.py``).  A client
returns ``(status, payload)`` for every call and raises
:class:`ReplicaUnreachable` ONLY for transport-level failures
(connection refused/reset, timeout) — an HTTP error status is a
*reachable* replica saying no, and the router treats the two very
differently (failover-and-retry vs mark-dead-and-backoff).

Anything that implements this four-method surface can sit behind the
router: :class:`HttpReplicaClient` for real fleets, the simulated
replicas of ``bench.py --mode routing``, and the in-memory fakes of
tests/test_routing.py.
"""

from __future__ import annotations

import json
import urllib.error
import urllib.request
from typing import Optional, Tuple


class ReplicaUnreachable(Exception):
    """Transport-level failure talking to a replica (dead process,
    refused/reset connection, timeout) — the router's mark-dead
    signal, as opposed to an HTTP error status from a live one."""


class HttpReplicaClient:
    """urllib-based client for one replica's exporter endpoint.

    Stateless (one request per call, no pooled sockets), so a replica
    death can never wedge the client beyond the current call's
    timeout."""

    def __init__(self, host: str, port: int,
                 timeout: float = 120.0) -> None:
        self.host = host
        self.port = int(port)
        self.timeout = float(timeout)
        self._base = f"http://{host}:{int(port)}"

    def _call(self, method: str, path: str,
              payload: Optional[dict] = None,
              timeout: Optional[float] = None) -> Tuple[int, dict]:
        body = None if payload is None else json.dumps(payload).encode()
        req = urllib.request.Request(self._base + path, data=body,
                                     method=method)
        if body is not None:
            req.add_header("Content-Type", "application/json")
        try:
            with urllib.request.urlopen(
                    req, timeout=self.timeout if timeout is None
                    else float(timeout)) as resp:
                raw = resp.read()
                status = resp.status
        except urllib.error.HTTPError as e:
            # A status the server chose (503 draining, 400, 500): the
            # replica is alive — hand the body to the router's policy.
            raw = e.read()
            status = e.code
        except (urllib.error.URLError, ConnectionError, OSError,
                TimeoutError) as e:
            raise ReplicaUnreachable(
                f"{self._base}{path}: {type(e).__name__}: {e}") from e
        try:
            parsed = json.loads(raw.decode() or "{}")
        except ValueError:
            parsed = {"raw": raw.decode(errors="replace")}
        if not isinstance(parsed, dict):
            parsed = {"payload": parsed}
        return status, parsed

    # -- the replica surface ----------------------------------------------
    def health(self) -> Tuple[int, dict]:
        """``GET /healthz`` — (status, payload); 200 means ready, 503
        carries the same payload with ``status: NOT_READY``."""
        return self._call("GET", "/healthz", timeout=5.0)

    def generate(self, payload: dict,
                 timeout: Optional[float] = None) -> Tuple[int, dict]:
        """``POST /generate`` — blocks for the completion (or the
        replica's own failure status)."""
        return self._call("POST", "/generate", payload, timeout=timeout)

    def drain(self) -> Tuple[int, dict]:
        """``POST /drain`` — stop admission, evict in-flight work as
        continuations; the payload is the elastic export (requests +
        prefix index) the caller resubmits/seeds elsewhere."""
        return self._call("POST", "/drain", {})

    def resume(self, payload: dict) -> Tuple[int, dict]:
        """``POST /resume`` — install a drained export (continuations
        resubmitted, prefix chains ghost-seeded) into this replica."""
        return self._call("POST", "/resume", payload)

    def prefixes(self) -> Tuple[int, dict]:
        """``GET /prefixes`` — the live prefix index as token chains
        (the autoscale boot-seed source; no drain required)."""
        return self._call("GET", "/prefixes", timeout=10.0)
