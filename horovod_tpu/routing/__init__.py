"""hvd-route: the pure-Python router tier over N serving replicas.

Least-loaded + prefix-affinity dispatch, drain-aware failover, and
fleet autoscaling — all over the HTTP contract the serving tier
already exports (``/healthz``, ``/generate``, and the fleet hooks
``/drain``/``/resume``/``/prefixes``).  No jax anywhere in this
package: like the scheduler, the router runs on any front-end box.
See docs/routing.md.
"""

from .affinity import (chain_hashes, prompt_header_hashes,
                       published_page_hashes)
from .autoscale import AutoscaleConfig, FleetAutoscaler
from .replica import HttpReplicaClient, ReplicaUnreachable
from .router import Router, RouterConfig
from .server import RouterServer

__all__ = [
    "AutoscaleConfig",
    "FleetAutoscaler",
    "HttpReplicaClient",
    "ReplicaUnreachable",
    "Router",
    "RouterConfig",
    "RouterServer",
    "chain_hashes",
    "prompt_header_hashes",
    "published_page_hashes",
]
