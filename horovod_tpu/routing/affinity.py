"""The ONE prompt-header chain-hash scheme, shared router ↔ replica.

Prefix-affinity routing only works if the router derives EXACTLY the
keys the replica's shared-prefix index holds: the page-aligned chain
hash of ``serving/kv_cache.py``.  A silent scheme divergence (different
dtype, different page alignment, a missing fingerprint seed) would not
error — it would quietly zero the affinity hit rate while the router
believes it is routing warm.  So the scheme lives HERE, in the jax-free
routing tier, and :meth:`~horovod_tpu.serving.kv_cache.PagedKVCache.
_chain_hashes` delegates to it — byte-identical by construction, and
CI-gated by tests/test_routing.py against a live cache.

The scheme: ``h = sha256(fingerprint)``, then per page ``j`` the hash
absorbs that page's token ids as little-endian int32 bytes and emits
its digest — ``h_j`` commits to the model fingerprint AND every token
of pages ``0..j``, so a hit on page ``j`` implies the whole prefix
matches with no token comparison.  ``fingerprint`` is the engine's
model-identity JSON (``serving/engine.py _model_dict``, sorted keys),
exported verbatim in ``/healthz`` so the router self-configures from
the replicas it fronts.
"""

from __future__ import annotations

import hashlib
from typing import List, Sequence

import numpy as np


def chain_hashes(fingerprint: bytes, tokens: Sequence[int],
                 page_size: int, n_pages: int) -> List[bytes]:
    """Chain hash per page boundary over ``tokens[:n_pages *
    page_size]`` — the index keys of ``PagedKVCache`` (which delegates
    its ``_chain_hashes`` here)."""
    h = hashlib.sha256(fingerprint)
    out: List[bytes] = []
    ps = int(page_size)
    for j in range(n_pages):
        h.update(np.asarray(tokens[j * ps:(j + 1) * ps],
                            np.int32).tobytes())
        out.append(h.digest())
    return out


def prompt_header_hashes(fingerprint: bytes, tokens: Sequence[int],
                         page_size: int,
                         pages_per_slot: int) -> List[str]:
    """Hex chain hashes of a prompt's page-aligned STRICT-prefix header
    — the router-side mirror of ``PagedKVCache.lookup_prefix``'s key
    sequence (same ``min((len - 1) // page_size, pages_per_slot)``
    bound: at least one suffix token always remains for the replica to
    prefill)."""
    if not tokens:
        return []
    max_pages = min((len(tokens) - 1) // int(page_size),
                    int(pages_per_slot))
    if max_pages <= 0:
        return []
    return [d.hex() for d in chain_hashes(fingerprint, tokens,
                                          page_size, max_pages)]


def published_page_hashes(fingerprint: bytes, tokens: Sequence[int],
                          page_size: int,
                          pages_per_slot: int) -> List[str]:
    """Hex chain hashes of the pages a replica PUBLISHES after fully
    prefilling ``tokens`` (``PagedKVCache.publish_prefix``'s key set:
    every page entirely covered by the prompt, NOT the strict-prefix
    bound) — what the router adds to its model of a replica's index
    after a completed dispatch."""
    n_full = min(len(tokens) // int(page_size), int(pages_per_slot))
    if n_full <= 0:
        return []
    return [d.hex() for d in chain_hashes(fingerprint, tokens,
                                          page_size, n_full)]
