"""hvd-route: least-loaded + prefix-affinity dispatch over N replicas.

Pure Python (no jax — like the scheduler, this tier runs on any
front-end box).  The router keeps one :class:`_Replica` record per
serving replica, refreshed from the ``/healthz`` contract the serving
tier already exports (``serving/engine.py health()``): readiness,
``queue_depth``, the ``kv_free_pages`` admission headroom, and the
shared-prefix index as chain-hash hex digests.  Dispatch then scores
every READY replica:

    score = (queue_depth + router_inflight) * queue_weight
            - affinity_pages * affinity_weight
            + headroom_penalty

where ``affinity_pages`` is the longest page-aligned header run of the
prompt already present in that replica's prefix index (the SAME chain
hashes the replica's ``PagedKVCache`` keys — affinity.py), and the
penalty applies when the replica lacks KV headroom for the prompt's
unshared pages.  Lowest score wins; ties break on replica name, so a
given fleet snapshot always routes a prompt the same way
(deterministic — the trace-replay gate of ``bench.py --mode routing``
relies on it).

Failover is drain-aware (docs/routing.md): a replica that answers 503
mid-generation was elastically drained — its partial tokens are a
CONTINUATION (the serving bitwise contract makes prompt+partial
reproduce the uninterrupted rollout), so the router extends the prompt
with them, debits ``max_tokens``, and resubmits elsewhere; the merged
completion is digest-identical to an uninterrupted run (chaos-gated:
``router_replica_death``).  A replica that is UNREACHABLE (connection
refused/reset — :class:`~horovod_tpu.routing.replica.
ReplicaUnreachable`) is marked dead and re-probed on the shared
jittered-backoff policy (utils/retry.py), the same machinery the
control-plane reconnect path rides.
"""

from __future__ import annotations

import random
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from .. import telemetry as _telemetry
from ..analysis import lockorder as _lockorder
from ..analysis import races as _races
from ..telemetry import flight as _flight
from ..utils.retry import BackoffPolicy
from .affinity import prompt_header_hashes, published_page_hashes
from .replica import ReplicaUnreachable

# Replica dispositions.  Only READY replicas are dispatch candidates;
# DRAINING and DEAD differ in how they got there (an explicit
# drain/503 vs a transport failure) and in re-probe backoff (dead
# replicas are probed on the jittered schedule, draining ones on every
# poll — a resumed replica should take traffic again promptly).
READY = "ready"
NOT_READY = "not_ready"
DRAINING = "draining"
DEAD = "dead"

_M_REQS = _telemetry.counter(
    "routing.requests", "requests dispatched through the router")
_M_AFF_HITS = _telemetry.counter(
    "routing.affinity_hits", "requests routed to a replica already "
    "holding at least one page of their prompt header")
_M_AFF_PAGES = _telemetry.counter(
    "routing.affinity_pages", "prompt-header pages routed onto a "
    "replica that already cached them (fleet-wide prefix reuse)")
_M_FAILOVERS = _telemetry.counter(
    "routing.failovers", "dispatch attempts moved to another replica "
    "(503-draining or unreachable)")
_M_CONTINUATIONS = _telemetry.counter(
    "routing.continuations", "drained replicas' partial completions "
    "resubmitted as continuations")
_M_DEATHS = _telemetry.counter(
    "routing.replica_deaths", "replicas marked dead after a "
    "transport-level failure")
_M_NO_REPLICA = _telemetry.counter(
    "routing.no_replica_errors", "requests failed because no replica "
    "was ready within the retry budget")
_M_READY = _telemetry.gauge(
    "routing.ready_replicas", "replicas currently dispatchable")


@dataclass(frozen=True)
class RouterConfig:
    queue_weight: float = 1.0      # score per queued/in-flight request
    affinity_weight: float = 1.0   # score credit per warm header page
    headroom_penalty: float = 1e6  # replica cannot hold the prompt
    max_attempts: int = 4          # dispatch tries across the fleet
    index_cap: int = 4096          # per-replica affinity-index bound
    probe_base: float = 0.05       # dead-replica re-probe backoff
    probe_cap: float = 2.0


class _Replica:
    """One replica's routing state.  Every field is guarded by the
    owning :class:`Router`'s ``_lock`` (the record never leaves it);
    the client object itself is only CALLED outside the lock."""

    def __init__(self, name: str, client) -> None:
        self.name = name
        self.client = client
        self.status = NOT_READY
        self.queue_depth = 0
        self.kv_free_pages = 0
        self.kv_total_pages = 0
        self.inflight = 0            # router-side dispatched, unanswered
        self.prefix: set = set()     # chain-hash hex digests
        self.fingerprint = b""
        self.page_size = 0
        self.pages_per_slot = 0
        self.failures = 0            # consecutive transport failures
        self.next_probe = 0.0        # monotonic; dead-replica backoff
        self.backoff = BackoffPolicy(rng=random.Random(
            hash(name) & 0xFFFF))


@_races.race_checked
class Router:
    """The fleet dispatcher.  Thread-safe: ``dispatch`` runs
    concurrently on the front door's per-request handler threads, and
    ``poll`` on the router server's poll thread — all shared state
    lives behind ``_lock``, and every replica CALL (health, generate,
    drain) happens outside it, so one slow replica never wedges
    routing to the others."""

    def __init__(self, cfg: Optional[RouterConfig] = None,
                 clock=time.monotonic, sleep=time.sleep) -> None:
        self.cfg = cfg or RouterConfig()
        self._clock = clock
        self._sleep = sleep
        self._lock = _lockorder.make_lock("routing.Router._lock")
        self._replicas: Dict[str, _Replica] = {}  # guarded_by: _lock
        # Fleet affinity config, adopted from the first replica whose
        # health exports a fingerprint; a replica advertising a
        # DIFFERENT fingerprint serves another model — it still takes
        # least-loaded traffic but never earns affinity credit.
        self._fingerprint = b""    # guarded_by: _lock
        self._page_size = 0        # guarded_by: _lock
        self._pages_per_slot = 0   # guarded_by: _lock

    # -- fleet membership --------------------------------------------------
    def add_replica(self, name: str, client) -> None:
        """Register a replica (NOT_READY until its first health poll;
        re-registration replaces the record — the relaunch path)."""
        with self._lock:
            self._replicas[name] = _Replica(name, client)

    def remove_replica(self, name: str) -> None:
        with self._lock:
            self._replicas.pop(name, None)

    def replica_names(self) -> List[str]:
        with self._lock:
            return sorted(self._replicas)

    def replica_status(self) -> Dict[str, dict]:
        """Snapshot for /healthz and tests: per-replica disposition,
        load and affinity-index size."""
        with self._lock:
            return {r.name: {
                "status": r.status,
                "queue_depth": r.queue_depth,
                "inflight": r.inflight,
                "kv_free_pages": r.kv_free_pages,
                "prefix_index_pages": len(r.prefix),
            } for r in self._replicas.values()}

    def ready_count(self) -> int:
        with self._lock:
            return sum(1 for r in self._replicas.values()
                       if r.status == READY)

    # -- health polling ----------------------------------------------------
    def poll(self, name: Optional[str] = None) -> None:
        """Refresh routing state from ``/healthz``.  Dead replicas are
        only re-probed once their jittered backoff expires (the
        thundering-herd discipline of utils/retry.py); everything else
        is probed every call."""
        now = self._clock()
        with self._lock:
            due = [r for r in self._replicas.values()
                   if (name is None or r.name == name)
                   and (r.status != DEAD or now >= r.next_probe)]
            targets = [(r.name, r.client) for r in due]
        for rep_name, client in targets:
            try:
                status, payload = client.health()
            except ReplicaUnreachable:
                self._mark_dead(rep_name)
                continue
            except Exception as e:  # noqa: BLE001 — a broken client
                # must degrade to "dead", never kill the poll thread
                _flight.record("route_poll_error", rep_name,
                               f"{type(e).__name__}: {e}")
                self._mark_dead(rep_name)
                continue
            self._apply_health(rep_name, status, payload)
        with self._lock:
            _M_READY.set(sum(1 for r in self._replicas.values()
                             if r.status == READY))

    def _apply_health(self, name: str, status: int,
                      payload: dict) -> None:
        # The exporter nests the engine's contribution under the
        # "serving" health key; simulated/faked replicas may hand the
        # detail dict back directly.
        det = payload.get("serving")
        if not isinstance(det, dict):
            det = payload
        fp = str(det.get("fingerprint") or "").encode()
        with self._lock:
            rep = self._replicas.get(name)
            if rep is None:
                return
            rep.failures = 0
            rep.status = READY if (status == 200
                                   and det.get("ready")) else NOT_READY
            rep.queue_depth = int(det.get("queue_depth", 0) or 0)
            rep.kv_free_pages = int(det.get("kv_free_pages", 0) or 0)
            rep.kv_total_pages = int(det.get("kv_total_pages", 0) or 0)
            rep.page_size = int(det.get("page_size", 0) or 0)
            rep.pages_per_slot = int(det.get("pages_per_slot", 0) or 0)
            rep.fingerprint = fp
            index = det.get("prefix_index")
            if isinstance(index, (list, tuple)):
                rep.prefix = set(str(h) for h in index)
            if fp and not self._fingerprint:
                self._fingerprint = fp
                self._page_size = rep.page_size
                self._pages_per_slot = rep.pages_per_slot

    def _mark_dead(self, name: str) -> None:
        now = self._clock()
        with self._lock:
            rep = self._replicas.get(name)
            if rep is None:
                return
            if rep.status != DEAD:
                _M_DEATHS.inc()
                _flight.record("route_replica_dead", name,
                               f"failures={rep.failures + 1}")
            rep.status = DEAD
            rep.failures += 1
            rep.next_probe = now + rep.backoff.delay(rep.failures - 1)

    def _mark_draining(self, name: str) -> None:
        with self._lock:
            rep = self._replicas.get(name)
            if rep is not None and rep.status != DEAD:
                rep.status = DRAINING

    # -- selection ---------------------------------------------------------
    def _header_hashes(self, tokens: List[int]) -> List[str]:
        with self._lock:
            fp, ps, pps = (self._fingerprint, self._page_size,
                           self._pages_per_slot)
        if not fp or ps <= 0 or pps <= 0:
            return []
        return prompt_header_hashes(fp, tokens, ps, pps)

    def select(self, tokens: List[int]) -> Optional[Tuple[str, int]]:
        """(replica_name, affinity_pages) for the best READY replica,
        or None when the fleet has none.  Pure in the fleet snapshot:
        no state moves here (``dispatch`` owns the inflight
        accounting), so benches and tests can call it freely."""
        header = self._header_hashes(tokens)
        cfg = self.cfg
        with self._lock:
            fleet_fp = self._fingerprint
            best: Optional[Tuple[float, str, int]] = None
            for name in sorted(self._replicas):
                rep = self._replicas[name]
                if rep.status != READY:
                    continue
                affinity = 0
                if header and rep.fingerprint == fleet_fp:
                    for h in header:
                        if h not in rep.prefix:
                            break
                        affinity += 1
                score = ((rep.queue_depth + rep.inflight)
                         * cfg.queue_weight
                         - affinity * cfg.affinity_weight)
                if rep.page_size > 0:
                    needed = (-(-len(tokens) // rep.page_size)
                              - affinity)
                    if needed > rep.kv_free_pages:
                        score += cfg.headroom_penalty
                if best is None or score < best[0]:
                    best = (score, name, affinity)
        if best is None:
            return None
        return best[1], best[2]

    # -- dispatch accounting ----------------------------------------------
    def _acquire(self, name: str) -> None:
        with self._lock:
            rep = self._replicas.get(name)
            if rep is not None:
                rep.inflight += 1

    def _release(self, name: str) -> None:
        with self._lock:
            rep = self._replicas.get(name)
            if rep is not None and rep.inflight > 0:
                rep.inflight -= 1

    def _client(self, name: str):
        with self._lock:
            rep = self._replicas.get(name)
            return None if rep is None else rep.client

    def _note_published(self, name: str, prompt: List[int]) -> None:
        """Optimistic index update after a 200: the replica published
        this prompt's full pages (``publish_prefix``), so credit them
        before the next health poll arrives — back-to-back shared
        headers route warm immediately."""
        with self._lock:
            rep = self._replicas.get(name)
            if rep is None or len(rep.prefix) >= self.cfg.index_cap:
                return
            fp, ps, pps = (self._fingerprint, self._page_size,
                           self._pages_per_slot)
            if not fp or rep.fingerprint != fp or ps <= 0:
                return
        for h in published_page_hashes(fp, prompt, ps, pps):
            with self._lock:
                rep = self._replicas.get(name)
                if rep is None:
                    return
                rep.prefix.add(h)

    # -- the failover dispatch loop ---------------------------------------
    def dispatch(self, payload: dict,
                 timeout: Optional[float] = None) -> Tuple[int, dict]:
        """Route one /generate request, surviving drains and deaths.

        Returns ``(status, response)``.  200 responses carry the FULL
        token list (continuation partials merged back in) plus a
        ``router`` stamp naming the serving replica, the affinity page
        count of the first routing, and how many failovers/continuation
        resubmits it took.  400/500/504 from a live replica pass
        through (they are not retryable: malformed input, a poisoned
        engine's partials, the client's own deadline).  503 is
        returned only when the retry budget exhausts with no ready
        replica."""
        tokens = payload.get("tokens")
        if not tokens:
            return 400, {"error": "router dispatch needs token ids "
                                  "(text encoding is replica-side)"}
        prompt = [int(t) for t in tokens]
        remaining = int(payload.get("max_tokens", 32))
        collected: List[int] = []
        failovers = 0
        resubmits = 0
        first_affinity: Optional[int] = None
        _M_REQS.inc()
        for attempt in range(self.cfg.max_attempts):
            pick = self.select(prompt)
            if pick is None:
                # Force a refresh (a drained replica may have resumed,
                # a dead one's backoff may have expired) and give the
                # fleet one jittered beat before burning the attempt.
                self.poll()
                pick = self.select(prompt)
            if pick is None:
                if attempt + 1 < self.cfg.max_attempts:
                    self._sleep(self.cfg.probe_base * (attempt + 1))
                continue
            name, affinity = pick
            if first_affinity is None:
                first_affinity = affinity
                if affinity > 0:
                    _M_AFF_HITS.inc()
                    _M_AFF_PAGES.inc(affinity)
            client = self._client(name)
            if client is None:
                continue
            body = dict(payload)
            body["tokens"] = prompt
            body["max_tokens"] = remaining
            self._acquire(name)
            try:
                status, resp = client.generate(body, timeout=timeout)
            except ReplicaUnreachable:
                self._mark_dead(name)
                failovers += 1
                _M_FAILOVERS.inc()
                _flight.record("route_failover", name, "unreachable")
                continue
            finally:
                self._release(name)
            if status == 200:
                self._note_published(name, prompt)
                out = dict(resp)
                out["tokens"] = collected + list(resp.get("tokens")
                                                 or [])
                if collected:
                    # The replica's text/latency fields describe only
                    # the final leg — drop what no longer matches the
                    # merged completion.
                    out.pop("text", None)
                out["router"] = {"replica": name,
                                 "affinity_pages": first_affinity or 0,
                                 "failovers": failovers,
                                 "resubmits": resubmits}
                return 200, out
            if status == 503:
                # Drained mid-flight (or refusing admission while
                # draining): partial tokens become a continuation —
                # the bitwise contract reproduces the rest anywhere.
                partial = [int(t) for t in resp.get("tokens") or []]
                if partial:
                    collected += partial
                    prompt = prompt + partial
                    remaining -= len(partial)
                    resubmits += 1
                    _M_CONTINUATIONS.inc()
                self._mark_draining(name)
                failovers += 1
                _M_FAILOVERS.inc()
                _flight.record("route_failover", name,
                               f"draining partial={len(partial)}")
                if remaining <= 0:
                    return 200, {"tokens": collected,
                                 "finish_reason": "length",
                                 "router": {
                                     "replica": name,
                                     "affinity_pages":
                                         first_affinity or 0,
                                     "failovers": failovers,
                                     "resubmits": resubmits}}
                continue
            out = dict(resp)
            out["router"] = {"replica": name,
                             "affinity_pages": first_affinity or 0,
                             "failovers": failovers,
                             "resubmits": resubmits}
            return status, out
        _M_NO_REPLICA.inc()
        return 503, {"error": "no ready replica within the retry "
                              "budget", "failovers": failovers,
                     "partial_tokens": collected}

    # -- fleet scale-down --------------------------------------------------
    def drain_replica(self, name: str) -> Optional[dict]:
        """Drain one replica for scale-down: ``POST /drain`` exports
        its queued/in-flight work as continuations plus its prefix
        index, and the replica stops taking traffic (NOT_READY).
        Returns the export payload (``{"requests": [...], "prefixes":
        [...]}``), or None when the replica was already gone."""
        client = self._client(name)
        if client is None:
            return None
        self._mark_draining(name)
        try:
            status, payload = client.drain()
        except ReplicaUnreachable:
            self._mark_dead(name)
            return None
        if status != 200:
            _flight.record("route_drain_failed", name, f"http={status}")
            return None
        return payload
