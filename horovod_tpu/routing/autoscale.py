"""hvd-route autoscaling: grow/shrink the replica fleet from load.

The autoscaler is a policy loop over the router's fleet snapshot, in
the same shape as hvd-tune's PolicyEngine: windowed observation →
hysteresis (``sustain`` consecutive ticks over threshold) → cooldown
after every action → a PLANNER VETO before anything irreversible.  It
never touches a replica directly — scale-up goes through an injected
``launch`` hook (subprocess, k8s pod, sim replica — the autoscaler
does not care) and the elastic seed path, scale-down through the
router's drain path:

* **up**: ``launch`` boots the replica, then its KV cache is warmed by
  ghost-seeding a donor replica's live prefix index (``GET /prefixes``
  → ``POST /resume``), so the newcomer starts with the fleet's hottest
  headers already cached instead of a cold TTFT cliff.  Before boot,
  the hvd-mem planner prices the replica's footprint against host
  headroom — a fleet that would OOM is a veto, not a crash.
* **down**: the router drains the victim (``POST /drain``); its
  in-flight HTTP requests come back 503-with-partials and the router's
  dispatch loop resubmits them as continuations (request continuity is
  NOT the autoscaler's job — see docs/routing.md), while the exported
  prefix index is donated to the least-loaded survivor so the fleet
  keeps the warm pages.

Deliberately clock-free: ``observe()`` is a pure tick, driven by the
router server's poll thread in production and called directly by
bench/tests — hysteresis and cooldown count ticks, not seconds.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, List, Optional

from .. import telemetry as _telemetry
from ..analysis import lockorder as _lockorder
from ..analysis import races as _races
from ..telemetry import flight as _flight
from .replica import ReplicaUnreachable

_M_UPS = _telemetry.counter(
    "routing.scale_ups", "replicas booted by the autoscaler")
_M_DOWNS = _telemetry.counter(
    "routing.scale_downs", "replicas drained away by the autoscaler")
_M_VETOES = _telemetry.counter(
    "routing.scale_vetoes", "scale-ups refused by the planner price "
    "check (insufficient host headroom)")
_M_FLEET = _telemetry.gauge(
    "routing.fleet_size", "replicas currently registered")


@dataclass(frozen=True)
class AutoscaleConfig:
    min_replicas: int = 1
    max_replicas: int = 8
    # Mean (queue_depth + router inflight) per READY replica.  Above
    # ``up_load`` the fleet is saturating (requests wait); below
    # ``down_load`` it idles.  The dead band between them is the
    # hysteresis that stops flapping on noisy traffic.
    up_load: float = 8.0
    down_load: float = 1.0
    sustain: int = 3       # consecutive ticks over threshold to act
    cooldown: int = 10     # ticks of enforced quiet after any action
    seed_prefix_limit: int = 256  # chains donated to a booting replica


@_races.race_checked
class FleetAutoscaler:
    """Tick-driven fleet sizing over a :class:`~horovod_tpu.routing.
    router.Router`.

    ``launch(name) -> client`` boots a replica and returns its client;
    ``retire(name)`` reclaims one the autoscaler booted.  ``price() ->
    bytes`` and ``headroom() -> bytes`` are the planner hooks: price
    is the hvd-mem plan's footprint for one replica (weights + KV pool
    + prefix reserve), headroom what the host still has — price >
    headroom vetoes the boot."""

    def __init__(self, router, launch: Callable[[str], object],
                 retire: Callable[[str], None],
                 cfg: Optional[AutoscaleConfig] = None,
                 price: Optional[Callable[[], int]] = None,
                 headroom: Optional[Callable[[], int]] = None) -> None:
        self.router = router
        self.cfg = cfg or AutoscaleConfig()
        self._launch = launch
        self._retire = retire
        self._price = price
        self._headroom = headroom
        self._lock = _lockorder.make_lock(
            "routing.FleetAutoscaler._lock")
        self._sustain_up = 0    # guarded_by: _lock
        self._sustain_down = 0  # guarded_by: _lock
        self._cooldown = 0      # guarded_by: _lock
        self._seq = 0           # guarded_by: _lock
        self._launched: List[str] = []  # guarded_by: _lock

    # -- observation -------------------------------------------------------
    def _fleet_load(self):
        status = self.router.replica_status()
        ready = [s for s in status.values() if s["status"] == "ready"]
        if not ready:
            return len(status), 0, 0.0
        load = sum(s["queue_depth"] + s["inflight"] for s in ready)
        return len(status), len(ready), load / len(ready)

    def observe(self) -> Optional[str]:
        """One autoscaling tick.  Returns what happened — ``"up:NAME"``,
        ``"down:NAME"``, ``"veto:up"`` or None — so benches and tests
        can assert the decision, not just its side effects."""
        total, ready, mean_load = self._fleet_load()
        _M_FLEET.set(total)
        cfg = self.cfg
        with self._lock:
            if self._cooldown > 0:
                self._cooldown -= 1
                self._sustain_up = 0
                self._sustain_down = 0
                return None
            want_up = (mean_load > cfg.up_load
                       and total < cfg.max_replicas)
            # Scale-down needs every registered replica healthy AND
            # idle — a dead replica mid-failover is not "overcapacity".
            want_down = (ready == total and total > cfg.min_replicas
                         and mean_load < cfg.down_load)
            self._sustain_up = self._sustain_up + 1 if want_up else 0
            self._sustain_down = (self._sustain_down + 1
                                  if want_down else 0)
            fire_up = self._sustain_up >= cfg.sustain
            fire_down = self._sustain_down >= cfg.sustain
            if fire_up or fire_down:
                self._sustain_up = 0
                self._sustain_down = 0
                self._cooldown = cfg.cooldown
        if fire_up:
            return self._scale_up(mean_load)
        if fire_down:
            return self._scale_down(mean_load)
        return None

    # -- actions -----------------------------------------------------------
    def _scale_up(self, mean_load: float) -> Optional[str]:
        if self._price is not None and self._headroom is not None:
            need, have = int(self._price()), int(self._headroom())
            if need > have:
                _M_VETOES.inc()
                _flight.record(
                    "route_scale_veto", "up",
                    f"price={need} headroom={have} load={mean_load:.1f}")
                return "veto:up"
        with self._lock:
            self._seq += 1
            name = f"auto{self._seq}"
        client = self._launch(name)
        self._seed_prefixes(client)
        self.router.add_replica(name, client)
        self.router.poll(name)
        with self._lock:
            self._launched.append(name)
        _M_UPS.inc()
        _flight.record("route_scale_up", name,
                       f"load={mean_load:.1f}")
        return f"up:{name}"

    def _seed_prefixes(self, client) -> None:
        """Warm a booting replica from the busiest survivor's live
        index — ghost-seeded via the elastic /resume path, so the
        newcomer's first affinity-routed requests hit instead of
        recomputing the fleet's hottest headers."""
        donor = self._donor_name()
        if donor is None:
            return
        donor_client = self.router._client(donor)
        if donor_client is None:
            return
        try:
            status, payload = donor_client.prefixes()
            if status != 200:
                return
            chains = list(payload.get("prefixes") or [])
            if not chains:
                return
            client.resume({"requests": [],
                           "prefixes":
                               chains[:self.cfg.seed_prefix_limit]})
        except ReplicaUnreachable:
            return

    def _donor_name(self) -> Optional[str]:
        status = self.router.replica_status()
        best = None
        for name, s in sorted(status.items()):
            if s["status"] != "ready":
                continue
            if best is None or (s["prefix_index_pages"]
                                > status[best]["prefix_index_pages"]):
                best = name
        return best

    def _scale_down(self, mean_load: float) -> Optional[str]:
        victim = self._victim_name()
        if victim is None:
            return None
        exported = self.router.drain_replica(victim)
        if exported is not None:
            self._donate_prefixes(victim, exported)
        self.router.remove_replica(victim)
        with self._lock:
            if victim in self._launched:
                self._launched.remove(victim)
        self._retire(victim)
        _M_DOWNS.inc()
        _flight.record("route_scale_down", victim,
                       f"load={mean_load:.1f}")
        return f"down:{victim}"

    def _victim_name(self) -> Optional[str]:
        """Least-loaded ready replica, preferring ones this autoscaler
        booted (the hand-provisioned core fleet is retired last)."""
        status = self.router.replica_status()
        with self._lock:
            launched = set(self._launched)
        best = None
        best_key = None
        for name, s in sorted(status.items()):
            if s["status"] != "ready":
                continue
            key = (0 if name in launched else 1,
                   s["queue_depth"] + s["inflight"])
            if best_key is None or key < best_key:
                best, best_key = name, key
        return best

    def _donate_prefixes(self, victim: str, exported: dict) -> None:
        chains = list(exported.get("prefixes") or [])
        if not chains:
            return
        status = self.router.replica_status()
        for name, s in sorted(status.items(),
                              key=lambda kv: (
                                  kv[1]["queue_depth"]
                                  + kv[1]["inflight"], kv[0])):
            if name == victim or s["status"] != "ready":
                continue
            client = self.router._client(name)
            if client is None:
                continue
            try:
                client.resume({
                    "requests": [],
                    "prefixes": chains[:self.cfg.seed_prefix_limit]})
            except ReplicaUnreachable:
                continue
            return
