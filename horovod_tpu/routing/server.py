"""The router's own HTTP front door.

A :class:`RouterServer` binds a PRIVATE
:class:`~horovod_tpu.telemetry.exporter.RouteRegistry` (the exporter's
``routes=`` escape hatch) — the process-global registry belongs to a
colocated serving replica's ``/generate``, and the router tier must be
able to front one on the same box without fighting it for the path.
The server exposes:

  POST /generate   the fleet front door — same request JSON as a
                   replica's (``tokens`` or ``text``), answered with
                   the replica's completion plus a ``router`` stamp
                   (which replica, affinity pages, failovers)
  GET  /healthz    the exporter contract, with a ``routing``
                   contributor: ready iff at least one replica is
                   dispatchable, payload carries the per-replica fleet
                   snapshot
  GET  /metrics    the usual registry exposition (``routing.*``
                   counters live next to everything else)

A poll thread refreshes replica health every ``poll_interval`` seconds
and, when an autoscaler is attached, gives it one ``observe()`` tick
per cycle — autoscaling shares the poll cadence by construction, so
its hysteresis counts are in units an operator can reason about.
"""

from __future__ import annotations

import json
import threading
from typing import Optional, Tuple

from .. import telemetry as _telemetry
from ..analysis import threads as _athreads
from ..telemetry import exporter as _exporter

HEALTH_KEY = "routing"
GENERATE_PATH = "/generate"


class RouterServer:
    """HTTP front door + poll loop over a
    :class:`~horovod_tpu.routing.router.Router`."""

    def __init__(self, router, port: int = 0,
                 host: str = "127.0.0.1",
                 poll_interval: float = 0.5,
                 autoscaler=None) -> None:
        self.router = router
        self.autoscaler = autoscaler
        self._poll_interval = float(poll_interval)
        self._routes = _exporter.RouteRegistry()
        self._routes.register_health(HEALTH_KEY, self._health)
        self._routes.register(GENERATE_PATH, self._handle_generate,
                              methods=("POST",))
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._exporter = _exporter.start_exporter(
            _telemetry.registry(), port, host=host,
            routes=self._routes)

    @property
    def port(self) -> int:
        return self._exporter.port

    def start(self) -> "RouterServer":
        self.router.poll()
        self._thread = threading.Thread(
            target=self._poll_loop, name="hvd-route-poll", daemon=True)
        self._thread.start()
        return self

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
        self._exporter.close()

    def __enter__(self) -> "RouterServer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def _poll_loop(self) -> None:  # thread: route-poll
        _athreads.set_role("route-poll")
        while not self._stop.wait(self._poll_interval):
            try:
                self.router.poll()
                if self.autoscaler is not None:
                    self.autoscaler.observe()
            except Exception as e:  # noqa: BLE001 — one bad poll
                # cycle (a replica mid-death, a raced removal) must
                # not kill the thread that notices recoveries
                _telemetry.exception_event(
                    "route-poll", f"{type(e).__name__}: {e}")

    def _health(self) -> Tuple[bool, dict]:
        status = self.router.replica_status()
        ready = sum(1 for s in status.values()
                    if s["status"] == "ready")
        return ready > 0, {"ready_replicas": ready,
                           "replicas": status}

    def _handle_generate(self, query: str,
                         body: bytes) -> Tuple[int, bytes, str]:
        try:
            payload = json.loads(body.decode() or "{}")
        except ValueError:
            return (400, b'{"error": "invalid JSON"}\n',
                    "application/json")
        if not payload.get("tokens") and "text" in payload:
            # The byte tokenizer, replica-compatible by construction
            # (UTF-8 bytes as ids < 256): the router tier knows no
            # vocab, so a model that cannot serve bytes rejects the
            # ids itself with its usual 400.
            payload = dict(payload)
            payload["tokens"] = list(
                str(payload.pop("text")).encode("utf-8"))
        timeout = payload.get("timeout")
        status, resp = self.router.dispatch(
            payload, timeout=None if timeout is None
            else float(timeout))
        return (status, (json.dumps(resp) + "\n").encode(),
                "application/json")
