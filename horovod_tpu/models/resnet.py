"""ResNet family — the reference's headline benchmark workload.

The reference's benchmark story is ResNet-50/101 ImageNet throughput and
scaling (README.md:45-51, docs/benchmarks.md:22-40,
examples/keras_imagenet_resnet50.py); this module provides the TPU-native
model.  TPU-first choices:

* NHWC layout, bfloat16 activations, float32 parameters and batch-norm
  statistics — keeps conv GEMMs on the MXU at full rate.
* ResNet-v1.5 (stride-2 in the 3×3, as the reference's Keras ResNet50
  weights use) with channel counts already multiples of 128.
* No data-dependent control flow — a single static graph XLA can fuse.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Sequence, Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn

ModuleDef = Any


class BottleneckBlock(nn.Module):
    filters: int
    strides: Tuple[int, int]
    conv: ModuleDef
    norm: ModuleDef

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1))(x)
        y = nn.relu(self.norm()(y))
        y = self.conv(self.filters, (3, 3), self.strides)(y)
        y = nn.relu(self.norm()(y))
        y = self.conv(self.filters * 4, (1, 1))(y)
        # Zero-init the last BN scale: standard large-batch recipe from the
        # same Goyal et al. playbook the reference's LR-warmup callback
        # implements (keras/callbacks.py:202-259).
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters * 4, (1, 1),
                                 self.strides, name="conv_proj")(residual)
            residual = self.norm(name="norm_proj")(residual)
        return nn.relu(residual + y)


class ResNet(nn.Module):
    stage_sizes: Sequence[int]
    num_classes: int = 1000
    num_filters: int = 64
    compute_dtype: Any = jnp.bfloat16
    # MLPerf space-to-depth stem: a 3-input-channel 7x7 conv cannot fill
    # the 128-lane MXU; rearranging 2x2 pixel blocks into 12 channels and
    # convolving 4x4/s1 computes the same stage (equivalent to a
    # zero-padded 8x8/s2 conv, a superset of the 7x7) with 4x the MXU
    # input-channel occupancy.
    space_to_depth: bool = False

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(nn.Conv, use_bias=False, dtype=self.compute_dtype,
                       padding="SAME")
        norm = partial(nn.BatchNorm, use_running_average=not train,
                       momentum=0.9, epsilon=1e-5, dtype=self.compute_dtype,
                       param_dtype=jnp.float32, axis_name=None)
        x = x.astype(self.compute_dtype)
        if self.space_to_depth:
            b, h, w, c = x.shape
            if h % 2 or w % 2:
                # Pad odd extents so the 2x2 block rearrange is defined
                # (SAME-conv tolerance, matching the 7x7/s2 stem).
                x = jnp.pad(x, ((0, 0), (0, h % 2), (0, w % 2), (0, 0)))
                b, h, w, c = x.shape
            x = x.reshape(b, h // 2, 2, w // 2, 2, c)
            x = x.transpose(0, 1, 3, 2, 4, 5).reshape(
                b, h // 2, w // 2, 4 * c)
            x = conv(self.num_filters, (4, 4), (1, 1),
                     name="conv_init")(x)
        else:
            x = conv(self.num_filters, (7, 7), (2, 2),
                     name="conv_init")(x)
        x = nn.relu(norm(name="bn_init")(x))
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for i, block_count in enumerate(self.stage_sizes):
            for j in range(block_count):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                x = BottleneckBlock(self.num_filters * 2 ** i, strides,
                                    conv=conv, norm=norm)(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=self.compute_dtype,
                     name="head")(x)
        return x.astype(jnp.float32)


ResNet50 = partial(ResNet, stage_sizes=[3, 4, 6, 3])
ResNet101 = partial(ResNet, stage_sizes=[3, 4, 23, 3])
ResNet18Thin = partial(ResNet, stage_sizes=[1, 1, 1, 1], num_filters=16)


def init_resnet(model: nn.Module, image_size: int = 224,
                batch_size: int = 8, seed: int = 0):
    """Initialize params + batch_stats."""
    rng = jax.random.PRNGKey(seed)
    dummy = jnp.zeros((batch_size, image_size, image_size, 3), jnp.float32)
    variables = model.init(rng, dummy, train=False)
    return variables["params"], variables.get("batch_stats", {})


def resnet_loss_fn(model: nn.Module, weight_decay: float = 1e-4):
    """Softmax CE + L2, returning (loss, new_batch_stats) for mutable BN.

    Matches the reference ResNet-50 example's objective
    (examples/keras_imagenet_resnet50.py:118-124: categorical CE + the
    weight decay baked into its conv kernels)."""

    def loss_fn(params, batch_stats, batch):
        images, labels = batch
        logits, mutated = model.apply(
            {"params": params, "batch_stats": batch_stats}, images,
            train=True, mutable=["batch_stats"])
        logp = jax.nn.log_softmax(logits)
        onehot = jax.nn.one_hot(labels, logits.shape[-1])
        ce = -jnp.mean(jnp.sum(onehot * logp, axis=-1))
        l2 = sum(jnp.sum(p.astype(jnp.float32) ** 2)
                 for p in jax.tree_util.tree_leaves(params)
                 if p.ndim > 1)
        return ce + weight_decay * 0.5 * l2, mutated["batch_stats"]

    return loss_fn


def synthetic_imagenet(num: int, image_size: int = 224, seed: int = 0,
                       num_classes: int = 1000):
    """Synthetic ImageNet-shaped batch (the reference benchmarks use
    synthetic data too — docs/benchmarks.md:28-33 '--data_name imagenet'
    with no data dir)."""
    import numpy as np

    rng = np.random.RandomState(seed)
    images = rng.rand(num, image_size, image_size, 3).astype("float32")
    labels = rng.randint(0, num_classes, size=(num,)).astype("int32")
    return images, labels
