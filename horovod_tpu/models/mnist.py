"""MNIST models — the reference's example workload family.

The reference trains a small ConvNet on MNIST in every frontend
(examples/tensorflow_mnist.py:32-60, examples/pytorch_mnist.py:54-70,
examples/keras_mnist.py:37-48); these are the TPU-native equivalents in
flax.  Architecture follows the reference examples' shape (two conv blocks
then two dense layers) but is laid out TPU-first: NHWC, bfloat16 compute
with float32 parameters, feature sizes padded to MXU-friendly multiples.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from flax import linen as nn


class MnistCNN(nn.Module):
    """ConvNet ≙ the reference examples' conv(32)-conv(64)-fc(512)-fc(10)
    (examples/tensorflow_mnist.py:32-60).  Compute dtype bfloat16 keeps the
    MXU busy; params stay float32 for stable SGD."""

    num_classes: int = 10
    compute_dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        # x: [B, 28, 28, 1] float32 in [0, 1]
        x = x.astype(self.compute_dtype)
        x = nn.Conv(32, (5, 5), padding="SAME", dtype=self.compute_dtype)(x)
        x = nn.max_pool(nn.relu(x), (2, 2), strides=(2, 2))
        x = nn.Conv(64, (5, 5), padding="SAME", dtype=self.compute_dtype)(x)
        x = nn.max_pool(nn.relu(x), (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(512, dtype=self.compute_dtype)(x))
        x = nn.Dense(self.num_classes, dtype=self.compute_dtype)(x)
        return x.astype(jnp.float32)


class MnistMLP(nn.Module):
    """Small dense net (≙ examples/keras_mnist.py's simpler topologies);
    handy for fast tests."""

    num_classes: int = 10
    hidden: int = 128

    @nn.compact
    def __call__(self, x):
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(self.hidden)(x))
        x = nn.Dense(self.num_classes)(x)
        return x


class MnistBNMLP(nn.Module):
    """Dense net with BatchNorm — the smallest model carrying non-trained
    state (running mean/var), for the stateful training-step variants
    (synchronized BatchNorm) without a conv stack's compile cost."""

    num_classes: int = 10
    hidden: int = 64

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(self.hidden)(x)
        x = nn.BatchNorm(use_running_average=not train, momentum=0.9)(x)
        x = nn.relu(x)
        x = nn.Dense(self.num_classes)(x)
        return x


def bn_mlp_loss_fn(model: nn.Module):
    """``loss_fn(params, model_state, batch) -> (loss, new_state)`` for
    the stateful step builders."""
    def loss_fn(params, model_state, batch):
        images, labels = batch
        logits, updates = model.apply(
            {"params": params, "batch_stats": model_state}, images,
            train=True, mutable=["batch_stats"])
        return cross_entropy_loss(logits, labels), updates["batch_stats"]
    return loss_fn


def init_bn_mlp(model: nn.Module, batch_size: int = 8, seed: int = 0):
    rng = jax.random.PRNGKey(seed)
    dummy = jnp.zeros((batch_size, 28, 28, 1), jnp.float32)
    variables = model.init(rng, dummy, train=False)
    return variables["params"], variables["batch_stats"]


def cross_entropy_loss(logits, labels):
    """Mean softmax cross-entropy over the (local) batch."""
    logp = jax.nn.log_softmax(logits)
    onehot = jax.nn.one_hot(labels, logits.shape[-1])
    return -jnp.mean(jnp.sum(onehot * logp, axis=-1))


def accuracy(logits, labels):
    return jnp.mean((jnp.argmax(logits, -1) == labels).astype(jnp.float32))


def synthetic_mnist(num: int, seed: int = 0):
    """Deterministic synthetic MNIST-shaped data (the container has no
    dataset egress; the reference CI likewise shrinks MNIST to a smoke run,
    .travis.yml:105-109).  Labels are a fixed function of the images so a
    model can actually fit them."""
    import numpy as np

    rng = np.random.RandomState(seed)
    images = rng.rand(num, 28, 28, 1).astype("float32")
    # Label = argmax of mean intensity over 10 fixed random masks: learnable
    # but non-trivial.
    masks = rng.rand(10, 28 * 28).astype("float32")
    flat = images.reshape(num, -1)
    labels = np.argmax(flat @ masks.T, axis=1).astype("int32")
    return images, labels


def init_params(model: nn.Module, batch_size: int = 8, seed: int = 0):
    rng = jax.random.PRNGKey(seed)
    dummy = jnp.zeros((batch_size, 28, 28, 1), jnp.float32)
    return model.init(rng, dummy)["params"]
