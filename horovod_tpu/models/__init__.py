"""horovod_tpu.models"""
