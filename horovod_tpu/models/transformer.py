"""Transformer LM family with composable 5-way parallelism.

Beyond-parity flagship for the long-context/distributed stack (the
reference has no models of its own — SURVEY.md §1 "no model zoo"; its
examples lean on TF/Keras/Torch zoos).  A decoder-only LM whose forward
is written for ``shard_map`` over a :func:`..core.topology.make_mesh`
mesh, composing:

* **DP** — batch sharded over ``data``; gradients reduce via shard_map AD
  (replicated-param transpose = psum, verified exact in tests).
* **TP** — attention heads + MLP hidden sharded over ``model``
  (column/row-parallel, :mod:`..parallel.tensor`).
* **SP** — sequence sharded over ``seq``; attention runs the Pallas ring
  attention (:mod:`..parallel.sequence`).
* **EP** — optional MoE FFN layers with experts sharded over the data
  axis (:mod:`..parallel.expert`), the conventional EP placement.
* **PP** — layers split into stages over ``pipe`` with GPipe
  microbatching (:mod:`..parallel.pipeline`).

Parameter storage is replicated; sharded *compute* slices its shard
in-trace (``local_shard`` / ``select_stage_params`` / ``local_experts``).
This keeps the optimizer and Horovod-parity broadcast/checkpoint paths
strategy-agnostic; for sharded parameter *storage* compose any loss with
the model-agnostic FSDP/ZeRO-3 builder (:mod:`..parallel.fsdp`).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

import jax

from ..core import compat as _compat
import jax.numpy as jnp

from ..core import topology as T
from ..parallel.expert import local_experts, moe_layer
from ..parallel.pipeline import gpipe
from ..parallel.sequence import ring_attention
from ..parallel.tensor import (column_parallel, local_shard, row_parallel,
                               tp_mlp)
from ..ops.flash_attention import flash_attention


@dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 256
    d_model: int = 128
    n_heads: int = 8
    n_layers: int = 4
    d_ff: int = 512
    max_seq_len: int = 2048
    dtype: object = jnp.float32
    # Mixture-of-experts FFN (replaces the dense MLP on every layer when
    # num_experts > 0).
    num_experts: int = 0
    top_k: int = 2
    capacity_factor: float = 2.0
    # Attention kernel blocks (MXU-aligned on TPU).
    block_q: int = 128
    block_k: int = 128
    # Rematerialize each layer in the backward pass (jax.checkpoint):
    # activation memory drops from O(n_layers) to O(1) layers at ~1/3
    # more FLOPs — the standard trade for long sequences / deep stacks.
    remat: bool = False
    # Chunked cross-entropy: compute the loss over sequence chunks of
    # this many positions, rematerializing each chunk's logits in the
    # backward pass.  The [batch, seq, vocab] float32 logits tensor —
    # the dominant long-context allocation (e.g. 8.6 GB at batch 8,
    # seq 8192, vocab 32768) — never materializes; peak extra memory is
    # one chunk's logits.  0 = off (single full-logits matmul).
    loss_chunk: int = 0


@dataclass(frozen=True)
class ParallelAxes:
    """Which mesh axis serves each strategy (None = strategy off)."""
    data: Optional[str] = T.DATA_AXIS
    model: Optional[str] = None
    seq: Optional[str] = None
    pipe: Optional[str] = None
    expert: Optional[str] = None  # conventionally = data
    num_microbatches: int = 2     # pipeline depth-filling factor


def init_transformer(key, cfg: TransformerConfig) -> dict:
    """Parameter pytree; per-layer leaves are stacked on a leading
    ``n_layers`` axis (scan/pipeline friendly)."""
    n, d, f, v = cfg.n_layers, cfg.d_model, cfg.d_ff, cfg.vocab_size
    keys = iter(jax.random.split(key, 16))
    dt = cfg.dtype
    s_d = d ** -0.5
    p = {
        "embed": jax.random.normal(next(keys), (v, d), dt) * 0.02,
        "pos_embed": jax.random.normal(next(keys),
                                       (cfg.max_seq_len, d), dt) * 0.02,
        "ln_f": {"scale": jnp.ones((d,), dt), "bias": jnp.zeros((d,), dt)},
        "unembed": jax.random.normal(next(keys), (d, v), dt) * s_d,
        "layers": {
            "ln1": {"scale": jnp.ones((n, d), dt),
                    "bias": jnp.zeros((n, d), dt)},
            "wq": jax.random.normal(next(keys), (n, d, d), dt) * s_d,
            "wk": jax.random.normal(next(keys), (n, d, d), dt) * s_d,
            "wv": jax.random.normal(next(keys), (n, d, d), dt) * s_d,
            "wo": jax.random.normal(next(keys), (n, d, d), dt) * s_d,
            "ln2": {"scale": jnp.ones((n, d), dt),
                    "bias": jnp.zeros((n, d), dt)},
        },
    }
    if cfg.num_experts > 0:
        e = cfg.num_experts
        p["layers"]["router"] = (
            jax.random.normal(next(keys), (n, d, e), dt) * s_d)
        p["layers"]["moe_w_in"] = (
            jax.random.normal(next(keys), (n, e, d, f), dt) * s_d)
        p["layers"]["moe_w_out"] = (
            jax.random.normal(next(keys), (n, e, f, d), dt)
            * (f ** -0.5))
    else:
        p["layers"]["w_in"] = (
            jax.random.normal(next(keys), (n, d, f), dt) * s_d)
        p["layers"]["b_in"] = jnp.zeros((n, f), dt)
        p["layers"]["w_out"] = (
            jax.random.normal(next(keys), (n, f, d), dt) * (f ** -0.5))
        p["layers"]["b_out"] = jnp.zeros((n, d), dt)
    return p


def _layernorm(x, scale, bias, eps=1e-5):
    mu = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.var(x, axis=-1, keepdims=True)
    return (x - mu) * jax.lax.rsqrt(var + eps) * scale + bias


def _attention_block(x, lp, cfg: TransformerConfig, ax: ParallelAxes,
                     aux_acc):
    """Pre-LN attention with TP head sharding + SP ring attention."""
    b, s_loc, d = x.shape
    h = _layernorm(x, lp["ln1"]["scale"], lp["ln1"]["bias"])

    if ax.model is not None:
        mp = _compat.axis_size(ax.model)
        if cfg.n_heads % mp != 0 or d % mp != 0:
            raise ValueError(
                f"tensor-parallel degree {mp} must divide both "
                f"n_heads ({cfg.n_heads}) and d_model ({d})")
        wq = local_shard(lp["wq"], 1, axis_name=ax.model)
        wk = local_shard(lp["wk"], 1, axis_name=ax.model)
        wv = local_shard(lp["wv"], 1, axis_name=ax.model)
        wo = local_shard(lp["wo"], 0, axis_name=ax.model)
    else:
        wq, wk, wv, wo = lp["wq"], lp["wk"], lp["wv"], lp["wo"]
        mp = 1
    heads_loc = cfg.n_heads // mp
    head_dim = d // cfg.n_heads

    def split_heads(y):
        return y.reshape(b, s_loc, heads_loc, head_dim).transpose(
            0, 2, 1, 3)

    # One fused [d, 3*d_local] projection instead of three separate
    # gemms: XLA does not merge gemms horizontally, and the wider
    # matmul tiles the MXU better at transformer widths.
    qkv = column_parallel(h, jnp.concatenate([wq, wk, wv], axis=-1),
                          axis_name=ax.model or T.MODEL_AXIS)
    q, k, v = (split_heads(y) for y in jnp.split(qkv, 3, axis=-1))
    if ax.seq is not None:
        attn = ring_attention(q, k, v, axis_name=ax.seq, causal=True,
                              block_q=cfg.block_q, block_k=cfg.block_k)
    else:
        attn = flash_attention(q, k, v, causal=True, block_q=cfg.block_q,
                               block_k=cfg.block_k)
    attn = attn.transpose(0, 2, 1, 3).reshape(b, s_loc,
                                              heads_loc * head_dim)
    if ax.model is not None:
        out = row_parallel(attn, wo, axis_name=ax.model)
    else:
        out = jnp.dot(attn, wo,
                      preferred_element_type=jnp.float32).astype(x.dtype)
    return x + out, aux_acc


def _ffn_block(x, lp, cfg: TransformerConfig, ax: ParallelAxes, aux_acc):
    """Pre-LN FFN: TP dense MLP, or MoE with EP over the expert axis."""
    b, s_loc, d = x.shape
    h = _layernorm(x, lp["ln2"]["scale"], lp["ln2"]["bias"])
    if cfg.num_experts > 0:
        flat = h.reshape(b * s_loc, d)
        params = {"router": lp["router"], "w_in": lp["moe_w_in"],
                  "w_out": lp["moe_w_out"]}
        ep_axis = ax.expert or ax.data
        if ep_axis is not None:
            params = local_experts(params, axis_name=ep_axis)
            out = moe_layer(flat, params, axis_name=ep_axis,
                            num_experts=cfg.num_experts, top_k=cfg.top_k,
                            capacity_factor=cfg.capacity_factor)
        else:
            raise ValueError("MoE needs an expert (or data) mesh axis")
        y = out.out.reshape(b, s_loc, d)
        aux_acc = aux_acc + out.aux_loss
    else:
        if ax.model is not None:
            y = tp_mlp(h, local_shard(lp["w_in"], 1, axis_name=ax.model),
                       local_shard(lp["b_in"], 0, axis_name=ax.model),
                       local_shard(lp["w_out"], 0, axis_name=ax.model),
                       lp["b_out"], axis_name=ax.model)
        else:
            hh = jax.nn.gelu(
                jnp.dot(h, lp["w_in"],
                        preferred_element_type=jnp.float32).astype(x.dtype)
                + lp["b_in"])
            y = (jnp.dot(hh, lp["w_out"],
                         preferred_element_type=jnp.float32).astype(x.dtype)
                 + lp["b_out"])
    return x + y, aux_acc


def _layer(x, lp, cfg, ax, aux_acc):
    x, aux_acc = _attention_block(x, lp, cfg, ax, aux_acc)
    return _ffn_block(x, lp, cfg, ax, aux_acc)


# Remat variant: recompute the layer's activations in the backward pass
# instead of storing them (cfg/ax are static trace-time configuration).
_layer_remat = jax.checkpoint(_layer, static_argnums=(2, 3))


def _layer_fn(cfg):
    return _layer_remat if cfg.remat else _layer


def _index_layer(layers: dict, i):
    return jax.tree_util.tree_map(lambda leaf: leaf[i], layers)


def _slice_layers(layers: dict, start, count: int):
    return jax.tree_util.tree_map(
        lambda leaf: jax.lax.dynamic_slice_in_dim(leaf, start, count,
                                                  axis=0), layers)


def forward(params: dict, tokens, cfg: TransformerConfig,
            ax: ParallelAxes = ParallelAxes(), return_hidden: bool = False):
    """Logits for local token shard; call inside shard_map.

    ``tokens``: ``[batch_local, seq_local]`` int32 — batch sharded over
    ``ax.data``, sequence sharded (shard-major) over ``ax.seq``.
    Returns ``(logits [b, s_loc, vocab], aux_loss scalar)`` — or, with
    ``return_hidden``, the final post-LN hidden states
    ``[b, s_loc, d_model]`` instead of logits (for chunked-loss callers
    that never materialize the full logits tensor).
    """
    b, s_loc = tokens.shape
    seq_off = 0
    global_seq = s_loc
    if ax.seq is not None:
        seq_off = jax.lax.axis_index(ax.seq) * s_loc
        global_seq = s_loc * _compat.axis_size(ax.seq)
    if global_seq > cfg.max_seq_len:
        raise ValueError(
            f"global sequence length {global_seq} exceeds "
            f"cfg.max_seq_len {cfg.max_seq_len}; positions would clamp "
            f"silently")
    pos = seq_off + jnp.arange(s_loc)
    x = params["embed"][tokens] + jnp.take(params["pos_embed"], pos,
                                           axis=0)
    aux = jnp.zeros((), jnp.float32)

    if ax.pipe is not None:
        n_stages = _compat.axis_size(ax.pipe)
        per_stage = cfg.n_layers // n_stages
        if per_stage * n_stages != cfg.n_layers:
            raise ValueError(
                f"n_layers {cfg.n_layers} not divisible by pipeline "
                f"stages {n_stages}")
        stage = jax.lax.axis_index(ax.pipe)
        mine = _slice_layers(params["layers"], stage * per_stage,
                             per_stage)

        # MoE aux loss inside the pipeline would need to ride the
        # activations; restrict PP to dense FFN layers for now.
        if cfg.num_experts > 0:
            raise ValueError("pipeline parallelism currently supports "
                             "dense FFN layers only (num_experts == 0)")

        def stage_fn(stage_params, x_mb):
            for i in range(per_stage):
                x_mb, _ = _layer_fn(cfg)(x_mb,
                                         _index_layer(stage_params, i),
                                         cfg, ax,
                                         jnp.zeros((), jnp.float32))
            return x_mb

        x = gpipe(stage_fn, mine, x,
                  num_microbatches=ax.num_microbatches,
                  axis_name=ax.pipe)
    else:
        for i in range(cfg.n_layers):
            x, aux = _layer_fn(cfg)(x, _index_layer(params["layers"], i),
                                    cfg, ax, aux)

    x = _layernorm(x, params["ln_f"]["scale"], params["ln_f"]["bias"])
    if return_hidden:
        return x, aux
    logits = jnp.dot(x, params["unembed"],
                     preferred_element_type=jnp.float32)
    return logits, aux


def make_loss_fn(cfg: TransformerConfig, ax: ParallelAxes = ParallelAxes(),
                 mesh_axes: Optional[tuple] = None):
    """Local shard loss for use inside shard_map: next-token cross-entropy
    pmean-ed over every mesh axis (a replicated logical scalar, so
    ``jax.grad`` outside the shard_map yields exact global gradients).

    ``mesh_axes``: all axis names of the mesh (defaults to the axes named
    in ``ax``).
    """
    axes = mesh_axes
    if axes is None:
        # dedup: ax.expert conventionally aliases ax.data.
        axes = tuple(dict.fromkeys(
            a for a in (ax.data, ax.model, ax.seq, ax.pipe, ax.expert)
            if a is not None))

    def dense_ce(params, tokens, targets):
        logits, aux = forward(params, tokens, cfg, ax)
        logp = jax.nn.log_softmax(logits, axis=-1)
        nll = -jnp.take_along_axis(logp, targets[..., None],
                                   axis=-1)[..., 0]
        return jnp.mean(nll) + aux

    def chunked_ce(params, tokens, targets):
        x, aux = forward(params, tokens, cfg, ax, return_hidden=True)
        b, s_loc, d = x.shape
        chunk = min(cfg.loss_chunk, s_loc)
        if s_loc % chunk != 0:
            raise ValueError(
                f"local sequence length {s_loc} not divisible by "
                f"loss_chunk {chunk}")
        n = s_loc // chunk
        xs = x.reshape(b, n, chunk, d).transpose(1, 0, 2, 3)
        ts = targets.reshape(b, n, chunk).transpose(1, 0, 2)

        @jax.checkpoint
        def chunk_nll(xc, tc):
            logits = jnp.dot(xc, params["unembed"],
                             preferred_element_type=jnp.float32)
            logp = jax.nn.log_softmax(logits, axis=-1)
            return jnp.sum(
                -jnp.take_along_axis(logp, tc[..., None], axis=-1))

        def body(total, xt):
            return total + chunk_nll(*xt), None

        total, _ = _compat.scan(body, jnp.zeros((), jnp.float32),
                                (xs, ts))
        return total / (b * s_loc) + aux

    def loss_fn(params, batch):
        tokens, targets = batch
        ce = chunked_ce if cfg.loss_chunk > 0 else dense_ce
        loss = ce(params, tokens, targets)
        return jax.lax.pmean(loss, axes)

    return loss_fn


def chained_lm_loss(cfg: TransformerConfig):
    """The transformer LM as a :class:`~..parallel.overlap.ChainedLoss`
    — the segmentable form the backward/communication-overlap step
    streams gradient buckets out of (one backward program per stage:
    embedding, each decoder layer, final-LN+unembed+cross-entropy).

    Single-axis data parallelism with dense FFN layers only (the 5-way
    parallel composition keeps :func:`make_loss_fn`; pipeline/expert
    axes have their own schedules).  Calling the returned object
    evaluates the identical monolithic loss, so ``HVD_TPU_OVERLAP=off``
    differentiates the same math — the bitwise-identity contract of
    ``bench.py --mode overlap``.  Pair with :func:`chained_lm_params`.
    """
    from ..parallel.overlap import ChainedLoss

    if cfg.num_experts > 0:
        raise ValueError("chained_lm_loss supports dense FFN layers only "
                         "(num_experts == 0)")
    ax = ParallelAxes()

    def embed_stage(p, carry, batch):
        tokens, _targets = batch
        _b, s = tokens.shape
        if s > cfg.max_seq_len:
            raise ValueError(
                f"sequence length {s} exceeds cfg.max_seq_len "
                f"{cfg.max_seq_len}; positions would clamp silently")
        pos = jnp.arange(s)
        return p["embed"][tokens] + jnp.take(p["pos_embed"], pos, axis=0)

    def make_layer_stage():
        def layer_stage(p, carry, batch):
            x, _aux = _layer_fn(cfg)(carry, p, cfg, ax,
                                     jnp.zeros((), jnp.float32))
            return x
        return layer_stage

    def head_stage(p, carry, batch):
        _tokens, targets = batch
        x = _layernorm(carry, p["ln_f"]["scale"], p["ln_f"]["bias"])
        logits = jnp.dot(x, p["unembed"],
                         preferred_element_type=jnp.float32)
        logp = jax.nn.log_softmax(logits, axis=-1)
        nll = -jnp.take_along_axis(logp, targets[..., None],
                                   axis=-1)[..., 0]
        return jnp.mean(nll)

    stages = [embed_stage]
    stages += [make_layer_stage() for _ in range(cfg.n_layers)]
    stages.append(head_stage)
    return ChainedLoss(stages)


def chained_lm_params(params: dict, cfg: TransformerConfig) -> list:
    """Restructure an :func:`init_transformer` tree into the per-stage
    sequence :func:`chained_lm_loss` expects: ``[embed, layer_0, ...,
    layer_{n-1}, head]`` (per-layer leaves unstacked from their leading
    ``n_layers`` axis — each layer's gradients become their own overlap
    dispatch segment)."""
    out = [{"embed": params["embed"], "pos_embed": params["pos_embed"]}]
    out += [_index_layer(params["layers"], i)
            for i in range(cfg.n_layers)]
    out.append({"ln_f": params["ln_f"], "unembed": params["unembed"]})
    return out


def synthetic_lm_batch(key, global_batch: int, seq_len: int,
                       vocab_size: int):
    """Synthetic next-token data (tokens, shifted targets)."""
    tokens = jax.random.randint(key, (global_batch, seq_len + 1), 0,
                                vocab_size)
    return tokens[:, :-1].astype(jnp.int32), tokens[:, 1:].astype(jnp.int32)


# ---------------------------------------------------------------------------
# Serving path: incremental decode against a fixed-capacity KV view
# (hvd-serve, docs/inference.md).  These functions are the model half of
# horovod_tpu/serving/: no shard_map, no flash attention — a plain
# masked-softmax attention whose program is IDENTICAL between a
# multi-token prefill and a one-token decode step, so the serving
# engine's "prefill + N decode steps ≡ non-incremental forward" contract
# can be tested (and CI-gated) bitwise.  Tensor parallelism for serving
# comes from GSPMD sharding of the KV view's head axis
# (serving/kv_cache.py reuses the parallel/tensor.py head-sharding
# layout), not from shard_map.
# ---------------------------------------------------------------------------


def cache_attention(q, k_view, v_view, q_pos):
    """Masked attention of ``q`` against a fixed-capacity KV view.

    ``q``: ``[b, s, heads, head_dim]`` queries at global positions
    ``q_pos`` (``[b, s]`` int32).  ``k_view``/``v_view``:
    ``[b, capacity, heads, head_dim]`` — entry ``j`` holds the key/value
    of global position ``j`` (the serving engine gathers its paged store
    into this logical order first).  Cache-aware causal masking for
    ragged batches: entry ``j`` participates in row ``(b, i)`` iff
    ``j <= q_pos[b, i]`` — per-sequence lengths ride in through
    ``q_pos``, so one program serves every slot-length mix.

    Rows whose mask is empty (inactive serving slots with
    ``q_pos < 0``) come out all-zero instead of NaN; active rows are
    bitwise-unaffected by the guard (it only ever adds ``0.0``).
    Softmax runs in float32 over the full capacity axis; masked entries
    contribute exact zeros, so results do not depend on how much unused
    capacity follows a sequence.
    """
    b, s, h, hd = q.shape
    cap = k_view.shape[1]
    scale = hd ** -0.5
    scores = jnp.einsum(
        "bshd,bchd->bhsc", q.astype(jnp.float32),
        k_view.astype(jnp.float32)) * scale
    kv_pos = jnp.arange(cap, dtype=jnp.int32)
    mask = kv_pos[None, None, None, :] <= q_pos[:, None, :, None]
    scores = jnp.where(mask, scores, -jnp.inf)
    m = jnp.max(scores, axis=-1, keepdims=True)
    m = jnp.where(jnp.isfinite(m), m, 0.0)  # all-masked rows: exp(-inf)
    p = jnp.exp(scores - m)
    denom = jnp.sum(p, axis=-1, keepdims=True)
    denom = jnp.where(denom == 0.0, 1.0, denom)
    p = p / denom
    out = jnp.einsum("bhsc,bchd->bshd", p, v_view.astype(jnp.float32))
    return out.astype(q.dtype)


def forward_step(params, tokens, start_pos, k_view, v_view,
                 cfg: TransformerConfig):
    """Cache-aware forward over ``tokens`` given already-cached context.

    The ONE program both serving phases run: prefill calls it with the
    whole (padded) prompt, decode with a single token per sequence —
    same code path, so the two compose bitwise.

    ``tokens``: ``[b, s]`` int32.  ``start_pos``: ``[b]`` int32 — the
    global position of ``tokens[:, 0]``, which is also how many valid
    entries the KV view already holds for that sequence (ragged across
    the batch).  ``k_view``/``v_view``:
    ``[n_layers, b, capacity, heads, head_dim]`` with positions
    ``< start_pos`` populated.

    Returns ``(logits [b, s, vocab] float32, k_new, v_new)`` where
    ``k_new``/``v_new`` are ``[n_layers, b, s, heads, head_dim]`` — the
    new tokens' entries, for the caller to scatter back into its paged
    store (the view itself is a gather, not the storage).
    """
    if cfg.num_experts > 0:
        raise ValueError("the serving path currently supports dense FFN "
                         "layers only (num_experts == 0)")
    b, s = tokens.shape
    h_n, d = cfg.n_heads, cfg.d_model
    if d % h_n != 0:
        raise ValueError(f"d_model {d} not divisible by n_heads {h_n}")
    hd = d // h_n
    cap = k_view.shape[2]
    if cap > cfg.max_seq_len:
        raise ValueError(f"KV capacity {cap} exceeds cfg.max_seq_len "
                         f"{cfg.max_seq_len}")
    pos = start_pos[:, None] + jnp.arange(s, dtype=jnp.int32)[None, :]
    # Inactive slots carry start_pos < 0; clamp the embedding lookup
    # (their rows are masked/garbage anyway, but the gather index must
    # stay in range).
    x = (params["embed"][tokens]
         + jnp.take(params["pos_embed"], jnp.clip(pos, 0, None), axis=0))
    ax = ParallelAxes(data=None)
    k_news, v_news = [], []

    def put(view_b, new_b, start_b):
        # Per-row scatter, NOT dynamic_update_slice: a slice window is
        # clamped as a whole, so a decode block [token, dummy] landing
        # at start == capacity-1 would shift back one position —
        # overwriting the previous token's entry and leaving the dummy
        # unmasked at capacity-1.  mode="drop" keeps every row at its
        # true index and discards rows past the capacity.  (hvd-serve's
        # scheduler evicts one step before that boundary; this keeps
        # forward_step's own contract exact for any caller stepping at
        # the final cached position.)
        idx = jnp.clip(start_b, 0, None) + jnp.arange(
            new_b.shape[0], dtype=jnp.int32)
        return view_b.at[idx].set(new_b, mode="drop",
                                  unique_indices=True)

    for i in range(cfg.n_layers):
        lp = _index_layer(params["layers"], i)
        h = _layernorm(x, lp["ln1"]["scale"], lp["ln1"]["bias"])
        # Same fused [d, 3d] projection as the training forward.
        qkv = jnp.dot(
            h, jnp.concatenate([lp["wq"], lp["wk"], lp["wv"]], axis=-1),
            preferred_element_type=jnp.float32).astype(x.dtype)
        q, k, v = (y.reshape(b, s, h_n, hd)
                   for y in jnp.split(qkv, 3, axis=-1))
        k_full = jax.vmap(put)(k_view[i], k, start_pos)
        v_full = jax.vmap(put)(v_view[i], v, start_pos)
        attn = cache_attention(q, k_full, v_full, pos)
        out = jnp.dot(attn.reshape(b, s, d), lp["wo"],
                      preferred_element_type=jnp.float32).astype(x.dtype)
        x, _ = _ffn_block(x + out, lp, cfg, ax, jnp.zeros((), jnp.float32))
        k_news.append(k)
        v_news.append(v)
    x = _layernorm(x, params["ln_f"]["scale"], params["ln_f"]["bias"])
    logits = jnp.dot(x, params["unembed"],
                     preferred_element_type=jnp.float32)
    return logits, jnp.stack(k_news), jnp.stack(v_news)


def _put_view(view, new, pos):
    """Scatter one KV entry per sequence into a fixed-capacity view:
    ``view [n_layers, b, capacity, heads, head_dim]``, ``new
    [n_layers, b, heads, head_dim]`` written at per-sequence position
    ``pos [b]`` (rows past the capacity drop — the same mode="drop"
    discipline as :func:`forward_step`'s in-block put)."""
    def one(vb, nb, pb):
        return vb.at[pb].set(nb, mode="drop")
    return jax.vmap(jax.vmap(one, in_axes=(0, 0, 0)),
                    in_axes=(0, 0, None))(view, new, pos)


def speculative_propose(params, prev, pending, start_pos, k_view,
                        v_view, cfg: TransformerConfig, n_propose: int):
    """Greedy draft rollout for speculative decoding (hvd-spec): ONE
    program proposing ``n_propose`` tokens per sequence by unrolling
    that many cache-aware forward steps over the draft's KV view.

    ``prev``/``pending``: ``[b]`` int32 — the second-newest context
    token (at global position ``start_pos``) and the newest, not yet
    cached one (at ``start_pos + 1``).  The first step is a width-2
    block of BOTH real tokens: re-deriving ``prev``'s KV is either an
    exact overwrite (the values are a pure function of the token, its
    position and the accepted prefix — bitwise-identical on
    recomputation) or, after a fully accepted previous iteration, the
    catch-up write for the one draft token whose KV the draft never
    computed (it was the last PROPOSAL, not an input).  That single
    rule keeps the program shape identical for every slot in a mixed
    batch — no per-slot catch-up flag.

    Subsequent steps run ``[token, dummy]`` width-2 blocks (the same
    M>=2 gemm discipline as decode) feeding each argmax proposal back
    in, with the freshly derived KV scattered into the view between
    steps so step ``j+1`` attends to step ``j``'s entry.

    Returns ``(proposals [b, n_propose] int32, k_writes, v_writes)``
    where the writes are ``[n_layers, b, n_propose + 1, heads,
    head_dim]`` — the KV entries for global positions ``start_pos ..
    start_pos + n_propose``, for the caller to scatter into its paged
    store.
    """
    if n_propose < 1:
        raise ValueError(f"n_propose must be >= 1, got {n_propose}")
    kv, vv = k_view, v_view
    k_cols, v_cols = [], []
    blk = jnp.stack([prev, pending], axis=1)
    logits, kn, vn = forward_step(params, blk, start_pos, kv, vv, cfg)
    cur = jnp.argmax(logits[:, 1], axis=-1).astype(jnp.int32)
    proposals = [cur]
    k_cols += [kn[:, :, 0], kn[:, :, 1]]
    v_cols += [vn[:, :, 0], vn[:, :, 1]]
    # prev's entry must land in the view too: after a fully-accepted
    # iteration it is the catch-up fill, and steps >= 2 attend to it.
    kv = _put_view(kv, k_cols[0], start_pos)
    vv = _put_view(vv, v_cols[0], start_pos)
    for j in range(1, n_propose):
        kv = _put_view(kv, k_cols[-1], start_pos + j)
        vv = _put_view(vv, v_cols[-1], start_pos + j)
        blk = jnp.stack([cur, jnp.zeros_like(cur)], axis=1)
        logits, kn, vn = forward_step(params, blk, start_pos + 1 + j,
                                      kv, vv, cfg)
        cur = jnp.argmax(logits[:, 0], axis=-1).astype(jnp.int32)
        proposals.append(cur)
        k_cols.append(kn[:, :, 0])
        v_cols.append(vn[:, :, 0])
    return (jnp.stack(proposals, axis=1),
            jnp.stack(k_cols, axis=2), jnp.stack(v_cols, axis=2))


def serving_forward(params, tokens, cfg: TransformerConfig,
                    capacity: Optional[int] = None):
    """Non-incremental reference for the serving path: the full sequence
    through :func:`forward_step` from an empty KV view.  Returns
    ``logits [b, s, vocab]`` (float32).  The serving bitwise contract —
    asserted by tests/test_serving.py and the serving bench — is that a
    prefill of ``tokens[:, :p]`` followed by ``s - p`` single-token
    decode steps reproduces these logits exactly."""
    b, s = tokens.shape
    cap = capacity if capacity is not None else s
    hd = cfg.d_model // cfg.n_heads
    zeros = jnp.zeros((cfg.n_layers, b, cap, cfg.n_heads, hd),
                      cfg.dtype)
    logits, _, _ = forward_step(
        params, tokens, jnp.zeros((b,), jnp.int32), zeros, zeros, cfg)
    return logits
