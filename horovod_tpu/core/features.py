"""Build/runtime feature-query shims (≙ the post-v0.13 Horovod API:
``hvd.mpi_built()``, ``hvd.nccl_built()``, ``hvd.gloo_built()``,
``hvd.cuda_built()``, ``hvd.rocm_built()``, ``hvd.mpi_enabled()``, …).

Migration scripts commonly branch on these to pick launch/tuning paths;
honest answers keep those branches working: there is no MPI, NCCL,
Gloo, CUDA or ROCm anywhere in this stack — the data plane is XLA
collectives over ICI/DCN and the control plane is the TCP coordinator.
``xla_built()``/``native_built()`` report what IS here.
"""

from __future__ import annotations


def mpi_built() -> bool:
    return False


def mpi_enabled() -> bool:
    return False


def nccl_built() -> bool:
    return False


def gloo_built() -> bool:
    return False


def gloo_enabled() -> bool:
    return False


def cuda_built() -> bool:
    return False


def rocm_built() -> bool:
    return False


def ccl_built() -> bool:
    return False


def ddl_built() -> bool:
    return False


def xla_built() -> bool:
    """The TPU-native data plane: XLA collectives over the device mesh."""
    return True


def native_built() -> bool:
    """True when the C++ coordinator/wire/timeline library is loaded
    (falls back to the pure-Python twins otherwise)."""
    from ..native import lib as _native

    return bool(_native.NATIVE)
