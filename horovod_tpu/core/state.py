"""Global runtime state for horovod_tpu.

TPU-native re-design of the reference's ``HorovodGlobalState`` singleton
(reference: horovod/common/operations.cc:107-200).  The reference keeps a
background thread, a mutex-guarded tensor table and MPI rank/size caches;
under JAX's single-controller SPMD model most of that machinery dissolves:

* Process bootstrap: ``jax.distributed`` + the process/device enumeration
  replaces ``MPI_Init_thread`` / ``MPI_COMM_WORLD``
  (reference: operations.cc:1173-1196).
* The device mesh (one logical axis, ``"hvd"``) replaces the flat
  ``MPI_COMM_WORLD`` rank space.  Collectives become XLA collectives over
  that axis, scheduled by the compiler instead of a 5 ms background tick
  (reference: operations.cc:1219-1221).

Topology model
--------------
The reference binds exactly one GPU to one MPI process, so "rank" is both a
process id and a device id.  On TPU one process typically controls several
chips, so the two concepts split:

* **replica** — one TPU device.  ``size()`` counts replicas globally;
  this is the axis gradients are averaged over.
* **process** — one controller host process (``jax.process_index()``).

``rank()``/``local_rank()`` keep Horovod's semantics at the host level: they
return the first replica owned by the calling process, which equals the
Horovod rank exactly in the one-device-per-process deployment the reference
assumes.  Inside traced per-replica code the true replica id is
``replica_id()`` (= ``lax.axis_index("hvd")``).
"""

from __future__ import annotations

import os
import sys
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Optional

import jax

from ..analysis import lockorder as _lockorder
from ..analysis import races as _races

# Name of the one-dimensional mesh axis all Horovod-style collectives run
# over.  Mirrors the single flat rank space of MPI_COMM_WORLD.
REPLICA_AXIS = "hvd"


class NotInitializedError(RuntimeError):
    """Raised when the library is used before ``init()``.

    Mirrors the reference's per-call ``CheckInitialized`` /
    "Horovod has not been initialized; use hvd.init()." errors
    (reference: horovod/common/operations.cc:210-220 analogue in
    common/__init__.py:54-58).
    """

    def __init__(self) -> None:
        super().__init__(
            "horovod_tpu has not been initialized; use horovod_tpu.init()."
        )


@_races.race_checked
@dataclass
class _GlobalState:
    """Mutable singleton state guarded by ``lock`` (coarse, like the
    reference's single global mutex — operations.cc:113)."""

    initialized: bool = False
    shutdown: bool = False
    # Set when any rank initiated shutdown (≙ the reference's shut_down
    # flag, operations.cc:134): pending ops get SHUT_DOWN_ERROR, new eager
    # ops are refused.
    peer_shutdown: bool = False
    # The 1-D replica mesh over every addressable device.
    mesh: Optional[jax.sharding.Mesh] = None
    # Devices in mesh order (process-major, then local ordinal).
    devices: tuple = ()
    # Cached topology numbers.
    size: int = 0
    local_size: int = 0
    process_index: int = 0
    process_count: int = 1
    # Multi-process mode (reference: N MPI ranks): True when this runtime
    # spans several jax processes under jax.distributed.
    multiprocess: bool = False
    # Cross-process control-plane transport (ops.transport.*Transport).
    transport: Any = None
    # Node-level placement (ops.transport.Topology) in multi-process mode.
    topology: Any = None
    # Tensor-fusion threshold in bytes (reference default 64 MB,
    # operations.cc:140, env HOROVOD_FUSION_THRESHOLD).
    fusion_threshold_bytes: int = 64 * 1024 * 1024
    # Background tick period (reference 5 ms, operations.cc:1221; env
    # HOROVOD_CYCLE_TIME in milliseconds, the post-v0.13 name).
    tick_seconds: float = 0.005
    # hvd-tune controller (tuning.Tuner) when HVD_TPU_TUNE=1 and/or the
    # deprecated HOROVOD_AUTOTUNE=1 sweep alias; coordinator-side only —
    # fusion decisions are made there.  ``autotuner`` is the same object
    # under the round-4 name (the drain loop's record_bytes/maybe_step
    # feed); ``tuner`` is the coordinator tick's RETUNE-marker source.
    autotuner: Any = None
    tuner: Any = None
    # Registered process sets (ops.process_set.ProcessSet) by id; id 0
    # (the global set) is implicit and never stored here.  Registered/
    # removed by user threads, read by the drain tick and the
    # controller's receive threads.
    # guarded_by: lock
    process_sets: dict = field(default_factory=dict)
    # guarded_by: lock
    next_process_set_id: int = 1
    # Timeline (utils.timeline.Timeline) when HOROVOD_TIMELINE is set.
    timeline: Any = None
    # hvd-telemetry HTTP exporter (telemetry/exporter.py) when
    # HVD_TPU_METRICS_PORT is set (rank 0 by default).
    metrics_exporter: Any = None
    # Steady-state negotiation response cache (ops.cache.ResponseCache);
    # one replica per rank, shared by the coordinator facades and the
    # transport.  None when HVD_TPU_RESPONSE_CACHE=0 or the program
    # tracker is armed (they are mutually exclusive — see cache_enabled).
    response_cache: Any = None
    # Native coordinator handle (ops.coordinator.Coordinator).
    coordinator: Any = None
    # Handle manager for the async API (ops.handles.HandleManager).
    handle_manager: Any = None
    # Background drain thread for async eager ops (≙ the reference's
    # background coordinator thread, operations.cc:1167).
    bg_thread: Any = None
    bg_stop: Any = None
    # hvd.join() state (post-v0.13 uneven-workload barrier): while
    # ``joining``, this process executes peers' collective responses with
    # zero contributions; ``join_result`` is set by the JOIN release
    # response (the last joining rank).
    joining: bool = False
    join_result: Optional[int] = None
    # Reentrant: init() holds it across nested helpers.  Created through
    # the hvd-analyze factory so HVD_TPU_LOCK_CHECK=1 puts it on the
    # lock-order graph (analysis/lockorder.py).
    lock: threading.RLock = field(
        default_factory=lambda: _lockorder.make_rlock("GlobalState.lock"))


_state = _GlobalState()


def global_state() -> _GlobalState:
    return _state


def _build_mesh(devices) -> jax.sharding.Mesh:
    import numpy as np

    return jax.sharding.Mesh(np.asarray(devices), (REPLICA_AXIS,))


def init(devices=None) -> None:
    """Initialize horovod_tpu.

    TPU-native equivalent of ``hvd.init()`` → ``horovod_init`` →
    ``InitializeHorovodOnce`` (reference: horovod/common/__init__.py:50-53,
    operations.cc:1479-1490).  Instead of spawning a background MPI thread,
    we enumerate the JAX process/device topology and build the replica mesh.
    Safe to call more than once (the reference's init is also idempotent via
    an atomic flag — operations.cc:1481).

    Args:
      devices: optional explicit device list (defaults to ``jax.devices()``
        in process-major order).  Used by tests to restrict the replica set.
    """
    if _state.initialized:
        if devices is None:
            return
        # Re-init with a different replica set: tear down the old runtime
        # (background thread, coordinator, timeline) first.
        shutdown()
    # Validate the SPMD-program-selecting env knobs UP FRONT: a typo'd
    # compressor / topology value must fail init with the full valid
    # list, not surface as a trace error inside the first collective.
    # (Cross-rank uniformity of the same knobs is checked by the
    # control-plane HELLO handshake — ops/transport.py warns naming the
    # rank and the divergent knobs.)
    from .. import chaos as _chaos_env
    from ..memory import oom as _mem_oom
    from ..ops import compression as _compression_env
    from ..ops import fused as _fused_env
    from ..ops import tree as _tree_env
    from ..parallel import overlap as _overlap_env
    from ..parallel import pipeline as _pipeline_env
    from . import topology as _topology_env

    _compression_env.validate_env()
    _topology_env.validate_env()
    _overlap_env.validate_env()
    _pipeline_env.validate_env()
    _tree_env.validate_env()
    # hvd-fuse: mode/chunk knobs select the compiled SPMD program.
    _fused_env.validate_env()
    # hvd-mem: a typo'd HVD_TPU_MEM_CAPACITY must fail init too.
    _mem_oom.validate_env()
    # hvd-chaos: a typo'd HVD_TPU_FAULTS clause must abort init with
    # the valid site/key list, not silently run a fault-free "chaos"
    # job (docs/chaos.md).
    _chaos_env.validate_env()
    # hvd-tune: a typo'd window/pin knob must fail init, not the first
    # decision window (docs/tuning.md).
    from .. import tuning as _tuning

    _tuning.validate_env()

    # Bootstrap the process cluster BEFORE the first device enumeration
    # (≙ MPI_Init_thread before MPI_Comm_rank, operations.cc:1173-1181).
    from . import cluster as _cluster

    spec = _cluster.maybe_initialize()
    with _state.lock:
        _state.process_index = jax.process_index()
        _state.process_count = jax.process_count()
        _state.multiprocess = _state.process_count > 1
        if _state.multiprocess and devices is not None:
            raise ValueError(
                "init(devices=...) subsets are single-process only; in "
                "multi-process mode every process must use the full global "
                "topology (the reference likewise fixes the communicator "
                "at MPI_COMM_WORLD).")
        devs = tuple(devices if devices is not None else jax.devices())
        _state.devices = devs
        _state.mesh = _build_mesh(devs)
        _state.size = len(devs)
        if devices is not None:
            local = [d for d in devs if d.process_index == _state.process_index]
            _state.local_size = len(local) if local else len(devs)
        else:
            _state.local_size = jax.local_device_count()
        _state.fusion_threshold_bytes = int(
            os.environ.get("HOROVOD_FUSION_THRESHOLD", 64 * 1024 * 1024)
        )
        _state.tick_seconds = float(
            os.environ.get("HOROVOD_CYCLE_TIME", 5.0)) / 1000.0
        _state.shutdown = False
        _state.peer_shutdown = False
        _state.process_sets = {}
        _state.next_process_set_id = 1
        _state.initialized = True

        # Timeline: rank-0-only Chrome tracing, same env contract as the
        # reference (operations.cc:1201-1204).
        timeline_path = os.environ.get("HOROVOD_TIMELINE")
        if timeline_path and _state.process_index == 0:
            from ..utils.timeline import Timeline

            _state.timeline = Timeline(timeline_path)
        else:
            _state.timeline = None

        from ..ops.handles import HandleManager

        _state.handle_manager = HandleManager()

        from ..ops import cache as _cache
        from ..ops.coordinator import Coordinator

        _state.response_cache = (
            _cache.ResponseCache(rank=_state.process_index)
            if _cache.cache_enabled() else None)

        if _state.multiprocess:
            # Reference topology: negotiation runs at process (MPI-rank)
            # granularity, with rank 0 as the coordinator and a TCP control
            # plane carrying the wire messages (≙ operations.cc:1226-1374).
            from ..ops import transport as _transport

            if spec is None:
                raise RuntimeError(
                    "jax.distributed is active but no HVD_TPU_COORDINATOR/"
                    "JAX_COORDINATOR_ADDRESS is visible; the eager control "
                    "plane needs it to locate the rank-0 controller.")
            # Tree overlay (ops/tree.py, ROADMAP "thousand-rank control
            # plane"): above HVD_TPU_TREE_THRESHOLD ranks the star
            # becomes a fanout-ary tree — interiors aggregate their
            # subtree's control traffic and relay broadcasts, so rank
            # 0's per-tick frame count drops from O(world) to O(fanout).
            from ..ops import tree as _tree

            layout = (_tree.build_layout(_state.process_count)
                      if _tree.tree_active(_state.process_count)
                      else None)
            if _state.process_index == 0:
                _state.coordinator = Coordinator(
                    size=_state.process_count,
                    fusion_threshold=_state.fusion_threshold_bytes,
                    timeline=_state.timeline,
                    cache=_state.response_cache,
                )
                _state.transport = _transport.ControllerTransport(
                    _state.coordinator, _state.process_count,
                    spec.controller_port, tree=layout)
                _state.topology = _state.transport.topology[0]
            else:
                _state.coordinator = None
                if layout is not None:
                    _state.transport = _tree.TreeWorkerTransport(
                        spec.controller_host, spec.controller_port,
                        _state.process_index, layout)
                else:
                    _state.transport = _transport.WorkerTransport(
                        spec.controller_host, spec.controller_port,
                        _state.process_index)
                _state.topology = _state.transport.topology
                if not _state.transport.controller_cache:
                    # Rank 0 advertised no response cache (its env
                    # disables it, or its program tracker is armed): a
                    # local replica would emit bits rank 0 can never
                    # resolve — run cache-less instead.
                    _state.response_cache = None
            _state.transport.cache = _state.response_cache
        else:
            _state.coordinator = Coordinator(
                size=_state.size,
                fusion_threshold=_state.fusion_threshold_bytes,
                timeline=_state.timeline,
                cache=_state.response_cache,
            )

        # hvd-tune (HVD_TPU_TUNE=1; HOROVOD_AUTOTUNE=1 is the deprecated
        # round-4 sweep alias): collector on every rank, controller on
        # the process that makes the fusion decisions — the coordinator.
        # Knob application rides RETUNE response-stream markers so every
        # rank (including this one) applies at the same cycle boundary
        # (tuning/actuation.py).
        _tuning.install(_state)

        # hvd-trace: fresh span buffer + (step, cycle, trace_id)
        # context for this incarnation; rank 0 mints the run's trace
        # id, workers adopt it from the first response broadcast.
        from .. import trace as _trace_mod

        _trace_mod.reset_run(rank=_state.process_index)

        # hvd-telemetry: register the pull-side collector over the
        # runtime's stats structs (idempotent across re-inits) and, when
        # HVD_TPU_METRICS_PORT is set, serve /metrics + /healthz — rank
        # 0 only unless HVD_TPU_METRICS_ALL_RANKS=1 (docs/metrics.md).
        from .. import telemetry as _telemetry
        from ..memory import ledger as _mem_ledger

        _telemetry.install_runtime_collector()
        # hvd-mem: (re-)register the memory gauge collector — ledger
        # categories, watermarks, device.memory_stats() — so per-rank
        # HBM rides every FRAME_METRICS / FRAME_METRICS_TREE pull.
        _mem_ledger.install_collector()
        port = os.environ.get("HVD_TPU_METRICS_PORT")
        if port and _state.metrics_exporter is None and (
                _state.process_index == 0
                or os.environ.get("HVD_TPU_METRICS_ALL_RANKS") == "1"):
            from ..telemetry import exporter as _exporter

            try:
                # ValueError too: a typo'd port is an observability env
                # mistake and must not abort the training job.
                _state.metrics_exporter = _exporter.start_exporter(
                    _telemetry.registry(), int(port.strip()),
                    host=os.environ.get("HVD_TPU_METRICS_HOST",
                                        "0.0.0.0"))
            except (OSError, ValueError) as e:
                print(f"WARNING: hvd-telemetry exporter could not serve "
                      f"on HVD_TPU_METRICS_PORT={port!r}: {e}",
                      file=sys.stderr)

        # Spawn the background tick thread serving async eager collectives
        # (≙ InitializeHorovodOnce spawning BackgroundThreadLoop,
        # operations.cc:1481-1483).
        from ..ops import collective as _collective

        _state.bg_stop = threading.Event()
        _state.bg_thread = threading.Thread(
            target=_collective._background_loop, args=(_state.bg_stop,),
            name="horovod_tpu-tick", daemon=True)
        _state.bg_thread.start()

    # Persistent compile cache (hvd-pipeline; OUTSIDE the state lock —
    # warm_start compiles and touches the filesystem): point jax's XLA
    # compilation cache at HVD_TPU_COMPILE_CACHE_DIR and AOT-rebuild the
    # megakernel executables the previous incarnation recorded there, so
    # an elastic relaunch (or any repeat run) skips the cold-compile
    # stall on its first training steps.
    cache_dir = os.environ.get("HVD_TPU_COMPILE_CACHE_DIR")
    if cache_dir:
        _configure_compile_cache(cache_dir)
        from ..ops import megakernel as _megakernel

        _megakernel.warm_start(_state.mesh, cache_dir)
    # hvd-mem pre-flight (docs/memory.md): when the per-rank HBM
    # capacity is known (backend memory_stats or HVD_TPU_MEM_CAPACITY),
    # size the largest recorded executable — the warm-start manifest's
    # fusion groups and any harvested memory_analysis() — against it
    # and WARN before the first training step.
    try:
        from ..memory import planner as _mem_planner

        if _mem_oom.advertised_capacity() is not None:
            # Per-DEVICE figures against the per-device capacity: the
            # manifest's device-bytes peak (not the 2·world global
            # model) and the harvest's own per-executable analysis
            # (XLA reports per-device numbers).
            man = (_mem_planner.manifest_section(cache_dir)
                   if cache_dir else {})
            harv = _mem_planner.harvest_section()
            predicted = max(
                int(man.get("peak_group_device_bytes") or 0),
                int(harv.get("peak_executable_bytes") or 0))
            if predicted:
                _mem_oom.preflight_warn(
                    predicted, "hvd.init",
                    "largest recorded executable footprint "
                    "(per-device)")
    except Exception:  # noqa: BLE001 — pre-flight must not break init
        pass


def _configure_compile_cache(directory: str) -> None:
    """Point jax's persistent XLA compilation cache at ``directory``
    (idempotent; thresholds dropped to zero so even small steady-state
    executables — the megakernels — persist).  Unknown options on older
    jax are skipped: the cache is an optimization, never a hard dep."""
    os.makedirs(directory, exist_ok=True)
    for option, value in (
            ("jax_compilation_cache_dir", directory),
            ("jax_persistent_cache_min_compile_time_secs", 0),
            ("jax_persistent_cache_min_entry_size_bytes", 0)):
        try:
            jax.config.update(option, value)
        except (AttributeError, ValueError):  # pragma: no cover - old jax
            pass


def shutdown() -> None:
    """Cooperative shutdown (≙ operations.cc:1377-1442, :1456-1474).

    Protocol: notify the peers (worker → SHUTDOWN frame to the
    controller; controller → SHUTDOWN response broadcast), then flush
    every still-pending async collective with the reference's
    SHUT_DOWN_ERROR so late ``synchronize`` calls raise it, then release
    the runtime.  Launched ops' handles stay valid — XLA owns those.
    """
    # Stop the background drain FIRST so the protocol below can't race an
    # in-flight poll/broadcast on the same sockets and op queue.
    if _state.bg_stop is not None:
        _state.bg_stop.set()
        if _state.bg_thread is not None:
            _state.bg_thread.join(timeout=2.0)
    if _state.initialized:
        from ..ops import collective as _collective

        with _collective._drain_lock:
            if (_state.multiprocess and _state.transport is not None
                    and _state.process_index != 0):
                # Drain responses the stopped background thread never got
                # to — a dead-peer SHUTDOWN diagnosis may be queued, and
                # executing it here still disarms jax's exit barrier
                # (otherwise this rank would exit armed and block on the
                # dead peer).
                while True:
                    resps = _state.transport.poll_responses()
                    if resps is None:
                        break
                    for resp in resps:
                        _collective._execute_response(
                            resp, _collective._queue.take(resp.tensor_names))
                try:
                    _state.transport.request_shutdown()
                except OSError:
                    pass  # controller already gone
            if (_state.multiprocess and _state.transport is not None
                    and _state.process_index == 0
                    and _state.transport.lost_ranks
                    and not _state.peer_shutdown):
                # A peer death detected after the last drain tick gets the
                # same handling as the drain loop's lost_ranks branch.
                _collective._handle_lost_ranks(_state, _state.transport)
            if not _state.peer_shutdown:
                _collective._initiate_shutdown()
    with _state.lock:
        _state.bg_thread = None
        _state.bg_stop = None
        if _state.autotuner is not None:
            _state.autotuner.close()
        _state.autotuner = None
        _state.tuner = None
        for ps in _state.process_sets.values():
            ps.close()
        _state.process_sets = {}
        # Kernel caches (_kernels/_subset_kernels/_mp_mesh_and_kernels)
        # survive shutdown on purpose: they are keyed on jax Device
        # OBJECTS, so same-backend re-inits (every test) share one XLA
        # compilation while a restarted backend's fresh Device objects
        # miss naturally instead of resurrecting a stale mesh.
        if _state.timeline is not None:
            _state.timeline.close()
            _state.timeline = None
        if _state.metrics_exporter is not None:
            _state.metrics_exporter.close()
            _state.metrics_exporter = None
        if _state.transport is not None:
            _state.transport.close()
            _state.transport = None
        if _state.coordinator is not None:
            _state.coordinator.close()
            _state.coordinator = None
        _state.response_cache = None
        _state.topology = None
        _state.multiprocess = False
        _state.shutdown = True
        _state.initialized = False


def get_process_set(psid: int):
    """The registered ProcessSet for ``psid`` (None when unknown), read
    under the state lock — the registry is mutated by user threads while
    the drain tick and the controller's receive threads read it."""
    with _state.lock:
        return _state.process_sets.get(psid)


def process_sets_snapshot() -> list:
    """Locked snapshot of the registered process sets (same rationale
    as :func:`get_process_set`)."""
    with _state.lock:
        return list(_state.process_sets.values())


def _check_initialized() -> None:
    if not _state.initialized:
        raise NotInitializedError()


def is_initialized() -> bool:
    return _state.initialized


def size() -> int:
    """Global replica (device) count.

    Reference: ``horovod_size`` (operations.cc:1511-1515) returns the
    MPI_COMM_WORLD size; here the replica mesh extent plays that role.
    NOTE: eager collectives average over :func:`contributor_count` (==
    ``size()`` single-process, ``process_count()`` multi-process, where
    each process contributes one tensor like an MPI rank).
    """
    _check_initialized()
    return _state.size


def contributor_count() -> int:
    """Number of independent contributions to an eager collective — the
    ``average=True`` denominator.  Multi-process mode: one per process
    (the reference's one-tensor-per-MPI-rank model).  Single-process: one
    per replica (the ``shard()`` layout)."""
    _check_initialized()
    return _state.process_count if _state.multiprocess else _state.size


def local_size() -> int:
    """Multi-process mode: processes sharing this node (reference:
    horovod_local_size, operations.cc:1523-1527, via
    MPI_Comm_split_type(SHARED), computed here from the hostname exchange
    on the control plane).  Single-process: replicas owned by this
    process."""
    _check_initialized()
    if _state.multiprocess:
        return _state.topology.local_size
    return _state.local_size


def rank() -> int:
    """Multi-process mode: this process's global rank — exact reference
    semantics (horovod_rank, operations.cc:1505-1509).  Single-process:
    first replica owned by this process.  Per-replica code inside traced
    functions should use ``replica_id()`` instead."""
    _check_initialized()
    if _state.multiprocess:
        return _state.process_index
    return _state.process_index * _state.local_size


def local_rank() -> int:
    """Multi-process mode: rank within this node (reference:
    horovod_local_rank, operations.cc:1517-1521).  Single-process: 0."""
    _check_initialized()
    if _state.multiprocess:
        return _state.topology.local_rank
    return 0


def cross_rank() -> int:
    """This node's index among all nodes (one representative per node)."""
    _check_initialized()
    if _state.multiprocess:
        return _state.topology.cross_rank
    return 0


def cross_size() -> int:
    """Number of distinct nodes in the job."""
    _check_initialized()
    if _state.multiprocess:
        return _state.topology.cross_size
    return 1


def process_index() -> int:
    _check_initialized()
    return _state.process_index


def process_count() -> int:
    _check_initialized()
    return _state.process_count


def start_timeline(file_path: str) -> None:
    """Begin (or switch) Chrome-trace timeline recording at runtime
    (≙ the post-v0.13 ``hvd.start_timeline``; the v0.13 reference could
    only enable it via ``HOROVOD_TIMELINE`` at init).  Rank-0-only like
    the env path — other ranks no-op."""
    _check_initialized()
    if _state.process_index != 0:
        return
    from ..ops.collective import _drain_lock
    from ..utils.timeline import Timeline

    with _state.lock:
        old, _state.timeline = _state.timeline, None
        if _state.coordinator is not None:
            _state.coordinator.timeline = None
        for ps in _state.process_sets.values():
            if ps.coordinator is not None:
                ps.coordinator.timeline = None
    if old is not None:
        # The tick period is runtime-adjustable (HOROVOD_CYCLE_TIME /
        # autotune), so a fixed sleep cannot bound an in-flight drain
        # tick — serialize with the drain loop instead.
        with _drain_lock:
            old.close()
    tl = Timeline(file_path)
    with _state.lock:
        _state.timeline = tl
        if _state.coordinator is not None:
            _state.coordinator.timeline = tl
        for ps in _state.process_sets.values():
            if ps.coordinator is not None:
                ps.coordinator.timeline = tl


def stop_timeline() -> None:
    """Stop timeline recording and flush the file (≙ the post-v0.13
    ``hvd.stop_timeline``)."""
    _check_initialized()
    from ..ops.collective import _drain_lock

    with _state.lock:
        tl, _state.timeline = _state.timeline, None
        if _state.coordinator is not None:
            _state.coordinator.timeline = None
        for ps in _state.process_sets.values():
            if ps.coordinator is not None:
                ps.coordinator.timeline = None
    if tl is not None:
        with _drain_lock:  # serialize with an in-flight drain tick
            tl.close()


def mpi_threads_supported() -> bool:
    """API-parity shim.  There is no MPI; multi-threaded host dispatch into
    XLA is always safe, so report True (reference:
    horovod_mpi_threads_supported, operations.cc:1531-1539)."""
    _check_initialized()
    return True


def mesh() -> jax.sharding.Mesh:
    """The global 1-D replica mesh (axis ``"hvd"``)."""
    _check_initialized()
    return _state.mesh


def replica_id():
    """The current replica's id inside traced per-replica code.

    Only valid under ``shard_map``/``pmap`` style tracing over the replica
    axis; this is the true analogue of the reference's per-process rank.
    """
    return jax.lax.axis_index(REPLICA_AXIS)
