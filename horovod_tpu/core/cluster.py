"""Multi-process cluster bootstrap.

TPU-native equivalent of the reference's MPI bootstrap
(``MPI_Init_thread`` + ``MPI_Comm_rank/size`` + the SHARED-memory
communicator split — reference: horovod/common/operations.cc:1173-1196).
The launcher (``python -m horovod_tpu.run``, ≙ ``mpirun -np N``) exports
the ``HVD_TPU_*`` variables below; ``maybe_initialize()`` turns them into
a ``jax.distributed`` cluster, after which every process sees the global
device topology and jitted collectives run SPMD across processes.

Environment contract (set by the launcher, overridable by schedulers):

  HVD_TPU_COORDINATOR      host:port of the jax.distributed rendezvous
  HVD_TPU_NUM_PROCESSES    world size
  HVD_TPU_PROCESS_ID       this process's rank
  HVD_TPU_CONTROLLER_PORT  TCP port of the rank-0 eager-op controller
                           (defaults to rendezvous port + 1)
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Optional


@dataclass(frozen=True)
class ClusterSpec:
    coordinator: str          # host:port for jax.distributed
    num_processes: int
    process_id: int

    @property
    def controller_host(self) -> str:
        return self.coordinator.rsplit(":", 1)[0]

    @property
    def controller_port(self) -> int:
        port = os.environ.get("HVD_TPU_CONTROLLER_PORT")
        if port:
            return int(port)
        if ":" in self.coordinator:
            return int(self.coordinator.rsplit(":", 1)[1]) + 1
        return 29521


def cluster_spec_from_env() -> Optional[ClusterSpec]:
    """Read the launcher contract; None when running single-process."""
    addr = (os.environ.get("HVD_TPU_COORDINATOR")
            or os.environ.get("JAX_COORDINATOR_ADDRESS"))
    n = (os.environ.get("HVD_TPU_NUM_PROCESSES")
         or os.environ.get("JAX_NUM_PROCESSES"))
    pid = (os.environ.get("HVD_TPU_PROCESS_ID")
           or os.environ.get("JAX_PROCESS_ID"))
    if not (addr and n and pid):
        return None
    return ClusterSpec(coordinator=addr, num_processes=int(n),
                       process_id=int(pid))


# Set by disarm_distributed_shutdown: a peer died, the jax.distributed
# client was abandoned, and this process can only exit.
_disarmed = False


def _distributed_is_initialized() -> bool:
    """``jax.distributed.is_initialized()`` with a fallback for jax
    versions that predate it (<= 0.4.x): those expose the same fact via
    the distributed global state's client handle."""
    import jax

    fn = getattr(jax.distributed, "is_initialized", None)
    if fn is not None:
        return bool(fn())
    try:
        from jax._src.distributed import global_state

        return global_state.client is not None
    except Exception:  # noqa: BLE001 — private module moved/renamed
        return False


def maybe_initialize() -> Optional[ClusterSpec]:
    """Initialize ``jax.distributed`` when a cluster env is present.

    Idempotent: if the user already called ``jax.distributed.initialize``
    (or a previous ``hvd.init()`` did), this is a no-op that still reports
    the spec.  Returns None in single-process mode.
    """
    import jax

    if _disarmed:
        raise RuntimeError(
            "horovod_tpu cannot re-initialize: a peer process died and "
            "the jax.distributed cluster was abandoned. Restart the job "
            "(e.g. relaunch via `python -m horovod_tpu.run`).")
    spec = cluster_spec_from_env()
    if spec is None:
        # The user may have initialized jax.distributed directly; honor it.
        # (is_initialized() does not touch the XLA backend.)
        if _distributed_is_initialized() and jax.process_count() > 1:
            return ClusterSpec(
                coordinator=os.environ.get("JAX_COORDINATOR_ADDRESS", ""),
                num_processes=jax.process_count(),
                process_id=jax.process_index())
        return None
    if spec.num_processes > 1 and not _distributed_is_initialized():
        kwargs = dict(
            coordinator_address=spec.coordinator,
            num_processes=spec.num_processes,
            process_id=spec.process_id,
            heartbeat_timeout_seconds=int(
                os.environ.get("HVD_TPU_HEARTBEAT_TIMEOUT", "100")),
            shutdown_timeout_seconds=int(
                os.environ.get("HVD_TPU_SHUTDOWN_TIMEOUT", "300")))
        try:
            jax.distributed.initialize(**kwargs)
        except TypeError:
            # Older jax without the timeout kwargs.
            kwargs.pop("heartbeat_timeout_seconds")
            kwargs.pop("shutdown_timeout_seconds")
            jax.distributed.initialize(**kwargs)
    return spec


def disarm_distributed_shutdown() -> None:
    """Skip ``jax.distributed``'s exit-time shutdown barrier.

    JAX registers an atexit hook (jax/_src/api.py ``clean_up``) that calls
    ``jax.distributed.shutdown()``, which enters a coordination-service
    barrier waiting for EVERY process.  Once we know a peer died without
    reaching that barrier, it can only fail — after blocking the survivor
    for ``heartbeat_timeout_seconds`` (100 s default) and then fatally
    aborting the process (client.h LOG(FATAL)), which also discards
    buffered output.  The reference's equivalent failure mode is an MPI
    job hanging in MPI_Finalize until the scheduler kills it.

    Dropping the client reference makes that atexit hook a no-op so the
    survivor can exit promptly with its diagnosis.  The coordination
    *service* (rank 0 hosts it) is left in place — its shutdown does not
    block on peers.

    After this, the process is expected to exit: the cluster is missing a
    member and cannot be re-formed from within (``jax.distributed`` does
    not support re-initialization), so ``maybe_initialize`` refuses with
    a diagnosis instead of letting jax raise an opaque error.
    """
    global _disarmed
    _disarmed = True
    try:
        from jax._src import distributed as _jd

        state = _jd.global_state
        if getattr(state, "preemption_sync_manager", None) is not None:
            state.preemption_sync_manager.shutdown()
            state.preemption_sync_manager = None
        state.client = None  # leaked deliberately; the process is exiting
    except Exception:  # noqa: BLE001 — best-effort across jax versions
        pass
