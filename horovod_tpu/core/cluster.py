"""Multi-process cluster bootstrap.

TPU-native equivalent of the reference's MPI bootstrap
(``MPI_Init_thread`` + ``MPI_Comm_rank/size`` + the SHARED-memory
communicator split — reference: horovod/common/operations.cc:1173-1196).
The launcher (``python -m horovod_tpu.run``, ≙ ``mpirun -np N``) exports
the ``HVD_TPU_*`` variables below; ``maybe_initialize()`` turns them into
a ``jax.distributed`` cluster, after which every process sees the global
device topology and jitted collectives run SPMD across processes.

Environment contract (set by the launcher, overridable by schedulers):

  HVD_TPU_COORDINATOR      host:port of the jax.distributed rendezvous
  HVD_TPU_NUM_PROCESSES    world size
  HVD_TPU_PROCESS_ID       this process's rank
  HVD_TPU_CONTROLLER_PORT  TCP port of the rank-0 eager-op controller
                           (defaults to rendezvous port + 1)
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Optional


@dataclass(frozen=True)
class ClusterSpec:
    coordinator: str          # host:port for jax.distributed
    num_processes: int
    process_id: int

    @property
    def controller_host(self) -> str:
        return self.coordinator.rsplit(":", 1)[0]

    @property
    def controller_port(self) -> int:
        port = os.environ.get("HVD_TPU_CONTROLLER_PORT")
        if port:
            return int(port)
        if ":" in self.coordinator:
            return int(self.coordinator.rsplit(":", 1)[1]) + 1
        return 29521


def cluster_spec_from_env() -> Optional[ClusterSpec]:
    """Read the launcher contract; None when running single-process."""
    addr = (os.environ.get("HVD_TPU_COORDINATOR")
            or os.environ.get("JAX_COORDINATOR_ADDRESS"))
    n = (os.environ.get("HVD_TPU_NUM_PROCESSES")
         or os.environ.get("JAX_NUM_PROCESSES"))
    pid = (os.environ.get("HVD_TPU_PROCESS_ID")
           or os.environ.get("JAX_PROCESS_ID"))
    if not (addr and n and pid):
        return None
    return ClusterSpec(coordinator=addr, num_processes=int(n),
                       process_id=int(pid))


def maybe_initialize() -> Optional[ClusterSpec]:
    """Initialize ``jax.distributed`` when a cluster env is present.

    Idempotent: if the user already called ``jax.distributed.initialize``
    (or a previous ``hvd.init()`` did), this is a no-op that still reports
    the spec.  Returns None in single-process mode.
    """
    import jax

    spec = cluster_spec_from_env()
    if spec is None:
        # The user may have initialized jax.distributed directly; honor it.
        # (is_initialized() does not touch the XLA backend.)
        if jax.distributed.is_initialized() and jax.process_count() > 1:
            return ClusterSpec(
                coordinator=os.environ.get("JAX_COORDINATOR_ADDRESS", ""),
                num_processes=jax.process_count(),
                process_id=jax.process_index())
        return None
    if spec.num_processes > 1 and not jax.distributed.is_initialized():
        jax.distributed.initialize(
            coordinator_address=spec.coordinator,
            num_processes=spec.num_processes,
            process_id=spec.process_id)
    return spec
