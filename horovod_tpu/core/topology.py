"""Multi-axis device-mesh topology for hybrid parallelism.

The reference's rank space is flat — one MPI_COMM_WORLD axis, because data
parallelism is its only strategy (SURVEY.md §2.3; reference
horovod/common/operations.cc:1176-1196).  A TPU pod is not flat: chips form
a torus of ICI links, and XLA shards programs over an N-dimensional
``jax.sharding.Mesh`` whose named axes map onto that torus.  This module
owns the axis vocabulary and mesh construction for every parallelism
strategy the framework offers beyond the reference's DP:

====== ============================ ======================================
axis   strategy                     what is sharded over it
====== ============================ ======================================
data   data parallel (DP)           batch; gradients psum over it
model  tensor parallel (TP)         weight matrices (heads / hidden dim)
seq    sequence/context par. (SP)   the sequence axis (ring attention)
pipe   pipeline parallel (PP)       transformer layer blocks
expert expert parallel (EP)         MoE experts (all_to_all routing)
====== ============================ ======================================

Axis ordering puts ``data`` outermost (it tolerates the slowest links —
gradient psum once per step, so it can ride DCN across slices) and
``model`` innermost (activations move every layer, so it must sit on the
fastest ICI neighbors).  This is the standard mapping from the public
scaling playbooks; XLA then lowers each collective onto the matching
links.

Expert parallelism conventionally *reuses* the data axis (experts sharded
over DP groups, tokens routed with all_to_all inside them), so ``expert``
only becomes its own mesh axis when explicitly requested.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Optional, Sequence, Tuple

import jax

from . import compat as _compat
import numpy as np

# Canonical axis names.  ``REPLICA_AXIS`` ("hvd") from core.state is the
# degenerate 1-D case used by the Horovod-parity API.
DATA_AXIS = "data"
MODEL_AXIS = "model"
SEQ_AXIS = "seq"
PIPE_AXIS = "pipe"
EXPERT_AXIS = "expert"

# Outermost → innermost mesh order (slowest → fastest links).
_AXIS_ORDER = (DATA_AXIS, PIPE_AXIS, EXPERT_AXIS, SEQ_AXIS, MODEL_AXIS)


@dataclass(frozen=True)
class ParallelConfig:
    """Degrees of each parallelism strategy.

    Any degree may be 1 (strategy disabled).  The product of all degrees
    must equal the number of devices the mesh is built over.  ``expert``
    defaults to 0 = "ride the data axis" (the conventional EP placement);
    set it >0 for a dedicated expert mesh axis.
    """

    data: int = 1
    model: int = 1
    seq: int = 1
    pipe: int = 1
    expert: int = 0

    @property
    def device_count(self) -> int:
        n = self.data * self.model * self.seq * self.pipe
        return n * (self.expert if self.expert > 0 else 1)

    def axis_sizes(self) -> dict:
        sizes = {DATA_AXIS: self.data, PIPE_AXIS: self.pipe,
                 SEQ_AXIS: self.seq, MODEL_AXIS: self.model}
        if self.expert > 0:
            sizes[EXPERT_AXIS] = self.expert
        return sizes


def _resolve(config, devices, degrees):
    if config is None:
        config = ParallelConfig(**degrees)
    elif degrees:
        raise TypeError("pass either a ParallelConfig or keyword degrees, "
                        "not both")
    devs = list(devices if devices is not None else jax.devices())
    if config.device_count != len(devs):
        raise ValueError(
            f"parallel config {config} needs {config.device_count} devices "
            f"but {len(devs)} were provided")
    return config, devs


def make_mesh(config: Optional[ParallelConfig] = None,
              devices: Optional[Sequence] = None,
              **degrees) -> jax.sharding.Mesh:
    """Build the multi-axis device mesh for a parallel configuration.

    Either pass a :class:`ParallelConfig` or axis degrees as keywords::

        mesh = make_mesh(data=2, model=2, seq=2)   # 8 devices

    Axes with degree 1 are still present in the mesh (size-1 axes are free)
    so the same model code works at any configuration.  Devices default to
    ``jax.devices()``; their count must equal the product of the degrees.
    """
    config, devs = _resolve(config, devices, degrees)
    sizes = config.axis_sizes()
    names = tuple(a for a in _AXIS_ORDER if a in sizes)
    shape = tuple(sizes[a] for a in names)
    arr = np.asarray(devs).reshape(shape)
    return jax.sharding.Mesh(arr, names)


def _hybrid_layout(devs, slice_of, names, sizes, dcn_factor) -> np.ndarray:
    """Explicit hybrid device layout: outer (DCN) blocks of each split
    axis cross slices, inner (ICI) blocks stay inside one slice — the
    same placement contract ``mesh_utils.create_hybrid_device_mesh``
    implements from hardware attributes, but computed from a declared
    slice assignment so it works with ANY devices (CPU test meshes,
    overridden topologies)."""
    groups: dict = {}
    for d in devs:
        groups.setdefault(slice_of(d), []).append(d)
    slice_ids = sorted(groups)
    if len({len(g) for g in groups.values()}) != 1:
        raise ValueError(
            f"slices must be equal-sized; got "
            f"{ {s: len(g) for s, g in groups.items()} }")
    shape = tuple(sizes[a] for a in names)
    ici_shape = [sizes[a] // dcn_factor.get(a, 1) for a in names]
    dcn_shape = [dcn_factor.get(a, 1) for a in names]
    arr = np.empty(shape, dtype=object)
    for idx in np.ndindex(shape):
        dcn_coord = [i // m for i, m in zip(idx, ici_shape)]
        ici_coord = [i % m for i, m in zip(idx, ici_shape)]
        sid = int(np.ravel_multi_index(dcn_coord, dcn_shape))
        wid = int(np.ravel_multi_index(ici_coord, ici_shape))
        arr[idx] = groups[slice_ids[sid]][wid]
    return arr


def make_hybrid_mesh(config: Optional[ParallelConfig] = None,
                     devices: Optional[Sequence] = None,
                     dcn_axes: Tuple[str, ...] = (DATA_AXIS,),
                     slice_map=None,
                     **degrees) -> jax.sharding.Mesh:
    """Build a mesh for a multi-slice (DCN-connected) TPU deployment.

    On a multi-slice pod, chips within a slice talk over ICI; slices talk
    over DCN.  The scaling recipe is to put the gradient-sync axes
    (``data``, and ``pipe`` when microbatches amortize it) across DCN —
    they communicate once per step — and keep every per-layer axis
    (``model``/``seq``/``expert``) inside a slice on ICI.  This wraps
    ``jax.experimental.mesh_utils.create_hybrid_device_mesh`` so the
    device order actually honors that placement; on single-slice (or CPU
    test) topologies it degrades to :func:`make_mesh` unchanged.

    ``dcn_axes`` lists the axes to lay across slices (outermost first).
    A DCN axis whose degree exceeds its share of the slice count is split
    between DCN and ICI — e.g. 2 slices x 4 chips with ``data=4, model=2``
    puts a 2-way data factor across DCN and a 2-way data factor on ICI
    inside each slice (the standard multi-slice DP recipe).

    ``slice_map`` overrides slice detection: a callable ``device →
    slice id`` or a ``device.id → slice id`` mapping.  Use it when the
    runtime misreports the topology — or to exercise the hybrid layout
    end-to-end on hardware without slices (the test suite trains over
    8 CPU devices declared as 2 virtual slices).
    """
    import math

    config, devs = _resolve(config, devices, degrees)

    if slice_map is not None:
        slice_of = slice_map if callable(slice_map) \
            else (lambda d: slice_map[d.id])
    else:
        slice_of = lambda d: getattr(d, "slice_index", 0)  # noqa: E731
    num_slices = len({slice_of(d) for d in devs})
    if num_slices <= 1:
        return make_mesh(config, devices=devs)

    sizes = config.axis_sizes()
    names = tuple(a for a in _AXIS_ORDER if a in sizes)
    for a in dcn_axes:
        if a not in names:
            raise ValueError(f"dcn axis {a!r} not in mesh axes {names}")
    # Split each DCN axis's degree into (cross-slice, in-slice) factors,
    # outermost first, until the slices are exactly tiled.
    remaining = num_slices
    dcn_factor = {}
    for a in dcn_axes:
        f = math.gcd(sizes[a], remaining)
        dcn_factor[a] = f
        remaining //= f
    if remaining != 1:
        raise ValueError(
            f"DCN axes {dcn_axes} with degrees "
            f"{[sizes[a] for a in dcn_axes]} cannot tile {num_slices} "
            f"slices; the cross-slice axes must tile the slices exactly.")
    if slice_map is not None:
        arr = _hybrid_layout(devs, slice_of, names, sizes, dcn_factor)
        return jax.sharding.Mesh(arr, names)
    from jax.experimental import mesh_utils

    mesh_shape = [sizes[a] // dcn_factor.get(a, 1) for a in names]
    dcn_shape = [dcn_factor.get(a, 1) for a in names]
    arr = mesh_utils.create_hybrid_device_mesh(
        mesh_shape, dcn_shape, devices=devs,
        allow_split_physical_axes=True)
    return jax.sharding.Mesh(arr, names)


# ---------------------------------------------------------------------------
# Replica-axis ICI x DCN hierarchy (the eager data plane's view of a
# multi-slice deployment)
# ---------------------------------------------------------------------------
# The eager collective path runs over the flat 1-D replica mesh; on a
# multi-slice pod that flatness hides a 2-level link topology — chips
# inside a slice talk over ICI, slices talk over DCN, and DCN is an
# order of magnitude slower.  A flat psum over the replica axis makes
# XLA move every byte across DCN n_slices times; the bandwidth-optimal
# decomposition is psum_scatter over ICI -> psum over DCN (1/ici_size
# of the bytes) -> all_gather over ICI, optionally quantizing the DCN
# leg only (cf. EQuARX, arXiv:2506.17615).  This block derives that
# hierarchy as axis_index_groups over the SAME flat replica axis, so
# the megakernel executor (ops/megakernel.py) can lower hierarchical
# collectives without re-meshing anything.
#
# Env contract (docs/performance.md):
#   HVD_TPU_HIERARCHICAL=auto|on|off   auto (default): hierarchical when
#                                      real multi-slice topology is
#                                      detected; on: also honor declared
#                                      virtual slices; off: always flat.
#   HVD_TPU_VIRTUAL_SLICES=<k>         declare k equal contiguous virtual
#                                      slices (CPU dryrun meshes / tests
#                                      / topology overrides).
HIERARCHICAL_ENV = "HVD_TPU_HIERARCHICAL"
VIRTUAL_SLICES_ENV = "HVD_TPU_VIRTUAL_SLICES"


def hierarchical_mode() -> str:
    mode = os.environ.get(HIERARCHICAL_ENV, "auto").lower()
    if mode not in ("auto", "on", "off"):
        raise ValueError(
            f"{HIERARCHICAL_ENV}={mode!r}: expected auto, on or off")
    return mode


def validate_env() -> None:
    """Fail ``hvd.init()`` — not the first collective — on malformed
    topology knobs.  These select the compiled SPMD program, so they
    must also be UNIFORM across ranks; the control-plane handshake
    cross-checks the combined fingerprint
    (ops/compression.env_fingerprint)."""
    hierarchical_mode()
    value = os.environ.get(VIRTUAL_SLICES_ENV)
    if value:
        try:
            int(value)
        except ValueError:
            raise ValueError(
                f"{VIRTUAL_SLICES_ENV}={value!r}: expected an "
                f"integer") from None


@dataclass(frozen=True)
class ReplicaHierarchy:
    """ICI x DCN decomposition of a flat replica axis of n devices.

    ``ici_groups``: one group per slice (positions along the replica
    axis); ``dcn_groups``: one group per in-slice position, pairing the
    k-th chip of every slice — together they express the two-level
    reduction as grouped collectives over the unchanged 1-D mesh.
    """

    n_slices: int
    ici_size: int
    ici_groups: Tuple[Tuple[int, ...], ...]
    dcn_groups: Tuple[Tuple[int, ...], ...]

    def slice_of_positions(self) -> Tuple[int, ...]:
        """Slice ordinal of every replica-axis position — the static
        lookup table quantized hierarchical kernels index with
        ``lax.axis_index`` to derive their per-leg noise/chunk
        coordinates (ops/megakernel.py)."""
        table = [0] * (self.n_slices * self.ici_size)
        for si, group in enumerate(self.ici_groups):
            for pos in group:
                table[pos] = si
        return tuple(table)


def replica_hierarchy(devices: Sequence) -> Optional[ReplicaHierarchy]:
    """The ICI x DCN hierarchy of ``devices`` (mesh order), or ``None``
    when the topology is flat / undecomposable / disabled.

    Real slice membership comes from ``device.slice_index`` (multi-slice
    runtimes); ``HVD_TPU_VIRTUAL_SLICES`` + ``HVD_TPU_HIERARCHICAL=on``
    declares contiguous virtual slices for dryrun meshes.  Unequal slice
    sizes degrade to flat — the grouped collectives need a rectangular
    decomposition.
    """
    mode = hierarchical_mode()
    if mode == "off":
        return None
    n = len(devices)
    if n < 2:
        return None
    slice_ids = [getattr(d, "slice_index", None) for d in devices]
    by_slice: dict = {}
    if any(s is not None for s in slice_ids) and len(
            {s for s in slice_ids if s is not None}) > 1:
        for pos, sid in enumerate(slice_ids):
            by_slice.setdefault(sid, []).append(pos)
    elif mode == "on":
        k = int(os.environ.get(VIRTUAL_SLICES_ENV, "0") or 0)
        if k > 1 and n % k == 0:
            ici = n // k
            by_slice = {s: list(range(s * ici, (s + 1) * ici))
                        for s in range(k)}
    if len(by_slice) < 2:
        return None
    sizes = {len(g) for g in by_slice.values()}
    if len(sizes) != 1:
        return None  # ragged slices: no rectangular decomposition
    ici_groups = tuple(tuple(by_slice[s]) for s in sorted(by_slice))
    ici = len(ici_groups[0])
    dcn_groups = tuple(tuple(g[i] for g in ici_groups)
                       for i in range(ici))
    return ReplicaHierarchy(
        n_slices=len(ici_groups), ici_size=ici,
        ici_groups=ici_groups, dcn_groups=dcn_groups)


def axis_size(axis: str) -> int:
    """Extent of ``axis`` inside traced code (static under shard_map)."""
    return _compat.axis_size(axis)


def axis_index(axis: str):
    """This shard's coordinate along ``axis`` inside traced code."""
    return jax.lax.axis_index(axis)


def mesh_axis_sizes(mesh: jax.sharding.Mesh) -> dict:
    return dict(zip(mesh.axis_names, mesh.devices.shape))


def validate_mesh(mesh: jax.sharding.Mesh,
                  required_axes: Tuple[str, ...]) -> None:
    """Raise with a clear message when a strategy is used on a mesh that
    lacks its axis (the analogue of the reference coordinator's explicit
    mismatch errors, operations.cc:255-461 — fail loudly, not with a
    compiler backtrace)."""
    missing = [a for a in required_axes if a not in mesh.axis_names]
    if missing:
        raise ValueError(
            f"mesh with axes {mesh.axis_names} is missing required "
            f"axes {missing}; build it with horovod_tpu.core.topology."
            f"make_mesh(...)")
