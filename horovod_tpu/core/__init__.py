"""horovod_tpu.core"""
