"""Version-compat shims over the jax API surface.

The runtime targets current jax (``jax.shard_map`` with ``check_vma``),
but containers pin older releases where the transform still lives at
``jax.experimental.shard_map.shard_map`` and the replication checker is
named ``check_rep`` (renamed in jax 0.6).  Everything routes through
:func:`shard_map` so the version split lives in exactly one place.
"""

from __future__ import annotations

import jax

try:  # jax >= 0.6: public symbol with check_vma
    _NEW_SHARD_MAP = getattr(jax, "shard_map", None)
except Exception:  # noqa: BLE001 — deprecation shims can raise oddly
    _NEW_SHARD_MAP = None


def shard_map(fn, *, mesh, in_specs, out_specs, check_vma: bool = True):
    """``jax.shard_map`` with the ``check_vma`` spelling on every
    supported jax version."""
    if _NEW_SHARD_MAP is not None:
        return _NEW_SHARD_MAP(fn, mesh=mesh, in_specs=in_specs,
                              out_specs=out_specs, check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as _sm

    return _sm(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               check_rep=check_vma)


def axis_size(axis_name) -> int:
    """``jax.lax.axis_size`` (jax >= 0.5); older jax exposes the same
    static extent through ``jax.core.axis_frame`` (which returns the
    bare size int on 0.4.x)."""
    fn = getattr(jax.lax, "axis_size", None)
    if fn is not None:
        return fn(axis_name)
    frame = jax.core.axis_frame(axis_name)
    return getattr(frame, "size", frame)


def scan(body, init, xs, length=None):
    """``lax.scan`` for loops that must differentiate inside a
    ``shard_map``: jax 0.4.x's experimental shard_map cannot transpose
    scan under ``check_rep=False`` (a ``_SpecError`` on the carry), so
    on those versions the loop unrolls — same math, larger XLA program.
    Current jax gets the real scan."""
    if _NEW_SHARD_MAP is not None:
        return jax.lax.scan(body, init, xs, length=length)
    import jax.numpy as jnp

    leaves = jax.tree_util.tree_leaves(xs)
    n = int(length) if length is not None else int(leaves[0].shape[0])
    carry = init
    ys = []
    for i in range(n):
        xi = jax.tree_util.tree_map(lambda a: a[i], xs) \
            if leaves else xs
        carry, y = body(carry, xi)
        ys.append(y)
    if not ys or all(y is None for y in ys):
        stacked = None
    else:
        stacked = jax.tree_util.tree_map(
            lambda *a: jnp.stack(a), *ys)
    return carry, stacked
