"""Lockset (Eraser-style) data-race detector for the runtime's shared
objects.

Fourth pass of the ``hvd-analyze`` subsystem (docs/analysis.md).  The
lint pass already checks ``# guarded_by:`` annotations *lexically* —
accesses it can type statically, inside a literal ``with lock:`` block.
This module enforces the same annotations *dynamically*: with
``HVD_TPU_RACE_CHECK=1`` in the environment at import time, the
:func:`race_checked` class decorator (applied to the runtime's shared
classes — coordinator, transports, tree overlay, response cache,
serving scheduler/KV cache, telemetry registry, memory ledger, trace
clock) replaces every annotated field with a tracking descriptor and
runs the classic Eraser state machine per (instance, field):

* **first-touch exemption** — while only the creating thread has ever
  touched a field, no locks are required (``__init__`` and
  single-threaded phases are silent);
* **read-share state** — a second thread *reading* moves the field to
  the shared state and initializes its **candidate lockset** to the
  locks that thread holds; every later access intersects the lockset
  with the accessor's held locks;
* **shared-modified** — a write from any thread other than the first
  makes the field shared-modified; if the candidate lockset is (or
  becomes) empty there, the access is a data race: no single lock
  protected every access.

A race raises :class:`DataRaceError` in the accessing thread, naming
the class.field, the annotated lock, BOTH threads and both stack
tails, and flight-records the event (``telemetry/flight.py``) with the
standard metrics tail so post-mortem dumps are self-contained.

Held-lock identity comes from the lock-order detector's thread-local
acquisition stack (``analysis/lockorder.py``) — the two checkers share
one switchboard: arming ``HVD_TPU_RACE_CHECK=1`` only observes locks
created as checked locks, so the race-check legs run with
``HVD_TPU_LOCK_CHECK=1`` as well (tests/conftest.py arms both).  Like
the lock-order graph, locksets are lock-NAME keyed: two instances'
``_lock`` of the same class are one name, so the checker proves the
locking *discipline*, not one instance's interleaving.

Zero overhead when disarmed: :func:`race_checked` returns the class
untouched unless the env was set when the class was defined.
"""

from __future__ import annotations

import os
import threading
import traceback
from typing import Dict, Optional, Set

from . import lockorder as _lockorder

_RACE_ENV = "HVD_TPU_RACE_CHECK"

# Eraser states (per instance x field).
_EXCLUSIVE = 0       # only the first-touch thread has ever accessed
_SHARED = 1          # >= 2 threads, reads only since the transition
_SHARED_MOD = 2      # >= 2 threads with at least one non-owner write
_REPORTED = 3        # race already raised once; stay quiet after

_STATE_SLOT = "_hvd_race_states"

# Serializes state-machine transitions.  Deliberately a plain lock
# (the checker cannot check itself) and a leaf: nothing is acquired
# while holding it.
_machine_lock = threading.Lock()


class DataRaceError(RuntimeError):
    """Two threads accessed a ``# guarded_by:`` field with no common
    lock held (candidate-lockset intersection became empty on a
    write-shared field)."""


def enabled() -> bool:
    """True when HVD_TPU_RACE_CHECK=1 (read per call so tests can flip
    it before defining the classes under test)."""
    return os.environ.get(_RACE_ENV) == "1"


# Slow-path verification count.  A plain int bumped under
# ``_machine_lock`` — NOT a telemetry Counter: the registry's own
# fields are race-checked, so the checker calling ``counter().inc()``
# would re-enter ``MetricsRegistry._metric`` while a registry method
# already holds ``MetricsRegistry._lock`` (self-deadlock).  Telemetry
# PULLS this via its ``analysis`` collector instead
# (``analysis.race_checks`` gauge, telemetry/__init__.py).
_n_checks = 0


def check_count() -> int:
    """Total slow-path lockset verifications (telemetry pull side)."""
    return _n_checks


def _tail(limit: int = 5) -> str:
    """Short innermost-stack tail outside this module (race reports
    name where each thread touched the field, not the descriptor)."""
    frames = [f for f in traceback.extract_stack(limit=limit + 4)
              if "analysis/races" not in f.filename.replace("\\", "/")]
    return " <- ".join(f"{os.path.basename(f.filename)}:{f.lineno}"
                       f"({f.name})" for f in reversed(frames[-limit:]))


def _held_names() -> Set[str]:
    return set(_lockorder._held_stack())


class _FieldState:
    __slots__ = ("state", "owner", "lockset", "peer_thread", "peer_tail",
                 "peer_write")

    def __init__(self, owner: int) -> None:
        self.state = _EXCLUSIVE
        self.owner = owner
        self.lockset: Optional[Set[str]] = None
        # The most recent access from a DIFFERENT thread than the
        # current accessor — the "other side" a race report names.
        self.peer_thread = ""
        self.peer_tail = ""
        self.peer_write = False


def _raise_race(cls_name: str, fld: str, lock: str, write: bool,
                peer_thread: str, peer_tail: str,
                peer_write: bool) -> None:
    """Flight-record + raise.  Runs OUTSIDE the state-machine lock (the
    flight dump walks the metrics registry, whose fields are themselves
    race-checked — calling out while holding ``_machine_lock`` would
    order it against every registry lock)."""
    me = threading.current_thread().name
    kind = "write" if write else "read"
    peer_kind = "write" if peer_write else "read"
    msg = (f"data race on {cls_name}.{fld} (guarded_by {lock!r}): "
           f"{kind} by thread {me!r} at [{_tail()}] with no lock in "
           f"common with the {peer_kind} by thread "
           f"{peer_thread!r} at [{peer_tail}] — the candidate "
           f"lockset is empty, so no single lock ordered these "
           f"accesses")
    try:
        from ..telemetry import flight as _flight

        _flight.record("data_race", f"{cls_name}.{fld}", lock, me,
                       peer_thread)
        _flight.dump("data-race", extra={
            "field": f"{cls_name}.{fld}", "guarded_by": lock,
            "thread": me, "peer_thread": peer_thread,
            "tail": _tail(), "peer_tail": peer_tail})
    except Exception:  # noqa: BLE001 — forensics only
        pass
    raise DataRaceError(msg)


# Reentrancy guard: the checker's own slow path calls out to telemetry
# and the flight recorder, whose classes are race-checked too — those
# nested accesses must observe, not re-enter, the state machine.
_tls = threading.local()


def _check(obj, fld: str, lock: str, cls_name: str, write: bool) -> None:
    tid = threading.get_ident()
    states: Dict[str, _FieldState] = obj.__dict__.get(_STATE_SLOT)  # type: ignore[assignment]
    if states is None:
        states = obj.__dict__.setdefault(_STATE_SLOT, {})
    s = states.get(fld)
    if s is None:
        with _machine_lock:
            s = states.setdefault(fld, _FieldState(tid))
        if s.owner == tid:
            return
    # Fast path: first-touch thread while still exclusive.
    if s.state == _EXCLUSIVE and s.owner == tid:
        return
    if s.state == _REPORTED:
        return
    if getattr(_tls, "in_check", False):
        return
    global _n_checks
    _tls.in_check = True
    try:
        held = _held_names()
        race = None  # (peer_thread, peer_tail, peer_write)
        with _machine_lock:
            _n_checks += 1
            if s.state == _REPORTED:
                return
            me = threading.current_thread().name
            if s.state == _EXCLUSIVE:
                if s.owner == tid:
                    return
                # Second thread: leave first-touch, seed the candidate
                # lockset from THIS access's held locks.
                s.lockset = set(held)
                s.state = _SHARED_MOD if write else _SHARED
                if write and not s.lockset:
                    # Unlocked write racing the first-touch thread: the
                    # peer side is the (unknown-stack) owner.
                    s.state = _REPORTED
                    race = (f"<first-touch thread {s.owner}>", "?", True)
                else:
                    s.peer_thread = me
                    s.peer_tail = _tail()
                    s.peer_write = write
            else:
                assert s.lockset is not None
                s.lockset &= held
                if write and s.state == _SHARED:
                    s.state = _SHARED_MOD
                if s.state == _SHARED_MOD and not s.lockset:
                    s.state = _REPORTED
                    race = (s.peer_thread, s.peer_tail, s.peer_write)
                elif me != s.peer_thread:
                    s.peer_thread = me
                    s.peer_tail = _tail()
                    s.peer_write = write
        if race is not None:
            _raise_race(cls_name, fld, lock, write, *race)
    finally:
        _tls.in_check = False


class _TrackedField:
    """Data descriptor standing in for one ``# guarded_by:`` field; the
    value itself lives in the instance ``__dict__`` under the same
    name (data descriptors take precedence on both get and set)."""

    __slots__ = ("fld", "lock", "cls_name", "default", "has_default")

    def __init__(self, fld: str, lock: str, cls_name: str,
                 default=None, has_default: bool = False) -> None:
        self.fld = fld
        self.lock = lock
        self.cls_name = cls_name
        self.default = default
        self.has_default = has_default

    def __get__(self, obj, objtype=None):
        if obj is None:
            # Class-level read (dataclass machinery, introspection).
            if self.has_default:
                return self.default
            return self
        _check(obj, self.fld, self.lock, self.cls_name, write=False)
        try:
            return obj.__dict__[self.fld]
        except KeyError:
            if self.has_default:
                return self.default
            raise AttributeError(
                f"{self.cls_name!r} object has no attribute "
                f"{self.fld!r}") from None

    def __set__(self, obj, value) -> None:
        _check(obj, self.fld, self.lock, self.cls_name, write=True)
        obj.__dict__[self.fld] = value

    def __delete__(self, obj) -> None:
        _check(obj, self.fld, self.lock, self.cls_name, write=True)
        try:
            del obj.__dict__[self.fld]
        except KeyError:
            raise AttributeError(self.fld) from None


def _annotated_fields(cls) -> Dict[str, str]:
    """``field -> lock`` from the class's ``# guarded_by:`` comments,
    resolved through the lint pass's scanner over the defining module's
    source (one parse per module, cached)."""
    import inspect
    import sys

    mod = sys.modules.get(cls.__module__)
    if mod is None:
        return {}
    cache = getattr(mod, "_hvd_race_scan_cache", None)
    if cache is None:
        from . import lint as _lint

        try:
            source = inspect.getsource(mod)
        except (OSError, TypeError):
            cache = {}
        else:
            fi = _lint._scan_file(getattr(mod, "__file__", "<mod>"),
                                  source)
            cache = {name: dict(ci.guarded)
                     for name, ci in (fi.classes if fi else {}).items()}
        try:
            mod._hvd_race_scan_cache = cache
        except Exception:  # noqa: BLE001 — frozen/odd modules
            pass
    return dict(cache.get(cls.__name__, {}))


def race_checked(cls):
    """Class decorator arming the lockset checker on every
    ``# guarded_by:`` field of ``cls``.  A no-op (returns ``cls``
    unchanged, zero overhead) unless ``HVD_TPU_RACE_CHECK=1`` was set
    when the class was defined — the same creation-time convention as
    :func:`analysis.lockorder.make_lock`.  Apply ABOVE ``@dataclass``
    so the descriptors install after the dataclass machinery ran."""
    if not enabled():
        return cls
    for fld, lock in _annotated_fields(cls).items():
        default = cls.__dict__.get(fld)
        has_default = (fld in cls.__dict__
                       and not hasattr(default, "__get__"))
        setattr(cls, fld, _TrackedField(
            fld, lock, cls.__name__, default=default,
            has_default=has_default))
    return cls


def states_of(obj) -> Dict[str, int]:
    """The per-field Eraser states of one instance (tests)."""
    return {k: v.state
            for k, v in (obj.__dict__.get(_STATE_SLOT) or {}).items()}
