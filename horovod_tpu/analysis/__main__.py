"""``python -m horovod_tpu.analysis`` — run the lint pass (see lint.py)."""

import sys

from . import main

sys.exit(main())
