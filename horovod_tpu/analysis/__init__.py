"""hvd-analyze — static + trace-time correctness tooling for horovod_tpu.

Three cooperating passes (docs/analysis.md):

* :mod:`.program` — trace-time collective-program signature verifier:
  :func:`verify_program` proves cross-rank agreement of the traced
  collective program over the control plane *before* any data-plane
  work, and :class:`ProgramTracker` does the same automatically inside
  the coordinator's negotiation path (``HVD_TPU_VERIFY_PROGRAM=1``).
* :mod:`.lint` — AST lint pass over the codebase itself
  (``python -m horovod_tpu.analysis [--strict] [paths]``): guarded_by
  lock discipline, blocking calls under locks, rank-conditioned
  collectives.
* :mod:`.lockorder` — runtime lock-order (inversion) detector
  (``HVD_TPU_LOCK_CHECK=1``): every internal runtime lock is created
  through its factories; an acquisition closing a cycle in the global
  lock-order graph raises :class:`~.lockorder.LockOrderError`
  immediately, in whichever single-threaded test first exhibits the
  ordering.
"""

from .lint import Finding, lint_paths, lint_sources  # noqa: F401
from .lockorder import (  # noqa: F401
    CheckedLock,
    CheckedRLock,
    LockOrderError,
    make_lock,
    make_rlock,
)
from .program import (  # noqa: F401
    ProgramRecorder,
    ProgramReport,
    ProgramTracker,
    SignatureEntry,
    collective_source,
    compare_signatures,
    record_collective,
    verify_program,
)


def main(argv=None) -> int:
    """CLI: lint the given paths (default: the horovod_tpu package)."""
    import argparse
    import os
    import sys

    parser = argparse.ArgumentParser(
        prog="python -m horovod_tpu.analysis",
        description="Lock-discipline + SPMD-divergence linter "
                    "(hvd-analyze pass 2).")
    parser.add_argument("paths", nargs="*",
                        help="files or directories to lint "
                             "(default: the horovod_tpu package)")
    parser.add_argument("--strict", action="store_true",
                        help="exit 1 when any finding is reported")
    args = parser.parse_args(argv)
    paths = args.paths or [os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))]
    findings = lint_paths(paths)
    for f in findings:
        print(f.render())
    print(f"hvd-analyze lint: {len(findings)} finding(s) over "
          f"{', '.join(paths)}", file=sys.stderr)
    if findings and args.strict:
        return 1
    return 0
