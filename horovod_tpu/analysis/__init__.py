"""hvd-analyze — static + trace-time correctness tooling for horovod_tpu.

Five cooperating passes (docs/analysis.md):

* :mod:`.program` — trace-time collective-program signature verifier:
  :func:`verify_program` proves cross-rank agreement of the traced
  collective program over the control plane *before* any data-plane
  work, and :class:`ProgramTracker` does the same automatically inside
  the coordinator's negotiation path (``HVD_TPU_VERIFY_PROGRAM=1``).
* :mod:`.lint` — AST lint pass over the codebase itself
  (``python -m horovod_tpu.analysis [--strict] [paths]``): guarded_by
  lock discipline, blocking calls under locks, rank-conditioned
  collectives — plus the stale-waiver audit: a ``# lint: ok(...)``
  waiver no pass still needs is itself a finding.
* :mod:`.lockorder` — runtime lock-order (inversion) detector
  (``HVD_TPU_LOCK_CHECK=1``): every internal runtime lock is created
  through its factories; an acquisition closing a cycle in the global
  lock-order graph raises :class:`~.lockorder.LockOrderError`
  immediately, in whichever single-threaded test first exhibits the
  ordering.
* :mod:`.races` — Eraser-style lockset data-race detector
  (``HVD_TPU_RACE_CHECK=1``): ``# guarded_by:`` annotations become
  tracking descriptors on the runtime's shared classes; an access
  pattern no single lock protects raises
  :class:`~.races.DataRaceError` naming the field, both threads, and
  both stack tails.  The same switch arms :mod:`.threads` dynamic
  role asserts (``# thread: <role>`` contracts).
* :mod:`.donation` — donation-lifetime sanitizer
  (``HVD_TPU_DONATION_CHECK=1`` for the runtime registry; the
  post-donation-read rule runs in the CLI): stale reads of
  ``donate_argnums`` buffers raise :class:`~.donation.DonationError`
  naming the executable, argument index, and donation site instead of
  XLA's opaque deletion error.

The CLI (``python -m horovod_tpu.analysis``) runs every static rule —
lint, thread-role, post-donation-read, stale-waiver — over the given
paths; ``--strict`` (CI's ``lint-analysis`` job) exits 1 on any
finding.
"""

from typing import Dict, List

from .donation import (  # noqa: F401
    DonationError,
    PoisonedBuffer,
    guard_dispatch,
)
from .lint import Finding, lint_paths, lint_sources  # noqa: F401
from .lockorder import (  # noqa: F401
    CheckedLock,
    CheckedRLock,
    LockOrderError,
    make_lock,
    make_rlock,
)
from .program import (  # noqa: F401
    ProgramRecorder,
    ProgramReport,
    ProgramTracker,
    SignatureEntry,
    collective_source,
    compare_signatures,
    record_collective,
    verify_program,
)
from .races import DataRaceError, race_checked  # noqa: F401
from .threads import ThreadRoleError  # noqa: F401


def analyze_sources(sources: Dict[str, str]) -> List[Finding]:
    """Run every static pass — lint rules, thread-role,
    post-donation-read — over one shared scan of ``{path: source}``,
    then audit the waivers: a ``# lint: ok(...)`` line no pass used to
    suppress a finding is reported as **stale-waiver** (waivers must
    not outlive the finding they excuse)."""
    from . import donation as _donation
    from . import lint as _lint
    from . import threads as _threads

    infos = _lint.scan_sources(sources)
    findings = _lint.lint_infos(infos)
    findings += _threads.check_infos(infos)
    findings += _donation.check_infos(infos)
    for fi in infos.values():
        for line, reason in sorted(fi.waivers.items()):
            if line not in fi.used_waivers:
                findings.append(Finding(
                    fi.path, line, "stale-waiver",
                    f"waiver `# lint: ok({reason})` suppresses nothing "
                    f"— no rule fires on this line any more; delete "
                    f"the waiver so a future regression here cannot "
                    f"hide behind it"))
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings


def analyze_paths(paths: List[str]) -> List[Finding]:
    from . import lint as _lint

    sources: Dict[str, str] = {}
    for path in _lint._iter_py_files(paths):
        try:
            with open(path, "r", encoding="utf-8") as f:
                sources[path] = f.read()
        except OSError:
            continue
    return analyze_sources(sources)


def main(argv=None) -> int:
    """CLI: run every static pass over the given paths (default: the
    horovod_tpu package)."""
    import argparse
    import os
    import sys

    parser = argparse.ArgumentParser(
        prog="python -m horovod_tpu.analysis",
        description="Static correctness passes: lock discipline, SPMD "
                    "divergence, thread-role contracts, post-donation "
                    "reads, stale waivers.")
    parser.add_argument("paths", nargs="*",
                        help="files or directories to analyze "
                             "(default: the horovod_tpu package)")
    parser.add_argument("--strict", action="store_true",
                        help="exit 1 when any finding is reported")
    args = parser.parse_args(argv)
    paths = args.paths or [os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))]
    findings = analyze_paths(paths)
    for f in findings:
        print(f.render())
    print(f"hvd-analyze: {len(findings)} finding(s) over "
          f"{', '.join(paths)}", file=sys.stderr)
    if findings and args.strict:
        return 1
    return 0
