"""Runtime lock-order (inversion) detector.

Third pass of the ``hvd-analyze`` subsystem (docs/analysis.md): a
drop-in instrumented ``threading.Lock``/``RLock`` that records the
global lock-acquisition graph and raises the moment any acquisition
would close a cycle — i.e. thread 1 acquired A→B somewhere while
thread 2 now tries B→A.  Classic potential-deadlock detection (the
"lockdep" idea from the Linux kernel, applied TLA+-style: verify the
*ordering discipline*, not one lucky interleaving), so a single-threaded
test run still proves the discipline that a production race would need
to violate.

The runtime creates every internal lock through :func:`make_lock` /
:func:`make_rlock`; with ``HVD_TPU_LOCK_CHECK=1`` in the environment at
creation time those return checked wrappers, otherwise the plain
``threading`` primitives with zero overhead.  The whole tier-1 suite
runs with the checker on (tests/conftest.py + .github/workflows/ci.yml).

The graph is name-keyed, not object-keyed: every ``PyCoordinator._lock``
is one node, so an inversion between *classes* of locks is caught even
when the two interleavings involve different instances.  Pass a unique
name when instances genuinely have independent ordering.
"""

from __future__ import annotations

import os
import threading
from typing import Dict, List, Optional, Set


class LockOrderError(RuntimeError):
    """An acquisition would create a cycle in the lock-order graph."""


# name -> set of names it was ever held BEFORE (edge a->b: a held while
# acquiring b).  Guarded by _graph_lock; the checker's own lock is
# deliberately a plain threading.Lock (it can't check itself).
_graph: Dict[str, Set[str]] = {}
_graph_edges_sites: Dict[tuple, str] = {}
_graph_lock = threading.Lock()
_tls = threading.local()


def _held_stack() -> List[str]:
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    return stack


def _find_path(src: str, dst: str) -> Optional[List[str]]:
    """DFS path src -> dst in the edge graph (callers hold _graph_lock)."""
    seen = {src}
    todo = [(src, [src])]
    while todo:
        node, path = todo.pop()
        for nxt in _graph.get(node, ()):
            if nxt == dst:
                return path + [nxt]
            if nxt not in seen:
                seen.add(nxt)
                todo.append((nxt, path + [nxt]))
    return None


def _record_acquire(name: str) -> None:
    """Add edges held→name; raise LockOrderError on a would-be cycle."""
    stack = _held_stack()
    if name in stack:
        # Reentrant acquisition (RLock) — no new ordering information.
        stack.append(name)
        return
    with _graph_lock:
        for held in set(stack):
            if held == name:
                continue
            # Would name -> ... -> held close a cycle with held -> name?
            path = _find_path(name, held)
            if path is not None:
                fwd = " -> ".join(path)
                site = _graph_edges_sites.get((path[0], path[1]), "?")
                raise LockOrderError(
                    f"lock-order inversion: acquiring {name!r} while "
                    f"holding {held!r}, but the reverse order "
                    f"{fwd} was already established (first at {site}). "
                    f"Two threads taking these locks in opposite orders "
                    f"can deadlock.")
            edge = (held, name)
            if name not in _graph.get(held, set()):
                _graph.setdefault(held, set()).add(name)
                import traceback

                frame = traceback.extract_stack(limit=8)
                # Innermost frame outside this module names the call site.
                site = next((f"{f.filename}:{f.lineno}"
                             for f in reversed(frame)
                             if "lockorder" not in f.filename), "?")
                _graph_edges_sites[edge] = site
                # New-edge breadcrumb for the hvd-telemetry flight ring
                # (telemetry/flight.py is stdlib-only, so this lazy
                # import cannot cycle back through make_lock).  New
                # edges appear a handful of times per process lifetime.
                try:
                    from ..telemetry import flight as _flight

                    _flight.record("lock_edge", held, name, site)
                except Exception:  # noqa: BLE001 — observability only
                    pass
    stack.append(name)


def _record_release(name: str) -> None:
    stack = _held_stack()
    # Release the most recent matching acquisition (locks are almost
    # always released LIFO; out-of-order release is tolerated).
    for i in range(len(stack) - 1, -1, -1):
        if stack[i] == name:
            del stack[i]
            return


class _CheckedBase:
    """Shared acquire/release bookkeeping over a real threading lock."""

    def __init__(self, name: str, inner) -> None:
        self._name = name
        self._inner = inner

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        # Record BEFORE blocking: the ordering violation exists whether
        # or not this particular acquisition would have blocked.
        _record_acquire(self._name)
        got = self._inner.acquire(blocking, timeout)
        if not got:
            _record_release(self._name)
        return got

    def release(self) -> None:
        self._inner.release()
        _record_release(self._name)

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:  # aids debugging lock dumps
        return f"<{type(self).__name__} {self._name!r} {self._inner!r}>"


class CheckedLock(_CheckedBase):
    def __init__(self, name: str) -> None:
        super().__init__(name, threading.Lock())


class CheckedRLock(_CheckedBase):
    def __init__(self, name: str) -> None:
        super().__init__(name, threading.RLock())


def enabled() -> bool:
    """True when HVD_TPU_LOCK_CHECK=1 (read per call so tests can flip
    it before constructing the locks under test)."""
    return os.environ.get("HVD_TPU_LOCK_CHECK") == "1"


def make_lock(name: str):
    """A ``threading.Lock`` — checked when HVD_TPU_LOCK_CHECK=1."""
    return CheckedLock(name) if enabled() else threading.Lock()


def make_rlock(name: str):
    """A ``threading.RLock`` — checked when HVD_TPU_LOCK_CHECK=1."""
    return CheckedRLock(name) if enabled() else threading.RLock()


def reset() -> None:
    """Drop the recorded acquisition graph (test isolation)."""
    with _graph_lock:
        _graph.clear()
        _graph_edges_sites.clear()


def graph_snapshot() -> Dict[str, Set[str]]:
    """Copy of the current lock-order graph (observability/debugging)."""
    with _graph_lock:
        return {k: set(v) for k, v in _graph.items()}
