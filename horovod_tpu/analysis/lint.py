"""AST-based lock-discipline and SPMD-divergence linter.

Second pass of the ``hvd-analyze`` subsystem (docs/analysis.md),
runnable as ``python -m horovod_tpu.analysis [--strict] [paths]``.
Three rules, each targeting a bug class this codebase has actually
shipped (see CHANGES.md) or that the reference could only discover as a
60 s stall:

* **guarded-by** — fields annotated ``# guarded_by: <lock>`` (on the
  dataclass field or the ``self.x = ...`` line in ``__init__``) must
  only be touched inside a lexical ``with <lock>:`` block.  Receivers
  are resolved statically: ``self`` inside the defining class, and any
  variable assigned from a function whose return annotation names an
  annotated class (e.g. ``st = global_state()`` →
  ``_GlobalState``), across every linted file.  Methods whose name ends
  in ``_locked`` assert the caller holds the lock and are exempt, as is
  ``__init__`` (no concurrent access during construction).

* **blocking-under-lock** — calls that can block indefinitely
  (``time.sleep``, ``socket.recv``/``accept``, future ``.result()``,
  frame receives, ``synchronize``) inside a lexical ``with <lock>:``
  region.  A blocked holder starves every other thread; the
  coordinator's 5 ms tick turns that into a job-wide stall.

* **rank-conditioned-collective** — collective calls lexically inside a
  branch conditioned on ``rank()`` / ``local_rank()`` /
  ``process_index()``: the classic SPMD divergence bug (only some ranks
  enter the collective, the rest stall for 60 s then die).

A finding line may carry ``# lint: ok(<why>)`` to waive it — the waiver
text is the audit trail.  Waivers are themselves audited: a waiver
comment on a line that no longer triggers ANY rule (of any pass — this
one, thread-role, or post-donation-read) is reported as a
**stale-waiver** finding by :func:`horovod_tpu.analysis.analyze_sources`
so dead waivers cannot accumulate silently and mask a future
regression on the same line.
"""

from __future__ import annotations

import ast
import io
import os
import re
import tokenize
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

_GUARDED_RE = re.compile(r"#\s*guarded_by:\s*([A-Za-z_][A-Za-z0-9_]*)")
_WAIVER_RE = re.compile(r"#\s*lint:\s*ok\((.*?)\)")

# Terminal attribute/function names that block indefinitely.
BLOCKING_CALLS = {"sleep", "recv", "recv_into", "accept", "result",
                  "_recv_frame", "synchronize"}

# Public collective entry points (every frontend alias funnels into
# these names).
COLLECTIVE_CALLS = {
    "allreduce", "allreduce_async", "allgather", "allgather_async",
    "broadcast", "broadcast_async", "alltoall", "alltoall_async",
    "reducescatter", "reducescatter_async", "barrier",
    "grouped_allreduce", "grouped_allreduce_async",
    "grouped_allgather", "grouped_allgather_async",
    "grouped_reducescatter", "grouped_reducescatter_async",
    "allgather_object", "broadcast_object", "broadcast_parameters",
    "broadcast_variables", "broadcast_optimizer_state",
}

# Rank-valued callables: an `if` whose test calls one of these guards a
# rank-divergent branch.
RANK_CALLS = {"rank", "local_rank", "cross_rank", "process_index",
              "replica_id"}


@dataclass(frozen=True)
class Finding:
    path: str
    line: int
    rule: str
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


@dataclass
class _ClassInfo:
    name: str
    guarded: Dict[str, str] = field(default_factory=dict)  # field -> lock


@dataclass
class _FileInfo:
    path: str
    tree: ast.AST
    comments: Dict[int, str]           # line -> comment text
    own_line: Set[int] = field(default_factory=set)
    classes: Dict[str, _ClassInfo] = field(default_factory=dict)
    producers: Dict[str, str] = field(default_factory=dict)  # fn -> class
    # Module-level singletons: `_state = _GlobalState()` → var -> class.
    module_vars: Dict[str, str] = field(default_factory=dict)
    # Waiver comments: line -> reason, and the subset a rule (of any
    # pass) actually suppressed — the difference is the stale-waiver
    # report.
    waivers: Dict[int, str] = field(default_factory=dict)
    used_waivers: Set[int] = field(default_factory=set)


def waiver_hit(fi: "_FileInfo", line: int) -> bool:
    """True (and marks the waiver used) when ``line`` carries a
    ``# lint: ok(...)`` waiver.  Shared by every static pass so the
    stale-waiver audit sees cross-pass usage."""
    if line in fi.waivers:
        fi.used_waivers.add(line)
        return True
    return False


def _terminal_name(node: ast.AST) -> Optional[str]:
    """`a.b.c` -> 'c'; `c` -> 'c'; anything else -> None."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def _collect_comments(source: str) -> Tuple[Dict[int, str], Set[int]]:
    """line -> comment text, plus the lines that are comment-ONLY (a
    trailing comment annotates its own statement; only a comment-only
    line annotates the statement below it)."""
    comments: Dict[int, str] = {}
    own_line: Set[int] = set()
    lines = source.splitlines()
    try:
        for tok in tokenize.generate_tokens(io.StringIO(source).readline):
            if tok.type == tokenize.COMMENT:
                line = tok.start[0]
                comments[line] = tok.string
                if line <= len(lines) and \
                        not lines[line - 1][:tok.start[1]].strip():
                    own_line.add(line)
    except tokenize.TokenError:
        pass
    return comments, own_line


def _guard_for(stmt: ast.stmt, comments: Dict[int, str],
               own_line: Set[int]) -> Optional[str]:
    """guarded_by lock named in a comment on any line of ``stmt``, or in
    a comment-ONLY line directly above it (leading-comment convention —
    a trailing comment annotates its own line's statement only)."""
    lines = list(range(stmt.lineno, (stmt.end_lineno or stmt.lineno) + 1))
    if stmt.lineno - 1 in own_line:
        lines.insert(0, stmt.lineno - 1)
    for line in lines:
        text = comments.get(line)
        if text:
            m = _GUARDED_RE.search(text)
            if m:
                return m.group(1)
    return None


def _scan_file(path: str, source: str) -> Optional[_FileInfo]:
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError:
        return None
    comments, own_line = _collect_comments(source)
    info = _FileInfo(path=path, tree=tree, comments=comments,
                     own_line=own_line)
    for line, text in comments.items():
        m = _WAIVER_RE.search(text)
        if m:
            info.waivers[line] = m.group(1)
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef):
            ci = _ClassInfo(name=node.name)
            for stmt in node.body:
                tgt = None
                if isinstance(stmt, ast.AnnAssign) and \
                        isinstance(stmt.target, ast.Name):
                    tgt = stmt.target.id
                elif isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                        and isinstance(stmt.targets[0], ast.Name):
                    tgt = stmt.targets[0].id
                if tgt is not None:
                    lock = _guard_for(stmt, info.comments, info.own_line)
                    if lock:
                        ci.guarded[tgt] = lock
                if isinstance(stmt, ast.FunctionDef) and \
                        stmt.name == "__init__":
                    for sub in ast.walk(stmt):
                        if isinstance(sub, (ast.Assign, ast.AnnAssign)):
                            targets = (sub.targets
                                       if isinstance(sub, ast.Assign)
                                       else [sub.target])
                            for t in targets:
                                if isinstance(t, ast.Attribute) and \
                                        isinstance(t.value, ast.Name) and \
                                        t.value.id == "self":
                                    lock = _guard_for(sub, info.comments, info.own_line)
                                    if lock:
                                        ci.guarded[t.attr] = lock
            if ci.guarded:
                info.classes[node.name] = ci
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            ret = node.returns
            cls = _terminal_name(ret) if ret is not None else None
            if cls:
                info.producers[node.name] = cls
    for stmt in tree.body:
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 and \
                isinstance(stmt.targets[0], ast.Name) and \
                isinstance(stmt.value, ast.Call):
            cls = _terminal_name(stmt.value.func)
            if cls:
                info.module_vars[stmt.targets[0].id] = cls
    return info


class _RuleWalker(ast.NodeVisitor):
    """Single traversal applying all three rules to one function body."""

    def __init__(self, fi: _FileInfo, registry: Dict[str, _ClassInfo],
                 producers: Dict[str, str], enclosing_class: Optional[str],
                 func: ast.FunctionDef, findings: List[Finding]) -> None:
        self.fi = fi
        self.registry = registry
        self.producers = producers
        self.enclosing_class = enclosing_class
        self.func = func
        self.findings = findings
        self.lock_stack: List[str] = []
        self.rank_branch_depth = 0
        # var name -> class name: module-level singletons of this file,
        # then producer-typed locals layered on top.
        self.var_types: Dict[str, str] = {
            v: c for v, c in fi.module_vars.items() if c in registry}
        self.in_init = func.name in ("__init__", "__del__")
        self.locked_method = func.name.endswith("_locked")

    # -- helpers -----------------------------------------------------------

    def _waived(self, line: int) -> bool:
        return waiver_hit(self.fi, line)

    def _emit(self, node: ast.AST, rule: str, message: str) -> None:
        if not self._waived(node.lineno):
            self.findings.append(Finding(self.fi.path, node.lineno, rule,
                                         message))

    def _receiver_class(self, node: ast.expr) -> Optional[str]:
        if isinstance(node, ast.Name):
            if node.id == "self":
                return self.enclosing_class
            return self.var_types.get(node.id)
        if isinstance(node, ast.Call):
            fn = _terminal_name(node.func)
            if fn in self.producers:
                return self.producers[fn]
            if fn in self.registry:  # direct construction
                return fn
        return None

    def _is_rank_test(self, test: ast.expr) -> bool:
        for sub in ast.walk(test):
            if isinstance(sub, ast.Call):
                name = _terminal_name(sub.func)
                if name in RANK_CALLS:
                    return True
            # st.process_index / req.request_rank style comparisons.
            if isinstance(sub, ast.Attribute) and \
                    sub.attr in ("process_index", "request_rank"):
                return True
        return False

    # -- traversal ---------------------------------------------------------

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        if node is self.func:
            self.generic_visit(node)
        # Nested defs get their own walker from the caller; their bodies
        # execute later, outside this lexical lock region.

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Assign(self, node: ast.Assign) -> None:
        if isinstance(node.value, ast.Call):
            cls = self._receiver_class(node.value)
            if cls and cls in self.registry:
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        self.var_types[t.id] = cls
        self.generic_visit(node)

    def visit_With(self, node: ast.With) -> None:
        names = []
        for item in node.items:
            self.visit(item.context_expr)
            name = _terminal_name(item.context_expr)
            # Conditions wrap their mutex: `with self._cond:` holds it.
            if name and ("lock" in name.lower() or "cond" in name.lower()):
                names.append(name)
        self.lock_stack.extend(names)
        for stmt in node.body:
            self.visit(stmt)
        for _ in names:
            self.lock_stack.pop()

    def visit_If(self, node: ast.If) -> None:
        self.visit(node.test)
        ranky = self._is_rank_test(node.test)
        if ranky:
            self.rank_branch_depth += 1
        for stmt in node.body + node.orelse:
            self.visit(stmt)
        if ranky:
            self.rank_branch_depth -= 1

    def visit_Attribute(self, node: ast.Attribute) -> None:
        cls = self._receiver_class(node.value)
        if cls:
            ci = self.registry.get(cls)
            if ci and node.attr in ci.guarded:
                lock = ci.guarded[node.attr]
                held = lock in self.lock_stack
                exempt = (self.locked_method or
                          (self.in_init and isinstance(node.value, ast.Name)
                           and node.value.id == "self"))
                if not held and not exempt:
                    self._emit(
                        node, "guarded-by",
                        f"{cls}.{node.attr} is guarded_by {lock!r} but "
                        f"accessed outside any `with {lock}:` block "
                        f"(in {self.func.name})")
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        name = _terminal_name(node.func)
        if name in BLOCKING_CALLS and self.lock_stack:
            self._emit(
                node, "blocking-under-lock",
                f"potentially-blocking call {name}() inside a "
                f"`with {self.lock_stack[-1]}:` region (in "
                f"{self.func.name}); a blocked holder stalls every "
                f"waiter")
        if name in COLLECTIVE_CALLS and self.rank_branch_depth > 0:
            self._emit(
                node, "rank-conditioned-collective",
                f"collective {name}() inside a rank-conditioned branch "
                f"(in {self.func.name}); only some ranks reach it — the "
                f"classic SPMD divergence stall")
        self.generic_visit(node)


def _walk_functions(fi: _FileInfo, registry: Dict[str, _ClassInfo],
                    producers: Dict[str, str],
                    findings: List[Finding]) -> None:
    def visit_body(body, enclosing_class):
        for node in body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                walker = _RuleWalker(fi, registry, producers,
                                     enclosing_class, node, findings)
                walker.generic_visit(node)
                # Nested function defs each get a fresh walker (fresh
                # lock/rank context — they run later, elsewhere).
                inner = [n for n in ast.walk(node)
                         if isinstance(n, (ast.FunctionDef,
                                           ast.AsyncFunctionDef))
                         and n is not node]
                for sub in inner:
                    w = _RuleWalker(fi, registry, producers,
                                    enclosing_class, sub, findings)
                    w.generic_visit(sub)
            elif isinstance(node, ast.ClassDef):
                visit_body(node.body, node.name)

    visit_body(fi.tree.body, None)  # type: ignore[attr-defined]


def scan_sources(sources: Dict[str, str]) -> Dict[str, "_FileInfo"]:
    """Parse a {path: source} mapping into per-file scan info (comments,
    annotations, waivers).  The other static passes (thread-role,
    post-donation-read) and the stale-waiver audit run over the same
    scan so waiver usage aggregates across passes."""
    return {fi.path: fi
            for fi in (_scan_file(p, s) for p, s in sorted(sources.items()))
            if fi is not None}


def lint_infos(infos: Dict[str, "_FileInfo"]) -> List[Finding]:
    """Run the three lint rules over pre-scanned files (marking used
    waivers on each :class:`_FileInfo` as a side effect)."""
    registry: Dict[str, _ClassInfo] = {}
    producers: Dict[str, str] = {}
    for fi in infos.values():
        registry.update(fi.classes)
    for fi in infos.values():
        for fn, cls in fi.producers.items():
            if cls in registry:
                producers[fn] = cls
    findings: List[Finding] = []
    for fi in infos.values():
        _walk_functions(fi, registry, producers, findings)
    findings.sort(key=lambda f: (f.path, f.line))
    return findings


def lint_sources(sources: Dict[str, str]) -> List[Finding]:
    """Lint a {path: source} mapping; annotations and producer functions
    are resolved across the whole set."""
    return lint_infos(scan_sources(sources))


def _iter_py_files(paths: List[str]) -> List[str]:
    out: List[str] = []
    for p in paths:
        if os.path.isfile(p):
            out.append(p)
        else:
            for root, dirs, files in os.walk(p):
                dirs[:] = [d for d in dirs
                           if d not in ("__pycache__", ".git", "build")]
                out.extend(os.path.join(root, f) for f in files
                           if f.endswith(".py"))
    return sorted(set(out))


def lint_paths(paths: List[str]) -> List[Finding]:
    sources: Dict[str, str] = {}
    for path in _iter_py_files(paths):
        try:
            with open(path, "r", encoding="utf-8") as f:
                sources[path] = f.read()
        except OSError:
            continue
    return lint_sources(sources)
