"""Donation-lifetime sanitizer: catch use-after-donation on
``donate_argnums`` buffers by name, not as XLA's opaque
"Array has been deleted".

Buffer donation is this runtime's core memory lever — the megakernel
fusion groups, the serving decode/prefill executables, ZeRO/FSDP
update steps, and the pipeline stages all donate their big operands so
XLA reuses the HBM in place.  It is also the dominant historical bug
class: the EF-residual TAKE fix, the pipeline jit-fallback-after-
consumed fix, and the megakernel dropped-refs fix were all stale reads
of an already-donated buffer, each diagnosed from a bare XLA deletion
error with no clue WHICH executable consumed the array.  This module
closes that gap twice over:

**Static pass** (``python -m horovod_tpu.analysis --strict``): the
**post-donation-read** rule flags a read of a local after it was
passed at a donated position through a ``jit``/``pjit`` callable with
``donate_argnums`` *in the same scope*.  Tracking is linear and
best-effort by design: locals bound to a donating ``jax.jit`` (and
``self._x`` slots assigned one) are followed; a call through one marks
the ``Name`` arguments at donated positions consumed; rebinding
(``params = step(params, batch)`` — the correct idiom) clears the
mark.  Waive intentional reads with ``# lint: ok(<why>)``.

**Runtime mode** (``HVD_TPU_DONATION_CHECK=1``): executors route
donated dispatches through :func:`guard_dispatch`, which (1) pre-checks
every to-be-donated argument against the registry of buffers donated
earlier — handing an already-donated buffer to another executable
raises :class:`DonationError` naming the ORIGINAL donation (executable
label, argument index, donation site) — and (2) after the call,
registers each donated buffer (weakref-finalized, so identity reuse
after GC cannot alias) and bumps ``analysis.donation_poisoned``.
:func:`check` is the point probe for hand-written re-read sites, and
:class:`PoisonedBuffer` is a sentinel executors can store back into
their own slots (a residual table, a page registry) so *any* attribute
access on the dead slot raises the named error.  Errors flight-record
with the standard metrics tail.  Zero overhead when disarmed: one env
read per dispatch.
"""

from __future__ import annotations

import ast
import os
import threading
import traceback
import weakref
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from . import lint as _lint
from .lint import Finding

_ENV = "HVD_TPU_DONATION_CHECK"


class DonationError(RuntimeError):
    """A buffer was read (or re-dispatched) after being donated to an
    XLA executable; the message names the executable, the argument
    index, and the donation site."""


def enabled() -> bool:
    return os.environ.get(_ENV) == "1"


# ---------------------------------------------------------------------------
# Runtime registry

# id(buf) -> (label, index, site); entries are weakref-finalized away
# when the buffer is collected, so a recycled id cannot alias a dead
# entry.  Plain dict + leaf lock: registrations are per-dispatch, not
# per-element.
_registry: Dict[int, Tuple[str, int, str]] = {}
_registry_lock = threading.Lock()

# Lifetime count of buffers ever registered as donated (telemetry pull
# side; the registry dict itself shrinks as buffers are collected).
_n_poisoned = 0


def poison_count() -> int:
    return _n_poisoned


def _site_tail(limit: int = 4) -> str:
    frames = [f for f in traceback.extract_stack(limit=limit + 4)
              if "analysis/donation" not in f.filename.replace("\\", "/")]
    return " <- ".join(f"{os.path.basename(f.filename)}:{f.lineno}"
                       f"({f.name})" for f in reversed(frames[-limit:]))


def _raise(label: str, index: int, site: str, context: str) -> None:
    msg = (f"use-after-donation: {context} a buffer donated to "
           f"{label!r} (argument {index}, donated at [{site}]); the "
           f"backing HBM was reused in place — keep the executable's "
           f"RETURN value instead of the consumed operand")
    try:
        from ..telemetry import flight as _flight

        _flight.record("donation_error", label, index, context)
        _flight.dump("donation-error", extra={
            "executable": label, "arg_index": index,
            "donation_site": site, "context": context,
            "read_site": _site_tail()})
    except Exception:  # noqa: BLE001 — forensics only
        pass
    raise DonationError(msg)


class PoisonedBuffer:
    """Sentinel an executor stores into its own slot after donating the
    slot's buffer; any attribute access raises the named
    :class:`DonationError` instead of XLA's deletion error."""

    __slots__ = ("_label", "_index", "_site")

    def __init__(self, label: str, index: int, site: str) -> None:
        object.__setattr__(self, "_label", label)
        object.__setattr__(self, "_index", index)
        object.__setattr__(self, "_site", site)

    def __getattr__(self, name: str):
        _raise(object.__getattribute__(self, "_label"),
               object.__getattribute__(self, "_index"),
               object.__getattribute__(self, "_site"),
               f"attribute read ({name!r}) of")

    def __repr__(self) -> str:  # repr stays safe for logging
        return (f"<PoisonedBuffer donated to "
                f"{object.__getattribute__(self, '_label')!r} arg "
                f"{object.__getattribute__(self, '_index')}>")


def _entry_for(buf) -> Optional[Tuple[str, int, str]]:
    with _registry_lock:
        return _registry.get(id(buf))


def check(buf, context: str = "read of") -> None:
    """Point probe: raise :class:`DonationError` if ``buf`` was donated
    through :func:`guard_dispatch` earlier (or is already deleted).
    No-op when disarmed."""
    if not enabled() or buf is None:
        return
    if isinstance(buf, PoisonedBuffer):
        buf.shape  # raises with the slot's own donation facts
    entry = _entry_for(buf)
    if entry is not None:
        _raise(entry[0], entry[1], entry[2], context)


def register(buf, label: str, index: int,
             site: Optional[str] = None) -> None:
    """Record ``buf`` as donated to ``label`` at argument ``index``.
    Buffers that cannot take a weakref (scalars, tracers) are skipped —
    without finalization an id-keyed entry could alias a later
    allocation."""
    if buf is None:
        return
    site = site or _site_tail()
    key = id(buf)
    try:
        def _drop(k=key):
            with _registry_lock:
                _registry.pop(k, None)

        weakref.finalize(buf, _drop)
    except TypeError:
        return
    global _n_poisoned
    with _registry_lock:
        _registry[key] = (label, index, site)
        # Under the leaf lock, NOT a telemetry Counter: guard_dispatch
        # may run under executor locks, so registration must not take
        # the registry's — telemetry pulls this via its `analysis`
        # collector (analysis.donation_poisoned gauge).
        _n_poisoned += 1


def guard_dispatch(label: str, fn, args: Sequence,
                   donated: Iterable[int], kwargs: Optional[dict] = None):
    """Run ``fn(*args, **kwargs)`` with donation bookkeeping: pre-check
    each ``donated`` index (a stale buffer raises the ORIGINAL
    donation's error before XLA sees it), then register the donated
    arguments.  When disarmed this is a plain call."""
    kwargs = kwargs or {}
    if not enabled():
        return fn(*args, **kwargs)
    donated = [i for i in donated if 0 <= i < len(args)]
    for i in donated:
        check(args[i], context=f"re-dispatch (into {label!r} arg {i}) of")
        deleted = getattr(args[i], "is_deleted", None)
        if deleted is not None:
            try:
                stale = bool(deleted())
            except Exception:  # noqa: BLE001 — non-array lookalikes
                stale = False
            if stale:
                _raise(label, i, "<unknown (deleted outside the "
                       "donation registry)>",
                       f"dispatch (into {label!r} arg {i}) of")
    out = fn(*args, **kwargs)
    site = _site_tail()
    for i in donated:
        register(args[i], label, i, site=site)
    return out


def reset() -> None:
    """Forget all registered donations (tests)."""
    with _registry_lock:
        _registry.clear()


# ---------------------------------------------------------------------------
# Static pass


def _donate_positions(call: ast.Call) -> Optional[List[int]]:
    """Donated positions of a ``jit``/``pjit`` call with a literal
    ``donate_argnums``; None when not a donating jit."""
    if _lint._terminal_name(call.func) not in ("jit", "pjit"):
        return None
    for kw in call.keywords:
        if kw.arg != "donate_argnums":
            continue
        v = kw.value
        if isinstance(v, ast.Constant) and isinstance(v.value, int):
            return [v.value]
        if isinstance(v, (ast.Tuple, ast.List)):
            out = []
            for el in v.elts:
                if isinstance(el, ast.Constant) and \
                        isinstance(el.value, int):
                    out.append(el.value)
                else:
                    return None  # non-literal: give up, stay silent
            return out
    return None


def _target_key(node: ast.expr) -> Optional[str]:
    """Trackable binding target: a local name, or a ``self.x`` slot
    (keyed ``self.x``) for the AOT-handle idiom."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return f"self.{node.attr}"
    return None


class _FnWalker(ast.NodeVisitor):
    """Linear per-function walk: follow donating-jit bindings, mark
    Name args at donated positions consumed, flag later reads."""

    def __init__(self, fi, func, findings: List[Finding]) -> None:
        self.fi = fi
        self.func = func
        self.findings = findings
        self.jitted: Dict[str, List[int]] = {}   # key -> donated args
        # local -> (executable key, index, donation line)
        self.consumed: Dict[str, Tuple[str, int, int]] = {}

    def _clear(self, key: Optional[str]) -> None:
        if key is not None:
            self.consumed.pop(key, None)

    def _handle_call(self, node: ast.Call) -> None:
        self.visit(node.func)
        key = _target_key(node.func)
        donated = self.jitted.get(key) if key else None
        if donated is None:
            donated = _donate_positions(node)  # inline jit(...)(...) form
            if donated is not None:
                key = _lint._terminal_name(node.func) or "jit"
        for arg in node.args:
            self.visit(arg)
        for kw in node.keywords:
            self.visit(kw.value)
        if donated:
            for i in donated:
                if i < len(node.args):
                    name = node.args[i]
                    if isinstance(name, ast.Name):
                        self.consumed[name.id] = (key or "jit", i,
                                                  node.lineno)

    def visit_Call(self, node: ast.Call) -> None:
        # The donating call itself may be the VALUE of an assignment
        # (visit_Assign orchestrates that case); a bare call lands here.
        self._handle_call(node)

    def visit_Assign(self, node: ast.Assign) -> None:
        if isinstance(node.value, ast.Call):
            donated = _donate_positions(node.value)
            if donated is not None:
                # `step = jax.jit(f, donate_argnums=...)`: track the
                # binding, don't treat the jit() call as a dispatch.
                for t in node.targets:
                    k = _target_key(t)
                    if k:
                        self.jitted[k] = donated
                        self._clear(k)
                return
            self._handle_call(node.value)
        else:
            self.visit(node.value)
        for t in node.targets:
            self._clear(_target_key(t))
            if not isinstance(t, ast.Name):
                self.visit(t)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self.visit(node.value)
        self.visit(node.target)  # reads before writing
        self._clear(_target_key(node.target))

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        if node.value is not None:
            if isinstance(node.value, ast.Call):
                self._handle_call(node.value)
            else:
                self.visit(node.value)
            self._clear(_target_key(node.target))

    def visit_Name(self, node: ast.Name) -> None:
        if isinstance(node.ctx, ast.Load) and node.id in self.consumed:
            key, idx, line = self.consumed[node.id]
            if _lint.waiver_hit(self.fi, node.lineno):
                return
            self.findings.append(Finding(
                self.fi.path, node.lineno, "post-donation-read",
                f"{node.id!r} is read after being donated to {key}() "
                f"(donate_argnums position {idx}, donated at line "
                f"{line}, in {self.func.name}); XLA reused its buffer "
                f"— use the executable's return value, or waive with "
                f"`# lint: ok(...)` if the read is pre-dispatch by "
                f"construction"))
            # One finding per donation; a fixed read usually fixes all.
            self.consumed.pop(node.id, None)
        elif isinstance(node.ctx, ast.Store):
            self._clear(node.id)

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        if node is self.func:
            self.generic_visit(node)
        # Nested defs execute later: separate walk, fresh state.

    visit_AsyncFunctionDef = visit_FunctionDef


def check_infos(infos: Dict[str, "_lint._FileInfo"]) -> List[Finding]:
    findings: List[Finding] = []
    for fi in infos.values():
        funcs = [n for n in ast.walk(fi.tree)
                 if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
        for func in funcs:
            _FnWalker(fi, func, findings).generic_visit(func)
    findings.sort(key=lambda f: (f.path, f.line))
    return findings


def check_sources(sources: Dict[str, str]) -> List[Finding]:
    return check_infos(_lint.scan_sources(sources))
