"""Thread-role contracts: ``# thread: <role>`` annotations, checked
statically and asserted dynamically.

The runtime's threading contracts have so far lived in prose — the
serving engine's docstring says "the data plane is driven from ONE
thread" and "``abort_all`` is serve-loop-only under multiprocess", the
prefetch stager and checkpoint writer each own their queues by
convention.  This pass makes those contracts machine-checked:

**Annotations.**  A trailing ``# thread: <role>`` comment on a ``def``
line declares "this method runs on the <role> thread".  Canonical
roles match the runtime's thread names: ``serve-loop``, ``drain``,
``rx``, ``stager``, ``writer``, ``ticker``, ``exporter``, ``accept``.
A call FROM a method of role A TO a method declared role B (B ≠ A) is
a **thread-role** finding unless the call line carries a handoff
marker — ``# thread: handoff(<how>)`` documents the mechanism that
moves the work across (a queue put, an event set, an enqueue) — or a
``# lint: ok(...)`` waiver.  Run via
``python -m horovod_tpu.analysis --strict`` alongside the lint rules.

**Dynamic asserts.**  Thread-creation sites stamp their target's role
with :func:`set_role` (first line of the thread's loop); annotated
entry points call :func:`require`.  With ``HVD_TPU_RACE_CHECK=1`` a
stamped thread entering a method of a different role raises
:class:`ThreadRoleError` naming the method, its declared role, and
the calling thread's stamped role + name, and flight-records the
event.  UNSTAMPED threads always pass: the contracts constrain the
runtime's own fleet, while user/main threads remain free to drive the
single-process API (the engine docstring's "single-process callers may
treat it like the rest of the drain family").  Each verification bumps
the ``analysis.thread_role_asserts`` counter.  Zero overhead when
disarmed: :func:`require` is one env-var read.
"""

from __future__ import annotations

import ast
import os
import re
import threading
from typing import Dict, List, Optional, Tuple

from . import lint as _lint
from .lint import Finding

# Roles mirror the fleet's thread names (core/state tick, transport rx,
# input stager, checkpoint writer, serve loop, tree ticker, exporter).
ROLES = ("serve-loop", "drain", "rx", "stager", "writer", "ticker",
         "exporter", "accept")

_THREAD_RE = re.compile(r"#\s*thread:\s*([a-z][a-z0-9-]*)\b")
_HANDOFF_RE = re.compile(r"#\s*thread:\s*handoff\((.*?)\)")

_tls = threading.local()


class ThreadRoleError(RuntimeError):
    """A thread stamped with one role entered a method declared
    ``# thread: <other role>``."""


_n_asserts = 0


def assert_count() -> int:
    """Total dynamic role verifications (telemetry pull side)."""
    return _n_asserts


def enabled() -> bool:
    """Dynamic asserts share the race detector's switch
    (HVD_TPU_RACE_CHECK=1), read per call."""
    return os.environ.get("HVD_TPU_RACE_CHECK") == "1"


def set_role(role: str) -> None:
    """Stamp the current thread's role (call once, first line of the
    thread's loop).  Cheap enough to run unconditionally."""
    _tls.role = role


def current_role() -> Optional[str]:
    return getattr(_tls, "role", None)


def require(role: str, what: str = "") -> None:
    """Assert the current thread is unstamped or stamped ``role``.

    Annotated entry points call this; disarmed it is one env read.
    Unstamped (user/main) threads pass — the runtime's own fleet is
    what the contracts constrain.
    """
    if not enabled():
        return
    have = getattr(_tls, "role", None)
    # Plain-int count (GIL-tolerant): require() may run under arbitrary
    # runtime locks, so it must not take the telemetry registry's —
    # telemetry pulls this via its `analysis` collector.
    global _n_asserts
    _n_asserts += 1
    if have is None or have == role:
        return
    me = threading.current_thread().name
    msg = (f"thread-role violation: {what or 'method'} is declared "
           f"`# thread: {role}` but was entered on thread {me!r} "
           f"stamped role {have!r}; hand the work off (queue/event) "
           f"instead of calling across roles")
    try:
        from ..telemetry import flight as _flight

        _flight.record("thread_role", what or "?", role, have, me)
        _flight.dump("thread-role", extra={
            "what": what, "declared_role": role, "thread_role": have,
            "thread": me})
    except Exception:  # noqa: BLE001 — forensics only
        pass
    raise ThreadRoleError(msg)


# ---------------------------------------------------------------------------
# Static pass


def _decl_role(fi, node: ast.AST) -> Optional[str]:
    """Role declared by a trailing ``# thread: <role>`` on the def
    line (handoff markers are not declarations)."""
    text = fi.comments.get(node.lineno, "")
    if _HANDOFF_RE.search(text):
        return None
    m = _THREAD_RE.search(text)
    if m and m.group(1) != "handoff":
        return m.group(1)
    return None


def check_infos(infos: Dict[str, "_lint._FileInfo"]) -> List[Finding]:
    """thread-role rule over pre-scanned files: a role-A method calling
    a role-B method (terminal-name match across the whole linted set)
    without a handoff marker on the call line."""
    # method name -> (role, path, line).  Terminal-name keyed, like the
    # lint pass's producer resolution; only annotated methods partake,
    # so the namespace stays small enough for that to be sound.
    declared: Dict[str, Tuple[str, str, int]] = {}
    annotated: List[Tuple["_lint._FileInfo", ast.FunctionDef, str]] = []
    for fi in infos.values():
        for node in ast.walk(fi.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                role = _decl_role(fi, node)
                if role:
                    declared[node.name] = (role, fi.path, node.lineno)
                    annotated.append((fi, node, role))
    findings: List[Finding] = []
    for fi, func, role in annotated:
        for node in ast.walk(func):
            if not isinstance(node, ast.Call):
                continue
            name = _lint._terminal_name(node.func)
            if name is None or name == func.name:
                continue
            decl = declared.get(name)
            if decl is None or decl[0] == role:
                continue
            line_text = fi.comments.get(node.lineno, "")
            if _HANDOFF_RE.search(line_text):
                continue
            if _lint.waiver_hit(fi, node.lineno):
                continue
            findings.append(Finding(
                fi.path, node.lineno, "thread-role",
                f"{func.name}() runs on the {role!r} thread but calls "
                f"{name}() which is declared `# thread: {decl[0]}` "
                f"({decl[1]}:{decl[2]}); cross-role work needs a "
                f"handoff — mark the line `# thread: handoff(<how>)` "
                f"once it goes through a queue/event"))
    findings.sort(key=lambda f: (f.path, f.line))
    return findings


def check_sources(sources: Dict[str, str]) -> List[Finding]:
    return check_infos(_lint.scan_sources(sources))
