"""Trace-time collective-program signature verifier.

First pass of the ``hvd-analyze`` subsystem (docs/analysis.md).  The
runtime coordinator (ops/coordinator.py ≙ operations.cc:222-461)
catches collective mismatches only at *runtime negotiation* — after
every rank has already traced and queued work, and only for tensors
that reach the same name.  This module proves the same invariants
earlier, TLA+-style ("verify the protocol, not the run"):

* every eager/traced collective entry point appends a
  ``(name, op_kind, dtype, shape, reduce_op, process_set_id)`` record
  to this process's :class:`ProgramRecorder` (hook: collective._enqueue
  — the single funnel every frontend routes through);
* :func:`verify_program` hashes the per-rank signature and
  cross-validates it over the existing TCP control plane *before* any
  data-plane work, reporting the exact first divergent entry with both
  ranks' views;
* :class:`ProgramTracker` is the automatic in-negotiation twin: fed by
  the coordinator as requests arrive (``HVD_TPU_VERIFY_PROGRAM=1``), it
  flags rank-divergent program *order* — which the name-keyed request
  table can only ever stall on — the moment the streams disagree.

Beyond the five runtime mismatch kinds (op, dtype, shape, reduce-op,
process-set), the comparison catches two statically-only failures:
rank-divergent collective *count*, and process-set deadlock *cycles*
(rank 0 issues set-A-then-set-B while rank 1 issues B-then-A: each
set's coordinator sees a perfectly consistent stream, no mismatch can
ever fire, and synchronous callers deadlock — detected here via the
order swap across sets, the wait-for-graph cycle A→B→A).
"""

from __future__ import annotations

import contextlib
import contextvars
import hashlib
import json
import os
import threading
from collections import deque
from typing import Dict, List, NamedTuple, Optional, Tuple
from . import races as _races

# Cap on retained records: verification aligns on absolute sequence
# numbers, so a long-running job keeps a sliding window instead of the
# whole history (the total count still rides the exchange, catching
# count divergence beyond the window).
PROGRAM_WINDOW = int(os.environ.get("HVD_TPU_PROGRAM_WINDOW", "65536"))


class SignatureEntry(NamedTuple):
    """One collective call in a rank's program signature."""

    seq: int                 # absolute position in this rank's program
    op: str                  # request kind: allreduce/allgather/...
    name: str                # wire tensor name
    dtype: str               # wire dtype name
    shape: Tuple[int, ...]   # this rank's payload shape
    reduce_op: str           # SUM/AVERAGE/... ("" for non-reductions)
    process_set_id: int      # 0 = the global set
    source: str = ""         # issuing frontend ("", "tf", "torch", ...)

    def describe(self) -> str:
        src = f", source={self.source}" if self.source else ""
        red = f", reduce_op={self.reduce_op}" if self.reduce_op else ""
        return (f"{self.op}(name={self.name!r}, dtype={self.dtype}, "
                f"shape={tuple(self.shape)}{red}, "
                f"process_set={self.process_set_id}{src})")


def _entry_mismatch(a: SignatureEntry, b: SignatureEntry) -> Optional[str]:
    """The first disagreeing field between two same-index entries, as a
    reference-style mismatch label — or None when the entries are
    compatible.  Shape rules follow the runtime validator: allgather
    ragged dim 0 is legal (operations.cc:334-392), alltoall compares
    trailing dims only; everything else is exact."""
    if a.name != b.name:
        return "Mismatched tensor names (rank-divergent program order)"
    if a.op != b.op:
        return "Mismatched collective operations"
    if a.process_set_id != b.process_set_id:
        return "Mismatched process sets"
    if a.dtype != b.dtype:
        return "Mismatched data types"
    if a.op in ("allgather", "alltoall"):
        if len(a.shape) != len(b.shape) or \
                tuple(a.shape[1:]) != tuple(b.shape[1:]):
            return "Mismatched tensor shapes"
    elif tuple(a.shape) != tuple(b.shape):
        return "Mismatched tensor shapes"
    if a.reduce_op != b.reduce_op:
        return "Mismatched reduce operations"
    return None


def _format_divergence(kind: str, rank_a: int, a: SignatureEntry,
                       rank_b: int, b: SignatureEntry) -> str:
    return (f"Collective program divergence at entry #{a.seq}: {kind}.\n"
            f"  rank {rank_a}: {a.describe()}\n"
            f"  rank {rank_b}: {b.describe()}")


def _find_cycle(rank_a: int, prog_a: List[SignatureEntry],
                rank_b: int, prog_b: List[SignatureEntry],
                i: int) -> Optional[str]:
    """Given the first divergent index ``i`` between two programs, test
    whether it is an ORDER SWAP across two process sets — the wait-for
    cycle no runtime check can catch.  X = rank_a's entry, Y = rank_b's
    entry at ``i``; a deadlock needs X and Y later on the *other* rank
    (both ranks will issue both ops) in swapped order, in different
    process sets (same-set swaps surface as that set's order
    divergence)."""
    x, y = prog_a[i], prog_b[i]
    if x.process_set_id == y.process_set_id:
        return None

    def _later(prog, entry) -> Optional[SignatureEntry]:
        for e in prog[i + 1:]:
            if e.name == entry.name and e.process_set_id == \
                    entry.process_set_id and e.op == entry.op:
                return e
        return None

    x_on_b = _later(prog_b, x)
    y_on_a = _later(prog_a, y)
    if x_on_b is None or y_on_a is None:
        return None
    pa, pb = x.process_set_id, y.process_set_id
    return (
        f"Potential process-set deadlock cycle: process sets "
        f"{pa} -> {pb} -> {pa} form a wait-for cycle.\n"
        f"  rank {rank_a} issues {x.describe()} (entry #{x.seq}) before "
        f"{y_on_a.describe()} (entry #{y_on_a.seq})\n"
        f"  rank {rank_b} issues {y.describe()} (entry #{y.seq}) before "
        f"{x_on_b.describe()} (entry #{x_on_b.seq})\n"
        f"Each set's coordinator sees a consistent stream, so no runtime "
        f"mismatch can fire; synchronous callers deadlock here.")


def compare_signatures(
        programs: Dict[int, List[SignatureEntry]],
        totals: Optional[Dict[int, int]] = None) -> Optional[str]:
    """Cross-validate per-rank program signatures.

    Returns ``None`` when every rank traced a compatible collective
    program, else a diagnostic naming the first divergent entry with
    both ranks' records.  ``totals`` carries each rank's lifetime
    collective count when the entry lists are a bounded window.
    """
    ranks = sorted(programs)
    if len(ranks) < 2:
        return None
    r0 = ranks[0]
    base = programs[r0]
    for r in ranks[1:]:
        other = programs[r]
        # Align by ABSOLUTE seq, not list position: bounded windows that
        # slid by different amounts (one rank traced extras before both
        # overflowed PROGRAM_WINDOW) would otherwise pair unrelated
        # entries and misreport the first divergence.
        a_list, b_list = base, other
        if a_list and b_list and a_list[0].seq != b_list[0].seq:
            start = max(a_list[0].seq, b_list[0].seq)
            a_list = a_list[start - a_list[0].seq:]
            b_list = b_list[start - b_list[0].seq:]
        for i, (a, b) in enumerate(zip(a_list, b_list)):
            kind = _entry_mismatch(a, b)
            if kind is None:
                continue
            cycle = _find_cycle(r0, a_list, r, b_list, i)
            if cycle is not None:
                return cycle
            return _format_divergence(kind, r0, a, r, b)
        n0 = totals[r0] if totals else len(base)
        n1 = totals[r] if totals else len(other)
        if n0 != n1:
            msg = (f"Rank-divergent collective count: rank {r0} recorded "
                   f"{n0} collectives but rank {r} recorded {n1}.")
            # Name the extra entry only when the higher-count rank's
            # window still holds it past the seq-aligned common prefix
            # (with offset sliding windows it may have slid out).
            longer_rank, longer = (r0, a_list) if n0 > n1 else (r, b_list)
            cut = min(len(a_list), len(b_list))
            if cut < len(longer):
                extra = longer[cut]
                msg += (f"\n  first unmatched entry (rank {longer_rank} "
                        f"only): {extra.describe()}")
            return msg
    return None


class ProgramRecorder:
    """This process's collective-program signature (thread-safe)."""

    def __init__(self, window: int = PROGRAM_WINDOW) -> None:
        self._lock = threading.Lock()
        self._entries: deque = deque(maxlen=window)
        self._total = 0

    def record(self, op: str, name: str, dtype: str,
               shape: Tuple[int, ...], reduce_op: str = "",
               process_set_id: int = 0, source: str = "") -> None:
        with self._lock:
            self._entries.append(SignatureEntry(
                seq=self._total, op=op, name=name, dtype=dtype,
                shape=tuple(int(d) for d in shape), reduce_op=reduce_op,
                process_set_id=int(process_set_id), source=source))
            self._total += 1

    def entries(self) -> List[SignatureEntry]:
        with self._lock:
            return list(self._entries)

    def total(self) -> int:
        with self._lock:
            return self._total

    def snapshot(self) -> Tuple[List[SignatureEntry], int]:
        """Atomic (entries, total) pair — verify_program must pack a
        consistent view while other threads may still be recording."""
        with self._lock:
            return list(self._entries), self._total

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._total = 0

    def digest(self) -> str:
        """SHA-256 over the canonical encoding of the signature — equal
        digests ⇒ byte-identical programs (the exchange's fast path)."""
        with self._lock:
            entries, total = list(self._entries), self._total
        return _digest(entries, total)


def _digest(entries: List[SignatureEntry], total: int) -> str:
    h = hashlib.sha256()
    h.update(str(total).encode())
    for e in entries:
        # source is per-rank provenance, not program content.
        h.update(repr(e[:7]).encode())
    return h.hexdigest()


def entries_digest(entries: List[SignatureEntry],
                   total: Optional[int] = None) -> str:
    """Public digest over a list of signature entries — the canonical
    program-identity scheme shared by verify_program's exchange and the
    response cache's cycle keys (ops/cache.py): equal digests ⇔
    identical programs under the same encoding everywhere."""
    return _digest(entries, len(entries) if total is None else total)


def pack_program(rank: int, entries: List[SignatureEntry],
                 total: int) -> bytes:
    return json.dumps({
        "rank": rank,
        "total": total,
        "digest": _digest(entries, total),
        "entries": [list(e) for e in entries],
    }).encode("utf-8")


def unpack_program(payload: bytes) -> Tuple[int, int, str,
                                            List[SignatureEntry]]:
    obj = json.loads(payload.decode("utf-8"))
    entries = [SignatureEntry(e[0], e[1], e[2], e[3], tuple(e[4]), e[5],
                              e[6], e[7] if len(e) > 7 else "")
               for e in obj["entries"]]
    return obj["rank"], obj["total"], obj["digest"], entries


def cross_validate(payloads: Dict[int, bytes]) -> Optional[str]:
    """Controller-side check over every rank's packed signature: equal
    digests short-circuit; otherwise decode and diff."""
    digests = {}
    programs: Dict[int, List[SignatureEntry]] = {}
    totals: Dict[int, int] = {}
    for r, payload in payloads.items():
        rank, total, digest, entries = unpack_program(payload)
        digests[r] = digest
        programs[r] = entries
        totals[r] = total
    if len(set(digests.values())) <= 1:
        return None
    return compare_signatures(programs, totals)


# ---------------------------------------------------------------------------
# Per-process recording (hooked from ops/collective._enqueue)
# ---------------------------------------------------------------------------

_recorder = ProgramRecorder()
_source: contextvars.ContextVar = contextvars.ContextVar(
    "hvd_tpu_collective_source", default="")


def recorder() -> ProgramRecorder:
    return _recorder


# Cached at import (like PROGRAM_WINDOW): recording sits on the
# per-collective dispatch path, so it must not re-read the environment
# every call.
_RECORDING = os.environ.get("HVD_TPU_PROGRAM_RECORD", "1") != "0"


def recording_enabled() -> bool:
    return _RECORDING


@contextlib.contextmanager
def collective_source(tag: str):
    """Tag collectives recorded inside the block with their issuing
    frontend — the TF/torch/Keras bridges wrap their dispatch in this so
    a divergence diagnostic names which binding issued the entry."""
    token = _source.set(tag)
    try:
        yield
    finally:
        _source.reset(token)


def tag_source(tag: str):
    """Decorator form of :func:`collective_source` — the one shared
    spelling the frontend entry points use."""
    import functools

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            with collective_source(tag):
                return fn(*args, **kwargs)
        return wrapper
    return deco


def record_collective(op: str, name: str, dtype: str,
                      shape: Tuple[int, ...], reduce_op: str = "",
                      process_set_id: int = 0) -> None:
    if not recording_enabled():
        return
    _recorder.record(op, name, dtype, shape, reduce_op=reduce_op,
                     process_set_id=process_set_id,
                     source=_source.get())


# ---------------------------------------------------------------------------
# Coordinator-side automatic tracker (HVD_TPU_VERIFY_PROGRAM=1)
# ---------------------------------------------------------------------------

def program_check_enabled() -> bool:
    return os.environ.get("HVD_TPU_VERIFY_PROGRAM") == "1"


@_races.race_checked
class ProgramTracker:
    """Per-rank request streams as the coordinator's negotiation path
    sees them.  ``feed`` appends one request's signature and compares it
    against every other rank's entry at the same absolute position —
    divergent *order* (which the name-keyed table would stall on
    forever) is reported immediately, before any data-plane work.  The
    cross-checked common prefix is trimmed, so memory stays bounded by
    the ranks' skew, not the job length.

    Two self-disarms keep the tracker honest: a JOIN request disables
    it for the rest of the run (``hvd.join`` explicitly legalizes
    rank-divergent programs, so positional comparison would report
    false divergences on a healthy uneven workload), and a stream
    outgrowing ``PROGRAM_WINDOW`` entries — an idle peer pinning the
    trim — disables it rather than growing without bound."""

    def __init__(self, size: int,
                 window: int = PROGRAM_WINDOW) -> None:
        self.size = size
        self.window = window
        self._lock = threading.Lock()
        self._disabled = False  # guarded_by: _lock
        self._streams: List[List[SignatureEntry]] = [[] for _ in range(size)]
        self._base = 0  # absolute seq of each stream's first entry

    def disable(self) -> None:
        with self._lock:
            self._disabled = True
            self._streams = [[] for _ in range(self.size)]

    def feed(self, req) -> Optional[str]:
        """Record one Request; returns a divergence diagnostic or None.
        A JOIN request disables tracking (see the class docstring)."""
        from ..ops import wire

        if req.request_type == wire.RequestType.JOIN:
            self.disable()
            return None
        entry = SignatureEntry(
            seq=0, op=req.request_type.name.lower(),
            name=req.tensor_name,
            dtype=wire.dtype_name(req.tensor_type),
            shape=tuple(req.tensor_shape),
            reduce_op=(wire.reduce_op_name(req.reduce_op)
                       if req.request_type.name in ("ALLREDUCE",
                                                    "REDUCESCATTER")
                       else ""),
            process_set_id=req.process_set_id)
        with self._lock:
            if self._disabled or not 0 <= req.request_rank < self.size:
                return None
            mine = self._streams[req.request_rank]
            idx = self._base + len(mine)
            entry = entry._replace(seq=idx)
            mine.append(entry)
            diag = None
            for r, stream in enumerate(self._streams):
                if r == req.request_rank:
                    continue
                off = idx - self._base
                if off < len(stream):
                    other = stream[off]
                    kind = _entry_mismatch(other, entry)
                    if kind is not None:
                        diag = _format_divergence(
                            kind, r, other, req.request_rank, entry)
                        break
            if diag is None:
                trim = min(len(s) for s in self._streams)
                if trim:
                    for s in self._streams:
                        del s[:trim]
                    self._base += trim
                elif len(mine) > self.window:
                    # An idle peer pins the trim; stop tracking instead
                    # of accumulating one entry per collective forever.
                    self._disabled = True
                    self._streams = [[] for _ in range(self.size)]
            return diag


# ---------------------------------------------------------------------------
# verify_program — the explicit pre-data-plane barrier check
# ---------------------------------------------------------------------------

class ProgramReport(NamedTuple):
    ranks: int
    entries: int
    digest: str


def verify_program(reset: bool = True,
                   timeout: Optional[float] = None) -> ProgramReport:
    """Cross-validate every rank's recorded collective program.

    Call it after tracing/issuing the collectives whose agreement you
    want proven — typically right after the first training step is
    built, before committing to the data plane.  Multi-process mode
    ships each rank's signature to the rank-0 controller over the TCP
    control plane (FRAME_SIGNATURE), which diffs them and broadcasts
    the verdict; a divergence raises :class:`HorovodError` on every
    rank, naming the first divergent entry with both ranks' records.
    Single-process mode has exactly one program, so only the recording
    itself is reported.

    Args:
      reset: clear the recorder afterwards (default), so successive
        phases verify independently.
      timeout: seconds to wait for the other ranks (default
        ``HVD_TPU_VERIFY_TIMEOUT``, 60).
    """
    from ..core import state as _state
    from ..ops.collective import HorovodError

    _state._check_initialized()
    st = _state.global_state()
    if timeout is None:
        timeout = float(os.environ.get("HVD_TPU_VERIFY_TIMEOUT", "60"))
    entries, total = _recorder.snapshot()
    report = ProgramReport(
        ranks=st.process_count if st.multiprocess else 1,
        entries=total, digest=_digest(entries, total))
    error: Optional[str] = None
    if st.multiprocess:
        payload = pack_program(st.process_index, entries, total)
        if st.process_index == 0:
            payloads = st.transport.collect_signatures(payload, timeout)
            error = cross_validate(payloads)
            st.transport.broadcast_signature_result(error)
        else:
            error = st.transport.exchange_signature(payload, timeout)
    if reset:
        _recorder.clear()
    if error is not None:
        raise HorovodError(error)
    return report
