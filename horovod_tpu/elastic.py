"""Elastic / fault-tolerant training — ``horovod_tpu.elastic``.

Horovod standardized elastic training after the v0.13 snapshot this
framework tracks (``horovod.elastic``: ``State`` objects with
``commit``/``restore``/``sync``, an ``@hvd.elastic.run`` retry loop, and
a driver that re-forms the Gloo ring in-process as hosts come and go).
The v0.13 reference itself has no recovery story at all — a lost rank
hangs the MPI job until the scheduler kills it (SURVEY.md §5 "no
elasticity"; reference horovod/common/operations.cc:1072-1115 only
*warns* about stalls).

TPU-native redesign
-------------------
The Gloo-style in-process ring re-formation cannot be translated:
``jax.distributed`` does not support re-initialization after a member is
lost (see :func:`.core.cluster.disarm_distributed_shutdown`), and on
real hardware a slice-membership change re-initializes the XLA runtime
anyway.  Production TPU elasticity is checkpoint-shaped: commit state
cheaply, let the scheduler restart the job, resume fast.  So the same
API contract splits across the process boundary:

* :class:`State` — named pytrees/scalars with ``commit()`` (every rank
  snapshots to host memory; the coordinating process additionally
  publishes to disk when ``HVD_TPU_ELASTIC_DIR`` is set), ``restore()``
  (roll back to the last commit), and ``sync()`` (converge every rank on
  the committed state via broadcast — also how a fresh incarnation picks
  up a previous incarnation's commit).
* :func:`run` — wraps the training function.  A collective failure
  (``HorovodError`` — e.g. a dead peer poisoning pending ops with its
  diagnosis) triggers rollback + reset callbacks + retry in-process when
  the cluster is still whole, or — when the cluster lost a member and
  cannot be re-formed — a clean ``EX_TEMPFAIL`` (75) exit that tells the
  elastic launcher to relaunch the job from the last commit.
* ``python -m horovod_tpu.run --elastic -np N`` — the launcher half:
  supervises the workers, and on failure tears the job down and
  relaunches it (bounded by ``--max-restarts``) with the commit
  directory preserved, so ``state.sync()`` resumes training where the
  last ``commit()`` left it.

Usage::

    import horovod_tpu as hvd
    from horovod_tpu import elastic

    hvd.init()
    state = elastic.State(params=params, opt_state=opt_state,
                          epoch=0, batch=0)

    @elastic.run
    def train(state):
        while state.epoch < epochs:
            for state.batch in range(state.batch, steps_per_epoch):
                state.params, state.opt_state, loss = step(
                    state.params, state.opt_state, batch(state))
                if state.batch % 10 == 9:
                    state.commit()
            state.batch = 0
            state.epoch += 1
            state.commit()

    train(state)
"""

from __future__ import annotations

import functools
import os
import sys
from typing import Any, Callable, Dict, List, Optional

import jax
import numpy as np

# The launcher interprets this exit code as "relaunch me from the last
# commit" (BSD sysexits EX_TEMPFAIL: temporary failure, retry later).
EX_TEMPFAIL = 75

_STATE_FILE = "elastic_state.msgpack"


def _elastic_dir() -> Optional[str]:
    return os.environ.get("HVD_TPU_ELASTIC_DIR") or None


def _host_copy(tree: Any) -> Any:
    """Device→host snapshot; scalars keep their Python types.

    Always a FRESH buffer (``np.array`` copies; ``np.asarray`` would
    alias a numpy-backed leaf, letting an in-place optimizer update
    silently corrupt the rollback point)."""
    return jax.tree_util.tree_map(
        lambda x: x if isinstance(x, (int, float, bool)) else np.array(x),
        tree)


def _restore_leaf(orig: Any, committed: Any) -> Any:
    """One leaf of a rollback: committed value, re-cast to ``orig``'s
    scalar type, copied so post-restore in-place mutation cannot reach
    back into the snapshot."""
    v = _cast_like(orig, committed)
    return np.array(v) if isinstance(v, np.ndarray) else v


def _cast_like(orig: Any, new: Any) -> Any:
    """Give ``new`` back the Python scalar type ``orig`` had, so loop
    counters survive the array round trip through broadcast/serialization
    (``for state.batch in range(state.batch, N)`` must keep working)."""
    if isinstance(orig, bool):
        return bool(np.asarray(new))
    if isinstance(orig, int) and not isinstance(orig, np.ndarray):
        return int(np.asarray(new))
    if isinstance(orig, float) and not isinstance(orig, np.ndarray):
        return float(np.asarray(new))
    return new


class State:
    """Committable, broadcastable training state.

    ≙ ``horovod.elastic.State``/``ObjectState`` (post-v0.13): named
    values — parameter/optimizer pytrees, loop counters — that can be
    atomically committed, rolled back, and synchronized across ranks.

    Values are attributes: ``state.params``, ``state.epoch = 3``.  New
    values may be added after construction; they join the next commit.
    """

    def __init__(self, **values: Any) -> None:
        object.__setattr__(self, "_values", dict(values))
        object.__setattr__(self, "_snapshot", None)
        object.__setattr__(self, "_reset_callbacks", [])
        object.__setattr__(self, "_commit_serial", 0)
        object.__setattr__(self, "_commit_write", None)
        # Pre-commit snapshot so restore() before any commit() returns to
        # the constructed state rather than failing.
        self._snapshot_now()

    # -- attribute plumbing ------------------------------------------------
    def __getattr__(self, name: str) -> Any:
        try:
            return object.__getattribute__(self, "_values")[name]
        except KeyError:
            raise AttributeError(name) from None

    def __setattr__(self, name: str, value: Any) -> None:
        if name.startswith("_"):
            object.__setattr__(self, name, value)
        else:
            self._values[name] = value

    # -- snapshot machinery ------------------------------------------------
    def _snapshot_now(self) -> None:
        object.__setattr__(self, "_snapshot", _host_copy(dict(self._values)))

    def register_reset_callbacks(self, callbacks: List[Callable]) -> None:
        """Callbacks invoked after a rollback, before retrying (≙ the
        reference API's hook for re-building lr schedules etc. when the
        world changed)."""
        self._reset_callbacks.extend(callbacks)

    # -- the contract ------------------------------------------------------
    def commit(self) -> None:
        """Atomically publish the current values as the rollback point.

        Every rank keeps a host-memory snapshot; when
        ``HVD_TPU_ELASTIC_DIR`` is set (the elastic launcher exports it)
        the coordinating process also publishes to disk so the commit
        survives a full job restart.  The disk write rides the
        background checkpoint writer (``utils/checkpoint``): commit()
        returns after the host snapshot — the training loop never waits
        on the filesystem — while the writer publishes with the same
        atomic tmp+rename discipline, in commit order.
        :meth:`wait_committed` is the explicit durability point;
        :meth:`sync` and a normal interpreter exit fence pending writes
        automatically.
        """
        self._snapshot_now()
        object.__setattr__(self, "_commit_serial", self._commit_serial + 1)
        d = _elastic_dir()
        if d is None:
            return
        from .core import state as _state

        if _state.is_initialized() and _state.process_index() != 0:
            return
        from .utils import checkpoint as _checkpoint

        # The snapshot is already a fresh host copy (_host_copy): safe
        # to hand to the writer thread as-is — restore()/sync() never
        # mutate it in place, they copy out of it.
        object.__setattr__(self, "_commit_write",
                           _checkpoint.write_tree_async(
                               os.path.join(d, _STATE_FILE),
                               self._snapshot))

    def wait_committed(self, timeout: Optional[float] = None) -> bool:
        """Block until the most recent :meth:`commit`'s disk publish is
        durable (no-op when commits are host-memory only).  Re-raises a
        writer failure as :class:`.utils.checkpoint.CheckpointError`."""
        w = self._commit_write
        return True if w is None else w.wait(timeout)

    def restore(self) -> None:
        """Roll back to the last :meth:`commit` (or the constructed
        state).  Local only — :meth:`sync` converges ranks."""
        snap = self._snapshot
        vals = self._values
        for k, committed in snap.items():
            cur = vals.get(k, committed)
            vals[k] = jax.tree_util.tree_map(
                _restore_leaf, cur, committed) if _same_structure(
                    cur, committed) else _host_copy(committed)
        # Values added after the snapshot are uncommitted: drop them.
        for k in [k for k in vals if k not in snap]:
            del vals[k]

    def sync(self) -> None:
        """Converge every rank on the committed state.

        Order of truth: a disk commit from a previous incarnation (the
        elastic-relaunch path) if present, else the coordinating rank's
        current values.  Either way the result is broadcast from rank 0
        — the reference's load-on-rank-0-then-broadcast convention — and
        becomes the new rollback point on every rank.
        """
        from .core import state as _state

        d = _elastic_dir()
        path = os.path.join(d, _STATE_FILE) if d else None
        if path and (not _state.is_initialized()
                     or _state.process_index() == 0):
            # Fence this process's own in-flight commit publish first:
            # sync() must converge on the newest commit, not race it.
            from .utils import checkpoint as _checkpoint

            _checkpoint.wait_for_writes()
        if path and os.path.exists(path) and (
                not _state.is_initialized()
                or _state.process_index() == 0):
            from flax import serialization

            with open(path, "rb") as f:
                blob = f.read()
            loaded = serialization.from_bytes(
                _host_copy(dict(self._values)), blob)
            for k, v in loaded.items():
                self._values[k] = jax.tree_util.tree_map(
                    _cast_like, self._values[k], v)
        if _state.is_initialized() and _state.process_count() > 1:
            from .parallel.data import broadcast_parameters

            synced = broadcast_parameters(dict(self._values), root_rank=0)
            for k, v in synced.items():
                self._values[k] = jax.tree_util.tree_map(
                    _cast_like, self._values[k], v)
        self._snapshot_now()


def _same_structure(a: Any, b: Any) -> bool:
    return (jax.tree_util.tree_structure(a)
            == jax.tree_util.tree_structure(b))


def _cluster_reformable() -> bool:
    """Can this process retry in-process, or is the job's only way
    forward a relaunch?  A lost peer permanently disarms the
    jax.distributed cluster (core/cluster.py); a peer-initiated shutdown
    likewise ends the group."""
    from .core import cluster as _cluster
    from .core import state as _state

    if _cluster._disarmed:
        return False
    st = _state.global_state()
    if st.multiprocess and (st.peer_shutdown or st.shutdown):
        return False
    return True


class TrainerState(State):
    """Elastic state bound to a :class:`~horovod_tpu.frontends.loop.Trainer`
    (≙ the reference-lineage framework State classes —
    ``hvd.elastic.TorchState`` et al. — which snapshot a live
    model/optimizer rather than raw values).

    Captures the trainer's parameters, optimizer state, model state and
    loop counters; :meth:`restore`/:meth:`sync` write them BACK into the
    trainer, so ``@elastic.run`` functions can drive ``trainer.fit``
    directly::

        trainer = Trainer(loss_fn, params, ...)
        state = elastic.TrainerState(trainer, epoch=0)

        @elastic.run
        def train(state):
            trainer.fit(batches, epochs, steps,
                        initial_epoch=state.epoch)
            state.epoch = epochs
            state.commit()

    Works with every Trainer storage mode — under ``fsdp=True`` the
    ``params`` property contract (read = gather, assign = re-shard)
    makes the snapshot/restore transparent.
    """

    def __init__(self, trainer: Any, **extra: Any) -> None:
        object.__setattr__(self, "_trainer", trainer)
        values = dict(params=trainer.params, opt_state=trainer.opt_state,
                      **extra)
        if trainer.model_state is not None:
            values["model_state"] = trainer.model_state
        super().__init__(**values)

    def _capture(self) -> None:
        t = self._trainer
        self._values["params"] = t.params
        self._values["opt_state"] = t.opt_state
        if t.model_state is not None:
            self._values["model_state"] = t.model_state

    def _install(self) -> None:
        t = self._trainer
        t.params = self._values["params"]
        t.opt_state = self._values["opt_state"]
        if "model_state" in self._values:
            t.model_state = self._values["model_state"]

    def commit(self) -> None:
        self._capture()
        super().commit()

    def restore(self) -> None:
        super().restore()
        self._install()

    def sync(self) -> None:
        self._capture()
        super().sync()
        self._install()


def serving_export_payload(engine: Any,
                           exported: Optional[List[dict]] = None
                           ) -> dict:
    """The serving migration payload: requests (queued + in-flight as
    continuations) plus the shared-prefix index as maximal token
    chains.  ``exported`` short-circuits the request export when the
    caller already drained the engine (the export must come from THAT
    drain — a second ``export_requests`` after it would be empty).
    Shared by :class:`ServingState`'s commit blob and the replica-side
    ``POST /drain`` hook the hvd-route tier scales down through."""
    if exported is None:
        exported = engine.export_requests()
    export = getattr(engine, "export_prefix_index", None)
    return {"requests": exported,
            "prefixes": export() if export is not None else []}


def serving_install_payload(engine: Any, payload: Any) -> List[dict]:
    """Install a :func:`serving_export_payload` dict into an engine:
    drain whatever it holds (retry path: the committed set replaces it
    wholesale), ghost-seed the shared-prefix chains (cheap, and the
    resubmitted continuations below already hit them), then resubmit.
    Accepts the pre-prefix-cache blob format (a bare request list).
    Returns the requests installed."""
    if isinstance(payload, list):  # pre-prefix-cache blob format
        payload = {"requests": payload, "prefixes": []}
    engine.drain()
    seed = getattr(engine, "seed_prefixes", None)
    if seed is not None and payload.get("prefixes"):
        seed(payload["prefixes"])
    requests = payload.get("requests", [])
    engine.import_requests(requests)
    return requests


class ServingState(State):
    """Elastic state for a serving fleet
    (:class:`horovod_tpu.serving.engine.InferenceEngine`) — the resize
    path of docs/inference.md: ``drain_commit()`` captures every queued
    AND in-flight request (in-flight sequences become continuations
    carrying what they already generated), publishes it through the
    same background-writer commit as training state, and stops
    admission; after the relaunch, ``sync()`` on a fresh engine
    resubmits the committed work.  Greedy continuations reproduce the
    uninterrupted rollout exactly (the serving bitwise contract), so a
    fleet resize is invisible in the completions.

    The request list rides the commit as a JSON blob in a uint8 array:
    its LENGTH changes between commits, which the fixed-structure
    pytree round trip of :class:`State` tolerates only for raw array
    leaves.  The blob also carries the engine's shared-prefix index
    (hash → token ids, exported as the maximal cached chains), so a
    relaunched fleet REBUILDS the shared pages on ``sync()`` — one
    ghost prefill per chain — instead of re-prefilling every cached
    prefix cold on its first live hit (hvd-spec satellite).

    Usage (mirrors :class:`TrainerState`)::

        engine = serving.InferenceEngine(params, cfg, ...)
        state = elastic.ServingState(engine)
        ...
        # on resize/failure:
        state.drain_commit(); state.wait_committed()
        # relaunched incarnation:
        state = elastic.ServingState(fresh_engine)
        state.sync()          # resubmits the committed requests
    """

    def __init__(self, engine: Any, **extra: Any) -> None:
        object.__setattr__(self, "_engine", engine)
        super().__init__(requests_blob=self._blob(), **extra)

    def _blob(self, exported: Optional[List[dict]] = None) -> Any:
        import json

        payload = serving_export_payload(self._engine, exported)
        return np.frombuffer(json.dumps(payload).encode(),
                             np.uint8).copy()

    def _capture(self) -> None:
        self._values["requests_blob"] = self._blob()

    def _install(self) -> None:
        import json

        blob = bytes(np.asarray(self._values["requests_blob"]))
        serving_install_payload(self._engine,
                                json.loads(blob.decode() or "[]"))

    def commit(self) -> None:
        self._capture()
        super().commit()

    def drain_commit(self) -> List[dict]:
        """Resize step 1: drain the engine (stop admission, evict
        in-flight sequences as continuations) and commit the captured
        request set plus the shared-prefix index (exported AFTER the
        drain, so pages the evictions just unreferenced are still in
        it).  Returns the export for inspection/logging."""
        exported = self._engine.drain()
        self._values["requests_blob"] = self._blob(exported)
        super().commit()
        return exported

    def restore(self) -> None:
        super().restore()
        self._install()

    def sync(self) -> None:
        super().sync()
        self._install()


def run(func: Callable) -> Callable:
    """Decorator making a training function elastic (≙
    ``@hvd.elastic.run``).

    ``func(state, ...)`` runs after an initial ``state.sync()`` (which
    resumes from a previous incarnation's commit when relaunched by the
    elastic launcher).  On ``HorovodError``:

    * cluster still whole → ``state.restore()``, reset callbacks,
      ``state.sync()``, retry (``HVD_TPU_ELASTIC_MAX_RETRIES``, default
      3);
    * cluster lost a member → under the elastic launcher
      (``HVD_TPU_ELASTIC=1``) exit with ``EX_TEMPFAIL`` so the job is
      relaunched from the last commit; otherwise re-raise.
    """

    @functools.wraps(func)
    def wrapper(state: State, *args: Any, **kwargs: Any) -> Any:
        from .ops.collective import HorovodError

        state.sync()
        retries = int(os.environ.get("HVD_TPU_ELASTIC_MAX_RETRIES", "3"))
        attempt = 0
        last_serial = -1
        while True:
            try:
                return func(state, *args, **kwargs)
            except HorovodError as e:
                if not _cluster_reformable():
                    if os.environ.get("HVD_TPU_ELASTIC"):
                        print(
                            "horovod_tpu.elastic: collective failure with "
                            f"an unrecoverable cluster ({e}); exiting "
                            f"EX_TEMPFAIL({EX_TEMPFAIL}) for the elastic "
                            "launcher to relaunch from the last commit.",
                            file=sys.stderr, flush=True)
                        sys.exit(EX_TEMPFAIL)
                    raise
                if state._commit_serial > last_serial and last_serial >= 0:
                    # Committed progress since the previous incident: the
                    # retry budget bounds consecutive failures of ONE
                    # incident, not the job's lifetime.
                    attempt = 0
                last_serial = state._commit_serial
                attempt += 1
                if attempt > retries:
                    raise
                print(
                    f"horovod_tpu.elastic: retrying after {e} "
                    f"(attempt {attempt}/{retries}); rolling back to the "
                    "last commit.", file=sys.stderr, flush=True)
                state.restore()
                for cb in state._reset_callbacks:
                    cb()
                state.sync()

    return wrapper
