"""Shared exponential-backoff-with-jitter policy (hvd-chaos hardening).

One implementation for every retry loop in the runtime — the worker's
initial controller connect, the control-plane reconnect path
(ops/transport.py), and the background checkpoint writer's transient
OSError retries (utils/checkpoint.py) — so the backoff shape is tuned
(and tested) in exactly one place.

Full jitter (the AWS architecture-blog scheme): attempt ``k`` sleeps
``uniform(0, min(cap, base * 2**k))``.  Jitter decorrelates a fleet of
workers reconnecting to one controller after a common fault — without
it every rank retries in lockstep and the controller eats a thundering
herd at each backoff step.
"""

from __future__ import annotations

import random
import time
from typing import Iterator, Optional


class BackoffPolicy:
    """Capped exponential backoff with full jitter.

    ``delays()`` yields the per-attempt sleep seconds until
    ``deadline`` (monotonic) would be crossed; the caller owns the
    actual attempt.  ``rng`` is injectable so tests pin the jitter."""

    def __init__(self, base: float = 0.05, cap: float = 2.0,
                 factor: float = 2.0,
                 rng: Optional[random.Random] = None) -> None:
        if base <= 0 or cap < base or factor < 1.0:
            raise ValueError(
                f"bad backoff policy: base={base} cap={cap} "
                f"factor={factor}")
        self.base = base
        self.cap = cap
        self.factor = factor
        self._rng = rng or random.Random()

    def delay(self, attempt: int) -> float:
        """Jittered sleep for 0-indexed ``attempt``: uniform in
        ``[0, min(cap, base * factor**attempt)]``."""
        ceiling = min(self.cap, self.base * self.factor ** attempt)
        return self._rng.uniform(0.0, ceiling)

    def delays(self, deadline: Optional[float] = None) -> Iterator[float]:
        """Yield jittered delays (one per attempt) while monotonic time
        stays ahead of ``deadline`` (None = forever).  The generator
        does NOT sleep — callers sleep so they can interleave logging
        (the connect loop logs each attempt with the remaining
        deadline)."""
        attempt = 0
        while deadline is None or time.monotonic() < deadline:
            yield self.delay(attempt)
            attempt += 1


def retry_call(fn, *, attempts: int, policy: Optional[BackoffPolicy]
               = None, retry_on=(OSError,), on_retry=None):
    """Call ``fn()`` up to ``attempts`` times, sleeping the policy's
    jittered backoff between failures.  ``on_retry(attempt, exc,
    delay)`` observes each retried failure (telemetry/flight hooks).
    The final failure re-raises unchanged — callers keep their
    exception contract (the checkpoint writer's CheckpointError
    wrapping happens at wait(), exactly as before)."""
    policy = policy or BackoffPolicy()
    last: Optional[BaseException] = None
    for attempt in range(attempts):
        try:
            return fn()
        except retry_on as e:  # noqa: PERF203 — retry loop by design
            last = e
            if attempt == attempts - 1:
                raise
            delay = policy.delay(attempt)
            if on_retry is not None:
                on_retry(attempt, e, delay)
            time.sleep(delay)
    raise last  # pragma: no cover — unreachable (attempts >= 1 raises)
