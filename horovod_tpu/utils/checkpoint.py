"""Checkpoint/resume with the reference's rank-0 + broadcast conventions,
overlapped with training (hvd-pipeline).

The reference delegates serialization to the frameworks but fixes two
conventions (SURVEY.md §5): save on rank 0 only (README.md:102-104,
examples/keras_imagenet_resnet50.py:126-127) and, on resume, load on rank 0
then broadcast — including the scalar ``resume_from_epoch``
(examples/keras_imagenet_resnet50.py:47-56, :130-133).

Serialization uses flax msgpack (``flax.serialization``) — a single
self-contained file, atomic-renamed into place.

Background writes (PR 5)
------------------------
``save_checkpoint`` no longer blocks the training loop on disk: the
caller pays only the device→host snapshot, then a dedicated rank-0
writer thread serializes and publishes the file (tmp + ``os.replace``,
so a reader NEVER sees a torn checkpoint — a write killed midway leaves
the previous checkpoint intact and at most an orphaned ``*.tmp.*``).
The returned :class:`CheckpointWrite` handle is truthy exactly when
this process performs the save (the historical bool contract) and has
``wait()`` for an explicit durability point; writes to one path apply
in submission order (single FIFO writer).  ``restore_checkpoint`` and
``resume_epoch`` fence pending writes to their path first, so
read-after-write inside one process stays coherent.  Pending writes
flush at interpreter exit (``atexit``); a writer failure re-raises at
``wait()`` AND is flight-recorded (``checkpoint_error``) so
fire-and-forget savers still see it.

Telemetry (docs/metrics.md): ``checkpoint.write_seconds`` histogram
(disk time per write, off the training loop), ``checkpoint.pending``
gauge (queued+in-flight writes), ``checkpoint.errors`` counter.
"""

from __future__ import annotations

import atexit
import os
import queue
import sys
import threading
import time
from typing import Any, Optional

import jax
import numpy as np

from .. import chaos as _chaos
from .. import telemetry as _telemetry
from .. import trace as _trace
from ..analysis import lockorder as _lockorder
from ..analysis import threads as _athreads
from ..core import state as _state
from ..memory import ledger as _mem
from ..parallel.data import broadcast_parameters
from ..telemetry import flight as _flight
from .retry import BackoffPolicy, retry_call

_M_WRITE_SECONDS = _telemetry.histogram(
    "checkpoint.write_seconds", "seconds",
    "disk seconds per background checkpoint write")
_M_PENDING = _telemetry.gauge(
    "checkpoint.pending", "checkpoint writes queued or in flight")
_M_RETRIES = _telemetry.counter(
    "checkpoint.retries", "transient write failures retried with "
    "backoff before surfacing CheckpointError (hvd-chaos hardening)")
_M_SHARDS = _telemetry.counter(
    "checkpoint.shards_written", "parameter shard files published by "
    "this process (sharded distributed checkpointing)")
_M_MANIFESTS = _telemetry.counter(
    "checkpoint.manifest_commits", "sharded-checkpoint manifests "
    "committed (rank 0; the save's durability point)")
_M_BCAST_SKIPPED = _telemetry.counter(
    "checkpoint.broadcast_skipped", "restore broadcasts skipped "
    "because a digest allgather proved every rank read identical "
    "bytes locally")


def _write_retries() -> int:
    """Attempts per checkpoint publish (1 = the pre-chaos no-retry
    behavior).  A transient OSError — flaky NFS, a momentary ENOSPC —
    should not permanently fail a CheckpointWrite that a retry 50 ms
    later would land."""
    return max(1, int(os.environ.get("HVD_TPU_CKPT_RETRIES", "3")))


class CheckpointError(RuntimeError):
    """A background checkpoint write failed (surfaced at ``wait()``)."""


class CheckpointWrite:
    """Handle for one (possibly still in-flight) checkpoint write.

    Truthiness keeps the historical ``save_checkpoint`` bool contract:
    truthy iff THIS process performs the save (rank 0), whether or not
    the bytes hit disk yet.  ``wait()`` is the durability point."""

    def __init__(self, path: Optional[str], performed: bool) -> None:
        self.path = path
        self._performed = performed
        self._done = threading.Event()
        self.error: Optional[BaseException] = None
        if not performed:
            self._done.set()  # nothing to wait for on non-saving ranks

    def __bool__(self) -> bool:
        return self._performed

    @property
    def done(self) -> bool:
        return self._done.is_set()

    def wait(self, timeout: Optional[float] = None) -> bool:
        """Block until the write is durably published (atomic rename
        complete).  Returns False on timeout; raises
        :class:`CheckpointError` if the write failed."""
        if not self._done.wait(timeout):
            return False
        if self.error is not None:
            raise CheckpointError(
                f"background checkpoint write to {self.path!r} failed: "
                f"{type(self.error).__name__}: {self.error}"
            ) from self.error
        return True


def _write_bytes_once(path: str, blob: bytes) -> None:
    """One atomic publish attempt: full write to a private tmp, then
    rename.  A crash at ANY point leaves either the previous file or
    the new one — never a torn read (tests kill this midway to prove
    it).  The hvd-chaos ``ckpt.oserror`` site injects its transient
    OSError here — inside the retried region, before the rename — so
    an injected fault can never publish partial bytes either."""
    tmp = f"{path}.tmp.{os.getpid()}.{threading.get_ident()}"
    try:
        with open(tmp, "wb") as f:
            fault = _chaos.fire("ckpt.oserror") if _chaos.active() \
                else None
            if fault is not None:
                raise OSError(28, "hvd-chaos: ckpt.oserror (injected "
                              "transient ENOSPC)", tmp)
            f.write(blob)
        os.replace(tmp, path)
    except BaseException:
        # A failed attempt must not strand its tmp: the NEXT attempt
        # re-creates it, and the atomicity story stays "previous file
        # or new file, never torn, at most one orphaned tmp".
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def _write_bytes(path: str, blob: bytes) -> None:
    """Atomic publish with transient-fault retries (hvd-chaos
    hardening): up to ``HVD_TPU_CKPT_RETRIES`` attempts with the shared
    jittered exponential backoff (utils/retry.py); each retried failure
    is counted, flight-recorded and logged.  Only OSError retries —
    serialization bugs fail immediately.  The final failure re-raises
    unchanged, keeping the CheckpointError contract at ``wait()``."""

    def on_retry(attempt: int, exc: BaseException, delay: float) -> None:
        _M_RETRIES.inc()
        _flight.record("ckpt_retry", path, attempt,
                       f"{type(exc).__name__}: {exc}")
        print(f"WARNING: checkpoint write to {path!r} failed "
              f"(attempt {attempt + 1}/{_write_retries()}: "
              f"{type(exc).__name__}: {exc}); retrying in "
              f"{delay * 1e3:.0f}ms", file=sys.stderr)

    retry_call(lambda: _write_bytes_once(path, blob),
               attempts=_write_retries(),
               policy=BackoffPolicy(base=0.02, cap=0.5),
               retry_on=(OSError,), on_retry=on_retry)


class _Writer:
    """The rank-0 background checkpoint writer: one FIFO thread, so
    writes to the same path apply in submission order."""

    def __init__(self) -> None:
        self._q: queue.Queue = queue.Queue()
        self._pending = 0
        self._lock = _lockorder.make_lock("checkpoint._Writer._lock")
        self._thread = threading.Thread(
            target=self._run, name="hvd-ckpt-writer", daemon=True)
        self._thread.start()

    def submit(self, handle: CheckpointWrite, host_tree: Any,
               step: Optional[int]) -> None:
        # hvd-mem: the host snapshot is framework-held memory until the
        # background write publishes it — charged per handle, released
        # in the writer's finally (success or failure alike).
        if _mem.enabled():
            handle._mem_bytes = _mem.tree_nbytes(host_tree)
            if handle._mem_bytes:
                _mem.ledger.alloc("checkpoint.snapshots",
                                  handle._mem_bytes)

        def publish() -> None:
            from flax import serialization

            blob = serialization.to_bytes(host_tree)
            _write_bytes(handle.path, blob)
            if step is not None:
                _write_bytes(f"{handle.path}.step", str(step).encode())

        self.submit_task(handle, publish)

    def submit_task(self, handle: CheckpointWrite, publish) -> None:
        """Queue an arbitrary publish thunk on the FIFO writer thread
        (the sharded-checkpoint path submits shard writes and the
        manifest commit through here, so ordering and the
        CheckpointError-at-wait() contract stay uniform)."""
        with self._lock:
            self._pending += 1
            _M_PENDING.set(self._pending)
        self._q.put((handle, publish))

    def pending(self) -> int:
        with self._lock:
            return self._pending

    def _run(self) -> None:  # thread: writer
        _athreads.set_role("writer")
        while True:
            item = self._q.get()
            if item is None:  # drain sentinel (wait_all)
                continue
            handle, publish = item
            t0 = time.perf_counter()
            mt0 = time.monotonic() if _trace.enabled() else 0.0
            try:
                publish()
            except BaseException as e:  # noqa: BLE001 — carried to wait()
                handle.error = e
                _telemetry.checkpoint_error_event(
                    handle.path, f"{type(e).__name__}: {e}")
            finally:
                _M_WRITE_SECONDS.observe(time.perf_counter() - t0)
                if _trace.enabled():
                    # hvd-trace: a write that stole the cycle shows up
                    # in the fleet trace as a checkpoint-leg span.
                    _trace.span("checkpoint.write", "checkpoint", mt0,
                                time.monotonic(),
                                args={"path": os.path.basename(
                                    handle.path)})
                nb = getattr(handle, "_mem_bytes", 0)
                if nb:
                    _mem.ledger.free("checkpoint.snapshots", nb)
                with self._lock:
                    self._pending -= 1
                    _M_PENDING.set(self._pending)
                handle._done.set()

    def wait_all(self, timeout: Optional[float] = None) -> bool:
        """Block until every submitted write has finished (the atexit
        flush; returns False on timeout)."""
        deadline = None if timeout is None else time.monotonic() + timeout
        while self.pending() > 0:
            if deadline is not None and time.monotonic() > deadline:
                return False
            time.sleep(0.005)
        return True


_writer: Optional[_Writer] = None
_writer_lock = _lockorder.make_lock("checkpoint._writer_lock")


def _get_writer() -> _Writer:
    global _writer
    with _writer_lock:
        if _writer is None or not _writer._thread.is_alive():
            _writer = _Writer()
            # Pending writes must survive a normal interpreter exit
            # (the thread is a daemon — without this flush a short job
            # could lose its final checkpoint).
            atexit.register(_writer.wait_all, 30.0)
        return _writer


def pending_writes() -> int:
    """Number of checkpoint writes queued or in flight on this process."""
    with _writer_lock:
        w = _writer
    return w.pending() if w is not None else 0


def wait_for_writes(timeout: Optional[float] = None) -> bool:
    """Flush every pending background write (all paths)."""
    with _writer_lock:
        w = _writer
    return w.wait_all(timeout) if w is not None else True


def _is_saving_process() -> bool:
    return _state.process_index() == 0


def _host_snapshot(tree: Any) -> Any:
    """Device→host snapshot the writer thread can serialize later.

    jax Arrays are immutable — ``np.asarray`` (the fetch) is safe to
    alias.  Raw numpy leaves are COPIED: the caller may mutate them
    in place after ``save_checkpoint`` returns, and the writer must
    capture the value at call time (same rationale as
    ``elastic._host_copy``)."""
    def snap(x):
        if isinstance(x, (int, float, bool, bytes, str)):
            return x
        if isinstance(x, np.ndarray):
            return np.array(x)
        return np.asarray(x)

    return jax.tree_util.tree_map(snap, tree)


def write_tree_async(path: str, host_tree: Any,
                     step: Optional[int] = None) -> CheckpointWrite:
    """Queue one already-host-resident tree for the background writer
    (the low-level half of :func:`save_checkpoint`; ``elastic.commit``
    feeds its snapshot through here so the commit barrier excludes disk
    latency).  Caller must guarantee ``host_tree`` is not mutated
    afterwards — :func:`_host_snapshot` produces such a tree."""
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    handle = CheckpointWrite(path, performed=True)
    _get_writer().submit(handle, host_tree, step)
    return handle


def save_checkpoint(path: str, tree: Any, step: Optional[int] = None,
                    block: bool = False) -> CheckpointWrite:
    """Save ``tree`` at ``path`` from the coordinating process only
    (≙ the rank-0 guard in every reference example).

    The call returns after the device→host snapshot; serialization and
    the atomic tmp+rename publish happen on the background writer
    thread, overlapped with training.  Returns a
    :class:`CheckpointWrite` — truthy iff this process performs the
    save (the historical bool contract: ``if save_checkpoint(...)``),
    with ``wait()`` as the explicit durability point.  ``block=True``
    restores fully synchronous semantics."""
    if not _is_saving_process():
        return CheckpointWrite(path, performed=False)
    handle = write_tree_async(path, _host_snapshot(tree), step=step)
    if block:
        handle.wait()
    return handle


def restore_checkpoint(path: str, target: Any, broadcast: bool = True) -> Any:
    """Load ``path`` into the structure of ``target`` and (by default)
    broadcast from root so all replicas resume identically
    (≙ load-on-rank-0-then-broadcast, keras_imagenet_resnet50.py:130-133).

    Only the coordinating process reads the file — non-root processes keep
    ``target`` and receive root's values through the broadcast, so a
    checkpoint that exists only on the coordinator's disk restores
    everywhere (the reference's save-on-rank-0 convention implies exactly
    this asymmetry).  Pending background writes are fenced first, so a
    restore right after an async save sees the new bytes (and the atomic
    rename means it can never see torn ones).

    Broadcast elision: on a shared filesystem every rank reads the SAME
    file, so broadcasting every parameter byte through rank 0 is pure
    waste.  When all ranks can read ``path`` locally, a 64-byte digest
    allgather over the control plane proves the reads are identical and
    the full-tree broadcast is skipped (``checkpoint.broadcast_skipped``
    counts it); any rank missing the file — the rank-0-local-disk
    deployment — falls back to the classic broadcast."""
    from flax import serialization

    st = _state.global_state()
    if broadcast and _state.is_initialized() and st.multiprocess:
        wait_for_writes()
        digest = _file_digest(path) if os.path.exists(path) else None
        if _broadcast_skippable(digest):
            _M_BCAST_SKIPPED.inc()
            with open(path, "rb") as f:
                return serialization.from_bytes(target, f.read())
    if not _state.is_initialized() or _is_saving_process():
        wait_for_writes()
        with open(path, "rb") as f:
            blob = f.read()
        tree = serialization.from_bytes(target, blob)
    else:
        tree = target
    if broadcast and _state.is_initialized():
        tree = broadcast_parameters(tree, root_rank=0)
    return tree


def _file_digest(path: str) -> str:
    """Chunked sha256 — the digest pass must not hold a multi-GB
    checkpoint resident on every rank just to decide whether the
    broadcast can be skipped (the bytes are only read in full on the
    branch that actually deserializes them)."""
    import hashlib

    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 26), b""):
            h.update(chunk)
    return h.hexdigest()


def _broadcast_skippable(digest: Optional[str]) -> bool:
    """True when every rank holds identical local checkpoint bytes —
    proved by an allgather of content digests (a control-plane object
    collective: 64 bytes per rank instead of every parameter byte
    through rank 0).  Deterministic fleet-wide: the gathered list is
    identical everywhere, so every rank takes the same branch."""
    from ..ops.objects import allgather_object

    digests = allgather_object(digest, name="checkpoint.restore.digest")
    return bool(digests) and all(
        d is not None and d == digests[0] for d in digests)


# -- sharded distributed checkpointing (docs/performance.md "Scale-out
# -- control plane") --------------------------------------------------------
#
# ``save_checkpoint`` funnels every parameter byte through rank 0 — the
# last O(world x bytes) cost in the runtime.  The sharded format splits
# the tree's leaves across the fleet: each host serializes and publishes
# ONLY its assigned shards through the background writer, and rank 0
# commits a manifest LAST — after every shard's digest sidecar proves it
# durable.  The ``MANIFEST`` pointer file is atomically renamed onto the
# new manifest only at commit, so a torn fleet (any host killed mid-
# write, rank 0 included) leaves the PREVIOUS complete checkpoint
# loadable and never shadows it with a partial one.  Restore reads the
# shards directly from shared storage — no broadcast, and the save-time
# world size is irrelevant: a checkpoint saved at np=8 reshards onto
# np=2 or np=32 by reassigning which process reads what (elastic resize
# stops round-tripping every byte through rank 0).
#
# Layout under ``directory``:
#   MANIFEST                      -> "manifest-<tag>.json" (atomic ptr)
#   manifest-<tag>.json           committed by rank 0, LAST
#   save-<tag>/shard-NNNNN-of-WWWWW.msgpack   (+ .ok digest sidecars)

MANIFEST_POINTER = "MANIFEST"
SHARDED_FORMAT = "hvd-sharded-checkpoint-v1"

_save_seq: dict = {}
_save_seq_lock = _lockorder.make_lock("checkpoint._save_seq_lock")


def _manifest_timeout() -> float:
    """How long rank 0 waits for the fleet's shard sidecars before
    failing the manifest commit (the torn-fleet bound)."""
    return float(os.environ.get("HVD_TPU_CKPT_MANIFEST_TIMEOUT", "120"))


def shard_assignment(nbytes: list, world: int) -> list:
    """Deterministic leaf -> writer-rank map: greedy largest-first onto
    the least-loaded writer, ties by rank then leaf index, so every
    rank derives the identical assignment with no agreement round."""
    order = sorted(range(len(nbytes)), key=lambda i: (-nbytes[i], i))
    load = [0] * max(1, world)
    assign = [0] * len(nbytes)
    for i in order:
        w = min(range(len(load)), key=lambda r: (load[r], r))
        assign[i] = w
        load[w] += nbytes[i]
    return assign


def _shard_name(rank: int, world: int) -> str:
    return f"shard-{rank:05d}-of-{world:05d}.msgpack"


def _sharded_leaf_specs(leaves: list) -> list:
    import json as _json

    specs = []
    for leaf in leaves:
        if isinstance(leaf, np.ndarray):
            specs.append({"kind": "array", "dtype": str(leaf.dtype),
                          "shape": list(leaf.shape),
                          "nbytes": int(leaf.nbytes)})
        else:
            # Python scalars/strings ride the manifest inline — they
            # are negotiation metadata, not parameter bytes.
            specs.append({"kind": "inline",
                          "value": _json.loads(_json.dumps(leaf)),
                          "nbytes": 0})
    return specs


def save_checkpoint_sharded(directory: str, tree: Any,
                            step: Optional[int] = None,
                            block: bool = False,
                            rank: Optional[int] = None,
                            world: Optional[int] = None,
                            virtual: Optional[bool] = None
                            ) -> CheckpointWrite:
    """Sharded distributed save: THIS process publishes the shards the
    deterministic assignment gives its rank; rank 0 additionally
    commits the manifest once every shard is durable.

    ``rank``/``world`` default to the live fleet.  Passing a ``world``
    different from the live process count is the dryrun/virtual mode:
    this one process writes EVERY shard of the declared layout (how the
    CI reshard gate saves an np=2-layout checkpoint from np=1).
    ``virtual=False`` forces the strict one-rank's-shards behavior even
    when the declared world differs from the live one — the torn-fleet
    tests drive each simulated rank through it separately.

    Multi-process fleets must pass ``step`` — the save tag has to be
    agreed across ranks, and only caller state (the training step) is
    shared by construction.

    Returns a :class:`CheckpointWrite`; on rank 0 ``wait()`` is the
    manifest commit — the save's durability point."""
    import hashlib
    import json as _json

    import jax

    live_world = (_state.global_state().process_count
                  if _state.is_initialized() else 1)
    if rank is None:
        rank = _state.process_index() if _state.is_initialized() else 0
    if world is None:
        world = live_world
    if virtual is None:
        virtual = world != live_world
    host = _host_snapshot(tree)
    leaves, _treedef = jax.tree_util.tree_flatten(host)
    specs = _sharded_leaf_specs(leaves)
    assign = shard_assignment([s["nbytes"] for s in specs], world)
    for i, s in enumerate(specs):
        if s["kind"] == "array":
            s["shard"] = assign[i]
    if step is not None:
        tag = f"s{step}"
    else:
        # The tag must be IDENTICAL on every rank — a per-process
        # counter diverges the moment one worker restarts (elastic
        # rejoin: its counter resets while the fleet's advanced, and
        # every later untagged save times out waiting for a shard in
        # the wrong save-<tag> dir).  Multi-rank fleets must pass
        # ``step`` (shared state by construction); the counter is the
        # single-process / virtual-dryrun convenience only.
        if not virtual and world > 1:
            raise ValueError(
                "save_checkpoint_sharded requires step= in "
                "multi-process mode: the save tag must be agreed "
                "across ranks, and a process-local counter diverges "
                "across elastic restarts")
        with _save_seq_lock:
            _save_seq[directory] = _save_seq.get(directory, 0) + 1
            tag = f"c{_save_seq[directory]}"
    save_dir = os.path.join(directory, f"save-{tag}")
    manifest_path = os.path.join(directory, f"manifest-{tag}.json")
    # Torn-retry detection (committing rank): a save-<tag>/ dir with no
    # committed manifest means a PREVIOUS attempt tore mid-fleet.  Its
    # leftover sidecars must not satisfy this attempt's commit while
    # the owning rank is still rewriting the shard — snapshot the ones
    # OLDER than the staleness margin (a torn attempt being retried is
    # minutes old; a same-attempt fast rank's sidecar is seconds old,
    # and must keep working — ranks complete in any order) and require
    # each to CHANGE (unlink+rewrite) before the commit accepts it.  A
    # rank that never republishes then times the commit out (pointer
    # preserved) instead of silently committing mixed-attempt bytes.
    prior_ok: dict = {}
    if rank == 0 and os.path.isdir(save_dir) \
            and not os.path.exists(manifest_path):
        margin = float(os.environ.get(
            "HVD_TPU_CKPT_STALE_OK_SECONDS", "60"))
        cutoff = time.time() - margin
        for w in range(world):
            ok = os.path.join(save_dir, _shard_name(w, world) + ".ok")
            try:
                st_ = os.stat(ok)
                if st_.st_mtime >= cutoff:
                    continue  # fresh: a same-attempt early completer
                with open(ok) as f:
                    prior_ok[w] = (f.read().strip(), st_.st_mtime)
            except OSError:
                pass
    os.makedirs(save_dir, exist_ok=True)
    writer_ranks = list(range(world)) if virtual else [rank]
    writer = _get_writer()
    handle = CheckpointWrite(manifest_path, performed=True)

    def shard_task(wr: int):
        my = {str(i): leaves[i] for i in range(len(leaves))
              if specs[i]["kind"] == "array" and assign[i] == wr}
        path = os.path.join(save_dir, _shard_name(wr, world))

        def publish() -> None:
            from flax import serialization

            # Invalidate a PREVIOUS attempt's sidecar FIRST: a torn
            # save retried under the same tag must never let the
            # manifest commit observe a stale shard+.ok pair while the
            # fresh shard is still being written.  (Belt: the commit
            # side ALSO snapshots pre-existing sidecars of an
            # uncommitted save dir and accepts each only once it has
            # changed — see ``prior_ok`` in save_checkpoint_sharded.)
            try:
                os.unlink(path + ".ok")
            except OSError:
                pass
            blob = serialization.to_bytes(my)
            _write_bytes(path, blob)
            _write_bytes(path + ".ok",
                         hashlib.sha256(blob).hexdigest().encode())
            _M_SHARDS.inc()

        return publish

    for wr in writer_ranks:
        writer.submit_task(CheckpointWrite(
            os.path.join(save_dir, _shard_name(wr, world)),
            performed=True), shard_task(wr))

    def commit_manifest() -> None:
        deadline = time.monotonic() + _manifest_timeout()
        digests: dict = {}
        while True:
            missing = [w for w in range(world) if str(w) not in digests]
            for w in list(missing):
                ok = os.path.join(save_dir,
                                  _shard_name(w, world) + ".ok")
                try:
                    st_ = os.stat(ok)
                    with open(ok) as f:
                        got = f.read().strip()
                except OSError:
                    continue
                if w in prior_ok and (got, st_.st_mtime) == prior_ok[w]:
                    continue  # previous torn attempt's sidecar,
                    # unchanged — the owning rank has not republished
                digests[str(w)] = got
            if len(digests) == world:
                break
            if time.monotonic() > deadline:
                raise CheckpointError(
                    f"sharded save {tag!r}: shards from writer rank(s) "
                    f"{[w for w in range(world) if str(w) not in digests]} "
                    f"never became durable within "
                    f"{_manifest_timeout():.0f}s; the previous complete "
                    f"checkpoint (MANIFEST pointer) is untouched")
            time.sleep(0.05)
        manifest = {
            "format": SHARDED_FORMAT, "tag": tag, "step": step,
            "world": world, "save_dir": f"save-{tag}",
            "leaves": specs, "shard_digests": digests,
        }
        _write_bytes(handle.path,
                     _json.dumps(manifest, indent=1).encode())
        # The durability point: only a COMPLETE save ever moves the
        # pointer (atomic rename), so a torn fleet can't shadow the
        # previous checkpoint.
        _write_bytes(os.path.join(directory, MANIFEST_POINTER),
                     f"manifest-{tag}.json".encode())
        _M_MANIFESTS.inc()

    if rank == 0:
        writer.submit_task(handle, commit_manifest)
    else:
        # Non-committing ranks: their durability point is their own
        # shard; ride a sentinel task so wait() fences the FIFO.
        writer.submit_task(handle, lambda: None)
    if block:
        handle.wait()
    return handle


def load_sharded_manifest(directory: str) -> dict:
    """The manifest the ``MANIFEST`` pointer names — always the latest
    COMPLETE save (the pointer moves only at commit)."""
    import json as _json

    with open(os.path.join(directory, MANIFEST_POINTER)) as f:
        name = f.read().strip()
    with open(os.path.join(directory, name)) as f:
        return _json.load(f)


def restore_checkpoint_sharded(directory: str, target: Any) -> Any:
    """Restore the latest complete sharded save into ``target``'s
    structure — at ANY world size.  Every process reads the shards it
    needs straight from shared storage (for replicated parameters:
    all of them), verifying each shard against the manifest digest; no
    byte crosses the control plane, so elastic resize restores at disk
    bandwidth instead of rank-0 uplink bandwidth."""
    import hashlib

    import jax
    from flax import serialization

    wait_for_writes()
    manifest = load_sharded_manifest(directory)
    if manifest.get("format") != SHARDED_FORMAT:
        raise CheckpointError(
            f"{directory!r} is not a sharded checkpoint "
            f"(format {manifest.get('format')!r})")
    leaves, treedef = jax.tree_util.tree_flatten(target)
    specs = manifest["leaves"]
    if len(leaves) != len(specs):
        raise CheckpointError(
            f"target structure has {len(leaves)} leaves but the "
            f"checkpoint holds {len(specs)} — the model changed since "
            f"the save")
    out = list(leaves)
    world = int(manifest["world"])
    save_dir = os.path.join(directory, manifest["save_dir"])
    by_shard: dict = {}
    for i, s in enumerate(specs):
        if s["kind"] == "inline":
            out[i] = type(leaves[i])(s["value"]) \
                if leaves[i] is not None else s["value"]
        else:
            by_shard.setdefault(int(s["shard"]), []).append(i)
    for wr, idxs in sorted(by_shard.items()):
        path = os.path.join(save_dir, _shard_name(wr, world))
        with open(path, "rb") as f:
            blob = f.read()
        want = manifest["shard_digests"].get(str(wr))
        got = hashlib.sha256(blob).hexdigest()
        if want != got:
            raise CheckpointError(
                f"shard {os.path.basename(path)} digest mismatch "
                f"({got[:12]} != manifest {str(want)[:12]}) — torn or "
                f"foreign file")
        template = {str(i): np.zeros(tuple(specs[i]["shape"]),
                                     np.dtype(specs[i]["dtype"]))
                    for i in idxs}
        data = serialization.from_bytes(template, blob)
        for i in idxs:
            out[i] = data[str(i)]
    return jax.tree_util.tree_unflatten(treedef, out)


# -- serving checkpoints (hvd-serve, docs/inference.md) --------------------

SERVING_PARAMS_FILE = "params.msgpack"
SERVING_META_FILE = "serving.json"


def save_serving_checkpoint(directory: str, params: Any, cfg: Any,
                            tokenizer: str = "byte",
                            extra: Optional[dict] = None,
                            block: bool = False) -> CheckpointWrite:
    """Export a serving-ready checkpoint: the parameter pytree (flax
    msgpack, via the background writer) plus a ``serving.json`` carrying
    the model config and tokenizer metadata, so
    ``examples/serve_lm.py`` / :func:`load_serving_checkpoint` can
    build an :class:`~horovod_tpu.serving.engine.InferenceEngine` with
    no knowledge of the training script.  Rank-0 only, like every save
    (``examples/transformer_lm.py --export`` rides this)."""
    import json

    import jax.numpy as jnp

    if _state.is_initialized() and not _is_saving_process():
        return CheckpointWrite(None, performed=False)
    os.makedirs(directory, exist_ok=True)
    handle = write_tree_async(
        os.path.join(directory, SERVING_PARAMS_FILE),
        _host_snapshot(params))
    meta = {
        "format": "hvd-serving-checkpoint-v1",
        "model": {
            "vocab_size": cfg.vocab_size, "d_model": cfg.d_model,
            "n_heads": cfg.n_heads, "n_layers": cfg.n_layers,
            "d_ff": cfg.d_ff, "max_seq_len": cfg.max_seq_len,
            "num_experts": cfg.num_experts,
            "dtype": jnp.dtype(cfg.dtype).name,
        },
        "tokenizer": {"kind": tokenizer},
        "extra": extra or {},
    }
    _write_bytes(os.path.join(directory, SERVING_META_FILE),
                 json.dumps(meta, indent=1).encode())
    if block:
        handle.wait()
    return handle


def load_serving_checkpoint(directory: str):
    """Load a :func:`save_serving_checkpoint` export.  Returns
    ``(params, cfg, meta)`` — ``cfg`` a reconstructed
    :class:`~horovod_tpu.models.transformer.TransformerConfig`, ``meta``
    the raw ``serving.json`` dict (tokenizer kind, extras)."""
    import json

    import jax
    import jax.numpy as jnp
    from flax import serialization

    from ..models.transformer import TransformerConfig, init_transformer

    with open(os.path.join(directory, SERVING_META_FILE)) as f:
        meta = json.load(f)
    m = meta["model"]
    cfg = TransformerConfig(
        vocab_size=int(m["vocab_size"]), d_model=int(m["d_model"]),
        n_heads=int(m["n_heads"]), n_layers=int(m["n_layers"]),
        d_ff=int(m["d_ff"]), max_seq_len=int(m["max_seq_len"]),
        num_experts=int(m.get("num_experts", 0)),
        dtype=jnp.dtype(m.get("dtype", "float32")))
    template = init_transformer(jax.random.PRNGKey(0), cfg)
    with open(os.path.join(directory, SERVING_PARAMS_FILE), "rb") as f:
        params = serialization.from_bytes(template, f.read())
    return params, cfg, meta


def resume_epoch(path: str) -> int:
    """Determine the epoch to resume from and agree on it across replicas —
    the reference broadcasts this scalar explicitly
    (keras_imagenet_resnet50.py:47-56)."""
    epoch = 0
    if not _state.is_initialized() or _is_saving_process():
        wait_for_writes()
    step_file = f"{path}.step"
    if os.path.exists(step_file):
        with open(step_file) as f:
            epoch = int(f.read().strip())
    if _state.is_initialized():
        from ..ops import collective as C

        epoch = int(np.asarray(C.broadcast(
            np.asarray(epoch, np.int32), root_rank=0,
            name="resume_from_epoch")))
    return epoch
