"""Chrome-tracing timeline.

TPU-native equivalent of the reference's Horovod Timeline
(horovod/common/timeline.{h,cc}): a ``chrome://tracing``-loadable JSON file
written when ``HOROVOD_TIMELINE=<path>`` is set, on the coordinating process
only (reference: operations.cc:1201-1204).  Per-tensor lifecycle follows the
same state machine UNKNOWN → NEGOTIATING → TOP_LEVEL → ACTIVITY
(timeline.h:34) and tensors are modeled as trace "processes" with pid
metadata so each gets its own row (timeline.cc:59-76).

Activity names are mapped from the reference's MPI/CUDA phases
(docs/timeline.md) to their XLA analogues:

  NEGOTIATE_*          — dynamic-path negotiation (unchanged)
  QUEUE                — host-side enqueue until XLA dispatch
  MEMCPY_IN_FUSION_BUFFER / MEMCPY_OUT_FUSION_BUFFER
                       — flatten/concat into and out of a fusion bucket
  XLA_ALLREDUCE / XLA_ALLGATHER / XLA_BCAST
                       — the compiled collective (≙ MPI_ALLREDUCE /
                         NCCL_ALLREDUCE etc.)
  WAIT_FOR_DATA        — host blocking on device completion

When the native library is built, event formatting/flushing runs in C++
(native/timeline.cc, ≙ common/timeline.cc); this class is the fallback and
the interface both share.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Optional

from ..native import lib as _native
from ..telemetry import flight as _flight

# Flush cadence, seconds (≙ TIMELINE_FLUSH_TIME, timeline.h:32).
_FLUSH_SECONDS = 1.0

# Event phase chars of the Chrome trace format.
_PH_METADATA = "M"
_PH_BEGIN = "B"
_PH_END = "E"
_PH_INSTANT = "i"

# hvd-trace context mirror: when set (trace/__init__.py), every event's
# args carry the propagated (step, cycle) so the rank-0 timeline joins
# against fleet traces on the same keys.  Late-bound module global so
# this module stays importable without the trace layer.
_context_provider = None


def set_context_provider(fn) -> None:
    """Install (or clear, with None) the callable whose dict is merged
    into every event's args (hvd-trace's ``current_args``)."""
    global _context_provider
    _context_provider = fn


class Timeline:
    def __init__(self, path: str):
        from ..analysis import lockorder as _lockorder

        self._path = path
        self._lock = _lockorder.make_lock("Timeline._lock")
        self._native = None
        if _native.NATIVE and hasattr(_native.raw(), "hvd_timeline_create"):
            self._native = _native.raw().hvd_timeline_create(path.encode())
        self._file = None
        self._tensor_pids = {}
        self._next_pid = 1
        self._start = time.monotonic()
        self._last_flush = self._start
        # True until the first event is written: events are emitted with
        # a LEADING ",\n" separator after the first, so the file is one
        # strictly valid JSON array the moment close() writes the "]" —
        # no trailing comma for viewers to tolerate (satellite fix; the
        # parse-it-back test holds json.load to it).
        self._fresh = True
        if self._native is None:
            self._file = open(path, "w")
            self._file.write("[\n")
        # Flight-ring breadcrumb: a forensic dump that shows a timeline
        # was live names the trace file to correlate with.
        _flight.record("timeline_open", path)

    # -- low-level ---------------------------------------------------------
    def _ts_us(self) -> float:
        return (time.monotonic() - self._start) * 1e6

    def _pid_locked(self, tensor: str) -> int:
        pid = self._tensor_pids.get(tensor)
        if pid is None:
            pid = self._next_pid
            self._next_pid += 1
            self._tensor_pids[tensor] = pid
            # Name the "process" row after the tensor (timeline.cc:59-76).
            self._emit_locked({"name": "process_name", "ph": _PH_METADATA,
                               "pid": pid, "args": {"name": tensor}})
            self._emit_locked({"name": "process_sort_index",
                               "ph": _PH_METADATA, "pid": pid,
                               "args": {"sort_index": pid}})
        return pid

    def _emit_locked(self, ev: dict) -> None:
        if self._file is None:
            return
        self._file.write(("" if self._fresh else ",\n") + json.dumps(ev))
        self._fresh = False
        now = time.monotonic()
        if now - self._last_flush > _FLUSH_SECONDS:
            self._file.flush()
            self._last_flush = now

    def _event(self, ph: str, tensor: str, name: str = "",
               args: Optional[dict] = None) -> None:
        # The whole event path holds the lock: writers run on the drain
        # tick thread AND user threads (sync eager submits), while rank 0
        # may concurrently stop_timeline() — the native handle must not
        # be freed under a writer, and a post-close event must be a
        # silent no-op, not a use-after-free.
        # hvd-trace context mirror: begin/instant events carry the
        # propagated (step, cycle) so the timeline's rows join against
        # fleet-trace spans; explicit caller args win on key collision.
        if _context_provider is not None and ph in (_PH_BEGIN,
                                                    _PH_INSTANT):
            ctx = _context_provider()
            if ctx:
                args = {**ctx, **(args or {})}
        with self._lock:
            if self._native is not None:
                _native.raw().hvd_timeline_event(
                    self._native,
                    {"B": 0, "E": 1, "i": 2, "M": 3}[ph],
                    tensor.encode(), name.encode(),
                    json.dumps(args or {}).encode(), 0.0)
                return
            ev = {"ph": ph, "ts": self._ts_us(),
                  "pid": self._pid_locked(tensor)}
            if name:
                ev["name"] = name
            if args:
                ev["args"] = args
            self._emit_locked(ev)

    # -- negotiation phase (timeline.cc:106-134) ---------------------------
    def negotiate_start(self, tensor: str, op_name: str) -> None:
        self._event(_PH_BEGIN, tensor, f"NEGOTIATE_{op_name.upper()}",
                    args={"phase": "NEGOTIATE"})

    def negotiate_rank_ready(self, tensor: str, rank: int,
                             first: bool = False) -> None:
        self._event(_PH_INSTANT, tensor, str(rank))

    def negotiate_end(self, tensor: str) -> None:
        self._event(_PH_END, tensor)

    # -- response cache (ops/cache.py) -------------------------------------
    def cache_event(self, tensor: str, hit: bool) -> None:
        """Instant marker on the tensor's row: its negotiation was
        served from (CACHE_HIT) or missed (CACHE_MISS) the response
        cache, so per-tensor cache wins read straight off the trace."""
        self._event(_PH_INSTANT, tensor,
                    "CACHE_HIT" if hit else "CACHE_MISS",
                    args={"cache": "hit" if hit else "miss"})

    def cache_counter(self, hits: int, misses: int) -> None:
        """Chrome counter track of cumulative response-cache hits vs
        misses (ph="C" renders as a stacked area in the trace viewer).
        The native writer has no counter phase; it records the same
        data as an instant on a dedicated row."""
        with self._lock:
            if self._native is not None:
                _native.raw().hvd_timeline_event(
                    self._native, 2, b"response_cache",
                    b"response_cache",
                    json.dumps({"hit": hits, "miss": misses}).encode(),
                    0.0)
                return
            self._emit_locked({"ph": "C", "ts": self._ts_us(), "pid": 0,
                               "name": "response_cache",
                               "args": {"hit": hits, "miss": misses}})

    # -- top-level + activities (timeline.cc:136-182) ----------------------
    def start(self, tensor: str, op_name: str, args: Optional[dict] = None
              ) -> None:
        self._event(_PH_BEGIN, tensor, op_name.upper(), args)

    def activity_start(self, tensor: str, activity: str) -> None:
        self._event(_PH_BEGIN, tensor, activity)

    def instant(self, tensor: str, name: str,
                args: Optional[dict] = None) -> None:
        """Zero-duration marker on the tensor's row — used for events
        that happen inside one compiled launch and so have no host-side
        duration of their own (e.g. DCN_ALLREDUCE: the hierarchical
        megakernel's cross-slice leg, docs/timeline.md)."""
        self._event(_PH_INSTANT, tensor, name, args)

    def activity_end(self, tensor: str) -> None:
        self._event(_PH_END, tensor)

    def end(self, tensor: str, dtype: str = "", shape: str = "") -> None:
        args = {}
        if dtype:
            args["dtype"] = dtype
        if shape:
            args["shape"] = shape
        self._event(_PH_END, tensor, args=args or None)

    def close(self) -> None:
        """Finalize the trace file.  Idempotent, including against a
        concurrent ``instant()`` writer: the whole close runs under the
        event lock, a second close finds ``_file is None`` and no-ops,
        and an event racing in after the close is a silent no-op in
        ``_emit_locked`` — never a write into a closed file or a stray
        element after the closing ``]``.  The emitted array is strictly
        valid JSON (the separator discipline in ``_emit_locked``); a
        parse-it-back test enforces it."""
        _flight.record("timeline_close", self._path)
        with self._lock:
            if self._native is not None:
                _native.raw().hvd_timeline_close(self._native)
                self._native = None
                return
            if self._file is not None:
                self._emit_locked(
                    {"ph": _PH_INSTANT, "ts": self._ts_us(), "pid": 0,
                     "name": "shutdown"})
                self._file.write("\n]\n")
                self._file.close()
                self._file = None
