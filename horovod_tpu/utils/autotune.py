"""Autotuning of the eager-path runtime parameters.

≙ the post-v0.13 Horovod autotuner (``HOROVOD_AUTOTUNE=1``): Horovod
runs Bayesian optimization over ``HOROVOD_FUSION_THRESHOLD`` and
``HOROVOD_CYCLE_TIME`` while training, scoring each sample by observed
throughput.  The v0.13 reference has only the static env vars
(operations.cc:140, :1207-1210).

TPU redesign: on TPU only the *dynamic* (eager) path has tunable host
machinery — the static pjit path is scheduled entirely by XLA — and its
two knobs span a small, well-understood space.  So instead of a
Gaussian-process loop (hard to reproduce, impossible to unit-test
deterministically), this tuner runs **explore-then-commit over a fixed
grid**: each (fusion_threshold, cycle_time) candidate is measured for a
sample window, scored by reduced bytes/second, and after one sweep the
best candidate is committed for the rest of the job.  Deterministic,
auditable (``HOROVOD_AUTOTUNE_LOG`` writes the same CSV contract as
Horovod's), and still captures the real trade-off: bigger buckets
amortize per-collective overhead until latency-to-first-byte dominates;
shorter cycles cut queueing delay until tick overhead dominates.

Env contract (names follow Horovod):
  HOROVOD_AUTOTUNE=1            enable (coordinator-side only)
  HOROVOD_AUTOTUNE_LOG=<path>   CSV of samples: score,threshold,cycle
  HOROVOD_AUTOTUNE_WARMUP_SAMPLES (default 3) discarded lead-in windows
  HOROVOD_AUTOTUNE_SAMPLE_SECONDS (default 2.0) seconds per candidate
"""

from __future__ import annotations

import itertools
import os
import time
from typing import Callable, List, Optional, Tuple

_MB = 1024 * 1024

# The explored grid.  Thresholds bracket the reference default (64 MB,
# operations.cc:140); cycles bracket the reference tick (5 ms,
# operations.cc:1221).
DEFAULT_THRESHOLDS = [1 * _MB, 4 * _MB, 16 * _MB, 64 * _MB, 128 * _MB]
DEFAULT_CYCLES = [0.002, 0.005, 0.010]


class Autotuner:
    """Explore-then-commit tuner for (fusion_threshold, cycle_time).

    ``record_bytes`` is fed from the drain loop with the payload bytes of
    every completed eager collective; ``maybe_step`` closes a sample
    window when its time is up, scores it, and advances the sweep.  The
    winning configuration is applied through ``apply`` and the tuner
    goes dormant.
    """

    def __init__(self, apply: Callable[[int, float], None],
                 thresholds: Optional[List[int]] = None,
                 cycles: Optional[List[float]] = None,
                 warmup_samples: Optional[int] = None,
                 sample_seconds: Optional[float] = None,
                 log_path: Optional[str] = None,
                 clock: Callable[[], float] = time.monotonic):
        self._apply = apply
        self._clock = clock
        self._configs: List[Tuple[int, float]] = list(itertools.product(
            thresholds or DEFAULT_THRESHOLDS, cycles or DEFAULT_CYCLES))
        self._warmup = int(warmup_samples if warmup_samples is not None
                           else os.environ.get(
                               "HOROVOD_AUTOTUNE_WARMUP_SAMPLES", 3))
        self._sample_s = float(sample_seconds if sample_seconds is not None
                               else os.environ.get(
                                   "HOROVOD_AUTOTUNE_SAMPLE_SECONDS", 2.0))
        self._log_path = log_path or os.environ.get("HOROVOD_AUTOTUNE_LOG")
        self._log_file = None
        if self._log_path:
            self._log_file = open(self._log_path, "w")
            self._log_file.write("score_bytes_per_sec,fusion_threshold,"
                                 "cycle_time_s\n")
        self._idx = -self._warmup  # negative = warmup windows, discarded
        self._bytes = 0
        self._window_start = self._clock()
        self._scores: List[Tuple[float, Tuple[int, float]]] = []
        self.committed: Optional[Tuple[int, float]] = None
        self._set_current()

    # -- wiring ------------------------------------------------------------
    def _current(self) -> Optional[Tuple[int, float]]:
        if 0 <= self._idx < len(self._configs):
            return self._configs[self._idx]
        return None

    def _set_current(self) -> None:
        cfg = self._current()
        if cfg is not None:
            self._apply(*cfg)

    def record_bytes(self, n: int) -> None:
        self._bytes += n

    @property
    def done(self) -> bool:
        return self.committed is not None

    def maybe_step(self) -> None:
        """Close the sample window if its time is up; advance the sweep.
        Cheap when called every drain tick (one clock read)."""
        if self.done:
            return
        now = self._clock()
        if now - self._window_start < self._sample_s:
            return
        elapsed = now - self._window_start
        score = self._bytes / elapsed if elapsed > 0 else 0.0
        cfg = self._current()
        if cfg is not None:  # warmup windows are measured but discarded
            self._scores.append((score, cfg))
            if self._log_file:
                self._log_file.write(f"{score:.1f},{cfg[0]},{cfg[1]}\n")
                self._log_file.flush()
        self._idx += 1
        self._bytes = 0
        self._window_start = now
        nxt = self._current()
        if nxt is not None:
            self._apply(*nxt)
        elif self._idx >= len(self._configs):
            # Sweep complete: commit the best-scoring configuration.
            best = max(self._scores, key=lambda s: s[0])
            self.committed = best[1]
            self._apply(*self.committed)
            if self._log_file:
                self._log_file.write(
                    f"# committed,{self.committed[0]},"
                    f"{self.committed[1]}\n")
                self._log_file.flush()

    def close(self) -> None:
        if self._log_file:
            self._log_file.close()
            self._log_file = None
