"""horovod_tpu.utils"""
