"""XLA executable-launch counting (dispatch-count instrumentation).

The data-plane megakernel work (ops/megakernel.py) collapses the
per-tensor eager choreography of a fused collective cycle into one
compiled launch per fusion group.  That property regresses silently —
one stray ``jnp.reshape`` on the drain thread and the steady state is
back to N dispatches — so it is asserted, not assumed: this module
counts *real* loaded-executable launches at jax's single dispatch choke
point (``pxla.ExecuteReplicated.__call__`` executes every compiled
program: jitted functions AND each eagerly-dispatched primitive), and
the megakernel executor + ``bench.py --mode dataplane`` + the
regression test in tests/test_megakernel.py read the counts.

The patch is installed lazily and only when counting is enabled
(``HVD_TPU_COUNT_DISPATCHES=1`` — set by tests/conftest.py for the
whole tier-1 suite and by the dataplane bench); production runs never
pay the per-dispatch bookkeeping.  Scopes come in two flavors:

* ``record()`` — thread-local: counts only launches issued by the
  calling thread while the scope is open.  Used by the megakernel
  executor to attribute dispatches to one response execution even
  while user threads concurrently classify/place inputs.
* ``record(all_threads=True)`` — global: counts every launch in the
  process.  Used by the bench to measure a whole submit→drain→
  synchronize cycle, wherever the drain happens to run.
"""

from __future__ import annotations

import contextlib
import os
import threading
from dataclasses import dataclass
from typing import List

_tls = threading.local()
_global_scopes: List["DispatchScope"] = []
_install_lock = threading.Lock()
_installed = False


def counting_enabled() -> bool:
    return os.environ.get("HVD_TPU_COUNT_DISPATCHES", "0") == "1"


@dataclass
class DispatchScope:
    """One open counting window; ``count`` is the number of XLA
    executable launches observed since the scope opened."""

    count: int = 0
    all_threads: bool = False


def _bump() -> None:
    for scope in getattr(_tls, "scopes", ()):  # thread-local windows
        scope.count += 1
    if _global_scopes:
        # Benign cross-thread increment race (GIL-serialized bytecode
        # makes torn counts impossible; at worst two racing launches
        # both land) — the bench opens exactly one global scope at a
        # time around an otherwise-quiet process.
        for scope in _global_scopes:
            scope.count += 1


def install() -> bool:
    """Patch the dispatch choke point once.  Returns False when this
    jax version has no recognizable choke point (counting becomes a
    no-op rather than an import error)."""
    global _installed
    with _install_lock:
        if _installed:
            return True
        try:
            from jax._src.interpreters import pxla
        except Exception:  # noqa: BLE001 — jax internals moved
            return False
        target = getattr(pxla, "ExecuteReplicated", None)
        orig = getattr(target, "__call__", None)
        if orig is None:
            return False

        def counted_call(self, *args, **kwargs):
            _bump()
            return orig(self, *args, **kwargs)

        target.__call__ = counted_call
        _installed = True
        return True


@contextlib.contextmanager
def exact_scope():
    """Make EVERY dispatch visible to :func:`record` while open.

    jax's C++ pjit fastpath executes warm calls without touching any
    Python frame, so the patched choke point only sees cold (first)
    launches.  This scope disables fastpath *population* — patching
    ``pjit._get_fastpath_data`` to return None makes the C++ wrapper
    fall back to the Python dispatch path on every call — and clears
    the global C++ PjitFunction caches so previously-warmed functions
    re-enter through it too.  Strictly a measurement mode (tests +
    ``bench.py --mode dataplane`` dispatch counting): warm dispatch
    gets slower while open, results are unchanged.  On exit the
    fastpath is restored (and the caches cleared again so the
    no-fastpath entries cannot linger).
    """
    try:
        from jax._src import pjit as _pjit_mod
    except Exception:  # noqa: BLE001 — jax internals moved
        yield
        return
    orig = getattr(_pjit_mod, "_get_fastpath_data", None)
    caches = [getattr(_pjit_mod, n, None)
              for n in ("_cpp_pjit_cache_fun_only",
                        "_cpp_pjit_cache_explicit_attributes")]
    if orig is None:
        yield
        return

    def _clear_caches():
        for c in caches:
            try:
                c.clear()
            except Exception:  # noqa: BLE001
                pass

    _pjit_mod._get_fastpath_data = lambda *a, **k: None
    _clear_caches()
    try:
        yield
    finally:
        _pjit_mod._get_fastpath_data = orig
        _clear_caches()


@contextlib.contextmanager
def record(all_threads: bool = False):
    """Open a counting window; yields a :class:`DispatchScope` whose
    ``count`` is live while the window is open and final after."""
    scope = DispatchScope(all_threads=all_threads)
    if not install():
        yield scope  # unpatchable jax: counts stay 0 (callers tolerate)
        return
    if all_threads:
        _global_scopes.append(scope)
    else:
        scopes = getattr(_tls, "scopes", None)
        if scopes is None:
            scopes = _tls.scopes = []
        scopes.append(scope)
    try:
        yield scope
    finally:
        if all_threads:
            _global_scopes.remove(scope)
        else:
            _tls.scopes.remove(scope)
