// Native coordinator: negotiation, validation, fusion planning, stall watch.
//
// C++ twin of horovod_tpu/ops/coordinator.py (the executable spec), itself
// the TPU-native re-design of the reference coordinator inside
// BackgroundThreadLoop (horovod/common/operations.cc:222-461, :1072-1115,
// :1328-1374). The reference keeps this machinery in C++ because it sits on
// the latency floor of every collective; ours does the same for the dynamic
// (eager) path while the static pjit path bypasses it entirely.
//
// The steady-state response cache (ops/cache.py) deliberately layers ABOVE
// this implementation, in the Python Coordinator facade: a cache hit skips
// hvd_coord_submit / response construction here entirely, so both the
// native and the Python twin profit identically and the wire parity
// contract (fuzzed in tests/test_coordinator.py) stays about negotiation
// alone. The submit-time nbytes bookkeeping added to the Python twin's
// _PendingTensor mirrors kPayloadBytes accounting here: both resolve a
// response's fusion size once, never per drain tick.

#include <algorithm>
#include <chrono>
#include <cstdio>
#include <map>
#include <mutex>
#include <set>
#include <sstream>
#include <string>
#include <unordered_map>
#include <vector>

#include "wire.h"

namespace hvdtpu {
namespace {

double MonotonicSeconds() {
  return std::chrono::duration<double>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

std::string ShapeStr(const std::vector<int64_t>& s) {
  std::ostringstream os;
  os << "[";
  for (size_t i = 0; i < s.size(); ++i) {
    if (i) os << ", ";
    os << s[i];
  }
  os << "]";
  return os.str();
}

const char* OpName(RequestType t) {
  switch (t) {
    case RequestType::kAllreduce: return "allreduce";
    case RequestType::kAllgather: return "allgather";
    case RequestType::kBroadcast: return "broadcast";
    case RequestType::kJoin: return "join";
    case RequestType::kReducescatter: return "reducescatter";
    case RequestType::kAlltoall: return "alltoall";
  }
  return "?";
}

struct Pending {
  std::vector<Request> requests;
  std::set<int32_t> ranks;
  double first_seen = 0;
};

// Shared ERROR text for an abandoned collective — byte-identical with
// ops/coordinator.py::_withdraw_message (parity fuzz-tested).
std::string WithdrawMessage(const std::string& name, int32_t rank) {
  std::ostringstream os;
  os << "Collective " << name << " was abandoned: rank " << rank
     << " timed out waiting for the remaining ranks; the operation fails"
     << " on all ranks.";
  return os.str();
}

class Coordinator {
 public:
  Coordinator(int size, int64_t fusion_threshold)
      : size_(size), fusion_threshold_(fusion_threshold) {}

  // ≙ IncrementTensorCount (operations.cc:222-247).
  // Returns 1 when all replicas reported, 0 pending, -1 duplicate rank.
  // Joined ranks (hvd.join) count as ready for every tensor; the last
  // JOIN queues the release response (after this batch's data).
  int Submit(const Request& req) {
    std::lock_guard<std::mutex> g(mu_);
    if (req.request_type == RequestType::kJoin) {
      joined_.insert(req.request_rank);
      last_joined_ = req.request_rank;
      for (const auto& kv : table_) {
        if (Complete(kv.second) &&
            std::find(ready_.begin(), ready_.end(), kv.first) ==
                ready_.end())
          ready_.push_back(kv.first);
      }
      if (static_cast<int>(joined_.size()) == size_) {
        Response rel;
        rel.response_type = ResponseType::kJoin;
        rel.tensor_sizes.push_back(last_joined_);
        join_release_.push_back(std::move(rel));
        joined_.clear();
        return 1;
      }
      return 0;
    }
    Pending& p = table_[req.tensor_name];
    if (p.requests.empty()) p.first_seen = MonotonicSeconds();
    if (p.ranks.count(req.request_rank)) return -1;
    p.ranks.insert(req.request_rank);
    p.requests.push_back(req);
    if (Complete(p)) {
      ready_.push_back(req.tensor_name);
      return 1;
    }
    return 0;
  }

  bool Complete(const Pending& p) const {
    std::set<int32_t> u = p.ranks;
    u.insert(joined_.begin(), joined_.end());
    return static_cast<int>(u.size()) == size_;
  }

  // ≙ ConstructMPIResponse (operations.cc:255-461).
  Response ConstructResponse(const std::string& name) {
    Pending p = std::move(table_[name]);
    table_.erase(name);
    std::sort(p.requests.begin(), p.requests.end(),
              [](const Request& a, const Request& b) {
                return a.request_rank < b.request_rank;
              });
    const Request& first = p.requests[0];
    std::string error;

    for (size_t i = 1; i < p.requests.size() && error.empty(); ++i) {
      const Request& r = p.requests[i];
      if (r.tensor_type != first.tensor_type) {
        std::ostringstream os;
        os << "Mismatched data types: One rank had type "
           << DataTypeName(first.tensor_type) << ", but another rank had type "
           << DataTypeName(r.tensor_type) << ".";
        error = os.str();
      }
    }
    for (size_t i = 1; i < p.requests.size() && error.empty(); ++i) {
      const Request& r = p.requests[i];
      if (r.request_type != first.request_type) {
        std::ostringstream os;
        os << "Mismatched collective operations: One rank did an "
           << OpName(first.request_type) << ", but another rank did an "
           << OpName(r.request_type) << ".";
        error = os.str();
      }
    }
    RequestType op = first.request_type;
    std::vector<int64_t> tensor_sizes;
    if (error.empty() && op == RequestType::kAllreduce) {
      for (size_t i = 1; i < p.requests.size() && error.empty(); ++i) {
        const Request& r = p.requests[i];
        if (r.tensor_shape != first.tensor_shape) {
          std::ostringstream os;
          os << "Mismatched allreduce tensor shapes: One rank sent a tensor "
             << "of shape " << ShapeStr(first.tensor_shape)
             << ", but another rank sent a tensor of shape "
             << ShapeStr(r.tensor_shape) << ".";
          error = os.str();
        }
      }
    }
    // Reducescatter (post-v0.13): full shape agreement; never completes
    // via joins (the joined rank must participate for its chunk).
    if (error.empty() && op == RequestType::kReducescatter) {
      for (size_t i = 1; i < p.requests.size() && error.empty(); ++i) {
        const Request& r = p.requests[i];
        if (r.tensor_shape != first.tensor_shape) {
          std::ostringstream os;
          os << "Mismatched reducescatter tensor shapes: One rank sent a "
             << "tensor of shape " << ShapeStr(first.tensor_shape)
             << ", but another rank sent a tensor of shape "
             << ShapeStr(r.tensor_shape) << ".";
          error = os.str();
        }
      }
      if (error.empty() && static_cast<int>(p.requests.size()) < size_) {
        error = "Reducescatter cannot complete after a rank has joined: "
                "every rank must participate to receive its chunk of the "
                "result.";
      }
    }
    // Reduce-op agreement (post-v0.13 hvd op= API; v0.13 hard-codes
    // MPI_SUM).  Must stay message-identical with ops/coordinator.py.
    if (error.empty() && (op == RequestType::kAllreduce ||
                          op == RequestType::kReducescatter)) {
      for (size_t i = 1; i < p.requests.size() && error.empty(); ++i) {
        const Request& r = p.requests[i];
        if (r.reduce_op != first.reduce_op) {
          std::ostringstream os;
          os << "Mismatched reduce operations: One rank specified reduce op "
             << ReduceOpName(first.reduce_op)
             << ", but another rank specified reduce op "
             << ReduceOpName(r.reduce_op) << ".";
          error = os.str();
        }
      }
      if (error.empty() && op == RequestType::kAllreduce &&
          static_cast<int>(p.requests.size()) < size_ &&
          first.reduce_op != ReduceOp::kSum &&
          first.reduce_op != ReduceOp::kAverage) {
        std::ostringstream os;
        os << "Allreduce with reduce op " << ReduceOpName(first.reduce_op)
           << " cannot complete after a rank has joined: a joined rank's "
           << "zero contribution is only an identity for sum/average.";
        error = os.str();
      }
    }
    if (error.empty() && op == RequestType::kAllgather) {
      if (first.tensor_shape.empty()) {
        error = "Rank zero tried to gather a rank-zero tensor.";
      }
      for (size_t i = 1; i < p.requests.size() && error.empty(); ++i) {
        const Request& r = p.requests[i];
        if (r.tensor_shape.size() != first.tensor_shape.size()) {
          std::ostringstream os;
          os << "Mismatched allgather tensor shapes: One rank sent a tensor "
             << "of rank " << first.tensor_shape.size()
             << ", but another rank sent a tensor of rank "
             << r.tensor_shape.size() << ".";
          error = os.str();
          break;
        }
        for (size_t dim = 1; dim < first.tensor_shape.size(); ++dim) {
          if (r.tensor_shape[dim] != first.tensor_shape[dim]) {
            std::ostringstream os;
            os << "Mismatched allgather tensor shapes: One rank sent a tensor "
               << "with dimension " << dim << " equal to "
               << first.tensor_shape[dim]
               << ", but another rank sent a tensor with dimension " << dim
               << " equal to " << r.tensor_shape[dim] << ".";
            error = os.str();
            break;
          }
        }
      }
      if (error.empty()) {
        // RANK-indexed extents: joined ranks contribute 0 rows.
        std::map<int32_t, int64_t> by_rank;
        for (const Request& r : p.requests)
          by_rank[r.request_rank] =
              r.tensor_shape.empty() ? 0 : r.tensor_shape[0];
        for (int32_t r = 0; r < size_; ++r) {
          auto it = by_rank.find(r);
          tensor_sizes.push_back(it == by_rank.end() ? 0 : it->second);
        }
      }
    }
    // Alltoall (post-v0.13): trailing-dim agreement; per-rank splits
    // must cover dim 0; never completes via joins.  tensor_sizes will
    // carry the full split matrix row-major by sender.  Must stay
    // message-identical with ops/coordinator.py.
    std::vector<int64_t> alltoall_sizes;
    if (error.empty() && op == RequestType::kAlltoall) {
      if (first.tensor_shape.empty())
        error = "An alltoall tensor needs at least one dimension.";
      for (size_t i = 1; i < p.requests.size() && error.empty(); ++i) {
        const Request& r = p.requests[i];
        bool trailing_ok =
            r.tensor_shape.size() == first.tensor_shape.size() &&
            std::equal(r.tensor_shape.begin() + 1, r.tensor_shape.end(),
                       first.tensor_shape.begin() + 1);
        if (!trailing_ok) {
          std::ostringstream os;
          os << "Mismatched alltoall tensor shapes: One rank sent a tensor "
             << "of shape " << ShapeStr(first.tensor_shape)
             << ", but another rank sent a tensor of shape "
             << ShapeStr(r.tensor_shape) << ".";
          error = os.str();
        }
      }
      if (error.empty() && static_cast<int>(p.requests.size()) < size_) {
        error = "Alltoall cannot complete after a rank has joined: every "
                "rank must both send and receive.";
      }
      if (error.empty()) {
        for (const Request& r : p.requests) {
          int64_t d0 = r.tensor_shape[0];
          if (r.splits.empty()) {
            if (d0 % size_ != 0) {
              std::ostringstream os;
              os << "Alltoall without splits needs dim 0 divisible by the "
                 << "rank count (" << size_ << "); rank " << r.request_rank
                 << " sent " << d0 << " rows.";
              error = os.str();
              break;
            }
            for (int i = 0; i < size_; ++i)
              alltoall_sizes.push_back(d0 / size_);
          } else {
            int64_t total = 0;
            bool neg = false;
            for (int64_t s : r.splits) {
              total += s;
              if (s < 0) neg = true;
            }
            if (static_cast<int>(r.splits.size()) != size_ || total != d0 ||
                neg) {
              std::ostringstream os;
              os << "Invalid alltoall splits from rank " << r.request_rank
                 << ": " << ShapeStr(r.splits)
                 << " must have one non-negative entry per rank (" << size_
                 << ") summing to its dim 0 (" << d0 << ").";
              error = os.str();
              break;
            }
            for (int64_t s : r.splits) alltoall_sizes.push_back(s);
          }
        }
      }
    }
    if (error.empty() && op == RequestType::kBroadcast) {
      for (size_t i = 1; i < p.requests.size() && error.empty(); ++i) {
        const Request& r = p.requests[i];
        if (r.root_rank != first.root_rank) {
          std::ostringstream os;
          os << "Mismatched broadcast root ranks: One rank specified root "
             << "rank " << first.root_rank
             << ", but another rank specified root rank " << r.root_rank
             << ".";
          error = os.str();
        }
      }
      for (size_t i = 1; i < p.requests.size() && error.empty(); ++i) {
        const Request& r = p.requests[i];
        if (r.tensor_shape != first.tensor_shape) {
          std::ostringstream os;
          os << "Mismatched broadcast tensor shapes: One rank sent a tensor "
             << "of shape " << ShapeStr(first.tensor_shape)
             << ", but another rank sent a tensor of shape "
             << ShapeStr(r.tensor_shape) << ".";
          error = os.str();
        }
      }
      if (error.empty() && static_cast<int>(p.requests.size()) < size_) {
        bool root_present = false;
        for (const Request& r : p.requests)
          if (r.request_rank == first.root_rank) root_present = true;
        if (!root_present) {
          std::ostringstream os;
          os << "Broadcast root rank " << first.root_rank
             << " has joined; a joined rank cannot be the source of a "
             << "broadcast.";
          error = os.str();
        }
      }
    }
    // Host/device placement agreement (≙ operations.cc:418-440).
    for (size_t i = 1; i < p.requests.size() && error.empty(); ++i) {
      const Request& r = p.requests[i];
      if ((r.device == kCpuDeviceId) != (first.device == kCpuDeviceId)) {
        std::ostringstream os;
        os << "Mismatched host/device selection: One rank specified device "
           << first.device << ", but another rank specified device "
           << r.device << ".";
        error = os.str();
      }
    }

    Response resp;
    resp.tensor_names = {name};
    resp.process_set_id = first.process_set_id;
    if (!error.empty()) {
      resp.response_type = ResponseType::kError;
      resp.error_message = error;
      return resp;
    }
    dtype_by_name_[name] = first.tensor_type;
    for (const Request& r : p.requests) resp.devices.push_back(r.device);
    // dtype + shape ride every data response for joined ranks' zero
    // contributions; BROADCAST also carries its root in tensor_sizes.
    resp.tensor_type = static_cast<int>(first.tensor_type);
    resp.tensor_shapes.push_back(first.tensor_shape);
    switch (op) {
      case RequestType::kAllreduce:
        resp.response_type = ResponseType::kAllreduce;
        resp.reduce_op = first.reduce_op;
        break;
      case RequestType::kReducescatter:
        resp.response_type = ResponseType::kReducescatter;
        resp.reduce_op = first.reduce_op;
        break;
      case RequestType::kAlltoall:
        resp.response_type = ResponseType::kAlltoall;
        resp.tensor_sizes = std::move(alltoall_sizes);
        break;
      case RequestType::kAllgather:
        resp.response_type = ResponseType::kAllgather;
        resp.tensor_sizes = std::move(tensor_sizes);
        break;
      case RequestType::kBroadcast:
        resp.response_type = ResponseType::kBroadcast;
        resp.tensor_sizes.push_back(first.root_rank);
        break;
      case RequestType::kJoin:
        break;  // unreachable: JOIN never enters the tensor table
    }
    return resp;
  }

  // Round 4; no reference equivalent — the reference can only hang when
  // a rank gives up (operations.cc:1290-1326).  Drops the pending entry
  // and queues an ERROR response so every rank fails the op promptly.
  // No-op when negotiation already completed (the op is about to finish).
  void Withdraw(const std::string& name, int32_t rank) {
    std::lock_guard<std::mutex> g(mu_);
    if (std::find(ready_.begin(), ready_.end(), name) != ready_.end())
      return;
    table_.erase(name);
    Response resp;
    resp.response_type = ResponseType::kError;
    resp.tensor_names.push_back(name);
    resp.error_message = WithdrawMessage(name, rank);
    withdrawn_.push_back(std::move(resp));
  }

  // Bytes of one replica's tensor for a response: the queue-side size
  // table when present, else shape × dtype from the response itself (a
  // process set excluding the controller has no entries in ITS queue;
  // an unbounded 0 fallback would defeat the threshold).  Must mirror
  // ops/coordinator.py::nbytes_of.
  int64_t NBytesOf(const Response& r,
                   const std::unordered_map<std::string, int64_t>& sizes) {
    auto it = sizes.find(r.tensor_names.empty() ? std::string()
                                                : r.tensor_names[0]);
    if (it != sizes.end()) return it->second;
    int64_t n = 1;
    if (!r.tensor_shapes.empty())
      for (int64_t d : r.tensor_shapes[0]) n *= d;
    DataType dt = DataType::kFloat32;
    auto dit = dtype_by_name_.find(r.tensor_names.empty()
                                       ? std::string()
                                       : r.tensor_names[0]);
    if (dit != dtype_by_name_.end()) dt = dit->second;
    return n * DataTypeSize(dt);
  }

  // ≙ the response fusion loop (operations.cc:1328-1374): same-device,
  // same-dtype ALLREDUCE responses merge under the byte threshold.
  // `sizes` maps tensor name → payload bytes of one replica's tensor.
  int PollResponses(const std::unordered_map<std::string, int64_t>& sizes) {
    std::lock_guard<std::mutex> g(mu_);
    std::vector<Response> responses;
    for (const auto& n : ready_) responses.push_back(ConstructResponse(n));
    ready_.clear();
    std::vector<Response> fused = std::move(withdrawn_);
    withdrawn_.clear();
    for (size_t i = 0; i < responses.size(); ++i) {
      Response r = std::move(responses[i]);
      // Adasum never fuses: its dot products are per-tensor scale
      // adaptations, not elementwise reductions.
      if (r.response_type != ResponseType::kAllreduce ||
          r.reduce_op == ReduceOp::kAdasum) {
        fused.push_back(std::move(r));
        continue;
      }
      int64_t total = NBytesOf(r, sizes);
      DataType dt = dtype_by_name_[r.tensor_names[0]];
      for (size_t j = i + 1; j < responses.size();) {
        Response& nxt = responses[j];
        int64_t nbytes = NBytesOf(nxt, sizes);
        if (nxt.response_type == ResponseType::kAllreduce &&
            nxt.devices == r.devices && nxt.reduce_op == r.reduce_op &&
            nxt.process_set_id == r.process_set_id &&
            !nxt.tensor_names.empty() &&
            dtype_by_name_[nxt.tensor_names[0]] == dt &&
            total + nbytes <= fusion_threshold_) {
          r.tensor_names.push_back(nxt.tensor_names[0]);
          r.tensor_shapes.insert(r.tensor_shapes.end(),
                                 nxt.tensor_shapes.begin(),
                                 nxt.tensor_shapes.end());
          total += nbytes;
          responses.erase(responses.begin() + j);
        } else {
          ++j;
        }
      }
      fused.push_back(std::move(r));
    }
    for (const auto& r : fused)
      for (const auto& n : r.tensor_names) dtype_by_name_.erase(n);
    // JOIN release LAST: joined ranks execute this batch's data
    // responses (zero contributions) before being released.
    for (auto& jr : join_release_) fused.push_back(std::move(jr));
    join_release_.clear();
    out_buffer_ = PackResponseList(fused);
    return static_cast<int>(fused.size());
  }

  ssize_t FetchResponses(char* out, size_t cap) {
    std::lock_guard<std::mutex> g(mu_);
    if (out_buffer_.size() > cap) return -1;
    std::memcpy(out, out_buffer_.data(), out_buffer_.size());
    return static_cast<ssize_t>(out_buffer_.size());
  }

  // Autotune hook: the fusion threshold is runtime-adjustable (≙ the
  // post-v0.13 HOROVOD_AUTOTUNE subsystem re-tuning
  // TensorFusionThresholdBytes between cycles).
  void SetFusionThreshold(int64_t v) {
    std::lock_guard<std::mutex> g(mu_);
    fusion_threshold_ = v;
  }

  // ≙ CheckForStalledTensors (operations.cc:1072-1115).
  std::string CheckStalled(double threshold_seconds) {
    std::lock_guard<std::mutex> g(mu_);
    double now = MonotonicSeconds();
    std::ostringstream os;
    for (const auto& kv : table_) {
      const Pending& p = kv.second;
      double waited = now - p.first_seen;
      if (waited > threshold_seconds) {
        std::set<int32_t> missing;
        for (int32_t r = 0; r < size_; ++r)
          if (!p.ranks.count(r)) missing.insert(r);
        os << "Tensor " << kv.first << " has been pending for "
           << static_cast<long>(waited) << "s; ready replicas: [";
        bool f = true;
        for (int32_t r : p.ranks) {
          if (!f) os << ", ";
          os << r;
          f = false;
        }
        os << "]; waiting on replicas: [";
        f = true;
        for (int32_t r : missing) {
          if (!f) os << ", ";
          os << r;
          f = false;
        }
        os << "]. One or more replicas submitted this collective and are "
           << "waiting for the remaining replicas to do the same.\n";
      }
    }
    return os.str();
  }

 private:
  int size_;
  int64_t fusion_threshold_;
  std::mutex mu_;
  std::map<std::string, Pending> table_;
  std::vector<std::string> ready_;
  std::vector<Response> withdrawn_;
  std::set<int32_t> joined_;
  int32_t last_joined_ = -1;
  std::vector<Response> join_release_;
  std::unordered_map<std::string, DataType> dtype_by_name_;
  std::string out_buffer_;
};

// Side-table parser: u16 count, then (u16 klen, key, i64 bytes)*.
bool ParseSizes(const uint8_t* buf, size_t len,
                std::unordered_map<std::string, int64_t>* out) {
  size_t off = 0;
  uint16_t n;
  if (off + 2 > len) return false;
  std::memcpy(&n, buf + off, 2);
  off += 2;
  for (uint16_t i = 0; i < n; ++i) {
    uint16_t klen;
    if (off + 2 > len) return false;
    std::memcpy(&klen, buf + off, 2);
    off += 2;
    if (off + klen + 8 > len) return false;
    std::string key(reinterpret_cast<const char*>(buf + off), klen);
    off += klen;
    int64_t v;
    std::memcpy(&v, buf + off, 8);
    off += 8;
    (*out)[key] = v;
  }
  return true;
}

}  // namespace
}  // namespace hvdtpu

extern "C" {

void* hvd_coord_create(int size, long long fusion_threshold) {
  return new hvdtpu::Coordinator(size, fusion_threshold);
}

void hvd_coord_destroy(void* c) {
  delete static_cast<hvdtpu::Coordinator*>(c);
}

int hvd_coord_submit(void* c, const char* buf, int len) {
  hvdtpu::Request req;
  if (hvdtpu::Request::Unpack(reinterpret_cast<const uint8_t*>(buf), len,
                              &req) < 0)
    return -2;
  return static_cast<hvdtpu::Coordinator*>(c)->Submit(req);
}

int hvd_coord_poll_responses(void* c, const char* sizes_buf, int sizes_len,
                             double now_unused) {
  (void)now_unused;
  std::unordered_map<std::string, int64_t> sizes;
  if (!hvdtpu::ParseSizes(reinterpret_cast<const uint8_t*>(sizes_buf),
                          sizes_len, &sizes))
    return -1;
  return static_cast<hvdtpu::Coordinator*>(c)->PollResponses(sizes);
}

int hvd_coord_fetch_responses(void* c, char* out, int cap) {
  return static_cast<int>(
      static_cast<hvdtpu::Coordinator*>(c)->FetchResponses(out, cap));
}

void hvd_coord_withdraw(void* c, const char* name, int len, int rank) {
  static_cast<hvdtpu::Coordinator*>(c)->Withdraw(std::string(name, len),
                                                 rank);
}

void hvd_coord_set_fusion_threshold(void* c, long long v) {
  static_cast<hvdtpu::Coordinator*>(c)->SetFusionThreshold(v);
}

int hvd_coord_check_stalled(void* c, double threshold, char* out, int cap) {
  std::string s =
      static_cast<hvdtpu::Coordinator*>(c)->CheckStalled(threshold);
  if (static_cast<int>(s.size()) > cap) return -1;
  std::memcpy(out, s.data(), s.size());
  return static_cast<int>(s.size());
}

}  // extern "C"
