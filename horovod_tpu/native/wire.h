// Wire format for control messages — C++ twin of horovod_tpu/ops/wire.py.
//
// TPU-native re-design of the reference's flatbuffers control layer
// (horovod/common/mpi_message.{h,cc}, wire/mpi_message.fbs): hand-rolled
// little-endian structs, since the messages are tiny and only travel the
// dynamic path (eager ops / variable allgather / error negotiation).
#pragma once

#include <cstdint>
#include <cstring>
#include <string>
#include <vector>

namespace hvdtpu {

// ≙ MPIDataType (mpi_message.h:26-36) + bfloat16/float16 for TPU.
enum class DataType : uint8_t {
  kUint8 = 0, kInt8 = 1, kUint16 = 2, kInt16 = 3, kInt32 = 4, kInt64 = 5,
  kFloat32 = 6, kFloat64 = 7, kBool = 8, kBfloat16 = 9, kFloat16 = 10,
  kUint32 = 11, kUint64 = 12,
};

const char* DataTypeName(DataType t);
int DataTypeSize(DataType t);  // bytes per element (≙ wire.dtype_size)

// ≙ MPIRequestType / MPIResponseType (mpi_message.h); JOIN is the
// post-v0.13 uneven-workload barrier (see ops/wire.py).
enum class RequestType : uint8_t { kAllreduce = 0, kAllgather = 1,
                                   kBroadcast = 2, kJoin = 3,
                                   kReducescatter = 4, kAlltoall = 5 };
// kCacheFlush is the response-cache epoch marker (ops/cache.py): the
// cache itself layers ABOVE both coordinator implementations in the
// Python facade (ops/coordinator.py Coordinator), so the native
// coordinator never produces or consumes it — the value is mirrored
// here only to keep the wire enum spaces identical.
enum class ResponseType : uint8_t { kAllreduce = 0, kAllgather = 1,
                                    kBroadcast = 2, kError = 3, kDone = 4,
                                    kShutdown = 5, kJoin = 6,
                                    kReducescatter = 7, kAlltoall = 8,
                                    kCacheFlush = 9 };

// Allreduce reduction operator (post-v0.13 Horovod op= API; the v0.13
// reference hard-codes MPI_SUM).  ≙ ops/wire.py ReduceOp.
enum class ReduceOp : uint8_t { kAverage = 0, kSum = 1, kAdasum = 2,
                                kMin = 3, kMax = 4, kProduct = 5 };

const char* ReduceOpName(ReduceOp op);

constexpr int kCpuDeviceId = -1;  // ≙ CPU_DEVICE_ID (common.h:28)

// ≙ MPIRequest (mpi_message.h:43-85).
struct Request {
  RequestType request_type;
  DataType tensor_type;
  int32_t request_rank;
  int32_t root_rank;
  int32_t device;
  // ALLREDUCE only; coordinator-validated for cross-rank agreement.
  ReduceOp reduce_op = ReduceOp::kAverage;
  // Process set (0 = global); ranks are set-local for non-global sets.
  uint16_t process_set_id = 0;
  std::string tensor_name;
  std::vector<int64_t> tensor_shape;
  // ALLTOALL only: dim-0 rows sent to each destination (empty = even).
  std::vector<int64_t> splits;

  std::string Pack() const;
  // Returns bytes consumed, or -1 on malformed input.
  static ssize_t Unpack(const uint8_t* buf, size_t len, Request* out);
};

// ≙ MPIResponse (mpi_message.h:112-157).
struct Response {
  ResponseType response_type;
  std::vector<std::string> tensor_names;
  std::string error_message;
  std::vector<int32_t> devices;
  // ALLGATHER: dim-0 per rank (0 for joined ranks); BROADCAST:
  // [root_rank]; JOIN: [last joining rank].
  std::vector<int64_t> tensor_sizes;
  // hvd.join support: validated dtype (-1 = absent, 255 on the wire)
  // and per-fused-tensor shapes, for joined ranks' zero contributions.
  int tensor_type = -1;
  std::vector<std::vector<int64_t>> tensor_shapes;
  // ALLREDUCE: validated reduction operator (fusion is homogeneous in it).
  ReduceOp reduce_op = ReduceOp::kAverage;
  // Process set the response belongs to (0 = global).
  uint16_t process_set_id = 0;

  std::string Pack() const;
};

std::string PackResponseList(const std::vector<Response>& rs);

}  // namespace hvdtpu
