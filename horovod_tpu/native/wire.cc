// See wire.h. Layouts must stay byte-identical with ops/wire.py.

#include "wire.h"

namespace hvdtpu {

namespace {

template <typename T>
void Append(std::string* out, T v) {
  out->append(reinterpret_cast<const char*>(&v), sizeof(T));
}

template <typename T>
bool ReadLE(const uint8_t* buf, size_t len, size_t* off, T* out) {
  if (*off + sizeof(T) > len) return false;
  std::memcpy(out, buf + *off, sizeof(T));
  *off += sizeof(T);
  return true;
}

}  // namespace

int DataTypeSize(DataType t) {
  switch (t) {
    case DataType::kUint8: case DataType::kInt8: case DataType::kBool:
      return 1;
    case DataType::kUint16: case DataType::kInt16:
    case DataType::kBfloat16: case DataType::kFloat16:
      return 2;
    case DataType::kInt32: case DataType::kFloat32: case DataType::kUint32:
      return 4;
    case DataType::kInt64: case DataType::kFloat64: case DataType::kUint64:
      return 8;
  }
  return 4;
}

const char* ReduceOpName(ReduceOp op) {
  switch (op) {
    case ReduceOp::kAverage: return "average";
    case ReduceOp::kSum: return "sum";
    case ReduceOp::kAdasum: return "adasum";
    case ReduceOp::kMin: return "min";
    case ReduceOp::kMax: return "max";
    case ReduceOp::kProduct: return "product";
  }
  return "unknown";
}

const char* DataTypeName(DataType t) {
  switch (t) {
    case DataType::kUint8: return "uint8";
    case DataType::kInt8: return "int8";
    case DataType::kUint16: return "uint16";
    case DataType::kInt16: return "int16";
    case DataType::kInt32: return "int32";
    case DataType::kInt64: return "int64";
    case DataType::kFloat32: return "float32";
    case DataType::kFloat64: return "float64";
    case DataType::kBool: return "bool";
    case DataType::kBfloat16: return "bfloat16";
    case DataType::kFloat16: return "float16";
    case DataType::kUint32: return "uint32";
    case DataType::kUint64: return "uint64";
  }
  return "unknown";
}

std::string Request::Pack() const {
  std::string out;
  Append<uint8_t>(&out, static_cast<uint8_t>(request_type));
  Append<uint8_t>(&out, static_cast<uint8_t>(tensor_type));
  Append<int32_t>(&out, request_rank);
  Append<int32_t>(&out, root_rank);
  Append<int32_t>(&out, device);
  Append<uint8_t>(&out, static_cast<uint8_t>(reduce_op));
  Append<uint16_t>(&out, process_set_id);
  Append<uint16_t>(&out, static_cast<uint16_t>(tensor_name.size()));
  out.append(tensor_name);
  Append<uint8_t>(&out, static_cast<uint8_t>(tensor_shape.size()));
  for (int64_t d : tensor_shape) Append<int64_t>(&out, d);
  Append<uint16_t>(&out, static_cast<uint16_t>(splits.size()));
  for (int64_t s : splits) Append<int64_t>(&out, s);
  return out;
}

ssize_t Request::Unpack(const uint8_t* buf, size_t len, Request* out) {
  size_t off = 0;
  uint8_t rt, tt, rop, ndim;
  uint16_t nlen;
  if (!ReadLE(buf, len, &off, &rt)) return -1;
  if (!ReadLE(buf, len, &off, &tt)) return -1;
  if (!ReadLE(buf, len, &off, &out->request_rank)) return -1;
  if (!ReadLE(buf, len, &off, &out->root_rank)) return -1;
  if (!ReadLE(buf, len, &off, &out->device)) return -1;
  if (!ReadLE(buf, len, &off, &rop)) return -1;
  if (!ReadLE(buf, len, &off, &out->process_set_id)) return -1;
  if (!ReadLE(buf, len, &off, &nlen)) return -1;
  if (off + nlen > len) return -1;
  out->tensor_name.assign(reinterpret_cast<const char*>(buf + off), nlen);
  off += nlen;
  if (!ReadLE(buf, len, &off, &ndim)) return -1;
  out->tensor_shape.clear();
  for (uint8_t i = 0; i < ndim; ++i) {
    int64_t d;
    if (!ReadLE(buf, len, &off, &d)) return -1;
    out->tensor_shape.push_back(d);
  }
  uint16_t nspl;
  if (!ReadLE(buf, len, &off, &nspl)) return -1;
  out->splits.clear();
  for (uint16_t i = 0; i < nspl; ++i) {
    int64_t s;
    if (!ReadLE(buf, len, &off, &s)) return -1;
    out->splits.push_back(s);
  }
  out->request_type = static_cast<RequestType>(rt);
  out->tensor_type = static_cast<DataType>(tt);
  out->reduce_op = static_cast<ReduceOp>(rop);
  return static_cast<ssize_t>(off);
}

std::string Response::Pack() const {
  std::string out;
  Append<uint8_t>(&out, static_cast<uint8_t>(response_type));
  Append<uint16_t>(&out, static_cast<uint16_t>(tensor_names.size()));
  for (const auto& n : tensor_names) {
    Append<uint16_t>(&out, static_cast<uint16_t>(n.size()));
    out.append(n);
  }
  Append<uint32_t>(&out, static_cast<uint32_t>(error_message.size()));
  out.append(error_message);
  Append<uint16_t>(&out, static_cast<uint16_t>(devices.size()));
  for (int32_t d : devices) Append<int32_t>(&out, d);
  Append<uint16_t>(&out, static_cast<uint16_t>(tensor_sizes.size()));
  for (int64_t s : tensor_sizes) Append<int64_t>(&out, s);
  Append<uint8_t>(&out, tensor_type < 0 ? 255
                                        : static_cast<uint8_t>(tensor_type));
  Append<uint16_t>(&out, static_cast<uint16_t>(tensor_shapes.size()));
  for (const auto& shape : tensor_shapes) {
    Append<uint8_t>(&out, static_cast<uint8_t>(shape.size()));
    for (int64_t d : shape) Append<int64_t>(&out, d);
  }
  Append<uint8_t>(&out, static_cast<uint8_t>(reduce_op));
  Append<uint16_t>(&out, process_set_id);
  return out;
}

std::string PackResponseList(const std::vector<Response>& rs) {
  std::string out;
  Append<uint16_t>(&out, static_cast<uint16_t>(rs.size()));
  for (const auto& r : rs) out.append(r.Pack());
  return out;
}

}  // namespace hvdtpu
