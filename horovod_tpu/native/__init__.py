"""horovod_tpu.native"""
