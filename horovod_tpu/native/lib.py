"""ctypes loader for the native runtime library ``libhvdtpu.so``.

The reference implements its runtime (coordinator, wire protocol, timeline,
handle manager — horovod/common/*.cc, horovod/torch/handle_manager.cc) in
C++; this package does the same for the pieces that remain host-side under
the TPU design:

* ``handle_manager.cc``  — atomic async-handle bookkeeping
                           (≙ torch/handle_manager.cc)
* ``wire.cc``            — compact binary serialization of control messages
                           (≙ common/mpi_message.cc + wire/mpi_message.fbs)
* ``coordinator.cc``     — name-keyed request table, readiness counting,
                           cross-replica shape/dtype/device validation,
                           fusion planning, stall detection
                           (≙ common/operations.cc:222-461, :1072-1115)
* ``timeline.cc``        — Chrome-tracing JSON writer (≙ common/timeline.cc)

Loading strategy: try the prebuilt ``libhvdtpu.so`` next to this file; if
absent, attempt a quick in-tree build with ``make`` (the sources are small);
if that fails (no toolchain), fall back to pure-Python implementations with
identical observable behavior so the package always works from a fresh
checkout.  ``NATIVE`` reports which path is active.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_DIR = os.path.dirname(os.path.abspath(__file__))
_SO_PATH = os.path.join(_DIR, "libhvdtpu.so")

_lib = None
NATIVE = False


def _needs_build() -> bool:
    """True when the .so is absent or older than any source file."""
    if not os.path.exists(_SO_PATH):
        return True
    so_mtime = os.path.getmtime(_SO_PATH)
    for name in os.listdir(_DIR):
        if name.endswith((".cc", ".h")) or name == "Makefile":
            if os.path.getmtime(os.path.join(_DIR, name)) > so_mtime:
                return True
    return False


def _try_build() -> bool:
    """Build under an exclusive file lock so concurrent ranks importing
    after a source edit serialize; the Makefile links to a temp name and
    renames, so a parallel ``CDLL`` never maps a half-written library."""
    try:
        import fcntl

        with open(os.path.join(_DIR, ".build.lock"), "w") as lock:
            fcntl.flock(lock, fcntl.LOCK_EX)
            if _needs_build():  # re-check: another rank may have built
                subprocess.run(
                    ["make", "-s", "-C", _DIR],
                    check=True,
                    capture_output=True,
                    timeout=120,
                )
        return os.path.exists(_SO_PATH)
    except Exception:
        return False


def _load() -> None:
    global _lib, NATIVE
    if os.environ.get("HVD_TPU_DISABLE_NATIVE"):
        return
    # Rebuild when a .cc/.h changed — a silently stale binary would
    # desync the native coordinator from its Python twin.  Fresh .so:
    # no subprocess, just mtime stats.
    if _needs_build() and os.path.exists(os.path.join(_DIR, "Makefile")):
        _try_build()
    if not os.path.exists(_SO_PATH):
        return  # no toolchain and no prebuilt library: Python fallback
    try:
        _lib = ctypes.CDLL(_SO_PATH)
    except OSError:
        _lib = None
        return
    # Signatures.
    _lib.hvd_handle_manager_create.restype = ctypes.c_void_p
    _lib.hvd_handle_manager_allocate.argtypes = [ctypes.c_void_p]
    _lib.hvd_handle_manager_allocate.restype = ctypes.c_int
    _lib.hvd_handle_manager_mark_done.argtypes = [ctypes.c_void_p, ctypes.c_int]
    _lib.hvd_handle_manager_poll.argtypes = [ctypes.c_void_p, ctypes.c_int]
    _lib.hvd_handle_manager_poll.restype = ctypes.c_int
    _lib.hvd_handle_manager_release.argtypes = [ctypes.c_void_p, ctypes.c_int]
    _lib.hvd_handle_manager_destroy.argtypes = [ctypes.c_void_p]

    _lib.hvd_coord_create.argtypes = [ctypes.c_int, ctypes.c_longlong]
    _lib.hvd_coord_create.restype = ctypes.c_void_p
    _lib.hvd_coord_destroy.argtypes = [ctypes.c_void_p]
    _lib.hvd_coord_submit.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int]
    _lib.hvd_coord_submit.restype = ctypes.c_int
    _lib.hvd_coord_poll_responses.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int, ctypes.c_double]
    _lib.hvd_coord_poll_responses.restype = ctypes.c_int
    _lib.hvd_coord_fetch_responses.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int]
    _lib.hvd_coord_fetch_responses.restype = ctypes.c_int
    _lib.hvd_coord_check_stalled.argtypes = [
        ctypes.c_void_p, ctypes.c_double, ctypes.c_char_p, ctypes.c_int]
    _lib.hvd_coord_check_stalled.restype = ctypes.c_int
    if hasattr(_lib, "hvd_coord_withdraw"):  # absent in a stale prebuilt
        _lib.hvd_coord_withdraw.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int, ctypes.c_int]
    if hasattr(_lib, "hvd_coord_set_fusion_threshold"):
        _lib.hvd_coord_set_fusion_threshold.argtypes = [
            ctypes.c_void_p, ctypes.c_longlong]

    _lib.hvd_timeline_create.argtypes = [ctypes.c_char_p]
    _lib.hvd_timeline_create.restype = ctypes.c_void_p
    _lib.hvd_handle_manager_create.argtypes = []
    _lib.hvd_timeline_event.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.c_char_p, ctypes.c_char_p,
        ctypes.c_char_p, ctypes.c_double]
    _lib.hvd_timeline_close.argtypes = [ctypes.c_void_p]
    NATIVE = True


_load()


# ---------------------------------------------------------------------------
# Handle manager facade (native when available, Python fallback otherwise).
# ---------------------------------------------------------------------------

class _PyHandleManager:
    """Python fallback mirroring native/handle_manager.cc (itself mirroring
    reference torch/handle_manager.cc:21-51)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._next = 0
        self._done: dict[int, bool] = {}

    def allocate(self) -> int:
        with self._lock:
            h = self._next
            self._next += 1
            self._done[h] = False
            return h

    def mark_done(self, h: int) -> None:
        with self._lock:
            if h in self._done:
                self._done[h] = True

    def poll(self, h: int) -> bool:
        with self._lock:
            return self._done.get(h, False)

    def release(self, h: int) -> None:
        with self._lock:
            self._done.pop(h, None)


def handle_manager_create():
    if NATIVE:
        return _lib.hvd_handle_manager_create()
    return _PyHandleManager()


def handle_manager_allocate(hm) -> int:
    if NATIVE:
        return _lib.hvd_handle_manager_allocate(hm)
    return hm.allocate()


def handle_manager_mark_done(hm, h: int) -> None:
    if NATIVE:
        _lib.hvd_handle_manager_mark_done(hm, h)
    else:
        hm.mark_done(h)


def handle_manager_poll(hm, h: int) -> bool:
    if NATIVE:
        return bool(_lib.hvd_handle_manager_poll(hm, h))
    return hm.poll(h)


def handle_manager_release(hm, h: int) -> None:
    if NATIVE:
        _lib.hvd_handle_manager_release(hm, h)
    else:
        hm.release(h)


def raw() -> ctypes.CDLL | None:
    return _lib
