"""Training-loop callbacks — TPU-native port of horovod.keras.callbacks.

Same four callbacks, same semantics (reference: horovod/keras/callbacks.py),
bound to :class:`horovod_tpu.frontends.loop.Trainer` instead of a Keras
model.  LR mutation goes through ``optax.inject_hyperparams`` state (no
recompilation) instead of ``K.set_value``.
"""

from __future__ import annotations

from typing import Callable, Optional, Union

import numpy as np

from .core import state as _state
from .parallel.data import broadcast_parameters


class Callback:
    def set_trainer(self, trainer) -> None:
        self.trainer = trainer


class BroadcastGlobalVariablesCallback(Callback):
    """Broadcast parameters (and optimizer state) from root at train start
    so every replica begins identical — required for fresh random inits and
    for checkpoint restores (≙ keras/callbacks.py:8-34)."""

    def __init__(self, root_rank: int = 0):
        self.root_rank = root_rank

    def on_train_begin(self, logs=None) -> None:
        self.trainer.params = broadcast_parameters(
            self.trainer.params, root_rank=self.root_rank)
        if getattr(self.trainer, "model_state", None) is not None:
            self.trainer.model_state = broadcast_parameters(
                self.trainer.model_state, root_rank=self.root_rank)


def _average_metric(allreduce_fn, metric: str, value):
    """Allreduce-average one logged metric; returns None for values that
    must pass through untouched (strings, objects).  The reference
    averages ANY logged value (keras/callbacks.py:37-87), so arrays
    (per-class accuracies, confusion rows) average too.

    Dtype contract: the average is computed in
    ``promote_types(dtype, float32)``, so float64 (and wider) inputs
    keep their dtype instead of being silently truncated to float32
    (the pre-round-6 behavior); ints average as floats (an averaged
    count is fractional).  Scalars come back as Python floats (the
    historical contract), arrays as ndarrays of the accumulation
    dtype.  NOTE: without ``jax_enable_x64`` the on-device reduction
    itself still runs in float32 — the contract here is the *dtype* of
    the result; full float64 wire precision additionally needs x64
    enabled."""
    try:
        arr = np.asarray(value)
    except Exception:
        return None
    if arr.dtype.kind not in "biuf":
        return None
    acc = np.promote_types(arr.dtype, np.float32)
    red = allreduce_fn(arr.astype(acc, copy=False), average=True,
                       name=f"metric.{metric}")
    out = np.asarray(red)
    if arr.ndim == 0:
        return float(out)
    return out.astype(acc, copy=False)


class MetricAverageCallback(Callback):
    """Average epoch metrics across replicas at epoch end, in place, so
    metric-driven callbacks (early stopping, LR plateau) see global values
    (≙ keras/callbacks.py:37-87).  Metrics are reduced in sorted-name order
    for cross-process determinism, as the reference does
    (keras/callbacks.py:72-73).  Any numeric log averages — scalars AND
    arrays; non-numeric values pass through."""

    def on_epoch_end(self, epoch: int, logs=None) -> None:
        from .ops import collective as C

        if not logs:
            return
        for metric in sorted(logs.keys()):
            red = _average_metric(C.allreduce, metric, logs[metric])
            if red is not None:
                logs[metric] = red


#: Default metric selection for :class:`MetricsLogger` — the handful
#: that answers "is the control plane healthy" at a glance; pass
#: ``metrics="all"`` for every scalar metric in the registry.
_DEFAULT_LOGGED_METRICS = (
    "collective.submitted",
    "collective.completed",
    "collective.errors",
    "cache.hits",
    "cache.misses",
    "events.stall_warnings",
    "events.dead_peers",
    "handles.live",
)


class MetricsLogger(Callback):
    """Attach hvd-telemetry values to the epoch logs (docs/metrics.md).

    At each epoch end the selected registry metrics are written into
    ``logs`` under ``<prefix><name>`` (scalars only — histograms log
    their ``count``), so downstream logging callbacks, CSV writers and
    early-stopping hooks see control-plane health next to the model
    metrics; ``verbose=1`` also prints one summary line.

    ``metrics`` is an iterable of registry names, ``"all"`` for every
    metric, or None for a curated control-plane-health default.
    """

    def __init__(self, metrics=None, prefix: str = "hvd/",
                 verbose: int = 0):
        self.metrics = metrics
        self.prefix = prefix
        self.verbose = verbose

    def on_epoch_end(self, epoch: int, logs=None) -> None:
        from . import telemetry

        snap = telemetry.metrics()
        if self.metrics == "all":
            names = sorted(snap)
        elif self.metrics is None:
            names = [n for n in _DEFAULT_LOGGED_METRICS if n in snap]
        elif isinstance(self.metrics, str):
            # A single metric name (not the "all" sentinel): treat it
            # as a one-element selection instead of iterating its
            # characters and silently logging nothing.
            names = [self.metrics] if self.metrics in snap else []
        else:
            names = [n for n in self.metrics if n in snap]
        rendered = {}
        for name in names:
            m = snap[name]
            v = m.get("count") if m.get("type") == "histogram" \
                else m.get("value")
            if v is None:
                continue
            rendered[name] = v
            if logs is not None:
                logs[self.prefix + name] = v
        if self.verbose:
            line = ", ".join(f"{k}={v}" for k, v in rendered.items())
            print(f"[hvd-telemetry] epoch {epoch}: {line}")


class LearningRateScheduleCallback(Callback):
    """Set ``lr = initial_lr * multiplier(epoch)`` between ``start_epoch``
    and ``end_epoch`` (≙ keras/callbacks.py:90-199).

    ``multiplier`` is a constant or ``f(epoch) -> factor``; with
    ``staircase=False`` adjustment happens every batch with fractional
    epochs ``epoch + batch/steps_per_epoch``.  ``momentum_correction``
    rescales momentum by ``new_lr/old_lr`` for the duration of the batch
    (Goyal et al., arXiv:1706.02677 — the same correction the reference
    applies, keras/callbacks.py:161-165).
    """

    def __init__(self, multiplier: Union[float, Callable[[float], float]],
                 start_epoch: int = 0, end_epoch: Optional[int] = None,
                 staircase: bool = True, momentum_correction: bool = True,
                 steps_per_epoch: Optional[int] = None):
        self.start_epoch = start_epoch
        self.end_epoch = end_epoch
        self.staircase = staircase
        self.momentum_correction = momentum_correction
        self.steps_per_epoch = steps_per_epoch
        self.initial_lr: Optional[float] = None
        self.restore_momentum: Optional[float] = None
        self.current_epoch: int = 0
        if not callable(multiplier):
            self.staircase = True
            self.multiplier = lambda epoch: multiplier
        else:
            self.multiplier = multiplier

    def _adjust_learning_rate(self, epoch: float) -> None:
        old_lr = self.trainer.lr
        new_lr = self.initial_lr * self.multiplier(epoch)
        self.trainer.lr = new_lr
        if self.momentum_correction and self.trainer.momentum is not None \
                and old_lr > 0:
            self.restore_momentum = self.trainer.momentum
            self.trainer.momentum = self.restore_momentum * new_lr / old_lr

    def _restore_momentum_if_needed(self) -> None:
        if self.restore_momentum is not None:
            self.trainer.momentum = self.restore_momentum
            self.restore_momentum = None

    def on_train_begin(self, logs=None) -> None:
        self.initial_lr = self.trainer.lr
        if not self.staircase and not self.steps_per_epoch:
            self.steps_per_epoch = self.trainer.steps_per_epoch
            if not self.steps_per_epoch:
                raise ValueError(
                    "steps_per_epoch is required for smooth (staircase="
                    "False) schedules.")

    def on_epoch_begin(self, epoch: int, logs=None) -> None:
        self.current_epoch = epoch

    def on_batch_begin(self, batch: int, logs=None) -> None:
        if (self.current_epoch < self.start_epoch or
                (self.end_epoch is not None
                 and self.current_epoch >= self.end_epoch)):
            return
        if self.staircase and batch == 0:
            self._adjust_learning_rate(self.current_epoch)
        elif not self.staircase:
            epoch = self.current_epoch + float(batch) / self.steps_per_epoch
            self._adjust_learning_rate(epoch)

    def on_batch_end(self, batch: int, logs=None) -> None:
        self._restore_momentum_if_needed()

    def on_epoch_end(self, epoch: int, logs=None) -> None:
        if logs is not None:
            logs["lr"] = self.trainer.lr


class LearningRateWarmupCallback(LearningRateScheduleCallback):
    """Gradual warmup ``lr/size → lr`` over ``warmup_epochs``
    (Goyal et al.; ≙ keras/callbacks.py:202-259, same multiplier formula:
    ``1/size * (epoch * (size-1)/warmup + 1)``)."""

    def __init__(self, warmup_epochs: int = 5,
                 momentum_correction: bool = True,
                 steps_per_epoch: Optional[int] = None, verbose: int = 0):
        self.warmup_epochs = warmup_epochs
        self.verbose = verbose

        def multiplier(epoch):
            size = _state.size()
            # Nudge so epoch boundaries land on round values
            # (≙ keras/callbacks.py:243-247).
            epoch += 1.0 / self.steps_per_epoch
            return 1.0 / size * (epoch * (size - 1) / warmup_epochs + 1)

        super().__init__(multiplier, start_epoch=0, end_epoch=warmup_epochs,
                         staircase=False,
                         momentum_correction=momentum_correction,
                         steps_per_epoch=steps_per_epoch)

    def on_epoch_end(self, epoch: int, logs=None) -> None:
        super().on_epoch_end(epoch, logs)
        if epoch == self.end_epoch - 1 and self.verbose > 0:
            print(f"\nEpoch {epoch + 1}: finished gradual learning rate "
                  f"warmup to {self.trainer.lr:g}.")


def warmup_then_decay_schedule(base_lr: float, warmup_epochs: int,
                               steps_per_epoch: int,
                               decay_epochs_and_factors=None):
    """The same warmup math as an *optax schedule* — the fully-static
    alternative for jit-everything training (no callback machinery,
    compiler sees the whole schedule)."""
    import optax

    size = _state.size()
    warmup_steps = warmup_epochs * steps_per_epoch
    # Segments: [warmup ramp][base_lr until first decay][decay segments...]
    # with len(boundaries) == len(schedules) - 1.
    schedules = [
        optax.linear_schedule(init_value=base_lr / size, end_value=base_lr,
                              transition_steps=warmup_steps),
        optax.constant_schedule(base_lr),
    ]
    boundaries = [warmup_steps]
    for epoch, factor in (decay_epochs_and_factors or []):
        schedules.append(optax.constant_schedule(base_lr * factor))
        boundaries.append(epoch * steps_per_epoch)
    return optax.join_schedules(schedules, boundaries)
