"""Sparse-gradient path tests (≙ the reference's IndexedSlices allreduce,
tensorflow/__init__.py:67-78, and the word2vec example that exercises it)."""

import jax
from horovod_tpu.core import compat as _compat
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from horovod_tpu.ops import sparse as S
from horovod_tpu.models import word2vec as W


def test_sparse_allreduce_union_of_rows(hvd):
    """Each replica contributes different rows; result is the union with
    averaged values — exactly the gather-of-(values, indices) semantics."""
    size = hvd.size()
    dense_shape = (100, 4)
    per = []
    for r in range(size):
        nnz = (r % 3) + 1  # variable nnz per replica → Allgatherv path
        idx = jnp.asarray([10 * r + k for k in range(nnz)], jnp.int32)
        vals = jnp.full((nnz, 4), float(r + 1), jnp.float32)
        per.append(S.IndexedSlices(vals, idx, dense_shape))
    out = S.allreduce(per, average=False)
    assert out.values.shape[0] == sum((r % 3) + 1 for r in range(size))
    dense = S.as_dense(out)
    # Each replica's rows landed at its indices with its value.
    arr = np.asarray(dense)
    for r in range(size):
        for k in range((r % 3) + 1):
            np.testing.assert_allclose(arr[10 * r + k],
                                       np.full(4, float(r + 1)))


def test_sparse_allreduce_average_divides_values(hvd):
    sl = S.IndexedSlices(jnp.ones((2, 3)), jnp.asarray([0, 1], jnp.int32),
                         (10, 3))
    out = S.allreduce(sl, average=True)
    # Replicated contribution gathered from `size` replicas then averaged:
    # size*nnz rows of 1/size.
    assert out.values.shape[0] == 2 * hvd.size()
    np.testing.assert_allclose(np.asarray(out.values),
                               np.full((2 * hvd.size(), 3),
                                       1.0 / hvd.size()), rtol=1e-6)
    dense = S.as_dense(out)
    np.testing.assert_allclose(np.asarray(dense[0]), np.ones(3), rtol=1e-6)


def test_as_dense_accumulates_duplicates(hvd):
    sl = S.IndexedSlices(jnp.ones((3, 2)),
                         jnp.asarray([5, 5, 7], jnp.int32), (10, 2))
    dense = np.asarray(S.as_dense(sl))
    np.testing.assert_allclose(dense[5], [2.0, 2.0])
    np.testing.assert_allclose(dense[7], [1.0, 1.0])
    assert dense.sum() == 6.0


def test_apply_to_embedding_rows(hvd):
    emb = jnp.zeros((8, 2))
    sl = S.IndexedSlices(jnp.ones((2, 2)), jnp.asarray([1, 3], jnp.int32),
                         (8, 2))
    out = np.asarray(S.apply_to(emb, sl, scale=-0.5))
    np.testing.assert_allclose(out[1], [-0.5, -0.5])
    np.testing.assert_allclose(out[3], [-0.5, -0.5])
    assert out.sum() == -2.0


def test_word2vec_sparse_training_step(hvd):
    """End-to-end word2vec step: dense grad → sparse slices → sparse
    allreduce → scatter update; embedding moves only on touched rows."""
    vocab, dim = 50, 16
    params = W.init_params(vocab, dim)
    corpus = W.synthetic_corpus(vocab, 2000)
    rng = np.random.RandomState(0)
    centers, targets = W.skipgram_batch(rng, corpus, batch_size=16)
    negs = rng.randint(0, vocab, size=8).astype("int32")

    def loss_fn(emb):
        p = params._replace(embeddings=emb)
        return W.nce_loss(p, jnp.asarray(centers), jnp.asarray(targets),
                          jnp.asarray(negs))

    dense_grad = jax.grad(loss_fn)(params.embeddings)
    sl = S.sparse_grad_from_dense(dense_grad, jnp.asarray(centers))
    red = S.allreduce(sl, average=True)
    new_emb = S.apply_to(params.embeddings, red, scale=-0.5)
    # Untouched rows unchanged.
    untouched = sorted(set(range(vocab)) - set(centers.tolist()))[0]
    np.testing.assert_allclose(np.asarray(new_emb[untouched]),
                               np.asarray(params.embeddings[untouched]))
    # Loss decreased after the sparse update.
    assert float(loss_fn(new_emb)) < float(loss_fn(params.embeddings))


def test_sparse_grad_from_dense_no_padding_duplication(hvd):
    """Regression: duplicate touched rows (incl. the last row) must not
    double-apply any row's gradient via unique() padding."""
    dense = jnp.zeros((10, 2)).at[9].set(1.0).at[5].set(2.0)
    touched = jnp.asarray([5, 5, 9], jnp.int32)
    sl = S.sparse_grad_from_dense(dense, touched)
    assert sl.values.shape[0] == 2  # unique rows only
    out = np.asarray(S.as_dense(sl))
    np.testing.assert_allclose(out[9], [1.0, 1.0])  # not 2x
    np.testing.assert_allclose(out[5], [2.0, 2.0])


def test_allreduce_dispatches_indexed_slices(hvd):
    """hvd.allreduce on IndexedSlices takes the sparse path transparently
    (≙ tensorflow/__init__.py:67-78) and matches the dense result."""
    import horovod_tpu as H

    dense_shape = (20, 3)
    sl = S.IndexedSlices(jnp.full((2, 3), 4.0), jnp.asarray([3, 7]),
                         dense_shape)
    out = H.allreduce(sl, average=True, name="dispatch.sparse")
    assert isinstance(out, S.IndexedSlices)
    got = np.asarray(S.as_dense(out))
    want = np.asarray(S.as_dense(sl))  # every replica contributed the same
    np.testing.assert_allclose(got, want, rtol=1e-6)


def test_distributed_optimizer_sparse_matches_dense(hvd):
    """DistributedOptimizer.update with an IndexedSlices leaf produces the
    same update as the equivalent dense gradient (eager path)."""
    import horovod_tpu as H

    dense_shape = (12, 4)
    dense_grad = jnp.zeros(dense_shape).at[2].set(1.5).at[9].set(-0.5)
    sparse_grad = S.sparse_grad_from_dense(dense_grad,
                                           jnp.asarray([2, 9], jnp.int32))
    params = {"emb": jnp.ones(dense_shape), "w": jnp.ones((4,))}
    opt = H.DistributedOptimizer(optax.sgd(0.1))
    state0 = opt.init(params)

    g_dense = {"emb": dense_grad, "w": jnp.full((4,), 2.0)}
    g_sparse = {"emb": sparse_grad, "w": jnp.full((4,), 2.0)}
    upd_dense, _ = opt.update(g_dense, state0, params)
    upd_sparse, _ = opt.update(g_sparse, state0, params)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(np.asarray(a),
                                                np.asarray(b), rtol=1e-6),
        upd_dense, upd_sparse)


def test_distributed_optimizer_sparse_as_dense_override(hvd):
    """sparse_as_dense=True densifies before the exchange (the reference's
    device_dense routing choice) with identical results."""
    import horovod_tpu as H

    dense_shape = (8, 2)
    sparse_grad = S.IndexedSlices(jnp.full((1, 2), 3.0),
                                  jnp.asarray([5], jnp.int32), dense_shape)
    params = {"emb": jnp.zeros(dense_shape)}
    for flag in (False, True):
        opt = H.DistributedOptimizer(optax.sgd(1.0), sparse_as_dense=flag)
        upd, _ = opt.update({"emb": sparse_grad}, opt.init(params), params)
        out = np.asarray(upd["emb"])
        np.testing.assert_allclose(out[5], [-3.0, -3.0], rtol=1e-6)
        assert np.all(out[:5] == 0) and np.all(out[6:] == 0)


def test_static_path_sparse_gradients(hvd):
    """IndexedSlices leaves reduce inside a shard_map trace via all_gather
    (the SPMD spelling of the sparse exchange)."""
    from jax.sharding import PartitionSpec as P

    from horovod_tpu.parallel.data import allreduce_gradients

    size = hvd.size()
    dense_shape = (size * 2, 3)

    def step(vals, idxs):
        vals = jnp.squeeze(vals, 0)
        idxs = jnp.squeeze(idxs, 0)
        sl = S.IndexedSlices(vals, idxs, dense_shape)
        red = allreduce_gradients({"e": sl}, average=False)["e"]
        return S.as_dense(red)[None]

    mesh = hvd.mesh()
    vals = jnp.stack([jnp.full((1, 3), float(r + 1)) for r in range(size)])
    idxs = jnp.stack([jnp.asarray([2 * r], jnp.int32) for r in range(size)])
    fn = jax.jit(_compat.shard_map(step, mesh=mesh,
                               in_specs=(P("hvd"), P("hvd")),
                               out_specs=P("hvd"), check_vma=False))
    out = np.asarray(fn(hvd.shard(vals), hvd.shard(idxs)))
    for r in range(size):
        np.testing.assert_allclose(out[r, 2 * r], float(r + 1))
