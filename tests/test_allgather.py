"""Allgather tests: rank-order concat, variable dim-0, mismatch errors
(≙ reference test_tensorflow.py:307-427, test_torch.py:296-360)."""

import jax.numpy as jnp
import numpy as np
import pytest

DTYPES = [jnp.uint8, jnp.int32, jnp.int64, jnp.float32]
DIMS = [1, 2, 3]


@pytest.mark.parametrize("dtype", DTYPES)
@pytest.mark.parametrize("dim", DIMS)
def test_allgather_equal_sizes(hvd, dtype, dim):
    """Each replica contributes a rank-constant block; the gathered result
    must contain each replica's block at its rank offset
    (≙ test_horovod_allgather, test_tensorflow.py:307-343)."""
    size = hvd.size()
    shape = (4,) + (7,) * (dim - 1)
    stack = jnp.stack([jnp.full(shape, r, dtype) for r in range(size)])
    out = hvd.allgather(hvd.shard(stack))
    assert out.shape == (4 * size,) + shape[1:]
    arr = np.asarray(out.astype(jnp.float64))
    for r in range(size):
        block = arr[r * 4:(r + 1) * 4]
        assert (block == r).all(), f"replica {r} block corrupted"


def test_allgather_replicated(hvd):
    x = jnp.arange(6, dtype=jnp.float32).reshape(2, 3)
    out = hvd.allgather(x)
    assert out.shape == (2 * hvd.size(), 3)
    np.testing.assert_allclose(np.asarray(out),
                               np.tile(np.asarray(x), (hvd.size(), 1)))


def test_allgather_variable_sizes(hvd):
    """Variable dim-0 per replica — the MPI_Allgatherv path, requiring the
    size-negotiation round (≙ test_horovod_allgather_variable_size,
    test_tensorflow.py:345-391)."""
    size = hvd.size()
    sizes = [(r % 3) + 1 for r in range(size)]
    pieces = [jnp.full((sizes[r], 5), r, jnp.float32) for r in range(size)]
    out = hvd.allgather(pieces)
    assert out.shape == (sum(sizes), 5)
    arr = np.asarray(out)
    off = 0
    for r in range(size):
        block = arr[off:off + sizes[r]]
        assert (block == r).all()
        off += sizes[r]


def test_allgather_ndim_mismatch_raises(hvd):
    if hvd.size() < 2:
        pytest.skip("needs >1 replica")
    from horovod_tpu.ops.coordinator import PyCoordinator
    from horovod_tpu.ops.wire import Request, RequestType, DataType

    # Private coordinator: the shared one is drained by the background
    # tick thread, which would race these direct injections.
    coord = PyCoordinator(hvd.size(), 64 << 20)
    name = "gather.mismatch.ndim"
    for r in range(hvd.size()):
        shape = (2, 3) if r % 2 == 0 else (2, 3, 4)
        coord.submit(Request(r, RequestType.ALLGATHER,
                             DataType.FLOAT32, name, -1, -1, shape))
    resps = coord.poll_responses({name: 24})
    assert resps[0].response_type.name == "ERROR"
    assert "sent a tensor of rank" in resps[0].error_message


def test_allgather_dim_mismatch_raises(hvd):
    """Non-first dimension mismatch (first dim may differ, others not)
    (≙ test_tensorflow.py:393-427)."""
    if hvd.size() < 2:
        pytest.skip("needs >1 replica")
    from horovod_tpu.ops.coordinator import PyCoordinator
    from horovod_tpu.ops.wire import Request, RequestType, DataType

    coord = PyCoordinator(hvd.size(), 64 << 20)
    name = "gather.mismatch.dim"
    for r in range(hvd.size()):
        shape = (2, 3) if r % 2 == 0 else (5, 4)
        coord.submit(Request(r, RequestType.ALLGATHER,
                             DataType.FLOAT32, name, -1, -1, shape))
    resps = coord.poll_responses({name: 24})
    assert resps[0].response_type.name == "ERROR"
    assert "dimension 1" in resps[0].error_message


def test_allgather_list_through_public_api_with_mismatch(hvd):
    """Ragged non-first dims through the public list API raise
    HorovodError end-to-end."""
    if hvd.size() < 2:
        pytest.skip("needs >1 replica")
    pieces = [jnp.zeros((2, 3 + (r % 2)), jnp.float32)
              for r in range(hvd.size())]
    with pytest.raises(Exception) as ei:
        hvd.allgather(pieces)
    assert "Mismatched" in str(ei.value) or "dimension" in str(ei.value)
