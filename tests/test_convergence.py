"""Quantized-allreduce convergence harness (ISSUE 6 acceptance gate).

The quality claim — int8/int4 wire reduction with stochastic rounding +
error feedback trains like fp32 — is TESTED here, not asserted: MNIST
and a tiny transformer LM run the real dynamic path (eager gradient
allreduce through the quantized megakernels) and their loss curves must
stay inside a tolerance band of the fp32 curve.

Per-replica gradients come from ``vmap(grad(loss))`` over the batch
shards — mathematically the data-parallel setup (per-shard grads,
AVERAGE allreduce) without needing shard_map, so every reduction goes
through the coordinator → fusion → megakernel pipeline under test.

``slow``-marked: three full training runs per model.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from horovod_tpu.ops import megakernel as mk

pytestmark = pytest.mark.slow


def _train(hvd, policy, init_fn, grad_fn, loss_fn, batch_shards,
           full_batch, steps, lr, name):
    """SGD loop with the gradient mean taken by the REAL dynamic-path
    grouped allreduce under ``policy``; returns the loss curve."""
    hvd.set_compression(default=policy)
    try:
        params = init_fn()
        losses = []
        for _ in range(steps):
            grads = grad_fn(params, batch_shards)  # leaves [n, ...]
            leaves, treedef = jax.tree_util.tree_flatten(grads)
            red = hvd.grouped_allreduce(
                [hvd.shard(np.asarray(leaf)) for leaf in leaves],
                average=True, name=name)
            mean = jax.tree_util.tree_unflatten(
                treedef, [jnp.asarray(r)[0] for r in red])
            params = jax.tree_util.tree_map(
                lambda p, g: p - lr * g, params, mean)
            losses.append(float(loss_fn(params, full_batch)))
        return np.asarray(losses)
    finally:
        hvd.set_compression()


def _band_check(base, quant, rel_band, abs_band):
    """The parity gate: the quantized curve tracks fp32 within a band
    scaled by how much the fp32 run actually learned."""
    drop = base[0] - base[-1]
    tol = max(abs_band, rel_band * drop)
    gap = np.abs(quant - base).max()
    assert gap <= tol, (
        f"quantized loss curve diverged from fp32 by {gap:.4f} "
        f"(allowed {tol:.4f}); fp32 {base[0]:.4f}->{base[-1]:.4f}, "
        f"quant {quant[0]:.4f}->{quant[-1]:.4f}")
    # And the quantized run itself must have learned.
    assert quant[-1] - base[-1] <= tol
    assert quant[-1] < quant[0] - 0.5 * drop


def _mnist_setup(hvd):
    from horovod_tpu.models.mnist import (MnistMLP, cross_entropy_loss,
                                          init_params, synthetic_mnist)

    n = hvd.size()
    model = MnistMLP(hidden=32)
    images, labels = synthetic_mnist(256)
    xs = jnp.asarray(images).reshape(n, 256 // n, 28, 28, 1)
    ys = jnp.asarray(labels).reshape(n, 256 // n)

    def loss(params, batch):
        x, y = batch
        return cross_entropy_loss(model.apply({"params": params}, x), y)

    grad_fn = jax.jit(jax.vmap(jax.grad(loss), in_axes=(None, 0)))
    loss_fn = jax.jit(loss)
    init_fn = lambda: init_params(model)  # noqa: E731 — fixed seed
    return init_fn, grad_fn, loss_fn, (xs, ys), \
        (jnp.asarray(images), jnp.asarray(labels))


@pytest.mark.parametrize("codec,rel_band", [("int8", 0.10),
                                            ("int4", 0.25)])
def test_mnist_loss_parity_quantized(hvd, monkeypatch, codec, rel_band):
    monkeypatch.setenv("HVD_TPU_QUANT_SEED", "7")
    init_fn, grad_fn, loss_fn, shards, full = _mnist_setup(hvd)
    steps, lr = 40, 0.5
    base = _train(hvd, "none", init_fn, grad_fn, loss_fn, shards, full,
                  steps, lr, "conv.mnist.none")
    assert base[-1] < base[0] * 0.8, "fp32 baseline failed to learn"
    quant0 = mk.stats.quant_launches
    quant = _train(hvd, codec, init_fn, grad_fn, loss_fn, shards, full,
                   steps, lr, f"conv.mnist.{codec}")
    assert mk.stats.quant_launches > quant0, \
        "the quantized leg never engaged the quantized kernels"
    _band_check(base, quant, rel_band, abs_band=0.02)


def test_mnist_error_feedback_is_load_bearing(hvd, monkeypatch):
    """With EF disabled, int4 tracks fp32 strictly worse than with EF —
    the residuals are doing real work, not decoration."""
    monkeypatch.setenv("HVD_TPU_QUANT_SEED", "7")
    init_fn, grad_fn, loss_fn, shards, full = _mnist_setup(hvd)
    steps, lr = 40, 0.5
    base = _train(hvd, "none", init_fn, grad_fn, loss_fn, shards, full,
                  steps, lr, "conv.ef.none")
    with_ef = _train(hvd, "int4", init_fn, grad_fn, loss_fn, shards,
                     full, steps, lr, "conv.ef.on")
    monkeypatch.setenv("HVD_TPU_QUANT_ERROR_FEEDBACK", "0")
    without_ef = _train(hvd, "int4", init_fn, grad_fn, loss_fn, shards,
                        full, steps, lr, "conv.ef.off")
    gap_on = np.abs(with_ef - base).max()
    gap_off = np.abs(without_ef - base).max()
    assert gap_on < gap_off, (gap_on, gap_off)


def _transformer_setup(hvd):
    from horovod_tpu.models.transformer import (ParallelAxes,
                                                TransformerConfig,
                                                forward,
                                                init_transformer,
                                                synthetic_lm_batch)

    n = hvd.size()
    cfg = TransformerConfig(vocab_size=64, d_model=32, n_heads=2,
                            n_layers=1, d_ff=64, max_seq_len=32)
    ax = ParallelAxes(data=None, model=None, seq=None, pipe=None,
                      expert=None)
    tokens, targets = synthetic_lm_batch(jax.random.PRNGKey(1),
                                         global_batch=32, seq_len=16,
                                         vocab_size=64)

    def loss(params, batch):
        toks, tgts = batch
        logits, aux = forward(params, toks, cfg, ax)
        logp = jax.nn.log_softmax(logits, axis=-1)
        nll = -jnp.take_along_axis(logp, tgts[..., None], axis=-1)[..., 0]
        return jnp.mean(nll) + aux

    xs = tokens.reshape(n, 32 // n, 16)
    ys = targets.reshape(n, 32 // n, 16)
    grad_fn = jax.jit(jax.vmap(jax.grad(loss), in_axes=(None, 0)))
    loss_fn = jax.jit(loss)
    init_fn = lambda: init_transformer(  # noqa: E731 — fixed seed
        jax.random.PRNGKey(0), cfg)
    return init_fn, grad_fn, loss_fn, (xs, ys), (tokens, targets)


def test_transformer_lm_loss_parity_int8(hvd, monkeypatch):
    monkeypatch.setenv("HVD_TPU_QUANT_SEED", "7")
    init_fn, grad_fn, loss_fn, shards, full = _transformer_setup(hvd)
    steps, lr = 30, 0.5
    base = _train(hvd, "none", init_fn, grad_fn, loss_fn, shards, full,
                  steps, lr, "conv.lm.none")
    assert base[-1] < base[0] - 0.3, "fp32 LM baseline failed to learn"
    quant0 = mk.stats.quant_launches
    quant = _train(hvd, "int8", init_fn, grad_fn, loss_fn, shards, full,
                   steps, lr, "conv.lm.int8")
    assert mk.stats.quant_launches > quant0
    _band_check(base, quant, rel_band=0.10, abs_band=0.03)
