"""ZeRO-1 sharded-optimizer-state training (parallel/zero.py).

The contract: identical training trajectory to plain replicated DP
(reduce_scatter + sharded update + all_gather == psum + replicated
update, for elementwise optimizers), with the optimizer state laid out
as 1/N-per-replica flat shards.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import horovod_tpu as hvd_api
from horovod_tpu.models.mnist import (MnistMLP, cross_entropy_loss,
                                      init_params, synthetic_mnist)
from horovod_tpu.parallel.training import make_train_step, shard_batch
from horovod_tpu.parallel.zero import make_zero_train_step


def _loss_fn(model):
    def loss_fn(params, batch):
        images, labels = batch
        return cross_entropy_loss(model.apply({"params": params}, images),
                                  labels)
    return loss_fn


@pytest.mark.parametrize("opt_ctor", [
    lambda: optax.sgd(0.1, momentum=0.9),
    lambda: optax.adam(1e-2),
])
def test_zero_matches_plain_dp(hvd, opt_ctor):
    """Same data, same steps: ZeRO-1 must track plain DP numerically."""
    model = MnistMLP(hidden=32)
    params = init_params(model)
    loss_fn = _loss_fn(model)
    images, labels = synthetic_mnist(64)
    batch = shard_batch((jnp.asarray(images), jnp.asarray(labels)))

    opt = opt_ctor()
    plain = make_train_step(loss_fn, opt, donate=False)
    p_ref, st_ref = params, opt.init(params)
    zstep = make_zero_train_step(loss_fn, opt_ctor(), donate=False)
    p_z, st_z = params, zstep.init(params)

    for _ in range(5):
        p_ref, st_ref, loss_ref = plain(p_ref, st_ref, batch)
        p_z, st_z, loss_z = zstep.step(p_z, st_z, batch)
    np.testing.assert_allclose(float(loss_z), float(loss_ref), rtol=1e-5)
    for a, b in zip(jax.tree_util.tree_leaves(p_z),
                    jax.tree_util.tree_leaves(p_ref)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


def test_zero_state_is_sharded(hvd):
    """Adam's mu/nu live as flat replica-sharded vectors: each device
    holds 1/N of the (padded) parameter count; the step count stays a
    replicated scalar."""
    model = MnistMLP(hidden=32)
    params = init_params(model)
    n = len(jax.devices())
    total = sum(l.size for l in jax.tree_util.tree_leaves(params))
    padded = -(-total // n) * n

    zstep = make_zero_train_step(_loss_fn(model), optax.adam(1e-3))
    st = zstep.init(params)
    vec_leaves = [l for l in jax.tree_util.tree_leaves(st) if l.ndim >= 1]
    assert vec_leaves, "expected adam mu/nu vector leaves"
    for leaf in vec_leaves:
        assert leaf.shape == (padded,)
        shard_rows = {s.data.shape[0] for s in leaf.addressable_shards}
        assert shard_rows == {padded // n}, shard_rows
    scalars = [l for l in jax.tree_util.tree_leaves(st) if l.ndim == 0]
    assert scalars, "expected adam count scalar"


def test_zero_training_converges(hvd):
    model = MnistMLP(hidden=64)
    params = init_params(model)
    zstep = make_zero_train_step(_loss_fn(model), optax.adam(1e-3))
    st = zstep.init(params)
    images, labels = synthetic_mnist(256)
    batch = shard_batch((jnp.asarray(images), jnp.asarray(labels)))
    losses = []
    for _ in range(30):
        params, st, loss = zstep.step(params, st, batch)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.7, losses[::10]


def test_zero_unwraps_distributed_optimizer(hvd):
    model = MnistMLP(hidden=16)
    params = init_params(model)
    dopt = hvd_api.DistributedOptimizer(optax.sgd(0.05))
    zstep = make_zero_train_step(_loss_fn(model), dopt, donate=False)
    st = zstep.init(params)
    images, labels = synthetic_mnist(32)
    batch = shard_batch((jnp.asarray(images), jnp.asarray(labels)))
    _, _, loss = zstep.step(params, st, batch)
    assert np.isfinite(float(loss))


def test_zero_with_state_matches_plain_dp(hvd):
    """Stateful variant (synchronized BatchNorm): tracks
    make_train_step_with_state on a BatchNorm MLP (the smallest model
    carrying running statistics — a conv stack adds only compile time
    here; ResNet itself is covered in test_resnet.py)."""
    from horovod_tpu.models.mnist import (MnistBNMLP, bn_mlp_loss_fn,
                                          init_bn_mlp, synthetic_mnist)
    from horovod_tpu.parallel.training import make_train_step_with_state
    from horovod_tpu.parallel.zero import make_zero_train_step_with_state

    model = MnistBNMLP(hidden=32)
    params, stats = init_bn_mlp(model)
    loss_fn = bn_mlp_loss_fn(model)
    images, labels = synthetic_mnist(16)
    batch = shard_batch((jnp.asarray(images), jnp.asarray(labels)))

    opt = optax.sgd(0.1, momentum=0.9)
    plain = make_train_step_with_state(loss_fn, opt, donate=False)
    zstep = make_zero_train_step_with_state(loss_fn, optax.sgd(
        0.1, momentum=0.9), donate=False)
    p1, s1, o1 = params, stats, opt.init(params)
    p2, s2, o2 = params, stats, zstep.init(params)
    for _ in range(3):
        p1, s1, o1, l1 = plain(p1, s1, o1, batch)
        p2, s2, o2, l2 = zstep.step(p2, s2, o2, batch)
    np.testing.assert_allclose(float(l2), float(l1), rtol=1e-4)
    for a, b in zip(jax.tree_util.tree_leaves(p2),
                    jax.tree_util.tree_leaves(p1)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-3, atol=1e-5)
    for a, b in zip(jax.tree_util.tree_leaves(s2),
                    jax.tree_util.tree_leaves(s1)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-3, atol=1e-5)


def test_zero_composes_with_compression(hvd):
    """bf16-compressed reduce_scatter stays close to the exact step and
    keeps f32 params (also exercised via DistributedOptimizer unwrap)."""
    from horovod_tpu.ops.compression import Compression

    model = MnistMLP(hidden=32)
    params = init_params(model)
    loss_fn = _loss_fn(model)
    images, labels = synthetic_mnist(64)
    batch = shard_batch((jnp.asarray(images), jnp.asarray(labels)))

    exact = make_zero_train_step(loss_fn, optax.sgd(0.1), donate=False)
    dopt = hvd_api.DistributedOptimizer(optax.sgd(0.1),
                                        compression=Compression.bf16)
    comp = make_zero_train_step(loss_fn, dopt, donate=False)
    p_e, _, _ = exact.step(params, exact.init(params), batch)
    p_c, _, _ = comp.step(params, comp.init(params), batch)
    for a, b in zip(jax.tree_util.tree_leaves(p_c),
                    jax.tree_util.tree_leaves(p_e)):
        assert a.dtype == b.dtype
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-3)


def test_zero_rejects_global_norm_clipping(hvd):
    """clip_by_global_norm aggregates across the whole tree; under ZeRO-1
    each replica would clip by its shard's norm — the build-time probe
    must refuse (round-3 verdict item 5)."""
    model = MnistMLP(hidden=32)
    opt = optax.chain(optax.clip_by_global_norm(1.0), optax.sgd(0.1))
    with pytest.raises(ValueError, match="ELEMENTWISE"):
        make_zero_train_step(_loss_fn(model), opt)


def test_zero_elementwise_escape_hatch(hvd):
    """validate_elementwise=False documents acceptance of shard-local
    semantics and builds (the documented escape hatch)."""
    model = MnistMLP(hidden=16)
    opt = optax.chain(optax.clip_by_global_norm(1.0), optax.sgd(0.1))
    zstep = make_zero_train_step(_loss_fn(model), opt,
                                 validate_elementwise=False, donate=False)
    params = init_params(model)
    images, labels = synthetic_mnist(32)
    batch = shard_batch((jnp.asarray(images), jnp.asarray(labels)))
    p, _, loss = zstep.step(params, zstep.init(params), batch)
    assert np.isfinite(float(loss))


@pytest.mark.parametrize("opt_ctor", [
    lambda: optax.adamw(1e-3),
    lambda: optax.chain(optax.clip(1.0), optax.sgd(0.1)),  # per-element
    lambda: optax.sgd(0.1, momentum=0.9),
])
def test_zero_probe_accepts_elementwise_chains(hvd, opt_ctor):
    """Per-element transforms (including optax.clip, the sanctioned
    clipping alternative) pass the probe."""
    model = MnistMLP(hidden=16)
    zstep = make_zero_train_step(_loss_fn(model), opt_ctor(), donate=False)
    assert zstep.init is not None


def test_zero_rejects_non_chunk_state_leaves(hvd):
    """A state leaf that is not one (chunk,)-slice per parameter would get
    silently wrong replica-axis sharding — init must refuse (advisor
    round-3 item 3)."""
    model = MnistMLP(hidden=16)

    def bad_init(params):
        return {"lr_table": jnp.ones((3,), jnp.float32)}

    def bad_update(updates, state, params=None):
        return jax.tree_util.tree_map(lambda u: -0.1 * u, updates), state

    opt = optax.GradientTransformation(bad_init, bad_update)
    zstep = make_zero_train_step(_loss_fn(model), opt, donate=False)
    params = init_params(model)
    with pytest.raises(ValueError, match="per-parameter slice"):
        zstep.init(params)
